#!/usr/bin/env python
"""scheduler_perf-equivalent benchmark for the TPU batch scheduler.

Reproduces the BASELINE.json config matrix (the TPU-era analogue of
test/integration/scheduler_perf/scheduler_bench_test.go:52-283 and the
density test in scheduler_test.go:72):

  1. 5k pods  /   500 nodes — NodeResourcesFit only
  2. 50k pods /  5k nodes   — + TaintToleration + NodeAffinity
  3. 100k pods / 10k nodes  — + PodTopologySpread (scoring)
  4. 20k pods /  2k nodes   — InterPodAffinity/anti-affinity heavy
  5. 1k groups x 64 pods    — gang / all-or-nothing (once wired)

Prints exactly ONE JSON line to stdout (the headline metric); the full
per-config breakdown goes to stderr and BENCH_DETAILS.json. vs_baseline is
relative to the reference's 100 pods/s warning threshold
(test/integration/scheduler_perf/scheduler_test.go:41-42) — its single-box
pass floor is 30 pods/s.

Runs on the default JAX platform (the real TPU chip in CI). Scale down for
smoke runs with BENCH_SCALE=0.1 or select configs with BENCH_CONFIGS=1,3.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax

try:
    # persistent XLA compile cache: first-batch compiles at the big bucket
    # shapes cost 1-2 minutes each on the remote-attached chip — cache them
    # across bench runs so re-runs measure the scheduler, not the compiler.
    # The cache lives inside the repo (gitignored) so it survives whatever
    # happens to /tmp between runs; a production deployment would ship the
    # same cache dir alongside the scheduler binary.
    _default_cache = os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache")
    jax.config.update("jax_compilation_cache_dir", os.environ.get(
        "JAX_COMPILATION_CACHE_DIR", _default_cache))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    # the compile PLAN persists its declared spec ladder next to the XLA
    # artifacts: a fresh process re-warms last run's exact ladder (specs
    # from ladder.json, compiled HLO from the XLA cache) — warmup becomes
    # trace-only, >=5x cheaper than cold (kubernetes_tpu/compile)
    os.environ.setdefault(
        "KTPU_COMPILE_CACHE_DIR", os.path.join(_default_cache, "compile_plan"))
except Exception:
    pass  # older jax or unsupported backend: run without the cache

import numpy as np

from kubernetes_tpu.api.types import (
    Affinity,
    Container,
    LabelSelector,
    Node,
    NodeAffinity,
    NodeSelector,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    Pod,
    PodAffinity,
    PodAffinityTerm,
    PodAntiAffinity,
    Quantity,
    RESOURCE_CPU,
    RESOURCE_MEMORY,
    RESOURCE_PODS,
    Taint,
    Toleration,
    TopologySpreadConstraint,
)
from kubernetes_tpu.scheduler.driver import Binder, Scheduler
from kubernetes_tpu.state.cache import SchedulerCache, per_shard_bytes
from kubernetes_tpu.state.queue import PriorityQueue

SCALE = float(os.environ.get("BENCH_SCALE", "1.0"))
# 4096 measured best on the remote-attached chip (round 3): the device
# program is now cheap (hash tie-noise + K=128 chunks), so per-batch cost
# is dominated by the ~100ms result round-trip plus host work that
# amortizes with batch size. The old 4096-bucket compile blowup was the
# per-pod split+vmap(threefry) noise — 4096 separate RNG programs — gone
# with the counter-based tie_noise. First compile is now ~60-90s, paid
# once thanks to the persistent compile cache.
BATCH = int(os.environ.get("BENCH_BATCH", "4096"))
ZONES = [f"zone-{i}" for i in range(8)]
# minimum batches for pods_per_sec_warm to be a real median: below this,
# warm is reported null ("n/a") — a 1-2 batch drain has no warm regime
MIN_WARM_BATCHES = 3
#: live MetricsServer while a BENCH_METRICS_PORT drain is in flight
#: (perf_smoke's mid-drain scraper polls this for the url); None otherwise
METRICS_SERVER = None


def _n(x: int) -> int:
    return max(int(x * SCALE), 8)


def mk_node(i: int, zone: str = "", taint: bool = False) -> Node:
    labels = {"kubernetes.io/hostname": f"node-{i}", "instance-type": ["small", "large"][i % 2]}
    if zone:
        labels["failure-domain.beta.kubernetes.io/zone"] = zone
    alloc = {
        RESOURCE_CPU: Quantity.parse("32"),
        RESOURCE_MEMORY: Quantity.parse("256Gi"),
        RESOURCE_PODS: Quantity.parse(110),
    }
    taints = [Taint(key="dedicated", value="batch", effect="NoSchedule")] if taint else []
    return Node(name=f"node-{i}", labels=labels, allocatable=alloc, capacity=dict(alloc), taints=taints)


def mk_pod(i: int, cpu: str = "100m", mem: str = "256Mi", **kw) -> Pod:
    return Pod(
        name=f"pod-{i}",
        namespace="bench",
        labels=kw.pop("labels", {"app": f"svc-{i % 50}"}),
        containers=[Container(name="c", requests={
            RESOURCE_CPU: Quantity.parse(cpu),
            RESOURCE_MEMORY: Quantity.parse(mem),
        })],
        **kw,
    )


# --- config builders: (nodes, pods) ----------------------------------------

def cfg1_resources():
    nodes = [mk_node(i) for i in range(_n(500))]
    pods = [mk_pod(i, cpu=["100m", "250m", "500m"][i % 3]) for i in range(_n(5000))]
    return nodes, pods


def cfg2_taint_affinity():
    n = _n(5000)
    nodes = [mk_node(i, taint=(i % 4 == 0)) for i in range(n)]
    pods = []
    for i in range(_n(50000)):
        p = mk_pod(i)
        if i % 2 == 0:
            p.tolerations = [Toleration(key="dedicated", operator="Equal", value="batch", effect="NoSchedule")]
        p.affinity = Affinity(node_affinity=NodeAffinity(required=NodeSelector(
            node_selector_terms=[NodeSelectorTerm(match_expressions=[
                NodeSelectorRequirement(key="instance-type", operator="In",
                                        values=["small", "large"] if i % 3 else ["large"]),
            ])])))
        pods.append(p)
    return nodes, pods


def cfg3_spread():
    n = _n(10000)
    nodes = [mk_node(i, zone=ZONES[i % len(ZONES)]) for i in range(n)]
    pods = []
    for i in range(_n(100000)):
        p = mk_pod(i, labels={"app": f"svc-{i % 100}"})
        p.topology_spread_constraints = [TopologySpreadConstraint(
            max_skew=1,
            topology_key="failure-domain.beta.kubernetes.io/zone",
            when_unsatisfiable="ScheduleAnyway",
            label_selector=LabelSelector(match_labels={"app": p.labels["app"]}),
        )]
        pods.append(p)
    return nodes, pods


def cfg4_interpod():
    n = _n(2000)
    nodes = [mk_node(i, zone=ZONES[i % len(ZONES)]) for i in range(n)]
    pods = []
    for i in range(_n(20000)):
        app = f"svc-{i % 20}"
        p = mk_pod(i, labels={"app": app})
        term = PodAffinityTerm(
            label_selector=LabelSelector(match_labels={"app": app}),
            topology_key="failure-domain.beta.kubernetes.io/zone",
        )
        if i % 10 == 0:
            # sparse REQUIRED anti-affinity (the quadratic pod x pod case)
            hterm = PodAffinityTerm(
                label_selector=LabelSelector(match_labels={"exclusive": app}),
                topology_key="kubernetes.io/hostname",
            )
            p.labels["exclusive"] = app
            p.affinity = Affinity(pod_anti_affinity=PodAntiAffinity(required=[hterm]))
        else:
            # preferred co-location: scoring-only, stays on the fast path
            from kubernetes_tpu.api.types import WeightedPodAffinityTerm

            p.affinity = Affinity(pod_affinity=PodAffinity(
                preferred=[WeightedPodAffinityTerm(weight=10, pod_affinity_term=term)]))
        pods.append(p)
    return nodes, pods


def cfg5_gang():
    from kubernetes_tpu.scheduler.driver import POD_GROUP_LABEL

    n = _n(2000)
    nodes = [mk_node(i) for i in range(n)]
    pods = []
    n_groups = _n(1000)
    for g in range(n_groups):
        for m in range(64):
            p = mk_pod(g * 64 + m, labels={"app": f"gang-{g}", POD_GROUP_LABEL: f"gang-{g}"})
            pods.append(p)
    return nodes, pods


def cfg6_preemption():
    """Preemption-enabled run (the only config exercising the eviction
    path under load): nodes pre-filled with low-priority pods consuming
    ~90% of CPU, then high-priority pods that can only land by evicting
    victims (pkg/scheduler/core preempt path).

    Sized an order below the other configs on purpose: preemption is a
    HOST-side scalar path — each failed pod's preempt() scans the whole
    snapshot (candidate nodes x victims), exactly like the reference's
    preemption (which is equally sequential). At 2k nodes x 10k pods the
    sweep runs for hours; ~500x2k keeps the bench honest about the
    path's throughput without drowning the suite. The recorded
    pods_per_sec IS the preemption path's measured rate."""
    n = _n(500)
    nodes = [mk_node(i) for i in range(n)]
    existing = []
    for i in range(n * 7):  # 7 x 4000m = 28 of 32 cores per node
        p = mk_pod(1_000_000 + i, cpu="4000m", mem="1Gi",
                   labels={"app": f"lowprio-{i % 20}"})
        p.priority = 0
        p.node_name = f"node-{i % n}"
        existing.append(p)
    # the high-priority pods carry a priorityClassName and get their
    # numeric priority from the Priority ADMISSION plugin on apiserver
    # create — the reference's end-to-end path
    # (plugin/pkg/admission/priority/admission.go:137), not a hardcoded
    # spec.priority
    from kubernetes_tpu.api.types import PriorityClass
    from kubernetes_tpu.apiserver import (
        FakeAPIServer,
        default_admission_chain,
        install_system_priority_classes,
    )

    api = FakeAPIServer(admission=default_admission_chain())
    install_system_priority_classes(api)
    api.create("priorityclasses", PriorityClass(name="bench-critical", value=1000))
    pending = []
    for i in range(_n(2000)):
        p = mk_pod(i, cpu="6000m", mem="2Gi", labels={"app": f"hiprio-{i % 20}"})
        p.priority_class_name = "bench-critical"
        p.priority = None
        admitted = api.create("pods", p)
        assert admitted.priority == 1000, "admission must resolve the class"
        pending.append(admitted)
    return nodes, pending, existing


CONFIGS = {
    "1": ("5k_pods_500_nodes_resources", cfg1_resources),
    "2": ("50k_pods_5k_nodes_taint_nodeaffinity", cfg2_taint_affinity),
    "3": ("100k_pods_10k_nodes_topology_spread", cfg3_spread),
    "4": ("20k_pods_2k_nodes_interpod_affinity", cfg4_interpod),
    "5": ("64k_pods_1k_gangs_2k_nodes", cfg5_gang),
    "6": ("2k_hi_pods_500_full_nodes_preemption", cfg6_preemption),
}
# per-config scheduler options (CONFIGS keeps its (name, build) shape for
# the microbench scripts that import it)
CONFIG_OPTS = {
    "6": {"enable_preemption": True},
}


def _hist_counts(h):
    with h._lock:
        return list(h._counts.get((), [0] * (len(h.buckets) + 1)))


def _hist_pct_from_diff(h, before, q):
    """Quantile (bucket upper bound) of ONLY the samples observed since
    `before` — isolates one config's pod latencies from the process-global
    histogram."""
    now = _hist_counts(h)
    diff = [b - a for a, b in zip(before, now)]
    total = sum(diff)
    if total == 0:
        return None
    target = q * total
    acc = 0
    for i, b in enumerate(h.buckets):
        acc += diff[i]
        if acc >= target:
            return b
    return float("inf")


def audit_placement(nodes, commits, existing=(), sample=1000, seed=0, deleted=frozenset()):
    """Post-run correctness audit of the FINAL placement + a sampled
    feasibility-at-commit-time replay (round-2 VERDICT weak #6: counters
    are not evidence).

    * full sweep (every node): capacity (cpu/mem/pod count vs allocatable
      minus pre-existing), host-port collisions, required anti-affinity in
      both directions, DoNotSchedule skew bound at final state.
    * sampled replay: commits re-applied IN COMMIT ORDER to a fresh
      Snapshot; for `sample` random pods the full oracle predicate chain
      (pod_fits_on_node) must accept the chosen node at its commit time.
    Returns a dict of violation counts (all zero = pass).
    """
    import random

    from kubernetes_tpu.oracle import Snapshot
    from kubernetes_tpu.oracle.predicates import (
        compute_predicate_metadata,
        get_pod_anti_affinity_terms,
        pod_fits_on_node,
        pod_matches_term,
    )

    rng = random.Random(seed)
    picked = set(
        rng.sample(range(len(commits)), min(sample, len(commits)))
    ) if commits else set()
    # preemption runs: victims (deleted mid-run) leave the final state; the
    # end-state sweeps below still hold exactly. Commit-TIME feasibility
    # replay is only meaningful without deletions (callers pass sample=0
    # alongside a non-empty deleted set).
    snap = Snapshot(
        list(nodes), [p for p in existing if p.key() not in deleted]
    )
    replay_violations = 0
    for i, (pod, node_name) in enumerate(commits):
        if pod.key() in deleted:
            continue
        ni = snap.get(node_name)
        if ni is None:
            replay_violations += 1
            continue
        if i in picked:
            meta = compute_predicate_metadata(pod, snap)
            ok, _ = pod_fits_on_node(pod, ni, meta=meta, snapshot=snap)
            if not ok:
                replay_violations += 1
        bound = pod.with_node(node_name)
        ni.add_pod(bound)

    # final-state sweeps
    cap_violations = port_violations = anti_violations = skew_violations = 0
    for name, ni in snap.node_infos.items():
        alloc = {k: q.value() if k != RESOURCE_CPU else q.milli_value()
                 for k, q in ni.node.allocatable.items()}
        used = ni.requested()
        for rname, v in used.items():
            cap = alloc.get(rname)
            if cap is not None and v > cap:
                cap_violations += 1
        pods_cap = alloc.get(RESOURCE_PODS)
        if pods_cap is not None and len(ni.pods) > pods_cap:
            cap_violations += 1
        seen_ports = {}
        for p in ni.pods:
            for t in p.host_ports():
                proto, ip, port = t
                for (pr2, ip2, po2) in seen_ports:
                    if pr2 == proto and po2 == port and (
                        ip == "0.0.0.0" or ip2 == "0.0.0.0" or ip == ip2
                    ):
                        port_violations += 1
                seen_ports[t] = True
    # anti-affinity: every pod's required anti terms vs all OTHER pods in
    # the term's topology domain
    domain_pods = {}  # (key, value) -> [pods]
    node_of = {}
    for name, ni in snap.node_infos.items():
        for p in ni.pods:
            node_of[id(p)] = ni.node
            for kv in ni.node.labels.items():
                domain_pods.setdefault(kv, []).append(p)
    for name, ni in snap.node_infos.items():
        for p in ni.pods:
            for term in get_pod_anti_affinity_terms(p.affinity):
                k = term.topology_key
                v = ni.node.labels.get(k) if k else None
                if v is None:
                    continue
                for q in domain_pods.get((k, v), ()):
                    if q is not p and pod_matches_term(q, p, term):
                        anti_violations += 1
    # DoNotSchedule skew at final state
    from kubernetes_tpu.oracle.predicates import get_hard_spread_constraints
    from kubernetes_tpu.api.selectors import match_label_selector

    hard_pods = [
        (p, node_of[id(p)])
        for ni in snap.node_infos.values()
        for p in ni.pods
        if get_hard_spread_constraints(p)
    ]
    for p, node in hard_pods:
        for c in get_hard_spread_constraints(p):
            counts = {}
            for name2, ni2 in snap.node_infos.items():
                v = ni2.node.labels.get(c.topology_key)
                if v is None:
                    continue
                counts[v] = counts.get(v, 0) + sum(
                    1 for q in ni2.pods
                    if q.namespace == p.namespace
                    and match_label_selector(c.label_selector, q.labels)
                )
            my_v = node.labels.get(c.topology_key)
            if counts and my_v in counts:
                if counts[my_v] - min(counts.values()) > c.max_skew:
                    skew_violations += 1
    return {
        "commits": len(commits),
        "replay_sampled": len(picked),
        "replay_violations": replay_violations,
        "capacity_violations": cap_violations,
        "port_violations": port_violations,
        "anti_affinity_violations": anti_violations,
        "hard_spread_skew_violations": skew_violations,
    }


def run_config(name, build, opts=None, inspect=None):
    """`inspect(sched)`, when given, runs after the drain settles and
    before the scheduler closes — the seam perf_smoke uses for bank-parity
    and donation checks without bench carrying test logic."""
    from kubernetes_tpu.metrics import metrics as M

    t_setup = time.perf_counter()
    built = build()
    nodes, pods = built[0], built[1]
    existing = built[2] if len(built) > 2 else []
    cache = SchedulerCache()
    for node in nodes:
        cache.add_node(node)
    for p in existing:
        cache.add_pod(p)
    queue = PriorityQueue()
    sched = Scheduler(
        cache=cache, queue=queue, binder=Binder(), batch_size=BATCH,
        deterministic=False, bind_workers=16,
        # deep speculation chain: drain-style workload, no live arrivals to
        # starve — depth 8 hides multi-second tunnel RTT phases entirely
        spec_depth=int(os.environ.get("BENCH_SPEC_DEPTH", "8")),
        **{"enable_preemption": False, **(opts or {})},
    )
    # preemption runs: record victim deletions so the audit can sweep the
    # true final state instead of being skipped (round-3 VERDICT weak #2)
    deleted_keys = set()
    if (opts or {}).get("enable_preemption"):
        def _delete_victim(v):
            deleted_keys.add(v.key())
            cache.remove_pod(v)

        sched.delete_fn = _delete_victim
    # flight recorder: a fresh timeline per config (the recorder is
    # process-global; without the reset config N's trace would replay
    # configs 1..N-1's spans)
    if sched.obs.enabled:
        sched.obs.reset()
    # pre-size the device banks: every capacity growth is an XLA recompile
    sched.mirror.reserve(len(nodes), len(pods))
    for p in pods:
        queue.add(p)
    setup_s = time.perf_counter() - t_setup
    # pre-pay compile (or persistent-cache load) + full bank upload at the
    # real shapes so the drain measures scheduling, not XLA (the production
    # analogue: a scheduler warms its executables at boot before Run()).
    # Timed OUTSIDE setup_s — the two fields must not overlap.
    t_w = time.perf_counter()
    warmed = sched.warmup()
    warmup_s = time.perf_counter() - t_w
    print(f"[bench] warmup: {warmed} pods, {warmup_s:.1f}s", file=sys.stderr, flush=True)
    # restart evidence: when a persisted ladder was re-warmed, compare the
    # actual warmup wall against the stored COLD compile budget of those
    # specs (note_compiled keeps the max, i.e. the cold cost) — this is
    # the warm-vs-cold ratio the compile cache exists for
    comp0 = sched.compile_plan.snapshot()
    cold_budget = sum(e["compile_s"] for e in comp0["specs"] if e["source"] == "persisted")
    if cold_budget > 0 and warmup_s > 0:
        print(
            f"[bench] persisted-ladder warmup: {warmup_s:.1f}s actual vs "
            f"{cold_budget:.1f}s cold budget "
            f"({cold_budget / warmup_s:.1f}x faster than cold)",
            file=sys.stderr, flush=True,
        )
    # pods enqueue BEFORE warmup (warmup peeks the queue), so their queue
    # age would include compile/upload time — rebase the enqueue clocks to
    # warmup-end so pod_sched percentiles measure SCHEDULING only (the
    # round-5 verdict's "p50 13.19s vs 0.276s elapsed" artifact)
    queue.rebase_timestamps()
    pod_hist_before = _hist_counts(M.pod_scheduling_duration)
    # EXACT per-pod queue-add → bound latency from raw samples, this config
    # only (round-3 VERDICT weak #8: bucket upper bounds are not
    # percentiles)
    M.pod_scheduling_duration.enable_sampling()
    M.pod_scheduling_duration.reset_samples()
    # attribution histograms (kubernetes_tpu/obs): queue wait (enqueue →
    # pop) + attempt (pop → bound) decompose the e2e number above; the
    # open-loop mode will quote its SLOs from these same reservoirs
    for h in (M.queue_incoming_wait, M.scheduling_attempt_duration,
              M.e2e_scheduling_duration):
        h.enable_sampling()
        h.reset_samples()
    # live scrape endpoint behind a flag: BENCH_METRICS_PORT=<port> (0 =
    # ephemeral) serves /metrics + /healthz + warmup-gated /readyz for
    # the duration of the drain (perf_smoke scrapes it mid-drain)
    global METRICS_SERVER
    msrv = None
    # steady-state health monitor behind a flag (BENCH_HEALTH=1): the
    # always-on gauges + sampled shadow audits run for the whole drain
    # (armed here, after warmup, on the driver thread — the monitor's
    # constructor publishes the driver-confined mirror census)
    if os.environ.get("BENCH_HEALTH", "") not in ("", "0"):
        sched.enable_health_monitor()
    if os.environ.get("BENCH_METRICS_PORT", "") != "":
        from kubernetes_tpu.metrics import MetricsServer
        from kubernetes_tpu.obs.introspect import census as _census

        msrv = MetricsServer(
            port=int(os.environ["BENCH_METRICS_PORT"]),
            ready_fn=lambda: sched.ready,
            debug_fn=lambda: _census(sched),
        ).start()
        METRICS_SERVER = msrv  # perf_smoke's mid-drain scraper reads the url
        print(f"[bench] metrics on {msrv.url}/metrics", file=sys.stderr, flush=True)
    # the cluster model is millions of long-lived objects; generational GC
    # walking them mid-batch shows up as ~1s commit-loop outliers. Freeze
    # the setup heap out of the collector and keep GC off during the
    # measured drain (a production deployment would tune exactly this).
    import gc

    gc.collect()
    gc.freeze()
    gc.disable()

    batch_times = []
    batch_sched = []
    commits = []  # [(pod, node_name)] in COMMIT order, for the audit
    pod_by_key = {p.key(): p for p in pods}
    t0 = time.perf_counter()
    first_batch_s = None
    scheduled = unsched = preempted = deferred = 0
    idle_rounds = 0
    try:
        while True:
            tb = time.perf_counter()
            r = sched.schedule_batch()
            dt = time.perf_counter() - tb
            if (r.scheduled == 0 and r.unschedulable == 0 and r.errors == 0
                    and r.deferred == 0):
                # preemption requeues its beneficiaries with BACKOFF (1s
                # initial, doubling to 10s — pod_backoff.go): wait out the
                # longest possible backoff before declaring the drain done,
                # not one second (fast batches made the old 1s window exit
                # with pods still backing off)
                active, backoff, unsched_q = queue.counts()
                if preempted and idle_rounds < 300 and (active + backoff + unsched_q):
                    idle_rounds += 1
                    time.sleep(0.05)
                    queue.move_all_to_active()
                    continue
                break
            idle_rounds = 0
            if first_batch_s is None:
                first_batch_s = dt
            batch_times.append(dt)
            batch_sched.append(r.scheduled)
            scheduled += r.scheduled
            unsched += r.unschedulable  # attempts; see unschedulable_pods below
            preempted += r.preempted
            deferred += r.deferred  # commit-plane defer-to-next-batch verdicts
            commits.extend(
                (pod_by_key[k], n) for k, n in r.assignments.items() if k in pod_by_key
            )
        sched.wait_for_binds()
        elapsed = time.perf_counter() - t0
    finally:
        # a scheduler error mid-drain must not leave GC disabled+frozen for
        # every remaining config in this same-process run
        gc.enable()
        gc.unfreeze()
        gc.collect()
        if msrv is not None:
            msrv.stop()
            METRICS_SERVER = None
    steady = sum(batch_times[1:]) or 1e-9
    # steady throughput must be MEASURABLE even when a config drains in
    # few batches (the preemption config used to report 0.0): prefer the
    # canonical batches-2..N rate, fall back to the post-first-batch
    # window (pods scheduled after the first batch completed over that
    # wall), and for a genuine single-batch drain fall back to that
    # batch's own rate — never 0.0 while pods actually scheduled.
    steady_sched = sum(batch_sched[1:])
    if len(batch_times) > 1 and steady_sched > 0:
        pps_steady = steady_sched / steady
    elif batch_times and batch_times[0] > 0 and batch_sched[0] > 0:
        post_window = elapsed - (first_batch_s or 0.0)
        post_sched = scheduled - batch_sched[0]
        if post_sched > 0 and post_window > 0:
            pps_steady = post_sched / post_window
        else:
            pps_steady = batch_sched[0] / batch_times[0]
    else:
        pps_steady = None
    bt = np.array(batch_times) if batch_times else np.array([0.0])
    # warm throughput: MEDIAN per-batch rate (actual scheduled / latency)
    # over the LAST half of batches — excludes the bounded one-time XLA
    # compiles AND is robust to the multi-minute stall outliers the
    # remote-attached tunnel occasionally injects (a mean would smear one
    # 300s hiccup over the whole tail). Below MIN_WARM_BATCHES the
    # "median" is one or two arbitrary batches and can land BELOW the
    # end-to-end rate (the round-5 config-1 artifact: 16,179 warm vs
    # 18,124 e2e over 2 batches) — report n/a instead of a fake number.
    half = len(batch_times) // 2 if len(batch_times) >= 4 else 0
    rates = [s / t for t, s in zip(batch_times[half:], batch_sched[half:]) if t > 0]
    warm_rate = (
        float(np.median(rates))
        if rates and len(batch_times) >= MIN_WARM_BATCHES
        else None
    )
    # honesty counter for the median: batches in the measured tail that ran
    # >5x the median latency (recompiles or tunnel stalls the median hides)
    tail_med = float(np.median(batch_times[half:])) if batch_times[half:] else 0.0
    stall_batches = sum(1 for t in batch_times[half:] if tail_med > 0 and t > 5 * tail_med)
    # per-pod queue-add → bound latency (PodSchedulingDuration histogram,
    # this config's samples only) — the BASELINE.json headline latency
    # exact percentiles from raw samples; the bucket-bound estimate stays
    # as a cross-check field (they must bracket each other)
    pod_p50 = M.pod_scheduling_duration.exact_percentile(0.5)
    pod_p99 = M.pod_scheduling_duration.exact_percentile(0.99)
    pod_p99_bucket = _hist_pct_from_diff(M.pod_scheduling_duration, pod_hist_before, 0.99)
    if pod_p50 is not None:
        pod_p50 = round(pod_p50, 4)
    if pod_p99 is not None:
        pod_p99 = round(pod_p99, 4)

    # per-pod ATTRIBUTION percentiles from the obs histograms' raw
    # reservoirs: queue wait (enqueue → pop) + attempt (pop → bound)
    # decompose pod_sched above; e2e (decided → bound incl. bind) is the
    # reference's E2eSchedulingLatency shape
    def _pct(hist, q):
        v = hist.exact_percentile(q)
        return round(v, 4) if v is not None else None

    attribution = {
        "queue_wait_p50_s": _pct(M.queue_incoming_wait, 0.5),
        "queue_wait_p99_s": _pct(M.queue_incoming_wait, 0.99),
        "attempt_p50_s": _pct(M.scheduling_attempt_duration, 0.5),
        "attempt_p99_s": _pct(M.scheduling_attempt_duration, 0.99),
        "e2e_p50_s": _pct(M.e2e_scheduling_duration, 0.5),
        "e2e_p99_s": _pct(M.e2e_scheduling_duration, 0.99),
    }
    if inspect is not None:
        inspect(sched)
    # flight-recorder export (KTPU_TRACE=1 / Scheduler(trace=True)):
    # outside the timed drain — resolve_pending may block on parked
    # device spans here, the one place that's allowed
    if sched.obs.enabled:
        safe = "".join(c if c.isalnum() else "_" for c in name)
        trace_path = os.environ.get("BENCH_TRACE_OUT", f"trace_{safe}.json")
        sched.dump_trace(trace_path)
        print(f"[bench] trace -> {trace_path}", file=sys.stderr, flush=True)
    # retire the background compile-warmup worker OUTSIDE the timed drain
    # (queued warms drop; an in-flight XLA compile at process exit would
    # otherwise abort the interpreter) and persist the grown ladder
    sched.close()
    # audit: preemption runs sweep the FINAL state (victim deletions
    # tracked via delete_fn) with the commit-time replay disabled — a
    # commit may have been legal only after a mid-run deletion the replay
    # cannot time-order. Non-preemption runs keep the sampled replay.
    t_a = time.perf_counter()
    audit = audit_placement(
        nodes, commits, existing=existing,
        sample=0 if preempted else int(os.environ.get("BENCH_AUDIT_SAMPLE", "1000")),
        deleted=frozenset(deleted_keys),
    )
    audit_s = time.perf_counter() - t_a
    # a failed audit with the flight recorder armed dumps the black-box
    # cycle ring next to the trace: the per-batch verdict/byte/fold
    # deltas are exactly what bisecting a placement violation needs
    if sched.obs.enabled and any(
        v for k, v in audit.items() if k.endswith("_violations")
    ):
        sched.obs.dump_blackbox("audit-failure")

    detail = {
        "config": name,
        "nodes": len(nodes),
        "pods": len(pods),
        "scheduled": scheduled,
        # attempt-counted (a preemption-retried pod counts once per retry
        # round); pods actually left unplaced:
        "unschedulable_attempts": unsched,
        "unschedulable_pods": max(len(pods) - scheduled, 0),
        "preempted": preempted,
        "deferred": deferred,
        # scheduling-only (enqueue clocks rebased at warmup end): warmup/
        # first-compile excluded by construction. The *_warm names are the
        # canonical BASELINE.json latency fields; the unsuffixed names
        # carry the same values now that warmup is excluded.
        "pod_sched_p50_warm_s": pod_p50,
        "pod_sched_p99_warm_s": pod_p99,
        "pod_sched_p50_s": pod_p50,
        "pod_sched_p99_s": pod_p99,
        "pod_sched_p99_bucket_s": pod_p99_bucket,
        # where the time went per pod (obs histograms, raw reservoirs):
        # queue_wait + attempt ≈ pod_sched; e2e is decided → bound
        "pod_latency_attribution": attribution,
        "audit": audit,
        "audit_s": round(audit_s, 3),
        "elapsed_s": round(elapsed, 3),
        "pods_per_sec": round(scheduled / elapsed, 1) if elapsed > 0 else 0.0,
        # actual pods scheduled in batches 2..N over their wall, with the
        # post-first-batch / single-batch fallbacks above — measurable for
        # every config that scheduled anything (the preemption config used
        # to report 0.0 when it drained in effectively one batch window)
        "pods_per_sec_steady": round(pps_steady, 1) if pps_steady is not None else None,
        "pods_per_sec_warm": round(warm_rate, 1) if warm_rate is not None else None,
        "warm_stall_batches": stall_batches,
        "first_batch_s": round(first_batch_s or 0.0, 3),
        "batch_p50_s": round(float(np.percentile(bt, 50)), 4),
        "batch_p99_s": round(float(np.percentile(bt, 99)), 4),
        "setup_s": round(setup_s, 3),
        "warmup_s": round(warmup_s, 3),
        "phase_split_s": {k: round(v, 3) if isinstance(v, float) else v
                          for k, v in sched.stats.items()},
        # host→device bank traffic by kind (full|rows|usage|fold|warm):
        # the resident-state plane's win as a measured byte count — on a
        # covered steady-state drain `usage` stays ~0 and only `fold`
        # (tiny control arrays) grows with the drain
        "patch_bytes": dict(sched.mirror.bytes_shipped),
        # commit-plane / fold-plane coverage as explicit counters (the
        # MULTICHIP_r* record: the win is measured coverage + bytes, not
        # just bit-identity), plus the sharded-fallback count — PER
        # DISPATCH (speculative chain entries count individually), zero
        # on a healthy mesh drain
        "coverage": {
            "batches": sched.stats.get("batches", 0),
            "arbiter_batches": sched.stats.get("arbiter_batches", 0),
            "fold_batches": sched.stats.get("fold_batches", 0),
            "fold_pods": sched.stats.get("fold_pods", 0),
            "sharded_fallbacks": sched.stats.get("sharded_fallbacks", 0),
            # pod-ingest plane: index-only vs host-built dispatches (per
            # dispatch, speculative entries included) + staleness events
            "ingest_index": sched.stats.get("ingest_index_batches", 0),
            "ingest_legacy": sched.stats.get("ingest_legacy_batches", 0),
            "ingest_stale_rows": sched.stats.get("ingest_stale_rows", 0),
            # term-bank plane: index-only vs host-compiled term tables
            # (per dispatch, like the ingest counters) + staleness events
            "term_index": sched.stats.get("term_index_batches", 0),
            "term_legacy": sched.stats.get("term_legacy_batches", 0),
            "term_stale_rows": sched.stats.get("term_stale_rows", 0),
        },
        # multi-chip: shard count + per-shard bank traffic (node-major
        # kinds split across shards; fold control replicates — the split
        # policy lives in state.cache.per_shard_bytes)
        "mesh_shards": sched._mesh_shards,
        "patch_bytes_per_shard": (
            per_shard_bytes(sched.mirror.bytes_shipped, sched._mesh_shards)
            if sched._mesh_shards else None
        ),
        "fold_undonated": sched.mirror.folds_undonated,
        "mirror_rebuilds": sched.mirror.rebuild_count,
        # compile-plan telemetry (kubernetes_tpu/compile): misses_after_
        # warmup is the mid-drain-XLA-stall count — zero on a healthy run
        "compile": sched.compile_plan.snapshot(),
    }
    if detail["compile"]["misses_after_warmup"]:
        print(
            f"[bench] WARNING {name}: "
            f"{detail['compile']['misses_after_warmup']} compile spec "
            f"miss(es) AFTER warmup — mid-drain XLA stalls",
            file=sys.stderr, flush=True,
        )
    return detail


def main():
    which = os.environ.get("BENCH_CONFIGS", "1,2,3,4,5,6").split(",")
    details = []
    for key in which:
        key = key.strip()
        if key not in CONFIGS:
            continue
        name, build = CONFIGS[key]
        print(f"[bench] running config {key}: {name} ...", file=sys.stderr, flush=True)
        d = run_config(name, build, CONFIG_OPTS.get(key))
        details.append(d)
        print(f"[bench] {json.dumps(d)}", file=sys.stderr, flush=True)

    with open(os.path.join(os.path.dirname(os.path.abspath(__file__)), "BENCH_DETAILS.json"), "w") as f:
        json.dump(details, f, indent=2)

    # ONE generator for the docs' round table (VERDICT r5 weak #5): the
    # table in PERF.md and README.md re-renders from the artifact just
    # written, so the three can no longer drift. Only CANONICAL runs may
    # publish: the full config matrix at full scale and the default batch
    # — a BENCH_SCALE/BENCH_BATCH smoke over all six configs must not
    # overwrite the published numbers with scaled-down ones.
    if (
        os.environ.get("BENCH_UPDATE_DOCS", "1") != "0"
        and len(details) == len(CONFIGS)
        and SCALE == 1.0
        and BATCH == 4096
    ):
        try:
            sys.path.insert(0, os.path.join(
                os.path.dirname(os.path.abspath(__file__)), "scripts"))
            import gen_perf_table

            gen_perf_table.run()
        except SystemExit as e:
            print(f"[bench] gen_perf_table: {e}", file=sys.stderr)
        except Exception as e:  # docs must never fail the measurement
            print(f"[bench] gen_perf_table failed: {e}", file=sys.stderr)

    # headline: config 3 (the north-star shape) if run, else the largest run
    headline = None
    for d in details:
        if d["config"].startswith("100k"):
            headline = d
    if headline is None and details:
        headline = max(details, key=lambda d: d["pods"])
    if headline is None:
        print(json.dumps({"metric": "none", "value": 0, "unit": "pods/s", "vs_baseline": 0}))
        return
    # headline stays END-TO-END (cold, incl. compiles) — comparable across
    # rounds and against the reference's end-to-end warn line; the warm
    # sustained rate is reported alongside in BENCH_DETAILS.json
    value = headline["pods_per_sec"]
    total_misses = sum(
        d.get("compile", {}).get("misses_after_warmup", 0) for d in details
    )
    print(json.dumps({
        "metric": f"pods_per_sec_{headline['config']}",
        "value": value,
        "unit": "pods/s",
        # reference warn line: 100 pods/s (scheduler_test.go:41-42)
        "vs_baseline": round(value / 100.0, 2),
    }))
    # the compile plan's whole point: no XLA stall may interrupt a drain.
    # Asserted AFTER the artifacts are written so a regression still
    # leaves BENCH_DETAILS.json to diagnose from; BENCH_ASSERT_COMPILE=0
    # opts out (e.g. first-ever run on new hardware without a cache).
    if os.environ.get("BENCH_ASSERT_COMPILE", "1") != "0":
        assert total_misses == 0, (
            f"{total_misses} compile spec miss(es) after warmup — "
            "mid-drain XLA stalls; see 'compile' in BENCH_DETAILS.json"
        )


if __name__ == "__main__":
    main()
