#!/usr/bin/env python
"""Warm per-batch cost split on the real chip: host->device upload vs device
execution, at BASELINE config-3-like shapes (10k nodes, B=1024 spread pods).

Run: python scripts/microbench_batch.py [n_nodes] [batch]
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_compilation_cache_dir", "/tmp/jax_compile_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

import jax.numpy as jnp
import numpy as np

N_NODES = int(sys.argv[1]) if len(sys.argv) > 1 else 10000
BATCH = int(sys.argv[2]) if len(sys.argv) > 2 else 1024

from bench import ZONES, mk_node, mk_pod  # noqa: E402
from kubernetes_tpu.api.types import LabelSelector, TopologySpreadConstraint  # noqa: E402
from kubernetes_tpu.oracle import Snapshot  # noqa: E402
from kubernetes_tpu.ops.pipeline import encode_solve_args, solve_pipeline  # noqa: E402

t0 = time.perf_counter()
nodes = [mk_node(i, zone=ZONES[i % len(ZONES)]) for i in range(N_NODES)]
pods = []
for i in range(BATCH):
    p = mk_pod(i, labels={"app": f"svc-{i % 100}"})
    p.topology_spread_constraints = [TopologySpreadConstraint(
        max_skew=1,
        topology_key="failure-domain.beta.kubernetes.io/zone",
        when_unsatisfiable="ScheduleAnyway",
        label_selector=LabelSelector(match_labels={"app": p.labels["app"]}),
    )]
    pods.append(p)
snap = Snapshot(nodes, [])
print(f"cluster build: {time.perf_counter()-t0:.2f}s", flush=True)

t0 = time.perf_counter()
args = encode_solve_args(snap, pods)
print(f"encode: {time.perf_counter()-t0:.2f}s", flush=True)

na, pa, ea, tb, xa, au, ids, key = args

def leaves(d):
    return list(d.items()) if isinstance(d, dict) else []

inventory = {"na": na, "pa": pa, "ea": ea, "tb": tb, "xa": xa, "au": au, "ids": ids}
total_leaves = 0
for name, d in inventory.items():
    n = len(leaves(d))
    b = sum(np.asarray(v).nbytes for _, v in leaves(d))
    total_leaves += n
    print(f"  {name}: {n} arrays, {b/1e6:.2f} MB")
print(f"total leaves: {total_leaves}", flush=True)

# hold host copies of the per-batch uploads (what the driver re-sends each batch)
pa_h = {k: np.asarray(v) for k, v in pa.items()}
tb_h = {k: np.asarray(v) for k, v in tb.items()}
au_h = {k: np.asarray(v) for k, v in au.items()}
per_batch_bytes = sum(v.nbytes for d in (pa_h, tb_h, au_h) for v in d.values())
per_batch_leaves = sum(len(d) for d in (pa_h, tb_h, au_h))
print(f"per-batch upload: {per_batch_leaves} arrays, {per_batch_bytes/1e6:.2f} MB", flush=True)

# 1. pure upload cost of the per-batch args (as the driver does: implicit
#    jnp conversion during dispatch). Measure device_put + block.
for trial in range(3):
    t0 = time.perf_counter()
    put = jax.device_put((pa_h, tb_h, au_h))
    jax.block_until_ready(put)
    print(f"upload trial {trial}: {time.perf_counter()-t0:.3f}s", flush=True)

# 2. solve with everything device-resident already (pure device exec)
dev_args = jax.device_put(args)
jax.block_until_ready(dev_args)
term_kinds = frozenset({"spread_soft", "sel_spread"})
t0 = time.perf_counter()
out = solve_pipeline(*dev_args, deterministic=False, term_kinds=term_kinds)
jax.block_until_ready(out)
print(f"first call (compile): {time.perf_counter()-t0:.1f}s", flush=True)
for trial in range(5):
    t0 = time.perf_counter()
    out = solve_pipeline(*dev_args, deterministic=False, term_kinds=term_kinds)
    jax.block_until_ready(out)
    print(f"device-resident solve trial {trial}: {time.perf_counter()-t0:.3f}s", flush=True)

# 3. solve with per-batch args passed as host numpy (the driver's actual path)
t0 = time.perf_counter()
out = solve_pipeline(dev_args[0], pa_h, dev_args[2], tb_h, dev_args[4], au_h,
                     dev_args[6], dev_args[7], deterministic=False, term_kinds=term_kinds)
jax.block_until_ready(out)
print(f"host-args solve (maybe compile): {time.perf_counter()-t0:.3f}s", flush=True)
for trial in range(5):
    t0 = time.perf_counter()
    out = solve_pipeline(dev_args[0], pa_h, dev_args[2], tb_h, dev_args[4], au_h,
                         dev_args[6], dev_args[7], deterministic=False, term_kinds=term_kinds)
    jax.block_until_ready(out)
    print(f"host-args solve trial {trial}: {time.perf_counter()-t0:.3f}s", flush=True)

# 4. fetch cost: device->host of the [B] assign only
assign = out[0]
for trial in range(3):
    t0 = time.perf_counter()
    np.asarray(assign)
    print(f"fetch assign trial {trial}: {time.perf_counter()-t0:.3f}s", flush=True)
