#!/usr/bin/env python
"""A/B microbench: object-walk vs columnar bulk assume/forget/bind.

Two measurements of the scheduler cache's commit/apply stage:

1. CACHE UPDATE (the component the columnar plane replaced — the >=5x
   acceptance number, measured here and quoted in PERF.md round 12; the
   ratio is reported, not CI-asserted, since shared-runner jitter rules
   hard timing gates out): applying a committed batch's adds + a
   rollback's removes to the cache's hot state.
     A (object walk) — the legacy path inside bulk assume/forget: per
       pod, `_add_pod_to_node`/`_remove_pod_from_node` → NodeInfo
       `_account` (Quantity-derived dict arithmetic, affinity list
       upkeep, port tuples) + the linear `pods` scan on remove.
     B (columnar)    — state/columns.py: ONE gather of interned
       per-spec delta rows + np.add.at scatters, journal appends only.
2. FULL STAGE CYCLE (reported for context): the public bulk API —
   assume_pods → finish_bindings → forget_pods — on both transports.
   The per-pod state machine (key dedup, _PodState, TTL bookkeeping) is
   UNCHANGED by the plane and common to both, so this ratio is smaller
   by construction; it is the end-to-end stage wall.

Memo pre-warming is pipeline-shaped: in the real driver the per-pod
request memos are computed once upstream (ingest staging at enqueue /
fold planning before the apply) and `with_node` clones inherit them, so
both transports arrive at the commit stage with warm memos; the bench
reproduces that (and B's spec slots via `delta_mats`, exactly what
`commit/fold.plan_fold` does).

Timing discipline matches the other microbenches: trials interleave
A/B/A/B so drift hits both alike. BIT-IDENTITY is asserted before
timing: after a half-forgotten cycle, B's lazily-materialized NodeInfo
aggregates and its columns must both agree exactly with A's eagerly
maintained objects.

Run: python scripts/microbench_cache.py [n_nodes] [n_pods]
Smoke (tier-1, via tests/test_columnar_cache.py): main(smoke=True).
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from kubernetes_tpu.api.types import (  # noqa: E402
    Container,
    Quantity,
    RESOURCE_CPU,
    RESOURCE_EPHEMERAL_STORAGE,
    RESOURCE_MEMORY,
)
from kubernetes_tpu.models.generators import make_node, make_pod  # noqa: E402
from kubernetes_tpu.oracle.nodeinfo import (  # noqa: E402
    accumulated_request,
    pod_non_zero_request,
)
from kubernetes_tpu.state.cache import SchedulerCache  # noqa: E402
from kubernetes_tpu.state.tensors import Vocab  # noqa: E402

N_SPECS = 32  # distinct controller specs; replicas share delta rows


def _mk_cache(n_nodes, columnar):
    cache = SchedulerCache()
    for i in range(n_nodes):
        cache.add_node(make_node(
            f"n{i}", cpu_milli=64_000_000,
            labels={"kubernetes.io/hostname": f"n{i}", "zone": f"z{i % 4}"},
        ))
    if columnar:
        cache.attach_columns(Vocab())
    return cache


def _mk_wave(tag, n_pods, n_nodes):
    """One trial's pre-cloned assumed pods (fresh keys per wave — the
    cache rejects re-used keys), request memos pre-warmed the way the
    pipeline leaves them by commit time (staging/fold planning run on
    the base pods; with_node clones carry the memos)."""
    out = []
    for i in range(n_pods):
        # a k8s-typical two-container spec (app + sidecar) with cpu/mem/
        # ephemeral requests — the request shape the object walk's
        # per-pod dict arithmetic actually pays for in the bench configs
        spec = i % N_SPECS
        containers = [
            Container(name="main", image="img:app", requests={
                RESOURCE_CPU: Quantity.parse(f"{100 + spec}m"),
                RESOURCE_MEMORY: Quantity.parse(64 * 2**20),
                RESOURCE_EPHEMERAL_STORAGE: Quantity.parse(2**30),
            }),
            Container(name="sidecar", image="img:sidecar", requests={
                RESOURCE_CPU: Quantity.parse("50m"),
                RESOURCE_MEMORY: Quantity.parse(16 * 2**20),
            }),
        ]
        p = make_pod(f"{tag}-p{i}", cpu_milli=0, mem=0, labels={"app": f"a{spec}"})
        p.containers = containers
        c = p.with_node(f"n{i % n_nodes}")
        accumulated_request(c)
        pod_non_zero_request(c)
        c.host_ports()
        c.key()
        out.append(c)
    return out


def _cycle(cache, wave, forget_all=True):
    """The full public stage cycle: one bulk assume, one bulk
    finish-bindings, one (gang-rollback-shaped) bulk forget."""
    rejected = cache.assume_pods(wave)
    assert not rejected
    cache.finish_bindings(wave)
    cache.forget_pods(wave if forget_all else wave[: len(wave) // 2])


def _object_state(cache):
    """Every node's aggregate state, materializing lazy views on read."""
    out = {}
    for name in sorted(cache.snapshot.node_infos):
        ni = cache.snapshot.node_infos[name]  # lazy map resolves here
        out[name] = (
            tuple(sorted(ni.requested().items())),
            ni.non_zero_requested(),
            len(ni.pods),
            tuple(sorted(p.key() for p in ni.pods)),
            tuple(sorted(ni.used_host_ports())),
        )
    return out


def _update_object(cache, wave):
    """Cache-update half, legacy transport: the per-pod object walk bulk
    assume/forget drive (state machine excluded — it is identical on
    both transports)."""
    with cache._lock:
        for p in wave:
            cache._add_pod_to_node(p)
        for p in wave:
            cache._remove_pod_from_node(p)


def _update_columnar(cache, rows, wave):
    """Cache-update half, columnar transport: the vectorized scatter +
    journal the bulk paths dispatch."""
    cols = cache._columns
    with cache._lock:
        cols.assume_bulk_locked(rows, wave)
        cols.forget_bulk_locked(rows, wave)


def _reset_transport_state(cache):
    """Drop the side effects the update halves leave (delta log, lazy
    journal) so trials stay O(1) in trial count."""
    with cache._lock:
        cache.pod_deltas.clear()
        cache.dirty_nodes.clear()
        cols = cache._columns
        if cols is not None:
            for row in list(cols._stale_rows):
                cols._pending[row] = []
            cols._stale_rows.clear()
            cols._overgrown.clear()


def main(smoke: bool = False):
    n_nodes = int(sys.argv[1]) if len(sys.argv) > 1 and not smoke else (16 if smoke else 512)
    n_pods = int(sys.argv[2]) if len(sys.argv) > 2 and not smoke else (128 if smoke else 4096)
    trials = 3 if smoke else 9

    cache_a = _mk_cache(n_nodes, columnar=False)
    cache_b = _mk_cache(n_nodes, columnar=True)

    # -- bit-identity first: a half-forgotten FULL cycle, compared three
    # ways (A objects vs B materialized objects vs B columns) -----------
    wave = _mk_wave("parity", n_pods, n_nodes)
    cache_b._columns.delta_mats(wave, 8)  # plan_fold-shaped slot warm
    _cycle(cache_a, wave, forget_all=False)
    _cycle(cache_b, wave, forget_all=False)
    state_a = _object_state(cache_a)
    state_b = _object_state(cache_b)  # materializes B's lazy views
    assert state_a == state_b, "A/B object aggregates diverge"
    div = cache_b._columns.object_divergence(
        {k: dict.__getitem__(cache_b.snapshot.node_infos, k)
         for k in cache_b.snapshot.node_infos}
    )
    assert div == [], f"columns diverge from materialized objects: {div}"
    cache_a.forget_pods(wave)
    cache_b.forget_pods(wave)

    # -- interleaved timing ----------------------------------------------
    upd_a, upd_b, cyc_a, cyc_b = [], [], [], []
    for t in range(trials):
        wa = _mk_wave(f"a{t}", n_pods, n_nodes)
        wb = _mk_wave(f"b{t}", n_pods, n_nodes)
        rows_b = [cache_b._columns.row_of[p.node_name] for p in wb]
        cache_b._columns.delta_mats(wb, 8)  # plan_fold warms the slots
        # cache-update half, interleaved
        t0 = time.perf_counter()
        _update_object(cache_a, wa)
        upd_a.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        _update_columnar(cache_b, rows_b, wb)
        upd_b.append(time.perf_counter() - t0)
        _reset_transport_state(cache_a)
        _reset_transport_state(cache_b)
        # full public stage cycle, interleaved (fresh keys again)
        wa = _mk_wave(f"ca{t}", n_pods, n_nodes)
        wb = _mk_wave(f"cb{t}", n_pods, n_nodes)
        cache_b._columns.delta_mats(wb, 8)
        t0 = time.perf_counter()
        _cycle(cache_a, wa)
        cyc_a.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        _cycle(cache_b, wb)
        cyc_b.append(time.perf_counter() - t0)
        _reset_transport_state(cache_a)
        _reset_transport_state(cache_b)

    med = lambda xs: float(np.median(xs))  # noqa: E731
    upd_ma, upd_mb = med(upd_a), med(upd_b)
    cyc_ma, cyc_mb = med(cyc_a), med(cyc_b)
    out = {
        "n_nodes": n_nodes,
        "n_pods": n_pods,
        "specs": N_SPECS,
        # the replaced component: per-pod object walk vs columnar scatter
        "update_object_ms": round(upd_ma * 1e3, 3),
        "update_columnar_ms": round(upd_mb * 1e3, 3),
        "update_speedup": round(upd_ma / upd_mb, 2) if upd_mb > 0 else None,
        # the end-to-end public stage cycle (state machine included)
        "cycle_object_ms": round(cyc_ma * 1e3, 3),
        "cycle_columnar_ms": round(cyc_mb * 1e3, 3),
        "cycle_speedup": round(cyc_ma / cyc_mb, 2) if cyc_mb > 0 else None,
        "columnar_stats": cache_b._columns.stats_snapshot(),
    }
    if not smoke:
        print(out, flush=True)
    return out


if __name__ == "__main__":
    import json

    print(json.dumps(main()))
