#!/usr/bin/env python
"""Truthful timing on the axon tunnel: chain N data-dependent calls, fetch a
scalar, divide by N. Avoids block_until_ready lies and fetch-latency noise."""
import os
import sys
import time
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_compilation_cache_dir", "/tmp/jax_compile_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

import jax.numpy as jnp
import numpy as np

from bench import ZONES, mk_node, mk_pod
from kubernetes_tpu.api.types import LabelSelector, TopologySpreadConstraint
from kubernetes_tpu.oracle import Snapshot
from kubernetes_tpu.ops.pipeline import encode_solve_args, mask_and_score, solve_pipeline
from kubernetes_tpu.ops.solver import pop_order, solve_greedy

N_NODES, BATCH = 10000, 1024
nodes = [mk_node(i, zone=ZONES[i % len(ZONES)]) for i in range(N_NODES)]
pods = []
for i in range(BATCH):
    p = mk_pod(i, labels={"app": f"svc-{i % 100}"})
    p.topology_spread_constraints = [TopologySpreadConstraint(
        max_skew=1, topology_key="failure-domain.beta.kubernetes.io/zone",
        when_unsatisfiable="ScheduleAnyway",
        label_selector=LabelSelector(match_labels={"app": p.labels["app"]}))]
    pods.append(p)
snap = Snapshot(nodes, [])
args = encode_solve_args(snap, pods)
dev_args = jax.device_put(args)
_ = np.asarray(jax.tree_util.tree_leaves(dev_args)[0][:1])  # settle uploads
na, pa, ea, tb, xa, au, ids, key = dev_args
term_kinds = frozenset({"spread_soft", "sel_spread"})


def chain(label, fn, seed_key, n=8):
    # warm (compile) once
    out = fn(seed_key)
    float(jnp.sum(out[0] if isinstance(out, tuple) else out).astype(jnp.float32))
    t0 = time.perf_counter()
    k = seed_key
    for i in range(n):
        k = jax.random.fold_in(k, i)
        out = fn(k)
        x = out[0] if isinstance(out, tuple) else out
        _ = float(jnp.max(x).astype(jnp.float32))  # scalar fetch forces completion
    dt = (time.perf_counter() - t0) / n
    print(f"{label}: {dt*1000:.1f}ms/call (chained {n})", flush=True)


ms_jit = jax.jit(partial(mask_and_score, config=None, term_kinds=term_kinds))
chain("mask_and_score", lambda k: ms_jit(na, pa, ea, tb, xa, au, ids), key)

mask, score = ms_jit(na, pa, ea, tb, xa, au, ids)
mask, score = jax.device_put((mask, score))
free0 = na["alloc"] - na["requested"]
order = pop_order(pa["priority"], jnp.arange(pa["valid"].shape[0], dtype=jnp.int32), pa["valid"])
count0 = na["pod_count"].astype(free0.dtype)
allowed = na["allowed_pods"].astype(free0.dtype)

chain("solve_greedy", lambda k: solve_greedy(
    mask, score, pa["req"], free0, count0, allowed, order, k,
    deterministic=False, req_any=pa["req_any"]), key)

chain("solve_pipeline", lambda k: solve_pipeline(
    *dev_args[:7], k, deterministic=False, term_kinds=term_kinds), key)
