#!/usr/bin/env python
"""ktpu-lint: enforce the repo's compile-plan / donation / lock invariants.

    python scripts/ktpu_lint.py                   # report all violations
    python scripts/ktpu_lint.py --check           # gate: fail if the set GREW
    python scripts/ktpu_lint.py --update-baseline # re-pin the baseline
    python scripts/ktpu_lint.py --rule KTPU003 kubernetes_tpu/state

The gate compares against kubernetes_tpu/analysis/baseline.txt: every
baselined entry carries a human justification; violations not in the
baseline fail the run (preflight + tier-1 both call --check). Stale
baseline entries (fixed violations) are reported so the file ratchets
down — they never fail the gate.

Rules: KTPU001 no-unplanned-jit, KTPU002 donation-safety, KTPU003
guarded-by, KTPU004 hot-path-host-sync, KTPU005 shadowed-module-import.
See INVARIANTS.md for the rule ↔ historical-bug cross-reference and the
annotation grammar (# ktpu: guarded-by/holds/hot-path/admitted/allow/...).
"""

from __future__ import annotations

import argparse
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from kubernetes_tpu.analysis import Baseline, scan_paths  # noqa: E402
from kubernetes_tpu.analysis.checkers import ALL_CHECKERS, repo_config  # noqa: E402

BASELINE_PATH = os.path.join(_REPO, "kubernetes_tpu", "analysis", "baseline.txt")
DEFAULT_SCAN = os.path.join(_REPO, "kubernetes_tpu")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="*", help="files/dirs (default: kubernetes_tpu/)")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 when violations beyond the baseline exist")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline to the current violation set")
    ap.add_argument("--rule", action="append", default=None,
                    help="restrict to one or more rule ids (repeatable)")
    ap.add_argument("--baseline", default=BASELINE_PATH)
    args = ap.parse_args(argv)

    paths = args.paths or [DEFAULT_SCAN]
    rules = set(args.rule) if args.rule else None
    violations = scan_paths(paths, _REPO, repo_config(), ALL_CHECKERS, rules)

    if args.update_baseline:
        if rules or args.paths:
            # a filtered scan sees a SUBSET of violations; saving it would
            # silently drop every other baselined entry + justification
            print(
                "--update-baseline requires a full default scan "
                "(no --rule, no path arguments): the baseline is rewritten "
                "from the scan's violation set."
            )
            return 2
        base = Baseline.load(args.baseline)
        base.save(args.baseline, violations)
        print(f"baseline updated: {len(violations)} entries -> {args.baseline}")
        return 0

    if not args.check:
        for v in violations:
            print(v.render())
        print(f"{len(violations)} violation(s)")
        return 1 if violations else 0

    # --check: fail closed only when the set grows beyond the baseline
    base = Baseline.load(args.baseline)
    new = base.missing(violations)
    stale = base.stale(violations)
    for fp in stale:
        print(f"stale baseline entry (violation fixed — remove the line): {fp}")
    if new:
        print(f"\n{len(new)} NEW violation(s) beyond the baseline:\n")
        for v in new:
            print(v.render())
            print()
        print(
            "Fix the violation, annotate the deliberate exception "
            "(# ktpu: allow/admitted/host-sync-ok/holds — see INVARIANTS.md), "
            "or, for a pre-existing condition only, add the fingerprint to "
            f"{os.path.relpath(args.baseline, _REPO)} with a justification."
        )
        return 1
    n_base = len(violations) - len(new)
    print(
        f"ktpu-lint: OK — {len(violations)} violation(s), all baselined "
        f"({n_base} baseline entries used, {len(stale)} stale)."
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
