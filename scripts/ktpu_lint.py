#!/usr/bin/env python
"""ktpu-lint: enforce the repo's compile-plan / donation / lock invariants.

    python scripts/ktpu_lint.py                   # report all violations
    python scripts/ktpu_lint.py --check           # gate: fail if the set GREW
    python scripts/ktpu_lint.py --update-baseline # re-pin the baseline
    python scripts/ktpu_lint.py --rule KTPU003 kubernetes_tpu/state
    python scripts/ktpu_lint.py --check --json    # machine-readable report
    python scripts/ktpu_lint.py --check --time-budget 60   # preflight gate

The gate compares against kubernetes_tpu/analysis/baseline.txt: every
baselined entry carries a human justification; violations not in the
baseline fail the run (preflight + tier-1 both call --check). Stale
baseline entries (fixed violations) are reported so the file ratchets
down — they never fail the gate.

Rules: the module-local KTPU001 no-unplanned-jit, KTPU002
donation-safety, KTPU003 guarded-by, KTPU004 hot-path-host-sync,
KTPU005 shadowed-module-import — plus the interprocedural (repo-wide
call graph + thread-role inference, analysis/callgraph.py + roles.py)
KTPU006 shared-attr-inference, KTPU007 transitive-hot-path-sync and
KTPU008 confinement-reachability. See INVARIANTS.md for the rule ↔
historical-bug cross-reference and the annotation grammar
(# ktpu: guarded-by/holds/hot-path/admitted/thread-entry/allow/...).

``--json`` emits one object: ``violations`` (rule/file/line/scope/
message/fingerprint), ``timings_s`` per rule (plus ``callgraph`` for
the shared graph build) and ``total_s`` — the wall the ``--time-budget``
gate asserts so the interprocedural pass can't silently make preflight
crawl.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from kubernetes_tpu.analysis import Baseline  # noqa: E402
from kubernetes_tpu.analysis.checkers import ALL_CHECKERS, repo_config  # noqa: E402
from kubernetes_tpu.analysis.callgraph import build_graph  # noqa: E402
from kubernetes_tpu.analysis.core import (  # noqa: E402
    iter_python_files,
    load_module,
    run_checkers,
)
from kubernetes_tpu.analysis.roles import (  # noqa: E402
    REPO_RULES,
    run_repo_checkers,
)

BASELINE_PATH = os.path.join(_REPO, "kubernetes_tpu", "analysis", "baseline.txt")
DEFAULT_SCAN = os.path.join(_REPO, "kubernetes_tpu")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="*", help="files/dirs (default: kubernetes_tpu/)")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 when violations beyond the baseline exist")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline to the current violation set")
    ap.add_argument("--rule", action="append", default=None,
                    help="restrict to one or more rule ids (repeatable)")
    ap.add_argument("--baseline", default=BASELINE_PATH)
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output (one JSON object on stdout)")
    ap.add_argument("--time-budget", type=float, default=None, metavar="SECONDS",
                    help="exit 3 when the total lint wall exceeds this many "
                         "seconds (preflight asserts the interprocedural "
                         "pass stays fast)")
    args = ap.parse_args(argv)

    paths = args.paths or [DEFAULT_SCAN]
    rules = set(args.rule) if args.rule else None
    if args.update_baseline and (rules or args.paths):
        # a filtered scan sees a SUBSET of violations; saving it would
        # silently drop every other baselined entry + justification —
        # refuse BEFORE paying for the scan
        print(
            "--update-baseline requires a full default scan "
            "(no --rule, no path arguments): the baseline is rewritten "
            "from the scan's violation set."
        )
        return 2
    timings: dict = {}
    t0 = time.perf_counter()
    # parse each module ONCE and share the ModuleInfo list between the
    # module-local checkers and the call-graph build (the graph re-parsing
    # the identical file set used to double the whole parse cost)
    files: list = []
    for p in paths:
        files.extend(iter_python_files(p) if os.path.isdir(p) else [p])
    config = repo_config()
    mods, violations = [], []
    for f in files:
        try:
            mod = load_module(f, _REPO)
        except SyntaxError:
            continue  # not this gate's job to police parseability
        mods.append(mod)
        violations.extend(run_checkers(mod, config, ALL_CHECKERS, rules, timings))
    # interprocedural rules: one shared call graph over the SAME module
    # set (a filtered graph is a smaller world — fine for spot checks;
    # the gate and the baseline always run the full default scan). A
    # --rule filter naming only module-local rules skips the graph.
    if rules is None or rules & set(REPO_RULES):
        t_graph = time.perf_counter()
        graph = build_graph(mods)
        timings["callgraph"] = time.perf_counter() - t_graph
        violations.extend(run_repo_checkers(graph, config, rules, timings=timings))
    violations.sort(key=lambda v: (v.path, v.line, v.rule))
    total_s = time.perf_counter() - t0
    over_budget = args.time_budget is not None and total_s > args.time_budget

    def emit_json(extra: dict) -> None:
        doc = {
            "violations": [
                {
                    "rule": v.rule,
                    "file": v.path,
                    "line": v.line,
                    "scope": v.scope,
                    "message": v.message,
                    "fingerprint": v.fingerprint(),
                }
                for v in violations
            ],
            "timings_s": {k: round(t, 4) for k, t in sorted(timings.items())},
            "total_s": round(total_s, 4),
            "time_budget_s": args.time_budget,
            "budget_exceeded": over_budget,
        }
        doc.update(extra)
        print(json.dumps(doc, indent=1))

    if args.update_baseline:
        base = Baseline.load(args.baseline)
        base.save(args.baseline, violations)
        print(f"baseline updated: {len(violations)} entries -> {args.baseline}")
        return 0

    if not args.check:
        if args.as_json:
            emit_json({"mode": "report"})
        else:
            for v in violations:
                print(v.render())
            print(f"{len(violations)} violation(s)")
            print(
                "timings: "
                + " ".join(f"{k}={t:.3f}s" for k, t in sorted(timings.items()))
                + f" total={total_s:.3f}s"
            )
        if violations:
            return 1
        return 3 if over_budget else 0

    # --check: fail closed only when the set grows beyond the baseline
    base = Baseline.load(args.baseline)
    new = base.missing(violations)
    stale = base.stale(violations)
    if args.as_json:
        emit_json({
            "mode": "check",
            "new": [v.fingerprint() for v in new],
            "stale": stale,
            "baselined": len(violations) - len(new),
            "ok": not new and not over_budget,
        })
        if new:
            return 1
        return 3 if over_budget else 0
    for fp in stale:
        print(f"stale baseline entry (violation fixed — remove the line): {fp}")
    if new:
        print(f"\n{len(new)} NEW violation(s) beyond the baseline:\n")
        for v in new:
            print(v.render())
            print()
        print(
            "Fix the violation, annotate the deliberate exception "
            "(# ktpu: allow/admitted/host-sync-ok/holds/thread-entry — see "
            "INVARIANTS.md), or, for a pre-existing condition only, add the "
            "fingerprint to "
            f"{os.path.relpath(args.baseline, _REPO)} with a justification."
        )
        return 1
    n_base = len(violations) - len(new)
    print(
        f"ktpu-lint: OK — {len(violations)} violation(s), all baselined "
        f"({n_base} baseline entries used, {len(stale)} stale); "
        f"wall {total_s:.2f}s ("
        + ", ".join(f"{k} {t:.2f}s" for k, t in sorted(timings.items()))
        + ")."
    )
    if over_budget:
        print(
            f"ktpu-lint: TIME BUDGET EXCEEDED — {total_s:.2f}s > "
            f"{args.time_budget:.2f}s (the interprocedural pass is the "
            "usual suspect: check callgraph build time above)"
        )
        return 3
    return 0


if __name__ == "__main__":
    sys.exit(main())
