#!/usr/bin/env python
"""Is the 1.5s/call solve_pipeline cost retracing, execution, or transfer?"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_compilation_cache_dir", "/tmp/jax_compile_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

import numpy as np

from bench import ZONES, mk_node, mk_pod
from kubernetes_tpu.api.types import LabelSelector, TopologySpreadConstraint
from kubernetes_tpu.oracle import Snapshot
from kubernetes_tpu.ops.pipeline import encode_solve_args, solve_pipeline

N_NODES, BATCH = 10000, 1024
nodes = [mk_node(i, zone=ZONES[i % len(ZONES)]) for i in range(N_NODES)]
pods = []
for i in range(BATCH):
    p = mk_pod(i, labels={"app": f"svc-{i % 100}"})
    p.topology_spread_constraints = [TopologySpreadConstraint(
        max_skew=1, topology_key="failure-domain.beta.kubernetes.io/zone",
        when_unsatisfiable="ScheduleAnyway",
        label_selector=LabelSelector(match_labels={"app": p.labels["app"]}))]
    pods.append(p)
snap = Snapshot(nodes, [])
args = encode_solve_args(snap, pods)
dev_args = jax.device_put(args)
jax.block_until_ready(dev_args)
term_kinds = frozenset({"spread_soft", "sel_spread"})

kw = dict(deterministic=False, term_kinds=term_kinds)

# warmup
out = solve_pipeline(*dev_args, **kw)
jax.block_until_ready(out)
print("tracing cache size after warmup:", solve_pipeline._cache_size(), flush=True)

for i in range(3):
    t0 = time.perf_counter()
    out = solve_pipeline(*dev_args, **kw)
    t1 = time.perf_counter()
    jax.block_until_ready(out)
    t2 = time.perf_counter()
    np.asarray(out[0])
    t3 = time.perf_counter()
    print(f"call {i}: dispatch {t1-t0:.3f}s block {t2-t1:.3f}s fetch-assign {t3-t2:.3f}s",
          flush=True)
print("tracing cache size after loop:", solve_pipeline._cache_size(), flush=True)

# AOT compile path
lowered = solve_pipeline.lower(*dev_args, **kw)
t0 = time.perf_counter()
compiled = lowered.compile()
print(f"AOT compile: {time.perf_counter()-t0:.1f}s", flush=True)
for i in range(3):
    t0 = time.perf_counter()
    out = compiled(*dev_args)
    t1 = time.perf_counter()
    jax.block_until_ready(out)
    t2 = time.perf_counter()
    print(f"AOT call {i}: dispatch {t1-t0:.3f}s block {t2-t1:.3f}s", flush=True)
