#!/usr/bin/env python
"""ktpu top: a live terminal table of the steady-state health census.

Renders the per-plane slab/occupancy/staleness view from EITHER source:

  * the ``/debug/ktpu`` JSON route (the full versioned census —
    preferred: includes the ladder kinds, fold bookkeeping, and the
    monitor's shadow-audit tallies), or
  * a raw ``/metrics`` registry scrape (the ``ktpu_*`` gauge subset —
    works against any Prometheus-compatible relay of the scrape, no
    debug route required).

Usage:
    python scripts/ktpu_top.py --url http://127.0.0.1:9090            # auto
    python scripts/ktpu_top.py --url http://... --source metrics      # scrape
    python scripts/ktpu_top.py --url http://... --once                # one shot

The render functions are pure (census/parsed-scrape dict -> str) so the
test suite drives them without a server.
"""

from __future__ import annotations

import json
import re
import sys
import time
import urllib.request
from typing import Dict, List, Optional, Tuple

#: one Prometheus text-format sample line: name{labels} value
_SAMPLE = re.compile(
    r'^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)'
    r'(?:\{(?P<labels>[^}]*)\})?'
    r' (?P<value>-?[0-9.eE+-]+|\+Inf|-Inf|NaN)$'
)
_LABEL = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\\n]|\\["\\n])*)"')

_PLANE_ORDER = (
    "ingest", "terms", "columns", "mirror_nodes", "mirror_sigs",
    "mirror_patterns",
)


def parse_metrics_text(text: str) -> Dict[str, Dict[Tuple[Tuple[str, str], ...], float]]:
    """{metric name: {sorted (label, value) tuple: sample value}} from a
    raw /metrics body. Comment/blank lines skipped; unparseable sample
    lines raise (a scrape the Prometheus parser would reject must not be
    silently half-rendered)."""
    out: Dict[str, Dict[Tuple[Tuple[str, str], ...], float]] = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE.match(line)
        if m is None:
            raise ValueError(f"unparseable /metrics line: {line!r}")
        labels = tuple(sorted(
            (k, v) for k, v in _LABEL.findall(m.group("labels") or "")
        ))
        value = m.group("value")
        v = float("inf") if value == "+Inf" else (
            float("-inf") if value == "-Inf" else float(value)
        )
        out.setdefault(m.group("name"), {})[labels] = v
    return out


def _metric(parsed, name, **labels) -> Optional[float]:
    series = parsed.get(name)
    if not series:
        return None
    key = tuple(sorted(labels.items()))
    return series.get(key)


def _fmt(v, integer=True) -> str:
    if v is None:
        return "-"
    if integer:
        return str(int(v))
    return f"{v:.2f}"


def _table(rows: List[Tuple[str, ...]], header: Tuple[str, ...]) -> str:
    widths = [
        max(len(str(r[i])) for r in [header] + rows) for i in range(len(header))
    ]
    lines = ["  ".join(str(c).ljust(w) for c, w in zip(header, widths))]
    for r in rows:
        lines.append("  ".join(str(c).ljust(w) for c, w in zip(r, widths)))
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# render: census (the /debug/ktpu document)
# ---------------------------------------------------------------------------

def render_census(doc: Dict) -> str:
    p = doc.get("planes", {})
    q = p.get("queue") or {}
    out = [
        f"ktpu top — census v{doc.get('version')} — "
        f"ready={doc.get('ready')}",
        (
            f"queue    active={_fmt(q.get('active'))} "
            f"backoff={_fmt(q.get('backoff'))} "
            f"unschedulable={_fmt(q.get('unschedulable'))} "
            f"oldest={_fmt(q.get('oldest_pending_age_s'), integer=False)}s "
            f"nominated={_fmt(q.get('nominated'))}"
        ),
    ]
    rows: List[Tuple[str, ...]] = []
    for key, label in (("ingest", "ingest"), ("terms", "terms")):
        d = p.get(key) or {}
        if d.get("enabled") is False:
            rows.append((label, "off", "-", "-", "-"))
            continue
        rows.append((
            label,
            f"{_fmt(d.get('rows'))}/{_fmt(d.get('capacity'))}",
            _fmt(d.get("free_rows")), _fmt(d.get("dirty_rows")),
            _fmt(d.get("refs_total")),
        ))
    cols = (p.get("cache") or {}).get("columns")
    if cols:
        rows.append((
            "columns",
            f"{_fmt(cols.get('rows'))}/{_fmt(cols.get('capacity'))}",
            _fmt(cols.get("free_rows")), _fmt(cols.get("stale_rows")),
            f"j={_fmt(cols.get('journal_depth'))}",
        ))
    mir = p.get("mirror") or {}
    if mir:
        stale = (
            (mir.get("pending_node_rows") or 0)
            + (mir.get("pending_usage_rows") or 0)
        )
        rows.append((
            "mirror_nodes",
            f"{_fmt(mir.get('node_rows'))}/{_fmt(mir.get('node_capacity'))}",
            "-", _fmt(stale),
            f"folds={_fmt(mir.get('fold_count'))}",
        ))
        rows.append((
            "mirror_sigs",
            f"{_fmt(mir.get('sig_rows'))}/{_fmt(mir.get('sig_capacity'))}",
            "-", _fmt(mir.get("dirty_sig_rows")), "-",
        ))
        rows.append((
            "mirror_patterns",
            f"{_fmt(mir.get('pattern_rows'))}/"
            f"{_fmt(mir.get('pattern_capacity'))}",
            "-", _fmt(mir.get("dirty_pattern_rows")), "-",
        ))
    out.append(_table(rows, ("PLANE", "ROWS/CAP", "FREE", "STALE", "REFS")))
    comp = p.get("compile") or {}
    kinds = comp.get("kinds") or {}
    kind_bits = " ".join(
        f"{k}={v.get('rungs')}" for k, v in sorted(kinds.items())
    )
    out.append(
        f"ladder   specs={_fmt(comp.get('declared_specs'))} "
        f"misses_after_warmup={_fmt(comp.get('misses_after_warmup'))} "
        f"[{kind_bits}]"
    )
    commit = p.get("commit") or {}
    cstats = commit.get("stats") or {}
    out.append(
        f"commit   in_flight={int(bool(commit.get('in_flight')))} "
        f"submitted={_fmt(cstats.get('submitted'))}"
    )
    rec = p.get("recorder") or {}
    out.append(
        f"recorder enabled={int(bool(rec.get('enabled')))} "
        f"pending_device={_fmt(rec.get('pending_device'))} "
        f"blackbox={_fmt(rec.get('blackbox_records'))}"
    )
    mon = doc.get("monitor")
    if mon:
        audits = mon.get("shadow_audits") or {}
        div = mon.get("last_divergence") or []
        out.append(
            f"audits   clean={_fmt(audits.get('clean'))} "
            f"divergent={_fmt(audits.get('divergent'))}"
            + (f" LAST DIVERGENCE: {div}" if div else "")
        )
    faults = p.get("faults") or {}
    breakers = faults.get("breakers") or {}
    if breakers:
        # one line, closed planes compressed — open/half-open breakers
        # are the thing an operator is looking for
        bits = []
        for plane, b in sorted(breakers.items()):
            if b.get("state") == "closed" and not b.get("trips"):
                continue
            bits.append(
                f"{plane}={b.get('state')}"
                f"(trips={b.get('trips')},reason={b.get('last_reason')})"
            )
        out.append(
            "breakers " + (" ".join(bits) if bits else "all closed, 0 trips")
        )
    restart = p.get("restart") or {}
    if restart.get("reconciled"):
        # the crash-restart plane's flight record: when this instance
        # last cold-start reconciled, and what each phase cost
        last = restart.get("last") or {}
        phases = last.get("phases_s") or {}
        phase_bits = " ".join(
            f"{k}={v:.3f}s" for k, v in phases.items()
        )
        out.append(
            f"restart  reconciled nodes={_fmt(last.get('nodes'))} "
            f"bound={_fmt(last.get('bound'))} "
            f"pending={_fmt(last.get('pending'))} "
            f"nominations={_fmt(last.get('nominations'))} "
            f"total={_fmt(last.get('total_s'), integer=False)}s"
            + (f" [{phase_bits}]" if phase_bits else "")
        )
    else:
        out.append("restart  never reconciled (cold-started fresh)")
    return "\n".join(out)


# ---------------------------------------------------------------------------
# render: raw registry scrape (the ktpu_* gauge subset)
# ---------------------------------------------------------------------------

def render_metrics(parsed: Dict) -> str:
    out = ["ktpu top — /metrics scrape"]
    out.append(
        "queue    "
        f"active={_fmt(_metric(parsed, 'scheduler_pending_pods', queue='active'))} "
        f"backoff={_fmt(_metric(parsed, 'scheduler_pending_pods', queue='backoff'))} "
        f"unschedulable={_fmt(_metric(parsed, 'scheduler_pending_pods', queue='unschedulable'))} "
        f"oldest={_fmt(_metric(parsed, 'scheduler_queue_oldest_pending_age_seconds'), integer=False)}s"
    )
    rows = []
    for plane in _PLANE_ORDER:
        occ = _metric(parsed, "ktpu_plane_slab_occupancy", plane=plane)
        if occ is None:
            continue
        cap = _metric(parsed, "ktpu_plane_slab_capacity", plane=plane)
        rows.append((
            plane,
            f"{_fmt(occ)}/{_fmt(cap)}",
            _fmt(_metric(parsed, "ktpu_plane_free_rows", plane=plane)),
            _fmt(_metric(parsed, "ktpu_plane_stale_rows", plane=plane)),
            _fmt(_metric(parsed, "ktpu_plane_refs_total", plane=plane)),
        ))
    out.append(_table(rows, ("PLANE", "ROWS/CAP", "FREE", "STALE", "REFS")))
    ladder = parsed.get("ktpu_compile_ladder_rungs") or {}
    kind_bits = " ".join(
        f"{dict(labels).get('kind')}={int(v)}"
        for labels, v in sorted(ladder.items())
    )
    out.append(
        f"ladder   misses_after_warmup="
        f"{_fmt(_metric(parsed, 'scheduler_compile_spec_misses_after_warmup'))} "
        f"[{kind_bits}]"
    )
    out.append(
        f"commit   in_flight={_fmt(_metric(parsed, 'ktpu_commit_inflight'))}"
    )
    out.append(
        "audits   "
        f"clean={_fmt(_metric(parsed, 'ktpu_shadow_audit_total', result='clean'))} "
        f"divergent={_fmt(_metric(parsed, 'ktpu_shadow_audit_total', result='divergent'))} "
        f"journal={_fmt(_metric(parsed, 'ktpu_cache_journal_depth'))}"
    )
    states = parsed.get("ktpu_plane_breaker_state") or {}
    if states:
        _NAMES = {0.0: "closed", 1.0: "half_open", 2.0: "open"}
        bits = [
            f"{dict(labels).get('plane')}={_NAMES.get(v, v)}"
            for labels, v in sorted(states.items())
            if v  # closed breakers stay quiet, like the census render
        ]
        out.append(
            "breakers " + (" ".join(bits) if bits else "all closed")
        )
    return "\n".join(out)


# ---------------------------------------------------------------------------
# fetch + main loop
# ---------------------------------------------------------------------------

def snapshot_from_debug(base_url: str, timeout: float = 5.0) -> str:
    with urllib.request.urlopen(f"{base_url}/debug/ktpu", timeout=timeout) as r:
        return render_census(json.loads(r.read().decode()))


def snapshot_from_metrics(base_url: str, timeout: float = 5.0) -> str:
    with urllib.request.urlopen(f"{base_url}/metrics", timeout=timeout) as r:
        return render_metrics(parse_metrics_text(r.read().decode()))


def main(argv: List[str]) -> int:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--url", required=True,
                    help="MetricsServer base url, e.g. http://127.0.0.1:9090")
    ap.add_argument("--source", choices=("auto", "debug", "metrics"),
                    default="auto")
    ap.add_argument("--interval", type=float, default=2.0)
    ap.add_argument("--once", action="store_true")
    args = ap.parse_args(argv)

    def shot() -> str:
        if args.source in ("auto", "debug"):
            try:
                return snapshot_from_debug(args.url)
            except Exception:
                if args.source == "debug":
                    raise
        return snapshot_from_metrics(args.url)

    if args.once:
        print(shot())
        return 0
    try:
        while True:
            body = shot()
            sys.stdout.write("\x1b[2J\x1b[H" + body + "\n")
            sys.stdout.flush()
            time.sleep(args.interval)
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
