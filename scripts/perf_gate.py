#!/usr/bin/env python
"""Perf-budget regression gate: machine-check "did this PR regress a
stage budget" against the committed budget file
(kubernetes_tpu/analysis/perf_budget.json) — the scheduler_perf
threshold discipline of the reference (PAPER.md §9), wired into
preflight.sh next to the ktpu-lint invariant gate.

How it measures
---------------
Stage budgets are p99 ceilings over the
``scheduler_scheduling_stage_duration_seconds`` histogram, computed as a
DELTA: ``snapshot_stages()`` captures per-stage bucket counts after
warmup, the measured drain runs, and ``stage_p99_delta()`` diffs — so
warmup's inline compiles and (in a shared pytest process) other tests'
observations never pollute the gated number. Quantized to bucket
resolution: the gate catches order-of-magnitude regressions (a stage
newly paying an inline XLA compile, a hidden device sync), not 10%
noise. Counter invariants (misses_after_warmup, sharded fallbacks,
legacy-path ratios) come from the measured scheduler's own stats.

Ratchet discipline (the ktpu-lint baseline contract, INVARIANTS.md)
-------------------------------------------------------------------
The budget is GROW-ONLY and fails CLOSED:
  * deleting a required stage/counter entry is a violation;
  * an entry without a justification (``why``) is a violation;
  * a stage observed in the measured drain with NO budget entry is a
    violation (new stages must gain budgets, with a why);
  * and of course any p99 over budget / counter over max is one.

Usage:
    JAX_PLATFORMS=cpu python scripts/perf_gate.py --check   # run the
        health-mode smoke drain and gate it against the budget
    python scripts/perf_gate.py --show                      # print the
        committed budget
"""

from __future__ import annotations

import json
import os
import sys
from typing import Dict, List, Optional, Tuple

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

BUDGET_VERSION = 1
BUDGET_PATH = os.path.join(
    _REPO, "kubernetes_tpu", "analysis", "perf_budget.json"
)

#: entries the committed budget MUST carry — deleting one is the
#: ratchet violation the gate fails closed on
REQUIRED_STAGES = (
    "sync", "encode", "gather", "dispatch", "fetch", "commit", "apply",
    "bind", "fold",
)
REQUIRED_COUNTERS = (
    "misses_after_warmup", "sharded_fallbacks", "ingest_legacy_ratio",
    "term_legacy_ratio",
)


def load_budget(path: Optional[str] = None) -> Dict:
    with open(path or BUDGET_PATH) as f:
        return json.load(f)


# ---------------------------------------------------------------------------
# observation collection (delta-based, warmup-excluded)
# ---------------------------------------------------------------------------

def snapshot_stages(hist=None) -> Dict[Tuple[str, ...], List[int]]:
    """Per-stage bucket-count snapshot of the stage-duration histogram —
    take one AFTER warmup / BEFORE the measured drain, pass it to
    stage_p99_delta afterwards."""
    from kubernetes_tpu.metrics import metrics as M

    h = hist if hist is not None else M.scheduling_stage_duration
    return {labels: h.bucket_counts(*labels)[0] for labels in h.labels()}


def stage_p99_delta(
    before: Dict[Tuple[str, ...], List[int]], hist=None
) -> Dict[str, float]:
    """{stage: p99 seconds} from the bucket-count DELTA since `before`
    (bucket-upper-bound resolution; +inf when the tail bucket grew).
    Stages with zero new observations are omitted."""
    from kubernetes_tpu.metrics import metrics as M

    h = hist if hist is not None else M.scheduling_stage_duration
    out: Dict[str, float] = {}
    for labels in h.labels():
        counts, _, _ = h.bucket_counts(*labels)
        prev = before.get(labels, [0] * len(counts))
        delta = [c - p for c, p in zip(counts, prev)]
        total = sum(delta)
        if total <= 0:
            continue
        target = 0.99 * total
        acc = 0
        p99 = float("inf")
        for i, b in enumerate(h.buckets):
            acc += delta[i]
            if acc >= target:
                p99 = b
                break
        out[labels[0]] = p99
    return out


def collect(
    stage_before: Dict[Tuple[str, ...], List[int]],
    counters: Dict[str, float],
    hist=None,
) -> Dict:
    """Assemble the observation dict check() consumes."""
    return {
        "stage_p99_s": stage_p99_delta(stage_before, hist=hist),
        "counters": dict(counters),
    }


def counters_from_sched(sched) -> Dict[str, float]:
    """The budget's counter invariants from a measured scheduler's own
    plan/stats (NOT the process-global registry: other tests in a shared
    pytest process legitimately exercise legacy fallbacks and would
    false-fire a global read)."""
    s = sched.stats
    idx = s.get("ingest_index_batches", 0)
    leg = s.get("ingest_legacy_batches", 0)
    tidx = s.get("term_index_batches", 0)
    tleg = s.get("term_legacy_batches", 0)
    return {
        "misses_after_warmup": int(
            sched.compile_plan.stats.get("misses_after_warmup", 0)
        ),
        "sharded_fallbacks": int(s.get("sharded_fallbacks", 0)),
        "ingest_legacy_ratio": leg / max(idx + leg, 1),
        "term_legacy_ratio": tleg / max(tidx + tleg, 1),
    }


# ---------------------------------------------------------------------------
# the gate (pure: tests inject synthetic budgets/observations)
# ---------------------------------------------------------------------------

def check(budget: Dict, obs: Dict) -> List[str]:
    """Problems list (empty = the gate passes). Fails closed on ratchet
    violations (deleted entries, missing justifications, unbudgeted
    observed stages) as well as on actual regressions."""
    problems: List[str] = []
    if budget.get("version") != BUDGET_VERSION:
        problems.append(
            f"budget version {budget.get('version')!r} != {BUDGET_VERSION}"
        )
    stages = budget.get("stage_p99_s") or {}
    counters = budget.get("counters") or {}
    for s in REQUIRED_STAGES:
        if s not in stages:
            problems.append(
                f"ratchet violation: required stage budget '{s}' missing "
                "from perf_budget.json (budgets are grow-only — entries "
                "may be loosened with justification, never deleted)"
            )
    for c in REQUIRED_COUNTERS:
        if c not in counters:
            problems.append(
                f"ratchet violation: required counter budget '{c}' missing "
                "from perf_budget.json"
            )
    for name, entry in list(stages.items()) + list(counters.items()):
        if not isinstance(entry, dict) or not str(entry.get("why", "")).strip():
            problems.append(
                f"budget entry '{name}' carries no justification ('why') — "
                "the ratchet requires every budget to explain itself"
            )
    for stage, p99 in (obs.get("stage_p99_s") or {}).items():
        entry = stages.get(stage)
        if not isinstance(entry, dict):
            problems.append(
                f"stage '{stage}' was observed in the measured drain but "
                "has NO budget entry — add one (with a why) to "
                "perf_budget.json"
            )
            continue
        try:
            limit = float(entry["budget"])
        except (KeyError, TypeError, ValueError):
            problems.append(f"stage budget '{stage}' has no numeric 'budget'")
            continue
        if p99 > limit:
            problems.append(
                f"stage '{stage}' p99 {p99:g}s exceeds budget {limit:g}s "
                "(delta-measured over the drain, warmup excluded)"
            )
    for name, value in (obs.get("counters") or {}).items():
        entry = counters.get(name)
        if not isinstance(entry, dict):
            continue  # unbudgeted counters are informational
        try:
            limit = float(entry["max"])
        except (KeyError, TypeError, ValueError):
            problems.append(f"counter budget '{name}' has no numeric 'max'")
            continue
        if float(value) > limit:
            problems.append(
                f"counter '{name}' = {value} exceeds budget max {limit:g}"
            )
    return problems


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def main(argv: List[str]) -> int:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--check", action="store_true",
                    help="run the health-mode smoke drain and gate it")
    ap.add_argument("--show", action="store_true",
                    help="print the committed budget and exit")
    ap.add_argument("--budget", default=None, help="budget file override")
    args = ap.parse_args(argv)

    budget = load_budget(args.budget)
    if args.show:
        json.dump(budget, sys.stdout, indent=2)
        print()
        return 0
    if not args.check:
        ap.print_help()
        return 2

    # structural half first: a broken budget must fail even if the run
    # would — the ratchet is not contingent on a healthy drain
    structural = check(budget, {"stage_p99_s": {}, "counters": {}})
    if structural:
        print("perf_gate: FAIL (budget file)", file=sys.stderr)
        for p in structural:
            print(f"  - {p}", file=sys.stderr)
        return 1

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    scripts_dir = os.path.dirname(os.path.abspath(__file__))
    if scripts_dir not in sys.path:
        sys.path.insert(0, scripts_dir)
    import perf_smoke

    # gate_budget=False: the smoke still raises on HEALTH regressions
    # (audits, gauges, overhead), but budget evaluation happens HERE so
    # a regression produces the structured report below — and so a
    # --budget override is actually the budget being judged
    detail = perf_smoke.main_health(gate_budget=False)
    obs = detail["budget_obs"]
    problems = check(load_budget(args.budget), obs)
    print(json.dumps({"obs": obs, "problems": problems}, indent=2))
    if problems:
        print("perf_gate: FAIL", file=sys.stderr)
        for p in problems:
            print(f"  - {p}", file=sys.stderr)
        return 1
    print("perf_gate: PASS", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
