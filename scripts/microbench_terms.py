#!/usr/bin/env python
"""A/B microbench: index-only term dispatch vs host-compiled TermBank.

Measures the two term-side transports for one solve dispatch's batch
term-table construction (the term plane's tentpole claim — the
InterPodAffinity config's remaining per-batch host work, PERF round 10):

  A (host-built) — the legacy per-batch path: `compile_batch_terms`
    re-walks every rep's spread/affinity/anti terms on the driver
    thread, then the whole padded term-table dict crosses the
    host→device wire (uploaded per dispatch).
  B (index)      — the term plane: term sets interned ONCE into the
    resident term bank (enqueue-time cost, off this measurement), per
    dispatch only int32 row/owner vectors + a [T] bool keep vector ship
    and a jitted gather (terms_plane/gather.gather_terms) rebuilds the
    batch table on device.

Timing discipline matches the other microbenches: trials interleave
A/B/A/B (drift hits both alike), each trial's device outputs are closed
with block_until_ready on a data-dependent output, and the reported
numbers are per-dispatch host wall + shipped bytes. The B path must be
STRICTLY cheaper on both at every bucket, with BIT-IDENTICAL device
content (every array of the gathered dict equals the host-built one,
padding and the rewritten owner column included) — asserted in smoke
mode, printed standalone.

Run: python scripts/microbench_terms.py [u_real]
Smoke (tier-1, via tests/test_terms_plane.py): main(smoke=True).
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if __name__ == "__main__":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np


def _mk_pods(n):
    """n distinct pod SPECS, every one carrying terms (the affinity-heavy
    shape the plane exists for): required anti-affinity, hard spread,
    required affinity + a preference, soft spread."""
    from kubernetes_tpu.api.types import (
        Affinity,
        LabelSelector,
        PodAffinity,
        PodAffinityTerm,
        PodAntiAffinity,
        TopologySpreadConstraint,
        WeightedPodAffinityTerm,
    )
    from kubernetes_tpu.models.generators import make_pod

    pods = []
    for i in range(n):
        p = make_pod(f"spec-{i}", cpu_milli=100 + i, labels={"app": f"a{i}"})
        sel = LabelSelector(match_labels={"app": p.labels["app"]})
        if i % 4 == 0:
            p.affinity = Affinity(pod_anti_affinity=PodAntiAffinity(
                required=[PodAffinityTerm(
                    label_selector=sel, topology_key="kubernetes.io/hostname",
                )]
            ))
        elif i % 4 == 1:
            p.topology_spread_constraints = [TopologySpreadConstraint(
                max_skew=1, topology_key="zone",
                when_unsatisfiable="DoNotSchedule", label_selector=sel,
            )]
        elif i % 4 == 2:
            p.affinity = Affinity(pod_affinity=PodAffinity(
                required=[PodAffinityTerm(label_selector=sel, topology_key="zone")],
                preferred=[WeightedPodAffinityTerm(
                    weight=5,
                    pod_affinity_term=PodAffinityTerm(
                        label_selector=LabelSelector(match_labels={"app": "x"}),
                        topology_key="zone",
                    ),
                )],
            ))
        else:
            p.topology_spread_constraints = [TopologySpreadConstraint(
                max_skew=2, topology_key="zone",
                when_unsatisfiable="ScheduleAnyway", label_selector=sel,
            )]
        pods.append(p)
    return pods


def main(smoke: bool = False):
    import jax
    import jax.numpy as jnp

    from kubernetes_tpu.state.tensors import Vocab, _bucket
    from kubernetes_tpu.state.terms import compile_batch_terms
    from kubernetes_tpu.terms_plane import TermBankDevice, TermStage
    from kubernetes_tpu.terms_plane.gather import gather_terms

    # smoke uses 64 specs: the host path's per-rep term walk needs enough
    # reps to clear the gather's fixed jit-dispatch cost on CPU (at 24
    # the two are within scheduler jitter of each other)
    u_real = int(sys.argv[1]) if len(sys.argv) > 1 and not smoke else (
        64 if smoke else 256
    )
    trials = 3 if smoke else 10
    vocab = Vocab()
    pods = _mk_pods(u_real)
    u = _bucket(u_real)

    # B's one-time staging (enqueue-time in the real system): intern every
    # spec's term set into the slab and upload the bank ONCE, pre-trial
    stage = TermStage(vocab, capacity=max(256, 2 * u))
    bank = TermBankDevice(stage)
    rows, owners = [], []
    for b, p in enumerate(pods):
        pair = stage.acquire(p)
        assert pair is not None
        e = stage._entries[pair[0]]
        rows.extend(e.rows)
        owners.extend([b] * len(e.rows))
    t = _bucket(max(len(rows), 1))
    bank_dev, empty_dev = bank.current_arrays()
    idx_host = np.zeros(t, np.int32)
    idx_host[: len(rows)] = rows
    own_host = np.zeros(t, np.int32)
    own_host[: len(rows)] = owners
    keep_host = np.zeros(t, bool)
    keep_host[: len(rows)] = True

    def run_a():
        """Host-built: compile_batch_terms + upload the full padded dict."""
        tb, _aux = compile_batch_terms(vocab, pods, capacity=t, b_capacity=u)
        host = tb.arrays()
        nbytes = sum(int(np.asarray(v).nbytes) for v in host.values())
        dev = {k: jnp.asarray(v) for k, v in host.items()}
        return dev, nbytes

    def run_b():
        """Index-only: ship row/owner/keep vectors, gather on device."""
        idx = idx_host.copy()
        own = own_host.copy()
        keep = keep_host.copy()
        nbytes = idx.nbytes + own.nbytes + keep.nbytes
        dev = gather_terms(bank_dev, idx, own, keep, empty_dev)
        return dev, nbytes

    # warm both jit paths + pin bit-identity before timing
    dev_a, bytes_a = run_a()
    dev_b, bytes_b = run_b()
    jax.block_until_ready((dev_a, dev_b))
    mismatches = [
        k for k in dev_a
        if not np.array_equal(np.asarray(dev_a[k]), np.asarray(dev_b[k]))
    ]
    assert not mismatches, f"index term dispatch diverged on: {mismatches}"

    t_a = t_b = 0.0
    for _ in range(trials):  # interleaved: drift hits both alike
        t0 = time.perf_counter()
        out, _ = run_a()
        jax.block_until_ready(out["ex_vals"])
        t_a += time.perf_counter() - t0
        t0 = time.perf_counter()
        out, _ = run_b()
        jax.block_until_ready(out["ex_vals"])
        t_b += time.perf_counter() - t0
    t_a /= trials
    t_b /= trials
    result = {
        "u_real": u_real,
        "t_rows": len(rows),
        "t_bucket": t,
        "host_built_s": round(t_a, 6),
        "index_s": round(t_b, 6),
        "speedup": round(t_a / t_b, 2) if t_b > 0 else float("inf"),
        "host_built_bytes": bytes_a,
        "index_bytes": bytes_b,
        "bytes_ratio": round(bytes_a / bytes_b, 1),
        "bit_identical": True,
    }
    if smoke:
        assert t_b < t_a, (
            f"index term dispatch not cheaper: {t_b:.6f}s vs {t_a:.6f}s"
        )
        assert bytes_b < bytes_a
    else:
        print(result)
    return result


if __name__ == "__main__":
    main()
