#!/usr/bin/env python
"""Convert / validate flight-recorder traces offline.

Two inputs, auto-detected:

* a RAW rings dump (``FlightRecorder.save_raw()``: ``{"epoch": ...,
  "rings": [...]}``) — converted to Chrome-trace-event JSON you can load
  in Perfetto (https://ui.perfetto.dev) or chrome://tracing;
* an already-exported Chrome-trace document (``{"traceEvents": [...]}``)
  — passed through (useful with ``--validate`` alone).

``--validate`` runs the structural checks tests/test_obs.py pins (sorted
ts, matched B/E, non-negative durations) and exits non-zero on problems,
so a CI step can gate on trace well-formedness.

Usage:
    python scripts/trace_export.py raw_rings.json -o trace.json
    python scripts/trace_export.py --validate trace.json
    python scripts/trace_export.py raw_rings.json -o trace.json --summary
"""

from __future__ import annotations

import argparse
import json
import sys
from collections import Counter


def _load(path: str) -> dict:
    with open(path) as f:
        return json.load(f)


def _summarize(doc: dict) -> str:
    events = [e for e in doc.get("traceEvents", []) if e.get("ph") != "M"]
    threads = {
        e["tid"]: e["args"]["name"]
        for e in doc.get("traceEvents", [])
        if e.get("ph") == "M" and e.get("name") == "thread_name"
    }
    by_name = Counter(e.get("name", "?") for e in events)
    wall = Counter()
    for e in events:
        wall[e.get("name", "?")] += e.get("dur", 0.0)
    lines = [
        f"{len(events)} events across {len(threads)} thread(s): "
        + ", ".join(sorted(threads.values()))
    ]
    for name, n in by_name.most_common():
        lines.append(f"  {name:<16} x{n:<7} {wall[name] / 1e6:.4f}s total")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("input", help="raw rings dump or Chrome-trace JSON")
    ap.add_argument("-o", "--output", help="write Chrome-trace JSON here")
    ap.add_argument(
        "--validate", action="store_true",
        help="run structural validation; exit 1 on problems",
    )
    ap.add_argument(
        "--summary", action="store_true",
        help="print per-span-name counts and total wall",
    )
    args = ap.parse_args(argv)

    sys.path.insert(0, ".")  # run from a checkout without installing
    from kubernetes_tpu.obs.export import raw_to_trace, validate_trace

    doc = _load(args.input)
    if "rings" in doc:  # raw save_raw() dump -> convert
        doc = raw_to_trace(doc)
    elif "traceEvents" not in doc:
        print(
            f"{args.input}: neither a raw rings dump nor a Chrome trace",
            file=sys.stderr,
        )
        return 2

    if args.output:
        with open(args.output, "w") as f:
            json.dump(doc, f)
        print(f"wrote {len(doc['traceEvents'])} events -> {args.output}")

    if args.summary:
        print(_summarize(doc))

    if args.validate:
        problems = validate_trace(doc)
        if problems:
            for p in problems:
                print(f"INVALID: {p}", file=sys.stderr)
            return 1
        print(f"valid: {len(doc['traceEvents'])} events")
    return 0


if __name__ == "__main__":
    sys.exit(main())
