#!/usr/bin/env python
"""Commit-plane perf smoke: a tiny bench config on the CPU backend.

Runs the REAL bench harness (bench.run_config — warmup, drain, audit,
compile-plan telemetry) against a miniature mixed workload that exercises
every commit-plane path: plain pods (bulk fast path), required
anti-affinity (arbiter tracking), and DoNotSchedule topology spread
(genuine in-batch arbitration → defer-to-next-batch verdicts). Asserts
the two invariants the plane lives by:

  * commit-plane coverage > 0 — the device arbiter actually committed
    batches (a silent fall-back to the per-pod host loop is a regression
    even when results stay correct);
  * zero compile-spec misses after warmup — no mid-drain XLA stall,
    including for the arbiter's and the fold's own programs;
  * resident-state plane engaged: fold coverage > 0, the device banks
    BIT-IDENTICAL to the host mirror after the drain (the folds, not a
    re-upload, produced them), zero dropped donations (a silently-copied
    donation doubles HBM and hides the copy cost), and the resident bank
    buffer population flat (no leaked bank copies).

Fast (~1 min on CPU) so it runs in tier-1 un-slow-marked, wired through
tests/test_perf_smoke.py; also runnable standalone:

    JAX_PLATFORMS=cpu python scripts/perf_smoke.py
"""

from __future__ import annotations

import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# several small batches instead of one giant one: spec reuse across
# batches (the zero-miss claim) is only tested if the drain has batches
os.environ.setdefault("BENCH_SPEC_DEPTH", "2")

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

N_NODES = 8
N_PODS = 96
SMOKE_BATCH = 32


def tiny_commit_plane_config():
    """(nodes, pods): 8 zoned nodes, 96 pods — 1/8 required anti-affinity,
    1/8 DoNotSchedule spread, the rest plain (bulk-path) pods."""
    import bench
    from kubernetes_tpu.api.types import (
        Affinity,
        LabelSelector,
        PodAffinityTerm,
        PodAntiAffinity,
        TopologySpreadConstraint,
    )

    nodes = [bench.mk_node(i, zone=bench.ZONES[i % 4]) for i in range(N_NODES)]
    pods = []
    for i in range(N_PODS):
        if i % 8 == 0:
            p = bench.mk_pod(i, cpu="100m", mem="64Mi",
                             labels={"exclusive": f"x{i % 16}"})
            p.affinity = Affinity(pod_anti_affinity=PodAntiAffinity(required=[
                PodAffinityTerm(
                    label_selector=LabelSelector(
                        match_labels={"exclusive": p.labels["exclusive"]}
                    ),
                    topology_key="kubernetes.io/hostname",
                )
            ]))
        elif i % 8 == 1:
            # a label space of their OWN: every pod a spread selector
            # matches must itself carry the constraint, or unconstrained
            # pods could legally skew the domain after placement
            p = bench.mk_pod(i, cpu="100m", mem="64Mi",
                             labels={"spread": f"grp{i % 2}"})
            p.topology_spread_constraints = [TopologySpreadConstraint(
                max_skew=1,
                topology_key="failure-domain.beta.kubernetes.io/zone",
                when_unsatisfiable="DoNotSchedule",
                label_selector=LabelSelector(
                    match_labels={"spread": p.labels["spread"]}
                ),
            )]
        else:
            p = bench.mk_pod(i, cpu="100m", mem="64Mi")
        pods.append(p)
    return nodes, pods


def main() -> dict:
    import bench

    bench.BATCH = SMOKE_BATCH
    fold_state = {}

    def inspect(sched):
        """Resident-state-plane probes against the LIVE scheduler, before
        it closes: device/host bank parity and the donation ledger."""
        import jax

        m = sched.mirror
        sched._commit_pipe.drain()
        m.sync()
        m.device_arrays()  # ships any non-folded remainder; folds stay put
        fold_state["divergence"] = m.device_bank_divergence()
        fold_state["undonated"] = m.folds_undonated
        # resident-bank buffer population must stay FLAT across folds: run
        # a few NO-OP folds (all-padding lanes — every scatter drops) and
        # demand the live-array census is unchanged. A silently-dropped
        # donation would allocate a fresh bank copy per fold and the
        # census would grow. Delta-based so arrays owned by the rest of
        # the process (other tests in a shared pytest run) cancel out.
        import gc

        import numpy as np

        from kubernetes_tpu.commit.fold import FoldProgram

        n_cap = m.nodes.capacity
        width = m.nodes.requested.shape[1]
        noop = FoldProgram(
            rows=np.full(16, n_cap, np.int32),
            req=np.zeros((16, width), np.int64),
            nz=np.zeros((16, 2), np.int64),
            cnt=np.zeros(16, np.int32),
            sig=np.full(16, m.eps.capacity, np.int32),
            pat_row=np.full(16, n_cap, np.int32),
            pat_col=np.full(16, m.pats.capacity, np.int32),
            pat_cnt=np.zeros(16, np.int16),
            pods=0,
        )
        gc.collect()
        before = len(jax.live_arrays())
        for _ in range(3):
            assert m.fold_commit(noop)
        gc.collect()
        fold_state["buffer_growth"] = len(jax.live_arrays()) - before
        fold_state["divergence_after_noop"] = m.device_bank_divergence()

    detail = bench.run_config(
        "tiny_commit_plane_smoke", tiny_commit_plane_config, inspect=inspect
    )
    phase = detail["phase_split_s"]
    audit = detail["audit"]
    problems = []
    if detail["scheduled"] != N_PODS:
        problems.append(f"scheduled {detail['scheduled']} of {N_PODS} pods")
    if not phase.get("arbiter_batches", 0):
        problems.append("commit-plane coverage is ZERO (arbiter never committed a batch)")
    if not phase.get("arbiter_place", 0):
        problems.append("arbiter placed no pods")
    if not phase.get("fold_batches", 0):
        problems.append(
            "resident-state fold coverage is ZERO (every commit re-shipped "
            "its rows host-to-device)"
        )
    if fold_state.get("divergence"):
        problems.append(
            f"device banks diverged from host mirror: {fold_state['divergence']}"
        )
    if fold_state.get("undonated"):
        problems.append(
            f"{fold_state['undonated']} fold(s) silently dropped buffer "
            "donation (bank copied instead of updated in place)"
        )
    if fold_state.get("buffer_growth", 0) > 0:
        problems.append(
            f"live device-buffer census grew by {fold_state['buffer_growth']} "
            "across no-op folds — donation is being dropped (bank copies)"
        )
    if fold_state.get("divergence_after_noop"):
        problems.append(
            f"no-op folds changed the banks: {fold_state['divergence_after_noop']}"
        )
    if detail["compile"]["misses_after_warmup"]:
        problems.append(
            f"{detail['compile']['misses_after_warmup']} compile-spec "
            "miss(es) after warmup — mid-drain XLA stalls"
        )
    for k, v in audit.items():
        if k.endswith("_violations") and v:
            problems.append(f"audit: {k}={v}")
    assert not problems, "; ".join(problems)
    return detail


if __name__ == "__main__":
    d = main()
    p = d["phase_split_s"]
    print(json.dumps({
        "config": d["config"],
        "scheduled": d["scheduled"],
        "deferred": d.get("deferred", 0),
        "arbiter_batches": p.get("arbiter_batches", 0),
        "arbiter_place": p.get("arbiter_place", 0),
        "arbiter_defer": p.get("arbiter_defer", 0),
        "fold_batches": p.get("fold_batches", 0),
        "fold_pods": p.get("fold_pods", 0),
        "patch_bytes": d.get("patch_bytes", {}),
        "commit_s": p.get("commit_s"),
        "solve_s": p.get("solve_s"),
        "misses_after_warmup": d["compile"]["misses_after_warmup"],
    }))
