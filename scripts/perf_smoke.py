#!/usr/bin/env python
"""Commit-plane perf smoke: a tiny bench config on the CPU backend.

Runs the REAL bench harness (bench.run_config — warmup, drain, audit,
compile-plan telemetry) against a miniature mixed workload that exercises
every commit-plane path: plain pods (bulk fast path), required
anti-affinity (arbiter tracking), and DoNotSchedule topology spread
(genuine in-batch arbitration → defer-to-next-batch verdicts). Asserts
the two invariants the plane lives by:

  * commit-plane coverage > 0 — the device arbiter actually committed
    batches (a silent fall-back to the per-pod host loop is a regression
    even when results stay correct);
  * zero compile-spec misses after warmup — no mid-drain XLA stall,
    including for the arbiter's and the fold's own programs;
  * resident-state plane engaged: fold coverage > 0, the device banks
    BIT-IDENTICAL to the host mirror after the drain (the folds, not a
    re-upload, produced them), zero dropped donations (a silently-copied
    donation doubles HBM and hides the copy cost), and the resident bank
    buffer population flat (no leaked bank copies).

Fast (~1 min on CPU) so it runs in tier-1 un-slow-marked, wired through
tests/test_perf_smoke.py; also runnable standalone:

    JAX_PLATFORMS=cpu python scripts/perf_smoke.py            # single-device
    JAX_PLATFORMS=cpu python scripts/perf_smoke.py sharded    # 8-way mesh
    JAX_PLATFORMS=cpu python scripts/perf_smoke.py preempt    # preemption
    JAX_PLATFORMS=cpu python scripts/perf_smoke.py trace      # flight recorder
    JAX_PLATFORMS=cpu python scripts/perf_smoke.py ingest     # pod-ingest plane
    JAX_PLATFORMS=cpu python scripts/perf_smoke.py terms      # term-bank plane
    JAX_PLATFORMS=cpu python scripts/perf_smoke.py columnar   # columnar cache
    JAX_PLATFORMS=cpu python scripts/perf_smoke.py health     # health monitor
    JAX_PLATFORMS=cpu python scripts/perf_smoke.py faults     # seeded chaos drain
    JAX_PLATFORMS=cpu python scripts/perf_smoke.py restart    # crash-restart cell

`main_restart()` (mode `restart`) guards the crash-restart plane
(kubernetes_tpu/restart): a deterministic crash:mid-bind-chunk
kill-point mid-drain, the supervised restart (fresh instance,
cold-start reconciliation from the persistent FakeAPIServer's relist),
and the resumed drain to completion — zero lost / zero double-bound
pods, no node over-commit, a clean shadow audit on the survivor,
`misses_after_warmup == 0` on the restarted incarnation, and the
reconciliation wall reported per phase.

`main_faults()` (mode `faults`) guards the fault plane
(kubernetes_tpu/faults): a seeded chaos drain — uploader death,
per-kind device raises, a watch-stream break, bind errors, a
commit-worker death, and a forced bank skew injected into one mixed +
preemption workload through the REAL informer replication path — must
complete with zero lost and zero double-bound pods, every targeted
plane must trip AND re-close through its shadow-audit-gated probe, the
skew must surface as a divergent (escalated) audit, and the final audit
must be clean.

`main_health()` (mode `health`) guards the steady-state health plane
(kubernetes_tpu/obs/introspect): with the background monitor ON during a
mixed drain, the always-on plane gauges must be non-empty and parse per
the exposition format, at least one sampled shadow audit must run CLEAN
(and none divergent), the /debug/ktpu census document must validate
against its versioned schema, monitor-ON overhead must stay within the
PR 7 trace-overhead bound vs monitor-OFF on the same warmed scheduler
with `misses_after_warmup == 0`, and the drain's delta-measured stage
p99s must pass the committed perf budget (scripts/perf_gate.py) — the
proof that perf_gate's committed thresholds hold on a real run.

`main_columnar()` (mode `columnar`) guards the columnar scheduler cache
(state/columns.py): a covered plain+anti drain must commit every pod
through the columnar bulk path — coverage > 0, ZERO lazy-view
materializations and ZERO scalar object-path pods on the commit path —
with the device-divergence probe (now including the vectorized
columns-vs-banks cross-check) empty and `misses_after_warmup == 0`.

`main_trace()` (mode `trace`) guards the flight recorder
(kubernetes_tpu/obs): a traced drain must export a structurally valid
Chrome-trace timeline covering every pipeline stage and every thread
role (informer admission, background uploader, driver, commit-apply
worker, bind pool, device pseudo-thread), hold `misses_after_warmup ==
0` with tracing ON, and stay within the per-pod overhead bound vs the
same scheduler's untraced drain. The mixed mode additionally serves its
own /metrics and scrapes it once MID-drain, asserting the readiness gate
and that the new attribution histograms expose and parse.

`main(sharded=True)` runs the SAME workload over a forced 8-virtual-device
node mesh and additionally asserts the multi-chip acceptance criteria:
arbiter coverage > 0, fold coverage > 0, `fold_undonated == 0`,
`patch_bytes.usage == 0`, and ZERO sharded→replicated fallbacks.

`main_preempt()` is the post-preemption shape-routing guard (BENCH_r05
config 6's cycle-2 solve spike): a tiny preemption drain must finish with
`misses_after_warmup == 0` AND `warm_stall_batches == 0` — victim-deletion
row patches, the nominee overlay, and the preempt kernel all land on
warmed programs.

`main_terms()` (mode `terms`) guards the term-bank plane
(kubernetes_tpu/terms_plane) with an affinity-heavy drain (every pod
carries terms — the InterPodAffinity wall's shape): term-index coverage
> 0, ZERO legacy/stale term batches, `patch_bytes.terms` KB-scale,
`misses_after_warmup == 0`, `mirror_rebuilds == 0`.

`main_ingest()` (mode `ingest`) guards the pod-ingest plane
(kubernetes_tpu/ingest): on a quiet drain every dispatch must take the
index-only path (staged coverage > 0, ZERO stale-row fallbacks), the
pod-side wire ledger (`patch_bytes.pods`) must stay KB-scale (vs the
full padded pod-array upload the legacy path ships per dispatch), the
gang smoke drain must finish with `mirror_rebuilds == 0` (the warmup
census pre-sizes SigBank from the FULL queue), and `misses_after_warmup`
must stay 0 — the staging scatters and the index-gather are planned
programs, never mid-drain compiles.
"""

from __future__ import annotations

import json
import os
import re
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# several small batches instead of one giant one: spec reuse across
# batches (the zero-miss claim) is only tested if the drain has batches
os.environ.setdefault("BENCH_SPEC_DEPTH", "2")

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)
_SCRIPTS = os.path.dirname(os.path.abspath(__file__))
if _SCRIPTS not in sys.path:  # perf_gate + ktpu_top (the health mode)
    sys.path.insert(0, _SCRIPTS)

N_NODES = 8
N_PODS = 96
SMOKE_BATCH = 32


def tiny_commit_plane_config():
    """(nodes, pods): 8 zoned nodes, 96 pods — 1/8 required anti-affinity,
    1/8 DoNotSchedule spread, the rest plain (bulk-path) pods."""
    import bench
    from kubernetes_tpu.api.types import (
        Affinity,
        LabelSelector,
        PodAffinityTerm,
        PodAntiAffinity,
        TopologySpreadConstraint,
    )

    nodes = [bench.mk_node(i, zone=bench.ZONES[i % 4]) for i in range(N_NODES)]
    pods = []
    for i in range(N_PODS):
        if i % 8 == 0:
            p = bench.mk_pod(i, cpu="100m", mem="64Mi",
                             labels={"exclusive": f"x{i % 16}"})
            p.affinity = Affinity(pod_anti_affinity=PodAntiAffinity(required=[
                PodAffinityTerm(
                    label_selector=LabelSelector(
                        match_labels={"exclusive": p.labels["exclusive"]}
                    ),
                    topology_key="kubernetes.io/hostname",
                )
            ]))
        elif i % 8 == 1:
            # a label space of their OWN: every pod a spread selector
            # matches must itself carry the constraint, or unconstrained
            # pods could legally skew the domain after placement
            p = bench.mk_pod(i, cpu="100m", mem="64Mi",
                             labels={"spread": f"grp{i % 2}"})
            p.topology_spread_constraints = [TopologySpreadConstraint(
                max_skew=1,
                topology_key="failure-domain.beta.kubernetes.io/zone",
                when_unsatisfiable="DoNotSchedule",
                label_selector=LabelSelector(
                    match_labels={"spread": p.labels["spread"]}
                ),
            )]
        else:
            p = bench.mk_pod(i, cpu="100m", mem="64Mi")
        pods.append(p)
    return nodes, pods


N_UNIQ = 288  # > the SigBank's 256-slot default: overflows mid-drain
# unless the warmup census pre-sizes from the FULL queue


def ingest_smoke_config():
    """(nodes, pods): the mixed commit-plane workload PLUS N_UNIQ pods
    with pairwise-distinct label sets — more distinct signatures than the
    SigBank's 256-slot default, so the drain rebuilds the mirror mid-way
    (the gang bench's `mirror_rebuilds: 1` failure mode at smoke scale)
    unless the warmup census walked the whole queue and pre-sized it."""
    import bench

    nodes, pods = tiny_commit_plane_config()
    for i in range(N_UNIQ):
        pods.append(bench.mk_pod(10_000 + i, cpu="50m", mem="32Mi",
                                 labels={"uniq": f"u{i}"}))
    return nodes, pods


def terms_smoke_config():
    """(nodes, pods): affinity-heavy — EVERY pod carries terms (required
    anti-affinity, DoNotSchedule spread, preferred affinity + soft
    spread), the InterPodAffinity shape (bench config 4) at smoke scale.
    The term plane must cover every dispatch with the index path."""
    import bench
    from kubernetes_tpu.api.types import (
        Affinity,
        LabelSelector,
        PodAffinity,
        PodAffinityTerm,
        PodAntiAffinity,
        TopologySpreadConstraint,
        WeightedPodAffinityTerm,
    )

    nodes = [bench.mk_node(i, zone=bench.ZONES[i % 4]) for i in range(N_NODES)]
    pods = []
    for i in range(N_PODS):
        if i % 3 == 0:
            p = bench.mk_pod(i, cpu="100m", mem="64Mi",
                             labels={"exclusive": f"x{i % 16}"})
            p.affinity = Affinity(pod_anti_affinity=PodAntiAffinity(required=[
                PodAffinityTerm(
                    label_selector=LabelSelector(
                        match_labels={"exclusive": p.labels["exclusive"]}
                    ),
                    topology_key="kubernetes.io/hostname",
                )
            ]))
        elif i % 3 == 1:
            p = bench.mk_pod(i, cpu="100m", mem="64Mi",
                             labels={"spread": f"grp{i % 2}"})
            p.topology_spread_constraints = [TopologySpreadConstraint(
                max_skew=1,
                topology_key="failure-domain.beta.kubernetes.io/zone",
                when_unsatisfiable="DoNotSchedule",
                label_selector=LabelSelector(
                    match_labels={"spread": p.labels["spread"]}
                ),
            )]
        else:
            p = bench.mk_pod(i, cpu="100m", mem="64Mi",
                             labels={"soft": f"s{i % 2}"})
            p.affinity = Affinity(pod_affinity=PodAffinity(preferred=[
                WeightedPodAffinityTerm(
                    weight=3,
                    pod_affinity_term=PodAffinityTerm(
                        label_selector=LabelSelector(
                            match_labels={"soft": p.labels["soft"]}
                        ),
                        topology_key="failure-domain.beta.kubernetes.io/zone",
                    ),
                )
            ]))
            p.topology_spread_constraints = [TopologySpreadConstraint(
                max_skew=2,
                topology_key="failure-domain.beta.kubernetes.io/zone",
                when_unsatisfiable="ScheduleAnyway",
                label_selector=LabelSelector(
                    match_labels={"soft": p.labels["soft"]}
                ),
            )]
        pods.append(p)
    return nodes, pods


def columnar_smoke_config():
    """(nodes, pods): plain + required-anti mix — every commit flavor
    the COVERED path serves (bulk fast path + arbiter), deliberately no
    hard spread: defer-escalation routes through the oracle, which READS
    the lazy NodeInfo views, and this config must prove the covered
    commit path materializes ZERO of them."""
    import bench
    from kubernetes_tpu.api.types import (
        Affinity,
        LabelSelector,
        PodAffinityTerm,
        PodAntiAffinity,
    )

    nodes = [bench.mk_node(i, zone=bench.ZONES[i % 4]) for i in range(N_NODES)]
    pods = []
    for i in range(N_PODS):
        if i % 8 == 0:
            p = bench.mk_pod(i, cpu="100m", mem="64Mi",
                             labels={"exclusive": f"x{i % 16}"})
            p.affinity = Affinity(pod_anti_affinity=PodAntiAffinity(required=[
                PodAffinityTerm(
                    label_selector=LabelSelector(
                        match_labels={"exclusive": p.labels["exclusive"]}
                    ),
                    topology_key="kubernetes.io/hostname",
                )
            ]))
        else:
            p = bench.mk_pod(i, cpu="100m", mem="64Mi")
        pods.append(p)
    return nodes, pods


def preemption_smoke_config():
    """(nodes, pending, existing): 8 nodes pre-filled to ~90% CPU with
    low-priority victims; high-priority pods that can only land by
    eviction — the bench's preemption config at smoke scale."""
    import bench

    nodes = [bench.mk_node(i) for i in range(N_NODES)]
    existing = []
    for i in range(N_NODES * 7):  # 7 x 4000m of 32 cores per node
        p = bench.mk_pod(1_000_000 + i, cpu="4000m", mem="1Gi",
                         labels={"app": f"lowprio-{i % 4}"})
        p.priority = 0
        p.node_name = f"node-{i % N_NODES}"
        existing.append(p)
    pending = []
    for i in range(24):
        p = bench.mk_pod(i, cpu="6000m", mem="2Gi",
                         labels={"app": f"hiprio-{i % 4}"})
        p.priority = 1000
        pending.append(p)
    return nodes, pending, existing


def _start_mid_drain_scraper(out: dict):
    """Background thread: wait for bench's MetricsServer, verify /readyz
    gates on warmup (503 before, 200 after), then scrape /metrics while
    the drain is running, keeping the last body that exposes the per-pod
    attempt histogram. Results land in `out` for main() to assert on."""
    import threading
    import time
    import urllib.error
    import urllib.request

    import bench

    def run():
        # the server starts after bench's warmup, whose COLD budget is
        # ~650s (persistent ladder empty) — the wait must outlast it
        deadline = time.time() + 720
        url = None
        while time.time() < deadline and url is None:
            srv = getattr(bench, "METRICS_SERVER", None)
            if srv is not None:
                url = srv.url
            time.sleep(0.01)
        if url is None:
            out["error"] = "metrics server never came up"
            return
        while time.time() < deadline:  # readiness gate: 503 until warmed
            try:
                with urllib.request.urlopen(f"{url}/readyz", timeout=2) as r:
                    out["ready_code"] = r.status
                    break
            except urllib.error.HTTPError as e:
                out["not_ready_code"] = e.code
            except OSError:
                pass
            time.sleep(0.02)
        while time.time() < deadline:  # scrape until drain activity shows
            try:
                with urllib.request.urlopen(f"{url}/metrics", timeout=5) as r:
                    out["text"] = r.read().decode()
            except OSError:
                break  # server closed: the drain ended — keep the last body
            if "scheduler_scheduling_attempt_duration_seconds_bucket" in out.get(
                "text", ""
            ):
                break
            time.sleep(0.02)

    t = threading.Thread(target=run, name="smoke-scraper", daemon=True)
    t.start()
    return t


#: one sample line of the Prometheus text exposition format:
#: name{label="value",...} value  — label values with escaped \" \\ \n only
_PROM_SAMPLE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*'
    r'(\{[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\\n]|\\["\\n])*"'
    r'(,[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\\n]|\\["\\n])*")*\})?'
    r' (-?[0-9.eE+-]+|\+Inf|-Inf|NaN)$'
)

NEW_HISTOGRAMS = (
    "scheduler_queue_incoming_wait_seconds",
    "scheduler_scheduling_attempt_duration_seconds",
    "scheduler_scheduling_stage_duration_seconds",
)


def _check_scrape(scrape: dict):
    """Problems list for the mid-drain /metrics scrape: readiness gate
    honest, every line parses per the text format, the new attribution
    histograms expose with full bucket/sum/count families."""
    problems = []
    if "error" in scrape:
        return [scrape["error"]]
    if scrape.get("ready_code") != 200:
        problems.append(f"/readyz never answered 200 ({scrape})")
    text = scrape.get("text", "")
    if not text:
        return problems + ["mid-drain /metrics scrape got no body"]
    for i, line in enumerate(text.splitlines()):
        if not line or line.startswith("#"):
            continue
        if not _PROM_SAMPLE.match(line):
            problems.append(f"/metrics line {i} unparseable: {line!r}")
    for h in NEW_HISTOGRAMS:
        for suffix in ("_bucket", "_sum", "_count"):
            if f"{h}{suffix}" not in text:
                problems.append(f"{h}{suffix} missing from mid-drain scrape")
    return problems


def _mesh8():
    import jax

    if len(jax.devices()) < 8:
        raise RuntimeError(
            "sharded perf_smoke needs 8 devices "
            "(xla_force_host_platform_device_count)"
        )
    from kubernetes_tpu.parallel import node_mesh

    return node_mesh(8)


def main(sharded: bool = False) -> dict:
    import bench

    bench.BATCH = SMOKE_BATCH
    fold_state = {}

    def inspect(sched):
        """Resident-state-plane probes against the LIVE scheduler, before
        it closes: device/host bank parity and the donation ledger."""
        import jax

        # quiesce the background compile-warmup worker FIRST: a growth-rung
        # warm compiling during the census below allocates device arrays on
        # its own thread and makes the buffer-growth delta flaky
        if sched._warm_svc is not None:
            sched._warm_svc.stop()
            sched._warm_svc.join()
        m = sched.mirror
        sched._commit_pipe.drain()
        m.sync()
        m.device_arrays()  # ships any non-folded remainder; folds stay put
        fold_state["divergence"] = m.device_bank_divergence()
        fold_state["undonated"] = m.folds_undonated
        # resident-bank buffer population must stay FLAT across folds: run
        # a few NO-OP folds (all-padding lanes — every scatter drops) and
        # demand the live-array census is unchanged. A silently-dropped
        # donation would allocate a fresh bank copy per fold and the
        # census would grow. Delta-based so arrays owned by the rest of
        # the process (other tests in a shared pytest run) cancel out.
        import gc

        import numpy as np

        from kubernetes_tpu.commit.fold import FoldProgram

        n_cap = m.nodes.capacity
        width = m.nodes.requested.shape[1]
        noop = FoldProgram(
            rows=np.full(16, n_cap, np.int32),
            req=np.zeros((16, width), np.int64),
            nz=np.zeros((16, 2), np.int64),
            cnt=np.zeros(16, np.int32),
            sig=np.full(16, m.eps.capacity, np.int32),
            pat_row=np.full(16, n_cap, np.int32),
            pat_col=np.full(16, m.pats.capacity, np.int32),
            pat_cnt=np.zeros(16, np.int16),
            pods=0,
        )
        gc.collect()
        before = len(jax.live_arrays())
        for _ in range(3):
            assert m.fold_commit(noop)
        gc.collect()
        fold_state["buffer_growth"] = len(jax.live_arrays()) - before
        fold_state["divergence_after_noop"] = m.device_bank_divergence()

    opts = {}
    name = "tiny_commit_plane_smoke"
    if sharded:
        opts["mesh"] = _mesh8()
        name = "tiny_commit_plane_smoke_sharded8"
    # observability satellite: the single-device smoke serves its own
    # /metrics (ephemeral port) and SCRAPES it once mid-drain — the
    # readiness gate plus the new attribution histograms must expose and
    # parse while the drain is actually running, not just at rest
    scrape = {}
    scraper = None
    if not sharded:
        os.environ["BENCH_METRICS_PORT"] = "0"
        scraper = _start_mid_drain_scraper(scrape)
    try:
        detail = bench.run_config(
            name, tiny_commit_plane_config, opts=opts, inspect=inspect
        )
    finally:
        if scraper is not None:
            os.environ.pop("BENCH_METRICS_PORT", None)
            scraper.join(timeout=10)
    phase = detail["phase_split_s"]
    audit = detail["audit"]
    problems = []
    if detail["scheduled"] != N_PODS:
        problems.append(f"scheduled {detail['scheduled']} of {N_PODS} pods")
    if sharded:
        # the multi-chip acceptance criteria ride the same assertions as
        # single-device — plus: the sharded pipeline must never have
        # silently dropped to the replicated solve
        if phase.get("sharded_fallbacks", 0):
            problems.append(
                f"{phase['sharded_fallbacks']} sharded->replicated "
                "fallback(s) on a mesh whose shard count divides the bucket"
            )
    if not phase.get("arbiter_batches", 0):
        problems.append("commit-plane coverage is ZERO (arbiter never committed a batch)")
    if not phase.get("arbiter_place", 0):
        problems.append("arbiter placed no pods")
    if not phase.get("fold_batches", 0):
        problems.append(
            "resident-state fold coverage is ZERO (every commit re-shipped "
            "its rows host-to-device)"
        )
    if fold_state.get("divergence"):
        problems.append(
            f"device banks diverged from host mirror: {fold_state['divergence']}"
        )
    if fold_state.get("undonated"):
        problems.append(
            f"{fold_state['undonated']} fold(s) silently dropped buffer "
            "donation (bank copied instead of updated in place)"
        )
    if fold_state.get("buffer_growth", 0) > 0:
        problems.append(
            f"live device-buffer census grew by {fold_state['buffer_growth']} "
            "across no-op folds — donation is being dropped (bank copies)"
        )
    if fold_state.get("divergence_after_noop"):
        problems.append(
            f"no-op folds changed the banks: {fold_state['divergence_after_noop']}"
        )
    if sharded and detail.get("patch_bytes", {}).get("usage", 0) > 4096:
        # "≈ 0": a covered mesh drain folds its usage deltas in place —
        # a few stray rows (escalations) are tolerable, a per-batch
        # re-ship is the regression this guards
        problems.append(
            f"usage patch bytes {detail['patch_bytes']['usage']} on a "
            "covered mesh drain (the resident-state plane is off on-mesh)"
        )
    if detail["compile"]["misses_after_warmup"]:
        problems.append(
            f"{detail['compile']['misses_after_warmup']} compile-spec "
            "miss(es) after warmup — mid-drain XLA stalls"
        )
    for k, v in audit.items():
        if k.endswith("_violations") and v:
            problems.append(f"audit: {k}={v}")
    # per-pod latency attribution (kubernetes_tpu/obs): bench must quote
    # real p50/p99 from the new histograms' sample reservoirs, not nulls
    attr = detail.get("pod_latency_attribution") or {}
    for k in ("queue_wait_p50_s", "queue_wait_p99_s", "attempt_p50_s",
              "attempt_p99_s", "e2e_p50_s", "e2e_p99_s"):
        if attr.get(k) is None:
            problems.append(f"pod_latency_attribution.{k} is null")
    if scraper is not None:
        problems += _check_scrape(scrape)
        detail["metrics_scrape"] = {
            "ready_code": scrape.get("ready_code"),
            "not_ready_code": scrape.get("not_ready_code"),
            "lines": len(scrape.get("text", "").splitlines()),
        }
    assert not problems, "; ".join(problems)
    return detail


#: every pipeline stage the flight recorder must have witnessed in a
#: traced smoke drain (host rings + the device pseudo-thread)
REQUIRED_SPANS = (
    "enqueue", "stage-encode", "upload", "sync", "dispatch", "gather",
    "solve", "arbitrate", "fold", "commit", "apply", "bind", "fetch",
    "cycle", "warmup",
)
#: thread-name fragments the timeline must span: informer admission,
#: background uploader, driver (main), commit-apply worker, bind pool,
#: and the device pseudo-thread
REQUIRED_THREADS = (
    "informer", "ingest-upload", "MainThread", "commit-apply", "bind",
    "device",
)
#: traced-vs-untraced per-pod overhead ceiling (2%), plus an absolute
#: floor so sub-second CPU smoke drains don't fail on scheduler jitter
TRACE_OVERHEAD_FRAC = 0.02
TRACE_OVERHEAD_ABS_S = 0.25


def _trace_wave(tag: str, n: int):
    """n pods namespaced by `tag` (labels disjoint across waves so wave
    B's anti-affinity can't collide with wave A's placements): same mix
    as tiny_commit_plane_config — 1/8 required anti-affinity, 1/8
    DoNotSchedule spread, the rest bulk-path."""
    import bench
    from kubernetes_tpu.api.types import (
        Affinity,
        LabelSelector,
        PodAffinityTerm,
        PodAntiAffinity,
        TopologySpreadConstraint,
    )

    base = {"a": 0, "b": 100_000, "live": 200_000, "p": 300_000}[tag]
    pods = []
    for i in range(n):
        if i % 8 == 0:
            p = bench.mk_pod(base + i, cpu="100m", mem="64Mi",
                             labels={"exclusive": f"{tag}{i % 16}"})
            p.affinity = Affinity(pod_anti_affinity=PodAntiAffinity(required=[
                PodAffinityTerm(
                    label_selector=LabelSelector(
                        match_labels={"exclusive": p.labels["exclusive"]}
                    ),
                    topology_key="kubernetes.io/hostname",
                )
            ]))
        elif i % 8 == 1:
            p = bench.mk_pod(base + i, cpu="100m", mem="64Mi",
                             labels={"spread": f"{tag}grp{i % 2}"})
            p.topology_spread_constraints = [TopologySpreadConstraint(
                max_skew=1,
                topology_key="failure-domain.beta.kubernetes.io/zone",
                when_unsatisfiable="DoNotSchedule",
                label_selector=LabelSelector(
                    match_labels={"spread": p.labels["spread"]}
                ),
            )]
        else:
            p = bench.mk_pod(base + i, cpu="100m", mem="64Mi",
                             labels={"wave": tag})
        pods.append(p)
    return pods


def main_trace() -> dict:
    """Flight-recorder smoke (KTPU_TRACE equivalent): ONE warmed
    scheduler drains wave A traced-OFF, then wave B traced-ON with a
    mid-drain live-arrival wave (so the background uploader ships fresh
    staged rows off-thread while spans record). Asserts the exported
    Chrome trace is structurally valid, covers every pipeline stage and
    every thread role, `misses_after_warmup == 0` held with tracing ON,
    and the traced per-pod batch wall stayed within the overhead bound
    of the untraced drain."""
    import threading
    import time

    import bench
    from kubernetes_tpu.obs import RECORDER
    from kubernetes_tpu.obs.export import validate_trace
    from kubernetes_tpu.scheduler.driver import Binder, Scheduler
    from kubernetes_tpu.state.cache import SchedulerCache
    from kubernetes_tpu.state.queue import PriorityQueue

    nodes = [bench.mk_node(i, zone=bench.ZONES[i % 4]) for i in range(N_NODES)]
    wave_p = _trace_wave("p", 32)  # priming drain (untraced, unmeasured)
    wave_a = _trace_wave("a", N_PODS)
    wave_b = _trace_wave("b", N_PODS)
    wave_live = _trace_wave("live", 16)

    RECORDER.enable(False)
    RECORDER.reset()
    cache = SchedulerCache()
    for node in nodes:
        cache.add_node(node)
    queue = PriorityQueue()
    sched = Scheduler(
        cache=cache, queue=queue, binder=Binder(), batch_size=SMOKE_BATCH,
        enable_preemption=False, spec_depth=2,
    )
    sched.mirror.reserve(
        len(nodes),
        len(wave_p) + len(wave_a) + len(wave_b) + len(wave_live),
    )

    def informer_add(pods):
        """Enqueue on a thread NAMED informer — admission (and the
        stage-encode) run off the driver thread exactly as in the live
        informer topology, so their spans land in their own ring."""
        t = threading.Thread(
            target=lambda: [queue.add(p) for p in pods], name="informer"
        )
        t.start()
        t.join()

    def drain(inject=None):
        """(sum of schedule_batch walls, scheduled). `inject()` runs
        after the first batch — live arrivals mid-drain."""
        wall = 0.0
        scheduled = 0
        injected = inject is None
        while True:
            t0 = time.perf_counter()
            r = sched.schedule_batch()
            wall += time.perf_counter() - t0
            scheduled += r.scheduled
            if not injected:
                injected = True
                inject()
                continue
            if (r.scheduled == 0 and r.unschedulable == 0
                    and r.errors == 0 and r.deferred == 0):
                break
        sched.wait_for_binds()
        return wall, scheduled

    problems = []
    try:
        # tracing ON for admission + warmup (the KTPU_TRACE=1 production
        # shape: warmup itself is on the timeline)
        RECORDER.enable(True)
        RECORDER.reset()
        informer_add(wave_p)
        sched.warmup()

        # priming drain, untraced + unmeasured: the FIRST drain of a fresh
        # scheduler pays Python/allocator warmth no later drain pays —
        # measuring it against anything else measures order, not tracing
        RECORDER.enable(False)
        drain()

        # untraced baseline on the now-warm scheduler
        informer_add(wave_b)
        off_wall, off_n = drain()

        # traced leg, same warmed programs, with a mid-drain live-arrival
        # wave so the background uploader ships fresh rows while recording
        RECORDER.enable(True)
        informer_add(wave_a)

        def inject_live():
            informer_add(wave_live)
            # give the background uploader its poll interval: the fresh
            # staged rows must ship OFF-THREAD (upload spans on the
            # ingest-upload ring), not via the driver's sync flush.
            # Outside the batch walls, so not counted as overhead.
            time.sleep(0.3)

        on_wall, on_n = drain(inject=inject_live)
        misses = int(
            sched.compile_plan.stats.get("misses_after_warmup", 0)
        )
        doc = RECORDER.export()
    finally:
        RECORDER.enable(False)
        sched.close()

    if off_n != len(wave_b):
        problems.append(f"untraced drain scheduled {off_n}/{len(wave_b)}")
    want_on = len(wave_a) + len(wave_live)
    if on_n != want_on:
        problems.append(f"traced drain scheduled {on_n}/{want_on}")
    if misses:
        problems.append(
            f"{misses} compile miss(es) after warmup with tracing ON"
        )

    structural = validate_trace(doc)
    if structural:
        problems.append(f"invalid trace: {'; '.join(structural[:5])}")
    events = [e for e in doc["traceEvents"] if e.get("ph") != "M"]
    names = {e["name"] for e in events}
    missing = [s for s in REQUIRED_SPANS if s not in names]
    if missing:
        problems.append(f"stages with NO span recorded: {missing}")
    threads = {
        e["args"]["name"]
        for e in doc["traceEvents"]
        if e.get("ph") == "M" and e.get("name") == "thread_name"
    }
    spanning = [
        frag for frag in REQUIRED_THREADS
        if not any(frag in t for t in threads)
    ]
    if spanning:
        problems.append(
            f"thread roles with NO spans: {spanning} (saw {sorted(threads)})"
        )

    off_pp = off_wall / max(off_n, 1)
    on_pp = on_wall / max(on_n, 1)
    overhead = on_pp / off_pp - 1.0 if off_pp > 0 else 0.0
    if (on_pp - off_pp) * on_n > TRACE_OVERHEAD_ABS_S and \
            overhead > TRACE_OVERHEAD_FRAC:
        problems.append(
            f"tracing overhead {overhead * 100:.1f}% per pod "
            f"({on_pp * 1e3:.3f}ms vs {off_pp * 1e3:.3f}ms untraced)"
        )
    assert not problems, "; ".join(problems)
    return {
        "config": "tiny_trace_smoke",
        "scheduled": off_n + on_n,
        "trace_events": len(events),
        "trace_threads": sorted(threads),
        "span_names": sorted(names),
        "overhead_frac": round(overhead, 4),
        "misses_after_warmup": misses,
        "phase_split_s": dict(sched.stats),
        "compile": {"misses_after_warmup": misses},
    }


def _check_health_gauges(scrape_text: str, census_doc: dict):
    """Problems list for the always-on gauges: every exported sample
    must agree with the census document taken at the same settled
    moment — VALUE checks against parsed samples, not substring
    presence (unlabeled gauges auto-emit a 0 sample on registration and
    a bare name also matches its own # HELP line, so presence alone
    would stay green with export_gauges unwired)."""
    import ktpu_top

    problems = []
    try:
        parsed = ktpu_top.parse_metrics_text(scrape_text)
    except ValueError as e:
        return [str(e)]

    def sample(name, **labels):
        series = parsed.get(name)
        if not series:
            return None
        return series.get(tuple(sorted(labels.items())))

    planes = census_doc["planes"]
    want = [
        ("ktpu_plane_slab_occupancy", {"plane": "ingest"},
         planes["ingest"]["rows"]),
        ("ktpu_plane_slab_capacity", {"plane": "ingest"},
         planes["ingest"]["capacity"]),
        ("ktpu_plane_slab_occupancy", {"plane": "terms"},
         planes["terms"]["rows"]),
        ("ktpu_plane_slab_occupancy", {"plane": "columns"},
         planes["cache"]["columns"]["rows"]),
        ("ktpu_plane_slab_occupancy", {"plane": "mirror_nodes"},
         planes["mirror"]["node_rows"]),
        ("ktpu_cache_journal_depth", {},
         planes["cache"]["columns"]["journal_depth"]),
        ("ktpu_commit_inflight", {},
         1.0 if planes["commit"]["in_flight"] else 0.0),
        # drained queue: the oldest-age gauge must read 0, not a relic
        ("scheduler_queue_oldest_pending_age_seconds", {}, 0.0),
    ]
    for kind, e in planes["compile"]["kinds"].items():
        want.append(("ktpu_compile_ladder_rungs", {"kind": kind}, e["rungs"]))
    for name, labels, expected in want:
        got = sample(name, **labels)
        if got is None:
            problems.append(f"gauge {name}{labels or ''} has no sample")
        elif float(got) != float(expected):
            problems.append(
                f"gauge {name}{labels or ''} = {got} but census says {expected}"
            )
    # liveness counters: real activity, not registration artifacts
    if not (sample("ktpu_health_refresh_total") or 0) > 0:
        problems.append("ktpu_health_refresh_total never incremented")
    if not (sample("ktpu_shadow_audit_total", result="clean") or 0) >= 1:
        problems.append("no clean shadow-audit sample on the scrape")
    return problems


def main_health(gate_budget: bool = True) -> dict:
    """Steady-state-health smoke: ONE warmed scheduler drains wave A with
    the monitor OFF (baseline), then wave B with the monitor ON (50ms
    refresh, audit every 2 cycles) plus a mid-drain live-arrival wave so
    the monitor ticks while the pipeline is genuinely busy. Asserts the
    acceptance criteria listed in the module docstring; returns a detail
    dict including `budget_obs` (scripts/perf_gate.py --check consumes
    it). `gate_budget=False` skips the inline committed-budget assert —
    perf_gate's CLI gates the observations itself (possibly against a
    --budget override) and must reach its structured FAIL report instead
    of an AssertionError out of here."""
    import threading
    import time

    import bench
    import perf_gate
    from kubernetes_tpu.metrics import metrics as M
    from kubernetes_tpu.obs import introspect as insp
    from kubernetes_tpu.scheduler.driver import Binder, Scheduler
    from kubernetes_tpu.state.cache import SchedulerCache
    from kubernetes_tpu.state.queue import PriorityQueue

    nodes = [bench.mk_node(i, zone=bench.ZONES[i % 4]) for i in range(N_NODES)]
    wave_p = _trace_wave("p", 32)  # priming drain (unmeasured)
    wave_a = _trace_wave("a", N_PODS)  # monitor OFF baseline
    wave_b = _trace_wave("b", N_PODS)  # monitor ON (refresh-only): overhead
    wave_live = _trace_wave("live", 64)  # audited wave (>=2 batches, so a
    # mid-drain due audit has a later batch's safe point to execute at)

    cache = SchedulerCache()
    for node in nodes:
        cache.add_node(node)
    queue = PriorityQueue()
    sched = Scheduler(
        cache=cache, queue=queue, binder=Binder(), batch_size=SMOKE_BATCH,
        enable_preemption=False, spec_depth=2,
    )
    sched.mirror.reserve(
        len(nodes),
        len(wave_p) + len(wave_a) + len(wave_b) + len(wave_live),
    )

    def informer_add(pods):
        t = threading.Thread(
            target=lambda: [queue.add(p) for p in pods], name="informer"
        )
        t.start()
        t.join()

    def drain(inject=None):
        wall = 0.0
        scheduled = 0
        injected = inject is None
        while True:
            t0 = time.perf_counter()
            r = sched.schedule_batch()
            wall += time.perf_counter() - t0
            scheduled += r.scheduled
            if not injected:
                injected = True
                inject()
                continue
            if (r.scheduled == 0 and r.unschedulable == 0
                    and r.errors == 0 and r.deferred == 0):
                break
        sched.wait_for_binds()
        return wall, scheduled

    problems = []
    try:
        # KTPU_HEALTH=1 in the ambient env would pre-arm a monitor and
        # silently turn the monitor-OFF baseline below into ON-vs-ON:
        # the baseline wave must be genuinely unmonitored
        if sched.health is not None:
            sched.health.stop()
            sched.health = None
        informer_add(wave_p)
        sched.warmup()
        drain()  # priming: first-drain Python/allocator warmth, unmeasured

        # perf-budget observation window opens HERE: post-warmup,
        # post-priming — warmup's inline compiles never pollute the
        # delta-measured stage p99s the committed budget gates
        stage_before = perf_gate.snapshot_stages()

        # monitor-OFF baseline on the warmed scheduler
        informer_add(wave_a)
        off_wall, off_n = drain()

        # monitor ON, refresh-only (audit_every=0): the STEADY-STATE cost
        # — gauge refreshes every 50ms against a live drain. This is the
        # wave the overhead bound judges: sampled shadow audits are rare
        # events on a production cadence (minutes), but a sub-second
        # smoke drain cannot amortize one, so they are exercised on their
        # own unmeasured wave below.
        mon = sched.enable_health_monitor(interval=0.05, audit_every=0)
        informer_add(wave_b)
        on_wall, on_n = drain()

        # audited wave: arm the sampled-audit cadence and drain the live
        # wave — due audits execute mid-drain at the driver's post-sync
        # safe point (this is the "shadow audits run during the drain"
        # acceptance, wall not overhead-measured)
        mon.audit_every = 2
        informer_add(wave_live)

        def inject_sleep():
            # a couple of refresh intervals mid-drain so the monitor
            # thread marks audits due while batches are still flowing
            time.sleep(0.3)

        _, live_n = drain(inject=inject_sleep)

        # deterministic floor: one guaranteed audit at an explicit safe
        # point (driver thread, pipeline drained, mirror synced) — the
        # in-drain sampled audits ride on top
        sched._commit_pipe.drain()
        sched.mirror.sync()
        mon.request_audit()
        mon.driver_sync_hook()
        mon.refresh()  # deterministic final gauge export before scraping

        audits = mon.audit_counts()
        misses = int(sched.compile_plan.stats.get("misses_after_warmup", 0))
        census_doc = insp.census(sched)
        census_problems = insp.validate_census(census_doc)
        budget_obs = perf_gate.collect(
            stage_before, perf_gate.counters_from_sched(sched)
        )
        scrape_text = M.registry.expose_text()
    finally:
        sched.close()

    if off_n != len(wave_a):
        problems.append(f"baseline drain scheduled {off_n}/{len(wave_a)}")
    if on_n != len(wave_b):
        problems.append(f"monitored drain scheduled {on_n}/{len(wave_b)}")
    if live_n != len(wave_live):
        problems.append(f"audited drain scheduled {live_n}/{len(wave_live)}")
    if misses:
        problems.append(
            f"{misses} compile miss(es) after warmup with the monitor ON"
        )
    if census_problems:
        problems.append(f"census schema: {'; '.join(census_problems[:5])}")
    if audits.get("clean", 0) < 1:
        problems.append(
            f"no CLEAN shadow audit ran during the monitored drain ({audits})"
        )
    if audits.get("divergent", 0):
        problems.append(
            f"{audits['divergent']} shadow audit(s) found divergence on a "
            f"healthy drain: {census_doc.get('monitor', {}).get('last_divergence')}"
        )

    # the always-on gauges: every line parseable, and every health
    # sample VALUE agrees with the census taken at the same settled
    # moment (presence alone is vacuous — see _check_health_gauges)
    for i, line in enumerate(scrape_text.splitlines()):
        if not line or line.startswith("#"):
            continue
        if not _PROM_SAMPLE.match(line):
            problems.append(f"/metrics line {i} unparseable: {line!r}")
    problems += _check_health_gauges(scrape_text, census_doc)

    # ktpu_top must render from BOTH sources (census + raw scrape)
    import ktpu_top

    top_census = ktpu_top.render_census(census_doc)
    top_scrape = ktpu_top.render_metrics(
        ktpu_top.parse_metrics_text(scrape_text)
    )
    for label, body in (("census", top_census), ("scrape", top_scrape)):
        if "ingest" not in body or "mirror_nodes" not in body:
            problems.append(f"ktpu_top {label} table missing plane rows")

    # the committed perf budget must pass on this real, measured drain
    if gate_budget:
        budget_problems = perf_gate.check(perf_gate.load_budget(), budget_obs)
        problems += [f"perf budget: {p}" for p in budget_problems]

    # monitor-ON overhead vs monitor-OFF: the PR 7 bound discipline
    off_pp = off_wall / max(off_n, 1)
    on_pp = on_wall / max(on_n, 1)
    overhead = on_pp / off_pp - 1.0 if off_pp > 0 else 0.0
    if (on_pp - off_pp) * on_n > TRACE_OVERHEAD_ABS_S and \
            overhead > TRACE_OVERHEAD_FRAC:
        problems.append(
            f"monitor overhead {overhead * 100:.1f}% per pod "
            f"({on_pp * 1e3:.3f}ms vs {off_pp * 1e3:.3f}ms monitor-off)"
        )
    assert not problems, "; ".join(problems)
    return {
        "config": "tiny_health_smoke",
        "scheduled": off_n + on_n + live_n,
        "audits": audits,
        "overhead_frac": round(overhead, 4),
        "misses_after_warmup": misses,
        "budget_obs": budget_obs,
        "census_planes": sorted(census_doc["planes"]),
        "phase_split_s": dict(sched.stats),
        "compile": {"misses_after_warmup": misses},
    }


def main_preempt() -> dict:
    """Preemption-path smoke: the post-preemption cycles must land on
    warmed programs. BENCH_r05's config 6 spent 2.58 s of 'solve' on its
    second cycle — which turned out to be the mirror's dirty-row scatter
    programs compiling inline after victim deletions dirtied rows at a
    fresh bucket (invisible to the plan: patches were not specs). With
    KIND_PATCH warming + the preempt victim-rung headroom warm, the whole
    drain must report zero misses after warmup and zero stall batches."""
    import bench

    bench.BATCH = SMOKE_BATCH
    detail = bench.run_config(
        "tiny_preemption_smoke", preemption_smoke_config,
        opts={"enable_preemption": True},
    )
    phase = detail["phase_split_s"]
    problems = []
    if detail["scheduled"] != 24:
        problems.append(f"scheduled {detail['scheduled']} of 24 pods")
    if not detail["preempted"]:
        problems.append("no preemption happened — the config is broken")
    if detail["compile"]["misses_after_warmup"]:
        problems.append(
            f"{detail['compile']['misses_after_warmup']} compile-spec "
            "miss(es) after warmup on the preemption drain "
            "(post-preemption shapes missed the warmed rungs)"
        )
    if detail["warm_stall_batches"]:
        problems.append(
            f"{detail['warm_stall_batches']} stall batch(es) in the "
            "measured tail — an inline compile (or equivalent) mid-drain"
        )
    for k, v in detail["audit"].items():
        if k.endswith("_violations") and v:
            problems.append(f"audit: {k}={v}")
    assert not problems, "; ".join(problems)
    return detail


def main_ingest() -> dict:
    """Pod-ingest-plane smoke: the mixed commit-plane workload (bulk,
    anti-affinity, hard spread — every dispatch flavor) plus a distinct-
    signature slice that overflows the SigBank default unless the warmup
    census pre-sized it. Must drain with the INDEX path covering every
    dispatch, only KB-scale pod-side bytes on the wire, zero stale-row
    fallbacks, and zero mid-drain mirror rebuilds."""
    import bench

    bench.BATCH = SMOKE_BATCH
    state = {}

    def inspect(sched):
        state["stats"] = dict(sched.stats)
        state["stage"] = dict(sched.stage.stats) if sched.stage else None
        state["bank"] = dict(sched.stage_bank.stats) if sched.stage_bank else None

    detail = bench.run_config(
        "tiny_ingest_smoke", ingest_smoke_config, inspect=inspect
    )
    phase = detail["phase_split_s"]
    problems = []
    want = N_PODS + N_UNIQ
    if detail["scheduled"] != want:
        problems.append(f"scheduled {detail['scheduled']} of {want} pods")
    if not phase.get("ingest_index_batches", 0):
        problems.append(
            "ingest coverage is ZERO (no dispatch took the index-only path)"
        )
    if phase.get("ingest_legacy_batches", 0):
        problems.append(
            f"{phase['ingest_legacy_batches']} legacy host-built dispatch(es) "
            "on a quiet drain (the plane fell back)"
        )
    if phase.get("ingest_stale_rows", 0):
        problems.append(
            f"{phase['ingest_stale_rows']} stale staged row(s) on a quiet "
            "drain (no update/delete happened — bookkeeping bug)"
        )
    pods_bytes = detail.get("patch_bytes", {}).get("pods", 0)
    if not 0 < pods_bytes <= 64 * 1024:
        problems.append(
            f"patch_bytes.pods = {pods_bytes} — expected KB-scale index "
            "vectors (the full pod-array upload is the legacy path)"
        )
    if detail["compile"]["misses_after_warmup"]:
        problems.append(
            f"{detail['compile']['misses_after_warmup']} compile-spec "
            "miss(es) after warmup — staging/gather compiled mid-drain"
        )
    if detail.get("mirror_rebuilds", 0):
        problems.append(
            f"mirror_rebuilds = {detail['mirror_rebuilds']} — the warmup "
            "census failed to pre-size the signature/pattern banks"
        )
    for k, v in detail["audit"].items():
        if k.endswith("_violations") and v:
            problems.append(f"audit: {k}={v}")
    assert not problems, "; ".join(problems)
    detail["ingest_state"] = state
    return detail


def main_terms() -> dict:
    """Term-bank-plane smoke: the affinity-heavy workload (every pod
    carries spread/affinity/anti terms — the InterPodAffinity wall's
    shape). Must drain with the term INDEX path covering every dispatch,
    only KB-scale term bytes on the wire (vs the full padded term-table
    upload the legacy path ships per dispatch), zero stale-entry
    fallbacks, zero mid-drain mirror rebuilds, and zero compile misses
    after warmup — the term scatters and the term gather are planned
    programs."""
    import bench

    bench.BATCH = SMOKE_BATCH
    state = {}

    def inspect(sched):
        state["stats"] = dict(sched.stats)
        state["tstage"] = dict(sched.tstage.stats) if sched.tstage else None
        state["term_bank"] = (
            dict(sched.term_bank.stats) if sched.term_bank else None
        )

    detail = bench.run_config(
        "tiny_terms_smoke", terms_smoke_config, inspect=inspect
    )
    phase = detail["phase_split_s"]
    problems = []
    if detail["scheduled"] != N_PODS:
        problems.append(f"scheduled {detail['scheduled']} of {N_PODS} pods")
    if not phase.get("term_index_batches", 0):
        problems.append(
            "term coverage is ZERO (no dispatch took the index-only term path)"
        )
    if phase.get("term_legacy_batches", 0):
        problems.append(
            f"{phase['term_legacy_batches']} legacy host-compiled term "
            "table(s) on a quiet drain (the plane fell back)"
        )
    if phase.get("term_stale_rows", 0):
        problems.append(
            f"{phase['term_stale_rows']} stale term entr(ies) on a quiet "
            "drain (no update/delete happened — bookkeeping bug)"
        )
    term_bytes = detail.get("patch_bytes", {}).get("terms", 0)
    if not 0 < term_bytes <= 64 * 1024:
        problems.append(
            f"patch_bytes.terms = {term_bytes} — expected KB-scale index/"
            "owner vectors (the full term-table upload is the legacy path)"
        )
    if detail["compile"]["misses_after_warmup"]:
        problems.append(
            f"{detail['compile']['misses_after_warmup']} compile-spec "
            "miss(es) after warmup — term staging/gather compiled mid-drain"
        )
    if detail.get("mirror_rebuilds", 0):
        problems.append(
            f"mirror_rebuilds = {detail['mirror_rebuilds']} mid-drain"
        )
    for k, v in detail["audit"].items():
        if k.endswith("_violations") and v:
            problems.append(f"audit: {k}={v}")
    assert not problems, "; ".join(problems)
    detail["terms_state"] = state
    return detail


def main_columnar() -> dict:
    """Columnar-scheduler-cache smoke (state/columns.py): a covered
    plain+anti drain must commit every pod through the COLUMNAR bulk
    path with ZERO per-pod NodeInfo object updates — columnar coverage
    > 0, zero lazy-view materializations on the commit path, zero
    scalar (object-path) pods — while the banks stay bit-exact: the
    device-divergence probe (which now cross-checks the columns against
    the host banks as one vectorized compare) must come back empty, and
    `misses_after_warmup == 0` as everywhere."""
    import bench

    bench.BATCH = SMOKE_BATCH
    state = {}

    def inspect(sched):
        # drain FIRST: in-flight tail applies are part of the commit
        # path — their materializations/scalar pods must not escape the
        # zero-assertions by a stats snapshot taken too early
        sched._commit_pipe.drain()
        m = sched.mirror
        m.sync()
        m.device_arrays()
        cols = sched.cache._columns
        state["cols"] = cols.stats_snapshot() if cols is not None else None
        state["divergence"] = m.device_bank_divergence()

    detail = bench.run_config(
        "tiny_columnar_smoke", columnar_smoke_config, inspect=inspect
    )
    problems = []
    if detail["scheduled"] != N_PODS:
        problems.append(f"scheduled {detail['scheduled']} of {N_PODS} pods")
    cols = state.get("cols")
    if cols is None:
        problems.append(
            "columnar cache never attached (KTPU_COLUMNAR_CACHE plane off)"
        )
    else:
        if not cols.get("bulk_pods", 0):
            problems.append(
                "columnar coverage is ZERO (no pod committed through the "
                "bulk column path)"
            )
        if cols.get("materializations", 0):
            problems.append(
                f"{cols['materializations']} lazy-view materialization(s) "
                "on a covered drain — something on the commit path still "
                "reads NodeInfo objects"
            )
        if cols.get("scalar_pods", 0):
            problems.append(
                f"{cols['scalar_pods']} pod(s) took the scalar object "
                "path on a covered drain"
            )
    if state.get("divergence"):
        problems.append(
            f"columns/banks diverged: {state['divergence']}"
        )
    if detail["compile"]["misses_after_warmup"]:
        problems.append(
            f"{detail['compile']['misses_after_warmup']} compile-spec "
            "miss(es) after warmup"
        )
    for k, v in detail["audit"].items():
        if k.endswith("_violations") and v:
            problems.append(f"audit: {k}={v}")
    assert not problems, "; ".join(problems)
    detail["columnar_state"] = state
    return detail


FAULTS_SPEC = (
    # the seeded chaos schedule, by injection site (faults/inject):
    # counts are CALL indices at each site, chosen so every fault lands
    # in a known phase of the drain — same spec, same schedule, any run.
    "uploader-death:ingest@1;"      # first post-warmup uploader wake dies
    "device-raise:gather-terms@3x3;"  # 3 consecutive → terms breaker trips
    "device-raise:fold@2x3;"        # 3 consecutive → fold breaker trips
    "device-raise:apply@2x3;"       # commit worker dies 3× → commit trips
    "device-raise:solve@8;"         # one solve dispatch raises mid-drain
    "bind-error@4x2;"               # two bind RPCs fail → backoff requeues
    "watch-break:pods@30;"          # the pod watch stream breaks mid-drain
    "bank-skew@5"                   # device bank skewed → divergent audit
)

#: planes the seeded schedule MUST trip (columns is exercised by the
#: unit suite; the smoke proves the drain-scale ladder)
FAULTS_EXPECT_TRIPPED = ("ingest", "terms", "fold", "commit", "mirror")


def main_faults() -> dict:
    """Seeded chaos smoke (kubernetes_tpu/faults): ONE drain through the
    REAL replication protocol (FakeAPIServer → informers → EventHandlers
    → queue/cache, binds echo back through the watch) with the full
    seeded fault schedule injected — uploader death, per-kind device
    raises, a watch-stream break, bind errors, a commit-worker death,
    and a forced bank skew — over a mixed (anti + hard-spread + plain)
    workload plus a preemption wave. Asserts the degradation ladder's
    acceptance criteria: the drain completes with ZERO lost and ZERO
    double-bound pods, every fault in the schedule fired, every plane
    the schedule targets tripped AND re-closed through the audit-gated
    probe, the final shadow audit is clean, and the recovered planes are
    COVERED again (index-path dispatches after re-close)."""
    import threading
    import time

    import bench
    from kubernetes_tpu.apiserver.store import FakeAPIServer
    from kubernetes_tpu.client.informer import APIBinder, start_scheduler_informers
    from kubernetes_tpu.faults import CLOSED, FaultPlan
    from kubernetes_tpu.metrics import metrics as M
    from kubernetes_tpu.scheduler.driver import Binder, Scheduler
    from kubernetes_tpu.scheduler.eventhandlers import EventHandlers
    from kubernetes_tpu.state.cache import SchedulerCache
    from kubernetes_tpu.state.queue import PriorityQueue

    plan = FaultPlan.parse(FAULTS_SPEC)
    api = FakeAPIServer()
    nodes = [bench.mk_node(i, zone=bench.ZONES[i % 4]) for i in range(N_NODES)]
    for n in nodes:
        api.create("nodes", n)

    cache = SchedulerCache()
    queue = PriorityQueue()
    binds: list = []
    bind_lock = threading.Lock()
    api_binder = APIBinder(api)

    def counted_bind(pod, node):
        api_binder.bind(pod, node)  # a raising bind is NOT counted
        with bind_lock:
            binds.append(pod.key())

    def delete_victim(p):
        # kube semantics: deleting an already-gone victim is a no-op (a
        # second preemption round can race the informer's removal)
        from kubernetes_tpu.apiserver.store import NotFoundError

        try:
            api.delete("pods", p.key())
        except NotFoundError:
            pass

    sched = Scheduler(
        cache=cache, queue=queue, binder=Binder(counted_bind),
        batch_size=SMOKE_BATCH, enable_preemption=True, spec_depth=2,
        delete_fn=delete_victim,
        fault_plan=plan,
    )
    # smoke-scale breaker cadence: trips must probe within the drain,
    # but the failure WINDOW stays wide — the schedule's consecutive
    # site calls land minutes apart at chaos-drain batch cadence
    for b in sched.faults.breakers.values():
        b.cooldown_s = 0.75
        b._cooldown = 0.75
        b.window_s = 300.0
    mon = sched.enable_health_monitor(interval=3600, audit_every=0, start=False)
    # baseline the process-global counters: a full pytest run's earlier
    # tests already incremented them, and absolute asserts would false-
    # pass on that history (the PR 10 never-the-shared-registry rule)
    rpc_fail0 = M.bind_failures.value("rpc")
    relists0 = int(M.informer_relists.value("pods"))
    handlers = EventHandlers(cache, queue)
    informers = start_scheduler_informers(api, handlers, fault_plan=plan)
    problems = []
    created = {}
    try:
        for inf in informers.values():
            assert inf.wait_for_sync()

        def create_pending(pods):
            for p in pods:
                created[p.key()] = p
                api.create("pods", p)

        # phase 1: the mixed wave (anti + hard spread + plain) — most of
        # the schedule lands here
        _, wave1 = tiny_commit_plane_config()
        create_pending(wave1)
        deadline = time.monotonic() + 30
        while queue.pending_count() < len(wave1) and time.monotonic() < deadline:
            time.sleep(0.01)
        sched.warmup()

        def drain(expect_bound, budget_s=120.0):
            """Drive batches until every expected pod is bound in the
            apiserver (lost pods would hang here — the budget converts a
            hang into a failure), servicing faults on idle rounds so
            open breakers keep probing."""
            deadline = time.monotonic() + budget_s
            while time.monotonic() < deadline:
                bound = sum(
                    1 for p in api.list("pods")[0] if p.node_name
                )
                if bound >= expect_bound and queue.pending_count() == 0:
                    return True
                r = sched.schedule_batch()
                if not (r.scheduled or r.unschedulable or r.errors
                        or r.deferred):
                    sched.service_faults()
                    queue.flush()
                    time.sleep(0.2)  # backoff requeues / informer lag
            return False

        if not drain(len(wave1)):
            problems.append("mixed chaos wave never fully bound")
        sched.wait_for_binds()

        # phase 2: preemption wave — fill the cluster with BOUND
        # low-priority victims, then high-priority pods that only fit by
        # eviction (victim deletes flow through the real API + informer)
        victims = []
        for i in range(N_NODES * 3):  # 3 × 9000m of each node's 32 cores
            p = bench.mk_pod(1_000_000 + i, cpu="9000m", mem="1Gi",
                             labels={"app": f"lowprio-{i % 4}"})
            p.priority = 0
            p.node_name = f"node-{i % N_NODES}"
            victims.append(p)
            api.create("pods", p)
        hiprio = []  # 6000m does NOT fit next to 27000m used: must evict
        for i in range(500_000, 500_000 + 4):
            p = bench.mk_pod(i, cpu="6000m", mem="2Gi",
                             labels={"app": "hiprio"})
            p.priority = 1000
            hiprio.append(p)
        deadline = time.monotonic() + 30
        while cache.pod_count() < len(wave1) + len(victims) and \
                time.monotonic() < deadline:
            time.sleep(0.01)  # victims land in the cache via the informer
        create_pending(hiprio)
        deadline = time.monotonic() + 30
        while queue.pending_count() < len(hiprio) and \
                time.monotonic() < deadline:
            time.sleep(0.01)
        total_created = len(api.list("pods")[0])  # wave1 + victims + hiprio
        # hiprio pods bind; some victims get DELETED (absent from the
        # store afterwards) — expected bound = everything still present
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            live = api.list("pods")[0]
            if all(p.node_name for p in live) and queue.pending_count() == 0:
                break
            r = sched.schedule_batch()
            if not (r.scheduled or r.unschedulable or r.errors or r.deferred):
                sched.service_faults()
                queue.flush()
                time.sleep(0.2)
        sched.wait_for_binds()
        live = api.list("pods")[0]
        if not all(p.node_name for p in live):
            problems.append(
                f"{sum(1 for p in live if not p.node_name)} pod(s) left "
                "unbound after the preemption wave"
            )
        n_evicted = total_created - len(live)
        if not n_evicted:
            problems.append("no preemption happened — the wave is broken")

        if not plan.exhausted():
            problems.append(f"schedule not fully delivered: {plan.census()}")

        # phase 3: recovery wave — every tripped plane must re-close
        # through its audit-gated probe, then run COVERED again
        idx0 = sched.stats.get("ingest_index_batches", 0)
        tidx0 = sched.stats.get("term_index_batches", 0)
        next_recovery = [700_000]  # monotone key source: no re-creates

        deadline = time.monotonic() + 60
        first_wave = True
        while time.monotonic() < deadline:
            states = {p: b.state for p, b in sched.faults.breakers.items()}
            # at least one recovery wave ALWAYS runs: the re-covered
            # assertion below needs covered batches after the re-closes
            if not first_wave and all(s == CLOSED for s in states.values()):
                break
            first_wave = False
            wave = []
            for _ in range(8):
                wave.append(bench.mk_pod(next_recovery[0], cpu="100m",
                                         mem="64Mi"))
                next_recovery[0] += 1
            create_pending(wave)
            t0 = time.monotonic()
            while queue.pending_count() == 0 and time.monotonic() - t0 < 5:
                time.sleep(0.01)
            drain(len(api.list("pods")[0]), budget_s=20.0)
        sched.wait_for_binds()

        census = sched.faults.census()["breakers"]
        for plane in FAULTS_EXPECT_TRIPPED:
            if not census[plane]["trips"]:
                problems.append(f"plane {plane} never tripped: {census[plane]}")
        for plane, c in census.items():
            if c["state"] != CLOSED:
                problems.append(f"plane {plane} did not re-close: {c}")
        for plane in ("ingest", "terms", "fold", "commit", "mirror"):
            if census[plane]["trips"] and not (
                census[plane]["probes_passed"]
            ):
                problems.append(
                    f"plane {plane} closed without a passed probe: "
                    f"{census[plane]}"
                )
        # recovered planes are COVERED again: index-path dispatches after
        # the trips (not a permanent legacy fallback)
        if not sched.stats.get("ingest_index_batches", 0) > idx0:
            problems.append("ingest plane never re-covered after its trip")
        if not sched.stats.get("term_index_batches", 0) > tidx0:
            problems.append("term plane never re-covered after its trip")

        # audits green: the forced skew was caught (divergent >= 1,
        # escalated) and the FINAL audit on the recovered banks is clean
        sched._commit_pipe.drain()
        sched.mirror.sync()
        final_div = mon.run_shadow_audit()
        if final_div:
            problems.append(f"final shadow audit divergent: {final_div}")
        audits = mon.audit_counts()
        if not audits.get("divergent"):
            problems.append(
                f"the forced bank skew never produced a divergent audit "
                f"({audits})"
            )
        uploader = sched.stage_bank.census()["uploader"]
        if uploader["restarts"] != 1:
            problems.append(
                f"uploader restarted {uploader['restarts']}× (contract: "
                "exactly once per trip)"
            )
        if not uploader["alive"]:
            problems.append("restarted uploader is not running")
        if M.bind_failures.value("rpc") - rpc_fail0 < 2:
            problems.append("injected bind errors were not counted")
        if int(M.informer_relists.value("pods")) - relists0 < 2:
            problems.append("the watch break never forced a relist")

        # zero lost / zero double-scheduled: every surviving pod bound
        # exactly once (victims were deleted, never re-bound)
        from collections import Counter

        per_key = Counter(binds)
        dups = {k: v for k, v in per_key.items() if v > 1}
        if dups:
            problems.append(f"double-bound pods: {dups}")
        live = api.list("pods")[0]
        unbound = [p.key() for p in live if not p.node_name]
        if unbound:
            problems.append(f"lost pods (never bound): {unbound[:8]}")
    finally:
        for inf in informers.values():
            inf.stop()
        sched.close()

    assert not problems, "; ".join(problems)
    return {
        "config": "tiny_faults_smoke",
        "bound": len(binds),
        "evicted": n_evicted,
        "breakers": {
            p: {k: c[k] for k in ("state", "trips", "probes_passed")}
            for p, c in census.items()
        },
        "audits": audits,
        "plan": plan.census(),
        "uploader_restarts": uploader["restarts"],
        "relists": int(M.informer_relists.value("pods")) - relists0,
        "phase_split_s": dict(sched.stats),
    }


def main_restart() -> dict:
    """Crash-restart smoke (kubernetes_tpu/restart): ONE persistent
    FakeAPIServer holds the mixed (anti + hard-spread + plain) workload;
    a deterministic ``crash:mid-bind-chunk@2`` kill-point simulates
    ``kill -9`` mid-drain — some binds of the chunk landed, the rest
    never happened — the Supervisor buries the dead instance, cold-start
    reconciles a FRESH one from the relist (same persistent compile
    ladder: the re-warm is trace-only), and the resumed drain completes.
    Asserts the crash-restart acceptance set: the kill fired, exactly
    one restart, zero lost / zero double-bound pods, no node
    over-commit, a clean shadow audit on the survivor,
    ``misses_after_warmup == 0`` on the restarted incarnation, and the
    reconciliation wall reported by phase (the report AND
    ``scheduler_restart_reconcile_duration_seconds{phase}``)."""
    import tempfile

    from kubernetes_tpu.apiserver.store import FakeAPIServer
    from kubernetes_tpu.metrics import metrics as M
    from kubernetes_tpu.restart import PHASES, check_invariants, run_cell

    api = FakeAPIServer()
    nodes, pods = tiny_commit_plane_config()
    for n in nodes:
        api.create("nodes", n)
    created = []
    for p in pods:
        created.append(p.key())
        api.create("pods", p)

    # baseline the process-global counters (the PR 10 never-the-shared-
    # registry rule: earlier tests in a full run already incremented them)
    mm0 = M.bind_conflicts.value("mismatch")
    restarts0 = M.restarts.value()
    phase_counts0 = {
        ph: M.restart_reconcile_duration.count(ph) for ph in PHASES
    }

    cache_dir = tempfile.mkdtemp(prefix="ktpu_restart_smoke_")
    rep = run_cell(
        api, "crash:mid-bind-chunk@2", compile_cache_dir=cache_dir,
        scheduler_kwargs=dict(batch_size=SMOKE_BATCH, speculate=False),
        budget_s=180.0,
    )
    problems = list(rep.problems)
    if not rep.completed:
        problems.append("resumed drain never completed")
    if rep.crashes != 1:
        problems.append(f"expected exactly 1 kill, saw {rep.crashes}")
    if len(rep.incarnations) != 2:
        problems.append(f"expected 2 incarnations, saw {len(rep.incarnations)}")
    surv = rep.final.sched
    problems += check_invariants(
        api, created, sched=surv,
        mismatch_conflicts=M.bind_conflicts.value("mismatch") - mm0,
    )
    if surv.compile_plan.stats["misses_after_warmup"]:
        problems.append(
            f"misses_after_warmup="
            f"{surv.compile_plan.stats['misses_after_warmup']} on the "
            "restarted incarnation (the persistent ladder re-warm must "
            "be trace-only)"
        )
    report = rep.final.report
    if report is None or not report.phases_s:
        problems.append("survivor carries no phase-timed reconcile report")
    else:
        missing = [ph for ph in PHASES if ph not in report.phases_s]
        if missing:
            problems.append(f"reconcile report missing phases: {missing}")
    # the wall also reached the exposition surface, per phase (2 cold
    # starts ran: the first incarnation's and the restarted one's)
    under_counted = [
        ph for ph in PHASES
        if M.restart_reconcile_duration.count(ph) - phase_counts0[ph] < 2
    ]
    if under_counted:
        problems.append(
            "scheduler_restart_reconcile_duration_seconds missing phase "
            f"observations: {under_counted}"
        )
    if M.restarts.value() - restarts0 < 2:
        problems.append("scheduler_restarts_total did not count the cold starts")

    # teardown (harness hygiene)
    for inc in rep.incarnations:
        for inf in inc.informers.values():
            inf.stop()
    surv.close()
    assert not problems, "; ".join(problems)
    return {
        "config": "tiny_restart_smoke",
        "crashes": rep.crashes,
        "incarnations": len(rep.incarnations),
        "bound": sum(1 for p in api.list("pods")[0] if p.node_name),
        "reconcile_phases_s": {
            k: round(v, 6) for k, v in report.phases_s.items()
        },
        "reconcile_total_s": round(report.total_s, 6),
        "misses_after_warmup": surv.compile_plan.stats["misses_after_warmup"],
    }


if __name__ == "__main__":
    mode = sys.argv[1] if len(sys.argv) > 1 else ""
    if mode == "preempt":
        d = main_preempt()
    elif mode == "ingest":
        d = main_ingest()
    elif mode == "terms":
        d = main_terms()
    elif mode == "columnar":
        d = main_columnar()
    elif mode == "trace":
        d = main_trace()
        print(json.dumps({
            k: d[k] for k in (
                "config", "scheduled", "trace_events", "trace_threads",
                "span_names", "overhead_frac", "misses_after_warmup",
            )
        }))
        sys.exit(0)
    elif mode == "health":
        d = main_health()
        print(json.dumps({
            k: d[k] for k in (
                "config", "scheduled", "audits", "overhead_frac",
                "misses_after_warmup", "budget_obs", "census_planes",
            )
        }))
        sys.exit(0)
    elif mode == "faults":
        d = main_faults()
        print(json.dumps({
            k: d[k] for k in (
                "config", "bound", "evicted", "breakers", "audits",
                "uploader_restarts", "relists",
            )
        }))
        sys.exit(0)
    elif mode == "restart":
        d = main_restart()
        print(json.dumps(d))
        sys.exit(0)
    else:
        d = main(sharded=(mode == "sharded"))
    p = d["phase_split_s"]
    print(json.dumps({
        "config": d["config"],
        "scheduled": d["scheduled"],
        "deferred": d.get("deferred", 0),
        "preempted": d.get("preempted", 0),
        "ingest_index_batches": p.get("ingest_index_batches", 0),
        "ingest_legacy_batches": p.get("ingest_legacy_batches", 0),
        "term_index_batches": p.get("term_index_batches", 0),
        "term_legacy_batches": p.get("term_legacy_batches", 0),
        "arbiter_batches": p.get("arbiter_batches", 0),
        "arbiter_place": p.get("arbiter_place", 0),
        "arbiter_defer": p.get("arbiter_defer", 0),
        "fold_batches": p.get("fold_batches", 0),
        "fold_pods": p.get("fold_pods", 0),
        "sharded_fallbacks": p.get("sharded_fallbacks", 0),
        "patch_bytes": d.get("patch_bytes", {}),
        "commit_s": p.get("commit_s"),
        "solve_s": p.get("solve_s"),
        "warm_stall_batches": d.get("warm_stall_batches", 0),
        "misses_after_warmup": d["compile"]["misses_after_warmup"],
    }))
