#!/usr/bin/env python
"""Stage split at a bench config's real shapes: mask_and_score vs
solve_greedy, chained truthfully. Env: CFG=2 BENCH_SCALE=0.2 N_PODS=1024."""
import os
import sys
import time
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_compilation_cache_dir",
                  os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), ".jax_cache"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

import jax.numpy as jnp

from bench import CONFIGS
from kubernetes_tpu.oracle import Snapshot
from kubernetes_tpu.ops.pipeline import encode_solve_args, mask_and_score
from kubernetes_tpu.ops.solver import pop_order, solve_greedy, tie_noise

name, build = CONFIGS[os.environ.get("CFG", "2")]
nodes, pods = build()
pods = pods[: int(os.environ.get("N_PODS", "1024"))]
snap = Snapshot(nodes, [])
args = encode_solve_args(snap, pods)
dev_args = jax.device_put(args)
na, pa, ea, tb, xa, au, ids, key = dev_args
print(f"{name}: N={na['valid'].shape[0]} B={pa['valid'].shape[0]}", flush=True)

ms_jit = jax.jit(partial(mask_and_score, config=None, term_kinds=None))


def chain(label, fn, n=6):
    out = fn(jax.random.fold_in(key, 999))
    jnp.max(out[0] if isinstance(out, tuple) else out).block_until_ready()
    t0 = time.perf_counter()
    for i in range(n):
        out = fn(jax.random.fold_in(key, i))
        x = out[0] if isinstance(out, tuple) else out
        _ = float(jnp.max(x).astype(jnp.float32))
    print(f"{label}: {(time.perf_counter()-t0)/n*1000:.1f}ms/call", flush=True)
    return out


mask, score = chain("mask_and_score", lambda k: ms_jit(na, pa, ea, tb, xa, au, ids))
mask, score = jax.device_put((mask, score))
free0 = na["alloc"] - na["requested"]
b = pa["valid"].shape[0]
order = pop_order(pa["priority"], jnp.arange(b, dtype=jnp.int32), pa["valid"])
count0 = na["pod_count"].astype(free0.dtype)
allowed = na["allowed_pods"].astype(free0.dtype)

chain("solve_greedy", lambda k: solve_greedy(
    mask, score, pa["req"], free0, count0, allowed, order, k,
    deterministic=False, req_any=pa["req_any"]))

chain("tie_noise alone", lambda k: tie_noise(k, b, int(na["valid"].shape[0])))
