#!/usr/bin/env python
"""Per-kernel device-time ablation: run each kernel R times inside one jitted
fori_loop (loop-carried perturbation defeats hoisting), so tunnel RTT and
dispatch overhead amortize away. CFG env var picks the bench config."""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_compilation_cache_dir",
                  os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), ".jax_cache"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

import jax.numpy as jnp

from bench import CONFIGS
from kubernetes_tpu.oracle import Snapshot
from kubernetes_tpu.ops import filters as F
from kubernetes_tpu.ops import scores as S
from kubernetes_tpu.ops.pipeline import encode_solve_args, mask_and_score
from kubernetes_tpu.ops.solver import pop_order, solve_greedy

name, build = CONFIGS[os.environ.get("CFG", "2")]
nodes, pods = build()
pods = pods[: int(os.environ.get("N_PODS", "128"))]
snap = Snapshot(nodes, [])
args = encode_solve_args(snap, pods)
dev_args = jax.device_put(args)
na, pa, ea, tb, xa, au, ids, key = dev_args
print(f"{name}: N={na['valid'].shape[0]} B={pa['valid'].shape[0]}", flush=True)

R = 20


def timeit(label, kernel):
    """kernel(na_perturbed) -> array; repeated R times in-program."""

    @jax.jit
    def rep(na_, pa_):
        def body(i, acc):
            na2 = dict(na_)
            na2["requested"] = na_["requested"] + i  # defeat loop hoisting
            return acc + jnp.max(kernel(na2, pa_)).astype(jnp.float32)

        return jax.lax.fori_loop(0, R, body, jnp.float32(0))

    float(rep(na, pa))  # compile
    t0 = time.perf_counter()
    float(rep(na, pa))
    dt = (time.perf_counter() - t0) / R
    print(f"{label}: {dt*1000:.1f}ms/call", flush=True)


timeit("mask_and_score", lambda na_, pa_: mask_and_score(na_, pa_, ea, tb, xa, au, ids)[1])
timeit("combined_mask", lambda na_, pa_: F.combined_mask(na_, pa_, ids))
timeit("score_matrix", lambda na_, pa_: S.score_matrix(na_, pa_))
timeit("least_requested", S.least_requested)
timeit("balanced_allocation", S.balanced_allocation)
timeit("node_affinity", S.node_affinity)
timeit("taint_toleration", S.taint_toleration)
timeit("prefer_avoid_pods", S.prefer_avoid_pods)
timeit("image_locality", lambda na_, pa_: S.image_locality(na_, pa_) if "image_scaled" in na_ else jnp.zeros(1))
timeit("pod_match_node_selector", F.pod_match_node_selector)

b = pa["valid"].shape[0]
order = pop_order(pa["priority"], jnp.arange(b, dtype=jnp.int32), pa["valid"])
count0 = na["pod_count"]
mask, score = mask_and_score(na, pa, ea, tb, xa, au, ids)
mask, score = jax.device_put((mask, score))


def solve_kernel(na_, pa_):
    free0 = na_["alloc"] - na_["requested"]
    return solve_greedy(mask, score, pa_["req"], free0,
                        count0.astype(free0.dtype),
                        na_["allowed_pods"].astype(free0.dtype),
                        order, key, deterministic=False, req_any=pa_["req_any"])


timeit("solve_greedy", solve_kernel)
