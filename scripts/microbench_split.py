#!/usr/bin/env python
"""Split warm device time: mask+score vs greedy scan vs RNG inside the scan."""
import os
import sys
import time
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_compilation_cache_dir", "/tmp/jax_compile_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

import jax.numpy as jnp

N_NODES = int(sys.argv[1]) if len(sys.argv) > 1 else 10000
BATCH = int(sys.argv[2]) if len(sys.argv) > 2 else 1024

from bench import ZONES, mk_node, mk_pod  # noqa: E402
from kubernetes_tpu.api.types import LabelSelector, TopologySpreadConstraint  # noqa: E402
from kubernetes_tpu.oracle import Snapshot  # noqa: E402
from kubernetes_tpu.ops.pipeline import encode_solve_args, mask_and_score  # noqa: E402
from kubernetes_tpu.ops.solver import pop_order, solve_greedy  # noqa: E402

nodes = [mk_node(i, zone=ZONES[i % len(ZONES)]) for i in range(N_NODES)]
pods = []
for i in range(BATCH):
    p = mk_pod(i, labels={"app": f"svc-{i % 100}"})
    p.topology_spread_constraints = [TopologySpreadConstraint(
        max_skew=1,
        topology_key="failure-domain.beta.kubernetes.io/zone",
        when_unsatisfiable="ScheduleAnyway",
        label_selector=LabelSelector(match_labels={"app": p.labels["app"]}),
    )]
    pods.append(p)
snap = Snapshot(nodes, [])
args = encode_solve_args(snap, pods)
dev_args = jax.device_put(args)
jax.block_until_ready(dev_args)
na, pa, ea, tb, xa, au, ids, key = dev_args
term_kinds = frozenset({"spread_soft", "sel_spread"})

ms_jit = jax.jit(partial(mask_and_score, config=None, term_kinds=term_kinds))


def timeit(label, fn, n=4):
    out = fn()
    jax.block_until_ready(out)
    ts = []
    for _ in range(n):
        t0 = time.perf_counter()
        out = fn()
        jax.block_until_ready(out)
        ts.append(time.perf_counter() - t0)
    print(f"{label}: {min(ts)*1000:.1f}ms (min of {n})", flush=True)
    return out


mask, score = timeit("mask_and_score", lambda: ms_jit(na, pa, ea, tb, xa, au, ids))

free0 = na["alloc"] - na["requested"]
b = pa["valid"].shape[0]
order = pop_order(pa["priority"], jnp.arange(b, dtype=jnp.int32), pa["valid"])
count0 = na["pod_count"].astype(free0.dtype)
allowed = na["allowed_pods"].astype(free0.dtype)

timeit("solve_greedy (random tie-break)", lambda: solve_greedy(
    mask, score, pa["req"], free0, count0, allowed, order, key,
    deterministic=False, req_any=pa["req_any"]))

timeit("solve_greedy (deterministic)", lambda: solve_greedy(
    mask, score, pa["req"], free0, count0, allowed, order, key,
    deterministic=True, req_any=pa["req_any"]))

print(f"shapes: mask {mask.shape} score {score.dtype}{score.shape} free0 {free0.shape}")
