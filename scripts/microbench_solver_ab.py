#!/usr/bin/env python
"""A/B solver variants on device at config-3 shapes, truthfully chained.
Interleaved repeats inside ONE process (tunnel weather varies hour-scale).
Env: CFG=3 N_PODS=1024 REPS=4."""
import os
import sys
import time
from functools import partial

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_compilation_cache_dir",
                  os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), ".jax_cache"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

import jax.numpy as jnp
import numpy as np

from bench import CONFIGS
from kubernetes_tpu.oracle import Snapshot
from kubernetes_tpu.ops.pipeline import encode_solve_args
from kubernetes_tpu.ops.solver import pop_order, solve_greedy

name, build = CONFIGS[os.environ.get("CFG", "3")]
nodes, pods = build()
pods = pods[: int(os.environ.get("N_PODS", "1024"))]
REPS = int(os.environ.get("REPS", "4"))
snap = Snapshot(nodes, [])
args = encode_solve_args(snap, pods)
na, pa, ea, tb, xa, au, ids, key = jax.device_put(args)
N = int(na["valid"].shape[0])
B = int(pa["valid"].shape[0])
print(f"{name}: N={N} B={B}", flush=True)

free0 = na["alloc"] - na["requested"]
order = pop_order(pa["priority"], jnp.arange(B, dtype=jnp.int32), pa["valid"])
count0 = na["pod_count"].astype(free0.dtype)
allowed = na["allowed_pods"].astype(free0.dtype)
# spec rows: identity here (un-deduped) -> worst case [B, N] mask.
# few distinct scores -> heavy ties -> the noise tie-break and the
# same-node repair loop are both exercised like the real spread configs
rng = np.random.RandomState(0)
mask = jnp.asarray(rng.rand(B, N) < 0.95) & na["valid"][None, :]
score = jnp.asarray(rng.randint(0, 8, (B, N)).astype(np.int64))


def hash_noise(rng_key, b, n):
    kd = jax.random.key_data(rng_key).astype(jnp.uint32)
    i = jnp.arange(b, dtype=jnp.uint32)[:, None]
    j = jnp.arange(n, dtype=jnp.uint32)[None, :]
    x = i * jnp.uint32(0x9E3779B1) + j * jnp.uint32(0x85EBCA77) ^ kd[0]
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x85EBCA6B)
    x = x ^ (x >> 13)
    x = x * jnp.uint32(0xC2B2AE35)
    x = x ^ kd[-1] ^ (x >> 16)
    return (x >> jnp.uint32(8)).astype(jnp.float32) * jnp.float32(1.0 / (1 << 24))


@partial(jax.jit, static_argnames=("b", "n"))
def noise_vmapped(k, b, n):
    keys = jax.random.split(k, b)
    return jax.vmap(lambda kk: jax.random.uniform(kk, (n,), dtype=jnp.float32))(keys)


@partial(jax.jit, static_argnames=("b", "n"))
def noise_single(k, b, n):
    return jax.random.uniform(k, (b, n), dtype=jnp.float32)


noise_hash = jax.jit(hash_noise, static_argnames=("b", "n"))


def chain(label, fn, reps=REPS):
    out = fn(jax.random.fold_in(key, 999))
    jnp.max(out[0] if isinstance(out, tuple) else out).block_until_ready()
    t0 = time.perf_counter()
    for i in range(reps):
        out = fn(jax.random.fold_in(key, i))
        x = out[0] if isinstance(out, tuple) else out
        _ = float(jnp.max(x).astype(jnp.float32))
    dt = (time.perf_counter() - t0) / reps * 1000
    print(f"{label}: {dt:.1f}ms/call", flush=True)
    return dt


results = {}
variants = [
    ("noise_vmapped", lambda k: noise_vmapped(k, B, N)),
    ("noise_single", lambda k: noise_single(k, B, N)),
    ("noise_hash", lambda k: noise_hash(k, B, N)),
    ("solve_K64", lambda k: solve_greedy(
        mask, score, pa["req"], free0, count0, allowed, order, k,
        deterministic=False, req_any=pa["req_any"], chunk=64)),
    ("solve_K128", lambda k: solve_greedy(
        mask, score, pa["req"], free0, count0, allowed, order, k,
        deterministic=False, req_any=pa["req_any"], chunk=128)),
    ("solve_K256", lambda k: solve_greedy(
        mask, score, pa["req"], free0, count0, allowed, order, k,
        deterministic=False, req_any=pa["req_any"], chunk=256)),
    ("solve_K512", lambda k: solve_greedy(
        mask, score, pa["req"], free0, count0, allowed, order, k,
        deterministic=False, req_any=pa["req_any"], chunk=512)),
]
# warm all compiles first, then interleave reps round-robin
for label, fn in variants:
    x = fn(jax.random.fold_in(key, 1234))
    x = x[0] if isinstance(x, tuple) else x
    jnp.max(x).block_until_ready()
print("compiles warm; interleaving", flush=True)
times = {label: 0.0 for label, _ in variants}
for rep in range(REPS):
    for label, fn in variants:
        t0 = time.perf_counter()
        out = fn(jax.random.fold_in(key, rep * 101))
        x = out[0] if isinstance(out, tuple) else out
        _ = float(jnp.max(x).astype(jnp.float32))
        times[label] += time.perf_counter() - t0
for label, _ in variants:
    print(f"{label}: {times[label] / REPS * 1000:.1f}ms/call", flush=True)
