"""Host-path microbenchmark: attribute the per-pod microseconds of a
schedule_batch cycle WITHOUT any device work (the device solve is ~10ms and
is not the wall — PERF.md round 3). Run on the bench host:

    python scripts/microbench_host.py

Phases measured on the 100k/10k headline shape (config 3):
  pop        — PriorityQueue.pop_batch(4096) from a ~100k heap
  spec_key   — _spec_key over the batch (dedup map)
  encode     — PodBatch.set_pod + compile_batch_terms over unique specs
  assume     — per-pod cache.assume_pod (with_node + NodeInfo accounting)
  sync       — TensorMirror.sync consuming the 4096 assume deltas
  commitmisc — CycleState + bookkeeping shell around assume
  bindchunk  — _lean_bind_chunk equivalent (finish_binding + histograms)
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


from kubernetes_tpu.models.generators import make_node, make_pod
from kubernetes_tpu.scheduler.driver import _spec_key
from kubernetes_tpu.state.cache import SchedulerCache, TensorMirror
from kubernetes_tpu.state.queue import PriorityQueue
from kubernetes_tpu.state.tensors import PodBatch, _bucket
from kubernetes_tpu.state.terms import compile_batch_terms

N_NODES = int(os.environ.get("MB_NODES", "10000"))
N_PODS = int(os.environ.get("MB_PODS", "100000"))
BATCH = int(os.environ.get("MB_BATCH", "4096"))
SPECS = int(os.environ.get("MB_SPECS", "100"))  # distinct controllers


def build():
    nodes = [
        make_node(
            f"n{i}",
            cpu_milli=64000,
            mem=256 * 2**30,
            labels={
                "zone": f"z{i % 16}",
                "kubernetes.io/hostname": f"n{i}",
            },
        )
        for i in range(N_NODES)
    ]
    pods = []
    for i in range(N_PODS):
        spec = i % SPECS
        p = make_pod(
            f"p{i}",
            cpu_milli=100,
            mem=200 * 2**20,
            labels={"app": f"a{spec}"},
        )
        pods.append(p)
    return nodes, pods


def t(label, fn, n=1, per=None):
    t0 = time.perf_counter()
    out = fn()
    dt = time.perf_counter() - t0
    unit = f"  ({dt / per * 1e6:.2f}us/pod)" if per else ""
    print(f"{label:12s} {dt * 1e3:9.2f} ms{unit}", flush=True)
    return out, dt


def main():
    print(f"nodes={N_NODES} pods={N_PODS} batch={BATCH} specs={SPECS}")
    nodes, pods = build()
    cache = SchedulerCache()
    for n in nodes:
        cache.add_node(n)
    queue = PriorityQueue()
    t("q.add all", lambda: [queue.add(p) for p in pods], per=N_PODS)

    mirror = TensorMirror(cache)
    mirror.reserve(N_NODES, N_PODS)
    mirror.sync()

    # the bench freezes+disables GC for the measured drain (bench.py) —
    # without this, generational walks over the ~1M-object cluster model
    # dominate every allocation-heavy phase below
    import gc

    gc.collect()
    gc.freeze()
    gc.disable()

    # -- pop ------------------------------------------------------------------
    infos, _ = t("pop_batch", lambda: queue.pop_batch(BATCH), per=BATCH)
    batch_pods = [i.pod for i in infos]

    # -- spec keys ------------------------------------------------------------
    def specs():
        sig_list = []
        reps = []
        idx = {}
        for p in batch_pods:
            k = _spec_key(p, None)
            u = idx.get(k)
            if u is None:
                u = len(reps)
                idx[k] = u
                reps.append(p)
            sig_list.append(u)
        return sig_list, reps

    (sig_list, reps), _ = t("spec_key", specs, per=BATCH)
    t("spec_key2", specs, per=BATCH)  # memo warm?

    # -- encode ---------------------------------------------------------------
    def encode():
        b = PodBatch(mirror.vocab, _bucket(len(reps)))
        for i, p in enumerate(reps):
            b.set_pod(i, p)
        tb, aux = compile_batch_terms(mirror.vocab, reps, b_capacity=b.capacity)
        return b, tb, aux

    t("encode", encode, per=BATCH)

    # -- assume (the commit loop's cache write) -------------------------------
    # round-robin placement; realistic: each node gets ~B/N pods
    names = [nodes[i % N_NODES].name for i in range(len(batch_pods))]

    def assume():
        cache.assume_pods([p.with_node(nm) for p, nm in zip(batch_pods, names)])

    t("assume_bulk", assume, per=BATCH)

    # -- sync (mirror consumes the deltas) ------------------------------------
    t("sync", mirror.sync, per=BATCH)

    # second round, warm
    infos2 = queue.pop_batch(BATCH)
    batch2 = [i.pod for i in infos2]
    names2 = [nodes[(7 * i) % N_NODES].name for i in range(len(batch2))]

    def assume2():
        cache.assume_pods([p.with_node(nm) for p, nm in zip(batch2, names2)])

    t("assume2_bulk", assume2, per=BATCH)
    t("sync2", mirror.sync, per=BATCH)

    def clone_only():
        return [p.with_node(nm) for p, nm in zip(batch2, names2)]

    t("with_node", clone_only, per=BATCH)

    # -- finish_binding + queue.age (the lean bind chunk) --------------------
    def finish():
        for p, info in zip(batch2, infos2):
            cache.finish_binding(p)
            queue.age(info)

    t("bind_finish", finish, per=BATCH)


if __name__ == "__main__":
    main()
