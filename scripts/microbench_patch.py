#!/usr/bin/env python
"""A/B microbench: dirty-row scatter-patch vs donated device fold.

Measures the two transports for one commit batch's bank update at the
ladder's row buckets:

  A (scatter) — the mirror's legacy patch path: gather the dirty rows'
    host slices (requested/nonzero_req/pod_count + signature counts),
    ship them, and `.at[idx].set(...)` into the banks — per-row bytes
    proportional to R + S.
  B (fold)    — the resident-state plane: ship only the per-commit
    control vectors and `.at[rows].add(...)` with BUFFER DONATION —
    banks updated in place, nothing row-shaped crosses the wire.

Timing discipline matches the other microbenches: trials interleave
A/B/A/B (drift hits both alike), and each trial runs a DATA-DEPENDENT
CHAIN — every call consumes the previous call's output bank, so async
dispatch can't overlap what we're trying to time — closed with one
block_until_ready.

Run: python scripts/microbench_patch.py [n_nodes] [sig_slots]
Smoke (tier-1, via tests/test_fold_plane.py): main(smoke=True) — tiny
shapes, asserts A/B produce bit-identical banks and returns the table.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if __name__ == "__main__":
    os.environ.setdefault("JAX_PLATFORMS", os.environ.get("JAX_PLATFORMS", ""))

import numpy as np


def _mk_banks(n, r, s, jnp):
    return {
        "requested": jnp.asarray(np.zeros((n, r), np.int64)),
        "nonzero_req": jnp.asarray(np.zeros((n, 2), np.int64)),
        "pod_count": jnp.asarray(np.zeros(n, np.int32)),
        "counts": jnp.asarray(np.zeros((n, s), np.int16)),
    }


def _mk_batch(rng, rows_b, n, r, s):
    """One commit batch's control data at row bucket rows_b."""
    rows = rng.integers(0, n, rows_b).astype(np.int32)
    req = rng.integers(0, 1000, (rows_b, r)).astype(np.int64)
    nz = rng.integers(0, 1000, (rows_b, 2)).astype(np.int64)
    cnt = np.ones(rows_b, np.int32)
    sig = rng.integers(0, s, rows_b).astype(np.int32)
    return rows, req, nz, cnt, sig


def main(smoke: bool = False):
    import jax
    import jax.numpy as jnp
    from functools import partial

    n = int(sys.argv[1]) if len(sys.argv) > 1 and not smoke else (64 if smoke else 4096)
    s = int(sys.argv[2]) if len(sys.argv) > 2 and not smoke else (64 if smoke else 256)
    r = 8
    buckets = (16, 64) if smoke else (64, 256, 1024, 4096)
    trials = 3 if smoke else 10
    chain = 4 if smoke else 16

    # A: the mirror's row scatter (no donation — the legacy transport)
    @jax.jit
    def scatter_patch(bank, idx, updates):
        out = dict(bank)
        for k, u in updates.items():
            out[k] = bank[k].at[idx].set(u)
        return out

    # B: the fold (donated adds — ops/fold.fold_commit_banks's shape,
    # inlined here so the bench is self-contained over one bank dict)
    @partial(jax.jit, donate_argnums=(0,))
    def fold_patch(bank, rows, req, nz, cnt, sig):
        return {
            "requested": bank["requested"].at[rows].add(
                req.astype(bank["requested"].dtype), mode="drop"),
            "nonzero_req": bank["nonzero_req"].at[rows].add(
                nz.astype(bank["nonzero_req"].dtype), mode="drop"),
            "pod_count": bank["pod_count"].at[rows].add(
                cnt.astype(bank["pod_count"].dtype), mode="drop"),
            "counts": bank["counts"].at[rows, sig].add(
                cnt.astype(bank["counts"].dtype), mode="drop"),
        }

    rng = np.random.default_rng(0)
    results = []
    for rows_b in buckets:
        rb = min(rows_b, n)
        batches = [_mk_batch(rng, rb, n, r, s) for _ in range(chain)]

        def host_apply(host, batch):
            rows, req, nz, cnt, sig = batch
            np.add.at(host["requested"], rows, req)
            np.add.at(host["nonzero_req"], rows, nz)
            np.add.at(host["pod_count"], rows, cnt)
            np.add.at(host["counts"], (rows, sig), cnt.astype(np.int16))

        def run_scatter():
            """Host-apply then ship the dirty rows — the legacy cycle."""
            bank = _mk_banks(n, r, s, jnp)
            host = {k: np.asarray(v).copy() for k, v in bank.items()}
            t0 = None
            for batch in batches:
                host_apply(host, batch)
                rows = np.unique(batch[0])
                idx = jnp.asarray(rows.astype(np.int32))
                updates = {k: np.ascontiguousarray(h[rows]) for k, h in host.items()}
                if t0 is None:
                    t0 = time.perf_counter()
                bank = scatter_patch(bank, idx, updates)  # chains on bank
            jax.block_until_ready(bank["requested"])
            return time.perf_counter() - t0, bank

        def run_fold():
            bank = _mk_banks(n, r, s, jnp)
            t0 = time.perf_counter()
            for batch in batches:
                bank = fold_patch(bank, *batch)  # chains on donated bank
            jax.block_until_ready(bank["requested"])
            return time.perf_counter() - t0, bank

        # parity: the two transports must land bit-identical banks
        _, bank_a = run_scatter()
        _, bank_b = run_fold()
        for k in bank_a:
            a, b = np.asarray(bank_a[k]), np.asarray(bank_b[k])
            assert np.array_equal(a, b.astype(a.dtype)), f"A/B diverge on {k}"

        ta, tb = [], []
        for _ in range(trials):  # interleaved: drift hits both alike
            ta.append(run_scatter()[0])
            tb.append(run_fold()[0])
        med_a = float(np.median(ta)) / chain
        med_b = float(np.median(tb)) / chain
        row = {
            "rows": rb,
            "scatter_ms": round(med_a * 1e3, 3),
            "fold_ms": round(med_b * 1e3, 3),
            "speedup": round(med_a / med_b, 2) if med_b > 0 else None,
            "scatter_bytes": int(sum(
                np.asarray(v).nbytes for v in _mk_banks(rb, r, s, np).values()
            )),
            "fold_bytes": int(sum(a.nbytes for a in batches[0])),
        }
        results.append(row)
        if not smoke:
            print(row, flush=True)
    return {"n_nodes": n, "sig_slots": s, "rows": results}


if __name__ == "__main__":
    import json

    out = main()
    print(json.dumps(out))
