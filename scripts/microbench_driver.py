#!/usr/bin/env python
"""Per-batch phase instrumentation of the real driver path on the TPU.
Runs bench config 1 shapes and prints per-batch deltas of every stat."""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_compilation_cache_dir", "/tmp/jax_compile_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)


from bench import CONFIGS, BATCH
from kubernetes_tpu.scheduler.driver import Binder, Scheduler
from kubernetes_tpu.state.cache import SchedulerCache
from kubernetes_tpu.state.queue import PriorityQueue

name, build = CONFIGS[os.environ.get("CFG", "1")]
nodes, pods = build()
cache = SchedulerCache()
for node in nodes:
    cache.add_node(node)
queue = PriorityQueue()
sched = Scheduler(cache=cache, queue=queue, binder=Binder(), batch_size=BATCH,
                  enable_preemption=False, deterministic=False, bind_workers=16)
sched.mirror.reserve(len(nodes), len(pods))
for p in pods:
    queue.add(p)

prev = dict(sched.stats)
while True:
    t0 = time.perf_counter()
    r = sched.schedule_batch()
    dt = time.perf_counter() - t0
    if (r.scheduled == 0 and r.unschedulable == 0 and r.errors == 0
            and getattr(r, "deferred", 0) == 0):
        break
    cur = dict(sched.stats)
    delta = {k: round(cur.get(k, 0) - prev.get(k, 0), 3) for k in cur}
    prev = cur
    print(f"batch {delta.get('batches')}: {dt:.3f}s sched={r.scheduled} "
          f"sync={delta.get('sync_s')} enc={delta.get('encode_s')} "
          f"patch={delta.get('patch_s')} disp={delta.get('dispatch_s')} "
          f"fetch={delta.get('fetch_s')} commit={delta.get('commit_s')} "
          f"specs={delta.get('batch_specs')} rebuilds={sched.mirror.rebuild_count}",
          flush=True)
sched.wait_for_binds()
