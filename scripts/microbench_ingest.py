#!/usr/bin/env python
"""A/B microbench: index-only dispatch vs host-built pod-array dispatch.

Measures the two pod-side transports for one solve dispatch's batch
construction (the ingest plane's tentpole claim):

  A (host-built) — the legacy per-batch path: `PodBatch.set_pod` per
    unique spec on the driver thread, then the whole padded array dict
    crosses the host→device wire (uploaded per dispatch).
  B (index)      — the ingest plane: rows staged ONCE into the resident
    bank (enqueue-time cost, off this measurement), per dispatch only an
    int32 index vector + two [U] bool control vectors ship and a jitted
    gather (ingest/gather.gather_stage) rebuilds the batch on device.

Timing discipline matches the other microbenches: trials interleave
A/B/A/B (drift hits both alike), each trial's device outputs are closed
with block_until_ready, and the reported numbers are per-dispatch host
wall + shipped bytes. The B path must be STRICTLY cheaper on both at
every bucket, with BIT-IDENTICAL device content (every array of the
gathered dict equals the host-built one, padding included) — asserted in
smoke mode, printed standalone.

Run: python scripts/microbench_ingest.py [u_real]
Smoke (tier-1, via tests/test_ingest_plane.py): main(smoke=True).
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if __name__ == "__main__":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np


def _mk_pods(n):
    """n distinct pod SPECS with realistic encode weight: labels,
    tolerations, node selectors, a spread/anti slice."""
    from kubernetes_tpu.api.types import (
        Affinity,
        LabelSelector,
        PodAffinityTerm,
        PodAntiAffinity,
        Toleration,
        TopologySpreadConstraint,
    )
    from kubernetes_tpu.models.generators import make_pod

    pods = []
    for i in range(n):
        p = make_pod(f"spec-{i}", cpu_milli=100 + i, labels={"app": f"a{i}"})
        p.tolerations = [Toleration(key="dedicated", operator="Equal",
                                    value="batch", effect="NoSchedule")]
        p.node_selector = {"instance-type": "small"}
        if i % 8 == 0:
            p.affinity = Affinity(pod_anti_affinity=PodAntiAffinity(required=[
                PodAffinityTerm(
                    label_selector=LabelSelector(match_labels={"app": p.labels["app"]}),
                    topology_key="kubernetes.io/hostname",
                )
            ]))
        elif i % 8 == 1:
            p.topology_spread_constraints = [TopologySpreadConstraint(
                max_skew=1, topology_key="zone",
                when_unsatisfiable="ScheduleAnyway",
                label_selector=LabelSelector(match_labels={"app": p.labels["app"]}),
            )]
        pods.append(p)
    return pods


def main(smoke: bool = False):
    import jax
    import jax.numpy as jnp

    from kubernetes_tpu.ingest import PodStage, StageBank
    from kubernetes_tpu.ingest.gather import gather_stage
    from kubernetes_tpu.state.tensors import PodBatch, Vocab, _bucket

    u_real = int(sys.argv[1]) if len(sys.argv) > 1 and not smoke else (
        24 if smoke else 256
    )
    trials = 3 if smoke else 10
    vocab = Vocab()
    pods = _mk_pods(u_real)
    u = _bucket(u_real)

    # B's one-time staging (enqueue-time in the real system): encode every
    # spec into the slab and upload the bank ONCE, before any trial
    stage = PodStage(vocab, capacity=max(256, u))
    bank = StageBank(stage)
    rows = []
    for p in pods:
        pair = stage.acquire(p)
        assert pair is not None
        rows.append(pair[0])
    bank_dev, empty_dev = bank.current_arrays()
    idx_host = np.zeros(u, np.int32)
    idx_host[:u_real] = rows

    def run_a():
        """Host-built: encode + upload the full padded dict."""
        batch = PodBatch(vocab, u)
        for i, p in enumerate(pods):
            batch.set_pod(i, p)
        host = batch.arrays()
        nbytes = sum(int(np.asarray(v).nbytes) for v in host.values())
        dev = {k: jnp.asarray(v) for k, v in host.items()}
        return dev, nbytes

    def run_b():
        """Index-only: ship idx + control vectors, gather on device."""
        idx = idx_host.copy()
        keep = np.zeros(u, bool)
        keep[:u_real] = True
        fb = np.zeros(u, bool)
        fb[:u_real] = stage.batch.fallback[np.asarray(rows, np.int64)]
        nbytes = idx.nbytes + keep.nbytes + fb.nbytes
        dev = gather_stage(bank_dev, idx, keep, empty_dev, fb)
        return dev, nbytes

    # warm both jit paths + pin bit-identity before timing
    dev_a, bytes_a = run_a()
    dev_b, bytes_b = run_b()
    jax.block_until_ready((dev_a, dev_b))
    mismatches = [
        k for k in dev_a
        if not np.array_equal(np.asarray(dev_a[k]), np.asarray(dev_b[k]))
    ]
    assert not mismatches, f"index dispatch diverged on: {mismatches}"

    t_a = t_b = 0.0
    for _ in range(trials):  # interleaved: drift hits both alike
        t0 = time.perf_counter()
        out, _ = run_a()
        jax.block_until_ready(out["req"])
        t_a += time.perf_counter() - t0
        t0 = time.perf_counter()
        out, _ = run_b()
        jax.block_until_ready(out["req"])
        t_b += time.perf_counter() - t0
    t_a /= trials
    t_b /= trials
    result = {
        "u_real": u_real,
        "u_bucket": u,
        "host_built_s": round(t_a, 6),
        "index_s": round(t_b, 6),
        "speedup": round(t_a / t_b, 2) if t_b > 0 else float("inf"),
        "host_built_bytes": bytes_a,
        "index_bytes": bytes_b,
        "bytes_ratio": round(bytes_a / bytes_b, 1),
        "bit_identical": True,
    }
    if smoke:
        assert t_b < t_a, (
            f"index dispatch not cheaper: {t_b:.6f}s vs {t_a:.6f}s"
        )
        assert bytes_b < bytes_a
    else:
        print(result)
    return result


if __name__ == "__main__":
    main()
