#!/usr/bin/env bash
# Pre-snapshot gate: NEVER commit a snapshot with red tests (round-2 VERDICT
# weak #1). Runs the full suite on the virtual 8-device CPU mesh, then the
# single-chip compile check and the multi-chip dryrun. Usage:
#   bash scripts/preflight.sh          # full gate
#   bash scripts/preflight.sh --fast   # tests only
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== preflight: ktpu-lint invariant gate (incl. lint-time budget) =="
# --time-budget: the repo-wide call-graph pass (KTPU006-008) must not
# silently make preflight crawl — ~12s today, 60s is the hard ceiling
# (exit 3). --json variants of this line feed dashboards/CI annotators.
python scripts/ktpu_lint.py --check --time-budget 60

if command -v ruff >/dev/null 2>&1; then
  echo "== preflight: ruff (pyflakes/unused-import/shadowing) =="
  ruff check kubernetes_tpu scripts tests bench.py __graft_entry__.py
else
  echo "== preflight: ruff not installed — skipping (config in pyproject.toml) =="
fi

echo "== preflight: full test suite (8-device CPU mesh) =="
python -m pytest tests/ -q

if [[ "${1:-}" != "--fast" ]]; then
  echo "== preflight: perf-budget regression gate (perf_gate --check) =="
  # same grow-only, justification-comment ratchet discipline as the
  # ktpu-lint baseline: deleted budget entries fail closed, measured
  # stage p99s must stay under the committed budgets (health-mode drain).
  # Deliberately a SECOND, standalone drain beyond the pytest health
  # test above: the gate must hold in a fresh process with nothing but
  # the committed budget, and the suite run has already warmed the XLA
  # disk cache so this leg is minutes, not the cold-compile cost.
  JAX_PLATFORMS=cpu python scripts/perf_gate.py --check

  echo "== preflight: __graft_entry__ compile check =="
  JAX_PLATFORMS=cpu python -c "
import __graft_entry__ as g
import jax
fn, args = g.entry()
jax.jit(fn).lower(*args).compile()
print('entry() compiles ok')
"
  echo "== preflight: dryrun_multichip(8) =="
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
    python -c "import __graft_entry__ as g; g.dryrun_multichip(8)"
fi
echo "== preflight: PASS =="
