"""Framework plugin wiring: custom plugins must actually change scheduling
decisions through the batch driver (VERDICT r1 weak #3 — the extension
points existed but were never invoked on the scheduling half of the
cycle)."""

import pytest

pytest.importorskip("jax")

from kubernetes_tpu.framework.interface import CycleState, Framework, Plugin, Status
from kubernetes_tpu.framework.plugins import (
    Handle,
    NodeName,
    PrioritySort,
    TaintToleration,
    new_default_registry,
    predicate_plugin,
    priority_plugin,
)
from kubernetes_tpu.models.generators import make_node, make_pod
from kubernetes_tpu.scheduler.driver import Binder, Scheduler
from kubernetes_tpu.state.cache import SchedulerCache
from kubernetes_tpu.state.queue import PodInfo, PriorityQueue


def _mk(nodes, plugins, **kw):
    cache = SchedulerCache()
    for n in nodes:
        cache.add_node(n)
    binds = []
    sched = Scheduler(
        cache=cache,
        queue=PriorityQueue(),
        binder=Binder(lambda pod, node: binds.append((pod.key(), node))),
        framework=Framework(plugins),
        deterministic=True,
        **kw,
    )
    return sched, binds


class OnlyNode(Plugin):
    """Filter plugin pinning every pod to one node."""

    name = "OnlyNode"

    def __init__(self, allowed):
        self.allowed = allowed

    def filter(self, state, pod, node_info):
        if node_info.node.name == self.allowed:
            return Status.success()
        return Status.unschedulable("not the chosen one")


class PreferNode(Plugin):
    """Score plugin heavily preferring one node."""

    name = "PreferNode"
    score_weight = 1

    def __init__(self, preferred):
        self.preferred = preferred

    def score(self, state, pod, node_name):
        return (1000 if node_name == self.preferred else 0), Status.success()


class RejectNamed(Plugin):
    name = "RejectNamed"

    def __init__(self, reject):
        self.reject = reject

    def pre_filter(self, state, pod):
        if pod.name == self.reject:
            return Status.unschedulable("rejected by prefilter")
        return Status.success()


def test_filter_plugin_changes_assignments():
    nodes = [make_node(f"n{i}", cpu_milli=4000, mem=8 * 2**30) for i in range(4)]
    sched, binds = _mk(nodes, [OnlyNode("n2")])
    for i in range(3):
        sched.queue.add(make_pod(f"p{i}", cpu_milli=100, mem=0))
    res = sched.schedule_batch()
    assert res.scheduled == 3
    assert set(res.assignments.values()) == {"n2"}


def test_filter_plugin_unschedulable_when_no_node_passes():
    nodes = [make_node(f"n{i}", cpu_milli=4000, mem=8 * 2**30) for i in range(2)]
    sched, _ = _mk(nodes, [OnlyNode("nope")], enable_preemption=False)
    sched.queue.add(make_pod("p0", cpu_milli=100, mem=0))
    res = sched.schedule_batch()
    assert res.scheduled == 0 and res.unschedulable == 1


def test_score_plugin_changes_selection():
    nodes = [make_node(f"n{i}", cpu_milli=4000, mem=8 * 2**30) for i in range(4)]
    sched, _ = _mk(nodes, [PreferNode("n3")])
    for i in range(3):
        sched.queue.add(make_pod(f"p{i}", cpu_milli=100, mem=0))
    res = sched.schedule_batch()
    assert res.scheduled == 3
    assert set(res.assignments.values()) == {"n3"}


def test_pre_filter_rejects_pod():
    nodes = [make_node("n0", cpu_milli=4000, mem=8 * 2**30)]
    sched, _ = _mk(nodes, [RejectNamed("bad")], enable_preemption=False)
    sched.queue.add(make_pod("good", cpu_milli=100, mem=0))
    sched.queue.add(make_pod("bad", cpu_milli=100, mem=0))
    res = sched.schedule_batch()
    assert res.scheduled == 1
    assert res.unschedulable == 1
    assert "default/good" in res.assignments


def test_queue_sort_plugin_overrides_pop_order():
    class ReversePriority(Plugin):
        name = "ReversePriority"

        def less(self, a, b):
            return a.pod.get_priority() < b.pod.get_priority()

    q = PriorityQueue()
    fw = Framework([ReversePriority()])
    # wiring happens in Scheduler.__init__
    cache = SchedulerCache()
    cache.add_node(make_node("n0", cpu_milli=1000, mem=2**30))
    sched = Scheduler(cache=cache, queue=q, framework=fw, deterministic=True)
    lo, hi = make_pod("lo", cpu_milli=100, mem=0), make_pod("hi", cpu_milli=100, mem=0)
    lo.priority, hi.priority = 0, 100
    q.add(hi)
    q.add(lo)
    popped = q.pop_batch(2)
    assert [i.pod.name for i in popped] == ["lo", "hi"]  # reversed order


def test_queue_sort_governs_in_batch_contention():
    """The comparator's order must decide who wins scarce capacity WITHIN a
    batch (device residual order + host commit order), not just pop order."""

    class ReversePriority(Plugin):
        name = "ReversePriority"

        def less(self, a, b):
            return a.pod.get_priority() < b.pod.get_priority()

    cache = SchedulerCache()
    cache.add_node(make_node("n0", cpu_milli=1000, mem=2**30))
    sched = Scheduler(
        cache=cache, queue=PriorityQueue(), framework=Framework([ReversePriority()]),
        deterministic=True, enable_preemption=False,
    )
    lo, hi = make_pod("lo", cpu_milli=800, mem=0), make_pod("hi", cpu_milli=800, mem=0)
    lo.priority, hi.priority = 0, 100
    sched.queue.add(hi)
    sched.queue.add(lo)
    res = sched.schedule_batch()
    # under the reversed comparator the LOW-priority pod is first in line
    assert res.assignments.get("default/lo") == "n0"
    assert "default/hi" not in res.assignments


def test_builtin_plugins_and_registry():
    reg = new_default_registry(Handle(lambda: None))
    assert set(reg.names()) == {"PrioritySort", "NodeName", "TaintToleration", "VolumeBinding"}
    nn = reg.make("NodeName")
    node = make_node("n0", cpu_milli=1000, mem=2**30)
    cache = SchedulerCache()
    cache.add_node(node)
    ni = cache.snapshot.get("n0")
    pinned = make_pod("p", cpu_milli=0, mem=0)
    pinned.node_name = ""
    st = nn.filter(CycleState(), pinned, ni)
    assert st.is_success()

    ps = reg.make("PrioritySort")
    a = PodInfo(pod=make_pod("a", cpu_milli=0, mem=0), seq=1)
    b = PodInfo(pod=make_pod("b", cpu_milli=0, mem=0), seq=2)
    a.pod.priority, b.pod.priority = 5, 1
    assert ps.less(a, b) is True


def test_migration_shims():
    from kubernetes_tpu.oracle import predicates as opred
    from kubernetes_tpu.oracle import priorities as opri

    nodes = [make_node(f"n{i}", cpu_milli=4000, mem=8 * 2**30) for i in range(3)]
    cache = SchedulerCache()
    for n in nodes:
        cache.add_node(n)
    handle = Handle(lambda: cache.snapshot)
    shim_f = predicate_plugin("ShimFit", opred.pod_fits_resources)
    shim_s = priority_plugin("ShimLeast", opri.least_requested_priority, handle, weight=2)
    st = shim_f.filter(CycleState(), make_pod("p", cpu_milli=100, mem=0), cache.snapshot.get("n0"))
    assert st.is_success()
    sc, st = shim_s.score(CycleState(), make_pod("p", cpu_milli=100, mem=0), "n0")
    assert st.is_success() and isinstance(sc, int)
