"""Term-bank plane parity suite (kubernetes_tpu/terms_plane + the
driver's index-only term dispatch).

The tentpole's correctness pin: a drain with the term plane ON must
schedule pod-for-pod identically to plane OFF (the plane is transport,
never policy) across mixed/anti/spread/gang/preemption drains, while
covering every quiet dispatch with the index path. Plus the staleness
contract — update + delete between enqueue and pop re-stage or fall back
(counted), slab overflow grows pow-2 leaving outstanding pairs
verifiably stale — the term-slab refcount lifecycle (the ingest slab
suite's mirror), the overflow_owners → scalar-oracle routing regression,
and the interleaved A/B microbench smoke.
"""

import os
import sys
import time

import pytest

pytest.importorskip("jax")

from kubernetes_tpu.api.types import (
    Affinity,
    LabelSelector,
    PodAffinity,
    PodAffinityTerm,
    PodAntiAffinity,
    TopologySpreadConstraint,
    WeightedPodAffinityTerm,
)
from kubernetes_tpu.models.generators import make_node, make_pod
from kubernetes_tpu.scheduler.driver import Binder, POD_GROUP_LABEL, Scheduler
from kubernetes_tpu.state.cache import SchedulerCache
from kubernetes_tpu.state.queue import PriorityQueue
from kubernetes_tpu.state.tensors import Vocab

HOST = "kubernetes.io/hostname"
ZONE = "zone"


def _nodes(n, zones=0, cpu=4000):
    out = []
    for i in range(n):
        labels = {HOST: f"n{i}"}
        if zones:
            labels[ZONE] = f"z{i % zones}"
        out.append(make_node(f"n{i}", cpu_milli=cpu, labels=labels))
    return out


def _anti_pod(name, app, cpu=100):
    p = make_pod(name, cpu_milli=cpu, labels={"app": app})
    p.affinity = Affinity(pod_anti_affinity=PodAntiAffinity(required=[
        PodAffinityTerm(
            label_selector=LabelSelector(match_labels={"app": app}),
            topology_key=HOST,
        )
    ]))
    return p


def _spread_pod(name, app, cpu=50):
    p = make_pod(name, cpu_milli=cpu, labels={"app": app})
    p.topology_spread_constraints = [TopologySpreadConstraint(
        max_skew=1,
        topology_key=ZONE,
        when_unsatisfiable="DoNotSchedule",
        label_selector=LabelSelector(match_labels={"app": app}),
    )]
    return p


def _pref_pod(name, app, cpu=50):
    p = make_pod(name, cpu_milli=cpu, labels={"app": app})
    p.affinity = Affinity(pod_affinity=PodAffinity(preferred=[
        WeightedPodAffinityTerm(weight=3, pod_affinity_term=PodAffinityTerm(
            label_selector=LabelSelector(match_labels={"app": app}),
            topology_key=ZONE,
        ))
    ]))
    return p


def _mk_sched(nodes, existing=(), **kw):
    cache = SchedulerCache()
    for n in nodes:
        cache.add_node(n)
    for p in existing:
        cache.add_pod(p)
    kw.setdefault("deterministic", True)
    return Scheduler(
        cache=cache, queue=PriorityQueue(),
        binder=Binder(lambda pod, node: None), **kw
    )


def _drain(sched, rounds=60):
    total, assignments = 0, {}
    for _ in range(rounds):
        r = sched.schedule_batch()
        total += r.scheduled
        assignments.update(r.assignments)
        if (r.scheduled == 0 and r.unschedulable == 0 and r.errors == 0
                and r.deferred == 0):
            active, backoff, unsched = sched.queue.counts()
            if not (active + backoff + unsched):
                break
            time.sleep(0.06)
            sched.queue.move_all_to_active()
    sched.wait_for_binds()
    return total, assignments


# ---------------------------------------------------------------------------
# plane ON == OFF pod-for-pod
# ---------------------------------------------------------------------------

def _enqueue_scenario(sched, scenario):
    q = sched.queue
    if scenario == "mixed":
        import random

        rng = random.Random(0)
        for i in range(24):
            roll = rng.random()
            if roll < 0.2:
                q.add(_anti_pod(f"a{i}", app=f"g{rng.randrange(3)}"))
            elif roll < 0.4:
                q.add(_spread_pod(f"s{i}", app=f"sp{rng.randrange(2)}"))
            elif roll < 0.55:
                q.add(_pref_pod(f"w{i}", app=f"pp{rng.randrange(2)}"))
            else:
                q.add(make_pod(f"p{i}", cpu_milli=100 + 10 * (i % 3)))
    elif scenario == "anti":
        for i in range(12):
            q.add(_anti_pod(f"a{i}", app=f"g{i % 4}"))
    elif scenario == "spread":
        for i in range(12):
            q.add(_spread_pod(f"s{i}", app=f"sp{i % 2}"))
    elif scenario == "gang":
        for g in range(2):
            for m in range(6):
                q.add(make_pod(
                    f"g{g}m{m}", cpu_milli=100,
                    labels={POD_GROUP_LABEL: f"gang-{g}"},
                ))
        for i in range(6):
            q.add(_anti_pod(f"a{i}", app=f"g{i % 2}"))
    else:
        raise AssertionError(scenario)


@pytest.mark.parametrize("scenario", ["mixed", "anti", "spread", "gang"])
def test_drain_parity_plane_on_vs_off(scenario):
    results = {}
    for terms in (True, False):
        sched = _mk_sched(
            _nodes(6, zones=3), enable_preemption=False, batch_size=8,
            term_plane=terms,
        )
        _enqueue_scenario(sched, scenario)
        sched.warmup()
        n, assigns = _drain(sched)
        results[terms] = (n, assigns)
        if terms:
            assert sched.stats.get("term_index_batches", 0) > 0, sched.stats
            assert sched.stats.get("term_legacy_batches", 0) == 0, sched.stats
        sched.close()
    assert results[True] == results[False]


def test_preemption_drain_parity_plane_on_vs_off():
    results = {}
    for terms in (True, False):
        nodes = _nodes(3, cpu=1000)
        existing = []
        for i, nd in enumerate(nodes):
            v = make_pod(f"victim{i}", cpu_milli=900, node_name=nd.name)
            v.priority = 0
            existing.append(v)
        sched = _mk_sched(
            nodes, existing=existing, enable_preemption=True, batch_size=8,
            term_plane=terms,
        )
        for i in range(3):
            p = _anti_pod(f"hi{i}", app="hi", cpu=800)
            p.priority = 1000
            sched.queue.add(p)
        sched.warmup()
        n, assigns = _drain(sched)
        results[terms] = (n, assigns)
        sched.close()
    assert results[True][0] == 3
    assert results[True] == results[False]


def test_node_churn_drain_parity_plane_on_vs_off():
    """Node add/remove mid-drain: node-side row remaps and bank rebuilds
    must not perturb the term plane (and vice versa)."""
    results = {}
    for terms in (True, False):
        sched = _mk_sched(
            _nodes(4, zones=2), enable_preemption=False, batch_size=8,
            term_plane=terms,
        )
        for i in range(8):
            sched.queue.add(_spread_pod(f"s{i}", app=f"sp{i % 2}"))
        sched.warmup()
        r1 = sched.schedule_batch()
        sched.cache.remove_node("n3")
        sched.cache.add_node(make_node(
            "n9", cpu_milli=4000, labels={HOST: "n9", ZONE: "z1"}
        ))
        for i in range(8, 16):
            sched.queue.add(_anti_pod(f"a{i}", app=f"g{i % 4}"))
        n, assigns = _drain(sched)
        results[terms] = (r1.scheduled + n, sorted(assigns))
        sched.close()
    assert results[True] == results[False]


# ---------------------------------------------------------------------------
# staleness: update + delete between enqueue and pop
# ---------------------------------------------------------------------------

def test_update_between_enqueue_and_pop_uses_new_terms():
    """An update that changes the pod's TERMS must be what the solve sees
    — the stale interned entry (old terms) is invalidated and the entry
    re-interns on the informer path."""
    sched = _mk_sched(_nodes(4), enable_preemption=False, batch_size=8)
    q = sched.queue
    # required affinity to a label NO existing pod carries, and the pod
    # does not match its own term → infeasible everywhere
    blocked = make_pod("u0", cpu_milli=100, labels={"app": "u"})
    blocked.affinity = Affinity(pod_affinity=PodAffinity(required=[
        PodAffinityTerm(
            label_selector=LabelSelector(match_labels={"anchor": "nowhere"}),
            topology_key=HOST,
        )
    ]))
    q.add(blocked)
    fixed = make_pod("u0", cpu_milli=100, labels={"app": "u"})  # terms gone
    q.update(blocked, fixed)
    sched.warmup()
    n, assigns = _drain(sched)
    assert n == 1 and "default/u0" in assigns
    sched.close()


def test_delete_between_pop_and_dispatch_counts_stale_and_restages():
    """queue.delete releases the entry's interned terms; a popped copy
    still in flight sees the generation mismatch, counts the staleness,
    re-interns from the captured pod object — the dispatch stays covered
    and the placement is unaffected."""
    sched = _mk_sched(_nodes(4), enable_preemption=False, batch_size=8)
    q = sched.queue
    lone = _anti_pod("lone", app="only")
    q.add(lone)
    sched.warmup()
    infos = q.pop_batch(8)
    assert len(infos) == 1 and infos[0].term_row >= 0
    eid, gen = infos[0].term_row, infos[0].term_gen
    q.delete(lone)  # last holder: the entry frees
    assert not sched.tstage.valid_pair(eid, gen)
    out = sched._device_solve(infos)
    assert int(out.assign[0]) >= 0
    assert sched.stats.get("term_stale_rows", 0) >= 1
    assert sched.stats.get("term_restaged", 0) >= 1
    assert sched.stats.get("term_index_batches", 0) >= 1  # still covered
    sched.close()


# ---------------------------------------------------------------------------
# term-slab refcount lifecycle (the ingest slab suite's mirror)
# ---------------------------------------------------------------------------

def test_slab_acquire_release_refcount_lifecycle():
    from kubernetes_tpu.terms_plane import TermStage

    st = TermStage(Vocab())
    p = _anti_pod("r0", app="x")
    pair = st.acquire(p)
    assert pair is not None
    eid, gen = pair
    e = st._entries[eid]
    assert e.refs == 1 and len(e.rows) == 1 and e.has_anti
    # replica of the same spec: intern HIT on the same entry, +1 ref
    p2 = _anti_pod("r1", app="x")
    assert st.acquire(p2) == pair and e.refs == 2
    free_before = len(st._free)
    st.release(eid, gen)
    assert e.refs == 1 and st.valid_pair(eid, gen)
    st.release(eid, gen)  # last holder: rows free, entry gone
    assert not st.valid_pair(eid, gen)
    assert len(st._free) == free_before + 1
    # stale release is a no-op
    st.release(eid, gen)
    # re-acquire re-encodes into a FRESH entry (new id, new gen)
    pair2 = st.acquire(_anti_pod("r2", app="x"))
    assert pair2 is not None and pair2 != pair


def test_queue_requeue_and_unschedulable_keep_one_reference():
    """add → pop → requeue / add_unschedulable round-trips must neither
    leak references nor drop the entry."""
    sched = _mk_sched(_nodes(2), enable_preemption=False, batch_size=8)
    q = sched.queue
    q.add(_anti_pod("rq", app="rq"))
    info = q.pop_batch(1)[0]
    eid, gen = info.term_row, info.term_gen
    entry = sched.tstage._entries[eid]
    assert entry.refs == 1
    q.requeue([info])
    assert (info.term_row, info.term_gen) == (eid, gen) and entry.refs == 1
    info = q.pop_batch(1)[0]
    q.add_unschedulable(info)
    assert (info.term_row, info.term_gen) == (eid, gen) and entry.refs == 1
    q.delete(info.pod)
    assert not sched.tstage.valid_pair(eid, gen)
    sched.close()


def test_mid_queue_label_update_bumps_generation():
    """A label update changes spread self-match (labels are in the intern
    key): the update must land a DIFFERENT entry and free the old one —
    the staleness tag for any popped copy."""
    sched = _mk_sched(_nodes(4, zones=2), enable_preemption=False,
                      batch_size=8)
    q = sched.queue
    old = _spread_pod("lu", app="a")
    q.add(old)
    info = next(i for i in q.pending_infos() if i.pod.key() == "default/lu")
    eid, gen = info.term_row, info.term_gen
    assert eid >= 0
    new = _spread_pod("lu", app="b")  # selector + labels change
    q.update(old, new)
    assert (info.term_row, info.term_gen) != (eid, gen)
    assert not sched.tstage.valid_pair(eid, gen)
    assert sched.tstage.valid_pair(info.term_row, info.term_gen)
    sched.close()


def test_slab_overflow_grows_pow2_and_invalidates(monkeypatch):
    from kubernetes_tpu.terms_plane import stage as stage_mod

    monkeypatch.setattr(stage_mod, "MIN_CAPACITY", 4)
    st = stage_mod.TermStage(Vocab(), capacity=4)
    pairs = [st.acquire(_anti_pod(f"o{i}", app=f"g{i}")) for i in range(4)]
    assert all(p is not None for p in pairs)
    # 5th distinct term set: slab full → grows to the next pow-2 rung,
    # every outstanding pair goes verifiably stale
    p5 = st.acquire(_anti_pod("o4", app="g4"))
    assert p5 is not None and st.capacity == 8
    assert st.stats["overflows"] == 1 and st.stats["rebuilds"] == 1
    assert all(not st.valid_pair(e, g) for e, g in pairs)
    assert st.valid_pair(*p5)


def test_slab_ceiling_falls_back_to_legacy_dispatch(monkeypatch):
    """When a rep's terms cannot be staged at all, the batch compiles the
    legacy host TermBank — counted, never wrong."""
    sched = _mk_sched(_nodes(4), enable_preemption=False, batch_size=8)
    for i in range(6):
        sched.queue.add(_anti_pod(f"p{i}", app=f"g{i % 2}"))
    sched.warmup()
    monkeypatch.setattr(
        sched.tstage, "ensure_entry",
        lambda pod, selectors=None: None,
    )
    for info in sched.queue.pending_infos():
        info.term_row = -1
    n, _ = _drain(sched)
    assert n == 6
    assert sched.stats.get("term_legacy_batches", 0) >= 1, sched.stats
    assert sched.stats.get("term_stale_rows", 0) >= 1
    sched.close()


def test_prologue_bails_when_slab_rebuilds_mid_resolve(monkeypatch):
    """A slab rebuild DURING entry resolution (a restage growing a full
    slab) invalidates the rows already collected — the prologue must
    detect the generation change and fall back to the legacy path rather
    than gather garbage rows from the rebuilt slab."""
    sched = _mk_sched(_nodes(4), enable_preemption=False, batch_size=8)
    for i in range(4):
        sched.queue.add(_anti_pod(f"p{i}", app=f"g{i}"))
    sched.warmup()
    infos = sched.queue.pop_batch(8)
    assert len(infos) == 4
    infos[-1].term_row = -1  # one stale rep, resolved AFTER the others
    real_ensure = sched.tstage.ensure_entry

    def growing_ensure(pod, selectors=None):
        sched.tstage._rebuild(sched.tstage.capacity * 2)
        return real_ensure(pod, selectors)

    monkeypatch.setattr(sched.tstage, "ensure_entry", growing_ensure)
    reps = [pi.pod for pi in infos]
    keys = [pi.pod.__dict__.get("_spec_key_memo") for pi in infos]
    assert sched._term_prologue(reps, infos, keys, None) is None
    # self-heal: the next dispatch re-interns into the new slab
    monkeypatch.setattr(sched.tstage, "ensure_entry", real_ensure)
    out = sched._device_solve(infos)
    assert all(int(a) >= 0 for a in out.assign[: len(infos)])
    sched.close()


# ---------------------------------------------------------------------------
# overflow_owners → scalar-oracle routing (satellite regression)
# ---------------------------------------------------------------------------

def _overflowing_pod(name):
    """ml_cap (4) + 1 matchLabels pairs: the compiled selector truncates,
    so the device row under-matches — the pod MUST route through the
    scalar oracle (TermBank.overflow_owners / TermEntry.overflow)."""
    p = make_pod(name, cpu_milli=100, labels={f"k{j}": "v" for j in range(5)})
    p.affinity = Affinity(pod_anti_affinity=PodAntiAffinity(required=[
        PodAffinityTerm(
            label_selector=LabelSelector(
                match_labels={f"k{j}": "v" for j in range(5)}
            ),
            topology_key=HOST,
        )
    ]))
    return p


@pytest.mark.parametrize("terms", [True, False])
def test_overflowing_terms_pod_reaches_scalar_oracle(terms):
    """Regression for the overflow routing on BOTH transports: the
    covered path patches only the host fallback vector — the pod must
    still reach the oracle (fallback=True in SolveOutput) and schedule
    correctly."""
    sched = _mk_sched(_nodes(4), enable_preemption=False, batch_size=8,
                      term_plane=terms)
    sched.queue.add(_overflowing_pod("ov0"))
    sched.warmup()
    infos = sched.queue.pop_batch(8)
    out = sched._device_solve(infos)
    assert bool(out.fallback[0]), (
        "overflowing-terms pod did not route to the scalar oracle "
        f"(term_plane={terms})"
    )
    if terms:
        assert sched.stats.get("term_index_batches", 0) >= 1
    # and the full drain still places it through the scalar oracle — a
    # device pick with fallback set escalates to the FULL oracle
    # recheck; a -1 would make the oracle place it outright
    sched.queue.requeue(infos)
    n, assigns = _drain(sched)
    assert n == 1 and "default/ov0" in assigns
    assert (
        sched.stats.get("oracle_rechecks", 0) >= 1
        or sched.stats.get("oracle_places", 0) >= 1
    ), sched.stats
    sched.close()


# ---------------------------------------------------------------------------
# kill switch + wire accounting + microbench smoke
# ---------------------------------------------------------------------------

def test_env_kill_switch(monkeypatch):
    monkeypatch.setenv("KTPU_TERM_PLANE", "0")
    sched = _mk_sched(_nodes(2), enable_preemption=False, batch_size=4)
    assert sched.tstage is None and sched.term_bank is None
    for i in range(2):
        sched.queue.add(_anti_pod(f"k{i}", app="k"))
    sched.warmup()
    n, _ = _drain(sched)
    assert n == 2
    assert sched.stats.get("term_index_batches", 0) == 0
    sched.close()


def test_terms_ledger_index_vs_legacy_bytes():
    """patch_bytes.terms: the covered path ships KB-scale index/owner
    vectors where the legacy path ships the full padded term table —
    both measured on the SAME ledger kind so the claim is a byte count."""
    sizes = {}
    for terms in (True, False):
        sched = _mk_sched(_nodes(4, zones=2), enable_preemption=False,
                          batch_size=16, term_plane=terms)
        for i in range(32):
            sched.queue.add(_anti_pod(f"p{i}", app=f"a{i % 8}"))
        sched.warmup()
        before = sched.mirror.bytes_shipped.get("terms", 0)
        n, _ = _drain(sched)
        assert n == 32
        sizes[terms] = sched.mirror.bytes_shipped.get("terms", 0) - before
        sched.close()
    assert sizes[True] * 4 < sizes[False], sizes


def test_microbench_terms_smoke():
    scripts = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "scripts"
    )
    if scripts not in sys.path:
        sys.path.insert(0, scripts)
    import microbench_terms

    result = microbench_terms.main(smoke=True)
    assert result["bit_identical"]
    assert result["index_s"] < result["host_built_s"]
    assert result["index_bytes"] < result["host_built_bytes"]


def test_background_uploader_drains_dirty_term_rows():
    """Entries interned while the drain runs are shipped by the
    off-thread terms-upload worker — the driver's dispatch should not
    have to flush them synchronously every batch."""
    sched = _mk_sched(_nodes(4), enable_preemption=False, batch_size=8)
    for i in range(8):
        sched.queue.add(_anti_pod(f"p{i}", app=f"g{i % 2}"))
    sched.warmup()  # arms the uploader + full-uploads the backlog
    for i in range(8, 16):
        sched.queue.add(_anti_pod(f"q{i}", app=f"h{i}"))
    deadline = time.time() + 5
    while sched.tstage.dirty_rows and time.time() < deadline:
        time.sleep(0.02)
    assert not sched.tstage.dirty_rows, "terms uploader never drained"
    assert sched.term_bank.stats["flush_rows"] > 0
    n, _ = _drain(sched)
    assert n == 16
    sched.close()
