"""Crash-restart plane suite (kubernetes_tpu/restart): the kill-point ×
workload chaos matrix, the mid-drain double restart, cold-start
reconciliation units, bind idempotency, the nomination wire round-trip,
and graceful-shutdown hardening.

Every matrix cell drives ONE persistent FakeAPIServer through a
supervised drain with a deterministic `crash:<site>[@n]` kill-point:
the instance dies at the named pipeline stage, the supervisor buries
it, builds a fresh scheduler, cold-start-reconciles from the relist,
and resumes — asserting zero lost pods, zero double-bound pods, no
node over-commit, a clean shadow audit on the survivor, and
misses_after_warmup == 0 on the restarted incarnation (the persistent
compile ladder makes the re-warm trace-only).
"""

import threading
import time

import pytest

pytest.importorskip("jax")

from kubernetes_tpu.api.types import (
    Affinity,
    LabelSelector,
    PodAffinityTerm,
    PodAntiAffinity,
    TopologySpreadConstraint,
)
from kubernetes_tpu.apiserver.store import ConflictError, FakeAPIServer
from kubernetes_tpu.client.informer import APIBinder, BindMismatchError
from kubernetes_tpu.metrics import metrics as M
from kubernetes_tpu.models.generators import make_node, make_pod
from kubernetes_tpu.restart import (
    Supervisor,
    check_invariants,
    cold_start,
    make_scheduler_factory,
)
from kubernetes_tpu.faults.inject import FaultPlan, SimulatedCrash
from kubernetes_tpu.scheduler.driver import (
    POD_GROUP_LABEL,
    POD_GROUP_MIN_AVAILABLE,
    Binder,
    Scheduler,
)
from kubernetes_tpu.state.cache import SchedulerCache
from kubernetes_tpu.state.queue import PriorityQueue

N_NODES = 4
NODE_CPU = 4000  # milli

#: the six kill-points, each pinned at a call index that lands mid-drain
#: (batch 1 commits/binds/preempts, batch 2 solves after the injected
#: late arrivals) — the same spec is deterministic across runs and
#: workloads by the FaultPlan counted-trigger contract
KILL_POINTS = (
    "crash:post-solve@2",
    "crash:mid-apply@1",
    "crash:mid-bind-chunk@2",
    "crash:post-bind@2",
    "crash:mid-preemption@1",
    "crash:mid-uploader-flush@1",
)

WORKLOADS = ("mixed", "anti", "gang", "preemption")

#: scheduler shape shared by every cell so the whole matrix rides one
#: set of XLA programs (jit caches are process-wide)
CELL_KWARGS = dict(batch_size=16, enable_preemption=True, speculate=False)


def build_cluster(api):
    for i in range(N_NODES):
        api.create("nodes", make_node(
            f"n{i}", cpu_milli=NODE_CPU, mem=32 * 2**30,
            labels={"kubernetes.io/hostname": f"n{i}",
                    "zone": "za" if i % 2 else "zb"},
        ))


def build_workload(api, kind: str, salt: str):
    """Create the cell's UPFRONT pods. Every workload shares the same
    skeleton so every kill-point can fire in every cell: bound
    low-priority victims (one per node), plain pods (batch 1 is a lean
    bulk commit → mid-apply/mid-bind-chunk/post-bind), a high-priority
    preemptor that only fits by eviction (→ mid-preemption), and
    workload-specific term-carrying pods. Returns (created_keys,
    evictable_keys, late_pods) — late_pods are injected after batch 1
    (→ post-solve@2 lands on a real second batch, and their admission
    dirties the staged slabs → mid-uploader-flush)."""
    created, evict = [], []

    def create(p):
        created.append(p.key())
        api.create("pods", p)

    for i in range(N_NODES):  # bound victims: 3000m of each node's 4000m
        v = make_pod(f"v{salt}-{i}", cpu_milli=3000, mem=2**20,
                     labels={"app": f"victim-{salt}"}, node_name=f"n{i}")
        v.priority = 0
        create(v)
        evict.append(v.key())
    for i in range(N_NODES):  # plains: 600m into each node's 1000m gap
        create(make_pod(f"pl{salt}-{i}", cpu_milli=600, mem=2**20))
    if kind in ("mixed", "anti"):
        n_anti = 2 if kind == "mixed" else 4
        for i in range(n_anti):  # required self-anti: one per node
            create(make_pod(
                f"an{salt}-{i}", cpu_milli=200, mem=2**20,
                labels={"app": f"anti-{salt}"},
                affinity=Affinity(pod_anti_affinity=PodAntiAffinity(required=[
                    PodAffinityTerm(
                        label_selector=LabelSelector(
                            match_labels={"app": f"anti-{salt}"}),
                        topology_key="kubernetes.io/hostname",
                    )])),
            ))
    if kind == "mixed":
        for i in range(2):  # DoNotSchedule zone spread
            create(make_pod(
                f"sp{salt}-{i}", cpu_milli=100, mem=2**20,
                labels={"app": f"spread-{salt}"},
                topology_spread_constraints=[TopologySpreadConstraint(
                    max_skew=1, topology_key="zone",
                    when_unsatisfiable="DoNotSchedule",
                    label_selector=LabelSelector(
                        match_labels={"app": f"spread-{salt}"}),
                )],
            ))
    hi = make_pod(f"hi{salt}", cpu_milli=1500, mem=2**20,
                  labels={"app": f"hi-{salt}"})
    hi.priority = 1000
    create(hi)

    late = [make_pod(f"lt{salt}-{i}", cpu_milli=100, mem=2**20)
            for i in range(2)]
    if kind == "gang":  # the gang arrives late so batch 1 stays lean
        for i in range(4):
            late.append(make_pod(
                f"gg{salt}-{i}", cpu_milli=100, mem=2**20,
                labels={POD_GROUP_LABEL: f"gang-{salt}",
                        POD_GROUP_MIN_AVAILABLE: "4"},
            ))
    created.extend(p.key() for p in late)
    return created, evict, late


def run_matrix_cell(kill_spec: str, kind: str, cache_dir: str, salt: str,
                    budget_s: float = 60.0):
    """One supervised chaos cell; returns (report, problems)."""
    api = FakeAPIServer()
    build_cluster(api)
    created, evict, late = build_workload(api, kind, salt)
    mm0 = M.bind_conflicts.value("mismatch")

    injected = [False]

    def inject_late():
        injected[0] = True
        for p in late:
            api.create("pods", p)

    def on_tick(sup, inc):
        # inject the late arrivals once the drain is underway (after
        # batch 1) so a second batch, and fresh slab dirt, always exist
        if not injected[0] and inc.sched.stats.get("batches", 0) >= 1:
            inject_late()

    def on_restart(sup):
        # a crash that fired before the live injection window means the
        # late traffic "arrived while the process was down": it lands in
        # the store BEFORE the successor cold-starts, so the relist (and
        # the warmup census over the relisted queue — solve_gang etc.
        # must warm from what is actually pending) sees it. A mid-drain
        # NEW-kind arrival is an ordinary live-process miss, orthogonal
        # to what this matrix pins.
        if not injected[0]:
            inject_late()

    plan = FaultPlan.parse(kill_spec)
    ref = {}
    factory = make_scheduler_factory(
        ref, api, compile_cache_dir=cache_dir,
        scheduler_kwargs=dict(CELL_KWARGS),
    )
    sup = Supervisor(api, plan, factory)
    sup.on_tick = on_tick
    sup.on_restart = on_restart
    ref["sup"] = sup
    rep = sup.run(budget_s=budget_s)
    problems = list(rep.problems)
    if not rep.completed:
        problems.append("drain never completed")
    if rep.crashes < kill_spec.count("crash:"):
        problems.append(
            f"expected {kill_spec.count('crash:')} kill(s), saw "
            f"{rep.crashes} — the kill-point never fired"
        )
    surv = rep.final.sched
    problems += check_invariants(
        api, created, evictable_keys=evict, sched=surv,
        mismatch_conflicts=M.bind_conflicts.value("mismatch") - mm0,
    )
    # the RESTARTED incarnation re-warmed trace-only from the persistent
    # ladder: zero compile misses after its warmup
    if surv.compile_plan.stats["misses_after_warmup"]:
        problems.append(
            f"misses_after_warmup="
            f"{surv.compile_plan.stats['misses_after_warmup']} on the "
            "restarted incarnation"
        )
    if rep.final.report is None or not rep.final.report.phases_s.get("warmup"):
        problems.append("survivor carries no phase-timed reconcile report")
    # teardown (harness hygiene, not part of the contract under test)
    for inc in rep.incarnations:
        for inf in inc.informers.values():
            inf.stop()
    surv.close()
    return rep, problems


@pytest.mark.parametrize("kind", WORKLOADS)
def test_restart_matrix(kind, tmp_path):
    """The kill-point × workload grid: every kill-point fires against
    every workload; every cell restarts, reconciles, and completes with
    the full invariant set green. Each cell gets its OWN persistent
    ladder dir — the restarted incarnation loads exactly what its dead
    predecessor persisted (a shared dir would also re-trace every other
    cell's specs at each warmup, O(cells × specs) setup for nothing)."""
    failures = []
    for k, kill in enumerate(KILL_POINTS):
        rep, problems = run_matrix_cell(
            kill, kind, str(tmp_path / f"ladder-{k}"), salt=f"{kind[:2]}{k}"
        )
        if problems:
            failures.append(f"[{kind} × {kill}] {'; '.join(problems)}")
    assert not failures, "\n".join(failures)


def test_restart_double_kill_mid_drain(tmp_path):
    """A drain that dies TWICE — mid-bind-chunk, then post-solve on the
    restarted incarnation — must still converge with the invariants
    green (the reconcile path is idempotent under repetition)."""
    rep, problems = run_matrix_cell(
        "crash:mid-bind-chunk@2;crash:post-solve@3", "mixed",
        str(tmp_path / "ladder"), salt="dbl",
    )
    assert rep.crashes == 2, (rep.crashes, rep.problems)
    assert len(rep.incarnations) == 3
    assert not problems, "\n".join(problems)


# ---------------------------------------------------------------------------
# cold-start reconciliation units
# ---------------------------------------------------------------------------

def test_cold_start_rebuilds_cache_queue_and_report():
    api = FakeAPIServer()
    build_cluster(api)
    bound = make_pod("b0", cpu_milli=500, mem=2**20, node_name="n1")
    api.create("pods", bound)
    for i in range(3):
        api.create("pods", make_pod(f"q{i}", cpu_milli=100, mem=2**20))
    foreign = make_pod("f0", cpu_milli=100, mem=2**20)
    foreign.scheduler_name = "other-scheduler"
    api.create("pods", foreign)

    sched = Scheduler(cache=SchedulerCache(), queue=PriorityQueue(),
                      **CELL_KWARGS)
    try:
        report = cold_start(sched, api)
        assert report.nodes == N_NODES
        assert report.bound == 1
        assert report.pending == 3  # the foreign-scheduler pod is NOT ours
        assert sched.cache.pod_count() == 1
        assert not sched.cache.is_assumed("default/b0")  # confirmed, not assumed
        assert sched.queue.pending_count() == 3
        assert set(report.phases_s) >= {
            "relist", "nodes", "assume", "queue", "nominations",
            "informers", "banks", "warmup",
        }
        assert sched.restart_report["bound"] == 1
        # the report reaches the census (schema v3) and ktpu_top renders it
        from kubernetes_tpu.obs.introspect import census, validate_census

        doc = census(sched)
        assert validate_census(doc) == []
        assert doc["planes"]["restart"]["reconciled"] is True
        import os
        import sys

        scripts = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "scripts")
        if scripts not in sys.path:
            sys.path.insert(0, scripts)
        import ktpu_top

        out = ktpu_top.render_census(doc)
        assert "restart" in out and "reconciled" in out
    finally:
        for inf in getattr(sched, "restart_informers", {}).values():
            inf.stop()
        sched.close()


def test_cold_start_reconstructs_nomination_overlay():
    """The nominated-node wire round-trip: a preemption nomination
    persisted via update_pod_status survives a relist — the fresh
    queue's overlay matches the wire EXACTLY, and the nominee usage
    fold sees the same (node, pod) extras the dead process saw."""
    api = FakeAPIServer()
    build_cluster(api)
    p = make_pod("nom0", cpu_milli=1500, mem=2**20)
    p.priority = 1000
    api.create("pods", p)
    api.update_pod_status("default", "nom0", nominated_node_name="n2")
    # relisted pod carries the nomination on the wire
    assert api.get("pods", "default/nom0").nominated_node_name == "n2"

    sched = Scheduler(cache=SchedulerCache(), queue=PriorityQueue(),
                      **CELL_KWARGS)
    try:
        report = cold_start(sched, api, warmup=False, start_informers=False)
        assert report.nominations == 1
        assert report.nomination_mismatches == 0
        noms = sched.queue.nomination_extras(set())
        assert [(n, pp.key()) for n, pp in noms] == [("n2", "default/nom0")]
        # usage-fold parity: the overlay the device fold consumes is
        # exactly the pre-crash nomination
        assert [pp.key() for pp in sched.queue.nominated_pods_for_node("n2")] \
            == ["default/nom0"]
    finally:
        sched.close()


def test_nomination_cleared_on_bind():
    api = FakeAPIServer()
    build_cluster(api)
    api.create("pods", make_pod("c0", cpu_milli=100, mem=2**20))
    api.update_pod_status("default", "c0", nominated_node_name="n1")
    api.bind("default", "c0", "n1")
    pod = api.get("pods", "default/c0")
    assert pod.node_name == "n1"
    assert pod.nominated_node_name == ""  # clear-on-bind


# ---------------------------------------------------------------------------
# bind idempotency (the benign/mismatch Conflict split)
# ---------------------------------------------------------------------------

def test_bind_conflict_benign_vs_mismatch():
    api = FakeAPIServer()
    build_cluster(api)
    api.create("pods", make_pod("ic0", cpu_milli=100, mem=2**20))
    binder = APIBinder(api)
    pod = api.get("pods", "default/ic0")
    b0 = M.bind_conflicts.value("benign")
    m0 = M.bind_conflicts.value("mismatch")
    binder.bind(pod, "n0")
    # replay of a landed bind: the store 409s, the binder verifies the
    # node and treats it as success
    binder.bind(pod, "n0")
    assert M.bind_conflicts.value("benign") == b0 + 1
    # a DIFFERENT node is a double-schedule: escalates, never silent
    with pytest.raises(BindMismatchError):
        binder.bind(pod, "n3")
    assert M.bind_conflicts.value("mismatch") == m0 + 1
    assert api.get("pods", "default/ic0").node_name == "n0"  # store unscathed
    # the raw store surface stays strict (BindingREST semantics)
    with pytest.raises(ConflictError):
        api.bind("default", "ic0", "n0")


def test_benign_conflict_not_routed_to_backoff():
    """The commit path counts a same-node replay as SCHEDULED: the pod
    must not land in the bind-failure backoff tier."""
    api = FakeAPIServer()
    build_cluster(api)
    # pinned to n0 so the replayed decision matches the landed bind (a
    # DIFFERENT node would be a true mismatch and SHOULD escalate)
    p = make_pod("rb0", cpu_milli=100, mem=2**20,
                 node_selector={"kubernetes.io/hostname": "n0"})
    api.create("pods", p)
    # simulate the landed-but-unacknowledged first attempt
    api.bind("default", "rb0", "n0")

    cache = SchedulerCache()
    queue = PriorityQueue()
    binder = APIBinder(api)
    rpc0 = M.bind_failures.value("rpc")
    sched = Scheduler(cache=cache, queue=queue, binder=Binder(binder.bind),
                      **CELL_KWARGS)
    try:
        # force the replay: the scheduler believes the pod pending and
        # solves it onto n0's ample capacity; the bind 409s benign
        queue.add(p)
        for node in api.list("nodes")[0]:
            cache.add_node(node)
        deadline = time.monotonic() + 20
        while queue.pending_count() and time.monotonic() < deadline:
            sched.schedule_batch()
            sched.wait_for_binds()
        assert M.bind_failures.value("rpc") == rpc0  # no backoff routing
        assert api.get("pods", "default/rb0").node_name == "n0"
    finally:
        sched.close()


# ---------------------------------------------------------------------------
# graceful shutdown hardening
# ---------------------------------------------------------------------------

def _pkg_threads():
    return {
        t for t in threading.enumerate()
        if t.name.startswith(("bind", "commit-apply", "ingest-upload",
                              "terms-upload", "health-monitor",
                              "compile-warmup"))
        and t.is_alive()
    }


def test_close_is_idempotent_and_leaks_no_threads():
    # snapshot first: assert on THIS scheduler's delta only — in a full
    # suite run, other tests' daemon uploaders may outlive their tests,
    # and this test's contract is "close() leaks nothing it created"
    pre_existing = _pkg_threads()
    api = FakeAPIServer()
    build_cluster(api)
    for i in range(4):
        api.create("pods", make_pod(f"cl{i}", cpu_milli=100, mem=2**20))
    sched = Scheduler(cache=SchedulerCache(), queue=PriorityQueue(),
                      binder=Binder(APIBinder(api).bind), **CELL_KWARGS)
    cold_start(sched, api)
    sched.enable_health_monitor(interval=0.05)
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        live, _ = api.list("pods")
        if all(p.node_name for p in live):
            break
        sched.schedule_batch()
        sched.wait_for_binds()

    def ours():
        return _pkg_threads() - pre_existing

    assert ours(), "expected live worker threads before close"
    for inf in sched.restart_informers.values():
        inf.stop()
    sched.close()
    deadline = time.monotonic() + 5
    while ours() and time.monotonic() < deadline:
        time.sleep(0.05)
    assert not ours(), f"leaked threads: {ours()}"
    # the final census was emitted and is schema-valid
    from kubernetes_tpu.obs.introspect import validate_census

    assert sched.last_census is not None
    assert validate_census(sched.last_census) == []
    # second close: clean no-op
    sched.close()
    assert not ours()


def test_simulated_crash_passes_fault_handlers():
    """SimulatedCrash must NOT be absorbed by any `except Exception`
    fault handler — kill -9 gives nothing a chance to recover."""
    assert issubclass(SimulatedCrash, BaseException)
    assert not issubclass(SimulatedCrash, Exception)
    plan = FaultPlan.parse("crash:post-solve@1")
    with pytest.raises(SimulatedCrash):
        plan.crash_if("post-solve")
    assert plan.crashed == "post-solve"
    # the latch fences every later kill-point call AND the write gate
    with pytest.raises(SimulatedCrash):
        plan.crash_if("mid-apply")
    with pytest.raises(SimulatedCrash):
        plan.crash_gate()
    # the rearmed twin shares counts but passes the gate
    twin = plan.rearm()
    twin.crash_gate()
    assert twin.events is plan.events
