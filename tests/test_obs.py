"""Flight-recorder (kubernetes_tpu/obs) coverage: span pairing across
threads, Chrome-trace export validity, two-phase device spans, ring
wraparound, the black box, the disabled fast path, per-pod latency
attribution, Prometheus exposition escaping, and the /readyz warmup gate.

The process-global RECORDER is shared with the package's instrumentation
sites; every test that arms it restores the disabled state (the
`recorder_hygiene` fixture) so the rest of the suite keeps the zero-cost
path.
"""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import pytest

from kubernetes_tpu.obs import (
    DEVICE_THREAD,
    FlightRecorder,
    NOOP_SPAN,
    RECORDER,
)
from kubernetes_tpu.obs.export import raw_to_trace, validate_trace


@pytest.fixture
def recorder_hygiene():
    yield
    RECORDER.enable(False)
    RECORDER.reset()


# ---------------------------------------------------------------------------
# span rings
# ---------------------------------------------------------------------------


def test_span_pairing_across_five_threads():
    """Every thread writes only its own ring; each begin gets its end
    (context-manager exit) and the merged export carries one complete
    event per span plus a thread_name metadata row per thread."""
    rec = FlightRecorder(enabled=True)
    n_threads, n_spans = 5, 10

    def worker(i):
        for j in range(n_spans):
            with rec.span(f"stage-{i}", j=j):
                pass

    threads = [
        threading.Thread(target=worker, args=(i,), name=f"worker-{i}")
        for i in range(n_threads)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    rings = rec.snapshot_rings()
    by_name = {name: recs for _, name, recs in rings}
    assert set(by_name) == {f"worker-{i}" for i in range(n_threads)}
    for recs in by_name.values():
        assert len(recs) == n_spans
        for _name, t0, dur, _args in recs:
            assert dur >= 0.0

    doc = rec.export()
    assert validate_trace(doc) == []
    meta_names = {
        e["args"]["name"]
        for e in doc["traceEvents"]
        if e.get("ph") == "M" and e.get("name") == "thread_name"
    }
    assert {f"worker-{i}" for i in range(n_threads)} <= meta_names
    spans = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
    assert len(spans) == n_threads * n_spans


def test_chrome_trace_sorted_and_json_round_trips(tmp_path):
    rec = FlightRecorder(enabled=True)
    with rec.span("outer", batch=1):
        with rec.span("inner", pods=32):
            pass
    rec.instant("marker", note="x")
    path = str(tmp_path / "trace.json")
    doc = rec.export(path)
    assert validate_trace(doc) == []
    ts = [e["ts"] for e in doc["traceEvents"] if e.get("ph") != "M"]
    assert ts == sorted(ts)
    with open(path) as f:
        assert validate_trace(json.load(f)) == []


def test_raw_dump_converts_offline(tmp_path):
    """save_raw -> raw_to_trace is the scripts/trace_export.py path."""
    rec = FlightRecorder(enabled=True)
    with rec.span("dispatch", pods=4):
        pass
    raw_path = str(tmp_path / "raw.json")
    rec.save_raw(raw_path)
    with open(raw_path) as f:
        doc = raw_to_trace(json.load(f))
    assert validate_trace(doc) == []
    assert any(e.get("name") == "dispatch" for e in doc["traceEvents"])


def test_ring_wraparound_keeps_newest():
    rec = FlightRecorder(capacity=8, enabled=True)
    for i in range(20):
        rec.record(f"s{i}", time.perf_counter())
    ((tid, name, recs),) = rec.snapshot_rings()
    assert len(recs) == 8
    assert [r[0] for r in recs] == [f"s{i}" for i in range(12, 20)]
    t0s = [r[1] for r in recs]
    assert t0s == sorted(t0s)


def test_span_set_attaches_args_mid_span():
    rec = FlightRecorder(enabled=True)
    with rec.span("flush") as sp:
        sp.set(rows=17)
    ((_tid, _name, recs),) = rec.snapshot_rings()
    assert recs[0][3] == {"rows": 17}


# ---------------------------------------------------------------------------
# disabled fast path
# ---------------------------------------------------------------------------


def test_disabled_mode_is_a_shared_noop():
    rec = FlightRecorder(enabled=False)
    assert rec.span("x") is NOOP_SPAN
    assert rec.span("y", a=1) is NOOP_SPAN  # same singleton, no allocation
    with rec.span("z") as sp:
        sp.set(rows=1)  # no-op, no error
    assert rec.device_begin("solve", object()) == 0
    rec.device_end(0)
    rec.record("x", time.perf_counter())
    rec.instant("x")
    rec.record_cycle({"cycle": 1})
    assert rec.snapshot_rings() == []  # no ring was ever created
    assert rec.blackbox_snapshot() == []
    assert rec.dump_blackbox("nothing") is None


def test_global_recorder_disabled_by_default():
    """The suite (and any un-opted-in production run) must be on the
    zero-cost path: KTPU_TRACE unset -> RECORDER.enabled False."""
    if os.environ.get("KTPU_TRACE", "") in ("", "0", "false", "False"):
        assert RECORDER.enabled is False


# ---------------------------------------------------------------------------
# two-phase device spans
# ---------------------------------------------------------------------------


class _Handle:
    """Stands in for a dispatched jax.Array: counts forcing calls."""

    def __init__(self):
        self.forced = 0

    def block_until_ready(self):
        self.forced += 1


def _device_records(rec):
    for tid, name, recs in rec.snapshot_rings():
        if name == DEVICE_THREAD:
            return recs
    return []


def test_device_end_never_forces_the_handle():
    rec = FlightRecorder(enabled=True)
    h = _Handle()
    tok = rec.device_begin("solve", h, pods=32)
    assert tok > 0
    rec.device_end(tok)
    assert h.forced == 0  # phase 2 at the fetch point stamps, not forces
    recs = _device_records(rec)
    assert [r[0] for r in recs] == ["solve"]
    assert rec.pending_count() == 0


def test_resolve_pending_blocks_abandoned_handles():
    rec = FlightRecorder(enabled=True)
    handles = [_Handle() for _ in range(3)]
    for i, h in enumerate(handles):
        rec.device_begin(f"solve-{i}", h)
    assert rec.pending_count() == 3
    n = rec.resolve_pending()
    assert n == 3
    assert all(h.forced == 1 for h in handles)
    assert rec.pending_count() == 0
    assert len(_device_records(rec)) == 3


def test_pending_overflow_abandons_oldest(monkeypatch):
    from kubernetes_tpu.obs import recorder as recorder_mod

    monkeypatch.setattr(recorder_mod, "MAX_PENDING_DEVICE", 4)
    rec = FlightRecorder(enabled=True)
    handles = [_Handle() for _ in range(6)]
    for i, h in enumerate(handles):
        rec.device_begin(f"d{i}", h)
    assert rec.pending_count() == 4
    assert rec.dropped_pending == 2
    # the two oldest were abandoned: zero duration, flagged, NOT forced
    # (read the ring directly — snapshot_rings would resolve the rest)
    abandoned = rec._device_ring.snapshot()
    assert [r[0] for r in abandoned] == ["d0", "d1"]
    for _name, _t0, dur, args in abandoned:
        assert dur == 0.0 and args["abandoned"] is True
    assert handles[0].forced == 0 and handles[1].forced == 0
    # export-time resolution picks up the still-parked four
    recs = _device_records(rec)
    assert [r[0] for r in recs] == [f"d{i}" for i in range(6)]
    assert all(h.forced == 1 for h in handles[2:])


# ---------------------------------------------------------------------------
# black box
# ---------------------------------------------------------------------------


def test_blackbox_ring_is_bounded():
    rec = FlightRecorder(enabled=True, blackbox_capacity=4)
    for i in range(10):
        rec.record_cycle({"cycle": i})
    snap = rec.blackbox_snapshot()
    assert [r["cycle"] for r in snap] == [6, 7, 8, 9]


def test_blackbox_dump_writes_artifact(tmp_path):
    rec = FlightRecorder(enabled=True)
    rec.record_cycle({"cycle": 1, "scheduled": 32})
    path = rec.dump_blackbox("unit-test", str(tmp_path / "bb.json"))
    with open(path) as f:
        doc = json.load(f)
    assert doc["reason"] == "unit-test"
    assert doc["cycles"][0]["scheduled"] == 32


def test_blackbox_dump_on_driver_exception(tmp_path, monkeypatch, recorder_hygiene):
    """An exception escaping a traced schedule_batch dumps the last N
    cycle records before propagating — the 'invisible mid-drain' class
    of bug becomes a log artifact."""
    pytest.importorskip("jax")
    from kubernetes_tpu.models.generators import make_node, make_pod
    from kubernetes_tpu.scheduler.driver import Binder, Scheduler
    from kubernetes_tpu.state.cache import SchedulerCache
    from kubernetes_tpu.state.queue import PriorityQueue

    monkeypatch.setenv("KTPU_TRACE_DIR", str(tmp_path))
    cache = SchedulerCache()
    for i in range(2):
        cache.add_node(make_node(f"n{i}", cpu_milli=4000, mem=4 * 2**30))
    sched = Scheduler(
        cache=cache, queue=PriorityQueue(), binder=Binder(),
        deterministic=True, trace=True,
    )
    try:
        for i in range(4):
            sched.queue.add(make_pod(f"p{i}", cpu_milli=100, mem=2**20))
        res = sched.schedule_batch()  # a real cycle -> a black-box record
        assert res.scheduled == 4
        assert sched.obs.blackbox_snapshot()

        def boom(max_pods=None):
            raise RuntimeError("injected driver failure")

        monkeypatch.setattr(sched, "_schedule_batch", boom)
        with pytest.raises(RuntimeError, match="injected"):
            sched.schedule_batch()
    finally:
        sched.close()
    dumps = [f for f in os.listdir(tmp_path) if f.startswith("ktpu_blackbox_")]
    assert len(dumps) == 1 and "driver-exception" in dumps[0]
    with open(tmp_path / dumps[0]) as f:
        doc = json.load(f)
    assert doc["reason"] == "driver-exception"
    assert doc["cycles"][0]["scheduled"] == 4


def test_blackbox_dump_on_lock_order_violation(tmp_path, monkeypatch,
                                               recorder_hygiene):
    """A LockOrderViolation dumps the black box before raising — same
    contract as the driver-exception path, fired from the lock-order
    harness's assert_acyclic."""
    monkeypatch.setenv("KTPU_LOCK_AUDIT", "1")
    monkeypatch.setenv("KTPU_TRACE_DIR", str(tmp_path))
    from kubernetes_tpu.analysis.lockorder import (
        REGISTRY,
        LockOrderViolation,
        audited_lock,
    )

    RECORDER.reset()
    RECORDER.enable(True)
    RECORDER.record_cycle({"cycle": 7, "scheduled": 12})
    REGISTRY.reset()
    try:
        a, b = audited_lock("obsLockA"), audited_lock("obsLockB")

        def nest(outer, inner):
            with outer:
                with inner:
                    pass

        for outer, inner in ((a, b), (b, a)):
            t = threading.Thread(target=nest, args=(outer, inner))
            t.start()
            t.join()
        with pytest.raises(LockOrderViolation):
            REGISTRY.assert_acyclic()
    finally:
        REGISTRY.reset()
    dumps = [f for f in os.listdir(tmp_path) if f.startswith("ktpu_blackbox_")]
    assert len(dumps) == 1 and "lock-order-violation" in dumps[0]
    with open(tmp_path / dumps[0]) as f:
        doc = json.load(f)
    assert doc["cycles"][0]["cycle"] == 7


# ---------------------------------------------------------------------------
# per-pod latency attribution
# ---------------------------------------------------------------------------


def test_attribution_sums_to_e2e():
    """queue_incoming_wait (enqueue -> pop) + scheduling_attempt_duration
    (pop -> bound) must reassemble pod_scheduling_duration (enqueue ->
    bound) — the decomposition bench's attribution block quotes. Deltas
    against the module histograms so a shared pytest process stays
    clean."""
    pytest.importorskip("jax")
    from kubernetes_tpu.metrics import metrics as M
    from kubernetes_tpu.models.generators import make_node, make_pod
    from kubernetes_tpu.scheduler.driver import Binder, Scheduler
    from kubernetes_tpu.state.cache import SchedulerCache
    from kubernetes_tpu.state.queue import PriorityQueue

    def snap():
        return (
            M.queue_incoming_wait.sum(),
            M.scheduling_attempt_duration.sum("scheduled")
            + M.scheduling_attempt_duration.sum("unschedulable"),
            M.pod_scheduling_duration.sum(),
            M.pod_scheduling_duration.count(),
        )

    cache = SchedulerCache()
    for i in range(4):
        cache.add_node(make_node(f"n{i}", cpu_milli=8000, mem=8 * 2**30))
    sched = Scheduler(
        cache=cache, queue=PriorityQueue(), binder=Binder(),
        deterministic=True,
    )
    n_pods = 16
    try:
        wait0, attempt0, e2e0, cnt0 = snap()
        for i in range(n_pods):
            sched.queue.add(make_pod(f"p{i}", cpu_milli=100, mem=2**20))
        res = sched.schedule_batch()
        sched.wait_for_binds()
        assert res.scheduled == n_pods
    finally:
        sched.close()
    wait, attempt, e2e, cnt = snap()
    d_wait, d_attempt, d_e2e = wait - wait0, attempt - attempt0, e2e - e2e0
    assert cnt - cnt0 == n_pods
    assert d_e2e > 0
    # single attempt per pod: wait + attempt ≈ e2e (the observation
    # points are microseconds apart on the same clock; the drain itself
    # is the signal, so 5% + a small absolute floor is strict enough)
    assert abs(d_wait + d_attempt - d_e2e) < max(0.05 * d_e2e, 0.05), (
        d_wait, d_attempt, d_e2e,
    )


def test_queue_stamps_enqueue_and_pop():
    from kubernetes_tpu.api.types import Container, Pod, Quantity
    from kubernetes_tpu.state.queue import PriorityQueue

    clock = [100.0]
    q = PriorityQueue(now=lambda: clock[0])
    pod = Pod(name="a", namespace="x", containers=[Container(name="c")])
    q.add(pod)
    info = q.peek_batch(1)[0]
    assert info.enqueue_ts == 100.0
    clock[0] = 103.0
    (popped,) = q.pop_batch(1)
    assert popped.pop_ts == 103.0
    clock[0] = 104.5
    assert q.attempt_age(popped) == pytest.approx(1.5)
    # re-add of the SAME key (requeue path) keeps the first-admission
    # stamp — the e2e anchor survives round trips
    q.add(pod)
    info2 = q.peek_batch(1)[0]
    assert info2.enqueue_ts == 100.0
    assert info2.timestamp == 104.5


# ---------------------------------------------------------------------------
# Prometheus exposition escaping (satellite)
# ---------------------------------------------------------------------------


def test_label_value_escaping_pins_text_format():
    import re

    from kubernetes_tpu.metrics.registry import Counter, Registry

    reg = Registry()
    c = reg.register(Counter("evil_total", "counts evil\nthings \\ ok",
                             label_names=("pod",)))
    c.inc('he said "hi"\\here\nand left')
    text = reg.expose_text()
    line = next(
        l for l in text.splitlines()
        if l.startswith("evil_total{")
    )
    assert line == (
        'evil_total{pod="he said \\"hi\\"\\\\here\\nand left"} 1.0'
    )
    # HELP escapes backslash + newline (quotes legal there)
    help_line = next(l for l in text.splitlines() if l.startswith("# HELP"))
    assert help_line == "# HELP evil_total counts evil\\nthings \\\\ ok"
    # the whole exposition stays machine-parseable line by line
    sample = re.compile(
        r'^[a-zA-Z_:][a-zA-Z0-9_:]*'
        r'(\{[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\\n]|\\["\\n])*"'
        r'(,[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\\n]|\\["\\n])*")*\})?'
        r' (-?[0-9.eE+-]+|\+Inf|-Inf|NaN)$'
    )
    for l in text.splitlines():
        if l and not l.startswith("#"):
            assert sample.match(l), l


# ---------------------------------------------------------------------------
# /readyz warmup gate (satellite)
# ---------------------------------------------------------------------------


def _get(url):
    try:
        with urllib.request.urlopen(url, timeout=5) as r:
            return r.status
    except urllib.error.HTTPError as e:
        return e.code


def test_readyz_gates_on_warmup_healthz_does_not():
    from kubernetes_tpu.metrics.serving import MetricsServer

    ready = {"v": False}
    srv = MetricsServer(port=0, ready_fn=lambda: ready["v"]).start()
    try:
        assert _get(f"{srv.url}/healthz") == 200  # alive the whole time
        assert _get(f"{srv.url}/livez") == 200
        assert _get(f"{srv.url}/readyz") == 503  # cold: not ready
        ready["v"] = True
        assert _get(f"{srv.url}/readyz") == 200  # warmed
        assert _get(f"{srv.url}/metrics") == 200
    finally:
        srv.stop()


def test_scheduler_ready_property_tracks_warmup():
    pytest.importorskip("jax")
    from kubernetes_tpu.models.generators import make_node, make_pod
    from kubernetes_tpu.scheduler.driver import Binder, Scheduler
    from kubernetes_tpu.state.cache import SchedulerCache
    from kubernetes_tpu.state.queue import PriorityQueue

    cache = SchedulerCache()
    cache.add_node(make_node("n0", cpu_milli=4000, mem=4 * 2**30))
    sched = Scheduler(
        cache=cache, queue=PriorityQueue(), binder=Binder(),
        deterministic=True,
    )
    try:
        assert sched.ready is False  # cold: /readyz must answer 503
        sched.queue.add(make_pod("p0", cpu_milli=100, mem=2**20))
        sched.warmup()
        assert sched.ready is True
    finally:
        sched.close()


# ---------------------------------------------------------------------------
# Scheduler.dump_trace API
# ---------------------------------------------------------------------------


def test_scheduler_dump_trace_exports_valid_json(tmp_path, recorder_hygiene):
    pytest.importorskip("jax")
    from kubernetes_tpu.models.generators import make_node, make_pod
    from kubernetes_tpu.scheduler.driver import Binder, Scheduler
    from kubernetes_tpu.state.cache import SchedulerCache
    from kubernetes_tpu.state.queue import PriorityQueue

    RECORDER.reset()
    cache = SchedulerCache()
    for i in range(2):
        cache.add_node(make_node(f"n{i}", cpu_milli=4000, mem=4 * 2**30))
    sched = Scheduler(
        cache=cache, queue=PriorityQueue(), binder=Binder(),
        deterministic=True, trace=True,
    )
    try:
        for i in range(8):
            sched.queue.add(make_pod(f"p{i}", cpu_milli=100, mem=2**20))
        res = sched.schedule_batch()
        sched.wait_for_binds()
        assert res.scheduled == 8
        path = sched.dump_trace(str(tmp_path / "drain.json"))
    finally:
        sched.close()
    with open(path) as f:
        doc = json.load(f)
    assert validate_trace(doc) == []
    names = {e["name"] for e in doc["traceEvents"] if e.get("ph") != "M"}
    # the core driver stages of even a cold un-warmed single batch
    for stage in ("cycle", "sync", "dispatch", "fetch", "commit",
                  "enqueue", "stage-encode"):
        assert stage in names, (stage, sorted(names))
    assert RECORDER.pending_count() == 0  # export resolved parked spans
