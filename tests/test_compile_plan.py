"""Compile-plan subsystem tests (kubernetes_tpu/compile): ladder
canonicalization, padded-vs-unpadded execution parity, persistent cache
round-trips (stubbed backend — no TPU, no real AOT serialization), the
warmup service's synthetic-bank growth warming, and the inline-fallback
miss accounting. All CPU-only tier-1."""

import json

import pytest

pytest.importorskip("jax")

from kubernetes_tpu.compile import (
    CompilePlan,
    PersistentCompileCache,
    ShapeLadder,
    SolveSpec,
)
from kubernetes_tpu.compile.ladder import (
    KIND_PREEMPT,
    KIND_SOLVE,
    KIND_SOLVE_GANG,
    node_axis_bucket,
    pow2_bucket,
)
from kubernetes_tpu.models.generators import make_node, make_pod
from kubernetes_tpu.scheduler.driver import Binder, Scheduler
from kubernetes_tpu.state.cache import SchedulerCache
from kubernetes_tpu.state.queue import PriorityQueue


def _mk_scheduler(nodes, existing=(), **kw):
    cache = SchedulerCache()
    for n in nodes:
        cache.add_node(n)
    for p in existing:
        cache.add_pod(p)
    binds = []
    binder = Binder(lambda pod, node: binds.append((pod.key(), node)))
    kw.setdefault("enable_preemption", False)
    sched = Scheduler(cache=cache, queue=PriorityQueue(), binder=binder,
                      deterministic=True, **kw)
    return sched, binds


# --- ladder -----------------------------------------------------------------

def test_bucket_quantizers():
    assert pow2_bucket(0) == 16 and pow2_bucket(16) == 16
    assert pow2_bucket(17) == 32 and pow2_bucket(4097) == 8192
    assert node_axis_bucket(2048) == 2048
    assert node_axis_bucket(2049) == 4096  # 2x2048, not pow2 jump
    assert node_axis_bucket(10000) == 10240  # 5x2048
    # state/tensors' aliases ARE these functions (one quantizer)
    from kubernetes_tpu.state.tensors import _bucket, _node_bucket

    assert _bucket is pow2_bucket and _node_bucket is node_axis_bucket


def test_ladder_canonicalization_and_declaration():
    lad = ShapeLadder()
    raw = SolveSpec(kind=KIND_SOLVE, b=37, u=100, t=5, n=3000, v=9,
                    k=64, r=8, s=256, pt=32)
    c = lad.canonicalize(raw)
    assert (c.b, c.t, c.n, c.v) == (64, 16, 4096, 16)
    assert c.u == 64  # clamped to b: a batch can't hold more specs than pods
    # canonicalization is idempotent and covers() sees through raw sizes
    assert lad.canonicalize(c) == c
    assert not lad.covers(raw)
    lad.declare(raw)
    assert lad.covers(raw) and lad.covers(c) and len(lad) == 1
    # a different static is a different program
    assert not lad.covers(
        SolveSpec(kind=KIND_SOLVE, b=37, u=100, t=5, n=3000, v=9,
                  k=64, r=8, s=256, pt=32, track_inbatch=True)
    )
    # preempt specs pass through UNCHANGED: their call site buckets with
    # minimum 8, and re-rounding here would alias distinct kernel shapes
    # onto one key (reporting a mid-drain compile as a plan hit)
    pre = SolveSpec(kind=KIND_PREEMPT, b=8, n=500, v=8, r=8)
    assert lad.canonicalize(pre) == pre


def test_growth_specs_cover_middrain_growth_axes():
    lad = ShapeLadder()
    c = lad.canonicalize(SolveSpec(kind=KIND_SOLVE, b=4096, u=64, t=64,
                                   n=2048, v=64, k=64, r=8, s=256, pt=32))
    growth = lad.growth_specs(c)
    axes = {(g.u, g.t, g.v, g.s, g.pt) for g in growth}
    assert (128, 64, 64, 256, 32) in axes  # unique-spec rung
    assert (64, 128, 64, 256, 32) in axes  # term rung
    assert (64, 64, 128, 256, 32) in axes  # segment rung
    assert (64, 64, 64, 1024, 32) in axes  # sig bank x4 (mirror rebuild)
    assert (64, 64, 64, 256, 128) in axes  # pattern bank x4


def test_spec_roundtrip_and_hash_stability():
    s = SolveSpec(kind=KIND_SOLVE_GANG, b=64, u=32, t=16, n=256, v=16,
                  k=64, r=8, s=256, pt=32,
                  term_kinds=frozenset({"anti_req", "pref"}),
                  with_carry=True)
    assert SolveSpec.from_dict(json.loads(json.dumps(s.to_dict()))) == s
    assert s.hash_hex() == SolveSpec.from_dict(s.to_dict()).hash_hex()


# --- padded vs unpadded execution parity ------------------------------------

def test_padded_execution_matches_unpadded():
    """Padding up to a bigger ladder rung must be bit-identical to the
    tight shapes: same workload through two drivers, one with pre-grown
    buckets (the padded-ladder execution path), identical placements."""
    def build():
        nodes = [make_node(f"n{i}", cpu_milli=4000, mem=8 * 2**30) for i in range(5)]
        pods = [make_pod(f"p{i}", cpu_milli=700, mem=2**27) for i in range(12)]
        return nodes, pods

    results = []
    for pad in (False, True):
        nodes, pods = build()
        sched, _ = _mk_scheduler(nodes)
        if pad:
            sched._b_bucket = 64
            sched._u_bucket = 64
            sched._t_bucket = 32
            sched._v_bucket = 32
        for p in pods:
            sched.queue.add(p)
        res = sched.schedule_batch()
        sched.wait_for_binds()
        results.append(dict(res.assignments))
    assert results[0] == results[1]
    assert len(results[0]) == 12


def test_preempt_padded_matches_unpadded():
    """batch_preempt_device's ladder-padded axes (pod bucket, node rung,
    victim bucket) must not change any plan."""
    from kubernetes_tpu.oracle import Snapshot
    from kubernetes_tpu.scheduler.preemption import batch_preempt_device

    nodes = [make_node(f"n{i}", cpu_milli=1000, mem=2**30) for i in range(3)]
    existing = []
    for i, n in enumerate(nodes):
        v = make_pod(f"victim{i}", cpu_milli=900, mem=2**20)
        v.priority = 0
        v.node_name = n.name
        existing.append(v)
    snap = Snapshot(nodes, existing)
    pres = []
    for i in range(2):
        p = make_pod(f"hi{i}", cpu_milli=800, mem=2**20)
        p.priority = 100
        pres.append(p)
    base = batch_preempt_device(pres, snap)
    padded = batch_preempt_device(pres, snap, pod_bucket=64, victim_bucket=32)
    assert base is not None and padded is not None

    def norm(plans):
        return [(n, [v.key() for v in vs], ff) for n, vs, ff in plans]

    assert norm(base) == norm(padded)


# --- warmup coverage ---------------------------------------------------------

def test_warmup_declares_ladder_and_drain_has_no_misses():
    nodes = [make_node(f"n{i}", cpu_milli=4000, mem=8 * 2**30) for i in range(4)]
    sched, binds = _mk_scheduler(nodes)
    for i in range(10):
        sched.queue.add(make_pod(f"p{i}", cpu_milli=300, mem=2**20))
    assert sched.warmup() == 10
    snap = sched.compile_plan.snapshot()
    assert snap["warmed"] and snap["declared_specs"] >= 2  # carry + carry-less
    while True:
        r = sched.schedule_batch()
        if r.scheduled == 0 and r.unschedulable == 0 and r.errors == 0:
            break
    sched.wait_for_binds()
    assert len(binds) == 10
    snap = sched.compile_plan.snapshot()
    assert snap["misses_after_warmup"] == 0, snap
    assert snap["hits"] >= 1


def test_warmup_service_synthetic_growth_banks():
    """Growth specs (sig/pattern bank one rung ahead) warm against
    SYNTHETIC banks — shapes the live mirror doesn't have yet."""
    nodes = [make_node(f"n{i}", cpu_milli=4000, mem=8 * 2**30) for i in range(3)]
    sched, _ = _mk_scheduler(nodes)
    for i in range(4):
        sched.queue.add(make_pod(f"p{i}", cpu_milli=300, mem=2**20))
    assert sched.warmup() == 4
    svc = sched._warm_svc
    # quiesce the background headroom worker first: warmup queues these
    # very growth specs on it, and whether it has finished them by now is
    # a timing race — warm_specs skips already-done specs, so the count
    # below would flake. Force a deterministic FOREGROUND execution.
    svc.stop()
    svc.join()
    spec = sched._solve_spec(gang=False, with_carry=False)
    growth = sched.compile_plan.ladder.growth_specs(spec)
    sig_specs = [g for g in growth if g.s != spec.s or g.pt != spec.pt]
    assert sig_specs
    with svc._lock:
        for g in sig_specs:
            svc._done.discard(svc.plan.canonicalize(g).key())
    warmed = svc.warm_specs(sig_specs)
    assert warmed == len(sig_specs)
    for g in sig_specs:
        assert sched.compile_plan.is_declared(g)


def test_warmup_arms_background_growth_warming():
    nodes = [make_node(f"n{i}", cpu_milli=4000, mem=8 * 2**30) for i in range(3)]
    sched, _ = _mk_scheduler(nodes)
    for i in range(4):
        sched.queue.add(make_pod(f"p{i}", cpu_milli=300, mem=2**20))
    assert not sched._aot_enabled
    sched.warmup()
    assert sched._aot_enabled
    sched.schedule_batch()
    sched.wait_for_binds()
    # the headroom worker ran (or is running) without disturbing the drain
    sched._warm_svc.join(timeout=60)
    assert sched._warm_svc.stats["failures"] == 0


def test_preempt_kernel_warmed_when_preemption_enabled():
    nodes = [make_node(f"n{i}", cpu_milli=1000, mem=2**30) for i in range(3)]
    existing = []
    for i, n in enumerate(nodes):
        v = make_pod(f"low{i}", cpu_milli=900, mem=2**20)
        v.priority = 0
        v.node_name = n.name
        existing.append(v)
    sched, _ = _mk_scheduler(nodes, existing=existing,
                             enable_preemption=True, batch_size=16)
    hi = make_pod("hi", cpu_milli=800, mem=2**20)
    hi.priority = 100
    sched.queue.add(hi)
    assert sched.warmup() == 1
    snap = sched.compile_plan.snapshot()
    assert any(s["spec"].startswith("preempt[") for s in snap["specs"]), snap
    # the real preemption round must HIT the warmed kernel spec
    res = sched.schedule_batch()
    assert res.preempted == 1
    assert sched.compile_plan.snapshot()["misses_after_warmup"] == 0


# --- inline fallback ----------------------------------------------------------

def test_inline_fallback_compiles_and_counts_miss():
    """An undeclared spec after warmup must still schedule (inline jit)
    while the plan counts + exposes the miss."""
    nodes = [make_node(f"n{i}", cpu_milli=4000, mem=8 * 2**30) for i in range(3)]
    sched, binds = _mk_scheduler(nodes)
    sched.compile_plan.mark_warmed()  # warmed, but nothing declared
    for i in range(5):
        sched.queue.add(make_pod(f"p{i}", cpu_milli=300, mem=2**20))
    res = sched.schedule_batch()
    sched.wait_for_binds()
    assert res.scheduled == 5 and len(binds) == 5  # correctness never waits
    snap = sched.compile_plan.snapshot()
    assert snap["misses_after_warmup"] >= 1
    assert snap["compiles"] >= 1 and snap["compile_s"] >= 0.0
    from kubernetes_tpu.metrics import metrics as M

    assert M.compile_spec_misses_after_warmup._values.get(()) is not None


# --- persistent cache ---------------------------------------------------------

def test_persistent_ladder_roundtrip(tmp_path):
    cache = PersistentCompileCache(str(tmp_path / "cc"))
    plan = CompilePlan(cache=cache)
    s1 = plan.declare(SolveSpec(kind=KIND_SOLVE, b=64, u=32, t=16, n=256,
                                v=16, k=64, r=8, s=256, pt=32))
    plan.note_compiled(s1, 12.5, "warmup")
    s2 = plan.declare(SolveSpec(kind=KIND_PREEMPT, b=64, n=256, v=16, r=8))
    assert plan.persist()
    # fresh process equivalent
    plan2 = CompilePlan(cache=PersistentCompileCache(str(tmp_path / "cc")))
    loaded = plan2.load_persisted()
    assert {x.key() for x in loaded} == {s1.key(), s2.key()}
    # compile budget survived (the >=5x warm-vs-cold bookkeeping)
    rec = [e for e in plan2.snapshot()["specs"] if e["spec"] == s1.short()]
    assert rec and rec[0]["compile_s"] == 12.5
    assert rec[0]["source"] == "persisted"


def test_persistent_ladder_rejects_foreign_environment(tmp_path):
    cache = PersistentCompileCache(str(tmp_path / "cc"))
    plan = CompilePlan(cache=cache)
    plan.declare(SolveSpec(kind=KIND_SOLVE, b=64, u=32, t=16, n=256,
                           v=16, k=64, r=8, s=256, pt=32))
    assert plan.persist()
    # tamper: pretend the ladder came from another jaxlib
    p = tmp_path / "cc" / "ladder.json"
    doc = json.loads(p.read_text())
    doc["environment"]["jaxlib"] = "0.0.0-other"
    p.write_text(json.dumps(doc))
    assert CompilePlan(cache=PersistentCompileCache(str(tmp_path / "cc"))).load_persisted() == []
    # corrupt file → cold start, never an error
    p.write_text("{ not json")
    assert CompilePlan(cache=PersistentCompileCache(str(tmp_path / "cc"))).load_persisted() == []


class _StubSerializer:
    """Executable-serialization backend stub: records round-trips without
    any XLA dependency (the satellite's 'stubbed backend')."""

    def __init__(self):
        self.serialized = 0
        self.deserialized = 0

    def serialize(self, compiled) -> bytes:
        self.serialized += 1
        return b"EXE:" + repr(compiled).encode()

    def deserialize(self, blob: bytes):
        self.deserialized += 1
        assert blob.startswith(b"EXE:")
        return ("executable", blob[4:].decode())


def test_executable_cache_roundtrip_with_stub_backend(tmp_path):
    stub = _StubSerializer()
    cache = PersistentCompileCache(str(tmp_path / "cc"), serializer=stub)
    spec = SolveSpec(kind=KIND_SOLVE, b=64, u=32, t=16, n=256, v=16,
                     k=64, r=8, s=256, pt=32)
    assert cache.save_executable(spec, {"fake": "compiled"})
    out = cache.load_executable(spec)
    assert out == ("executable", repr({"fake": "compiled"}))
    assert stub.serialized == 1 and stub.deserialized == 1
    # unknown spec → None, not an error
    other = SolveSpec(kind=KIND_SOLVE, b=128, u=32, t=16, n=256, v=16,
                      k=64, r=8, s=256, pt=32)
    assert cache.load_executable(other) is None


class _FailingSerializer:
    def serialize(self, compiled):
        raise NotImplementedError("backend can't serialize")

    def deserialize(self, blob):
        raise NotImplementedError


def test_executable_cache_degrades_without_backend(tmp_path):
    cache = PersistentCompileCache(str(tmp_path / "cc"), serializer=_FailingSerializer())
    spec = SolveSpec(kind=KIND_SOLVE, b=64, u=32, t=16, n=256, v=16,
                     k=64, r=8, s=256, pt=32)
    assert not cache.save_executable(spec, object())
    assert cache.load_executable(spec) is None


def test_scheduler_restart_rewarmups_from_persisted_ladder(tmp_path):
    """Process 1 warms + persists; process 2 (fresh Scheduler, same cache
    dir) re-declares the ladder at warmup and drains with zero misses."""
    cache_dir = str(tmp_path / "cc")

    def run(pods_prefix):
        nodes = [make_node(f"n{i}", cpu_milli=4000, mem=8 * 2**30) for i in range(3)]
        plan = CompilePlan(cache=PersistentCompileCache(cache_dir))
        sched, binds = _mk_scheduler(nodes, compile_plan=plan)
        for i in range(6):
            sched.queue.add(make_pod(f"{pods_prefix}{i}", cpu_milli=300, mem=2**20))
        assert sched.warmup() == 6
        while True:
            r = sched.schedule_batch()
            if r.scheduled == 0 and r.unschedulable == 0 and r.errors == 0:
                break
        sched.wait_for_binds()
        return sched.compile_plan.snapshot()

    snap1 = run("a")
    assert snap1["misses_after_warmup"] == 0
    snap2 = run("b")
    assert snap2["misses_after_warmup"] == 0
    # the restart re-declared the persisted ladder (source recorded)
    assert any(e["source"] == "persisted" for e in snap2["specs"]), snap2


def test_failed_persisted_warm_is_undeclared(tmp_path):
    """A persisted spec whose warm is skipped/fails must NOT stay
    declared — a later dispatch of it would otherwise count as a hit
    while paying a real inline compile (silent stall)."""
    cache_dir = str(tmp_path / "cc")
    plan = CompilePlan(cache=PersistentCompileCache(cache_dir))
    # a spec this deployment can't realize (foreign SolveConfig repr)
    bogus = SolveSpec(kind=KIND_SOLVE, b=16, u=16, t=16, n=16, v=16,
                      k=64, r=8, s=256, pt=32,
                      config_repr="SolveConfig(predicates=frozenset({'X'}))")
    plan.declare(bogus)
    assert plan.persist()

    nodes = [make_node(f"n{i}", cpu_milli=4000, mem=8 * 2**30) for i in range(3)]
    plan2 = CompilePlan(cache=PersistentCompileCache(cache_dir))
    sched, _ = _mk_scheduler(nodes, compile_plan=plan2)
    for i in range(4):
        sched.queue.add(make_pod(f"p{i}", cpu_milli=300, mem=2**20))
    assert sched.warmup() == 4
    assert not sched.compile_plan.is_declared(bogus)
    snap = sched.compile_plan.snapshot()
    assert all(e["spec"] != bogus.short() for e in snap["specs"])


def test_warm_context_confines_mirror_to_role_boundary():
    """KTPU006/008 regression (thread-role analysis): the background
    warm worker used to read live mirror shapes/vocab — and gate
    device_arrays on a current_thread() check — from its own thread,
    racing any concurrent rebuild. The _WarmContext snapshot is now the
    ONLY mirror touch, taken at the role boundary on the driver: a
    background ctx never carries the live-bank resolver, and the fold
    kernels are captured only when a sharded fold spec is visible."""
    import numpy as np

    from kubernetes_tpu.compile.ladder import KIND_FOLD, KIND_SOLVE, SolveSpec
    from kubernetes_tpu.compile.warmup import _WarmContext

    calls = []

    class _Vocab:
        class config:
            key_slots = 8
            resource_slots = 3

    class _NodeBank:
        capacity = 4
        key_capacity = 8
        alloc = np.zeros((4, 3), np.int64)
        image_scaled = np.zeros((4, 16), np.int64)

    class _Bank:
        capacity = 4

    class _Mirror:
        nodes = _NodeBank()
        eps = _Bank()
        pats = _Bank()
        vocab = _Vocab()

        def _to_dev(self, v, node_major=False):
            return v

        def device_arrays(self):
            calls.append("device_arrays")
            return ({}, {}, {})

        def _fold_fns(self):
            calls.append("fold_fns")
            return (lambda *a: a, lambda *a: a)

    m = _Mirror()
    solve = SolveSpec(kind=KIND_SOLVE, b=16, u=16, t=16, n=4, v=2)
    bg = _WarmContext(m, [solve], foreground=False)
    assert bg.live_banks is None          # worker can NEVER resolve live banks
    assert bg.fold_fns is None            # no sharded fold spec visible
    assert bg.live_shape == (4, 8, 3, 4, 4)
    assert bg.img_w == 16 and bg.vocab is m.vocab
    assert calls == []                    # capture itself touched neither

    fg = _WarmContext(m, [solve], foreground=True)
    assert fg.live_banks == m.device_arrays  # bound, invoked lazily
    assert calls == []                    # still not CALLED at capture

    fold = SolveSpec(kind=KIND_FOLD, b=16, n=4, r=3, shards=2)
    ctx = _WarmContext(m, [fold], foreground=False)
    assert ctx.fold_fns is not None and calls == ["fold_fns"]
