"""Test configuration: run JAX on a virtual 8-device CPU mesh.

Real TPU hardware in CI is a single chip; multi-chip sharding tests need
several devices, so tests force the CPU backend with 8 virtual host devices
(jax's xla_force_host_platform_device_count). Must run before jax imports.
"""

import os
import sys

# Force CPU even when the session pins JAX_PLATFORMS to the real chip: the
# multi-chip parity tests need 8 devices. KTPU_TEST_PLATFORM=axon opts back
# into running the (single-device) suite on real hardware. The CI image's
# sitecustomize re-pins the platform at jax-import time, so the env var
# alone is not enough — the jax.config update below wins.
_platform = os.environ.get("KTPU_TEST_PLATFORM", "cpu")
os.environ["JAX_PLATFORMS"] = _platform
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402  (must happen after the env setup above)

jax.config.update("jax_platforms", _platform)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
