"""Test configuration: run JAX on a virtual 8-device CPU mesh.

Real TPU hardware in CI is a single chip; multi-chip sharding tests need
several devices, so tests force the CPU backend with 8 virtual host devices
(jax's xla_force_host_platform_device_count). Must run before jax imports.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
