"""Bit-for-bit parity: device filter kernels vs the scalar oracle.

The correctness gate from SURVEY.md section 4: identical feasibility sets on
randomized clusters exercising every predicate.
"""

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from kubernetes_tpu.models.generators import ClusterGen
from kubernetes_tpu.ops import filters as F
from kubernetes_tpu.oracle import Snapshot
from kubernetes_tpu.oracle import predicates as opred
from kubernetes_tpu.state.tensors import PodBatch, _bucket, encode_snapshot


def _encode(snap, pods):
    bank, eps, rows = encode_snapshot(snap)
    batch = PodBatch(bank.vocab, _bucket(len(pods)))
    for i, p in enumerate(pods):
        batch.set_pod(i, p)
    na = {k: jnp.asarray(v) for k, v in bank.arrays().items()}
    pa = {k: jnp.asarray(v) for k, v in batch.arrays().items()}
    return na, pa, F.make_ids(bank.vocab), batch


ORACLE_FNS = {
    "unschedulable": opred.check_node_unschedulable,
    "host": opred.pod_fits_host,
    "ports": opred.pod_fits_host_ports,
    "selector": opred.pod_match_node_selector,
    "resources": opred.pod_fits_resources,
    "taints": opred.pod_tolerates_node_taints,
}


@pytest.mark.parametrize("seed", [0, 1, 2, 3, 4])
def test_filter_parity_random_clusters(seed):
    g = ClusterGen(seed)
    nodes, existing = g.cluster(32, 120, feature_rate=0.5)
    snap = Snapshot(nodes, existing)
    pods = [g.pod(50_000 + i, feature_rate=0.5) for i in range(24)]
    na, pa, ids, batch = _encode(snap, pods)
    assert not batch.fallback.any(), "generator should stay within capacities"
    masks = {k: np.asarray(v) for k, v in F.filter_masks(na, pa, ids).items()}
    node_list = list(snap.node_infos.values())
    for b, p in enumerate(pods):
        for n, ni in enumerate(node_list):
            for name, fn in ORACLE_FNS.items():
                assert bool(masks[name][b, n]) == fn(p, ni), (
                    f"seed={seed} predicate={name} pod={p.name} node={ni.node.name}"
                )


def test_combined_mask_matches_oracle_subset():
    g = ClusterGen(99)
    nodes, existing = g.cluster(16, 60, feature_rate=0.4)
    snap = Snapshot(nodes, existing)
    pods = [g.pod(60_000 + i, feature_rate=0.4) for i in range(8)]
    # strip topology features (handled by topology.py kernels)
    for p in pods:
        p.topology_spread_constraints = []
        if p.affinity is not None:
            p.affinity.pod_affinity = None
            p.affinity.pod_anti_affinity = None
    na, pa, ids, _ = _encode(snap, pods)
    combined = np.asarray(F.combined_mask(na, pa, ids))
    node_list = list(snap.node_infos.values())
    for b, p in enumerate(pods):
        for n, ni in enumerate(node_list):
            expect = all(fn(p, ni) for fn in ORACLE_FNS.values())
            assert bool(combined[b, n]) == expect

    # padding rows/cols must be masked off
    assert not combined[len(pods):, :].any()
    assert not combined[:, len(node_list):].any()


def test_fallback_flag_on_overflow():
    from kubernetes_tpu.api.types import Toleration

    g = ClusterGen(5)
    nodes, _ = g.cluster(4, 0)
    snap = Snapshot(nodes, [])
    pod = g.pod(1)
    pod.tolerations = [Toleration(key=f"k{i}", operator="Exists") for i in range(20)]
    bank, _, _ = encode_snapshot(snap)
    batch = PodBatch(bank.vocab, 16)
    batch.set_pod(0, pod)
    assert batch.fallback[0]
