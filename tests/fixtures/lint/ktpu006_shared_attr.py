"""KTPU006 fixture pair: the unannotated uploader→driver attribute.

Reproduces the hole KTPU003 cannot see: ``StageBank.fault_plan``-style
state written on one thread role and read on another with NO
``guarded-by``/``confined`` declaration — module-locally there is
nothing to check, because nobody ever declared the attribute shared.
The role graph (thread-entry seeds + call-graph propagation) infers the
sharing instead.

Must flag:     Bank.report_generation  (written by uploader, read by driver)
Must not flag: Bank.declared_rows      (declared guarded-by + locked)
               Bank.ctor_only          (written only in __init__)
               Bank.handoff            (allow(KTPU006) with a reason)
"""

import threading


class Bank:
    def __init__(self):
        self._lock = threading.Lock()
        self.ctor_only = {"frozen": True}  # published before any spawn
        self.report_generation = 0  # <- shared, written, UNDECLARED
        self.declared_rows = 0  # ktpu: guarded-by(self._lock)
        # ktpu: allow(KTPU006) single-owner handoff: built by the driver,
        # read by the uploader only after start() (Thread.start is the
        # happens-before edge)
        self.handoff = None

    def start(self):
        # ktpu: thread-entry(fixture-upload)
        threading.Thread(target=self._drain, daemon=True).start()

    # ktpu: thread-entry(fixture-upload)
    def _drain(self):
        while True:
            self.report_generation += 1  # uploader-side write
            with self._lock:
                self.declared_rows += 1
            if self.handoff is None:
                return

    # ktpu: thread-entry(fixture-driver)
    def dispatch(self):
        gen = self.report_generation  # driver-side read of the same attr
        cfg = self.ctor_only["frozen"]
        self.handoff = {"batch": gen}  # allowed: documented handoff
        with self._lock:
            rows = self.declared_rows
        return gen, rows, cfg
