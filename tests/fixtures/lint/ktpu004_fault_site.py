"""MUST-FLAG KTPU004: a fault-injection site that FORCES a device value
to decide whether to fire, inside a hot-path dispatch function.

The fault plane's injection-site contract (kubernetes_tpu/faults): every
site lives inside a `# ktpu: hot-path` function and must cost exactly
ONE attribute read when no FaultPlan is configured — and when one is,
the trigger decision is a host-side counter (`plan.fire(site)`), never a
device read. A site that inspects a device bank's VALUE to decide
("inject only when the bank is non-empty") silently serializes the
pipelined drain on every dispatch — the exact stall class KTPU004
exists to catch. The sanctioned idiom is the attribute-read + counted
raise below.
"""

import numpy as np


class InjectedFault(RuntimeError):
    pass


class Dispatcher:
    def __init__(self, bank_dev):
        self.bank_dev = bank_dev
        self.fault_plan = None

    # ktpu: hot-path
    def bad_dispatch(self, idx):
        fp = self.fault_plan
        if fp is not None:
            # <- forces a device->host sync ON THE HOT PATH to decide
            # whether to inject — the site itself became the stall
            occupied = float(np.asarray(self.bank_dev["rows"]).sum())
            if occupied > 0 and fp.fire("device-raise"):
                raise InjectedFault("device-raise")
        return self.bank_dev["rows"]

    # ktpu: hot-path
    def good_dispatch(self, idx):
        # sanctioned injection-site idiom: one attribute read when no
        # plan is armed; the trigger is a host-side counted schedule
        fp = self.fault_plan
        if fp is not None and fp.fire("device-raise"):
            raise InjectedFault("device-raise")
        return self.bank_dev["rows"]
