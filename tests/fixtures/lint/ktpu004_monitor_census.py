"""MUST-FLAG KTPU004 + KTPU003: a health-monitor census that forces a
device value / writes its shared state unlocked.

The steady-state health monitor's hazard shape (obs/introspect): the
monitor thread refreshes gauges next to a live drain, so its census
functions are `# ktpu: hot-path`-marked — reading a device bank's VALUE
(np.asarray / float / .item) from the monitor silently serializes the
pipelined drain on every refresh interval, and its shared state (read by
the /debug/ktpu mux threads and written by monitor + driver hooks) is
guarded-by one audited lock. The sanctioned pattern is the metadata-only
census: shapes, lens, counters, the bytes ledger — never array contents.
"""

import threading

import numpy as np


class Monitor:
    def __init__(self, mirror):
        self._lock = threading.Lock()
        self.mirror = mirror
        self.last_census = {}  # ktpu: guarded-by(self._lock)

    # ktpu: hot-path
    def bad_census(self):
        bank_dev = self.mirror.dev_nodes
        census = {
            # <- forces a device->host sync on every monitor refresh
            "requested_total": float(np.asarray(bank_dev["requested"]).sum()),
        }
        self.last_census = census  # <- unlocked write to guarded state
        return census

    # ktpu: hot-path
    def good_census(self):
        bank_dev = self.mirror.dev_nodes
        census = {
            "rows": bank_dev["requested"].shape[0],  # metadata probe: free
            "bytes": dict(self.mirror.bytes_shipped),  # host counters
        }
        with self._lock:
            self.last_census = census
        return census
