"""MUST-FLAG KTPU003: unlocked scatter-add into the columnar cache's
hot columns.

The columnar-cache hazard shape (state/columns.py): the columns are
written by bulk assume/forget on the COMMIT WORKER while the informer
thread's pod events take the scalar path and the driver's fold planner
reads the interned spec rows — an unlocked np.add.at is a lost-update
race that silently skews `requested`/`pod_count` until the divergence
probe (or a placement audit) trips. Same RMW class as PR 5's vocab-slot
interning bug; every column is declared guarded-by the cache's lock.
"""

import threading

import numpy as np


class Columns:
    def __init__(self):
        self._lock = threading.RLock()
        self.requested = np.zeros((8, 4), np.int64)  # ktpu: guarded-by(self._lock)
        self.pod_count = np.zeros(8, np.int32)  # ktpu: guarded-by(self._lock)
        self.spec_req = np.zeros((4, 4), np.int64)  # ktpu: guarded-by(self._lock)

    def bad_assume(self, rows, slots):
        # <- unlocked read-modify-write on guarded columns
        np.add.at(self.requested, rows, self.spec_req[slots])
        np.add.at(self.pod_count, rows, 1)

    def good_assume(self, rows, slots):
        with self._lock:
            np.add.at(self.requested, rows, self.spec_req[slots])
            np.add.at(self.pod_count, rows, 1)

    def assume_bulk_locked(self, rows, slots):
        # repo convention: the *_locked suffix asserts the caller (the
        # cache's bulk state machine) already holds the lock
        np.add.at(self.requested, rows, self.spec_req[slots])
        np.add.at(self.pod_count, rows, 1)

    # ktpu: holds(self._lock) the fold planner gathers delta rows inside
    # the cache's locked window (plan_fold's delta_mats contract)
    def delta_rows(self, slots):
        return self.spec_req[slots]
