"""MUST-FLAG KTPU001: the invisible mid-drain patch-program compile.

Reproduces PR 4's BENCH_r05 config-6 bug: the mirror's dirty-row scatter
was jitted in a plain factory with no compile-plan admission, so the
scatter programs compiled INLINE mid-drain (a 2.58s "solve" spike the
plan's miss counters never saw).
"""

import jax

_SCATTER = None


def scatter_fn():
    global _SCATTER
    if _SCATTER is None:

        @jax.jit  # <- no KIND_* spec, no plan.admit, no admitted() mark
        def scatter(dev, idx, updates):
            out = dict(dev)
            for k, u in updates.items():
                out[k] = dev[k].at[idx].set(u)
            return out

        _SCATTER = scatter
    return _SCATTER
