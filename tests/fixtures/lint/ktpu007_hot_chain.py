"""KTPU007 fixture pair: the transitive hot-path → host-sync chain.

Reproduces the hole in module-local KTPU004: the hot-path function
itself contains no forcing call — it reaches ``np.asarray`` on a device
value ONE CALL DEEP through an innocent-looking helper, which is
exactly how every PERF round's silent round-trip hid.

Must flag:     hot_dispatch      (hot-path → _summarize → np.asarray(dev))
Must not flag: hot_via_syncpoint (the reached fetcher is allowlisted)
               hot_host_only     (the helper forces a HOST value only)
               cold_dispatch     (not hot-path-marked at all)
"""

import numpy as np


def _summarize(dev_rows):
    return np.asarray(dev_rows).sum()  # device→host sync, one call deep


def _host_tally(rows):
    return np.asarray(rows).sum()  # host list → host array: free


def fetch_results(dev_rows):
    """The designated sync point (fixture sync_allowlist entry)."""
    return np.asarray(dev_rows)


# ktpu: hot-path
def hot_dispatch(dev_rows):
    return _summarize(dev_rows)  # <- reaches a forcing call: must flag


# ktpu: hot-path
def hot_via_syncpoint(dev_rows):
    return fetch_results(dev_rows)  # allowlisted barrier: clean


# ktpu: hot-path
def hot_host_only(rows):
    return _host_tally(rows)  # host-only chain: clean


def cold_dispatch(dev_rows):
    return _summarize(dev_rows)  # not hot-marked: KTPU007 says nothing
