"""MUST-FLAG KTPU002 (host-sync): np.asarray on a mirror-resident array.

Reproduces PR 4's donation blocker: np.asarray on a sharded resident
array caches `_npy_value` INSIDE the jax Array, and that cached host view
silently blocks the NEXT fold's buffer donation — the probe perturbs
what it measures. Fetches must go through a device-side copy at a
declared sync point (`device_bank_divergence` is the allowlisted twin).
"""

import numpy as np
import jax.numpy as jnp


class Mirror:
    def __init__(self, banks):
        self._dev_nodes = banks

    def bad_probe(self):
        # <- direct host view of the resident array: cached _npy_value
        return np.asarray(self._dev_nodes["requested"]).sum()

    def device_bank_divergence(self):
        # allowlisted sync point: fetches via a device-side COPY
        return np.asarray(jnp.array(self._dev_nodes["requested"], copy=True))

    def annotated_probe(self):
        return np.asarray(self._dev_nodes["valid"])  # ktpu: host-sync-ok test-only debug probe
