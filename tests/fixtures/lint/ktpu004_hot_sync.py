"""MUST-FLAG KTPU004: device→host forcing inside a hot-path function.

One hidden round-trip in dispatch/arbiter/fold code serializes the whole
pipelined drain (every PERF round found at least one of these). Results
belong at the batch's designated fetch point.
"""

import jax
import numpy as np


# ktpu: hot-path
def bad_dispatch(solver, na_dev, pa_dev):
    assign_dev = solver(na_dev, pa_dev)
    return jax.device_get(assign_dev)  # <- forcing inside the hot path


# ktpu: hot-path
def good_dispatch(solver, na_dev, pa_dev):
    width = int(na_dev["requested"].shape[1])  # shape probe: free
    rows = np.asarray([0] * width, np.int32)  # host->host: fine
    return solver(na_dev, pa_dev), rows  # fetch happens downstream


def cold_fetch(assign_dev):
    """Not hot-path-marked: fetching here is the designated sync point."""
    return jax.device_get(assign_dev)
