"""MUST-FLAG KTPU003: unlocked access to a guarded-by attribute.

Reproduces PR 5's vocab-slot interning race: slot assignment is a
read-modify-write (len → insert); once encodes moved to the informer
thread, an unlocked access could hand two keys the SAME slot, silently
corrupting label matching forever.
"""

import threading


class SlotTable:
    def __init__(self):
        self._lock = threading.Lock()
        self.slots = {}  # ktpu: guarded-by(self._lock)

    def bad_slot_of(self, key):
        s = self.slots.get(key)  # <- unlocked read-modify-write
        if s is None:
            s = len(self.slots)
            self.slots[key] = s
        return s

    def good_slot_of(self, key):
        with self._lock:
            s = self.slots.get(key)
            if s is None:
                s = len(self.slots)
                self.slots[key] = s
            return s

    def _drain_locked(self):
        return sorted(self.slots)  # caller holds the lock (suffix contract)

    # ktpu: holds(self._lock) called only from good_slot_of's locked block
    def _helper(self):
        return len(self.slots)


class FoldBook:
    """confined(): single-thread state with NO lock — accesses must come
    from methods carrying the matching confined mark."""

    def __init__(self):
        self.folded_rows = set()  # ktpu: confined(driver)

    def bad_note(self, row):
        self.folded_rows.add(row)  # <- unmarked method: race or missing mark

    # ktpu: confined(driver) dispatch runs on the driver thread only
    def good_note(self, row):
        self.folded_rows.add(row)
