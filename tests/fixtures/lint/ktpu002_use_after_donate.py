"""MUST-FLAG KTPU002 (use-after-donate): reading a donated buffer.

The fold plane's contract: a donated argument's buffer is DELETED at
dispatch. Reading the stale reference afterwards raises (best case) or
silently reads garbage through a cached view (worst case). The idiomatic
fix — rebinding the result to the same name — is the must-not-flag twin
below.
"""

from functools import partial

import jax


@partial(jax.jit, donate_argnums=(0,))
def fold_counts(counts, rows, deltas):
    return counts.at[rows].add(deltas)


def bad_apply(counts, rows, deltas):
    out = fold_counts(counts, rows, deltas)
    return counts.sum() + out.sum()  # <- `counts` was donated above


def good_apply(counts, rows, deltas):
    counts = fold_counts(counts, rows, deltas)  # rebind ends the taint
    return counts.sum()
