"""MUST-FLAG KTPU002 (host-sync): a forcing call inside a span resolver
that is NOT the sanctioned allowlisted one.

The flight recorder's two-phase device-timing idiom (kubernetes_tpu/obs)
parks dispatched array handles on the hot path and resolves their
durations off-thread. The ONE sanctioned resolution point is the
allowlisted ``Recorder.resolve_pending`` twin below; any other helper
that blocks on a parked handle re-creates the hot-path sync KTPU004
exists to forbid — the whole point of parking the handle was to move the
wait off the dispatch thread, so an un-allowlisted resolver is a
regression waiting to be inlined back into the driver.
"""

import time


class Recorder:
    def __init__(self):
        self._pending = {}
        self._ring = []

    def eager_resolve(self, token):
        # <- forcing call in a NON-allowlisted resolver: must flag
        name, t0, handle_dev, args = self._pending.pop(token)
        handle_dev.block_until_ready()
        self._ring.append((name, t0, time.perf_counter() - t0, args))

    def resolve_pending(self):
        # allowlisted twin of FlightRecorder.resolve_pending: the same
        # forcing call is sanctioned HERE (and only here) — export/drain
        # time, never a hot path
        pending, self._pending = self._pending, {}
        for name, t0, handle_dev, args in pending.values():
            handle_dev.block_until_ready()
            self._ring.append((name, t0, time.perf_counter() - t0, args))
