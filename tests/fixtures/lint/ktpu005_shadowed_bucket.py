"""MUST-FLAG KTPU005: the seed `_bucket` UnboundLocalError.

The module imports `_bucket`; a function used it and ALSO re-imported it
locally further down — Python then treats `_bucket` as function-local
everywhere in that function, so the early use raised UnboundLocalError
at runtime. At seed this broke warmup for every enable_preemption=False
drain.
"""

from math import floor as _bucket


def bad_warm(n):
    r = _bucket(n)  # <- UnboundLocalError: the import below makes it local
    from math import ceil as _bucket
    return _bucket(r)


def shadow_only(n):
    from math import ceil as _bucket  # <- shadows the module-level name
    return _bucket(n)


def good_local_import(n):
    from math import trunc as _trunc  # fresh name: no shadow, no flag
    return _trunc(n) + _bucket(n)
