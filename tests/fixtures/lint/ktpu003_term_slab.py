"""MUST-FLAG KTPU003: unlocked refcount bookkeeping on a term-slab entry
map.

The term-bank plane's hazard shape (terms_plane/stage.py): entries are
refcounted by queue holders on the INFORMER thread while the driver's
dispatch prologue resolves them — an unlocked release is a lost-update
race on `refs` that either frees rows a live dispatch is about to gather
or pins them forever. Same RMW class as PR 5's vocab-slot interning bug.
"""

import threading


class TermSlab:
    def __init__(self):
        self._lock = threading.RLock()
        self.entries = {}  # ktpu: guarded-by(self._lock)
        self.free_rows = []  # ktpu: guarded-by(self._lock)

    def bad_release(self, eid):
        e = self.entries.get(eid)  # <- unlocked read-modify-write
        if e is not None:
            e["refs"] -= 1
            if e["refs"] <= 0:
                self.free_rows.extend(e["rows"])
                del self.entries[eid]

    def good_release(self, eid):
        with self._lock:
            e = self.entries.get(eid)
            if e is not None:
                e["refs"] -= 1
                if e["refs"] <= 0:
                    self.free_rows.extend(e["rows"])
                    del self.entries[eid]

    # ktpu: holds(self._lock) the prologue resolves entries inside its
    # locked capture window (the driver's _term_prologue contract)
    def entry_for(self, eid):
        return self.entries.get(eid)
