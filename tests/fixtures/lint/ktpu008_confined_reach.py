"""KTPU008 fixture pair: a confined(driver) method reached by the monitor.

Before this rule, ``# ktpu: confined(driver)`` claims were purely
syntactic — KTPU003 checks that confined ATTRS are touched only by
confined-marked METHODS, but nothing checked that those methods really
run on one thread. The role graph closes that: a confined method
reachable from any other role's entry is a violation. The spawn-site
contract rides along: every Thread/submit must be rooted in the role
graph.

Must flag:     Mirror.census          (confined(driver), reached by monitor)
               Monitor.start_unrooted (spawn with no thread-entry anywhere)
Must not flag: Mirror.fold_rows       (confined(driver), driver-only reach)
               Monitor.read_mailbox   (reads the published copy instead)
"""

import threading


class Mirror:
    def __init__(self):
        self.folded = set()  # ktpu: confined(fixture-driver)
        self.mailbox = {}

    # ktpu: confined(fixture-driver) the monitor must consume the mailbox
    def census(self):
        return {"folded": len(self.folded)}

    # ktpu: confined(fixture-driver)
    def fold_rows(self, rows):
        self.folded.update(rows)
        self.mailbox = dict(self.census())  # driver publishes


class Monitor:
    def __init__(self, mirror: Mirror):
        self.mirror = mirror

    def start(self):
        # ktpu: thread-entry(fixture-health)
        threading.Thread(target=self._run, daemon=True).start()

    def start_unrooted(self):
        threading.Thread(target=self._tick, daemon=True).start()  # <- unrooted

    # ktpu: thread-entry(fixture-health)
    def _run(self):
        while True:
            self.mirror.census()  # <- crosses the confinement: must flag
            self.read_mailbox()

    def _tick(self):
        pass

    def read_mailbox(self):
        return dict(self.mirror.mailbox)  # the sanctioned monitor read


class Driver:
    # ktpu: thread-entry(fixture-driver)
    def cycle(self, mirror: Mirror):
        mirror.fold_rows({1, 2})  # driver reaching confined state: clean
