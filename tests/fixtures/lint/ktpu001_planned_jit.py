"""MUST-NOT-FLAG KTPU001: plan-admitted jit factories.

Both admission mechanisms: a factory whose scope visibly routes through
the compile plan (KIND_* spec / plan.admit), and a factory carrying an
explicit `# ktpu: admitted(...)` mark.
"""

import jax

KIND_PATCH = "patch"

_A = None
_B = None


def planned_factory(plan, spec_of):
    """The jit sits in a scope that admits a KIND_* spec — self-evidently
    planned."""
    global _A
    if _A is None:

        @jax.jit
        def scatter(dev, idx):
            return {k: v.at[idx].set(0) for k, v in dev.items()}

        _A = scatter
    plan.admit(spec_of(KIND_PATCH))
    return _A


# ktpu: admitted(KIND_PATCH) dispatched only via the mirror's admitted
# scatter path; warmed at startup
def annotated_factory():
    global _B
    if _B is None:

        @jax.jit
        def scatter(dev, idx):
            return {k: v.at[idx].set(0) for k, v in dev.items()}

        _B = scatter
    return _B
