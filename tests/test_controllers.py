"""Control-plane controllers: ReplicaSet + NodeLifecycle reconcile loops
over the fake apiserver, and the full control loop with the scheduler in
the middle (create → schedule → node death → evict → recreate →
re-schedule). Reference anchors: replica_set.go syncReplicaSet,
node_lifecycle_controller.go, controllermanager.go:373."""

import time

import pytest

pytest.importorskip("jax")

from kubernetes_tpu.api.types import (
    Container,
    LabelSelector,
    Pod,
    Quantity,
    RESOURCE_CPU,
    RESOURCE_MEMORY,
    ReplicaSet,
    Toleration,
    replicaset_from_k8s,
)
from kubernetes_tpu.apiserver import FakeAPIServer
from kubernetes_tpu.client import APIBinder, start_scheduler_informers
from kubernetes_tpu.controllers import ControllerManager, TAINT_NOT_READY
from kubernetes_tpu.models.generators import make_node
from kubernetes_tpu.scheduler.driver import Binder, Scheduler
from kubernetes_tpu.scheduler.eventhandlers import EventHandlers


def _template(app: str, cpu="100m") -> Pod:
    return Pod(
        name="template", labels={"app": app},
        containers=[Container(name="c", requests={
            RESOURCE_CPU: Quantity.parse(cpu),
            RESOURCE_MEMORY: Quantity.parse("64Mi"),
        })],
    )


def _rs(name: str, replicas: int, app: str) -> ReplicaSet:
    return ReplicaSet(
        name=name, replicas=replicas,
        selector=LabelSelector(match_labels={"app": app}),
        template=_template(app),
    )


def _pods(api, app=None):
    pods, _ = api.list("pods")
    if app is None:
        return pods
    return [p for p in pods if p.labels.get("app") == app]


def test_replicaset_scales_up_and_down():
    api = FakeAPIServer()
    cm = ControllerManager(api).start()
    try:
        rs = _rs("web", 5, "web")
        api.create("replicasets", rs)
        assert cm.wait_idle()
        assert len(_pods(api, "web")) == 5
        # every replica is owned and Pending
        for p in _pods(api, "web"):
            assert p.owner_references[0]["uid"] == rs.uid
            assert p.phase == "Pending"
        # scale down → surplus deleted
        rs.replicas = 2
        api.update("replicasets", rs)
        assert cm.wait_idle()
        assert len(_pods(api, "web")) == 2
        # scale back up
        rs.replicas = 4
        api.update("replicasets", rs)
        assert cm.wait_idle()
        assert len(_pods(api, "web")) == 4
    finally:
        cm.stop()


def test_replicaset_replaces_deleted_and_failed_pods():
    api = FakeAPIServer()
    cm = ControllerManager(api).start()
    try:
        api.create("replicasets", _rs("job", 3, "job"))
        assert cm.wait_idle()
        pods = _pods(api, "job")
        assert len(pods) == 3
        # external deletion → replacement
        api.delete("pods", pods[0].key())
        assert cm.wait_idle()
        assert len(_pods(api, "job")) == 3
        # a pod failing (phase) no longer counts as live → replaced
        victim = _pods(api, "job")[0]
        victim.phase = "Failed"
        api.update("pods", victim)
        assert cm.wait_idle()
        live = [p for p in _pods(api, "job") if p.phase != "Failed"]
        assert len(live) == 3
    finally:
        cm.stop()


def test_replicaset_json_round_trip():
    rs = replicaset_from_k8s({
        "metadata": {"name": "api", "namespace": "prod", "uid": "u-1"},
        "spec": {
            "replicas": 3,
            "selector": {"matchLabels": {"app": "api"}},
            "template": {
                "metadata": {"labels": {"app": "api"}},
                "spec": {"containers": [{"name": "c", "resources": {
                    "requests": {"cpu": "250m", "memory": "1Gi"}}}]},
            },
        },
    })
    assert rs.replicas == 3 and rs.namespace == "prod"
    assert rs.template.containers[0].requests["cpu"].milli_value() == 250
    assert rs.selector.match_labels == {"app": "api"}


def test_nodelifecycle_taints_and_untaints():
    api = FakeAPIServer()
    n = make_node("n0", cpu_milli=4000, mem=8 * 2**30)
    api.create("nodes", n)
    cm = ControllerManager(api).start()
    try:
        n.conditions = [{"type": "Ready", "status": "False"}]
        api.update("nodes", n)
        assert cm.wait_idle()
        node = api.get("nodes", "n0")
        assert {t.effect for t in node.taints if t.key == TAINT_NOT_READY} == {
            "NoSchedule", "NoExecute"}
        node.conditions = [{"type": "Ready", "status": "True"}]
        api.update("nodes", node)
        assert cm.wait_idle()
        node = api.get("nodes", "n0")
        assert not any(t.key == TAINT_NOT_READY for t in node.taints)
    finally:
        cm.stop()


def test_nodelifecycle_evicts_without_toleration():
    api = FakeAPIServer()
    n = make_node("n0", cpu_milli=4000, mem=8 * 2**30)
    api.create("nodes", n)
    bound = Pod(name="victim", node_name="n0")
    tolerant = Pod(name="survivor", node_name="n0", tolerations=[
        Toleration(key=TAINT_NOT_READY, operator="Exists")])
    api.create("pods", bound)
    api.create("pods", tolerant)
    cm = ControllerManager(api).start()
    try:
        n.conditions = [{"type": "Ready", "status": "False"}]
        api.update("nodes", n)
        assert cm.wait_idle()
        keys = {p.key() for p in _pods(api)}
        assert "default/victim" not in keys
        assert "default/survivor" in keys
        assert cm.nodelifecycle.evictions == 1
    finally:
        cm.stop()


def test_full_control_loop_with_scheduler():
    """The VERDICT's end-to-end bar: pods are CREATED by the controller,
    scheduled by the driver, 'fail' when their node dies (lifecycle taints
    + evicts), get recreated by the ReplicaSet, and are re-scheduled onto
    surviving nodes — with the queue flush observed via re-binds."""
    api = FakeAPIServer()
    for i in range(3):
        api.create("nodes", make_node(f"n{i}", cpu_milli=2000, mem=8 * 2**30))

    sched = Scheduler(batch_size=16, deterministic=True, enable_preemption=False)
    sched.binder = Binder(APIBinder(api).bind)
    handlers = EventHandlers(sched.cache, sched.queue, "default-scheduler")
    informers = start_scheduler_informers(api, handlers)
    for inf in informers.values():
        inf.wait_for_sync()
    cm = ControllerManager(api).start()
    try:
        api.create("replicasets", _rs("svc", 6, "svc"))
        assert cm.wait_idle()

        def drain(deadline=20.0):
            end = time.monotonic() + deadline
            while time.monotonic() < end:
                r = sched.schedule_batch()
                sched.wait_for_binds()
                if r.scheduled == 0 and r.unschedulable == 0 and r.errors == 0:
                    bound = [p for p in _pods(api, "svc")
                             if p.node_name and p.phase != "Failed"]
                    if len(bound) >= 6 and cm.wait_idle(timeout=1.0):
                        return bound
                time.sleep(0.05)
            raise AssertionError(
                f"drain timed out; pods={[ (p.key(), p.node_name) for p in _pods(api) ]}"
            )

        bound = drain()
        assert len(bound) == 6

        # node death: some replicas lived on n0
        on_n0 = [p for p in bound if p.node_name == "n0"]
        assert on_n0, "expected replicas on n0"
        n0 = api.get("nodes", "n0")
        n0.conditions = [{"type": "Ready", "status": "False"}]
        api.update("nodes", n0)
        assert cm.wait_idle()
        # lifecycle evicted them; RS recreated; scheduler must re-place on
        # n1/n2 only (n0 carries the NoSchedule taint now)
        bound2 = drain()
        assert len(bound2) == 6
        assert all(p.node_name in ("n1", "n2") for p in bound2), [
            (p.key(), p.node_name) for p in bound2]
        # the evicted generation is gone from the apiserver
        assert cm.nodelifecycle.evictions >= len(on_n0)
    finally:
        cm.stop()
        for inf in informers.values():
            inf.stop()


def test_deployment_creates_scales_and_rolls():
    """Deployment → template-hash ReplicaSet: create, scale, and a
    template edit rolls to a NEW RS while the old one drains to zero
    (deployment_controller.go reconcile, Recreate-shaped)."""
    from kubernetes_tpu.api.types import Deployment, Quantity as Q

    api = FakeAPIServer()
    cm = ControllerManager(api).start()
    try:
        dep = Deployment(
            name="web", replicas=4,
            selector=LabelSelector(match_labels={"app": "web"}),
            template=_template("web"),
        )
        api.create("deployments", dep)
        assert cm.wait_idle()
        rss, _ = api.list("replicasets")
        assert len(rss) == 1 and rss[0].replicas == 4
        assert rss[0].name.startswith("web-")
        assert len(_pods(api, "web")) == 4
        gen1 = rss[0].name

        # scale
        dep.replicas = 2
        api.update("deployments", dep)
        assert cm.wait_idle()
        assert api.get("replicasets", f"default/{gen1}").replicas == 2
        assert len(_pods(api, "web")) == 2

        # template edit → new hash → new RS; old drains
        dep.template.containers[0].requests[RESOURCE_CPU] = Q.parse("200m")
        api.update("deployments", dep)
        assert cm.wait_idle()
        rss, _ = api.list("replicasets")
        by_name = {rs.name: rs for rs in rss}
        assert len(by_name) == 2
        assert by_name[gen1].replicas == 0
        gen2 = next(n for n in by_name if n != gen1)
        assert by_name[gen2].replicas == 2
        live = [p for p in _pods(api, "web") if p.phase != "Failed"]
        assert len(live) == 2
        # the survivors are the NEW generation (owned by gen2's RS)
        assert all(r["name"] == gen2 for p in live for r in p.owner_references)
    finally:
        cm.stop()


def test_job_runs_to_completion_and_replaces_failures():
    """Job keeps `parallelism` pods active until `completions` Succeeded
    (job_controller.go syncJob): failures are replaced, successes counted
    and never replaced, and a finished job stops creating pods."""
    from kubernetes_tpu.api.types import Job

    api = FakeAPIServer()
    cm = ControllerManager(api).start()
    try:
        api.create("jobs", Job(name="batch", parallelism=2, completions=3,
                               template=_template("batch")))
        assert cm.wait_idle()
        active = [p for p in _pods(api, "batch") if p.phase not in ("Succeeded", "Failed")]
        assert len(active) == 2

        # one completes → a replacement is created (2 active, 1 done)
        done = active[0]
        done.phase = "Succeeded"
        api.update("pods", done)
        assert cm.wait_idle()
        pods = _pods(api, "batch")
        assert sum(1 for p in pods if p.phase == "Succeeded") == 1
        assert sum(1 for p in pods if p.phase not in ("Succeeded", "Failed")) == 2

        # one fails → replaced, count unchanged
        victim = next(p for p in _pods(api, "batch") if p.phase not in ("Succeeded", "Failed"))
        victim.phase = "Failed"
        api.update("pods", victim)
        assert cm.wait_idle()
        pods = _pods(api, "batch")
        assert sum(1 for p in pods if p.phase not in ("Succeeded", "Failed")) == 2

        # two more succeed → 3 completions reached; only the needed pods
        # were kept active near the end (min(parallelism, remaining))
        for p in [p for p in _pods(api, "batch") if p.phase not in ("Succeeded", "Failed")]:
            p.phase = "Succeeded"
            api.update("pods", p)
        assert cm.wait_idle()
        pods = _pods(api, "batch")
        assert sum(1 for p in pods if p.phase == "Succeeded") == 3
        # done: nothing new is created
        assert cm.wait_idle()
        assert sum(1 for p in _pods(api, "batch")
                   if p.phase not in ("Succeeded", "Failed")) == 0
    finally:
        cm.stop()
