"""Simulated apiserver + list/watch informers + end-to-end churn.

The integration-test tier (SURVEY §4 tier 2): in-process apiserver, real
informer threads, the scheduler consuming only watch events and writing
only Bindings — while nodes and pods churn.
"""

import threading
import time

import pytest

pytest.importorskip("jax")

from kubernetes_tpu.apiserver import (
    ADDED,
    DELETED,
    MODIFIED,
    ConflictError,
    FakeAPIServer,
    GoneError,
    NotFoundError,
)
from kubernetes_tpu.client import APIBinder, Informer, start_scheduler_informers
from kubernetes_tpu.models.generators import make_node, make_pod
from kubernetes_tpu.scheduler.driver import Binder, Scheduler
from kubernetes_tpu.scheduler.eventhandlers import EventHandlers
from kubernetes_tpu.state.cache import SchedulerCache
from kubernetes_tpu.state.queue import PriorityQueue


# --- store semantics --------------------------------------------------------

def test_store_rv_ordering_and_watch():
    api = FakeAPIServer()
    n = api.create("nodes", make_node("n0", cpu_milli=1000, mem=2**30))
    rv0 = int(n.resource_version)
    w = api.watch("nodes", 0)
    ev = w.next(timeout=1)
    assert ev.type == ADDED and ev.obj.name == "n0" and ev.rv == rv0
    n.labels["x"] = "y"
    api.update("nodes", n)
    ev = w.next(timeout=1)
    assert ev.type == MODIFIED and ev.obj.labels["x"] == "y"
    api.delete("nodes", "n0")
    ev = w.next(timeout=1)
    assert ev.type == DELETED
    w.close()


def test_store_deep_copies_block_mutation():
    api = FakeAPIServer()
    node = make_node("n0", cpu_milli=1000, mem=2**30)
    api.create("nodes", node)
    node.labels["mutated"] = "yes"  # caller keeps mutating its object
    got = api.get("nodes", "n0")
    assert "mutated" not in got.labels
    got.labels["also-mutated"] = "yes"
    assert "also-mutated" not in api.get("nodes", "n0").labels


def test_store_watch_compaction_gone():
    api = FakeAPIServer(history_window=4)
    for i in range(10):
        api.create("pods", make_pod(f"p{i}", cpu_milli=1, mem=0))
    with pytest.raises(GoneError):
        api.watch("pods", 1)


def test_bind_subresource_conflicts():
    api = FakeAPIServer()
    api.create("pods", make_pod("p0", cpu_milli=1, mem=0))
    api.bind("default", "p0", "n1")
    assert api.get("pods", "default/p0").node_name == "n1"
    # BindingREST semantics: ANY re-bind of a bound pod is 409 — the
    # same-node case too (the idempotent-replay handling lives with the
    # binder, client/informer.APIBinder, which verifies the bound node)
    with pytest.raises(ConflictError):
        api.bind("default", "p0", "n1")
    with pytest.raises(ConflictError):
        api.bind("default", "p0", "n2")
    assert api.get("pods", "default/p0").node_name == "n1"


# --- informer ---------------------------------------------------------------

def test_informer_sync_watch_and_relist():
    api = FakeAPIServer(history_window=8)
    for i in range(3):
        api.create("nodes", make_node(f"n{i}", cpu_milli=1000, mem=2**30))
    seen = {"add": [], "update": [], "delete": []}
    inf = Informer(api, "nodes")
    inf.add_event_handler(
        on_add=lambda o: seen["add"].append(o.name),
        on_update=lambda o, n: seen["update"].append(n.name),
        on_delete=lambda o: seen["delete"].append(o.name),
    )
    inf.start()
    assert inf.wait_for_sync()
    deadline = time.time() + 5
    while len(seen["add"]) < 3 and time.time() < deadline:
        time.sleep(0.01)
    assert sorted(seen["add"]) == ["n0", "n1", "n2"]
    api.create("nodes", make_node("n3", cpu_milli=1000, mem=2**30))
    n1 = api.get("nodes", "n1")
    n1.labels["updated"] = "true"
    api.update("nodes", n1)
    api.delete("nodes", "n0")
    deadline = time.time() + 5
    while (len(seen["add"]) < 4 or not seen["update"] or not seen["delete"]) and time.time() < deadline:
        time.sleep(0.01)
    assert "n3" in seen["add"] and "n1" in seen["update"] and "n0" in seen["delete"]
    # simulate apiserver dropping the watch: the informer must relist
    before = inf.relists()  # scheduler_informer_relists_total{kind}
    api.close_watchers("nodes")
    deadline = time.time() + 5
    while inf.relists() == before and time.time() < deadline:
        time.sleep(0.01)
    assert inf.relists() > before
    assert inf.last_relist_reason in ("stream-closed", "gone")
    assert {o.name for o in inf.list()} == {"n1", "n2", "n3"}
    inf.stop()


# --- full loop: watch → schedule → bind → confirm ---------------------------

def _spin_up(api, scheduler_name="default-scheduler"):
    cache = SchedulerCache()
    queue = PriorityQueue()
    sched = Scheduler(
        cache=cache, queue=queue, binder=Binder(APIBinder(api).bind),
        deterministic=True, enable_preemption=False,
    )
    handlers = EventHandlers(cache, queue, scheduler_name)
    informers = start_scheduler_informers(api, handlers)
    for inf in informers.values():
        assert inf.wait_for_sync()
    return sched, informers


def test_end_to_end_watch_schedule_bind_confirm():
    api = FakeAPIServer()
    for i in range(4):
        api.create("nodes", make_node(f"n{i}", cpu_milli=4000, mem=8 * 2**30))
    for i in range(10):
        api.create("pods", make_pod(f"p{i}", cpu_milli=500, mem=0))
    sched, informers = _spin_up(api)
    try:
        deadline = time.time() + 15
        while time.time() < deadline:
            sched.schedule_batch()
            bound = sum(1 for p, _ in [(p, p) for p in api.list("pods")[0]] if p.node_name)
            if bound == 10:
                break
            time.sleep(0.02)
        sched.wait_for_binds()
        pods, _ = api.list("pods")
        assert all(p.node_name for p in pods), [p.name for p in pods if not p.node_name]
        # the informer echo confirmed every assumed pod into the cache
        deadline = time.time() + 5
        while time.time() < deadline and sched.cache.assumed_count() > 0:
            time.sleep(0.02)
        assert sched.cache.assumed_count() == 0
    finally:
        for inf in informers.values():
            inf.stop()


def test_end_to_end_churn_while_scheduling():
    """Stream node/pod churn while the scheduling loop runs — the
    watch→patch→solve loop end-to-end under concurrency (VERDICT item 10)."""
    api = FakeAPIServer()
    for i in range(6):
        api.create("nodes", make_node(f"n{i}", cpu_milli=8000, mem=16 * 2**30))
    sched, informers = _spin_up(api)
    stop = threading.Event()
    created = []

    def churn():
        for i in range(60):
            api.create("pods", make_pod(f"c{i}", cpu_milli=200, mem=0))
            created.append(f"default/c{i}")
            if i % 10 == 5:
                api.create("nodes", make_node(f"extra{i}", cpu_milli=8000, mem=16 * 2**30))
            if i % 15 == 7:
                try:
                    api.delete("nodes", f"n{i % 6}")
                except NotFoundError:
                    pass  # a prior churn round already deleted this node
            time.sleep(0.005)
        stop.set()

    t = threading.Thread(target=churn)
    t.start()
    try:
        deadline = time.time() + 30
        while time.time() < deadline:
            sched.schedule_batch()
            if stop.is_set():
                pods, _ = api.list("pods")
                if len(pods) == 60 and all(p.node_name for p in pods):
                    break
            time.sleep(0.01)
        t.join()
        sched.wait_for_binds()
        # a couple more cycles for stragglers requeued by node deletions
        for _ in range(50):
            sched.queue.move_all_to_active()
            sched.queue.flush()
            sched.schedule_batch()
            pods, _ = api.list("pods")
            if all(p.node_name for p in pods):
                break
            time.sleep(0.05)
        sched.wait_for_binds()
        pods, _ = api.list("pods")
        unbound = [p.name for p in pods if not p.node_name]
        assert not unbound, f"unbound after churn: {unbound}"
        # every binding refers to a node that exists (or existed when bound)
        live_nodes = {n.name for n in api.list("nodes")[0]}
        on_dead = [p.name for p in pods if p.node_name not in live_nodes]
        # pods bound to deleted nodes are allowed transiently (the node
        # lifecycle controller's business) but must be a small minority here
        assert len(on_dead) <= 20
    finally:
        for inf in informers.values():
            inf.stop()
