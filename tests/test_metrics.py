"""Metrics, events, healthz, tracing."""

import logging
import time
import urllib.request

import pytest

pytest.importorskip("jax")

from kubernetes_tpu.metrics import MetricsServer, metrics as M
from kubernetes_tpu.metrics.registry import Counter, Gauge, Histogram, Registry
from kubernetes_tpu.models.generators import make_node, make_pod
from kubernetes_tpu.scheduler.driver import Binder, Scheduler
from kubernetes_tpu.state.cache import SchedulerCache
from kubernetes_tpu.state.queue import PriorityQueue
from kubernetes_tpu.utils import Recorder, Trace


def test_registry_exposition_format():
    r = Registry()
    c = r.register(Counter("my_total", "a counter", label_names=("result",)))
    g = r.register(Gauge("my_gauge", "a gauge"))
    h = r.register(Histogram("my_seconds", "a histogram", buckets=(0.1, 1.0)))
    c.inc("ok")
    c.inc("ok")
    c.inc("bad")
    g.set(42)
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    text = r.expose_text()
    assert 'my_total{result="ok"} 2.0' in text
    assert 'my_total{result="bad"} 1.0' in text
    assert "my_gauge 42.0" in text
    assert 'my_seconds_bucket{le="0.1"} 1' in text
    assert 'my_seconds_bucket{le="1.0"} 2' in text
    assert 'my_seconds_bucket{le="+Inf"} 3' in text
    assert "my_seconds_count 3" in text
    assert h.percentile(0.5) == 1.0


def test_scheduler_records_metrics_and_events():
    before_sched = M.schedule_attempts.value(M.SCHEDULED)
    before_batches = M.batch_size.count()
    cache = SchedulerCache()
    cache.add_node(make_node("n0", cpu_milli=2000, mem=4 * 2**30))
    rec = Recorder()
    sched = Scheduler(
        cache=cache, queue=PriorityQueue(), binder=Binder(),
        event_fn=rec.pod_event_fn(), deterministic=True, enable_preemption=False,
    )
    for i in range(3):
        sched.queue.add(make_pod(f"p{i}", cpu_milli=500, mem=0))
    sched.queue.add(make_pod("toobig", cpu_milli=9999, mem=0))
    res = sched.schedule_batch()
    sched.wait_for_binds()
    assert res.scheduled == 3 and res.unschedulable == 1
    assert M.schedule_attempts.value(M.SCHEDULED) == before_sched + 3
    assert M.batch_size.count() == before_batches + 1
    assert M.device_solve_duration.count() >= 1
    # events: 3 Scheduled + 1 FailedScheduling
    assert len(rec.events()) >= 4
    reasons = {e.reason for e in rec.events()}
    assert {"Scheduled", "FailedScheduling"} <= reasons
    failed = [e for e in rec.events() if e.reason == "FailedScheduling"]
    assert failed[0].type == "Warning"


def test_metrics_server_scrape_and_healthz():
    srv = MetricsServer().start()
    try:
        with urllib.request.urlopen(srv.url + "/metrics", timeout=5) as r:
            body = r.read().decode()
            assert r.headers["Content-Type"].startswith("text/plain")
        assert "scheduler_schedule_attempts_total" in body
        assert "scheduler_e2e_scheduling_duration_seconds_bucket" in body
        with urllib.request.urlopen(srv.url + "/healthz", timeout=5) as r:
            assert r.read() == b"ok"
    finally:
        srv.stop()


def test_trace_logs_only_slow_cycles(caplog):
    t = Trace("fast_op", pods=1)
    t.step("a")
    assert t.log_if_long(threshold_s=10.0) is False
    slow = Trace("slow_op", pods=2)
    time.sleep(0.01)
    slow.step("phase one")
    with caplog.at_level(logging.WARNING, logger="kubernetes_tpu.trace"):
        assert slow.log_if_long(threshold_s=0.005) is True
    assert "slow_op" in caplog.text and "phase one" in caplog.text


def test_event_series_deduplication():
    rec = Recorder()
    for _ in range(5):
        rec.event("default/p", "FailedScheduling", "no fit", "Warning")
    evs = rec.events("default/p")
    assert len(evs) == 1 and evs[0].count == 5
