"""Columnar scheduler cache parity suite (state/columns.py + the
SchedulerCache columnar integration).

The tentpole's correctness pin: the columns, the lazily-materialized
NodeInfo views, and the device banks must all agree BIT-FOR-BIT after
every composition of bulk assume / forget / bind, node churn,
preemption eviction, and gang rollback — and a drain with the columnar
plane ON must schedule pod-for-pod identically to plane OFF (the
columns are bookkeeping/transport, never policy). Plus: lazy-view
staleness-by-generation, the vectorized cleanup_expired twin, the
journal bound, the kill switch, and the A/B microbench smoke.
"""

import time

import pytest

pytest.importorskip("jax")

from kubernetes_tpu.api.types import (
    Affinity,
    Container,
    ContainerPort,
    LabelSelector,
    PodAffinityTerm,
    PodAntiAffinity,
    Quantity,
    RESOURCE_CPU,
)
from kubernetes_tpu.models.generators import make_node, make_pod
from kubernetes_tpu.scheduler.driver import (
    Binder,
    POD_GROUP_LABEL,
    POD_GROUP_MIN_AVAILABLE,
    Scheduler,
)
from kubernetes_tpu.state.cache import SchedulerCache
from kubernetes_tpu.state.columns import JOURNAL_BOUND
from kubernetes_tpu.state.queue import PriorityQueue
from kubernetes_tpu.state.tensors import Vocab

HOST = "kubernetes.io/hostname"
ZONE = "zone"


def _nodes(n, zones=0, cpu=4000):
    out = []
    for i in range(n):
        labels = {HOST: f"n{i}"}
        if zones:
            labels[ZONE] = f"z{i % zones}"
        out.append(make_node(f"n{i}", cpu_milli=cpu, labels=labels))
    return out


def _anti_pod(name, app, cpu=100):
    p = make_pod(name, cpu_milli=cpu, labels={"app": app})
    p.affinity = Affinity(pod_anti_affinity=PodAntiAffinity(required=[
        PodAffinityTerm(
            label_selector=LabelSelector(match_labels={"app": app}),
            topology_key=HOST,
        )
    ]))
    return p


def _mk_cache(nodes, columnar=True, existing=(), **cache_kw):
    cache = SchedulerCache(**cache_kw)
    for n in nodes:
        cache.add_node(n)
    for p in existing:
        cache.add_pod(p)
    if columnar:
        cache.attach_columns(Vocab())
    return cache


def _raw_infos(cache):
    """The raw (unresolved) NodeInfo objects, keyed by name."""
    return {
        k: dict.__getitem__(cache.snapshot.node_infos, k)
        for k in cache.snapshot.node_infos
    }


def _assert_columns_exact(cache):
    div = cache._columns.object_divergence(_raw_infos(cache))
    assert div == [], div


def _mk_sched(nodes, existing=(), columnar=True, **kw):
    cache = SchedulerCache()
    for n in nodes:
        cache.add_node(n)
    for p in existing:
        cache.add_pod(p)
    binds = []
    binder = Binder(lambda pod, node: binds.append((pod.key(), node)))
    kw.setdefault("deterministic", True)
    sched = Scheduler(
        cache=cache, queue=PriorityQueue(), binder=binder,
        columnar_cache=columnar, **kw,
    )
    return sched, binds


def _drain(sched, rounds=60):
    total, assignments = 0, {}
    for _ in range(rounds):
        r = sched.schedule_batch()
        total += r.scheduled
        assignments.update(r.assignments)
        if (r.scheduled == 0 and r.unschedulable == 0 and r.errors == 0
                and r.deferred == 0):
            active, backoff, unsched = sched.queue.counts()
            if not (active + backoff + unsched):
                break
            time.sleep(0.06)
            sched.queue.move_all_to_active()
    sched.wait_for_binds()
    return total, assignments


# ---------------------------------------------------------------------------
# cache-level round-trips (no scheduler)
# ---------------------------------------------------------------------------

def test_bulk_assume_forget_bind_round_trip_parity():
    """assume → finish_bindings → forget over replicas sharing specs:
    columns and materialized views track exactly; after the full forget
    the columns are all-zero again."""
    cache = _mk_cache(_nodes(4, zones=2))
    pods = [
        make_pod(f"p{i}", cpu_milli=100 + (i % 4) * 10,
                 labels={"app": f"a{i % 4}"}).with_node(f"n{i % 4}")
        for i in range(32)
    ]
    assert cache.assume_pods(pods) == []
    cache.finish_bindings(pods)
    _assert_columns_exact(cache)
    cache.forget_pods(pods[16:])
    _assert_columns_exact(cache)
    cache.forget_pods(pods[:16])
    cols = cache._columns
    assert not cols.requested.any()
    assert not cols.pod_count.any()
    assert not cols.zone_pods.any()
    assert cache.pod_count() == 0


def test_ported_and_affinity_pods_hit_port_and_aff_columns():
    cache = _mk_cache(_nodes(2))
    ported = make_pod("web", cpu_milli=100)
    ported.containers = [Container(
        name="main", image="img",
        requests={RESOURCE_CPU: Quantity.parse("100m")},
        ports=[ContainerPort(container_port=8080, host_port=8080)],
    )]
    anti = _anti_pod("anti", app="solo")
    assert cache.assume_pods([ported.with_node("n0"), anti.with_node("n1")]) == []
    _assert_columns_exact(cache)
    cols = cache._columns
    assert cols.aff_count[cols.row_of["n1"]] == 1
    assert cols.host_port_conflict("n0", ported)
    assert not cols.host_port_conflict("n1", ported)
    cache.forget_pods([ported.with_node("n0"), anti.with_node("n1")])
    _assert_columns_exact(cache)


def test_node_churn_with_pending_journal():
    """remove_node on a node with an unmaterialized journal: the pop
    resolves the view first (pod states dropped correctly), the row is
    freed, and a new node reuses it cleanly."""
    cache = _mk_cache(_nodes(3, zones=3))
    pods = [make_pod(f"p{i}", cpu_milli=50).with_node(f"n{i % 3}") for i in range(9)]
    assert cache.assume_pods(pods) == []
    row_before = cache._columns.row_of["n1"]
    cache.remove_node("n1")
    assert cache.pod_count() == 6  # n1's three pods dropped with it
    assert "n1" not in cache._columns.row_of
    cache.add_node(make_node("n9", cpu_milli=4000, labels={HOST: "n9", ZONE: "z0"}))
    assert cache._columns.row_of["n9"] == row_before  # free-list reuse
    more = [make_pod(f"q{i}", cpu_milli=50).with_node("n9") for i in range(2)]
    assert cache.assume_pods(more) == []
    _assert_columns_exact(cache)


def test_preemption_evict_round_trip():
    """remove_pod (the victim-delete path) on both materialized and
    journal-pending pods keeps columns exact."""
    existing = []
    for i in range(4):
        v = make_pod(f"v{i}", cpu_milli=500, node_name=f"n{i % 2}")
        existing.append(v)
    cache = _mk_cache(_nodes(2), existing=existing)
    fresh = [make_pod(f"f{i}", cpu_milli=100).with_node(f"n{i % 2}") for i in range(4)]
    assert cache.assume_pods(fresh) == []
    # evict one pre-existing (materialized) and one journal-pending pod
    cache.remove_pod(existing[0])
    cache.remove_pod(fresh[0])
    _assert_columns_exact(cache)
    assert cache.pod_count() == 6


def test_lazy_view_staleness_by_generation():
    """Bulk ops advance row_gen without touching the view; the first
    read materializes and stamps the view's generation; a second read
    replays nothing."""
    cache = _mk_cache(_nodes(2))
    cols = cache._columns
    row = cols.row_of["n0"]
    ni_raw = _raw_infos(cache)["n0"]
    assert ni_raw.generation == 0
    pods = [make_pod(f"p{i}", cpu_milli=100).with_node("n0") for i in range(3)]
    assert cache.assume_pods(pods) == []
    # view untouched: the object cache is STALE by generation
    assert len(ni_raw.pods) == 0
    assert cols.row_stale_locked(row)
    assert int(cols.row_gen[row]) > ni_raw.generation
    # first resolved read materializes + stamps
    ni = cache.snapshot.get("n0")
    assert ni is ni_raw and len(ni.pods) == 3
    assert ni.generation == int(cols.row_gen[row])
    assert not cols.row_stale_locked(row)
    m0 = cols.stats_snapshot()["materializations"]
    cache.snapshot.get("n0")  # second read: no replay
    assert cols.stats_snapshot()["materializations"] == m0


def test_cleanup_expired_vectorized_matches_legacy_semantics():
    """The deadline-column cleanup expires exactly what the legacy walk
    would: finished-and-past-deadline pods only, with stale slots
    (informer confirm) dropped silently."""
    clock = [0.0]
    legacy = SchedulerCache(ttl=10.0, now=lambda: clock[0])
    colcache = _mk_cache([], columnar=False, ttl=10.0, now=lambda: clock[0])
    colcache.attach_columns(Vocab())
    for c in (legacy, colcache):
        c.add_node(make_node("n0", cpu_milli=4000, labels={HOST: "n0"}))
    pods = [make_pod(f"p{i}", cpu_milli=10).with_node("n0") for i in range(6)]
    for c in (legacy, colcache):
        assert c.assume_pods(pods) == []
        c.finish_bindings(pods[:4])     # 4 armed, 2 never finished
        c.add_pod(pods[0])              # informer confirms one armed pod
    clock[0] = 5.0
    assert legacy.cleanup_expired() == [] and colcache.cleanup_expired() == []
    clock[0] = 11.0
    exp_l = sorted(p.key() for p in legacy.cleanup_expired())
    exp_c = sorted(p.key() for p in colcache.cleanup_expired())
    assert exp_c == exp_l == [f"default/p{i}" for i in (1, 2, 3)]
    assert legacy.assumed_count() == colcache.assumed_count() == 2
    _assert_columns_exact(colcache)


def test_journal_bound_forces_materialization():
    """A never-read node's journal must not grow without bound: churning
    assume/forget past JOURNAL_BOUND materializes the row inline."""
    cache = _mk_cache(_nodes(1))
    cols = cache._columns
    row = cols.row_of["n0"]
    waves = (JOURNAL_BOUND // 64) + 2
    for w in range(waves):
        pods = [make_pod(f"w{w}p{i}", cpu_milli=1).with_node("n0") for i in range(32)]
        assert cache.assume_pods(pods) == []
        cache.forget_pods(pods)
    assert len(cols._pending[row] or ()) < JOURNAL_BOUND
    assert cols.stats_snapshot()["materializations"] > 0
    _assert_columns_exact(cache)


def test_kill_switch_leaves_legacy_cache(monkeypatch):
    monkeypatch.setenv("KTPU_COLUMNAR_CACHE", "0")
    sched, _ = _mk_sched(_nodes(2), enable_preemption=False, batch_size=4)
    assert sched.cache._columns is None
    assert not sched.columnar_cache
    for i in range(4):
        sched.queue.add(make_pod(f"p{i}", cpu_milli=100))
    n, _ = _drain(sched)
    assert n == 4
    sched.close()


def test_ingest_filters_pods_pseudo_resource():
    """Adopting a pre-populated cache must filter the 'pods' pseudo-
    resource exactly like every delta consumer does — otherwise the slot
    skews forever and the divergence probe never goes quiet."""
    from kubernetes_tpu.api.types import RESOURCE_PODS

    odd = make_pod("odd", cpu_milli=100, node_name="n0")
    odd.containers[0].requests[RESOURCE_PODS] = Quantity.parse(1)
    cache = _mk_cache(_nodes(2), existing=[odd])
    _assert_columns_exact(cache)
    more = [make_pod(f"p{i}", cpu_milli=50).with_node("n0") for i in range(2)]
    assert cache.assume_pods(more) == []
    _assert_columns_exact(cache)


def test_reattach_with_new_vocab_rebuilds_columns():
    """A second scheduler over the same cache brings its own Vocab with
    a different resource-slot order: attach_columns must REBUILD the
    columns (reusing the old spec rows would scatter old-slot matrices
    into new-slot banks)."""
    cache = _mk_cache([], columnar=False)
    cache.add_node(make_node("n0", cpu_milli=64_000, labels={HOST: "n0"}))
    gpu_pod = make_pod("g0", cpu_milli=100, node_name="n0")
    gpu_pod.containers[0].requests["example.com/gpu"] = Quantity.parse(2)
    fpga_pod = make_pod("f0", cpu_milli=100, node_name="n0")
    fpga_pod.containers[0].requests["example.com/fpga"] = Quantity.parse(1)
    cache.add_pod(gpu_pod)
    cache.add_pod(fpga_pod)
    v1 = Vocab()
    v1.slot_of_resource("example.com/gpu")  # gpu before fpga
    v1.slot_of_resource("example.com/fpga")
    cols1 = cache.attach_columns(v1)
    _assert_columns_exact(cache)
    v2 = Vocab()
    v2.slot_of_resource("example.com/fpga")  # REVERSED slot order
    v2.slot_of_resource("example.com/gpu")
    cols2 = cache.attach_columns(v2)
    assert cols2 is not cols1 and cols2.vocab is v2
    assert cache._columns is cols2
    _assert_columns_exact(cache)
    # same vocab again: idempotent
    assert cache.attach_columns(v2) is cols2
    # and bulk ops on the rebuilt columns stay exact
    more = [make_pod(f"m{i}", cpu_milli=50).with_node("n0") for i in range(3)]
    assert cache.assume_pods(more) == []
    _assert_columns_exact(cache)


def test_pod_key_memo_survives_clone_then_rename():
    """The controllers clone a template via with_node and then rename it
    (new_child_pod / StatefulSet ordinals): the key memo must invalidate
    on rename, never pin children to the template's identity."""
    template = make_pod("tmpl", cpu_milli=10, namespace="ctrl")
    assert template.key() == "ctrl/tmpl"  # seeds the memo
    child = template.with_node("")
    child.name = "tmpl-abc12"
    assert child.key() == "ctrl/tmpl-abc12"
    assert template.key() == "ctrl/tmpl"
    child.namespace = "other"
    assert child.key() == "other/tmpl-abc12"


def test_vocab_mismatched_columns_fall_back_on_mirror_paths():
    """Columns rebuilt on a foreign Vocab (second-scheduler re-attach)
    must NOT feed this mirror's delta gather, fold planning, or the
    divergence cross-check — slot orders differ. Everything falls back
    to the per-pod build and the banks stay exact."""
    from kubernetes_tpu.commit.fold import plan_fold
    from kubernetes_tpu.state.cache import TensorMirror

    cache = SchedulerCache()
    cache.add_node(make_node("n0", cpu_milli=64_000, labels={HOST: "n0"}))
    mirror = TensorMirror(cache)
    # foreign vocab with a REVERSED extended-resource slot order
    foreign = Vocab()
    foreign.slot_of_resource("example.com/fpga")
    foreign.slot_of_resource("example.com/gpu")
    cache.attach_columns(foreign)
    mirror.vocab.slot_of_resource("example.com/gpu")
    mirror.vocab.slot_of_resource("example.com/fpga")
    gpu = make_pod("g0", cpu_milli=100)
    gpu.containers[0].requests["example.com/gpu"] = Quantity.parse(2)
    prog = plan_fold(mirror, [(gpu, mirror.row_of["n0"])], 16, 16)
    # the fold planned from the PER-POD build in the mirror's slot space
    gpu_slot = mirror.vocab.resource_slot["example.com/gpu"]
    assert prog is not None and int(prog.req[0, gpu_slot]) == 2
    assert cache.assume_pods([gpu.with_node("n0")]) == []
    mirror.sync()
    mirror.device_arrays()
    div = mirror.device_bank_divergence()  # cross-check must not false-fire
    assert div == [], div
    assert int(mirror.nodes.requested[mirror.row_of["n0"], gpu_slot]) == 2


# ---------------------------------------------------------------------------
# plane ON == plane OFF, pod for pod (drains through the real driver)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("scenario", ["mixed", "gang", "churn", "preempt"])
def test_columnar_off_schedules_identically(scenario):
    def build(sched):
        if scenario == "mixed":
            for i in range(12):
                if i % 3 == 0:
                    sched.queue.add(_anti_pod(f"a{i}", app=f"g{i % 2}"))
                else:
                    sched.queue.add(make_pod(f"p{i}", cpu_milli=100))
        elif scenario == "gang":
            for m in range(2):
                sched.queue.add(make_pod(
                    f"gm{m}", cpu_milli=100,
                    labels={POD_GROUP_LABEL: "g1", POD_GROUP_MIN_AVAILABLE: "4"},
                ))
            for i in range(8):
                sched.queue.add(make_pod(f"p{i}", cpu_milli=100))
        elif scenario == "churn":
            for i in range(8):
                sched.queue.add(make_pod(f"p{i}", cpu_milli=100))
        elif scenario == "preempt":
            for i in range(3):
                p = make_pod(f"hi{i}", cpu_milli=800)
                p.priority = 1000
                sched.queue.add(p)

    def run(columnar):
        existing = []
        enable_preemption = scenario == "preempt"
        nodes = _nodes(6, zones=3)
        if scenario == "preempt":
            nodes = _nodes(3, cpu=1000)
            for i, nd in enumerate(nodes):
                v = make_pod(f"victim{i}", cpu_milli=900, node_name=nd.name)
                v.priority = 0
                existing.append(v)
        sched, _ = _mk_sched(
            nodes, existing=existing, columnar=columnar,
            enable_preemption=enable_preemption, batch_size=8,
        )
        build(sched)
        if scenario == "churn":
            r = sched.schedule_batch()
            sched.cache.remove_node("n3")
            sched.cache.add_node(
                make_node("n9", cpu_milli=4000, labels={HOST: "n9", ZONE: "z0"})
            )
            for i in range(8, 16):
                sched.queue.add(make_pod(f"p{i}", cpu_milli=100))
            n, asg = _drain(sched)
            n += r.scheduled
            asg.update(r.assignments)
        else:
            n, asg = _drain(sched)
        # settle + bank parity (the divergence probe includes the
        # vectorized columns cross-check when columns are attached)
        sched._commit_pipe.drain()
        sched.mirror.sync()
        sched.mirror.device_arrays()
        div = sched.mirror.device_bank_divergence()
        if columnar:
            _assert_columns_exact(sched.cache)
        sched.close()
        return n, asg, div

    n_on, asg_on, div_on = run(True)
    n_off, asg_off, div_off = run(False)
    assert n_on == n_off
    assert asg_on == asg_off
    assert div_on == [] and div_off == []


# ---------------------------------------------------------------------------
# microbench smoke + divergence probe sensitivity
# ---------------------------------------------------------------------------

def test_microbench_cache_smoke():
    """Tier-1 wiring for scripts/microbench_cache.py: the A/B must run
    and agree bit-for-bit (asserted inside main); timings are reported,
    not asserted (CPU CI jitter)."""
    import os
    import sys

    scripts = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "scripts"
    )
    if scripts not in sys.path:
        sys.path.insert(0, scripts)
    import microbench_cache

    out = microbench_cache.main(smoke=True)
    assert out["update_columnar_ms"] >= 0 and out["update_object_ms"] >= 0
    assert out["cycle_columnar_ms"] >= 0 and out["cycle_object_ms"] >= 0
    assert out["columnar_stats"]["bulk_pods"] > 0


def test_columnar_divergence_probe_detects_skew():
    """The vectorized columns-vs-banks cross-check must actually FIRE on
    a forced skew (a probe that can't fail guards nothing)."""
    sched, _ = _mk_sched(_nodes(2), enable_preemption=False, batch_size=4)
    for i in range(4):
        sched.queue.add(make_pod(f"p{i}", cpu_milli=100))
    n, _ = _drain(sched)
    assert n == 4
    sched._commit_pipe.drain()
    sched.mirror.sync()
    sched.mirror.device_arrays()
    assert sched.mirror.device_bank_divergence() == []
    cols = sched.cache._columns
    with sched.cache._lock:
        cols.pod_count[cols.row_of["n0"]] += 1  # forced skew
    div = sched.mirror.device_bank_divergence()
    assert any(d.startswith("columns.") for d in div), div
    with sched.cache._lock:
        cols.pod_count[cols.row_of["n0"]] -= 1
    assert sched.mirror.device_bank_divergence() == []
    sched.close()
