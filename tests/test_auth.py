"""Authn (bearer tokens) + RBAC authz on the apiserver HTTP front door.
Reference anchors: DefaultBuildHandlerChain
(staging/src/k8s.io/apiserver/pkg/server/config.go:539) — authentication
then authorization before anything else; RBAC evaluation
plugin/pkg/auth/authorizer/rbac/rbac.go:74; bootstrap policy
plugin/pkg/auth/authorizer/rbac/bootstrappolicy/policy.go.

Deny-by-default is the contract: an unauthenticated request is 401, an
authenticated-but-unbound one is 403, and the full scheduler loop runs
with every component presenting its own identity."""

import time

import pytest

pytest.importorskip("jax")

from kubernetes_tpu.api.types import (
    ClusterRole,
    ClusterRoleBinding,
    PolicyRule,
    Role,
    RoleBinding,
    RoleRef,
    Subject,
)
from kubernetes_tpu.apiserver import (
    APIServerHTTP,
    FakeAPIServer,
    ForbiddenError,
    RBACAuthorizer,
    TokenAuthenticator,
    UnauthorizedError,
    UserInfo,
    install_bootstrap_rbac,
)
from kubernetes_tpu.apiserver.auth import (
    GROUP_MASTERS,
    GROUP_NODES,
    USER_SCHEDULER,
)
from kubernetes_tpu.client import Informer, RemoteAPIServer
from kubernetes_tpu.models.generators import make_node, make_pod

ADMIN, SCHED, NODE, NOBODY, DEV = "tok-admin", "tok-sched", "tok-node", "tok-nobody", "tok-dev"


@pytest.fixture()
def secured():
    store = FakeAPIServer()
    install_bootstrap_rbac(store)
    authn = TokenAuthenticator({
        ADMIN: UserInfo("admin", (GROUP_MASTERS,)),
        SCHED: UserInfo(USER_SCHEDULER),
        NODE: UserInfo("system:node:n0", (GROUP_NODES,)),
        NOBODY: UserInfo("nobody"),
        DEV: UserInfo("dev-user"),
    })
    srv = APIServerHTTP(store, authenticator=authn,
                        authorizer=RBACAuthorizer(store)).start()
    yield store, srv
    srv.stop()


def _client(srv, token=None):
    return RemoteAPIServer(srv.url, token=token)


# ---------------------------------------------------------------------------
# authentication
# ---------------------------------------------------------------------------

def test_unauthenticated_is_401(secured):
    _, srv = secured
    with pytest.raises(UnauthorizedError):
        _client(srv).list("pods")
    with pytest.raises(UnauthorizedError):
        _client(srv, token="no-such-token").list("pods")
    with pytest.raises(UnauthorizedError):
        _client(srv).create("pods", make_pod("x"))
    with pytest.raises(UnauthorizedError):
        _client(srv).watch("pods", 0)


def test_authenticated_unbound_is_403(secured):
    _, srv = secured
    c = _client(srv, token=NOBODY)
    with pytest.raises(ForbiddenError):
        c.list("pods")
    with pytest.raises(ForbiddenError):
        c.create("pods", make_pod("x"))
    with pytest.raises(ForbiddenError):
        c.delete("nodes", "n0")


def test_masters_group_is_cluster_admin(secured):
    store, srv = secured
    c = _client(srv, token=ADMIN)
    c.create("nodes", make_node("n0"))
    c.create("pods", make_pod("a"))
    assert [p.name for p in c.list("pods")[0]] == ["a"]
    c.delete("pods", "default/a")


# ---------------------------------------------------------------------------
# RBAC evaluation
# ---------------------------------------------------------------------------

def test_namespaced_role_binding_scopes_to_its_namespace(secured):
    store, srv = secured
    store.create("roles", Role(
        name="pod-writer", namespace="dev",
        rules=[PolicyRule(verbs=["create", "get", "delete"], resources=["pods"])],
    ))
    store.create("rolebindings", RoleBinding(
        name="dev-writers", namespace="dev",
        role_ref=RoleRef(kind="Role", name="pod-writer"),
        subjects=[Subject(kind="User", name="dev-user")],
    ))
    c = _client(srv, token=DEV)
    p = make_pod("inns")
    p.namespace = "dev"
    c.create("pods", p)  # allowed: binding's namespace
    assert c.get("pods", "dev/inns").name == "inns"
    other = make_pod("elsewhere")
    other.namespace = "prod"
    with pytest.raises(ForbiddenError):
        c.create("pods", other)  # same verb+resource, wrong namespace
    with pytest.raises(ForbiddenError):
        c.list("pods")  # cluster-wide list needs cluster-level grant


def test_rolebinding_can_reference_clusterrole(secured):
    store, srv = secured
    store.create("clusterroles", ClusterRole(
        name="pod-reader",
        rules=[PolicyRule(verbs=["get"], resources=["pods"])],
    ))
    store.create("rolebindings", RoleBinding(
        name="dev-readers", namespace="dev",
        role_ref=RoleRef(kind="ClusterRole", name="pod-reader"),
        subjects=[Subject(kind="User", name="dev-user")],
    ))
    p = make_pod("target")
    p.namespace = "dev"
    store.create("pods", p)
    q = make_pod("target")
    q.namespace = "prod"
    store.create("pods", q)
    c = _client(srv, token=DEV)
    assert c.get("pods", "dev/target").name == "target"
    with pytest.raises(ForbiddenError):
        c.get("pods", "prod/target")  # grant is namespaced by the binding


def test_serviceaccount_subject(secured):
    store, srv = secured
    store.create("clusterroles", ClusterRole(
        name="ci-role", rules=[PolicyRule(verbs=["list"], resources=["pods"])]))
    store.create("clusterrolebindings", ClusterRoleBinding(
        name="ci-binding",
        role_ref=RoleRef(kind="ClusterRole", name="ci-role"),
        subjects=[Subject(kind="ServiceAccount", name="ci", namespace="infra")],
    ))
    # a token whose user follows the serviceaccount username convention
    srv_authn = srv._srv.RequestHandlerClass.authenticator
    srv_authn.add("tok-ci", UserInfo("system:serviceaccount:infra:ci"))
    c = _client(srv, token="tok-ci")
    assert c.list("pods")[0] == []
    with pytest.raises(ForbiddenError):
        c.create("pods", make_pod("x"))


def test_scheduler_identity_can_bind_but_not_mutate_cluster(secured):
    store, srv = secured
    store.create("nodes", make_node("n0"))
    store.create("pods", make_pod("todo"))
    c = _client(srv, token=SCHED)
    assert [p.name for p in c.list("pods")[0]] == ["todo"]
    c.bind("default", "todo", "n0")  # pods/binding create
    assert store.get("pods", "default/todo").node_name == "n0"
    with pytest.raises(ForbiddenError):
        c.delete("nodes", "n0")
    with pytest.raises(ForbiddenError):
        c.create("nodes", make_node("n1"))


def test_kubelet_identity_heartbeats_but_cannot_admin(secured):
    store, srv = secured
    c = _client(srv, token=NODE)
    c.create("nodes", make_node("n0"))  # register itself
    n = c.get("nodes", "n0")
    c.update("nodes", n)  # heartbeat
    with pytest.raises(ForbiddenError):
        c.delete("nodes", "n0")
    with pytest.raises(ForbiddenError):
        c.create("clusterrolebindings", ClusterRoleBinding(
            name="evil", role_ref=RoleRef(name="cluster-admin"),
            subjects=[Subject(kind="Group", name=GROUP_NODES)]))


def test_wildcard_subresource_rule():
    # rbac.go ResourceMatches: "pods/*" covers "pods/binding"; bare
    # "pods" does NOT
    from kubernetes_tpu.apiserver.auth import _rule_allows

    assert _rule_allows(PolicyRule(verbs=["create"], resources=["pods/*"]),
                        "create", "pods/binding")
    assert not _rule_allows(PolicyRule(verbs=["create"], resources=["pods"]),
                            "create", "pods/binding")
    assert _rule_allows(PolicyRule(verbs=["*"], resources=["*"]),
                        "delete", "anything")


# ---------------------------------------------------------------------------
# the suite's own loop, fully authenticated
# ---------------------------------------------------------------------------

def test_scheduler_loop_fully_authenticated(secured):
    """Informers + bind run over HTTP with the scheduler's own identity;
    node registration uses the kubelet identity; pod creation the admin
    identity — no open-door path anywhere."""
    store, srv = secured
    kubelet = _client(srv, token=NODE)
    kubelet.create("nodes", make_node("n0", cpu_milli=4000, mem=8 * 2**30))
    admin = _client(srv, token=ADMIN)
    admin.create("pods", make_pod("w", cpu_milli=100, mem=2**20))

    sched_client = _client(srv, token=SCHED)
    inf = Informer(sched_client, "pods")
    seen = []
    inf.add_event_handler(on_add=lambda p: seen.append(p.name))
    inf.start()
    assert inf.wait_for_sync()
    assert seen == ["w"]
    sched_client.bind("default", "w", "n0")
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        if (inf.get("default/w") or make_pod("w")).node_name == "n0":
            break
        time.sleep(0.05)
    assert inf.get("default/w").node_name == "n0"
    inf.stop()


def test_put_body_namespace_cannot_bypass_rbac(secured):
    """Advisor finding #1 (high): do_PUT authorized the URL-path namespace
    but keyed the write by the BODY's namespace/name — a user bound only
    in 'dev' could overwrite any 'prod' object via
    PUT /api/v1/pods/dev/x with a body claiming prod. Must be 400 and the
    prod object untouched."""
    import json as _json
    import urllib.error
    import urllib.request

    from kubernetes_tpu.api.types import pod_to_k8s

    store, srv = secured
    target = make_pod("target")
    target.namespace = "prod"
    store.create("pods", target)
    store.create("roles", Role(
        name="pod-writer", namespace="dev",
        rules=[PolicyRule(verbs=["create", "get", "update"], resources=["pods"])],
    ))
    store.create("rolebindings", RoleBinding(
        name="dev-writers", namespace="dev",
        role_ref=RoleRef(kind="Role", name="pod-writer"),
        subjects=[Subject(kind="User", name="dev-user")],
    ))
    mine = make_pod("x")
    mine.namespace = "dev"
    _client(srv, token=DEV).create("pods", mine)
    evil = pod_to_k8s(store.get("pods", "prod/target"))
    evil["spec"]["nodeName"] = "pwned"
    evil["metadata"].pop("resourceVersion", None)
    req = urllib.request.Request(
        srv.url + "/api/v1/pods/dev/x", data=_json.dumps(evil).encode(),
        method="PUT", headers={"Content-Type": "application/json",
                               "Authorization": f"Bearer {DEV}"},
    )
    try:
        with urllib.request.urlopen(req) as resp:
            code = resp.status
    except urllib.error.HTTPError as e:
        code = e.code
    assert code == 400
    assert store.get("pods", "prod/target").node_name != "pwned"


def _raw_get(srv, path, token):
    import urllib.error
    import urllib.request

    req = urllib.request.Request(
        srv.url + path, headers={"Authorization": f"Bearer {token}"}
    )
    try:
        with urllib.request.urlopen(req) as resp:
            import json as _json

            return resp.status, _json.loads(resp.read() or b"{}")
    except urllib.error.HTTPError as e:
        return e.code, {}


def test_namespaced_list_authorized_against_request_namespace(secured):
    """Advisor finding #6 (ISSUE 2 satellite): list/watch used to be
    authorized at cluster scope only, so a user with only a namespaced
    RoleBinding could never list even their own namespace. The namespaced
    routes (/api/v1/namespaces/{ns}/{kind}) authorize against the request
    namespace and restrict results to it."""
    store, srv = secured
    store.create("roles", Role(
        name="pod-reader", namespace="dev",
        rules=[PolicyRule(verbs=["list", "watch", "get"], resources=["pods"])],
    ))
    store.create("rolebindings", RoleBinding(
        name="dev-readers", namespace="dev",
        role_ref=RoleRef(kind="Role", name="pod-reader"),
        subjects=[Subject(kind="User", name="dev-user")],
    ))
    mine = make_pod("mine")
    mine.namespace = "dev"
    store.create("pods", mine)
    other = make_pod("other")
    other.namespace = "prod"
    store.create("pods", other)
    # namespaced list: authorized by the dev RoleBinding, dev objects only
    code, body = _raw_get(srv, "/api/v1/namespaces/dev/pods", DEV)
    assert code == 200
    names = [i["metadata"]["name"] for i in body["items"]]
    assert names == ["mine"]
    # same verb+resource in a namespace without a binding: 403
    code, _ = _raw_get(srv, "/api/v1/namespaces/prod/pods", DEV)
    assert code == 403
    # cluster-scope list still needs a cluster-level grant: 403
    code, _ = _raw_get(srv, "/api/v1/pods", DEV)
    assert code == 403
    # the namespaced item path works and authorizes per namespace
    code, body = _raw_get(srv, "/api/v1/namespaces/dev/pods/mine", DEV)
    assert code == 200 and body["metadata"]["name"] == "mine"
    code, _ = _raw_get(srv, "/api/v1/namespaces/prod/pods/other", DEV)
    assert code == 403


def test_namespaced_watch_filters_foreign_namespaces(secured):
    """A namespaced watch streams only the authorized namespace's events
    (objects in other namespaces must never cross the wire)."""
    import json as _json
    import urllib.request

    store, srv = secured
    store.create("roles", Role(
        name="pod-reader", namespace="dev",
        rules=[PolicyRule(verbs=["list", "watch"], resources=["pods"])],
    ))
    store.create("rolebindings", RoleBinding(
        name="dev-readers", namespace="dev",
        role_ref=RoleRef(kind="Role", name="pod-reader"),
        subjects=[Subject(kind="User", name="dev-user")],
    ))
    a = make_pod("visible")
    a.namespace = "dev"
    store.create("pods", a)
    b = make_pod("hidden")
    b.namespace = "prod"
    store.create("pods", b)
    req = urllib.request.Request(
        srv.url + "/api/v1/namespaces/dev/pods?watch=1&resourceVersion=0"
        "&timeoutSeconds=1",
        headers={"Authorization": f"Bearer {DEV}"},
    )
    with urllib.request.urlopen(req, timeout=5) as resp:
        assert resp.status == 200
        raw = resp.read().decode()
    events = [_json.loads(line) for line in raw.splitlines() if line.strip()]
    names = [e["object"]["metadata"]["name"] for e in events]
    assert "visible" in names
    assert "hidden" not in names
    # an unbound namespace's watch is denied outright
    code, _ = _raw_get(
        srv, "/api/v1/namespaces/prod/pods?watch=1&timeoutSeconds=1", DEV
    )
    assert code == 403


def test_namespaced_create_defaults_and_validates_namespace(secured):
    """POST /api/v1/namespaces/{ns}/{kind}: the body namespace defaults to
    the path; a conflicting one is a 400 (no cross-namespace smuggling)."""
    import json as _json
    import urllib.error
    import urllib.request

    from kubernetes_tpu.api.types import pod_to_k8s

    store, srv = secured
    store.create("roles", Role(
        name="pod-writer", namespace="dev",
        rules=[PolicyRule(verbs=["create"], resources=["pods"])],
    ))
    store.create("rolebindings", RoleBinding(
        name="dev-writers", namespace="dev",
        role_ref=RoleRef(kind="Role", name="pod-writer"),
        subjects=[Subject(kind="User", name="dev-user")],
    ))

    def post(body):
        req = urllib.request.Request(
            srv.url + "/api/v1/namespaces/dev/pods",
            data=_json.dumps(body).encode(), method="POST",
            headers={"Content-Type": "application/json",
                     "Authorization": f"Bearer {DEV}"},
        )
        try:
            with urllib.request.urlopen(req) as resp:
                return resp.status
        except urllib.error.HTTPError as e:
            return e.code

    clean = pod_to_k8s(make_pod("fresh"))
    clean["metadata"].pop("namespace", None)
    assert post(clean) == 201
    assert store.get("pods", "dev/fresh").namespace == "dev"
    smuggle = pod_to_k8s(make_pod("sneaky"))
    smuggle["metadata"]["namespace"] = "prod"
    assert post(smuggle) == 400
    with pytest.raises(KeyError):
        store.get("pods", "prod/sneaky")


def test_token_auth_file_parsing():
    """Advisor finding #3: malformed --token-auth-file lines must be a
    clear configuration error (line number, expected format), not an
    IndexError; empty tokens/users never silently authenticate."""
    import os
    import tempfile

    from kubernetes_tpu.cmd import load_token_auth_file

    def write(content):
        f = tempfile.NamedTemporaryFile(
            "w", suffix=".csv", delete=False)
        f.write(content)
        f.close()
        return f.name

    good = write("# comment\n\ntok1,alice,grp1|grp2\ntok2,bob\n"
                 'tok3,"Smith, Alice",ops\n')
    tokens = load_token_auth_file(good)
    assert tokens["tok1"].name == "alice" and tokens["tok1"].groups == ("grp1", "grp2")
    assert tokens["tok2"].name == "bob" and tokens["tok2"].groups == ()
    # quoted CSV field containing a comma (encoding/csv semantics)
    assert tokens["tok3"].name == "Smith, Alice" and tokens["tok3"].groups == ("ops",)
    for bad, frag in (
        ("justonetoken\n", ":1"),
        ("tok,alice\nno-comma-line\n", ":2"),
        (",alice\n", ":1"),  # empty token
        ("tok,\n", ":1"),  # empty user
    ):
        path = write(bad)
        with pytest.raises(ValueError) as ei:
            load_token_auth_file(path)
        assert frag in str(ei.value)
        os.unlink(path)
    os.unlink(good)
