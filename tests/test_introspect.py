"""Steady-state health plane (kubernetes_tpu/obs/introspect): the unified
plane census, the /debug/ktpu route, always-on queue gauges, sampled
shadow audits (incl. the forced-skew divergent path), the perf-budget
gate's fail-closed semantics, ktpu_top rendering from both sources, and
black-box dump-dir hygiene.

The monitor-ON drain with overhead/audit/coverage acceptance lives in
test_perf_smoke.test_perf_smoke_health_monitor (the audited full drain);
this module pins the mechanics with a small shared warmed scheduler.
"""

import json
import os
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

import pytest

pytest.importorskip("jax")

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SCRIPTS = os.path.join(_REPO, "scripts")
if _SCRIPTS not in sys.path:
    sys.path.insert(0, _SCRIPTS)

from kubernetes_tpu.metrics import MetricsServer, metrics as M  # noqa: E402
from kubernetes_tpu.obs import introspect  # noqa: E402
from kubernetes_tpu.obs.recorder import FlightRecorder  # noqa: E402
from kubernetes_tpu.state.queue import PriorityQueue  # noqa: E402


def _mk_pods(n, base=0, anti_every=6):
    import bench
    from kubernetes_tpu.api.types import (
        Affinity,
        LabelSelector,
        PodAffinityTerm,
        PodAntiAffinity,
    )

    pods = []
    for i in range(n):
        if anti_every and i % anti_every == 0:
            p = bench.mk_pod(base + i, cpu="100m", mem="64Mi",
                             labels={"exclusive": f"ix{base + i}"})
            p.affinity = Affinity(pod_anti_affinity=PodAntiAffinity(required=[
                PodAffinityTerm(
                    label_selector=LabelSelector(
                        match_labels={"exclusive": p.labels["exclusive"]}
                    ),
                    topology_key="kubernetes.io/hostname",
                )
            ]))
        else:
            p = bench.mk_pod(base + i, cpu="100m", mem="64Mi")
        pods.append(p)
    return pods


@pytest.fixture(scope="module")
def warmed():
    """One warmed, drained scheduler with a (thread-stopped) health
    monitor attached — shared by the census/route/audit tests."""
    import bench
    from kubernetes_tpu.scheduler.driver import Binder, Scheduler
    from kubernetes_tpu.state.cache import SchedulerCache

    cache = SchedulerCache()
    for i in range(4):
        cache.add_node(bench.mk_node(i, zone=bench.ZONES[i % 4]))
    queue = PriorityQueue()
    sched = Scheduler(
        cache=cache, queue=queue, binder=Binder(), batch_size=16,
        enable_preemption=False, spec_depth=2,
    )
    sched.mirror.reserve(4, 160)
    for p in _mk_pods(48):
        queue.add(p)
    sched.warmup()
    # start=False: tests drive refresh()/audits deterministically inline;
    # the monitor THREAD is exercised by the perf_smoke health mode
    mon = sched.enable_health_monitor(
        interval=0.05, audit_every=2, start=False
    )
    res = sched.run_until_empty()
    sched.wait_for_binds()
    assert res.scheduled == 48
    yield sched, mon
    sched.close()


# ---------------------------------------------------------------------------
# the unified census + schema
# ---------------------------------------------------------------------------

def test_census_covers_all_planes_and_validates(warmed):
    sched, mon = warmed
    doc = introspect.census(sched)
    assert introspect.validate_census(doc) == []
    planes = doc["planes"]
    assert set(introspect.REQUIRED_PLANES) <= set(planes)
    # a warmed drained scheduler has real occupancy everywhere
    assert planes["ingest"]["capacity"] > 0
    assert planes["terms"]["capacity"] > 0
    assert planes["cache"]["nodes"] == 4
    assert planes["cache"]["columns"]["rows"] == 4
    assert planes["mirror"]["device_resident"] is True
    assert planes["mirror"]["node_rows"] == 4
    assert planes["compile"]["warmed"] is True
    assert planes["compile"]["kinds"], "per-kind ladder census is empty"
    assert planes["queue"]["active"] == 0
    assert doc["monitor"]["shadow_audits"] is not None
    json.dumps(doc, default=str)  # the route's serialization contract


def test_validate_census_catches_structural_breaks(warmed):
    sched, _ = warmed
    doc = introspect.census(sched)
    bad = json.loads(json.dumps(doc, default=str))
    bad["version"] = 99
    assert any("version" in p for p in introspect.validate_census(bad))
    bad = json.loads(json.dumps(doc, default=str))
    del bad["planes"]["mirror"]
    assert any("mirror" in p for p in introspect.validate_census(bad))
    bad = json.loads(json.dumps(doc, default=str))
    del bad["planes"]["queue"]["oldest_pending_age_s"]
    assert any(
        "oldest_pending_age_s" in p for p in introspect.validate_census(bad)
    )


def test_export_gauges_projects_census(warmed):
    sched, mon = warmed
    doc = mon.refresh()  # inline refresh: census -> gauges
    assert introspect.validate_census(doc) == []
    assert M.plane_slab_occupancy.value("ingest") > 0
    assert M.plane_slab_capacity.value("ingest") >= 256
    assert M.plane_slab_occupancy.value("mirror_nodes") == 4
    assert M.plane_slab_capacity.value("columns") >= 4
    assert "ktpu_compile_ladder_rungs{" in M.registry.expose_text()
    assert M.health_refresh.value() >= 1


# ---------------------------------------------------------------------------
# sampled shadow audits: clean + forced-skew divergent
# ---------------------------------------------------------------------------

def test_shadow_audit_clean_then_forced_skew_divergent(warmed):
    sched, mon = warmed
    m = sched.mirror
    sched._commit_pipe.drain()
    m.sync()
    m.device_arrays()
    assert mon.run_shadow_audit() == []  # healthy drain: clean
    clean_before = M.shadow_audit.value("clean")
    assert clean_before >= 1
    # forced skew: perturb HOST truth so device + columns both disagree
    m.nodes.requested[0, 0] += 1
    try:
        div = mon.run_shadow_audit()
        assert div, "forced skew not detected"
        assert M.shadow_audit.value("divergent") >= 1
        block = mon.census_block()
        assert block["shadow_audits"]["divergent"] >= 1
        assert block["last_divergence"]  # detail lands in /debug/ktpu
        doc = introspect.census(sched)
        assert doc["monitor"]["last_divergence"]
    finally:
        m.nodes.requested[0, 0] -= 1
    assert mon.run_shadow_audit() == []  # restored: clean again


def test_audit_due_bookkeeping_schedules_at_driver_hook(warmed):
    sched, mon = warmed
    counts0 = mon.audit_counts()
    mon.refresh()  # audit_every=2: first refresh arms nothing...
    mon.refresh()  # ...second marks due
    mon.driver_sync_hook()  # the driver's safe point executes it
    counts1 = mon.audit_counts()
    assert sum(counts1.values()) == sum(counts0.values()) + 1


# ---------------------------------------------------------------------------
# /debug/ktpu route
# ---------------------------------------------------------------------------

def _get(url, timeout=5):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def test_debug_route_503_before_warmup_consistent_with_readyz():
    from kubernetes_tpu.scheduler.driver import Binder, Scheduler

    cold = Scheduler(binder=Binder(), enable_preemption=False)
    srv = MetricsServer(
        port=0, ready_fn=lambda: cold.ready,
        debug_fn=lambda: introspect.census(cold),
    ).start()
    try:
        ready_code, _ = _get(f"{srv.url}/readyz")
        debug_code, _ = _get(f"{srv.url}/debug/ktpu")
        assert ready_code == 503
        assert debug_code == 503  # same gate, by construction
    finally:
        srv.stop()
        cold.close()


def test_debug_route_serves_schema_valid_census(warmed):
    import ktpu_top

    sched, mon = warmed
    srv = MetricsServer(
        port=0, ready_fn=lambda: sched.ready,
        debug_fn=lambda: introspect.census(sched),
    ).start()
    try:
        code, body = _get(f"{srv.url}/readyz")
        assert code == 200
        code, body = _get(f"{srv.url}/debug/ktpu")
        assert code == 200
        doc = json.loads(body)
        assert introspect.validate_census(doc) == []
        # ktpu_top renders a live table from BOTH sources over HTTP
        top = ktpu_top.snapshot_from_debug(srv.url)
        assert "ingest" in top and "mirror_nodes" in top
        mon.refresh()  # ensure the gauges reflect this scheduler
        top = ktpu_top.snapshot_from_metrics(srv.url)
        assert "ingest" in top and "mirror_nodes" in top
    finally:
        srv.stop()


def test_debug_route_answers_during_drain_without_blocking(warmed):
    """The census must answer with bounded latency while the driver is
    mid-drain — its snapshots hold each plane lock only for a counter
    walk, never for device work."""
    sched, _ = warmed
    for p in _mk_pods(64, base=50_000, anti_every=0):
        sched.queue.add(p)
    srv = MetricsServer(
        port=0, ready_fn=lambda: sched.ready,
        debug_fn=lambda: introspect.census(sched),
    ).start()
    codes, lats, errors = [], [], []
    stop = threading.Event()

    def scrape():
        while not stop.is_set():
            t0 = time.perf_counter()
            try:
                with urllib.request.urlopen(
                    f"{srv.url}/debug/ktpu", timeout=10
                ) as r:
                    codes.append(r.status)
                    json.loads(r.read().decode())
            except Exception as e:  # noqa: BLE001 - recorded for the assert
                errors.append(repr(e))
            lats.append(time.perf_counter() - t0)
            time.sleep(0.01)

    t = threading.Thread(target=scrape, name="debug-scraper")
    t.start()
    try:
        res = sched.run_until_empty()
        sched.wait_for_binds()
    finally:
        stop.set()
        t.join(timeout=10)
        srv.stop()
    assert res.scheduled == 64
    assert not errors, errors[:3]
    assert codes and all(c == 200 for c in codes)
    assert max(lats) < 2.0, f"census latency p100 {max(lats):.3f}s"


# ---------------------------------------------------------------------------
# kube-shaped queue gauges (oldest-pending age on the queue's own clock)
# ---------------------------------------------------------------------------

def test_queue_oldest_age_pinned_across_add_pop_requeue():
    import bench

    t = {"now": 100.0}
    q = PriorityQueue(now=lambda: t["now"])
    p1 = bench.mk_pod(1, cpu="100m", mem="64Mi")
    p2 = bench.mk_pod(2, cpu="100m", mem="64Mi")
    assert q.oldest_pending_age() == 0.0  # empty queue
    q.add(p1)  # timestamp 100
    t["now"] = 103.0
    q.add(p2)  # timestamp 103
    t["now"] = 104.0
    assert q.oldest_pending_age() == pytest.approx(4.0)
    cen = q.census()
    assert cen["active"] == 2
    assert cen["oldest_pending_age_s"] == pytest.approx(4.0)
    # the gauges project from the census (observed OUTSIDE the lock)
    introspect.export_gauges({"planes": {"queue": cen}})
    assert M.pending_pods.value("active") == 2
    assert M.queue_oldest_pending_age.value() == pytest.approx(4.0)
    # pop the oldest: age re-anchors on the remaining entry
    batch = q.pop_batch(1)
    assert batch[0].pod.key() == p1.key()
    assert q.oldest_pending_age() == pytest.approx(1.0)  # p2, queued at 103
    # requeue (defer verdict): the original enqueue timestamp survives,
    # so the entry's age resumes, not restarts
    q.requeue(batch)
    t["now"] = 107.0
    assert q.oldest_pending_age() == pytest.approx(7.0)
    q.delete(p1)
    q.delete(p2)
    assert q.oldest_pending_age() == 0.0


# ---------------------------------------------------------------------------
# perf-budget gate: fails closed
# ---------------------------------------------------------------------------

def test_perf_gate_committed_budget_is_structurally_sound():
    import perf_gate

    budget = perf_gate.load_budget()
    assert perf_gate.check(budget, {"stage_p99_s": {}, "counters": {}}) == []


def test_perf_gate_fails_closed_on_injected_regression():
    import perf_gate

    budget = perf_gate.load_budget()
    obs = {"stage_p99_s": {"dispatch": float("inf")}, "counters": {}}
    assert any("dispatch" in p for p in perf_gate.check(budget, obs))
    obs = {"stage_p99_s": {}, "counters": {"misses_after_warmup": 3}}
    assert any("misses_after_warmup" in p for p in perf_gate.check(budget, obs))
    obs = {"stage_p99_s": {}, "counters": {"ingest_legacy_ratio": 0.5}}
    assert any("ingest_legacy_ratio" in p for p in perf_gate.check(budget, obs))


def test_perf_gate_fails_closed_on_ratchet_violations():
    import copy

    import perf_gate

    budget = perf_gate.load_budget()
    empty = {"stage_p99_s": {}, "counters": {}}
    # deleted stage entry
    b = copy.deepcopy(budget)
    del b["stage_p99_s"]["commit"]
    assert any(
        "ratchet" in p and "commit" in p for p in perf_gate.check(b, empty)
    )
    # deleted counter entry
    b = copy.deepcopy(budget)
    del b["counters"]["sharded_fallbacks"]
    assert any(
        "ratchet" in p and "sharded_fallbacks" in p
        for p in perf_gate.check(b, empty)
    )
    # stripped justification
    b = copy.deepcopy(budget)
    b["stage_p99_s"]["sync"]["why"] = ""
    assert any("justification" in p for p in perf_gate.check(b, empty))
    # a new stage observed with no budget entry must fail, not pass
    obs = {"stage_p99_s": {"brand_new_stage": 0.01}, "counters": {}}
    assert any("brand_new_stage" in p for p in perf_gate.check(budget, obs))


def test_perf_gate_delta_p99_excludes_presnapshot_samples():
    """The delta discipline: warmup's inline-compile walls (observed
    BEFORE the snapshot) must not pollute the gated p99, and a
    post-snapshot outlier must dominate it."""
    import perf_gate
    from kubernetes_tpu.metrics.registry import Histogram

    h = Histogram("t_introspect_stage", "t", label_names=("stage",),
                  buckets=(0.1, 1.0, 10.0))
    h.observe(50.0, "dispatch")  # "warmup compile": pre-snapshot
    before = perf_gate.snapshot_stages(h)
    for _ in range(100):
        h.observe(0.05, "dispatch")
    p99 = perf_gate.stage_p99_delta(before, h)
    assert p99["dispatch"] == pytest.approx(0.1)  # outlier excluded
    for _ in range(10):
        h.observe(50.0, "dispatch")  # injected mid-drain stall
    p99 = perf_gate.stage_p99_delta(before, h)
    assert p99["dispatch"] == float("inf")  # caught at bucket resolution


# ---------------------------------------------------------------------------
# ktpu_top: pure renderers
# ---------------------------------------------------------------------------

def test_ktpu_top_parses_and_renders_registry_scrape(warmed):
    import ktpu_top

    _, mon = warmed
    mon.refresh()
    parsed = ktpu_top.parse_metrics_text(M.registry.expose_text())
    assert "ktpu_plane_slab_occupancy" in parsed
    body = ktpu_top.render_metrics(parsed)
    for frag in ("ingest", "terms", "mirror_nodes", "queue", "audits"):
        assert frag in body, body
    with pytest.raises(ValueError):
        ktpu_top.parse_metrics_text("not a metric line at all{")


def test_ktpu_top_renders_census_table(warmed):
    import ktpu_top

    sched, _ = warmed
    body = ktpu_top.render_census(introspect.census(sched))
    for frag in ("ingest", "terms", "columns", "mirror_nodes", "ladder",
                 "commit", "recorder", "audits"):
        assert frag in body, body


# ---------------------------------------------------------------------------
# black-box dump hygiene (KTPU_BLACKBOX_DIR, never CWD)
# ---------------------------------------------------------------------------

def test_blackbox_dump_routes_to_configured_dir(tmp_path, monkeypatch):
    monkeypatch.setenv("KTPU_BLACKBOX_DIR", str(tmp_path / "artifacts"))
    monkeypatch.delenv("KTPU_TRACE_DIR", raising=False)
    rec = FlightRecorder(enabled=True)
    rec.record_cycle({"cycle": 1})
    path = rec.dump_blackbox("introspect-test")
    assert path is not None
    assert os.path.dirname(path) == str(tmp_path / "artifacts")
    assert os.path.exists(path)
    with open(path) as f:
        assert json.load(f)["reason"] == "introspect-test"


def test_blackbox_dump_default_never_lands_in_cwd(tmp_path, monkeypatch):
    monkeypatch.delenv("KTPU_BLACKBOX_DIR", raising=False)
    monkeypatch.delenv("KTPU_TRACE_DIR", raising=False)
    monkeypatch.chdir(tmp_path)
    rec = FlightRecorder(enabled=True)
    rec.record_cycle({"cycle": 1})
    path = rec.dump_blackbox("introspect-cwd-test")
    try:
        assert path is not None
        assert os.path.dirname(path) == tempfile.gettempdir()
        assert not list(tmp_path.glob("ktpu_blackbox_*.json"))
    finally:
        if path and os.path.exists(path):
            os.remove(path)
