"""Durable store (WAL + snapshot + restart recovery) and server-side
list/watch selectors. Reference anchors: etcd3/store.go:239 (revision-CAS
writes; etcd IS the checkpoint), etcd3/watcher.go:105,
apimachinery/pkg/fields/selector.go (pods-by-nodeName is how kubelets
watch only their pods)."""

import os

import pytest

pytest.importorskip("jax")

from kubernetes_tpu.apiserver import FakeAPIServer
from kubernetes_tpu.apiserver.persist import WAL
from kubernetes_tpu.client import Informer
from kubernetes_tpu.models.generators import make_node, make_pod


def _wal(tmp_path, **kw):
    return WAL(str(tmp_path / "store.wal"), **kw)


def test_restart_recovers_objects_and_rv(tmp_path):
    path = str(tmp_path / "store.wal")
    api = FakeAPIServer(wal=path)
    api.create("nodes", make_node("n0"))
    p = api.create("pods", make_pod("a", cpu_milli=100, mem=2**20))
    api.bind("default", "a", "n0")
    api.create("pods", make_pod("b", cpu_milli=100, mem=2**20))
    api.delete("pods", "default/b")
    rv_before = api.list("pods")[1]

    # "kill -9": a brand-new process opens the same files
    api2 = FakeAPIServer(wal=path)
    pods, rv = api2.list("pods")
    assert [p.name for p in pods] == ["a"]
    assert pods[0].node_name == "n0"  # the bind survived
    assert api2.get("nodes", "n0").name == "n0"
    # resourceVersion CONTINUITY: new writes move past the old revisions
    assert rv >= rv_before
    created = api2.create("pods", make_pod("c", cpu_milli=100, mem=2**20))
    assert int(created.resource_version) > rv_before


def test_restart_clients_relist_and_converge(tmp_path):
    """Scheduler-style informer against the reborn store: list+watch
    resumes, and the informer's view converges on the recovered state."""
    path = str(tmp_path / "store.wal")
    api = FakeAPIServer(wal=path)
    for i in range(4):
        api.create("pods", make_pod(f"p{i}", cpu_milli=100, mem=2**20))
    api2 = FakeAPIServer(wal=path)
    inf = Informer(api2, "pods")
    inf.start()
    assert inf.wait_for_sync()
    try:
        assert sorted(p.name for p in inf.list()) == ["p0", "p1", "p2", "p3"]
        api2.delete("pods", "default/p1")
        import time

        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and len(inf.list()) != 3:
            time.sleep(0.02)
        assert sorted(p.name for p in inf.list()) == ["p0", "p2", "p3"]
    finally:
        inf.stop()


def test_snapshot_compaction_truncates_log(tmp_path):
    wal = _wal(tmp_path, compact_every=10)
    api = FakeAPIServer(wal=wal)
    for i in range(25):
        api.create("pods", make_pod(f"p{i}", cpu_milli=1, mem=1))
    assert os.path.exists(wal.snap_path)
    # the log was truncated at least once: fewer lines than total writes
    with open(wal.path) as f:
        lines = sum(1 for _ in f)
    assert lines < 25
    api2 = FakeAPIServer(wal=WAL(wal.path))
    assert len(api2.list("pods")[0]) == 25


def test_torn_tail_write_is_dropped(tmp_path):
    path = str(tmp_path / "store.wal")
    api = FakeAPIServer(wal=path)
    api.create("pods", make_pod("a", cpu_milli=1, mem=1))
    api.create("pods", make_pod("b", cpu_milli=1, mem=1))
    with open(path, "a") as f:
        f.write('{"op": "PUT", "kind": "pods", "key": "default/c"')  # crash mid-append
    api2 = FakeAPIServer(wal=path)
    assert sorted(p.name for p in api2.list("pods")[0]) == ["a", "b"]


def test_list_watch_field_selector_per_node(served=None):
    """A kubelet-style watch with spec.nodeName sees ONLY its node's pods —
    events for other nodes never reach it."""
    api = FakeAPIServer()
    w = api.watch("pods", 0, field_selector={"spec.nodeName": "n1"})
    p1 = make_pod("mine", cpu_milli=1, mem=1)
    p1.node_name = "n1"
    p2 = make_pod("other", cpu_milli=1, mem=1)
    p2.node_name = "n2"
    api.create("pods", p1)
    api.create("pods", p2)
    ev = w.next(timeout=2)
    assert ev is not None and ev.obj.name == "mine"
    assert w.next(timeout=0.3) is None  # n2's pod never arrives
    # list-side filtering too
    pods, _ = api.list("pods", field_selector={"spec.nodeName": "n2"})
    assert [p.name for p in pods] == ["other"]
    lab, _ = api.list("pods", label_selector={"app": "nope"})
    assert lab == []


def test_selectors_over_http(tmp_path):
    from kubernetes_tpu.apiserver import APIServerHTTP
    from kubernetes_tpu.client import RemoteAPIServer

    api = FakeAPIServer()
    srv = APIServerHTTP(api).start()
    try:
        remote = RemoteAPIServer(srv.url)
        a = make_pod("a", cpu_milli=1, mem=1, labels={"app": "x"})
        a.node_name = "n1"
        b = make_pod("b", cpu_milli=1, mem=1, labels={"app": "y"})
        remote.create("pods", a)
        remote.create("pods", b)
        only_n1, _ = remote.list("pods", field_selector={"spec.nodeName": "n1"})
        assert [p.name for p in only_n1] == ["a"]
        only_x, _ = remote.list("pods", label_selector={"app": "x"})
        assert [p.name for p in only_x] == ["a"]
        w = remote.watch("pods", 0, field_selector={"spec.nodeName": "n1"})
        ev = w.next(timeout=3)
        assert ev is not None and ev.obj.name == "a"
        assert w.next(timeout=0.3) is None
        w.close()
    finally:
        srv.stop()


def test_hollow_kubelets_watch_only_their_pods(tmp_path):
    """HollowCluster default: per-kubelet field-selected informers."""
    from kubernetes_tpu.kubemark import HollowCluster

    api = FakeAPIServer()
    nodes = [make_node(f"n{i}", cpu_milli=4000, mem=8 * 2**30) for i in range(3)]
    hollow = HollowCluster(api, nodes, heartbeat_s=0.3).start()
    try:
        p = make_pod("w", cpu_milli=100, mem=2**20)
        api.create("pods", p)
        api.bind("default", "w", "n1")
        import time

        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if api.get("pods", "default/w").phase == "Running":
                break
            time.sleep(0.05)
        assert api.get("pods", "default/w").phase == "Running"
        # the OTHER kubelets' informers never stored it
        assert hollow.kubelets["n0"]._pod_informer.list() == []
        assert hollow.kubelets["n2"]._pod_informer.list() == []
        assert [q.name for q in hollow.kubelets["n1"]._pod_informer.list()] == ["w"]
    finally:
        hollow.stop()


def test_torn_tail_then_new_writes_survive_second_restart(tmp_path):
    """Replay must TRUNCATE the torn fragment: without it, writes appended
    after the first crash-restart are unreadable on the second restart
    (round-4 review finding)."""
    path = str(tmp_path / "store.wal")
    api = FakeAPIServer(wal=path)
    api.create("pods", make_pod("a", cpu_milli=1, mem=1))
    with open(path, "a") as f:
        f.write('{"op": "PUT", "kind": "pods"')  # crash mid-append
    api2 = FakeAPIServer(wal=path)  # restart 1: drops the fragment
    api2.create("pods", make_pod("b", cpu_milli=1, mem=1))
    api3 = FakeAPIServer(wal=path)  # restart 2: b must still be there
    assert sorted(p.name for p in api3.list("pods")[0]) == ["a", "b"]


def test_selector_watcher_gets_deleted_on_label_transition(tmp_path):
    """An object leaving a watcher's selector produces a synthetic DELETED
    (the watch-cache match-transition contract) so filtered informer
    caches never go stale."""
    api = FakeAPIServer()
    p = make_pod("w", cpu_milli=1, mem=1, labels={"app": "web"})
    api.create("pods", p)
    watcher = api.watch("pods", 0, label_selector={"app": "web"})
    ev = watcher.next(timeout=2)
    assert ev is not None and ev.type == "ADDED"
    moved = api.get("pods", "default/w")
    moved.labels = {"app": "api"}
    api.update("pods", moved)
    ev = watcher.next(timeout=2)
    assert ev is not None and ev.type == "DELETED", ev
