"""Commit-plane suite (kubernetes_tpu/commit): device-arbitrated commits
must be bit-identical to the legacy host recheck walk, the columnar apply
must preserve every commit invariant under faults, and the pipeline must
never lose a pod.

Three layers:
* verdict equivalence — `arbitrate` (device) vs `host_arbitrate` (the
  pure-oracle sequential walk) across seeded anti-affinity / host-port /
  DoNotSchedule-spread workloads;
* drain equivalence — a full drain with the commit plane ON equals the
  legacy loop (plane OFF) pod-for-pod, node-for-node, across anti-heavy,
  gang, and preemption workloads;
* faults — gang rollback through the single GangRollbackRecord, and bind
  failures mid-chunk on the arbitrated path (forget + requeue, the rest
  of the chunk unharmed).
"""

import time

import pytest

pytest.importorskip("jax")

from kubernetes_tpu.api.types import (
    Affinity,
    ContainerPort,
    LabelSelector,
    PodAffinityTerm,
    PodAntiAffinity,
    TopologySpreadConstraint,
)
from kubernetes_tpu.commit import V_DEFER, host_arbitrate
from kubernetes_tpu.commit.apply import ColumnarApply, GangRollbackRecord
from kubernetes_tpu.commit.pipeline import CommitPipeline
from kubernetes_tpu.models.generators import make_node, make_pod
from kubernetes_tpu.scheduler.driver import (
    Binder,
    POD_GROUP_LABEL,
    POD_GROUP_MIN_AVAILABLE,
    Scheduler,
)
from kubernetes_tpu.state.cache import SchedulerCache
from kubernetes_tpu.state.queue import PriorityQueue

HOST = "kubernetes.io/hostname"
ZONE = "zone"


def _nodes(n, zones=0, cpu=4000):
    out = []
    for i in range(n):
        labels = {HOST: f"n{i}"}
        if zones:
            labels[ZONE] = f"z{i % zones}"
        out.append(make_node(f"n{i}", cpu_milli=cpu, labels=labels))
    return out


def _anti_pod(name, app, cpu=100):
    p = make_pod(name, cpu_milli=cpu, labels={"app": app})
    p.affinity = Affinity(pod_anti_affinity=PodAntiAffinity(required=[
        PodAffinityTerm(
            label_selector=LabelSelector(match_labels={"app": app}),
            topology_key=HOST,
        )
    ]))
    return p


def _spread_pod(name, app, max_skew=1, cpu=50):
    p = make_pod(name, cpu_milli=cpu, labels={"app": app})
    p.topology_spread_constraints = [TopologySpreadConstraint(
        max_skew=max_skew,
        topology_key=ZONE,
        when_unsatisfiable="DoNotSchedule",
        label_selector=LabelSelector(match_labels={"app": app}),
    )]
    return p


def _port_pod(name, port, cpu=50):
    p = make_pod(name, cpu_milli=cpu)
    p.containers[0].ports = [ContainerPort(host_port=port)]
    p.__dict__.pop("_host_ports_memo", None)
    return p


def _mk_sched(nodes, existing=(), **kw):
    cache = SchedulerCache()
    for n in nodes:
        cache.add_node(n)
    for p in existing:
        cache.add_pod(p)
    binds = []
    binder = Binder(lambda pod, node: binds.append((pod.key(), node)))
    kw.setdefault("deterministic", True)
    sched = Scheduler(cache=cache, queue=PriorityQueue(), binder=binder, **kw)
    return sched, binds


# ---------------------------------------------------------------------------
# verdict equivalence: device arbiter == host sequential walk, bit for bit
# ---------------------------------------------------------------------------

def _verdicts_for(sched, pods):
    for p in pods:
        sched.queue.add(p)
    infos = sched.queue.pop_batch(len(pods))
    disp = sched._dispatch_solve(infos)
    out = sched._finish_solve(disp)
    assert out.verdicts is not None, "arbiter was not dispatched"
    host = host_arbitrate(
        [i.pod for i in infos],
        out.assign,
        sched.mirror.node_name_of_row,
        sched.cache.snapshot,
    )
    return [int(v) for v in out.verdicts], host, out


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_verdicts_match_host_walk_anti_heavy(seed):
    import random

    rng = random.Random(seed)
    sched, _ = _mk_sched(_nodes(4))
    pods = []
    for i in range(12):
        if rng.random() < 0.6:
            pods.append(_anti_pod(f"a{i}", app=f"g{rng.randrange(2)}"))
        else:
            pods.append(make_pod(f"p{i}", cpu_milli=100))
    dev, host, _ = _verdicts_for(sched, pods)
    assert dev == host


def test_verdicts_match_host_walk_hard_spread():
    # 6 zones-worth of pods into 2 zones with maxSkew=1: the solve's mask
    # predates in-batch commits, so the arbiter must defer the overflow —
    # and must defer exactly the pods the host sequential walk defers
    sched, _ = _mk_sched(_nodes(4, zones=2))
    pods = [_spread_pod(f"s{i}", app="web") for i in range(6)]
    dev, host, out = _verdicts_for(sched, pods)
    assert dev == host
    assert V_DEFER in dev  # the workload genuinely exercised arbitration


def test_verdicts_match_host_walk_host_ports():
    sched, _ = _mk_sched(_nodes(2))
    pods = [_port_pod(f"hp{i}", port=8080) for i in range(4)]
    pods += [make_pod(f"f{i}", cpu_milli=50) for i in range(2)]
    dev, host, _ = _verdicts_for(sched, pods)
    assert dev == host


def test_verdicts_minus_one_couldfit_defers():
    # nodes full for zone z1 → a -1 spread pod whose constraint an earlier
    # commit matched must DEFER (the could-fit rule), not fail outright
    sched, _ = _mk_sched(_nodes(2, zones=2, cpu=300))
    pods = [_spread_pod(f"s{i}", app="web", cpu=100) for i in range(8)]
    dev, host, _ = _verdicts_for(sched, pods)
    assert dev == host


# ---------------------------------------------------------------------------
# drain equivalence: commit plane ON == legacy host loop, pod for pod
# ---------------------------------------------------------------------------

def _drain(sched, rounds=60):
    total_sched = 0
    assignments = {}
    deferred = 0
    for _ in range(rounds):
        r = sched.schedule_batch()
        total_sched += r.scheduled
        deferred += r.deferred
        assignments.update(r.assignments)
        if (r.scheduled == 0 and r.unschedulable == 0 and r.errors == 0
                and r.deferred == 0):
            active, backoff, unsched = sched.queue.counts()
            if not (active + backoff + unsched):
                break
            time.sleep(0.06)
            sched.queue.move_all_to_active()
    sched.wait_for_binds()
    return total_sched, assignments, deferred


@pytest.mark.parametrize("workload", ["anti", "gang", "preemption"])
def test_drain_bit_identical_to_legacy(workload):
    def build(commit_plane):
        if workload == "preemption":
            nodes = _nodes(3, cpu=1000)
            existing = []
            for i, n in enumerate(nodes):
                v = make_pod(f"victim{i}", cpu_milli=900, node_name=n.name)
                v.priority = 0
                existing.append(v)
            sched, binds = _mk_sched(
                nodes, existing=existing, commit_plane=commit_plane,
                enable_preemption=True, batch_size=8,
            )
            for i in range(3):
                p = make_pod(f"hi{i}", cpu_milli=800)
                p.priority = 1000
                sched.queue.add(p)
        else:
            sched, binds = _mk_sched(
                _nodes(6), commit_plane=commit_plane,
                enable_preemption=False, batch_size=4,
            )
            if workload == "anti":
                for i in range(6):
                    sched.queue.add(_anti_pod(f"solo{i}", app="solo"))
                for i in range(6):
                    sched.queue.add(make_pod(f"free{i}", cpu_milli=100))
            else:  # gang
                for g in range(2):
                    for m in range(3):
                        sched.queue.add(make_pod(
                            f"g{g}m{m}", cpu_milli=100,
                            labels={POD_GROUP_LABEL: f"gang-{g}"},
                        ))
        n_sched, assignments, _ = _drain(sched)
        sched.close()
        return n_sched, assignments, sched

    n_on, asg_on, s_on = build(True)
    n_off, asg_off, _ = build(False)
    assert n_on == n_off
    assert asg_on == asg_off
    if workload == "anti":
        # the plane actually engaged on the covered batches
        assert s_on.stats.get("arbiter_batches", 0) > 0, s_on.stats


def test_speculative_anti_defers_then_places():
    """Speculative chains make the mask one batch stale: the arbiter (or
    its prior-index downgrade) must defer the stale picks, and the defers
    must land cleanly next batch — every pod placed, one host each."""
    sched, binds = _mk_sched(
        _nodes(10), enable_preemption=False, batch_size=4, speculate=True,
        spec_depth=2,
    )
    for i in range(10):
        sched.queue.add(_anti_pod(f"solo{i}", app="solo"))
    n_sched, assignments, _deferred = _drain(sched)
    assert n_sched == 10
    assert len(set(assignments.values())) == 10  # anti respected everywhere
    sched.close()


def test_hard_spread_drain_respects_skew():
    """A one-batch flood of DoNotSchedule pods: the arbiter defers the
    in-batch skew violations; the drain must converge with the final
    placement satisfying the constraint (audited exactly)."""
    from bench import audit_placement

    nodes = _nodes(6, zones=3)
    sched, binds = _mk_sched(nodes, enable_preemption=False, batch_size=16)
    for i in range(9):
        sched.queue.add(_spread_pod(f"s{i}", app="web"))
    n_sched, assignments, deferred = _drain(sched)
    assert n_sched == 9
    assert deferred > 0, sched.stats  # arbitration actually fired
    commits = []
    by_name = {f"default/s{i}": _spread_pod(f"s{i}", app="web") for i in range(9)}
    for key, node in assignments.items():
        commits.append((by_name[key], node))
    audit = audit_placement(nodes, commits, sample=0)
    assert audit["hard_spread_skew_violations"] == 0
    assert audit["capacity_violations"] == 0
    sched.close()


# ---------------------------------------------------------------------------
# faults: gang rollback record, bind failure mid-chunk
# ---------------------------------------------------------------------------

def test_gang_rollback_record_unwinds_cache():
    sched, binds = _mk_sched(_nodes(4), enable_preemption=False)
    for m in range(2):
        p = make_pod(f"gm{m}", cpu_milli=100, labels={
            POD_GROUP_LABEL: "g1", POD_GROUP_MIN_AVAILABLE: "4",
        })
        sched.queue.add(p)
    r = sched.schedule_batch()
    sched.wait_for_binds()
    # min-available 4 with only 2 members queued: the whole group rolls
    # back through ONE record — nothing assumed, nothing bound
    assert r.scheduled == 0
    assert r.unschedulable >= 2
    assert sched.cache.pod_count() == 0
    assert sched.cache.assumed_count() == 0
    assert binds == []


def test_gang_rollback_record_direct():
    cache = SchedulerCache()
    cache.add_node(make_node("n0"))
    from kubernetes_tpu.framework.interface import CycleState, Framework

    from kubernetes_tpu.state.queue import PodInfo

    fw = Framework()
    rec = GangRollbackRecord("g")
    failed = []
    for i in range(3):
        pod = make_pod(f"m{i}")
        assumed = pod.with_node("n0")
        cache.assume_pod(assumed)
        rec.stage(PodInfo(pod=pod), assumed, "n0", CycleState())
    assert cache.pod_count() == 3
    n = rec.rollback(
        cache, fw, None, lambda info, cycle, msg: failed.append(msg), 7,
        "gang incomplete",
    )
    assert n == 3
    assert cache.pod_count() == 0
    assert failed == ["gang incomplete"] * 3
    assert len(rec) == 0  # record consumed


def test_bind_failure_mid_chunk_on_arbiter_path():
    """One failing bind inside a columnar chunk must forget+requeue ONLY
    its pod; the rest of the chunk stays bound (lean-chunk isolation)."""
    fails = {"default/a1": 1}

    def flaky_bind(pod, node):
        if fails.get(pod.key(), 0) > 0:
            fails[pod.key()] -= 1
            raise RuntimeError("bind RPC down")

    cache = SchedulerCache()
    for n in _nodes(4):
        cache.add_node(n)
    sched = Scheduler(
        cache=cache, queue=PriorityQueue(), binder=Binder(flaky_bind),
        deterministic=True, enable_preemption=False,
    )
    for i in range(4):
        sched.queue.add(_anti_pod(f"a{i}", app=f"app{i}"))
    r1 = sched.schedule_batch()
    sched.wait_for_binds()
    assert r1.scheduled == 4
    assert sched.stats.get("arbiter_batches", 0) == 1, sched.stats
    # the failed bind forgot its assume and requeued the pod
    assert sched.cache.pod_count() == 3
    time.sleep(1.1)  # bind-failure requeue goes through backoff
    sched.queue.move_all_to_active()
    r2 = sched.run_until_empty()
    sched.wait_for_binds()
    assert r2.scheduled == 1
    assert sched.cache.pod_count() == 4
    sched.close()


# ---------------------------------------------------------------------------
# plumbing units: columnar apply, pipeline backpressure, defer requeue
# ---------------------------------------------------------------------------

def test_columnar_apply_rejects_already_assumed():
    cache = SchedulerCache()
    cache.add_node(make_node("n0"))
    queue = PriorityQueue()
    col = ColumnarApply(cache, queue)
    from kubernetes_tpu.state.queue import PodInfo

    a, b = make_pod("a"), make_pod("b")
    cache.assume_pod(a.with_node("n0"))  # duplicate key already in cache
    result = col.apply([(PodInfo(pod=a), "n0"), (PodInfo(pod=b), "n0")])
    assert len(result.placed) == 1 and result.placed[0][2] == "n0"
    assert len(result.rejected) == 1 and result.rejected[0][0].pod is a
    assert cache.pod_count() == 2


def test_commit_pipeline_backpressure_and_errors():
    pipe = CommitPipeline()
    order = []

    def slow():
        time.sleep(0.05)
        order.append("first")

    pipe.submit(slow)
    pipe.submit(lambda: order.append("second"))  # must drain `first` before
    pipe.drain()
    assert order == ["first", "second"]
    assert pipe.stats["submitted"] == 2

    def boom():
        raise RuntimeError("apply exploded")

    pipe.submit(boom)
    with pytest.raises(RuntimeError, match="apply exploded"):
        pipe.drain()
    pipe.drain()  # error consumed; pipeline still usable
    pipe.submit(lambda: order.append("third"))
    pipe.close()
    assert order[-1] == "third"


def test_queue_requeue_preserves_seq_no_backoff():
    q = PriorityQueue()
    q.add(make_pod("a"))
    q.add(make_pod("b"))
    infos = q.pop_batch(2)
    assert [i.pod.name for i in infos] == ["a", "b"]
    q.requeue([infos[1]])
    q.requeue([infos[0]])
    again = q.pop_batch(2)
    # seq preserved → original order restored, no backoff delay
    assert [i.pod.name for i in again] == ["a", "b"]


def test_commit_pipeline_worker_stat_handoff():
    """KTPU006 regression (thread-role analysis): the submitted closure
    used to write Scheduler.stats directly from the worker thread — a
    cross-thread read-modify-write on the driver's single-writer dict.
    Contributions now accumulate in the pipeline's locked sink and the
    DRIVER merges them at drain (Scheduler._drain_commit)."""
    pipe = CommitPipeline()
    try:
        pipe.submit(lambda: pipe.note_stat("apply_s", 0.25))
        pipe.submit(lambda: pipe.note_stat("apply_rejects", 1))
        pipe.drain()
        got = pipe.take_worker_stats()
        assert got == {"apply_s": 0.25, "apply_rejects": 1}
        # drain-and-clear: the merge consumes the contributions exactly once
        assert pipe.take_worker_stats() == {}
    finally:
        pipe.close()


def test_driver_merges_worker_stats_at_drain():
    """The driver-side half: _drain_commit folds the worker's pending
    contributions into Scheduler.stats (which stays single-writer)."""
    sched = Scheduler(cache=SchedulerCache(), queue=PriorityQueue())
    try:
        sched._commit_pipe.submit(
            lambda: sched._commit_pipe.note_stat("apply_s", 0.5)
        )
        sched._drain_commit()
        assert sched.stats.get("apply_s", 0.0) >= 0.5
        # idempotent: a second drain merges nothing twice
        before = sched.stats["apply_s"]
        sched._drain_commit()
        assert sched.stats["apply_s"] == before
    finally:
        sched.close()
