"""Tier-1 wiring for scripts/perf_smoke.py: the commit-plane smoke runs
as a FAST test (deliberately not slow-marked) so a regression that drops
arbiter coverage to zero or reintroduces mid-drain XLA compiles fails CI,
not just the nightly bench."""

import os
import sys

import pytest

pytest.importorskip("jax")

_SCRIPTS = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "scripts"
)


def test_perf_smoke_commit_plane(tmp_path, monkeypatch):
    # hermetic compile-plan persistence: a ladder left by other runs must
    # not pre-warm (or mis-warm) this process's specs
    monkeypatch.setenv("KTPU_COMPILE_CACHE_DIR", str(tmp_path / "plan"))
    # the mixed smoke drain doubles as the LOCK-ORDER-AUDITED drain
    # (analysis/lockorder): every package lock constructed during the run
    # is wrapped, and the acquisition-order graph across the informer /
    # uploader / commit-apply / warmup threads must stay acyclic
    monkeypatch.setenv("KTPU_LOCK_AUDIT", "1")
    from kubernetes_tpu.analysis.lockorder import REGISTRY

    REGISTRY.reset()
    if _SCRIPTS not in sys.path:
        sys.path.insert(0, _SCRIPTS)
    import perf_smoke

    detail = perf_smoke.main()  # raises AssertionError on any regression
    REGISTRY.assert_acyclic()
    report = REGISTRY.report()
    assert report["acquisitions"] > 0 and report["edges"], (
        "lock audit recorded nothing — the audited_* factories are no "
        "longer wired into the package's lock construction sites"
    )
    # thread-role soundness probe (analysis/roles.py): every (lock role,
    # thread role) observation from this drain must be contained in the
    # static inference, and the observed graph must be NON-EMPTY — the
    # register_thread_role spawn-site stamps unwiring silently fails
    # here, same discipline as the non-empty-edge assertion above
    from kubernetes_tpu.analysis import roles as roles_mod

    role_report = roles_mod.assert_runtime_subset(REGISTRY)
    assert role_report["observed"], "no role observations recorded"
    phase = detail["phase_split_s"]
    assert phase["arbiter_batches"] > 0
    assert phase["arbiter_place"] > 0
    assert detail["compile"]["misses_after_warmup"] == 0
    assert detail["scheduled"] == perf_smoke.N_PODS
    # the defer path is part of the contract: the spread slice of the
    # workload must actually arbitrate (bit-identity is pinned elsewhere;
    # this guards the wiring staying live)
    assert detail["audit"]["hard_spread_skew_violations"] == 0


def test_perf_smoke_sharded_mesh(tmp_path, monkeypatch):
    """Multi-chip acceptance, tier-1-fast: the SAME smoke workload over a
    forced 8-virtual-device node mesh must reach the zero-round-trip
    steady state — arbiter coverage > 0, fold coverage > 0, zero dropped
    donations, `patch_bytes.usage ≈ 0`, zero sharded→replicated
    fallbacks, zero compile misses after warmup."""
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    monkeypatch.setenv("KTPU_COMPILE_CACHE_DIR", str(tmp_path / "plan_sh"))
    if _SCRIPTS not in sys.path:
        sys.path.insert(0, _SCRIPTS)
    import perf_smoke

    detail = perf_smoke.main(sharded=True)
    phase = detail["phase_split_s"]
    assert phase["arbiter_batches"] > 0
    assert phase["fold_batches"] > 0
    assert phase.get("sharded_fallbacks", 0) == 0
    assert detail["fold_undonated"] == 0
    assert detail["patch_bytes"].get("usage", 0) <= 4096
    assert detail["compile"]["misses_after_warmup"] == 0
    assert detail["scheduled"] == perf_smoke.N_PODS


def test_perf_smoke_preemption_no_midrain_compiles(tmp_path, monkeypatch):
    """Post-preemption cycles must land on warmed programs (the BENCH_r05
    config-6 cycle-2 spike regression guard): zero compile misses after
    warmup AND zero stall batches across a drain that actually evicts."""
    monkeypatch.setenv("KTPU_COMPILE_CACHE_DIR", str(tmp_path / "plan_pre"))
    monkeypatch.setenv("KTPU_LOCK_AUDIT", "1")  # audited preemption drain
    from kubernetes_tpu.analysis.lockorder import REGISTRY

    REGISTRY.reset()
    if _SCRIPTS not in sys.path:
        sys.path.insert(0, _SCRIPTS)
    import perf_smoke

    detail = perf_smoke.main_preempt()
    REGISTRY.assert_acyclic()
    # the preemption drain is the second lock-audited smoke: it must
    # ALSO prove observed roles ⊆ static inference with a live graph
    from kubernetes_tpu.analysis import roles as roles_mod

    role_report = roles_mod.assert_runtime_subset(REGISTRY)
    assert role_report["observed"], "no role observations recorded"
    assert detail["preempted"] > 0
    assert detail["compile"]["misses_after_warmup"] == 0
    assert detail["warm_stall_batches"] == 0
    assert detail["scheduled"] == 24


def test_perf_smoke_trace_mode(tmp_path, monkeypatch):
    """Flight-recorder acceptance, tier-1-fast: a traced smoke drain
    exports a valid Chrome-trace timeline with spans from the informer,
    uploader, driver, commit-apply, bind, and device threads for every
    pipeline stage; `misses_after_warmup == 0` holds with tracing ON;
    the traced drain stays within the overhead bound of the untraced
    one (disabled path is a no-op)."""
    monkeypatch.setenv("KTPU_COMPILE_CACHE_DIR", str(tmp_path / "plan_tr"))
    if _SCRIPTS not in sys.path:
        sys.path.insert(0, _SCRIPTS)
    import perf_smoke

    detail = perf_smoke.main_trace()  # raises AssertionError on regression
    assert detail["misses_after_warmup"] == 0
    assert detail["trace_events"] > 0
    for stage in perf_smoke.REQUIRED_SPANS:
        assert stage in detail["span_names"], stage


def test_perf_smoke_term_plane(tmp_path, monkeypatch):
    """Term-bank-plane acceptance, tier-1-fast: on an affinity-heavy
    quiet drain every dispatch gathers its term table from the
    device-resident term bank (coverage > 0, zero stale entries, zero
    legacy host compiles), `patch_bytes.terms` stays KB-scale (index/
    owner vectors, not the padded term-table upload), and no program
    compiles mid-drain."""
    monkeypatch.setenv("KTPU_COMPILE_CACHE_DIR", str(tmp_path / "plan_term"))
    # the affinity-heavy drain doubles as a lock-order-audited drain for
    # the new "terms" lock role (queue → terms nesting on the informer
    # admission path, terms-upload worker in the mix)
    monkeypatch.setenv("KTPU_LOCK_AUDIT", "1")
    from kubernetes_tpu.analysis.lockorder import REGISTRY

    REGISTRY.reset()
    if _SCRIPTS not in sys.path:
        sys.path.insert(0, _SCRIPTS)
    import perf_smoke

    detail = perf_smoke.main_terms()  # raises AssertionError on regression
    REGISTRY.assert_acyclic()
    phase = detail["phase_split_s"]
    assert phase["term_index_batches"] > 0
    assert phase.get("term_legacy_batches", 0) == 0
    assert phase.get("term_stale_rows", 0) == 0
    assert 0 < detail["patch_bytes"]["terms"] <= 64 * 1024
    assert detail["mirror_rebuilds"] == 0
    assert detail["compile"]["misses_after_warmup"] == 0
    assert detail["scheduled"] == perf_smoke.N_PODS


def test_perf_smoke_columnar_cache(tmp_path, monkeypatch):
    """Columnar-scheduler-cache acceptance, tier-1-fast: a covered
    plain+anti drain commits every pod through the columnar bulk path
    (coverage > 0) with ZERO lazy-view materializations and ZERO scalar
    object-path pods on the commit path — per-pod NodeInfo/Quantity
    object updates are gone from bulk assume/forget/bind — while the
    device-divergence probe (now a vectorized columns-vs-banks
    cross-check too) stays empty and no program compiles mid-drain.
    Runs lock-order-audited: the column scatters join the cache lock's
    acquisition graph."""
    monkeypatch.setenv("KTPU_COMPILE_CACHE_DIR", str(tmp_path / "plan_col"))
    monkeypatch.setenv("KTPU_LOCK_AUDIT", "1")
    from kubernetes_tpu.analysis.lockorder import REGISTRY

    REGISTRY.reset()
    if _SCRIPTS not in sys.path:
        sys.path.insert(0, _SCRIPTS)
    import perf_smoke

    detail = perf_smoke.main_columnar()  # raises AssertionError on regression
    REGISTRY.assert_acyclic()
    cols = detail["columnar_state"]["cols"]
    assert cols["bulk_pods"] > 0
    assert cols["materializations"] == 0
    assert cols["scalar_pods"] == 0
    assert detail["columnar_state"]["divergence"] == []
    assert detail["compile"]["misses_after_warmup"] == 0
    assert detail["scheduled"] == perf_smoke.N_PODS


def test_perf_smoke_health_monitor(tmp_path, monkeypatch):
    """Steady-state-health acceptance, tier-1-fast: with the background
    monitor ON during a mixed drain, the always-on plane gauges are
    non-empty and parseable, >=1 sampled shadow audit runs CLEAN (zero
    divergent), the /debug/ktpu census validates against its versioned
    schema, the committed perf budget (perf_gate) passes on the
    delta-measured stage p99s, `misses_after_warmup == 0` holds monitor-
    ON, and the monitor stays within the PR 7 trace-overhead bound.
    Runs lock-order-audited: the monitor's "health" lock role joins the
    acquisition graph alongside every plane lock it snapshots."""
    monkeypatch.setenv("KTPU_COMPILE_CACHE_DIR", str(tmp_path / "plan_hm"))
    monkeypatch.setenv("KTPU_LOCK_AUDIT", "1")
    from kubernetes_tpu.analysis.lockorder import REGISTRY

    REGISTRY.reset()
    if _SCRIPTS not in sys.path:
        sys.path.insert(0, _SCRIPTS)
    import perf_smoke

    detail = perf_smoke.main_health()  # raises AssertionError on regression
    REGISTRY.assert_acyclic()
    assert detail["audits"]["clean"] >= 1
    assert detail["audits"].get("divergent", 0) == 0
    assert detail["misses_after_warmup"] == 0
    assert detail["budget_obs"]["stage_p99_s"], "no stage p99 data collected"
    assert detail["scheduled"] == 2 * perf_smoke.N_PODS + 64


def test_perf_smoke_ingest_plane(tmp_path, monkeypatch):
    """Pod-ingest-plane acceptance, tier-1-fast: on a quiet drain every
    dispatch takes the index-only path (coverage > 0, zero stale-row
    fallbacks, zero legacy dispatches), `patch_bytes.pods` stays KB-scale
    (index vectors, not the padded pod-array upload), the warmup census
    keeps `mirror_rebuilds == 0` across a distinct-signature overflow
    workload, and no program compiles mid-drain."""
    monkeypatch.setenv("KTPU_COMPILE_CACHE_DIR", str(tmp_path / "plan_ing"))
    if _SCRIPTS not in sys.path:
        sys.path.insert(0, _SCRIPTS)
    import perf_smoke

    detail = perf_smoke.main_ingest()  # raises AssertionError on regression
    phase = detail["phase_split_s"]
    assert phase["ingest_index_batches"] > 0
    assert phase.get("ingest_legacy_batches", 0) == 0
    assert phase.get("ingest_stale_rows", 0) == 0
    assert 0 < detail["patch_bytes"]["pods"] <= 64 * 1024
    assert detail["mirror_rebuilds"] == 0
    assert detail["compile"]["misses_after_warmup"] == 0
    assert detail["scheduled"] == perf_smoke.N_PODS + perf_smoke.N_UNIQ


def test_perf_smoke_fault_plane_chaos(tmp_path, monkeypatch):
    """Fault-plane acceptance, tier-1-fast: the seeded chaos drain
    (uploader kill + per-kind device raises + watch break + bind errors
    + commit-worker death + forced bank skew over a mixed + preemption
    workload, through the REAL informer replication path) must complete
    with zero lost and zero double-bound pods, every targeted plane must
    trip AND re-close through its shadow-audit-gated probe, the forced
    skew must surface as a divergent audit (escalated: trip + resync +
    black box), and the final audit must be clean — all under the
    lock-order audit."""
    monkeypatch.setenv("KTPU_COMPILE_CACHE_DIR", str(tmp_path / "plan"))
    monkeypatch.setenv("KTPU_BLACKBOX_DIR", str(tmp_path / "bb"))
    monkeypatch.setenv("KTPU_LOCK_AUDIT", "1")
    monkeypatch.delenv("KTPU_FAULTS", raising=False)
    from kubernetes_tpu.analysis.lockorder import REGISTRY

    REGISTRY.reset()
    if _SCRIPTS not in sys.path:
        sys.path.insert(0, _SCRIPTS)
    import perf_smoke

    detail = perf_smoke.main_faults()  # raises AssertionError on regression
    REGISTRY.assert_acyclic()
    report = REGISTRY.report()
    assert report["acquisitions"] > 0 and report["edges"]
    # (no edge assertion for the board's own "faults" lock: it is a LEAF
    # by contract — its only neighbors are the metric locks, which are
    # plain primitives when metrics.py was imported before the audit env
    # was set, as happens in the full suite)
    for plane in perf_smoke.FAULTS_EXPECT_TRIPPED:
        b = detail["breakers"][plane]
        assert b["trips"] >= 1 and b["state"] == "closed", (plane, b)
        assert b["probes_passed"] >= 1, (plane, b)
    assert detail["audits"].get("divergent", 0) >= 1
    assert detail["uploader_restarts"] == 1
    assert detail["evicted"] > 0  # the preemption wave really preempted


def test_perf_smoke_crash_restart(tmp_path, monkeypatch):
    """Crash-restart acceptance, tier-1-fast: a deterministic
    `crash:mid-bind-chunk` kill-point mid-drain, the supervised restart
    (fresh instance, cold-start reconciliation from the persistent
    FakeAPIServer's relist), and the resumed drain to completion — zero
    lost pods, zero double-bound pods, no node over-commit, a clean
    shadow audit on the survivor, `misses_after_warmup == 0` on the
    restarted incarnation (the persistent ladder re-warm is trace-only),
    and the reconciliation wall reported per phase through the report
    AND `scheduler_restart_reconcile_duration_seconds{phase}` — all
    under the lock-order audit."""
    monkeypatch.setenv("KTPU_LOCK_AUDIT", "1")
    monkeypatch.setenv("KTPU_BLACKBOX_DIR", str(tmp_path / "bb"))
    monkeypatch.delenv("KTPU_FAULTS", raising=False)
    monkeypatch.delenv("KTPU_COMPILE_CACHE_DIR", raising=False)
    from kubernetes_tpu.analysis.lockorder import REGISTRY

    REGISTRY.reset()
    if _SCRIPTS not in sys.path:
        sys.path.insert(0, _SCRIPTS)
    import perf_smoke

    detail = perf_smoke.main_restart()  # raises AssertionError on regression
    REGISTRY.assert_acyclic()
    report = REGISTRY.report()
    assert report["acquisitions"] > 0 and report["edges"]
    assert detail["crashes"] == 1
    assert detail["incarnations"] == 2
    assert detail["misses_after_warmup"] == 0
    assert detail["bound"] == perf_smoke.N_PODS
    # every reconciliation phase was timed on the survivor
    from kubernetes_tpu.restart import PHASES

    for ph in PHASES:
        assert ph in detail["reconcile_phases_s"], ph
