"""Tier-1 wiring for scripts/perf_smoke.py: the commit-plane smoke runs
as a FAST test (deliberately not slow-marked) so a regression that drops
arbiter coverage to zero or reintroduces mid-drain XLA compiles fails CI,
not just the nightly bench."""

import os
import sys

import pytest

pytest.importorskip("jax")

_SCRIPTS = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "scripts"
)


def test_perf_smoke_commit_plane(tmp_path, monkeypatch):
    # hermetic compile-plan persistence: a ladder left by other runs must
    # not pre-warm (or mis-warm) this process's specs
    monkeypatch.setenv("KTPU_COMPILE_CACHE_DIR", str(tmp_path / "plan"))
    if _SCRIPTS not in sys.path:
        sys.path.insert(0, _SCRIPTS)
    import perf_smoke

    detail = perf_smoke.main()  # raises AssertionError on any regression
    phase = detail["phase_split_s"]
    assert phase["arbiter_batches"] > 0
    assert phase["arbiter_place"] > 0
    assert detail["compile"]["misses_after_warmup"] == 0
    assert detail["scheduled"] == perf_smoke.N_PODS
    # the defer path is part of the contract: the spread slice of the
    # workload must actually arbitrate (bit-identity is pinned elsewhere;
    # this guards the wiring staying live)
    assert detail["audit"]["hard_spread_skew_violations"] == 0
