"""Multi-chip parity: the sharded (mesh) pipeline must be bit-identical to
the single-device solve — same masks, same scores, same greedy commits,
same selectHost tie-breaks — on the virtual 8-device CPU mesh (conftest
sets xla_force_host_platform_device_count=8)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 devices (KTPU_TEST_PLATFORM=axon is single-chip)"
)

from kubernetes_tpu.models.generators import ClusterGen
from kubernetes_tpu.ops.pipeline import encode_solve_args, solve_pipeline
from kubernetes_tpu.oracle import Snapshot
from kubernetes_tpu.parallel import make_sharded_pipeline, node_mesh


def _encode(seed, n_nodes=24, n_existing=90, n_pending=14, feature_rate=0.6):
    g = ClusterGen(seed)
    nodes, existing = g.cluster(n_nodes, n_existing, feature_rate)
    snap = Snapshot(nodes, existing)
    pods = [g.pod(70_000 + i, feature_rate) for i in range(n_pending)]
    return encode_solve_args(snap, pods)[:-1]  # key supplied per test


@pytest.mark.parametrize("seed", [40, 41, 42])
@pytest.mark.parametrize("deterministic", [True, False])
def test_sharded_pipeline_matches_single_device(seed, deterministic):
    args = _encode(seed)
    key = jax.random.PRNGKey(seed)
    ref_assign, ref_score = solve_pipeline(*args, key, deterministic=deterministic)
    mesh = node_mesh(8)
    sharded = make_sharded_pipeline(mesh)
    got_assign, got_score = sharded(*args, key, deterministic=deterministic)
    np.testing.assert_array_equal(np.asarray(ref_score), np.asarray(got_score))
    np.testing.assert_array_equal(np.asarray(ref_assign), np.asarray(got_assign))


@pytest.mark.parametrize("pods_parallel", [2, 4])
@pytest.mark.parametrize("deterministic", [True, False])
def test_sharded_pipeline_2d_mesh(pods_parallel, deterministic):
    """A ("pods", "nodes") 2D mesh — data-parallel mask/score compute with
    node-sharded commit — produces the same result as 1D, including the
    selectHost tie-break noise path (dryrun_multichip's default config)."""
    args = _encode(43)
    key = jax.random.PRNGKey(7)
    ref_assign, ref_score = solve_pipeline(*args, key, deterministic=deterministic)
    mesh = node_mesh(8, pods_parallel=pods_parallel)
    sharded = make_sharded_pipeline(mesh)
    got_assign, got_score = sharded(*args, key, deterministic=deterministic)
    np.testing.assert_array_equal(np.asarray(ref_score), np.asarray(got_score))
    np.testing.assert_array_equal(np.asarray(ref_assign), np.asarray(got_assign))


def test_sharded_residuals_bind_within_batch():
    """Capacity consumed by an earlier pod on one shard is visible to later
    pods' commits across shards: pack a node tight and assert the sharded
    scan spills exactly like the single-device one."""
    from kubernetes_tpu.api.types import Container, Node, Pod, Quantity, RESOURCE_CPU, RESOURCE_MEMORY, RESOURCE_PODS

    g = ClusterGen(44)
    nodes = []
    for i in range(16):
        # one big node the scorer will prefer, fifteen small
        cpu = "8" if i == 0 else "2"
        nodes.append(Node(
            name=f"n{i}",
            labels={"kubernetes.io/hostname": f"n{i}"},
            allocatable={
                RESOURCE_CPU: Quantity.parse(cpu),
                RESOURCE_MEMORY: Quantity.parse("16Gi"),
                RESOURCE_PODS: Quantity.parse(110),
            },
        ))
    snap = Snapshot(nodes, [])
    pods = [
        Pod(name=f"p{i}", namespace="d", containers=[
            Container(name="c", requests={RESOURCE_CPU: Quantity.parse("1500m")})])
        for i in range(12)
    ]
    args = encode_solve_args(snap, pods)[:-1]
    key = jax.random.PRNGKey(3)
    ref_assign, _ = solve_pipeline(*args, key, deterministic=True)
    sharded = make_sharded_pipeline(node_mesh(8))
    got_assign, _ = sharded(*args, key, deterministic=True)
    np.testing.assert_array_equal(np.asarray(ref_assign), np.asarray(got_assign))
    # all 12 pods placed, none on -1, and no node over its 5-pod cpu capacity
    placed = np.asarray(got_assign)[:12]
    assert (placed >= 0).all()
    counts = np.bincount(placed, minlength=16)
    assert counts[0] <= 5  # 8 cpu / 1.5 = 5 pods max on the big node
    assert (counts[1:16] <= 1).all()  # 2 cpu / 1.5 = 1 pod per small node


def test_sharded_chunked_contention_multi_chunk():
    """B=256 (4 chunks of 64) fighting over 8 tight nodes on an 8-shard
    mesh: the cross-shard chunk repair loop (election + pmin(first_rej) +
    chunk-scan carry) must stay bit-identical to the single-device solver
    across chunk boundaries, in both tie-break modes."""
    import numpy as np

    from kubernetes_tpu.ops.solver import solve_greedy

    rng = np.random.RandomState(5)
    B, N, R = 256, 8, 2
    mask = jnp.asarray(rng.rand(B, N) < 0.9)
    score = jnp.asarray(rng.randint(0, 3, (B, N)).astype(np.int64))
    req = jnp.asarray(rng.randint(1, 4, (B, R)).astype(np.int64))
    req_any = jnp.ones(B, bool)
    free = jnp.asarray(rng.randint(10, 30, (N, R)).astype(np.int64))
    count = jnp.zeros(N, jnp.int64)
    allowed = jnp.full(N, 12, jnp.int64)
    order = jnp.arange(B, dtype=jnp.int32)
    key = jax.random.PRNGKey(5)
    mesh = node_mesh(8)
    from functools import partial

    from kubernetes_tpu.parallel.mesh import shard_map
    from kubernetes_tpu.parallel.sharded import _solver_body
    from jax.sharding import PartitionSpec as P

    for det in (False, True):
        expect = np.asarray(solve_greedy(
            mask, score, req, free, count, allowed, order, key,
            deterministic=det, req_any=req_any,
        ))
        if det:
            noise = jnp.zeros((B, 8))
        else:
            from kubernetes_tpu.ops.solver import tie_noise

            noise = tie_noise(key, B, N)
        solver = shard_map(
            partial(_solver_body, deterministic=det, n_local=1),
            mesh=mesh,
            in_specs=(P(None, "nodes"), P(None, "nodes"), P(), P("nodes"),
                      P("nodes"), P("nodes"), P(), P(None, "nodes"), P(),
                      P(), P(), P("nodes"), P()),
            out_specs=(P(), P("nodes"), P("nodes"), P("nodes")),
        )
        choices, _, _, _ = solver(
            mask, score, req, free.astype(jnp.int64), count,
            allowed, order, noise, req_any,
            jnp.arange(B, dtype=jnp.int32), jnp.ones(B, bool),
            jnp.zeros((N, 2), jnp.int64), jnp.zeros((B, 2), jnp.int64))
        got = np.asarray(jnp.full((B,), -1, jnp.int32).at[order].set(choices))
        assert (got == expect).all(), (det, np.nonzero(got != expect))
        assert (got == -1).sum() > 0  # contention actually rejected pods


def test_multihost_mesh_single_process():
    """multihost_node_mesh over the 8 virtual devices + the sharded solve:
    the DCN wiring is a plain Mesh, so the single-process path must produce
    the same bit-identical assignment as the 1D node mesh."""
    from kubernetes_tpu.parallel.multihost import init_distributed, multihost_node_mesh

    assert init_distributed() == 0  # single-process no-op path
    mesh = multihost_node_mesh(pods_axis=2)
    assert mesh.shape["nodes"] == 4 and mesh.shape["pods"] == 2
    args = _encode(seed=3)
    key = jax.random.PRNGKey(3)
    want_assign, want_score = solve_pipeline(*args, key, deterministic=True)
    sharded = make_sharded_pipeline(mesh)
    got_assign, got_score = sharded(*args, key, deterministic=True)
    assert np.array_equal(np.asarray(want_assign), np.asarray(got_assign))
    assert np.array_equal(np.asarray(want_score), np.asarray(got_score))


@pytest.mark.parametrize("pods_parallel", [1, 2])
def test_driver_over_mesh_matches_single_device(pods_parallel):
    """PRODUCTION-path parity (round-2 VERDICT missing #1): a Scheduler
    constructed with a mesh must produce bit-identical binds to the
    single-device Scheduler on the same cluster — including consuming the
    sharded speculative carry (spec_hits > 0) and the noise tie-break."""
    from kubernetes_tpu.models.generators import ClusterGen
    from kubernetes_tpu.scheduler.driver import Binder, Scheduler
    from kubernetes_tpu.state.cache import SchedulerCache
    from kubernetes_tpu.state.queue import PriorityQueue

    def run(mesh_arg):
        g = ClusterGen(31)
        nodes, existing = g.cluster(16, 40, feature_rate=0.5)
        cache = SchedulerCache()
        for nd in nodes:
            cache.add_node(nd)
        for p in existing:
            cache.add_pod(p)
        binds = {}
        sched = Scheduler(
            cache=cache, queue=PriorityQueue(),
            binder=Binder(lambda p, n: binds.__setitem__(p.key(), n)),
            batch_size=8, enable_preemption=False, seed=11, mesh=mesh_arg,
        )
        # constraint-free pods keep the speculative chain alive (anti
        # commits poison it by design); the mixed existing pods still
        # exercise the topology kernels in mask/score
        for i in range(24):
            sched.queue.add(g.pod(70_000 + i, 0.0))
        total = 0
        while True:
            r = sched.schedule_batch()
            if r.scheduled == 0 and r.unschedulable == 0 and r.errors == 0:
                break
            total += r.scheduled
        sched.wait_for_binds()
        sched.close()
        return binds, total, sched.stats.get("spec_hits", 0)

    mesh = node_mesh(8, pods_parallel=pods_parallel)
    binds_mesh, n_mesh, hits = run(mesh)
    binds_one, n_one, _ = run(None)
    assert n_mesh == n_one
    assert binds_mesh == binds_one, (binds_mesh, binds_one)
    assert hits >= 1, "sharded speculative carry never consumed"


def test_driver_over_mesh_gang():
    """Gang batches route through the sharded all-or-nothing twin when a
    mesh is configured; verdict must match the single-device driver."""
    from kubernetes_tpu.models.generators import make_node, make_pod
    from kubernetes_tpu.scheduler.driver import POD_GROUP_LABEL, Binder, Scheduler
    from kubernetes_tpu.state.cache import SchedulerCache
    from kubernetes_tpu.state.queue import PriorityQueue

    def run(mesh_arg):
        cache = SchedulerCache()
        for i in range(8):
            cache.add_node(make_node(f"n{i}", cpu_milli=1000, mem=8 * 2**30))
        binds = {}
        sched = Scheduler(
            cache=cache, queue=PriorityQueue(),
            binder=Binder(lambda p, n: binds.__setitem__(p.key(), n)),
            batch_size=32, deterministic=True, enable_preemption=False,
            mesh=mesh_arg,
        )
        # gang A (4 x 400m) fits spread out; gang B (8 x 900m) cannot fully
        # fit alongside and must be dropped whole
        for m in range(4):
            p = make_pod(f"a{m}", cpu_milli=400, mem=2**20,
                         labels={POD_GROUP_LABEL: "ga"})
            p.priority = 10
            sched.queue.add(p)
        for m in range(12):
            p = make_pod(f"b{m}", cpu_milli=900, mem=2**20,
                         labels={POD_GROUP_LABEL: "gb"})
            p.priority = 5
            sched.queue.add(p)
        r = sched.schedule_batch()
        sched.wait_for_binds()
        return binds, r

    mesh = node_mesh(8)
    binds_mesh, r_mesh = run(mesh)
    binds_one, r_one = run(None)
    assert binds_mesh == binds_one, (binds_mesh, binds_one)
    assert r_mesh.scheduled == r_one.scheduled
    assert set(binds_mesh) == {f"default/a{m}" for m in range(4)}


def test_sharded_arbiter_verdicts_match_host_and_single_device():
    """The shard_map'd commit arbiter (commit/arbiter.make_sharded_arbiter,
    dispatched via pipeline.arbitrate) must produce BIT-IDENTICAL verdicts
    to both the single-device arbiter and the pure-oracle host walk, on a
    mixed anti/hard-spread/ports batch — the commit plane's multi-chip
    parity pin."""
    from kubernetes_tpu.api.types import (
        Affinity,
        ContainerPort,
        LabelSelector,
        PodAffinityTerm,
        PodAntiAffinity,
        TopologySpreadConstraint,
    )
    from kubernetes_tpu.commit import host_arbitrate
    from kubernetes_tpu.models.generators import make_node, make_pod
    from kubernetes_tpu.scheduler.driver import Binder, Scheduler
    from kubernetes_tpu.state.cache import SchedulerCache
    from kubernetes_tpu.state.queue import PriorityQueue

    HOST = "kubernetes.io/hostname"
    ZONE = "zone"

    def verdicts(mesh_arg):
        cache = SchedulerCache()
        for i in range(8):
            cache.add_node(make_node(
                f"n{i}", cpu_milli=4000, labels={HOST: f"n{i}", ZONE: f"z{i % 2}"},
            ))
        sched = Scheduler(
            cache=cache, queue=PriorityQueue(), binder=Binder(),
            deterministic=True, enable_preemption=False, mesh=mesh_arg,
        )
        for i in range(6):
            p = make_pod(f"a{i}", cpu_milli=100, labels={"app": "g"})
            p.affinity = Affinity(pod_anti_affinity=PodAntiAffinity(required=[
                PodAffinityTerm(
                    label_selector=LabelSelector(match_labels={"app": "g"}),
                    topology_key=HOST,
                )
            ]))
            sched.queue.add(p)
        for i in range(6):
            p = make_pod(f"s{i}", cpu_milli=50, labels={"app": "web"})
            p.topology_spread_constraints = [TopologySpreadConstraint(
                max_skew=1, topology_key=ZONE,
                when_unsatisfiable="DoNotSchedule",
                label_selector=LabelSelector(match_labels={"app": "web"}),
            )]
            sched.queue.add(p)
        for i in range(4):
            p = make_pod(f"hp{i}", cpu_milli=50)
            p.containers[0].ports = [ContainerPort(host_port=8080)]
            sched.queue.add(p)
        infos = sched.queue.pop_batch(16)
        out = sched._finish_solve(sched._dispatch_solve(infos))
        assert out.verdicts is not None, "arbiter was not dispatched on-mesh"
        host = host_arbitrate(
            [i.pod for i in infos], out.assign,
            sched.mirror.node_name_of_row, sched.cache.snapshot,
        )
        return list(out.assign), [int(v) for v in out.verdicts], host

    a_mesh, v_mesh, host_mesh = verdicts(node_mesh(8))
    a_one, v_one, _ = verdicts(None)
    assert a_mesh == a_one
    assert v_mesh == v_one
    assert v_mesh == host_mesh


def test_driver_over_mesh_zero_round_trip_steady_state():
    """The tentpole's acceptance pin: a covered drain on the 8-way mesh
    commits EVERY batch through the device arbiter, folds EVERY batch's
    deltas into the sharded resident banks (no usage bytes shipped), never
    falls back to the replicated pipeline, keeps device/host bank
    bit-parity — and schedules pod-for-pod identically to the
    single-device driver."""
    import time as _time

    from kubernetes_tpu.api.types import Affinity, LabelSelector, PodAffinityTerm, PodAntiAffinity
    from kubernetes_tpu.models.generators import make_node, make_pod
    from kubernetes_tpu.scheduler.driver import Binder, Scheduler
    from kubernetes_tpu.state.cache import SchedulerCache
    from kubernetes_tpu.state.queue import PriorityQueue

    HOST = "kubernetes.io/hostname"

    def run(mesh_arg):
        cache = SchedulerCache()
        for i in range(8):
            cache.add_node(make_node(f"n{i}", cpu_milli=4000, labels={HOST: f"n{i}"}))
        binds = {}
        sched = Scheduler(
            cache=cache, queue=PriorityQueue(),
            binder=Binder(lambda p, n: binds.__setitem__(p.key(), n)),
            deterministic=True, enable_preemption=False, batch_size=8,
            mesh=mesh_arg,
        )
        for i in range(24):
            if i % 4 == 0:
                p = make_pod(f"a{i}", cpu_milli=100, labels={"app": f"g{i % 8}"})
                p.affinity = Affinity(pod_anti_affinity=PodAntiAffinity(required=[
                    PodAffinityTerm(
                        label_selector=LabelSelector(
                            match_labels={"app": p.labels["app"]}
                        ),
                        topology_key=HOST,
                    )
                ]))
                sched.queue.add(p)
            else:
                sched.queue.add(make_pod(f"p{i}", cpu_milli=100))
        total = 0
        for _ in range(40):
            r = sched.schedule_batch()
            total += r.scheduled
            if (r.scheduled == 0 and r.unschedulable == 0 and r.errors == 0
                    and r.deferred == 0):
                active, backoff, unsched = sched.queue.counts()
                if not (active + backoff + unsched):
                    break
                _time.sleep(0.05)
                sched.queue.move_all_to_active()
        sched.wait_for_binds()
        sched._commit_pipe.drain()
        sched.mirror.sync()
        sched.mirror.device_arrays()
        div = sched.mirror.device_bank_divergence()
        stats = dict(sched.stats)
        shipped = dict(sched.mirror.bytes_shipped)
        undonated = sched.mirror.folds_undonated
        sched.close()
        return binds, total, stats, div, shipped, undonated

    b_mesh, n_mesh, st, div, shipped, undonated = run(node_mesh(8))
    b_one, n_one, _, _, _, _ = run(None)
    assert n_mesh == n_one == 24
    assert b_mesh == b_one, (b_mesh, b_one)
    batches = st["batches"]
    assert st.get("arbiter_batches", 0) == batches, st
    assert st.get("fold_batches", 0) == batches, st
    assert st.get("sharded_fallbacks", 0) == 0, st
    assert div == [], div
    assert shipped.get("usage", 0) == 0, shipped
    assert undonated == 0


def test_sharded_fallback_is_observable():
    """A mesh whose shard count does not divide the node bucket must still
    schedule correctly (replicated fallback) — but the fallback is now
    COUNTED (scheduler_sharded_fallbacks_total / stats), never silent."""
    from kubernetes_tpu.models.generators import make_node, make_pod
    from kubernetes_tpu.scheduler.driver import Binder, Scheduler
    from kubernetes_tpu.state.cache import SchedulerCache
    from kubernetes_tpu.state.queue import PriorityQueue

    cache = SchedulerCache()
    for i in range(4):
        cache.add_node(make_node(f"n{i}", cpu_milli=4000))
    binds = {}
    sched = Scheduler(
        cache=cache, queue=PriorityQueue(),
        binder=Binder(lambda p, n: binds.__setitem__(p.key(), n)),
        deterministic=True, enable_preemption=False, batch_size=8,
        mesh=node_mesh(8),
    )
    # force indivisibility: node capacity 6 % 8 != 0 (capacity buckets are
    # pow-2/min-16 so fake it via the gate's own divisor)
    sched._mesh_shards = 7  # 16 % 7 != 0 → every dispatch falls back
    for i in range(8):
        sched.queue.add(make_pod(f"p{i}", cpu_milli=100))
    r = sched.schedule_batch()
    sched.wait_for_binds()
    assert r.scheduled == 8
    assert sched.stats.get("sharded_fallbacks", 0) >= 1, sched.stats
    sched.close()


@pytest.mark.parametrize("deterministic", [True, False])
def test_driver_over_mesh_inbatch_anti_and_ports(deterministic):
    """The SHARDED solve also sequentializes required anti-affinity and
    host ports in-batch (commit counts replicated, winning bucket broadcast
    from the owner shard): bit-identical placements to the single-device
    driver with ZERO host LIGHT rechecks on both paths — including under
    the selectHost noise tie-break."""
    from kubernetes_tpu.api.types import (
        Affinity,
        Container,
        ContainerPort,
        LabelSelector,
        PodAffinityTerm,
        PodAntiAffinity,
    )
    from kubernetes_tpu.models.generators import make_node, make_pod
    from kubernetes_tpu.scheduler.driver import Binder, Scheduler
    from kubernetes_tpu.state.cache import SchedulerCache
    from kubernetes_tpu.state.queue import PriorityQueue

    HOST = "kubernetes.io/hostname"
    ZONE = "topology.kubernetes.io/zone"

    def run(mesh_arg):
        cache = SchedulerCache()
        for i in range(8):
            cache.add_node(make_node(
                f"n{i}",
                cpu_milli=8000, mem=16 * 2**30,
                labels={HOST: f"n{i}", ZONE: f"z{i % 4}"},
            ))
        binds = {}
        sched = Scheduler(
            cache=cache, queue=PriorityQueue(),
            binder=Binder(lambda p, n: binds.__setitem__(p.key(), n)),
            batch_size=32, deterministic=deterministic,
            enable_preemption=False, seed=5, mesh=mesh_arg, speculate=False,
        )
        term = PodAffinityTerm(
            label_selector=LabelSelector(match_labels={"grp": "a"}),
            topology_key=ZONE,
        )
        for i in range(6):  # 6 zone-anti pods over 4 zones: 4 fit
            p = make_pod(f"anti{i}", cpu_milli=100, mem=2**20,
                         labels={"grp": "a"})
            p.priority = 20
            p.affinity = Affinity(pod_anti_affinity=PodAntiAffinity(required=[term]))
            sched.queue.add(p)
        for i in range(10):  # 10 ported pods over 8 hosts: 8 fit
            p = make_pod(f"port{i}", cpu_milli=100, mem=2**20)
            p.priority = 10
            p.containers[0].ports = [ContainerPort(host_port=9090, container_port=80)]
            sched.queue.add(p)
        r = sched.schedule_batch()
        sched.wait_for_binds()
        return binds, r, dict(sched.stats)

    mesh = node_mesh(8)
    b_mesh, r_mesh, s_mesh = run(mesh)
    b_one, r_one, s_one = run(None)
    assert b_mesh == b_one, (b_mesh, b_one)
    assert r_mesh.scheduled == r_one.scheduled == 12
    assert r_mesh.unschedulable == 4  # 2 anti + 2 port leftovers
    for s in (s_mesh, s_one):
        assert s.get("light_rechecks", 0) == 0, s
        assert s.get("oracle_places", 0) == 0, s
