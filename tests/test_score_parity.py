"""Bit-for-bit parity: device score kernels vs the scalar oracle."""

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from kubernetes_tpu.models.generators import ClusterGen
from kubernetes_tpu.ops import scores as S
from kubernetes_tpu.oracle import Snapshot
from kubernetes_tpu.oracle import priorities as opri
from kubernetes_tpu.state.tensors import PodBatch, _bucket, encode_snapshot

ORACLE_FNS = {
    "least_requested": opri.least_requested_priority,
    "most_requested": opri.most_requested_priority,
    "balanced_allocation": opri.balanced_resource_allocation,
    "node_affinity": opri.node_affinity_priority,
    "taint_toleration": opri.taint_toleration_priority,
    "prefer_avoid_pods": opri.node_prefer_avoid_pods_priority,
    "image_locality": opri.image_locality_priority,
}


def _encode(snap, pods):
    bank, eps, rows = encode_snapshot(snap)
    batch = PodBatch(bank.vocab, _bucket(len(pods)))
    for i, p in enumerate(pods):
        batch.set_pod(i, p)
    na = {k: jnp.asarray(v) for k, v in bank.arrays().items()}
    pa = {k: jnp.asarray(v) for k, v in batch.arrays().items()}
    return na, pa


@pytest.mark.parametrize("seed", [10, 11])
def test_score_parity_random_clusters(seed):
    g = ClusterGen(seed)
    nodes, existing = g.cluster(20, 70, feature_rate=0.5)
    snap = Snapshot(nodes, existing)
    pods = [g.pod(70_000 + i, feature_rate=0.5) for i in range(12)]
    na, pa = _encode(snap, pods)
    device = {k: np.asarray(v) for k, v in S.score_components(na, pa).items()}
    node_names = list(snap.node_infos.keys())
    for name, fn in ORACLE_FNS.items():
        for b, p in enumerate(pods):
            expect = fn(p, snap)
            for n, node_name in enumerate(node_names):
                assert int(device[name][b, n]) == expect[node_name], (
                    f"seed={seed} priority={name} pod={p.name} node={node_name} "
                    f"oracle={expect[node_name]} device={int(device[name][b, n])}"
                )


def test_prefer_avoid_pods_signature():
    import json

    from kubernetes_tpu.models.generators import make_node, make_pod

    node_bad = make_node("n-avoid")
    node_bad.annotations[opri.PREFER_AVOID_PODS_ANNOTATION] = json.dumps(
        {
            "preferAvoidPods": [
                {"podSignature": {"podController": {"kind": "ReplicaSet", "uid": "rs-1"}}}
            ]
        }
    )
    node_ok = make_node("n-ok")
    snap = Snapshot([node_bad, node_ok], [])
    pod = make_pod("p")
    pod.owner_references = [{"kind": "ReplicaSet", "uid": "rs-1", "controller": True}]
    na, pa = _encode(snap, [pod])
    got = np.asarray(S.prefer_avoid_pods(na, pa))
    assert got[0, 0] == 0 and got[0, 1] == S.MAX_NODE_SCORE
    expect = opri.node_prefer_avoid_pods_priority(pod, snap)
    assert expect["n-avoid"] == 0 and expect["n-ok"] == 10
