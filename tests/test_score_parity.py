"""Bit-for-bit parity: device score kernels vs the scalar oracle."""

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from kubernetes_tpu.models.generators import ClusterGen
from kubernetes_tpu.ops import scores as S
from kubernetes_tpu.oracle import Snapshot
from kubernetes_tpu.oracle import priorities as opri
from kubernetes_tpu.state.tensors import PodBatch, _bucket, encode_snapshot

ORACLE_FNS = {
    "least_requested": opri.least_requested_priority,
    "most_requested": opri.most_requested_priority,
    "balanced_allocation": opri.balanced_resource_allocation,
    "node_affinity": opri.node_affinity_priority,
    "taint_toleration": opri.taint_toleration_priority,
    "prefer_avoid_pods": opri.node_prefer_avoid_pods_priority,
    "image_locality": opri.image_locality_priority,
}


def _encode(snap, pods):
    bank, eps, rows = encode_snapshot(snap)
    batch = PodBatch(bank.vocab, _bucket(len(pods)))
    for i, p in enumerate(pods):
        batch.set_pod(i, p)
    na = {k: jnp.asarray(v) for k, v in bank.arrays().items()}
    pa = {k: jnp.asarray(v) for k, v in batch.arrays().items()}
    return na, pa


@pytest.mark.parametrize("seed", [10, 11])
def test_score_parity_random_clusters(seed):
    g = ClusterGen(seed)
    nodes, existing = g.cluster(20, 70, feature_rate=0.5)
    snap = Snapshot(nodes, existing)
    pods = [g.pod(70_000 + i, feature_rate=0.5) for i in range(12)]
    na, pa = _encode(snap, pods)
    device = {k: np.asarray(v) for k, v in S.score_components(na, pa).items()}
    node_names = list(snap.node_infos.keys())
    for name, fn in ORACLE_FNS.items():
        for b, p in enumerate(pods):
            expect = fn(p, snap)
            for n, node_name in enumerate(node_names):
                assert int(device[name][b, n]) == expect[node_name], (
                    f"seed={seed} priority={name} pod={p.name} node={node_name} "
                    f"oracle={expect[node_name]} device={int(device[name][b, n])}"
                )


def test_prefer_avoid_pods_signature():
    import json

    from kubernetes_tpu.models.generators import make_node, make_pod

    node_bad = make_node("n-avoid")
    node_bad.annotations[opri.PREFER_AVOID_PODS_ANNOTATION] = json.dumps(
        {
            "preferAvoidPods": [
                {"podSignature": {"podController": {"kind": "ReplicaSet", "uid": "rs-1"}}}
            ]
        }
    )
    node_ok = make_node("n-ok")
    snap = Snapshot([node_bad, node_ok], [])
    pod = make_pod("p")
    pod.owner_references = [{"kind": "ReplicaSet", "uid": "rs-1", "controller": True}]
    na, pa = _encode(snap, [pod])
    got = np.asarray(S.prefer_avoid_pods(na, pa))
    assert got[0, 0] == 0 and got[0, 1] == S.MAX_NODE_SCORE
    expect = opri.node_prefer_avoid_pods_priority(pod, snap)
    assert expect["n-avoid"] == 0 and expect["n-ok"] == 10


# ---------------------------------------------------------------------------
# RequestedToCapacityRatio (requested_to_capacity_ratio.go) + ResourceLimits
# (resource_limits.go)
# ---------------------------------------------------------------------------

from kubernetes_tpu.api.types import Quantity, RESOURCE_CPU, RESOURCE_MEMORY

RTCR_SHAPES = [
    S.DEFAULT_RTCR_SHAPE,  # least-utilized preferred
    ((0, 0), (100, 10)),  # bin-packing: most-utilized preferred
    ((0, 0), (40, 6), (60, 6), (100, 2)),  # plateau with down-slope tail
]
RTCR_RESOURCE_SETS = [
    S.DEFAULT_RTCR_RESOURCES,
    (("cpu", 3), ("memory", 1)),
    (("memory", 2),),
]


@pytest.mark.parametrize("seed", [21, 22])
@pytest.mark.parametrize("shape_i", range(len(RTCR_SHAPES)))
def test_requested_to_capacity_ratio_parity(seed, shape_i):
    shape = RTCR_SHAPES[shape_i]
    resources = RTCR_RESOURCE_SETS[shape_i]
    g = ClusterGen(seed)
    nodes, existing = g.cluster(16, 50, feature_rate=0.4)
    snap = Snapshot(nodes, existing)
    pods = [g.pod(80_000 + i, feature_rate=0.3) for i in range(8)]
    na, pa = _encode(snap, pods)
    device = np.asarray(S.requested_to_capacity_ratio(na, pa, shape, resources))
    node_names = list(snap.node_infos.keys())
    for b, p in enumerate(pods):
        expect = opri.requested_to_capacity_ratio_priority(p, snap, shape, resources)
        for n, node_name in enumerate(node_names):
            assert int(device[b, n]) == expect[node_name], (
                f"seed={seed} shape={shape} pod={p.name} node={node_name} "
                f"oracle={expect[node_name]} device={int(device[b, n])}"
            )


def test_rtcr_full_node_evaluates_at_100_percent():
    from kubernetes_tpu.models.generators import make_node, make_pod

    n_full = make_node("n-full", cpu_milli=100, mem=2**30)
    n_big = make_node("n-big", cpu_milli=64_000, mem=64 * 2**30)
    snap = Snapshot([n_full, n_big], [])
    pod = make_pod("p", cpu_milli=500)
    expect = opri.requested_to_capacity_ratio_priority(pod, snap)
    # cpu requested (500m) > capacity (100m) → p=100 → cpu score 0, which the
    # reference EXCLUDES from the weighted mean; memory (128Mi/1Gi = 13%
    # utilization) scores 10 + trunc(-10*13/100) = 9 and carries the mean
    assert expect["n-full"] == 9
    # both resources near-idle on the big node → full score
    assert expect["n-big"] == 10
    na, pa = _encode(snap, [pod])
    got = np.asarray(S.requested_to_capacity_ratio(na, pa))
    names = list(snap.node_infos.keys())
    for i, nm in enumerate(names):
        assert int(got[0, i]) == expect[nm]


@pytest.mark.parametrize("seed", [31, 32])
def test_resource_limits_parity(seed):
    g = ClusterGen(seed)
    nodes, existing = g.cluster(12, 30, feature_rate=0.4)
    snap = Snapshot(nodes, existing)
    pods = []
    for i in range(6):
        p = g.pod(90_000 + i, feature_rate=0.3)
        # attach limits the generator doesn't produce: mix of none / cpu-only
        # / huge (unsatisfiable) / both
        if i % 4 == 1:
            p.containers[0].limits = {RESOURCE_CPU: Quantity.parse("500m")}
        elif i % 4 == 2:
            p.containers[0].limits = {
                RESOURCE_CPU: Quantity.parse("9999"),
                RESOURCE_MEMORY: Quantity.parse("9999Ti"),
            }
        elif i % 4 == 3:
            p.containers[0].limits = {
                RESOURCE_CPU: Quantity.parse("1"),
                RESOURCE_MEMORY: Quantity.parse("1Gi"),
            }
        pods.append(p)
    na, pa = _encode(snap, pods)
    device = np.asarray(S.resource_limits(na, pa))
    node_names = list(snap.node_infos.keys())
    for b, p in enumerate(pods):
        expect = opri.resource_limits_priority(p, snap)
        for n, node_name in enumerate(node_names):
            assert int(device[b, n]) == expect[node_name]


def test_resource_limits_init_container_max():
    from kubernetes_tpu.api.types import Container
    from kubernetes_tpu.models.generators import make_node, make_pod

    node = make_node("n", cpu_milli=4000, mem=8 * 2**30)
    snap = Snapshot([node], [])
    pod = make_pod("p")
    pod.containers[0].limits = {RESOURCE_CPU: Quantity.parse("1")}
    # init container limit larger than the container sum → max wins
    pod.init_containers = [
        Container(name="init", limits={RESOURCE_CPU: Quantity.parse("8")})
    ]
    assert opri._pod_resource_limits(pod) == (8000, 0)
    # 8 cores > 4 allocatable and no mem limit → score 0
    assert opri.resource_limits_priority(pod, snap)["n"] == 0
