"""Regression tests for round-1 advisor/judge findings:

1. intra-batch required anti-affinity vs constraint-free pods (ADVICE high)
2. term-table overflow → oracle fallback (ADVICE high)
3. nominated-node protection + clear list (ADVICE med / generic_scheduler.go:612)
4. ImageLocality in the production device path (ADVICE med)
5. zero-request pods on overcommitted nodes (ADVICE low / predicates.go:854)
6. skipPodUpdate semantics (eventhandlers.go:336)
7. PDB-aware preemption (generic_scheduler.go:1055)
8. incremental (dirty-only) TensorMirror sync
"""

import time

import pytest

pytest.importorskip("jax")

from kubernetes_tpu.api.types import (
    Affinity,
    LabelSelector,
    LabelSelectorRequirement,
    PodAffinityTerm,
    PodAntiAffinity,
    PodDisruptionBudget,
)
from kubernetes_tpu.models.generators import make_node, make_pod
from kubernetes_tpu.scheduler.driver import Binder, Scheduler
from kubernetes_tpu.scheduler.eventhandlers import EventHandlers
from kubernetes_tpu.state.cache import SchedulerCache, TensorMirror
from kubernetes_tpu.state.queue import PriorityQueue

HOSTNAME = "kubernetes.io/hostname"


def _mk(nodes, existing=(), **kw):
    cache = SchedulerCache()
    for n in nodes:
        cache.add_node(n)
    for p in existing:
        cache.add_pod(p)
    binds = []
    binder = Binder(lambda pod, node: binds.append((pod.key(), node)))
    sched = Scheduler(cache=cache, queue=PriorityQueue(), binder=binder,
                      deterministic=True, **kw)
    return sched, binds


def _host_nodes(n, **kw):
    return [make_node(f"n{i}", labels={HOSTNAME: f"n{i}"}, **kw) for i in range(n)]


# 1 ─ intra-batch anti-affinity: the anti-affinity CARRIER commits first
# (higher priority), then a constraint-free pod whose labels match the
# carrier's term must not land in the carrier's topology domain.
def test_constraint_free_pod_respects_earlier_anti_affinity_commit():
    nodes = _host_nodes(2)
    sched, _ = _mk(nodes)
    term = PodAffinityTerm(
        label_selector=LabelSelector(match_labels={"app": "x"}),
        topology_key=HOSTNAME,
    )
    carrier = make_pod("carrier", labels={"app": "x"})
    carrier.affinity = Affinity(pod_anti_affinity=PodAntiAffinity(required=[term]))
    carrier.priority = 100
    free = make_pod("free", labels={"app": "x"})  # no constraints of its own
    free.priority = 0
    sched.queue.add(carrier)
    sched.queue.add(free)
    res = sched.schedule_batch()
    assert res.scheduled == 2, res
    assert res.assignments["default/carrier"] != res.assignments["default/free"]


def test_multi_anti_terms_same_topology_key_both_enforced():
    """A committed pod carrying TWO required anti terms with the SAME
    topologyKey must block later pods matching EITHER term — the conflict
    index buckets terms by (kv, spec) and must evaluate every distinct
    term, not just a bucket representative (predicates.go:1284 iterates
    all existing-pod terms). Unit-tests _BatchConflictIndex directly: the
    device inb tables cover same-dispatch pods, but the host index is the
    guard on speculative-chain rechecks."""
    from kubernetes_tpu.scheduler.driver import _BatchConflictIndex

    nodes = _host_nodes(2)
    t_x = PodAffinityTerm(
        label_selector=LabelSelector(match_labels={"app": "x"}), topology_key=HOSTNAME)
    t_y = PodAffinityTerm(
        label_selector=LabelSelector(match_labels={"app": "y"}), topology_key=HOSTNAME)
    carrier = make_pod("carrier", labels={"team": "z"})
    carrier.affinity = Affinity(pod_anti_affinity=PodAntiAffinity(required=[t_x, t_y]))
    ix = _BatchConflictIndex()
    ix.add_commit(carrier, nodes[0])
    ix.add_anti(carrier, nodes[0])
    hits_first = make_pod("first", labels={"app": "x"})
    hits_second = make_pod("second", labels={"app": "y"})
    clean = make_pod("clean", labels={"app": "z"})
    assert ix.anti_conflict(hits_first, nodes[0])
    assert ix.anti_conflict(hits_second, nodes[0])  # the dropped-term case
    assert not ix.anti_conflict(clean, nodes[0])
    assert not ix.anti_conflict(hits_second, nodes[1])  # other domain is fine
    # end-to-end: same pair through a real batch still places apart
    sched, _ = _mk(nodes)
    carrier.priority = 100
    later = make_pod("later", labels={"app": "y"})
    later.priority = 0
    sched.queue.add(carrier)
    sched.queue.add(later)
    res = sched.schedule_batch()
    assert res.scheduled == 2, res
    assert res.assignments["default/carrier"] != res.assignments["default/later"]


def test_constraint_free_pod_fails_when_anti_affinity_blocks_everywhere():
    # one node: carrier takes it; the matching constraint-free pod must NOT
    # be committed onto the same host (the reference's sequential loop
    # rejects it via satisfiesExistingPodsAntiAffinity, predicates.go:1284)
    nodes = _host_nodes(1)
    sched, _ = _mk(nodes)
    term = PodAffinityTerm(
        label_selector=LabelSelector(match_labels={"app": "x"}),
        topology_key=HOSTNAME,
    )
    carrier = make_pod("carrier", labels={"app": "x"})
    carrier.affinity = Affinity(pod_anti_affinity=PodAntiAffinity(required=[term]))
    carrier.priority = 100
    free = make_pod("free", labels={"app": "x"})
    sched.queue.add(carrier)
    sched.queue.add(free)
    res = sched.schedule_batch()
    assert res.assignments.get("default/carrier") == "n0"
    assert "default/free" not in res.assignments
    assert res.unschedulable == 1


# 2 ─ term overflow: an existing pod's anti-affinity with >6 In-values is
# truncated on device; the driver must fall back to the oracle rather than
# committing a violating placement.
def test_existing_term_value_overflow_forces_oracle():
    nodes = _host_nodes(1)
    vals = [f"v{i}" for i in range(10)]  # > val_cap (6)
    term = PodAffinityTerm(
        label_selector=LabelSelector(
            match_expressions=[LabelSelectorRequirement(key="app", operator="In", values=vals)]
        ),
        topology_key=HOSTNAME,
    )
    existing = make_pod("anti", node_name="n0", labels={"app": "keeper"})
    existing.affinity = Affinity(pod_anti_affinity=PodAntiAffinity(required=[term]))
    sched, _ = _mk(nodes, existing=[existing])
    # incoming matches value v9 — truncated OUT of the device table, so the
    # device mask wrongly allows n0; the oracle must veto it
    incoming = make_pod("incoming", labels={"app": "v9"})
    sched.queue.add(incoming)
    res = sched.schedule_batch()
    assert "default/incoming" not in res.assignments
    assert res.unschedulable == 1


def test_batch_term_value_overflow_falls_back_to_oracle():
    # the INCOMING pod's own anti-affinity truncates: device over/under-
    # matches; the oracle path must still produce a correct placement
    nodes = _host_nodes(2)
    existing = make_pod("blocker", node_name="n0", labels={"app": "v9"})
    sched, _ = _mk(nodes, existing=[existing])
    vals = [f"v{i}" for i in range(10)]
    term = PodAffinityTerm(
        label_selector=LabelSelector(
            match_expressions=[LabelSelectorRequirement(key="app", operator="In", values=vals)]
        ),
        topology_key=HOSTNAME,
    )
    incoming = make_pod("incoming")
    incoming.affinity = Affinity(pod_anti_affinity=PodAntiAffinity(required=[term]))
    sched.queue.add(incoming)
    res = sched.schedule_batch()
    # v9 is beyond the device value capacity; only the oracle sees the match
    assert res.assignments.get("default/incoming") == "n1"


# 3 ─ nominated-node protection: after preemption nominates a node, a
# lower-priority pod in the next batch must not consume the freed capacity.
def test_nominated_capacity_protected_from_lower_priority():
    nodes = [make_node("n0", cpu_milli=1000, mem=2**30)]
    victim = make_pod("victim", cpu_milli=900, mem=0, node_name="n0")
    victim.priority = 0
    sched, _ = _mk(nodes, existing=[victim])
    urgent = make_pod("urgent", cpu_milli=900, mem=0)
    urgent.priority = 1000
    sched.queue.add(urgent)
    res = sched.schedule_batch()
    assert res.preempted == 1
    assert urgent.nominated_node_name == "n0"
    # queue's nominated index learned of it at requeue time
    assert sched.queue.nominated_pods_for_node("n0")

    # a lower-priority opportunist arrives before the urgent pod's backoff
    opportunist = make_pod("opportunist", cpu_milli=900, mem=0)
    opportunist.priority = 1
    sched.queue.add(opportunist)
    res2 = sched.schedule_batch()
    assert "default/opportunist" not in res2.assignments, res2
    # after backoff, the urgent pod takes its nominated node
    time.sleep(1.1)
    res3 = sched.schedule_batch()
    assert res3.assignments.get("default/urgent") == "n0"


# 4 ─ ImageLocality is live in the device path via TensorMirror.
def test_image_locality_scored_in_device_path():
    from kubernetes_tpu.api.types import ContainerImage

    big = 900 * 2**20
    img = "registry.local/app-0:v1"  # the image make_pod assigns
    nodes = [
        make_node("with-image", images=[ContainerImage(names=[img], size_bytes=big)]),
        make_node("without-image"),
    ]
    sched, _ = _mk(nodes)
    sched.queue.add(make_pod("p0"))
    res = sched.schedule_batch()
    assert res.assignments["default/p0"] == "with-image"


# 5 ─ zero-request pod on an overcommitted node must schedule.
def test_zero_request_pod_on_overcommitted_node():
    node = make_node("n0", cpu_milli=100, mem=2**20)
    hog = make_pod("hog", cpu_milli=200, mem=2**22, node_name="n0")  # overcommit
    sched, _ = _mk([node], existing=[hog])
    empty = make_pod("empty", cpu_milli=0, mem=0)
    sched.queue.add(empty)
    res = sched.schedule_batch()
    assert res.assignments.get("default/empty") == "n0", res


# 6 ─ skipPodUpdate: only assumed pods with RV/nodeName/annotation-only
# diffs are skipped; real spec changes always requeue.
def test_skip_pod_update_semantics():
    import dataclasses

    cache = SchedulerCache()
    queue = PriorityQueue()
    h = EventHandlers(cache, queue)
    cache.add_node(make_node("n0"))

    # an assumed pod: RV-only echo of our own bind → skipped
    assumed = make_pod("a", node_name="n0")
    cache.assume_pod(assumed)
    echo = dataclasses.replace(assumed, resource_version="2")
    moves_before = cache.pod_count()
    h.on_pod_update(assumed, echo)
    assert cache.pod_count() == moves_before  # no churn

    # NOT assumed: identical-looking update must still be processed
    pending = make_pod("b")
    queue.add(pending)
    changed = dataclasses.replace(pending, resource_version="3", labels={"new": "label"})
    h.on_pod_update(pending, changed)
    # the queue sees the new object (labels changed → real update)
    infos = queue.pop_batch(10)
    assert any(i.pod.labels.get("new") == "label" for i in infos)


# 7 ─ PDB-aware preemption: prefer the node whose victims violate no PDB.
def test_preemption_prefers_node_without_pdb_violation():
    nodes = [make_node("n0", cpu_milli=1000, mem=2**30),
             make_node("n1", cpu_milli=1000, mem=2**30)]
    protected = make_pod("protected", cpu_milli=900, mem=0, node_name="n0",
                         labels={"app": "guarded"})
    protected.priority = 0
    plain = make_pod("plain", cpu_milli=900, mem=0, node_name="n1")
    plain.priority = 0
    pdb = PodDisruptionBudget(
        name="guard", namespace="default",
        selector=LabelSelector(match_labels={"app": "guarded"}),
        disruptions_allowed=0,
    )
    cache = SchedulerCache()
    for n in nodes:
        cache.add_node(n)
    cache.add_pod(protected)
    cache.add_pod(plain)
    sched = Scheduler(cache=cache, queue=PriorityQueue(), binder=Binder(),
                      deterministic=True, pdb_lister=lambda: [pdb])
    urgent = make_pod("urgent", cpu_milli=900, mem=0)
    urgent.priority = 1000
    sched.queue.add(urgent)
    res = sched.schedule_batch()
    assert res.preempted == 1
    assert urgent.nominated_node_name == "n1"  # plain victim, no PDB hit
    # the protected pod survived
    assert any(p.name == "protected" for p in cache.snapshot.get("n0").pods)


# 8 ─ TensorMirror sync touches only dirty nodes' pods.
def test_sync_touches_only_dirty_nodes(monkeypatch):
    cache = SchedulerCache()
    for i in range(8):
        cache.add_node(make_node(f"n{i}"))
    for i in range(30):
        cache.add_pod(make_pod(f"p{i}", node_name=f"n{i % 8}"))
    mirror = TensorMirror(cache)

    recounted = []
    orig = type(mirror.eps).encode_node

    def spy(self, node_row, pods):
        recounted.append((node_row, sorted(p.key() for p in pods)))
        return orig(self, node_row, pods)

    monkeypatch.setattr(type(mirror.eps), "encode_node", spy)
    cache.add_pod(make_pod("p-new", node_name="n3"))
    mirror.sync()
    # a single-pod change is a DELTA: no node re-count at all (O(1) patch)
    assert recounted == [], recounted
    # and the delta-maintained signature counts must equal a from-scratch
    # encode of the same snapshot
    from kubernetes_tpu.state.tensors import encode_snapshot

    bank, fresh_eps, row_of = encode_snapshot(cache.snapshot, with_images=False)
    for name, row in mirror.row_of.items():
        mine = {
            s: int(mirror.eps.counts[row, s])
            for s in range(mirror.eps.capacity)
            if mirror.eps.counts[row, s]
        }
        frow = row_of[name]
        theirs = {
            s: int(fresh_eps.counts[frow, s])
            for s in range(fresh_eps.capacity)
            if fresh_eps.counts[frow, s]
        }
        assert sorted(mine.values()) == sorted(theirs.values()), (name, mine, theirs)
    recounted.clear()  # the fresh encode above also went through the spy
    # node-level structural dirt still re-counts that node only
    cache.update_node(make_node("n5"))
    mirror.sync()
    assert len(recounted) == 1 and recounted[0][1] == sorted(
        ["default/p5", "default/p13", "default/p21", "default/p29"]
    ), recounted
