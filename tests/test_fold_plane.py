"""Resident-state plane parity suite (ops/fold + commit/fold + the
TensorMirror fold bookkeeping).

The tentpole's correctness pin: after a seeded drain, the DEVICE banks —
produced by donated fold scatter-adds, never re-shipped from host for the
folded rows — must be BIT-IDENTICAL to the host mirror
(TensorMirror.device_bank_divergence() == []). Scenarios cover every
composition rule: covered-only commits, mixed covered/oracle/escalated
batches, preemption victim deletions, gang rollback, mid-drain node
churn, and a mid-drain signature-bank rebuild (full re-upload while folds
are outstanding). Plus: a drain with the fold plane ON schedules
pod-for-pod identically to plane OFF (the fold is transport, never
policy), the failed-fold correction path, and the A/B microbench smoke.
"""

import time

import numpy as np
import pytest

pytest.importorskip("jax")

from kubernetes_tpu.api.types import (
    Affinity,
    LabelSelector,
    PodAffinity,
    PodAffinityTerm,
    PodAntiAffinity,
    TopologySpreadConstraint,
)
from kubernetes_tpu.models.generators import make_node, make_pod
from kubernetes_tpu.scheduler.driver import (
    Binder,
    POD_GROUP_LABEL,
    POD_GROUP_MIN_AVAILABLE,
    Scheduler,
)
from kubernetes_tpu.state.cache import SchedulerCache
from kubernetes_tpu.state.queue import PriorityQueue

HOST = "kubernetes.io/hostname"
ZONE = "zone"


def _nodes(n, zones=0, cpu=4000):
    out = []
    for i in range(n):
        labels = {HOST: f"n{i}"}
        if zones:
            labels[ZONE] = f"z{i % zones}"
        out.append(make_node(f"n{i}", cpu_milli=cpu, labels=labels))
    return out


def _anti_pod(name, app, cpu=100):
    p = make_pod(name, cpu_milli=cpu, labels={"app": app})
    p.affinity = Affinity(pod_anti_affinity=PodAntiAffinity(required=[
        PodAffinityTerm(
            label_selector=LabelSelector(match_labels={"app": app}),
            topology_key=HOST,
        )
    ]))
    return p


def _aff_pod(name, app, cpu=100):
    """Required pod AFFINITY: uncovered by the arbiter → oracle path."""
    p = make_pod(name, cpu_milli=cpu, labels={"app": app})
    p.affinity = Affinity(pod_affinity=PodAffinity(required=[
        PodAffinityTerm(
            label_selector=LabelSelector(match_labels={"app": app}),
            topology_key=HOST,
        )
    ]))
    return p


def _spread_pod(name, app, cpu=50):
    p = make_pod(name, cpu_milli=cpu, labels={"app": app})
    p.topology_spread_constraints = [TopologySpreadConstraint(
        max_skew=1,
        topology_key=ZONE,
        when_unsatisfiable="DoNotSchedule",
        label_selector=LabelSelector(match_labels={"app": app}),
    )]
    return p


def _mesh8():
    """8-way node mesh or skip (KTPU_TEST_PLATFORM=axon is single-chip)."""
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 devices")
    from kubernetes_tpu.parallel import node_mesh

    return node_mesh(8)


def _mk_sched(nodes, existing=(), on_mesh=False, **kw):
    cache = SchedulerCache()
    for n in nodes:
        cache.add_node(n)
    for p in existing:
        cache.add_pod(p)
    binds = []
    binder = Binder(lambda pod, node: binds.append((pod.key(), node)))
    kw.setdefault("deterministic", True)
    if on_mesh:
        kw["mesh"] = _mesh8()
    sched = Scheduler(cache=cache, queue=PriorityQueue(), binder=binder, **kw)
    return sched, binds


def _drain(sched, rounds=60):
    total, assignments, deferred = 0, {}, 0
    for _ in range(rounds):
        r = sched.schedule_batch()
        total += r.scheduled
        deferred += r.deferred
        assignments.update(r.assignments)
        if (r.scheduled == 0 and r.unschedulable == 0 and r.errors == 0
                and r.deferred == 0):
            active, backoff, unsched = sched.queue.counts()
            if not (active + backoff + unsched):
                break
            time.sleep(0.06)
            sched.queue.move_all_to_active()
    sched.wait_for_binds()
    return total, assignments, deferred


def _assert_parity(sched, expect_folds=True):
    """The suite's core assert: settle everything, ship whatever the host
    still owes, then demand bit-identity — the FOLDED rows were never
    shipped, so any fold bug shows up here."""
    m = sched.mirror
    sched._commit_pipe.drain()
    m.sync()
    m.device_arrays()
    div = m.device_bank_divergence()
    assert div == [], f"device banks diverged: {div}"
    if expect_folds:
        assert sched.stats.get("fold_batches", 0) > 0, sched.stats
    assert m.folds_undonated == 0


# ---------------------------------------------------------------------------
# seeded drain parity
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("on_mesh", [False, True])
def test_covered_only_drain_parity_and_zero_usage_bytes(on_mesh):
    """Plain pods → the bulk fast path folds every batch: the device banks
    stay exact with ZERO usage-column bytes shipped (the tentpole's
    acceptance number, asserted at smoke scale) — single-device AND on
    the 8-way node-sharded mesh (the folds dispatch through the
    shard_map kernels there, donation preserving the NamedSharding)."""
    sched, _ = _mk_sched(
        _nodes(4), enable_preemption=False, batch_size=8, on_mesh=on_mesh
    )
    for i in range(24):
        sched.queue.add(make_pod(f"p{i}", cpu_milli=100))
    n, _, _ = _drain(sched)
    assert n == 24
    _assert_parity(sched)
    assert sched.mirror.bytes_shipped.get("usage", 0) == 0, (
        sched.mirror.bytes_shipped
    )
    assert sched.mirror.bytes_shipped.get("fold", 0) > 0
    if on_mesh:
        assert sched.stats.get("sharded_fallbacks", 0) == 0, sched.stats
    sched.close()


@pytest.mark.parametrize("seed,on_mesh", [(0, False), (1, False), (0, True)])
def test_mixed_covered_oracle_escalated_drain_parity(seed, on_mesh):
    """Arbiter-covered (anti/spread), oracle (required affinity), and
    plain pods in one drain: folded and host-shipped rows interleave on
    the same banks and must compose exactly — on-mesh too (sharded
    arbiter + sharded folds + host-wins rows on sharded banks)."""
    import random

    rng = random.Random(seed)
    sched, _ = _mk_sched(
        _nodes(6, zones=3), enable_preemption=False, batch_size=8,
        on_mesh=on_mesh,
    )
    for i in range(24):
        roll = rng.random()
        if roll < 0.25:
            sched.queue.add(_anti_pod(f"a{i}", app=f"g{rng.randrange(3)}"))
        elif roll < 0.45:
            sched.queue.add(_spread_pod(f"s{i}", app="web"))
        elif roll < 0.55:
            sched.queue.add(_aff_pod(f"f{i}", app="anchor"))
        else:
            sched.queue.add(make_pod(f"p{i}", cpu_milli=100))
    n, _, _ = _drain(sched)
    assert n > 0
    _assert_parity(sched)
    sched.close()


@pytest.mark.parametrize("on_mesh", [False, True])
def test_preemption_drain_parity(on_mesh):
    """Victim deletions dirty their node rows mid-drain (host-wins path)
    while the preemptors' commits fold — and outstanding nominations
    exercise the donated nominee overlay + exact restore. On-mesh the
    overlay folds through the sharded usage kernel and the victim rows
    re-ship onto sharded banks."""
    nodes = _nodes(3, cpu=1000)
    existing = []
    for i, nd in enumerate(nodes):
        v = make_pod(f"victim{i}", cpu_milli=900, node_name=nd.name)
        v.priority = 0
        existing.append(v)
    sched, _ = _mk_sched(
        nodes, existing=existing, enable_preemption=True, batch_size=8,
        on_mesh=on_mesh,
    )
    for i in range(3):
        p = make_pod(f"hi{i}", cpu_milli=800)
        p.priority = 1000
        sched.queue.add(p)
    n, _, _ = _drain(sched)
    assert n == 3
    _assert_parity(sched)
    sched.close()


@pytest.mark.parametrize("on_mesh", [False, True])
def test_gang_rollback_drain_parity(on_mesh):
    """A gang that rolls back (min-available unmet) plus plain pods that
    fold: forget_pods pushes removes the host-wins path must reconcile."""
    sched, _ = _mk_sched(
        _nodes(4), enable_preemption=False, batch_size=16, on_mesh=on_mesh
    )
    for m in range(2):
        sched.queue.add(make_pod(
            f"gm{m}", cpu_milli=100,
            labels={POD_GROUP_LABEL: "g1", POD_GROUP_MIN_AVAILABLE: "4"},
        ))
    for i in range(8):
        sched.queue.add(make_pod(f"p{i}", cpu_milli=100))
    n, _, _ = _drain(sched)
    assert n == 8  # gang rolled back, plain pods landed
    # gang batches never fold (arbiter skips them) — the plain pods may
    # have ridden the same batch as the gang, so folds are not guaranteed
    _assert_parity(sched, expect_folds=False)
    sched.close()


@pytest.mark.parametrize("on_mesh", [False, True])
def test_node_churn_mid_drain_parity(on_mesh):
    """Folds outstanding when nodes arrive AND leave: removed rows are
    released + reused, new rows encode fresh — all host-wins, composed
    with the folded rows (on-mesh: host-wins scatters land on the
    sharded banks without disturbing the folded rows)."""
    sched, _ = _mk_sched(
        _nodes(4), enable_preemption=False, batch_size=8, on_mesh=on_mesh
    )
    for i in range(8):
        sched.queue.add(make_pod(f"p{i}", cpu_milli=100))
    r = sched.schedule_batch()
    assert r.scheduled == 8
    # churn between batches: one node out, one in
    sched.cache.remove_node("n3")
    sched.cache.add_node(make_node("n9", cpu_milli=4000, labels={HOST: "n9"}))
    for i in range(8, 16):
        sched.queue.add(make_pod(f"p{i}", cpu_milli=100))
    n, _, _ = _drain(sched)
    assert n + r.scheduled >= 14  # pods on the removed node may requeue
    _assert_parity(sched)
    sched.close()


def test_sig_bank_rebuild_mid_drain_parity():
    """Distinct label sets overflow a deliberately tiny signature bank
    mid-drain: the rebuild full-re-uploads while folds are outstanding —
    the stale path must discard the fold bookkeeping cleanly."""
    sched, _ = _mk_sched(_nodes(4), enable_preemption=False, batch_size=8)
    sched.mirror._min_sigs = 4
    sched.mirror._rebuild()
    rebuilds0 = sched.mirror.rebuild_count
    for i in range(24):
        # 24 distinct label sets >> 4 signature slots
        sched.queue.add(make_pod(f"p{i}", cpu_milli=50, labels={"u": f"v{i}"}))
    n, _, _ = _drain(sched)
    assert n == 24
    # the overflow rebuild may land mid-drain or at the settle sync below
    # (the last batch's deltas can be the ones that overflow) — either
    # way the fold bookkeeping must compose with the full re-upload
    _assert_parity(sched, expect_folds=False)
    assert sched.mirror.rebuild_count > rebuilds0  # the overflow really hit
    sched.close()


# ---------------------------------------------------------------------------
# plane ON == plane OFF, pod for pod
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("on_mesh", [False, True])
def test_fold_plane_off_schedules_identically(on_mesh):
    def run(fold_plane):
        sched, _ = _mk_sched(
            _nodes(6, zones=3), enable_preemption=False, batch_size=8,
            fold_plane=fold_plane, on_mesh=on_mesh,
        )
        for i in range(12):
            if i % 3 == 0:
                sched.queue.add(_anti_pod(f"a{i}", app="solo"))
            else:
                sched.queue.add(make_pod(f"p{i}", cpu_milli=100))
        n, assignments, _ = _drain(sched)
        stats = dict(sched.stats)
        sched.close()
        return n, assignments, stats

    n_on, asg_on, st_on = run(True)
    n_off, asg_off, st_off = run(False)
    assert n_on == n_off
    assert asg_on == asg_off
    assert st_on.get("fold_batches", 0) > 0
    assert st_off.get("fold_batches", 0) == 0


# ---------------------------------------------------------------------------
# correction + kernel units
# ---------------------------------------------------------------------------

def test_failed_fold_reships_row_host_wins():
    """A fold lane whose assume never lands (informer race) leaves a
    phantom delta on device; note_failed_fold must restore parity via a
    host-wins re-ship at the next sync."""
    from kubernetes_tpu.commit.fold import plan_fold
    from kubernetes_tpu.state.cache import TensorMirror

    cache = SchedulerCache()
    cache.add_node(make_node("n0", cpu_milli=4000, labels={HOST: "n0"}))
    mirror = TensorMirror(cache)
    mirror.device_arrays()
    ghost = make_pod("ghost", cpu_milli=500)
    prog = plan_fold(mirror, [(ghost, mirror.row_of["n0"])], 16, 16)
    assert prog is not None
    assert mirror.fold_commit(prog)
    # the delta landed on device but the assume is never made
    assert mirror.device_bank_divergence() != []
    mirror.note_failed_fold("n0")
    mirror.sync()
    mirror.device_arrays()
    assert mirror.device_bank_divergence() == []


def test_fold_then_host_overlap_host_wins():
    """A row receiving both a folded add and an unfolded remove ships host
    truth — the overwrite must not double-count the folded add."""
    cache = SchedulerCache()
    cache.add_node(make_node("n0", cpu_milli=4000, labels={HOST: "n0"}))
    from kubernetes_tpu.commit.fold import plan_fold
    from kubernetes_tpu.state.cache import TensorMirror

    mirror = TensorMirror(cache)
    mirror.device_arrays()
    pod = make_pod("p0", cpu_milli=500)
    prog = plan_fold(mirror, [(pod, mirror.row_of["n0"])], 16, 16)
    assert mirror.fold_commit(prog)
    assumed = pod.with_node("n0")
    cache.assume_pods([assumed], folded=True)
    mirror.sync()
    mirror.device_arrays()
    assert mirror.device_bank_divergence() == []
    # now an UNFOLDED remove on the same row (bind failure): host wins
    cache.forget_pod(assumed)
    mirror.sync()
    mirror.device_arrays()
    assert mirror.device_bank_divergence() == []
    assert int(mirror.nodes.pod_count[mirror.row_of["n0"]]) == 0


def test_nominee_overlay_restores_exactly():
    """fold_nominees/unfold_nominees: donated overlay + exact integer
    inverse — the resident bank after restore is bit-identical."""
    from kubernetes_tpu.state.cache import TensorMirror

    cache = SchedulerCache()
    cache.add_node(make_node("n0", cpu_milli=4000, labels={HOST: "n0"}))
    mirror = TensorMirror(cache)
    mirror.device_arrays()
    before = np.asarray(mirror._dev_nodes["requested"]).copy()
    n_cap = mirror.nodes.capacity
    width = mirror.nodes.requested.shape[1]
    rows = np.asarray([mirror.row_of["n0"]] + [n_cap] * 15, np.int32)
    vecs = np.zeros((16, width), np.int64)
    vecs[0, 0] = 777
    cnt = np.asarray([1] + [0] * 15, np.int32)
    overlaid = mirror.fold_nominees(rows, vecs, cnt)
    assert int(np.asarray(overlaid["requested"])[mirror.row_of["n0"], 0]) == 777
    mirror.unfold_nominees()
    after = np.asarray(mirror._dev_nodes["requested"])
    assert np.array_equal(before, after)
    assert mirror.device_bank_divergence() == []


def test_microbench_patch_smoke():
    """Tier-1 wiring for scripts/microbench_patch.py: the A/B must run and
    agree bit-for-bit (the assert inside main); timings are reported, not
    asserted (CPU CI jitter)."""
    import os
    import sys

    scripts = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "scripts"
    )
    if scripts not in sys.path:
        sys.path.insert(0, scripts)
    import microbench_patch

    out = microbench_patch.main(smoke=True)
    assert out["rows"], out
    for row in out["rows"]:
        assert row["fold_bytes"] > 0 and row["scatter_bytes"] > 0
        assert row["fold_ms"] >= 0 and row["scatter_ms"] >= 0
