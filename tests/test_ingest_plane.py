"""Pod-ingest plane parity suite (kubernetes_tpu/ingest + the driver's
index-only dispatch).

The tentpole's correctness pin: a drain with the ingest plane ON must
schedule pod-for-pod identically to plane OFF (the plane is transport,
never policy) across mixed/anti/churn/preemption/gang drains, while
covering every quiet dispatch with the index path. Plus the staleness
contract — update + delete between enqueue and pop re-stage or fall back
(counted), slab overflow grows through the ladder, a mirror rebuild
(vocab width growth) bumps the slab generation and the plane self-heals —
the warmup census pin (mid-drain SigBank overflow rebuilds are dead), and
the interleaved A/B microbench smoke.
"""

import os
import sys
import time

import pytest

pytest.importorskip("jax")

from kubernetes_tpu.api.types import (
    Affinity,
    LabelSelector,
    PodAffinityTerm,
    PodAntiAffinity,
    TopologySpreadConstraint,
)
from kubernetes_tpu.models.generators import make_node, make_pod
from kubernetes_tpu.scheduler.driver import Binder, POD_GROUP_LABEL, Scheduler
from kubernetes_tpu.state.cache import SchedulerCache
from kubernetes_tpu.state.queue import PriorityQueue

HOST = "kubernetes.io/hostname"
ZONE = "zone"


def _nodes(n, zones=0, cpu=4000):
    out = []
    for i in range(n):
        labels = {HOST: f"n{i}"}
        if zones:
            labels[ZONE] = f"z{i % zones}"
        out.append(make_node(f"n{i}", cpu_milli=cpu, labels=labels))
    return out


def _anti_pod(name, app, cpu=100):
    p = make_pod(name, cpu_milli=cpu, labels={"app": app})
    p.affinity = Affinity(pod_anti_affinity=PodAntiAffinity(required=[
        PodAffinityTerm(
            label_selector=LabelSelector(match_labels={"app": app}),
            topology_key=HOST,
        )
    ]))
    return p


def _spread_pod(name, app, cpu=50):
    p = make_pod(name, cpu_milli=cpu, labels={"app": app})
    p.topology_spread_constraints = [TopologySpreadConstraint(
        max_skew=1,
        topology_key=ZONE,
        when_unsatisfiable="DoNotSchedule",
        label_selector=LabelSelector(match_labels={"app": app}),
    )]
    return p


def _mk_sched(nodes, existing=(), **kw):
    cache = SchedulerCache()
    for n in nodes:
        cache.add_node(n)
    for p in existing:
        cache.add_pod(p)
    kw.setdefault("deterministic", True)
    sched = Scheduler(
        cache=cache, queue=PriorityQueue(),
        binder=Binder(lambda pod, node: None), **kw
    )
    return sched


def _drain(sched, rounds=60):
    total, assignments = 0, {}
    for _ in range(rounds):
        r = sched.schedule_batch()
        total += r.scheduled
        assignments.update(r.assignments)
        if (r.scheduled == 0 and r.unschedulable == 0 and r.errors == 0
                and r.deferred == 0):
            active, backoff, unsched = sched.queue.counts()
            if not (active + backoff + unsched):
                break
            time.sleep(0.06)
            sched.queue.move_all_to_active()
    sched.wait_for_binds()
    return total, assignments


# ---------------------------------------------------------------------------
# plane ON == OFF pod-for-pod
# ---------------------------------------------------------------------------

def _enqueue_scenario(sched, scenario):
    q = sched.queue
    if scenario == "mixed":
        import random

        rng = random.Random(0)
        for i in range(24):
            roll = rng.random()
            if roll < 0.25:
                q.add(_anti_pod(f"a{i}", app=f"g{rng.randrange(3)}"))
            elif roll < 0.5:
                q.add(_spread_pod(f"s{i}", app=f"sp{rng.randrange(2)}"))
            else:
                q.add(make_pod(f"p{i}", cpu_milli=100 + 10 * (i % 3)))
    elif scenario == "anti":
        for i in range(12):
            q.add(_anti_pod(f"a{i}", app=f"g{i % 4}"))
    elif scenario == "gang":
        for g in range(2):
            for m in range(6):
                q.add(make_pod(
                    f"g{g}m{m}", cpu_milli=100,
                    labels={POD_GROUP_LABEL: f"gang-{g}"},
                ))
        for i in range(6):
            q.add(make_pod(f"p{i}", cpu_milli=100))
    else:
        raise AssertionError(scenario)


@pytest.mark.parametrize("scenario", ["mixed", "anti", "gang"])
def test_drain_parity_plane_on_vs_off(scenario):
    results = {}
    for ingest in (True, False):
        sched = _mk_sched(
            _nodes(6, zones=3), enable_preemption=False, batch_size=8,
            ingest_plane=ingest,
        )
        _enqueue_scenario(sched, scenario)
        sched.warmup()
        n, assigns = _drain(sched)
        results[ingest] = (n, assigns)
        if ingest:
            assert sched.stats.get("ingest_index_batches", 0) > 0, sched.stats
        sched.close()
    assert results[True] == results[False]


def test_preemption_drain_parity_plane_on_vs_off():
    results = {}
    for ingest in (True, False):
        nodes = _nodes(3, cpu=1000)
        existing = []
        for i, nd in enumerate(nodes):
            v = make_pod(f"victim{i}", cpu_milli=900, node_name=nd.name)
            v.priority = 0
            existing.append(v)
        sched = _mk_sched(
            nodes, existing=existing, enable_preemption=True, batch_size=8,
            ingest_plane=ingest,
        )
        for i in range(3):
            p = make_pod(f"hi{i}", cpu_milli=800)
            p.priority = 1000
            sched.queue.add(p)
        sched.warmup()
        n, assigns = _drain(sched)
        results[ingest] = (n, assigns)
        sched.close()
    assert results[True][0] == 3
    assert results[True] == results[False]


def test_node_churn_drain_parity_plane_on_vs_off():
    """Nodes added/removed mid-drain: row remaps + bank rebuilds on the
    node side must not perturb the pod-side plane (and vice versa)."""
    results = {}
    for ingest in (True, False):
        sched = _mk_sched(
            _nodes(4), enable_preemption=False, batch_size=8,
            ingest_plane=ingest,
        )
        for i in range(8):
            sched.queue.add(make_pod(f"p{i}", cpu_milli=100))
        sched.warmup()
        r1 = sched.schedule_batch()
        sched.cache.remove_node("n3")
        sched.cache.add_node(make_node("n9", cpu_milli=4000,
                                       labels={HOST: "n9"}))
        for i in range(8, 16):
            sched.queue.add(make_pod(f"p{i}", cpu_milli=100))
        n, assigns = _drain(sched)
        results[ingest] = (r1.scheduled + n, sorted(assigns))
        sched.close()
    assert results[True] == results[False]


# ---------------------------------------------------------------------------
# staleness: update + delete between enqueue and pop
# ---------------------------------------------------------------------------

def test_update_between_enqueue_and_pop_uses_new_content():
    """An update that changes placement-relevant spec MUST be what the
    solve sees — the stale staged row (old content) is invalidated and
    the entry re-stages on the informer path."""
    sched = _mk_sched(_nodes(4), enable_preemption=False, batch_size=8)
    q = sched.queue
    blocked = make_pod("u0", cpu_milli=100)
    blocked.node_selector = {"no-such-label": "x"}  # fits nowhere
    q.add(blocked)
    fixed = make_pod("u0", cpu_milli=100)  # same key, selector gone
    q.update(blocked, fixed)
    sched.warmup()
    n, assigns = _drain(sched)
    assert n == 1 and "default/u0" in assigns
    sched.close()


def test_delete_between_pop_and_dispatch_counts_stale_and_restages():
    """queue.delete releases the entry's staged row; a popped copy still
    in flight sees the generation mismatch, counts the staleness, and
    re-stages from the captured pod object — the dispatch stays covered
    and the placement is unaffected."""
    sched = _mk_sched(_nodes(4), enable_preemption=False, batch_size=8)
    q = sched.queue
    lone = make_pod("lone", cpu_milli=100, labels={"only": "holder"})
    q.add(lone)
    sched.warmup()
    infos = q.pop_batch(8)
    assert len(infos) == 1 and infos[0].staged_row >= 0
    row, gen = infos[0].staged_row, infos[0].staged_gen
    q.delete(lone)  # last holder: the row frees, generation bumps
    assert not sched.stage.valid_pair(row, gen)
    out = sched._device_solve(infos)
    assert int(out.assign[0]) >= 0
    assert sched.stats.get("ingest_stale_rows", 0) >= 1
    assert sched.stats.get("ingest_restaged", 0) >= 1
    assert sched.stats.get("ingest_index_batches", 0) >= 1  # still covered
    sched.close()


# ---------------------------------------------------------------------------
# slab overflow + width growth
# ---------------------------------------------------------------------------

def test_slab_overflow_grows_capacity_and_invalidates(monkeypatch):
    from kubernetes_tpu.ingest import stage as stage_mod
    from kubernetes_tpu.state.tensors import Vocab

    monkeypatch.setattr(stage_mod, "MIN_CAPACITY", 4)
    st = stage_mod.PodStage(Vocab(), capacity=4)
    pairs = [st.acquire(make_pod(f"d{i}", cpu_milli=100 + i)) for i in range(4)]
    assert all(p is not None for p in pairs)
    # 5th distinct spec: slab full → grows to the next rung, every
    # outstanding pair goes stale (generation bump), staging resumes
    p5 = st.acquire(make_pod("d4", cpu_milli=999))
    assert p5 is not None and st.capacity == 8
    assert st.stats["overflows"] == 1 and st.stats["rebuilds"] == 1
    assert all(not st.valid_pair(r, g) for r, g in pairs)


def test_slab_ceiling_falls_back_to_legacy_dispatch(monkeypatch):
    """When a rep cannot be staged at all, the whole batch takes the
    legacy host-built dispatch — counted, never wrong."""
    sched = _mk_sched(_nodes(4), enable_preemption=False, batch_size=8)
    for i in range(6):
        sched.queue.add(make_pod(f"p{i}", cpu_milli=100 + i))
    sched.warmup()
    # poison every pair + refuse restage: the covered path must bail
    monkeypatch.setattr(sched.stage, "ensure_row", lambda pod: None)
    for info in sched.queue.pending_infos():
        info.staged_row = -1
    n, _ = _drain(sched)
    assert n == 6
    assert sched.stats.get("ingest_legacy_batches", 0) >= 1, sched.stats
    assert sched.stats.get("ingest_stale_rows", 0) >= 1
    sched.close()


def test_prologue_bails_when_slab_rebuilds_mid_resolve(monkeypatch):
    """A slab rebuild DURING row resolution (a stale rep's restage hits a
    full slab and grows it) invalidates the rows already collected — the
    prologue must detect the generation change and fall back to the
    legacy path rather than gather garbage rows from the rebuilt slab."""
    sched = _mk_sched(_nodes(4), enable_preemption=False, batch_size=8)
    for i in range(4):
        sched.queue.add(make_pod(f"p{i}", cpu_milli=100 + i))
    sched.warmup()
    infos = sched.queue.pop_batch(8)
    assert len(infos) == 4
    infos[-1].staged_row = -1  # one stale rep, resolved AFTER the others
    real_ensure = sched.stage.ensure_row

    def growing_ensure(pod):
        sched.stage._rebuild(sched.stage.capacity * 2)
        return real_ensure(pod)

    monkeypatch.setattr(sched.stage, "ensure_row", growing_ensure)
    reps = [pi.pod for pi in infos]
    assert sched._stage_prologue(reps, infos) is None
    # self-heal: the next dispatch restages everything into the new slab
    monkeypatch.setattr(sched.stage, "ensure_row", real_ensure)
    out = sched._device_solve(infos)
    assert all(int(a) >= 0 for a in out.assign[: len(infos)])
    sched.close()


def test_mirror_rebuild_width_growth_bumps_generation_and_self_heals():
    """A vocab key-slot growth (mirror rebuild territory) changes the
    slab's array WIDTHS: every staged row is the wrong shape, the slab
    rebuilds (generation bump), stale entries re-stage at dispatch, and
    the plane returns to covered dispatches."""
    sched = _mk_sched(_nodes(4), enable_preemption=False, batch_size=8)
    q = sched.queue
    for i in range(4):
        q.add(make_pod(f"p{i}", cpu_milli=100))
    sched.warmup()
    gen0 = sched.stage.generation
    n1, _ = _drain(sched)
    # a node with more distinct label keys than the vocab's K=64 width
    wide = make_node("wide", cpu_milli=4000,
                     labels={f"k{j}": "v" for j in range(70)})
    sched.cache.add_node(wide)
    for i in range(4, 8):
        q.add(make_pod(f"p{i}", cpu_milli=100))
    n2, _ = _drain(sched)
    assert n1 + n2 == 8
    assert sched.stage.generation > gen0  # slab rebuilt at the new width
    assert sched.stage.key_capacity == sched.mirror.vocab.config.key_slots
    assert sched.stats.get("ingest_index_batches", 0) >= 2  # covered again
    sched.close()


# ---------------------------------------------------------------------------
# warmup census (satellite: the gang config's mirror_rebuilds root cause)
# ---------------------------------------------------------------------------

def _census_workload(sched, n=340):
    # 340 > 256 by enough that the overflow crosses DURING the drain
    # (sync N interns batch N-1's commits, so the count lags one batch)
    for i in range(n):
        sched.queue.add(make_pod(f"u{i}", cpu_milli=10,
                                 labels={"uniq": f"u{i}"}))


def test_warmup_census_presizes_sigbank_no_midrain_rebuild():
    """More distinct pending label sets than the SigBank's 256-slot
    default: WITHOUT the census the bank overflows as commits intern
    signatures mid-drain (a rebuild + recompile — the gang bench's
    mirror_rebuilds: 1); the census walks the full queue at warmup and
    pre-sizes it, so the drain must finish with rebuild_count == 0."""
    sched = _mk_sched(_nodes(8, cpu=16000), enable_preemption=False,
                      batch_size=64)
    _census_workload(sched)
    sched.warmup()
    assert sched.mirror.eps.capacity >= 340  # census sized it up front
    n, _ = _drain(sched)
    assert n == 340
    assert sched.mirror.rebuild_count == 0, (
        f"mid-drain mirror rebuild(s): {sched.mirror.rebuild_count}"
    )
    sched.close()


def test_without_census_the_same_workload_rebuilds(monkeypatch):
    """Control for the census pin: no-op the census and the identical
    drain MUST rebuild mid-way — proving the census is what kills it."""
    sched = _mk_sched(_nodes(8, cpu=16000), enable_preemption=False,
                      batch_size=64)
    monkeypatch.setattr(sched, "_warmup_census", lambda: None)
    _census_workload(sched)
    sched.warmup()
    n, _ = _drain(sched)
    assert n == 340
    assert sched.mirror.rebuild_count >= 1
    sched.close()


# ---------------------------------------------------------------------------
# wire accounting + microbench smoke
# ---------------------------------------------------------------------------

def test_pods_ledger_index_vs_legacy_bytes():
    """patch_bytes.pods: the covered path ships KB-scale index/control
    vectors where the legacy path ships the full padded pod arrays —
    both measured on the SAME ledger so the claim is a byte count."""
    sizes = {}
    for ingest in (True, False):
        sched = _mk_sched(_nodes(4), enable_preemption=False, batch_size=16,
                          ingest_plane=ingest)
        for i in range(32):
            sched.queue.add(make_pod(f"p{i}", cpu_milli=100,
                                     labels={"app": f"a{i % 8}"}))
        sched.warmup()
        before = sched.mirror.bytes_shipped.get("pods", 0)
        n, _ = _drain(sched)
        assert n == 32
        sizes[ingest] = sched.mirror.bytes_shipped.get("pods", 0) - before
        sched.close()
    assert sizes[True] * 10 < sizes[False], sizes


def test_microbench_ingest_smoke():
    scripts = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "scripts"
    )
    if scripts not in sys.path:
        sys.path.insert(0, scripts)
    import microbench_ingest

    result = microbench_ingest.main(smoke=True)
    assert result["bit_identical"]
    assert result["index_s"] < result["host_built_s"]
    assert result["index_bytes"] < result["host_built_bytes"]


def test_background_uploader_drains_dirty_rows():
    """Rows staged while the drain runs are shipped by the off-thread
    uploader — the driver's dispatch should not have to flush them
    synchronously every batch."""
    sched = _mk_sched(_nodes(4), enable_preemption=False, batch_size=8)
    for i in range(8):
        sched.queue.add(make_pod(f"p{i}", cpu_milli=100))
    sched.warmup()  # arms the uploader + full-uploads the backlog
    # stage fresh specs AFTER the bank upload: dirty rows appear
    for i in range(8, 16):
        sched.queue.add(make_pod(f"q{i}", cpu_milli=100 + i))
    deadline = time.time() + 5
    while sched.stage.dirty_rows and time.time() < deadline:
        time.sleep(0.02)
    assert not sched.stage.dirty_rows, "uploader never drained"
    assert sched.stage_bank.stats["flush_rows"] > 0
    n, _ = _drain(sched)
    assert n == 16
    sched.close()
