"""Watch-stream recovery suite (fault-plane satellite): the reflector's
recover-and-restart discipline pinned with event-loss and duplicate-
dispatch assertions — until now only the relist COUNT was observable.

Four recovery paths:
  * 410 Gone — a compacted resourceVersion forces a relist;
  * mid-stream close — the apiserver drops every watcher (restart);
  * handler raise — a broken handler drops the stream, and the relist
    RE-DELIVERS the event it interrupted (at-least-once: the store
    commits after dispatch, so a raise cannot silently eat an event);
  * remote-watcher reconnect — the HTTP transport's stream dies and the
    informer converges through a fresh list+watch.

The assertions are per-key: every object reaches the handlers at least
once (no loss), no key is dispatched as a FIRST-TIME add twice (the
informer degrades replayed adds to updates), and the local store always
converges to the server's truth.
"""

import threading
import time

import pytest

from kubernetes_tpu.apiserver.store import FakeAPIServer, GoneError, _key_of
from kubernetes_tpu.client.informer import Informer
from kubernetes_tpu.faults import FaultPlan
from kubernetes_tpu.models.generators import make_node, make_pod


class HandlerLog:
    """Thread-safe per-key dispatch log: adds / updates / deletes."""

    def __init__(self, raise_on=None, raises=1):
        self._lock = threading.Lock()
        self.adds = {}
        self.updates = {}
        self.deletes = {}
        self._raise_on = raise_on  # key that raises on its first dispatch(es)
        self._raises_left = raises

    def _bump(self, d, key):
        with self._lock:
            d[key] = d.get(key, 0) + 1

    def _maybe_raise(self, key):
        with self._lock:
            if self._raise_on == key and self._raises_left > 0:
                self._raises_left -= 1
                raise RuntimeError(f"handler bug on {key}")

    def on_add(self, obj):
        self._maybe_raise(_key_of(obj))
        self._bump(self.adds, _key_of(obj))

    def on_update(self, old, new):
        self._maybe_raise(_key_of(new))
        self._bump(self.updates, _key_of(new))

    def on_delete(self, obj):
        self._bump(self.deletes, _key_of(obj))

    def seen(self, key):
        with self._lock:
            return self.adds.get(key, 0) + self.updates.get(key, 0)


def _wait(pred, timeout=8.0, step=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(step)
    return pred()


def _start(api, kind="pods", log=None, fault_plan=None):
    log = log or HandlerLog()
    inf = Informer(api, kind, fault_plan=fault_plan)
    inf.add_event_handler(
        on_add=log.on_add, on_update=log.on_update, on_delete=log.on_delete
    )
    inf.start()
    assert inf.wait_for_sync()
    return inf, log


def test_gone_410_forces_relist_without_loss_or_dup():
    """Compacted history: a watch from a stale rv raises GoneError and
    the informer relists — every pod delivered, no key double-added."""
    api = FakeAPIServer(history_window=4)
    for i in range(3):
        api.create("pods", make_pod(f"a{i}"))
    inf, log = _start(api)
    assert _wait(lambda: all(log.seen(f"default/a{i}") for i in range(3)))
    r0 = inf.relists()
    # age the history PAST the window while no stream is attached, so the
    # re-watch's resourceVersion is compacted → 410 → relist
    api.close_watchers("pods")
    for i in range(8):
        api.create("pods", make_pod(f"b{i}"))
    assert _wait(lambda: all(log.seen(f"default/b{i}") for i in range(8)))
    assert inf.relists() > r0
    # the direct stale watch really is Gone (the 410 path, not a quiet
    # stream restart)
    with pytest.raises(GoneError):
        api.watch("pods", 1)
    # zero loss: every key reached the handlers; zero dup: no key was
    # first-time-added twice (replayed adds degrade to updates)
    for i in range(8):
        assert log.adds.get(f"default/b{i}", 0) == 1
    assert {o.key() for o in inf.list()} == {
        f"default/a{i}" for i in range(3)
    } | {f"default/b{i}" for i in range(8)}
    inf.stop()


def test_mid_stream_close_recovers_and_converges():
    api = FakeAPIServer()
    api.create("nodes", make_node("n0"))
    inf, log = _start(api, kind="nodes")
    assert _wait(lambda: log.adds)
    r0 = inf.relists()
    api.close_watchers("nodes")  # server restart: every stream dies
    api.create("nodes", make_node("n1"))  # lands while no stream is up
    assert _wait(lambda: any("n1" in k for k in log.adds))
    assert _wait(lambda: inf.relists() > r0)
    assert inf.last_relist_reason in ("stream-closed", "gone")
    # no key double-added across the restart
    assert all(v == 1 for v in log.adds.values()), log.adds
    assert len(inf.list()) == 2
    inf.stop()


def test_handler_raise_relists_and_redelivers_event():
    """A raising handler must not LOSE its event: the store commits
    after dispatch, so the relist diff re-delivers the object (at-least-
    once semantics, the reference's pop-after-process)."""
    api = FakeAPIServer()
    api.create("pods", make_pod("ok0"))
    log = HandlerLog(raise_on="default/boom", raises=1)
    inf, _ = _start(api, log=log)
    assert _wait(lambda: log.seen("default/ok0"))
    r0 = inf.relists()
    api.create("pods", make_pod("boom"))  # first dispatch raises
    # the relist must re-deliver it (this was silently lost before: the
    # old _apply committed the store BEFORE dispatch, so the relist diff
    # came back empty for the interrupted event)
    assert _wait(lambda: log.seen("default/boom") > 0)
    assert inf.relists() > r0
    assert inf.last_relist_reason == "handler-error"
    assert inf.get("default/boom") is not None
    # the undisturbed pod was not re-added as a first-timer
    assert log.adds.get("default/ok0") == 1
    inf.stop()


def test_handler_raise_during_relist_dispatch_redelivers():
    """The RELIST-path twin of the watch-path redelivery pin: a handler
    raising while the relist dispatches its diff must not lose events —
    the store commits only after the whole diff dispatched, so the retry
    re-delivers (labeled handler-error, not list-error)."""
    api = FakeAPIServer()
    api.create("pods", make_pod("seed"))
    log = HandlerLog(raise_on="default/lost", raises=1)
    inf, _ = _start(api, log=log)
    assert _wait(lambda: log.seen("default/seed"))
    # create while NO stream is up: the pod arrives via a RELIST diff,
    # whose first dispatch raises
    api.close_watchers("pods")
    api.create("pods", make_pod("lost"))
    assert _wait(lambda: log.seen("default/lost") > 0)
    assert inf.last_relist_reason == "handler-error"
    assert inf.get("default/lost") is not None
    assert log.adds.get("default/seed") == 1  # no duplicate first-add
    inf.stop()


def test_injected_watch_break_and_list_error_recover():
    """The fault plane's informer sites: an injected mid-stream break
    and an injected list error both recover through the relist path with
    capped backoff — no loss, no duplicate first-adds."""
    api = FakeAPIServer()
    for i in range(2):
        api.create("pods", make_pod(f"w{i}"))
    # break the stream on the 1st watched event; fail the 2nd relist once
    plan = FaultPlan.parse("watch-break:pods@1;list-error:pods@2")
    inf, log = _start(api, fault_plan=plan)
    r0 = inf.relists()
    for i in range(2, 6):
        api.create("pods", make_pod(f"w{i}"))
    assert _wait(lambda: all(log.seen(f"default/w{i}") for i in range(6)))
    assert _wait(lambda: inf.relists() > r0)
    assert plan.exhausted(), plan.census()
    # the injected list error surfaced in the error bookkeeping
    assert inf.last_relist_error and "list-error" in inf.last_relist_error
    assert all(v == 1 for v in log.adds.values()), log.adds
    assert len(inf.list()) == 6
    inf.stop()


def test_remote_watcher_reconnects_over_http():
    """The HTTP transport: kill the server-side streams under a remote
    informer; it must reconnect via list+watch and converge."""
    from kubernetes_tpu.apiserver.http import APIServerHTTP
    from kubernetes_tpu.client.remote import RemoteAPIServer

    store = FakeAPIServer()
    srv = APIServerHTTP(store).start()
    try:
        store.create("pods", make_pod("r0"))
        remote = RemoteAPIServer(srv.url)
        log = HandlerLog()
        inf = Informer(remote, "pods")
        inf.add_event_handler(
            on_add=log.on_add, on_update=log.on_update,
            on_delete=log.on_delete,
        )
        inf.start()
        assert inf.wait_for_sync()
        assert _wait(lambda: store._watchers.get("pods"), timeout=5)
        r0 = inf.relists()
        store.close_watchers("pods")  # server restart: streams die
        store.create("pods", make_pod("r1"))
        assert _wait(lambda: log.seen("default/r1") > 0, timeout=10)
        assert inf.relists() > r0
        assert inf.get("default/r1") is not None
        assert all(v == 1 for v in log.adds.values()), log.adds
        inf.stop()
    finally:
        srv.stop()
