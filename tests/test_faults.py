"""Fault-plane suite (kubernetes_tpu/faults): the breaker state machine
on a fake clock, the seeded FaultPlan's deterministic schedule, and the
driver-integrated degradation ladder — trips route planes to their
legacy paths, recoveries resync from host truth, probes re-close only
through the shadow-audit gate, and no pod is ever lost or bound twice.

(The full seeded chaos drain — uploader kill + device raises + watch
break + bind errors + forced skew in one workload — lives in
scripts/perf_smoke.py `faults` mode, wired into test_perf_smoke with
KTPU_LOCK_AUDIT=1.)
"""

import time

import pytest

pytest.importorskip("jax")

from kubernetes_tpu.faults import (
    BreakerBoard,
    CLOSED,
    FaultPlan,
    HALF_OPEN,
    InjectedFault,
    OPEN,
    PLANES,
)
from kubernetes_tpu.models.generators import make_node, make_pod
from kubernetes_tpu.scheduler.driver import Binder, Scheduler
from kubernetes_tpu.state.cache import SchedulerCache
from kubernetes_tpu.state.queue import PriorityQueue


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# ---------------------------------------------------------------------------
# breaker state machine (fake clock, no scheduler)
# ---------------------------------------------------------------------------

def test_breaker_trips_at_counted_threshold_and_cools_down():
    clk = FakeClock()
    board = BreakerBoard(clock=clk, threshold=3, cooldown_s=5.0)
    b = board.breaker("ingest")
    assert b.closed and board.quiet
    assert not board.record_failure("ingest", "boom")
    assert not board.record_failure("ingest", "boom")
    assert board.record_failure("ingest", "boom")  # third: trip
    assert b.state == OPEN and not b.closed and not board.quiet
    assert board.take_recoveries() == ["ingest"]
    # open: no probe before the cool-down expires
    assert not board.ok("ingest")
    clk.advance(4.9)
    assert not board.ok("ingest")
    clk.advance(0.2)
    assert board.ok("ingest")  # half-open: exactly one probe
    assert b.state == HALF_OPEN and b.probing
    assert not board.ok("ingest")  # second caller stays legacy
    b.probe_passed()
    assert b.state == CLOSED and b.closed
    board.settle()
    assert board.quiet


def test_breaker_failure_window_restarts_count():
    """Sporadic faults spread wider than one cool-down must NOT
    accumulate into a trip (windowed counting)."""
    clk = FakeClock()
    board = BreakerBoard(clock=clk, threshold=3, cooldown_s=5.0,
                         window_s=5.0)
    for _ in range(5):
        assert not board.record_failure("fold", "sporadic")
        clk.advance(6.0)  # wider than the window: count restarts
    assert board.breaker("fold").state == CLOSED
    # default window decouples from the cool-down (batch cadence can be
    # much slower than the probe cadence)
    assert BreakerBoard().breaker("fold").window_s >= 30.0


def test_probe_failure_escalates_cooldown_and_force_trip():
    clk = FakeClock()
    board = BreakerBoard(clock=clk, threshold=3, cooldown_s=2.0)
    b = board.breaker("mirror")
    assert board.record_failure("mirror", "shadow-divergence", force=True)
    assert b.state == OPEN  # force: no counted threshold
    clk.advance(2.1)
    assert board.ok("mirror")
    # a fault DURING the probe re-opens with the cool-down doubled
    assert board.record_failure("mirror", "probe-batch-fault")
    assert b.state == OPEN and b.probes_failed == 1
    clk.advance(2.1)
    assert not board.ok("mirror")  # 4s now, not 2s
    clk.advance(2.1)
    assert board.ok("mirror")
    b.probe_passed()
    assert b.state == CLOSED and b._cooldown == 2.0  # escalation reset


def test_board_census_covers_every_plane():
    board = BreakerBoard()
    doc = board.census()
    assert set(doc["breakers"]) == set(PLANES)
    assert doc["quiet"] is True
    for b in doc["breakers"].values():
        assert b["state"] == CLOSED


# ---------------------------------------------------------------------------
# FaultPlan: grammar, determinism, seeded schedules
# ---------------------------------------------------------------------------

def test_fault_plan_parse_grammar_and_counted_fire():
    p = FaultPlan.parse("device-raise:solve@3x2;bind-error;watch-break:pods@2")
    specs = [e.spec() for e in p.events]
    assert specs == ["device-raise:solve@3x2", "bind-error", "watch-break:pods@2"]
    # counted per (site, arg): fires on call 3 and 4 only
    fires = [p.fire("device-raise", "solve") for _ in range(5)]
    assert fires == [False, False, True, True, False]
    assert p.fire("bind-error")  # @1 default
    assert not p.fire("watch-break", "pods")
    assert p.fire("watch-break", "pods")
    assert p.exhausted()
    with pytest.raises(ValueError):
        FaultPlan.parse("bad entry with spaces")


def test_fault_plan_seeded_schedule_is_reproducible():
    sites = [("device-raise", "solve", 10), ("bind-error", "", 6)]
    a = FaultPlan.seeded(42, sites)
    b = FaultPlan.seeded(42, sites)
    c = FaultPlan.seeded(43, sites)
    assert [e.at for e in a.events] == [e.at for e in b.events]
    assert [e.at for e in a.events] != [e.at for e in c.events] or a.seed != c.seed
    assert all(1 <= e.at <= 10 for e in a.events[:1])


def test_forced_report_while_open_still_queues_recovery():
    """An uploader dying DURING another fault's cool-down must still get
    its recovery: a forced report in the OPEN state queues the plane's
    repair action even though it cannot re-trip the breaker (otherwise a
    clean probe would re-close right over the dead thread)."""
    clk = FakeClock()
    board = BreakerBoard(clock=clk, threshold=1, cooldown_s=5.0)
    assert board.record_failure("ingest", "gather-fault")  # trips
    assert board.take_recoveries() == ["ingest"]
    # while OPEN: an unforced report queues nothing...
    assert not board.record_failure("ingest", "another")
    assert board.take_recoveries() == []
    # ...but a FORCED one (known-wrong state) queues the recovery
    assert not board.record_failure("ingest", "uploader-death", force=True)
    assert board.take_recoveries() == ["ingest"]


def test_any_arg_event_counts_site_wide_calls():
    """'fire on the n-th matching call' for an arg-less event means the
    n-th call at the SITE, not the n-th call of every distinct arg."""
    p = FaultPlan.parse("device-raise@2")
    assert not p.fire("device-raise", "solve")   # site call 1
    assert p.fire("device-raise", "fold")        # site call 2: fires
    assert not p.fire("device-raise", "gather-stage")  # call 3: spent
    assert not p.fire("device-raise", "solve")
    assert p.exhausted()
    assert p.events[0].fired == 1  # once total, never once-per-arg


def test_raise_if_raises_injected_fault():
    p = FaultPlan.parse("uploader-death:ingest@1")
    with pytest.raises(InjectedFault):
        p.raise_if("uploader-death", "ingest")


# ---------------------------------------------------------------------------
# queue: bind/solve failures take the backoff tier
# ---------------------------------------------------------------------------

def test_requeue_backoff_exponential_per_pod():
    now = FakeClock()
    q = PriorityQueue(now=now)
    q.add(make_pod("p0"))
    info = q.pop_batch(1)[0]
    q.requeue_backoff(info)
    assert q.counts() == (0, 1, 0)  # backoff tier, not unschedulable
    assert q.pop_batch(1) == []  # 1s initial backoff holds it
    now.advance(1.1)
    info = q.pop_batch(1)[0]
    # second failure: doubled backoff
    q.requeue_backoff(info)
    now.advance(1.1)
    assert q.pop_batch(1) == []  # 2s now
    now.advance(1.0)
    assert len(q.pop_batch(1)) == 1


def test_injected_bind_error_requeues_with_backoff_and_metric():
    from kubernetes_tpu.metrics import metrics as M

    cache = SchedulerCache()
    for i in range(2):
        cache.add_node(make_node(f"n{i}", cpu_milli=4000))
    q = PriorityQueue()
    plan = FaultPlan.parse("bind-error@2")
    s = Scheduler(cache=cache, queue=q, binder=Binder(), batch_size=8,
                  enable_preemption=False, fault_plan=plan)
    rpc0 = M.bind_failures.value("rpc")
    for i in range(4):
        q.add(make_pod(f"p{i}", cpu_milli=50))
    r1 = s.schedule_batch()
    s.wait_for_binds()
    assert r1.scheduled == 4  # counted at commit; one bind failed after
    assert M.bind_failures.value("rpc") == rpc0 + 1
    # the failed pod is in the BACKOFF tier, not unschedulable
    active, backoff, unsched = q.counts()
    assert backoff == 1 and unsched == 0
    time.sleep(1.1)
    total = r1.scheduled - 1  # one bind failed
    for _ in range(10):
        r = s.run_until_empty()
        total += r.scheduled
        if total >= 4:
            break
        time.sleep(0.5)
    s.wait_for_binds()
    assert total == 4
    assert s.cache.pod_count() == 4  # bound exactly once each
    s.close()


# ---------------------------------------------------------------------------
# driver integration: trips route to legacy, probes re-close audit-gated
# ---------------------------------------------------------------------------

def _mini_sched(plan=None, pods=32, cooldown=1.0):
    cache = SchedulerCache()
    for i in range(4):
        cache.add_node(make_node(f"n{i}", cpu_milli=8000))
    q = PriorityQueue()
    s = Scheduler(cache=cache, queue=q, binder=Binder(), batch_size=8,
                  enable_preemption=False, fault_plan=plan)
    clk = FakeClock()
    s.faults = BreakerBoard(clock=clk, cooldown_s=cooldown)
    for i in range(pods):
        q.add(make_pod(f"p{i}", cpu_milli=50))
    return s, q, clk


def _drain(s, q, clk, want, max_cycles=80, step=0.5):
    total = 0
    for _ in range(max_cycles):
        r = s.schedule_batch()
        total += r.scheduled
        clk.advance(step)
        if total >= want:
            break
        if not (r.scheduled or r.unschedulable or r.errors or r.deferred):
            q.flush()
            time.sleep(0.25)  # let backoff requeues expire
    s.wait_for_binds()
    return total


def test_gather_faults_trip_ingest_breaker_then_probe_recloses():
    plan = FaultPlan.parse("device-raise:gather-stage@2x3")
    s, q, clk = _mini_sched(plan, pods=64)
    total = _drain(s, q, clk, want=64)
    assert total == 64
    c = s.faults.census()["breakers"]["ingest"]
    assert c["trips"] == 1 and c["state"] == CLOSED and c["probes_passed"] >= 1
    # while open, dispatches took the LEGACY host path (counted)
    assert s.stats.get("ingest_legacy_batches", 0) >= 1
    assert s.stats.get("ingest_fault_batches", 0) == 3
    assert plan.exhausted()
    s.close()


def test_solve_fault_errors_requeue_and_drain_completes():
    plan = FaultPlan.parse("device-raise:solve@2")
    s, q, clk = _mini_sched(plan, pods=32)
    total = _drain(s, q, clk, want=32)
    assert total == 32
    assert plan.exhausted()
    assert s.cache.pod_count() == 32
    s.close()


def test_uploader_death_restarts_exactly_once_per_trip():
    plan = FaultPlan.parse("uploader-death:ingest@1")
    s, q, clk = _mini_sched(plan, pods=32)
    s.warmup()  # arms the uploader threads
    # let the uploader wake, hit the injected death, and report
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and not s.faults.breakers["ingest"].trips:
        s.stage.on_dirty()  # wake the (possibly already dead) worker
        time.sleep(0.05)
    assert s.faults.breakers["ingest"].trips == 1  # force-trip on death
    total = _drain(s, q, clk, want=32)
    assert total == 32
    bank = s.stage_bank.census()["uploader"]
    assert bank["restarts"] == 1
    assert bank["alive"] is True  # the restarted worker is running
    assert "uploader-death" in str(bank["last_error"])
    s.close()


def test_fold_fault_resyncs_banks_and_audit_stays_clean():
    plan = FaultPlan.parse("device-raise:fold@1x3")
    s, q, clk = _mini_sched(plan, pods=64)
    total = _drain(s, q, clk, want=64)
    assert total == 64
    c = s.faults.census()["breakers"]["fold"]
    assert c["trips"] == 1
    # banks resynced from host truth: the parity probe must be clean
    s.service_faults()
    s.mirror.device_arrays()
    assert s.mirror.device_bank_divergence() == []
    s.close()


def test_columns_fault_detaches_inline_and_probe_reattaches():
    plan = FaultPlan.parse("device-raise:columns@2")
    s, q, clk = _mini_sched(plan, pods=48)
    assert s.cache._columns is not None
    total = _drain(s, q, clk, want=48)
    assert total == 48
    c = s.faults.census()["breakers"]["columns"]
    assert c["trips"] == 1
    # the inline detach preserved object truth mid-batch (every pod
    # landed exactly once in the NodeInfo views)
    assert s.cache.pod_count() == 48
    # the probe re-attached fresh columns and the audit re-closed it
    assert c["state"] == CLOSED
    assert s.cache._columns is not None
    s.close()


def test_shadow_divergence_escalates_trip_resync_blackbox(tmp_path, monkeypatch):
    monkeypatch.setenv("KTPU_BLACKBOX_DIR", str(tmp_path))
    from kubernetes_tpu.faults.inject import apply_bank_skew
    from kubernetes_tpu.metrics import metrics as M

    s, q, clk = _mini_sched(None, pods=16)
    mon = s.enable_health_monitor(interval=3600, audit_every=0, start=False)
    total = _drain(s, q, clk, want=16)
    assert total == 16
    d0 = M.shadow_audit.value("divergent")
    s._commit_pipe.drain()
    s.mirror.sync()
    s.mirror.device_arrays()
    apply_bank_skew(s.mirror)
    div = mon.run_shadow_audit()
    assert div, "forced skew must be detected"
    assert M.shadow_audit.value("divergent") == d0 + 1
    # escalation: metric → automatic trip + queued resync
    b = s.faults.breakers["mirror"]
    assert b.trips == 1 and b.last_reason == "shadow-divergence"
    # the driver's next safe point resyncs + probes + re-closes
    s.service_faults()  # recovery (resync queued at trip)
    clk.advance(10.0)
    s.service_faults()  # half-open
    s.service_faults()  # audit-gated close
    assert b.state == CLOSED
    assert mon.run_shadow_audit() == []  # resynced from host truth
    s.close()


def test_no_fault_plan_means_no_plan_attribute_and_quiet_board():
    """The zero-overhead contract: without KTPU_FAULTS / fault_plan, every
    injection site sees None (one attribute read) and the board is quiet
    (one bool read per batch)."""
    s, q, clk = _mini_sched(None, pods=8)
    assert s._fault_plan is None
    assert s.mirror.fault_plan is None
    assert s.stage_bank.fault_plan is None
    assert s.cache._columns is not None and s.cache._columns.fault_hook is None
    assert s.faults.quiet
    total = _drain(s, q, clk, want=8)
    assert total == 8
    assert s.faults.quiet and s.faults.trips_total() == 0
    s.close()


def test_census_and_gauges_reflect_breaker_transitions():
    from kubernetes_tpu.metrics import metrics as M
    from kubernetes_tpu.obs import introspect as insp

    plan = FaultPlan.parse("device-raise:gather-stage@1x3")
    s, q, clk = _mini_sched(plan, pods=48)
    total = _drain(s, q, clk, want=48)
    assert total == 48
    doc = insp.census(s)
    assert insp.validate_census(doc) == []
    faults = doc["planes"]["faults"]
    assert faults["breakers"]["ingest"]["trips"] == 1
    assert faults["plan"]["events"][0]["fired"] == 3
    assert M.plane_trips.value("ingest", "InjectedFault") >= 1
    assert M.plane_breaker_state.value("ingest") == 0.0  # re-closed
    s.close()
