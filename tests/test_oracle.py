"""Table-driven unit tests for the oracle (reference semantics).

Scenario structure mirrors the reference's predicates_test.go /
priorities *_test.go tables.
"""


from kubernetes_tpu.api.quantity import Quantity
from kubernetes_tpu.api.types import (
    Affinity,
    Container,
    ContainerPort,
    LabelSelector,
    NodeAffinity,
    NodeSelector,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    PodAffinity,
    PodAffinityTerm,
    PodAntiAffinity,
    PreferredSchedulingTerm,
    Taint,
    Toleration,
    TopologySpreadConstraint,
)
from kubernetes_tpu.models.generators import make_node, make_pod
from kubernetes_tpu.oracle import Snapshot, find_nodes_that_fit, pod_fits_on_node
from kubernetes_tpu.oracle.predicates import (
    check_node_unschedulable,
    even_pods_spread_predicate,
    compute_even_pods_spread_metadata,
    compute_pod_affinity_metadata,
    inter_pod_affinity_matches,
    pod_fits_host_ports,
    pod_fits_resources,
    pod_match_node_selector,
    pod_tolerates_node_taints,
)
from kubernetes_tpu.oracle.priorities import (
    MAX_NODE_SCORE,
    balanced_resource_allocation,
    inter_pod_affinity_priority,
    least_requested_priority,
    node_affinity_priority,
    selector_spread_priority,
    taint_toleration_priority,
)


def snap_of(nodes, pods=()):
    return Snapshot(list(nodes), list(pods))


class TestPodFitsResources:
    def test_fits_empty_node(self):
        node = make_node("n1", cpu_milli=1000, mem=2**30)
        snap = snap_of([node])
        pod = make_pod("p", cpu_milli=500, mem=2**29)
        assert pod_fits_resources(pod, snap.get("n1"))

    def test_cpu_exceeded_by_existing(self):
        node = make_node("n1", cpu_milli=1000, mem=2**30)
        existing = make_pod("e", cpu_milli=800, mem=0, node_name="n1")
        snap = snap_of([node], [existing])
        pod = make_pod("p", cpu_milli=300, mem=0)
        assert not pod_fits_resources(pod, snap.get("n1"))

    def test_zero_request_pod_always_fits_resources(self):
        node = make_node("n1", cpu_milli=100, mem=1)
        existing = make_pod("e", cpu_milli=100, mem=1, node_name="n1")
        snap = snap_of([node], [existing])
        pod = make_pod("p", cpu_milli=0, mem=0)
        # zero-request pod skips cpu/mem checks (predicates.go:878-884)
        assert pod_fits_resources(pod, snap.get("n1"))

    def test_pod_count_limit(self):
        node = make_node("n1", pods=1)
        existing = make_pod("e", node_name="n1")
        snap = snap_of([node], [existing])
        pod = make_pod("p", cpu_milli=0, mem=0)
        assert not pod_fits_resources(pod, snap.get("n1"))

    def test_init_container_max_counts_for_incoming_only(self):
        node = make_node("n1", cpu_milli=1000, mem=2**30)
        # existing pod with big init container: init requests do NOT
        # accumulate into node requested (calculateResource)
        existing = make_pod("e", cpu_milli=100, mem=0, node_name="n1")
        existing.init_containers = [
            Container(name="i", requests={"cpu": Quantity.parse("900m")})
        ]
        snap = snap_of([node], [existing])
        # incoming pod with big init container: its request IS max(init, sum)
        pod = make_pod("p", cpu_milli=100, mem=0)
        pod.init_containers = [Container(name="i", requests={"cpu": Quantity.parse("950m")})]
        assert not pod_fits_resources(pod, snap.get("n1"))
        pod2 = make_pod("p2", cpu_milli=100, mem=0)
        pod2.init_containers = [Container(name="i", requests={"cpu": Quantity.parse("800m")})]
        assert pod_fits_resources(pod2, snap.get("n1"))

    def test_extended_resource(self):
        node = make_node("n1")
        node.allocatable["example.com/gpu"] = Quantity.parse(2)
        e = make_pod("e", node_name="n1")
        e.containers[0].requests["example.com/gpu"] = Quantity.parse(2)
        snap = snap_of([node], [e])
        pod = make_pod("p")
        pod.containers[0].requests["example.com/gpu"] = Quantity.parse(1)
        assert not pod_fits_resources(pod, snap.get("n1"))


class TestNodeSelectorAndTaints:
    def test_node_selector(self):
        node = make_node("n1", labels={"disk": "ssd"})
        snap = snap_of([node])
        pod = make_pod("p")
        pod.node_selector = {"disk": "ssd"}
        assert pod_match_node_selector(pod, snap.get("n1"))
        pod.node_selector = {"disk": "hdd"}
        assert not pod_match_node_selector(pod, snap.get("n1"))

    def test_required_node_affinity_terms_ored(self):
        node = make_node("n1", labels={"disk": "ssd"})
        snap = snap_of([node])
        pod = make_pod("p")
        pod.affinity = Affinity(
            node_affinity=NodeAffinity(
                required=NodeSelector(
                    node_selector_terms=[
                        NodeSelectorTerm(
                            match_expressions=[
                                NodeSelectorRequirement(key="disk", operator="In", values=["hdd"])
                            ]
                        ),
                        NodeSelectorTerm(
                            match_expressions=[
                                NodeSelectorRequirement(key="disk", operator="Exists")
                            ]
                        ),
                    ]
                )
            )
        )
        assert pod_match_node_selector(pod, snap.get("n1"))

    def test_empty_term_list_matches_nothing(self):
        node = make_node("n1")
        snap = snap_of([node])
        pod = make_pod("p")
        pod.affinity = Affinity(
            node_affinity=NodeAffinity(required=NodeSelector(node_selector_terms=[]))
        )
        assert not pod_match_node_selector(pod, snap.get("n1"))

    def test_match_fields_metadata_name(self):
        node = make_node("n1")
        snap = snap_of([node])
        pod = make_pod("p")
        pod.affinity = Affinity(
            node_affinity=NodeAffinity(
                required=NodeSelector(
                    node_selector_terms=[
                        NodeSelectorTerm(
                            match_fields=[
                                NodeSelectorRequirement(
                                    key="metadata.name", operator="In", values=["n1"]
                                )
                            ]
                        )
                    ]
                )
            )
        )
        assert pod_match_node_selector(pod, snap.get("n1"))

    def test_gt_lt_operators(self):
        node = make_node("n1", labels={"cores": "16"})
        snap = snap_of([node])
        pod = make_pod("p")
        pod.affinity = Affinity(
            node_affinity=NodeAffinity(
                required=NodeSelector(
                    node_selector_terms=[
                        NodeSelectorTerm(
                            match_expressions=[
                                NodeSelectorRequirement(key="cores", operator="Gt", values=["8"])
                            ]
                        )
                    ]
                )
            )
        )
        assert pod_match_node_selector(pod, snap.get("n1"))

    def test_taints(self):
        node = make_node("n1", taints=[Taint(key="dedicated", value="gpu", effect="NoSchedule")])
        snap = snap_of([node])
        pod = make_pod("p")
        assert not pod_tolerates_node_taints(pod, snap.get("n1"))
        pod.tolerations = [Toleration(key="dedicated", operator="Equal", value="gpu", effect="NoSchedule")]
        assert pod_tolerates_node_taints(pod, snap.get("n1"))
        # PreferNoSchedule taints never block
        node2 = make_node("n2", taints=[Taint(key="x", value="", effect="PreferNoSchedule")])
        snap2 = snap_of([node2])
        assert pod_tolerates_node_taints(make_pod("q"), snap2.get("n2"))

    def test_exists_empty_key_tolerates_everything(self):
        node = make_node("n1", taints=[Taint(key="any", value="v", effect="NoExecute")])
        snap = snap_of([node])
        pod = make_pod("p")
        pod.tolerations = [Toleration(key="", operator="Exists")]
        assert pod_tolerates_node_taints(pod, snap.get("n1"))

    def test_unschedulable_node(self):
        node = make_node("n1", unschedulable=True)
        snap = snap_of([node])
        pod = make_pod("p")
        assert not check_node_unschedulable(pod, snap.get("n1"))
        pod.tolerations = [
            Toleration(key="node.kubernetes.io/unschedulable", operator="Exists", effect="NoSchedule")
        ]
        assert check_node_unschedulable(pod, snap.get("n1"))


class TestHostPorts:
    def _pod_with_port(self, name, port, proto="TCP", ip="", node_name=""):
        p = make_pod(name, node_name=node_name)
        p.containers[0].ports = [
            ContainerPort(host_port=port, container_port=port, protocol=proto, host_ip=ip)
        ]
        return p

    def test_conflict_same_port(self):
        node = make_node("n1")
        snap = snap_of([node], [self._pod_with_port("e", 8080, node_name="n1")])
        assert not pod_fits_host_ports(self._pod_with_port("p", 8080), snap.get("n1"))
        assert pod_fits_host_ports(self._pod_with_port("p2", 8081), snap.get("n1"))

    def test_protocol_disambiguates(self):
        node = make_node("n1")
        snap = snap_of([node], [self._pod_with_port("e", 8080, proto="TCP", node_name="n1")])
        assert pod_fits_host_ports(self._pod_with_port("p", 8080, proto="UDP"), snap.get("n1"))

    def test_wildcard_ip_conflicts_with_specific(self):
        node = make_node("n1")
        snap = snap_of([node], [self._pod_with_port("e", 8080, ip="127.0.0.1", node_name="n1")])
        assert not pod_fits_host_ports(self._pod_with_port("p", 8080, ip="0.0.0.0"), snap.get("n1"))

    def test_different_specific_ips_no_conflict(self):
        node = make_node("n1")
        snap = snap_of([node], [self._pod_with_port("e", 8080, ip="127.0.0.1", node_name="n1")])
        assert pod_fits_host_ports(self._pod_with_port("p", 8080, ip="10.0.0.1"), snap.get("n1"))


class TestEvenPodsSpread:
    def _constraint(self, key="zone", max_skew=1, when="DoNotSchedule"):
        return TopologySpreadConstraint(
            max_skew=max_skew,
            topology_key=key,
            when_unsatisfiable=when,
            label_selector=LabelSelector(match_labels={"app": "web"}),
        )

    def test_skew_enforced(self):
        nodes = [
            make_node("n1", labels={"zone": "a"}),
            make_node("n2", labels={"zone": "b"}),
        ]
        existing = [
            make_pod("e1", labels={"app": "web"}, node_name="n1"),
            make_pod("e2", labels={"app": "web"}, node_name="n1"),
        ]
        snap = snap_of(nodes, existing)
        pod = make_pod("p", labels={"app": "web"})
        pod.topology_spread_constraints = [self._constraint()]
        meta = compute_even_pods_spread_metadata(pod, snap)
        # zone a has 2, zone b has 0 -> min=0; placing on n1: 2+1-0=3 > 1
        assert not even_pods_spread_predicate(pod, snap.get("n1"), meta)
        assert even_pods_spread_predicate(pod, snap.get("n2"), meta)

    def test_node_missing_topology_key_fails(self):
        nodes = [make_node("n1", labels={"zone": "a"}), make_node("n3", labels={})]
        snap = snap_of(nodes, [make_pod("e1", labels={"app": "web"}, node_name="n1")])
        pod = make_pod("p", labels={"app": "web"})
        pod.topology_spread_constraints = [self._constraint()]
        meta = compute_even_pods_spread_metadata(pod, snap)
        assert not even_pods_spread_predicate(pod, snap.get("n3"), meta)

    def test_namespace_scoped_counting(self):
        nodes = [make_node("n1", labels={"zone": "a"}), make_node("n2", labels={"zone": "b"})]
        # matching pods but in a different namespace -> not counted
        existing = [
            make_pod("e1", namespace="other", labels={"app": "web"}, node_name="n1"),
            make_pod("e2", namespace="other", labels={"app": "web"}, node_name="n1"),
        ]
        snap = snap_of(nodes, existing)
        pod = make_pod("p", namespace="default", labels={"app": "web"})
        pod.topology_spread_constraints = [self._constraint()]
        meta = compute_even_pods_spread_metadata(pod, snap)
        assert even_pods_spread_predicate(pod, snap.get("n1"), meta)


class TestInterPodAffinity:
    def _term(self, app, key="zone", namespaces=()):
        return PodAffinityTerm(
            label_selector=LabelSelector(match_labels={"app": app}),
            namespaces=list(namespaces),
            topology_key=key,
        )

    def test_required_affinity(self):
        nodes = [make_node("n1", labels={"zone": "a"}), make_node("n2", labels={"zone": "b"})]
        existing = [make_pod("e", labels={"app": "db"}, node_name="n1")]
        snap = snap_of(nodes, existing)
        pod = make_pod("p")
        pod.affinity = Affinity(pod_affinity=PodAffinity(required=[self._term("db")]))
        meta = compute_pod_affinity_metadata(pod, snap)
        assert inter_pod_affinity_matches(pod, snap.get("n1"), meta)
        assert not inter_pod_affinity_matches(pod, snap.get("n2"), meta)

    def test_required_anti_affinity(self):
        nodes = [make_node("n1", labels={"zone": "a"}), make_node("n2", labels={"zone": "b"})]
        existing = [make_pod("e", labels={"app": "db"}, node_name="n1")]
        snap = snap_of(nodes, existing)
        pod = make_pod("p")
        pod.affinity = Affinity(pod_anti_affinity=PodAntiAffinity(required=[self._term("db")]))
        meta = compute_pod_affinity_metadata(pod, snap)
        assert not inter_pod_affinity_matches(pod, snap.get("n1"), meta)
        assert inter_pod_affinity_matches(pod, snap.get("n2"), meta)

    def test_existing_pod_anti_affinity_blocks(self):
        nodes = [make_node("n1", labels={"zone": "a"}), make_node("n2", labels={"zone": "b"})]
        blocker = make_pod("e", labels={"app": "db"}, node_name="n1")
        blocker.affinity = Affinity(
            pod_anti_affinity=PodAntiAffinity(required=[self._term("web")])
        )
        snap = snap_of(nodes, [blocker])
        pod = make_pod("p", labels={"app": "web"})
        meta = compute_pod_affinity_metadata(pod, snap)
        assert not inter_pod_affinity_matches(pod, snap.get("n1"), meta)
        assert inter_pod_affinity_matches(pod, snap.get("n2"), meta)

    def test_first_pod_self_affinity_escape(self):
        nodes = [make_node("n1", labels={"zone": "a"})]
        snap = snap_of(nodes, [])
        pod = make_pod("p", labels={"app": "web"})
        pod.affinity = Affinity(pod_affinity=PodAffinity(required=[self._term("web")]))
        meta = compute_pod_affinity_metadata(pod, snap)
        # no pods anywhere match, but pod matches its own selector -> allowed
        assert inter_pod_affinity_matches(pod, snap.get("n1"), meta)
        # pod NOT matching its own selector -> still blocked
        pod2 = make_pod("p2", labels={"app": "web"})
        pod2.affinity = Affinity(pod_affinity=PodAffinity(required=[self._term("db")]))
        meta2 = compute_pod_affinity_metadata(pod2, snap)
        assert not inter_pod_affinity_matches(pod2, snap.get("n1"), meta2)

    def test_namespace_defaulting(self):
        nodes = [make_node("n1", labels={"zone": "a"})]
        existing = [make_pod("e", namespace="other", labels={"app": "db"}, node_name="n1")]
        snap = snap_of(nodes, existing)
        pod = make_pod("p", namespace="default")
        pod.affinity = Affinity(pod_affinity=PodAffinity(required=[self._term("db")]))
        meta = compute_pod_affinity_metadata(pod, snap)
        # term namespaces default to the POD's namespace -> "other" not seen
        assert not inter_pod_affinity_matches(pod, snap.get("n1"), meta)
        pod.affinity.pod_affinity.required[0].namespaces = ["other"]
        meta = compute_pod_affinity_metadata(pod, snap)
        assert inter_pod_affinity_matches(pod, snap.get("n1"), meta)


class TestPriorities:
    def test_least_requested(self):
        n1 = make_node("n1", cpu_milli=1000, mem=1000)
        n2 = make_node("n2", cpu_milli=1000, mem=1000)
        e = make_pod("e", cpu_milli=500, mem=500, node_name="n1")
        snap = snap_of([n1, n2], [e])
        pod = make_pod("p", cpu_milli=0, mem=0)
        scores = least_requested_priority(pod, snap)
        assert scores["n2"] > scores["n1"]

    def test_least_requested_formula(self):
        # capacity 1000m cpu / 1000 bytes mem; pod explicit 200m/200
        n1 = make_node("n1", cpu_milli=1000, mem=1000)
        snap = snap_of([n1])
        pod = make_pod("p", cpu_milli=200, mem=200)
        scores = least_requested_priority(pod, snap)
        # cpu: (1000-200)*10/1000 = 8 ; mem: (1000-200)*10/1000 = 8 -> 8
        assert scores["n1"] == 8

    def test_nonzero_defaulting(self):
        # pod with NO requests gets 100m/200Mi defaults in scoring
        n1 = make_node("n1", cpu_milli=1000, mem=400 * 2**20)
        snap = snap_of([n1])
        pod = make_pod("p", cpu_milli=0, mem=0)
        # make_pod with zeros -> no request entries at all
        assert not pod.containers[0].requests
        scores = least_requested_priority(pod, snap)
        # cpu: (1000-100)*10/1000 = 9 ; mem: (400Mi-200Mi)*10/400Mi = 5 -> (9+5)/2 = 7
        assert scores["n1"] == 7

    def test_balanced_allocation(self):
        n1 = make_node("n1", cpu_milli=1000, mem=1000)
        snap = snap_of([n1])
        pod = make_pod("p", cpu_milli=500, mem=500)
        scores = balanced_resource_allocation(pod, snap)
        assert scores["n1"] == MAX_NODE_SCORE  # perfectly balanced

    def test_node_affinity_priority(self):
        n1 = make_node("n1", labels={"disk": "ssd"})
        n2 = make_node("n2", labels={"disk": "hdd"})
        snap = snap_of([n1, n2])
        pod = make_pod("p")
        pod.affinity = Affinity(
            node_affinity=NodeAffinity(
                preferred=[
                    PreferredSchedulingTerm(
                        weight=10,
                        preference=NodeSelectorTerm(
                            match_expressions=[
                                NodeSelectorRequirement(key="disk", operator="In", values=["ssd"])
                            ]
                        ),
                    )
                ]
            )
        )
        scores = node_affinity_priority(pod, snap)
        assert scores["n1"] == MAX_NODE_SCORE
        assert scores["n2"] == 0

    def test_taint_toleration_priority(self):
        n1 = make_node("n1", taints=[Taint(key="a", value="", effect="PreferNoSchedule")])
        n2 = make_node("n2")
        snap = snap_of([n1, n2])
        pod = make_pod("p")
        scores = taint_toleration_priority(pod, snap)
        assert scores["n2"] == MAX_NODE_SCORE
        assert scores["n1"] == 0

    def test_selector_spread(self):
        n1 = make_node("n1")
        n2 = make_node("n2")
        sel = LabelSelector(match_labels={"app": "web"})
        e1 = make_pod("e1", labels={"app": "web"}, node_name="n1")
        snap = snap_of([n1, n2], [e1])
        pod = make_pod("p", labels={"app": "web"})
        scores = selector_spread_priority(pod, snap, [sel])
        assert scores["n2"] == MAX_NODE_SCORE
        assert scores["n1"] == 0

    def test_interpod_affinity_preferred(self):
        n1 = make_node("n1", labels={"zone": "a"})
        n2 = make_node("n2", labels={"zone": "b"})
        e = make_pod("e", labels={"app": "db"}, node_name="n1")
        snap = snap_of([n1, n2], [e])
        pod = make_pod("p")
        pod.affinity = Affinity(
            pod_affinity=PodAffinity(
                preferred=[
                    __import__(
                        "kubernetes_tpu.api.types", fromlist=["WeightedPodAffinityTerm"]
                    ).WeightedPodAffinityTerm(
                        weight=50,
                        pod_affinity_term=PodAffinityTerm(
                            label_selector=LabelSelector(match_labels={"app": "db"}),
                            topology_key="zone",
                        ),
                    )
                ]
            )
        )
        scores = inter_pod_affinity_priority(pod, snap)
        assert scores["n1"] == MAX_NODE_SCORE
        assert scores["n2"] == 0


class TestEndToEnd:
    def test_find_nodes_that_fit_runs(self):
        from kubernetes_tpu.models.generators import ClusterGen

        g = ClusterGen(7)
        nodes, existing = g.cluster(30, 100)
        snap = Snapshot(nodes, existing)
        for i in range(10):
            pod = g.pod(10_000 + i)
            fits = find_nodes_that_fit(pod, snap)
            for name in fits:
                ok, _ = pod_fits_on_node(pod, snap.get(name), snapshot=snap)
                assert ok


def test_affinity_index_metadata_equivalence():
    """SnapshotAffinityIndex (grouped, pod-independent halves) must yield
    EXACTLY the same PodAffinityMetadata pair sets as the per-pod cluster
    walk, over seeded random clusters — including extras replay for pods
    committed after the index was built."""
    from kubernetes_tpu.models.generators import ClusterGen
    from kubernetes_tpu.oracle.nodeinfo import Snapshot
    from kubernetes_tpu.oracle.predicates import (
        SnapshotAffinityIndex,
        compute_pod_affinity_metadata,
    )

    for seed in range(12):
        g = ClusterGen(seed)
        nodes, existing = g.cluster(14, 60, feature_rate=0.7)
        snap = Snapshot(nodes, existing)
        index = SnapshotAffinityIndex(snap)
        # extras: two additional pods committed after the index build
        extra_pods = []
        names = list(snap.node_infos)
        for j in range(2):
            p = g.pod(90_000 + j, feature_rate=0.7)
            ni = snap.node_infos[names[j % len(names)]]
            bound = p.with_node(ni.node.name)
            ni.add_pod(bound)
            extra_pods.append((bound, ni.node.labels))
        for i in range(8):
            pod = g.pod(95_000 + i, feature_rate=0.7)
            legacy = compute_pod_affinity_metadata(pod, snap)
            fast = compute_pod_affinity_metadata(pod, snap, index=index, extra=extra_pods)
            assert fast.existing_anti_pairs == legacy.existing_anti_pairs, (seed, i)
            assert fast.incoming_affinity_pairs == legacy.incoming_affinity_pairs, (seed, i)
            assert fast.incoming_anti_pairs == legacy.incoming_anti_pairs, (seed, i)
