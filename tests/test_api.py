
import pytest

from kubernetes_tpu.api import (
    LabelSelector,
    LabelSelectorRequirement,
    NodeSelectorRequirement,
    NodeSelectorTerm,
    Taint,
    Toleration,
    match_label_selector,
    match_node_selector_terms,
    parse_quantity,
    pod_from_k8s,
    pod_to_k8s,
    node_from_k8s,
    node_to_k8s,
)
from kubernetes_tpu.api.types import Container, ContainerPort, Pod
from kubernetes_tpu.state.interner import ABSENT, StringInterner


class TestQuantity:
    @pytest.mark.parametrize(
        "s,value",
        [
            ("1", 1),
            ("100m", 1),  # 0.1 rounds up to 1
            ("1500m", 2),
            ("1Ki", 1024),
            ("1Mi", 1 << 20),
            ("2Gi", 2 << 30),
            ("1k", 1000),
            ("1G", 10**9),
            ("1e3", 1000),
            ("0.5", 1),
        ],
    )
    def test_value_rounds_up(self, s, value):
        assert parse_quantity(s).value() == value

    @pytest.mark.parametrize(
        "s,milli",
        [("100m", 100), ("1", 1000), ("2.5", 2500), ("250m", 250), ("1m", 1), ("0.0001", 1)],
    )
    def test_milli_value(self, s, milli):
        assert parse_quantity(s).milli_value() == milli

    def test_invalid(self):
        for bad in ["", "abc", "1Q", "--1"]:
            with pytest.raises(ValueError):
                parse_quantity(bad)


class TestSelectors:
    def test_label_selector_nil_matches_nothing(self):
        assert not match_label_selector(None, {"a": "b"})

    def test_label_selector_empty_matches_everything(self):
        assert match_label_selector(LabelSelector(), {})
        assert match_label_selector(LabelSelector(), {"a": "b"})

    def test_match_labels(self):
        sel = LabelSelector(match_labels={"app": "web"})
        assert match_label_selector(sel, {"app": "web", "x": "y"})
        assert not match_label_selector(sel, {"app": "db"})

    def test_expressions(self):
        sel = LabelSelector(
            match_expressions=[
                LabelSelectorRequirement("tier", "In", ["fe", "be"]),
                LabelSelectorRequirement("canary", "DoesNotExist"),
            ]
        )
        assert match_label_selector(sel, {"tier": "fe"})
        assert not match_label_selector(sel, {"tier": "fe", "canary": "y"})
        assert not match_label_selector(sel, {"tier": "mid"})

    def test_notin_absent_key_matches(self):
        sel = LabelSelector(match_expressions=[LabelSelectorRequirement("a", "NotIn", ["x"])])
        assert match_label_selector(sel, {})

    def test_node_selector_terms_ored_empty_matches_nothing(self):
        assert not match_node_selector_terms([], {"a": "b"})
        t1 = NodeSelectorTerm(match_expressions=[NodeSelectorRequirement("zone", "In", ["us-a"])])
        t2 = NodeSelectorTerm(match_expressions=[NodeSelectorRequirement("zone", "In", ["us-b"])])
        assert match_node_selector_terms([t1, t2], {"zone": "us-b"})
        assert not match_node_selector_terms([t1, t2], {"zone": "us-c"})

    def test_empty_term_matches_nothing(self):
        assert not match_node_selector_terms([NodeSelectorTerm()], {"a": "b"})

    def test_gt_lt(self):
        gt = NodeSelectorTerm(match_expressions=[NodeSelectorRequirement("cores", "Gt", ["8"])])
        assert match_node_selector_terms([gt], {"cores": "16"})
        assert not match_node_selector_terms([gt], {"cores": "8"})
        assert not match_node_selector_terms([gt], {"cores": "abc"})
        assert not match_node_selector_terms([gt], {})


class TestTolerations:
    def test_exists_empty_key_tolerates_all(self):
        t = Toleration(operator="Exists")
        assert t.tolerates(Taint("any", "v", "NoSchedule"))
        assert t.tolerates(Taint("other", "", "NoExecute"))

    def test_equal(self):
        t = Toleration(key="k", operator="Equal", value="v", effect="NoSchedule")
        assert t.tolerates(Taint("k", "v", "NoSchedule"))
        assert not t.tolerates(Taint("k", "w", "NoSchedule"))
        assert not t.tolerates(Taint("k", "v", "NoExecute"))

    def test_empty_effect_matches_all_effects(self):
        t = Toleration(key="k", operator="Exists")
        assert t.tolerates(Taint("k", "v", "NoExecute"))


class TestPodResources:
    def test_max_of_init_and_sum_of_containers(self):
        pod = Pod(
            name="p",
            containers=[
                Container(requests={"cpu": parse_quantity("100m"), "memory": parse_quantity("1Gi")}),
                Container(requests={"cpu": parse_quantity("200m")}),
            ],
            init_containers=[Container(requests={"cpu": parse_quantity("250m"), "memory": parse_quantity("2Gi")})],
        )
        req = pod.resource_request()
        assert req["cpu"] == 300  # sum(100,200) > init 250
        assert req["memory"] == 2 << 30  # init container dominates

    def test_host_ports(self):
        pod = Pod(
            name="p",
            containers=[
                Container(ports=[ContainerPort(host_port=80, protocol="TCP"), ContainerPort(container_port=8080)])
            ],
        )
        assert pod.host_ports() == [("TCP", "0.0.0.0", 80)]


class TestRoundTrip:
    def test_pod_round_trip(self):
        obj = {
            "metadata": {"name": "p1", "namespace": "ns", "labels": {"app": "x"}},
            "spec": {
                "priority": 10,
                "nodeSelector": {"disk": "ssd"},
                "containers": [
                    {
                        "name": "c",
                        "image": "nginx:1.2",
                        "resources": {"requests": {"cpu": "500m", "memory": "128Mi"}},
                        "ports": [{"hostPort": 80, "containerPort": 80, "protocol": "TCP"}],
                    }
                ],
                "tolerations": [{"key": "k", "operator": "Exists", "effect": "NoSchedule"}],
                "affinity": {
                    "nodeAffinity": {
                        "requiredDuringSchedulingIgnoredDuringExecution": {
                            "nodeSelectorTerms": [
                                {"matchExpressions": [{"key": "zone", "operator": "In", "values": ["a"]}]}
                            ]
                        }
                    },
                    "podAntiAffinity": {
                        "requiredDuringSchedulingIgnoredDuringExecution": [
                            {
                                "labelSelector": {"matchLabels": {"app": "x"}},
                                "topologyKey": "kubernetes.io/hostname",
                            }
                        ]
                    },
                },
                "topologySpreadConstraints": [
                    {
                        "maxSkew": 2,
                        "topologyKey": "zone",
                        "whenUnsatisfiable": "DoNotSchedule",
                        "labelSelector": {"matchLabels": {"app": "x"}},
                    }
                ],
            },
        }
        pod = pod_from_k8s(obj)
        assert pod.get_priority() == 10
        assert pod.resource_request() == {"cpu": 500, "memory": 128 << 20}
        assert pod.affinity.pod_anti_affinity.required[0].topology_key == "kubernetes.io/hostname"
        assert pod.topology_spread_constraints[0].max_skew == 2
        pod2 = pod_from_k8s(pod_to_k8s(pod))
        assert pod2.resource_request() == pod.resource_request()
        assert pod2.node_selector == pod.node_selector
        assert pod2.tolerations == pod.tolerations
        assert pod2.affinity == pod.affinity

    def test_node_round_trip(self):
        obj = {
            "metadata": {"name": "n1", "labels": {"zone": "a"}},
            "spec": {"unschedulable": True, "taints": [{"key": "k", "value": "v", "effect": "NoSchedule"}]},
            "status": {
                "allocatable": {"cpu": "4", "memory": "16Gi", "pods": "110"},
                "capacity": {"cpu": "4", "memory": "16Gi", "pods": "110"},
                "images": [{"names": ["nginx:1.2"], "sizeBytes": 100000}],
            },
        }
        node = node_from_k8s(obj)
        assert node.unschedulable
        assert node.allocatable_int() == {"cpu": 4000, "memory": 16 << 30, "pods": 110}
        node2 = node_from_k8s(node_to_k8s(node))
        assert node2.taints == node.taints
        assert node2.allocatable_int() == node.allocatable_int()


class TestInterner:
    def test_basic(self):
        it = StringInterner()
        a = it.intern("app")
        b = it.intern("tier")
        assert a != b and a != ABSENT and b != ABSENT
        assert it.intern("app") == a
        assert it.lookup("app") == a
        assert it.lookup("nope") == ABSENT
        assert it.string(a) == "app"
        assert len(it) == 2

    def test_kv_injective(self):
        it = StringInterner()
        assert it.intern_kv("a", "b=c") != it.intern_kv("a=b", "c")
        assert it.lookup_kv("a", "b=c") == it.intern_kv("a", "b=c")
