"""Config surface: feature gates, providers, Policy, ComponentConfig,
Configurator → a Scheduler whose behavior actually follows the config."""

import json

import pytest

pytest.importorskip("jax")

from kubernetes_tpu.config import (
    Configurator,
    Policy,
    PolicyError,
    default_predicates,
    default_priorities,
    get_provider,
    parse_component_config,
    parse_policy,
)
from kubernetes_tpu.models.generators import make_node, make_pod
from kubernetes_tpu.state.cache import SchedulerCache
from kubernetes_tpu.utils.featuregate import FeatureGate


def test_feature_gate_defaults_and_parse():
    fg = FeatureGate()
    assert fg.enabled("TaintNodesByCondition") is True
    assert fg.enabled("EvenPodsSpread") is False
    fg.parse("EvenPodsSpread=true,ResourceLimits=false")
    assert fg.enabled("EvenPodsSpread") is True
    with pytest.raises(KeyError):
        fg.parse("NoSuchGate=true")
    with pytest.raises(ValueError):
        fg.parse("TaintNodesByCondition=false")  # GA locked


def test_provider_feature_gating():
    fg = FeatureGate()
    preds = default_predicates(fg)
    assert "EvenPodsSpread" not in preds
    assert "GeneralPredicates" in preds and "MatchInterPodAffinity" in preds
    fg.parse("EvenPodsSpread=true")
    assert "EvenPodsSpread" in default_predicates(fg)
    assert ("EvenPodsSpreadPriority", 1) in default_priorities(fg)
    ca_preds, ca_prios = get_provider("ClusterAutoscalerProvider", fg)
    names = [n for n, _ in ca_prios]
    assert "MostRequestedPriority" in names and "LeastRequestedPriority" not in names


def test_policy_parsing_and_validation():
    p = parse_policy({
        "kind": "Policy",
        "predicates": [{"name": "PodFitsResources"}, {"name": "PodToleratesNodeTaints"}],
        "priorities": [{"name": "LeastRequestedPriority", "weight": 2}],
        "extenders": [{"urlPrefix": "http://x:1", "filterVerb": "filter",
                       "nodeCacheCapable": True, "weight": 3}],
        "hardPodAffinitySymmetricWeight": 10,
    })
    assert p.predicates == frozenset({"PodFitsResources", "PodToleratesNodeTaints"})
    assert p.priorities == (("LeastRequestedPriority", 2),)
    assert p.extenders[0].weight == 3 and p.extenders[0].node_cache_capable
    assert p.hard_pod_affinity_symmetric_weight == 10
    with pytest.raises(PolicyError):
        parse_policy({"predicates": [{"name": "NotAPredicate"}]})
    # absent keys → defaults
    d = parse_policy({})
    assert d.predicates == default_predicates()


def test_component_config_parsing():
    cc = parse_component_config({
        "schedulerName": "tpu-scheduler",
        "algorithmSource": {"policy": {"file": {"path": "/tmp/p.json"}}},
        "bindTimeoutSeconds": 30,
        "leaderElection": {"leaderElect": True, "leaseDuration": "30s"},
        "featureGates": {"EvenPodsSpread": True},
    })
    assert cc.scheduler_name == "tpu-scheduler"
    assert cc.policy_file == "/tmp/p.json" and cc.algorithm_provider is None
    assert cc.leader_election.leader_elect and cc.leader_election.lease_duration_s == 30.0
    assert cc.feature_gates == {"EvenPodsSpread": True}


def _sched_from_policy(policy_dict, cache):
    cfgr = Configurator(deterministic=True)
    sched = cfgr.create_from_config(policy_dict)
    sched.cache = cache
    # rebind internals constructed against the default cache
    from kubernetes_tpu.state.cache import TensorMirror

    sched.mirror = TensorMirror(cache)
    return sched


def test_policy_disabling_taints_changes_scheduling():
    """A Policy without PodToleratesNodeTaints schedules onto tainted nodes
    — device mask and oracle chain both follow the config."""
    from kubernetes_tpu.api.types import Taint

    cache = SchedulerCache()
    n = make_node("tainted", cpu_milli=4000, mem=8 * 2**30)
    n.taints = [Taint(key="dedicated", value="x", effect="NoSchedule")]
    cache.add_node(n)

    # default provider: pod cannot land (taint not tolerated)
    cfgr = Configurator(deterministic=True)
    s1 = cfgr.create_from_provider("DefaultProvider")
    s1.cache = cache
    from kubernetes_tpu.state.cache import TensorMirror

    s1.mirror = TensorMirror(cache)
    s1.enable_preemption = False
    s1.queue.add(make_pod("p0", cpu_milli=100, mem=0))
    r1 = s1.schedule_batch()
    assert r1.scheduled == 0 and r1.unschedulable == 1

    # policy without the taint predicate: pod lands
    s2 = _sched_from_policy({
        "predicates": [{"name": "GeneralPredicates"}],
        "priorities": [{"name": "LeastRequestedPriority", "weight": 1}],
    }, cache)
    s2.enable_preemption = False
    s2.queue.add(make_pod("p1", cpu_milli=100, mem=0))
    r2 = s2.schedule_batch()
    assert r2.scheduled == 1


def test_policy_priority_weights_change_selection():
    """MostRequested vs LeastRequested flips which node wins."""
    cache = SchedulerCache()
    for name, used in (("packed", 3000), ("empty", 0)):
        n = make_node(name, cpu_milli=4000, mem=8 * 2**30)
        cache.add_node(n)
    filler = make_pod("filler", cpu_milli=3000, mem=0)
    filler.node_name = "packed"
    cache.add_pod(filler)

    least = _sched_from_policy({
        "predicates": [{"name": "GeneralPredicates"}],
        "priorities": [{"name": "LeastRequestedPriority", "weight": 1}],
    }, cache)
    least.queue.add(make_pod("a", cpu_milli=100, mem=0))
    r = least.schedule_batch()
    assert r.assignments["default/a"] == "empty"

    most = _sched_from_policy({
        "predicates": [{"name": "GeneralPredicates"}],
        "priorities": [{"name": "MostRequestedPriority", "weight": 1}],
    }, cache)
    most.queue.add(make_pod("b", cpu_milli=100, mem=0))
    r = most.schedule_batch()
    assert r.assignments["default/b"] == "packed"


def test_cli_sim_mode(tmp_path, capsys):
    from kubernetes_tpu.cmd import main

    rc = main(["--mode", "sim", "--nodes", "8", "--pods", "20",
               "--deterministic", "--batch-size", "32"])
    out = capsys.readouterr().out.strip().splitlines()[-1]
    result = json.loads(out)
    assert rc == 0
    assert result["bound"] == result["pods"] == 20


def test_policy_rtcr_arguments_parse_and_validate():
    from kubernetes_tpu.config.policy import PolicyError, parse_policy

    pol = parse_policy({
        "kind": "Policy",
        "priorities": [{
            "name": "CustomBinPack",
            "weight": 2,
            "argument": {"requestedToCapacityRatioArguments": {
                "shape": [{"utilization": 0, "score": 0},
                          {"utilization": 100, "score": 10}],
                "resources": [{"name": "cpu", "weight": 3},
                              {"name": "memory"}],
            }},
        }],
    })
    assert pol.rtcr == (((0, 0), (100, 10)), (("cpu", 3), ("memory", 1)))
    assert ("RequestedToCapacityRatioPriority", 2) in pol.priorities

    # unsorted shape rejected (NewFunctionShape preconditions)
    with pytest.raises(PolicyError):
        parse_policy({"priorities": [{"name": "x", "argument": {
            "requestedToCapacityRatioArguments": {
                "shape": [{"utilization": 50, "score": 1},
                          {"utilization": 50, "score": 2}]}}}]})
    # extended resources not supported on the device path
    with pytest.raises(PolicyError):
        parse_policy({"priorities": [{"name": "x", "argument": {
            "requestedToCapacityRatioArguments": {
                "shape": [{"utilization": 0, "score": 10}],
                "resources": [{"name": "nvidia.com/gpu", "weight": 1}]}}}]})


def test_policy_rtcr_bin_packing_changes_selection():
    """A bin-packing shape (score grows with utilization) packs the busy
    node, where the default shape would spread to the empty one."""
    cache = SchedulerCache()
    for name in ("packed", "empty"):
        cache.add_node(make_node(name, cpu_milli=4000, mem=8 * 2**30))
    filler = make_pod("filler", cpu_milli=3000, mem=0)
    filler.node_name = "packed"
    cache.add_pod(filler)

    binpack = _sched_from_policy({
        "predicates": [{"name": "GeneralPredicates"}],
        "priorities": [{
            "name": "RequestedToCapacityRatio",
            "weight": 1,
            "argument": {"requestedToCapacityRatioArguments": {
                "shape": [{"utilization": 0, "score": 0},
                          {"utilization": 100, "score": 10}]}},
        }],
    }, cache)
    binpack.queue.add(make_pod("c", cpu_milli=100, mem=0))
    r = binpack.schedule_batch()
    assert r.assignments["default/c"] == "packed"


def test_resource_limits_feature_gate_registration():
    from kubernetes_tpu.config.provider import default_priorities
    from kubernetes_tpu.utils.featuregate import FeatureGate

    off = default_priorities(FeatureGate())
    assert not any(n == "ResourceLimitsPriority" for n, _ in off)
    fg = FeatureGate()
    fg.parse("ResourceLimits=true")
    on = default_priorities(fg)
    assert ("ResourceLimitsPriority", 1) in on


def test_policy_rtcr_negative_weight_and_duplicates_rejected():
    from kubernetes_tpu.config.policy import PolicyError, parse_policy

    shape = [{"utilization": 0, "score": 10}, {"utilization": 100, "score": 0}]
    with pytest.raises(PolicyError):
        parse_policy({"priorities": [{"name": "x", "argument": {
            "requestedToCapacityRatioArguments": {
                "shape": shape,
                "resources": [{"name": "cpu", "weight": -2}]}}}]})
    with pytest.raises(PolicyError):
        parse_policy({"priorities": [
            {"name": "a", "argument": {"requestedToCapacityRatioArguments": {"shape": shape}}},
            {"name": "b", "argument": {"requestedToCapacityRatioArguments": {"shape": shape}}},
        ]})


def test_policy_labels_presence_predicate():
    """labelsPresence argument (api/types.go:115): presence=False evicts
    labeled nodes; user-named predicate runs as a framework Filter plugin."""
    cache = SchedulerCache()
    cache.add_node(make_node("retiring", labels={"retiring": "2026-01-01"}))
    cache.add_node(make_node("healthy"))
    sched = _sched_from_policy({
        "predicates": [
            {"name": "GeneralPredicates"},
            {"name": "NoRetiringNodes",
             "argument": {"labelsPresence": {"labels": ["retiring"], "presence": False}}},
        ],
        "priorities": [{"name": "LeastRequestedPriority", "weight": 1}],
    }, cache)
    sched.enable_preemption = False
    sched.queue.add(make_pod("p", cpu_milli=100, mem=0))
    r = sched.schedule_batch()
    assert r.assignments["default/p"] == "healthy"


def test_policy_label_preference_priority():
    """labelPreference argument (api/types.go:130): presence=True prefers
    labeled nodes."""
    cache = SchedulerCache()
    cache.add_node(make_node("plain"))
    cache.add_node(make_node("ssd", labels={"disktype": "ssd"}))
    sched = _sched_from_policy({
        "predicates": [{"name": "GeneralPredicates"}],
        "priorities": [
            {"name": "PreferSSD", "weight": 5,
             "argument": {"labelPreference": {"label": "disktype", "presence": True}}},
        ],
    }, cache)
    sched.enable_preemption = False
    sched.queue.add(make_pod("p", cpu_milli=100, mem=0))
    r = sched.schedule_batch()
    assert r.assignments["default/p"] == "ssd"


def test_policy_service_affinity_and_anti_affinity():
    """serviceAffinity predicate pins a service's pods to one region
    (predicates.go:1123 implicit-selector backfill); serviceAntiAffinity
    priority spreads them across zones (selector_spreading.go:211)."""
    from kubernetes_tpu.api.types import Service
    from kubernetes_tpu.config.factory import Configurator
    from kubernetes_tpu.state.cache import TensorMirror

    cache = SchedulerCache()
    for name, region, zone in (
        ("r1a", "r1", "a"), ("r1b", "r1", "b"), ("r2a", "r2", "a"),
    ):
        cache.add_node(make_node(name, labels={"region": region, "zone": zone}))
    services = [Service(name="svc", namespace="default", selector={"app": "web"})]
    cfgr = Configurator(deterministic=True, service_lister=lambda: services)
    sched = cfgr.create_from_config({
        "predicates": [
            {"name": "GeneralPredicates"},
            {"name": "SvcRegion", "argument": {"serviceAffinity": {"labels": ["region"]}}},
        ],
        "priorities": [
            {"name": "SvcSpread", "weight": 10,
             "argument": {"serviceAntiAffinity": {"label": "zone"}}},
        ],
    })
    sched.cache = cache
    sched.mirror = TensorMirror(cache)
    sched.enable_preemption = False
    # anchor: one service pod already on r1a
    anchor = make_pod("w0", labels={"app": "web"}, cpu_milli=100, mem=0)
    anchor.node_name = "r1a"
    cache.add_pod(anchor)
    # next service pod must stay in region r1 (affinity) but prefer the
    # OTHER zone (anti-affinity): r1b
    sched.queue.add(make_pod("w1", labels={"app": "web"}, cpu_milli=100, mem=0))
    r = sched.schedule_batch()
    assert r.assignments["default/w1"] == "r1b", r.assignments


def test_cli_sim_leader_election(tmp_path, capsys):
    """leaderElection.leaderElect=true: the sim acquires the lease before
    scheduling and records itself as holder (server.go:157 semantics)."""
    from kubernetes_tpu.cmd import main

    cfg = tmp_path / "cc.json"
    cfg.write_text(json.dumps({
        "kind": "KubeSchedulerConfiguration",
        "leaderElection": {"leaderElect": True, "leaseDuration": "15s",
                           "renewDeadline": "10s", "retryPeriod": "2s"},
    }))
    rc = main(["--mode", "sim", "--config", str(cfg), "--nodes", "6",
               "--pods", "12", "--deterministic", "--batch-size", "16"])
    out = capsys.readouterr().out.strip().splitlines()[-1]
    result = json.loads(out)
    assert rc == 0 and result["bound"] == 12
