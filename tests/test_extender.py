"""HTTP SchedulerExtender integration tests.

Modeled on test/integration/scheduler/extender_test.go: real HTTP servers,
real wire JSON. Two directions:
  * server: a fake kube-scheduler client POSTs extender/v1 filter /
    prioritize / bind / preemption args at our solver-backed ExtenderServer
    (both nodeCacheCapable wire modes);
  * client: our Scheduler driver consults an out-of-tree extender via
    HTTPExtender and its answers change assignments.
"""

import json
import threading
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

pytest.importorskip("jax")

from kubernetes_tpu.api.types import Node, Pod, node_to_k8s, pod_to_k8s
from kubernetes_tpu.extender import (
    ExtenderConfig,
    ExtenderServer,
    HTTPExtender,
)
from kubernetes_tpu.models.generators import make_node, make_pod
from kubernetes_tpu.scheduler.driver import Binder, Scheduler
from kubernetes_tpu.state.cache import SchedulerCache
from kubernetes_tpu.state.queue import PriorityQueue


def _post(url: str, obj, timeout: float = 120) -> dict:
    # generous timeout: the device-path request pays the first XLA compile
    req = urllib.request.Request(
        url, data=json.dumps(obj).encode(), headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


@pytest.fixture
def server():
    cache = SchedulerCache()
    for i in range(6):
        cache.add_node(make_node(f"n{i}", cpu_milli=4000, mem=8 * 2**30))
    binds = []
    srv = ExtenderServer(cache=cache, bind_fn=lambda args: binds.append(args)).start()
    srv.test_binds = binds
    yield srv
    srv.stop()


def test_filter_node_cache_capable(server):
    pod = make_pod("p0", cpu_milli=100, mem=0)
    args = {"Pod": pod_to_k8s(pod), "NodeNames": ["n0", "n1", "ghost"]}
    res = _post(server.url + "/filter", args)
    assert sorted(res["NodeNames"]) == ["n0", "n1"]
    assert res["FailedNodes"] == {"ghost": "node unknown"}
    assert not res["Error"]


def test_filter_full_nodes_mode(server):
    # non-cache-capable: full v1.Node objects on the wire, transient snapshot
    pod = make_pod("p0", cpu_milli=3000, mem=0)
    big = make_node("big", cpu_milli=4000, mem=8 * 2**30)
    small = make_node("small", cpu_milli=1000, mem=8 * 2**30)
    args = {"Pod": pod_to_k8s(pod), "Nodes": {"items": [node_to_k8s(big), node_to_k8s(small)]}}
    res = _post(server.url + "/filter", args)
    names = [n["metadata"]["name"] for n in res["Nodes"]["items"]]
    assert names == ["big"]
    assert "small" in res["FailedNodes"]


def test_prioritize(server):
    # one node already carries load → LeastRequested prefers the others
    loaded = make_pod("existing", cpu_milli=3500, mem=2**30)
    loaded.node_name = "n0"
    server.cache.add_pod(loaded)
    pod = make_pod("p0", cpu_milli=100, mem=0)
    args = {"Pod": pod_to_k8s(pod), "NodeNames": ["n0", "n1", "n2"]}
    res = _post(server.url + "/prioritize", args)
    scores = {d["Host"]: d["Score"] for d in res}
    assert set(scores) == {"n0", "n1", "n2"}
    assert scores["n0"] < scores["n1"] == scores["n2"]
    assert all(0 <= s <= 10 for s in scores.values())


def test_bind_and_healthz(server):
    args = {"PodName": "p0", "PodNamespace": "default", "PodUID": "u1", "Node": "n3"}
    res = _post(server.url + "/bind", args)
    assert res["Error"] == ""
    assert server.test_binds[0].node == "n3"
    with urllib.request.urlopen(server.url + "/healthz", timeout=5) as r:
        assert json.loads(r.read())["ok"] is True


def test_preemption_validates_victims(server):
    victim = make_pod("victim", cpu_milli=100, mem=0)
    victim.node_name = "n1"
    server.cache.add_pod(victim)
    pod = make_pod("preemptor", cpu_milli=100, mem=0)
    args = {
        "Pod": pod_to_k8s(pod),
        "NodeNameToMetaVictims": {
            "n1": {"Pods": [{"UID": victim.uid}], "NumPDBViolations": 0},
            "n2": {"Pods": [{"UID": "unknown-uid"}], "NumPDBViolations": 0},
            "ghost": {"Pods": [{"UID": victim.uid}], "NumPDBViolations": 0},
        },
    }
    res = _post(server.url + "/preemption", args)
    out = res["NodeNameToMetaVictims"]
    assert list(out) == ["n1"]
    assert out["n1"]["Pods"] == [{"UID": victim.uid}]


# --- client direction: our driver consults an out-of-tree extender ---------


class _FakeExtender(BaseHTTPRequestHandler):
    """An out-of-tree extender in the style of extender_test.go's
    fakeExtender: only allows nodes whose name ends in an even digit and
    strongly prefers the highest-numbered of those."""

    def log_message(self, fmt, *a):
        pass

    def do_POST(self):
        n = int(self.headers.get("Content-Length", 0))
        payload = json.loads(self.rfile.read(n))
        if self.path.endswith("/filter"):
            names = payload["NodeNames"]
            keep = [x for x in names if int(x[-1]) % 2 == 0]
            out = {"NodeNames": keep, "FailedNodes": {}, "Error": ""}
        elif self.path.endswith("/prioritize"):
            names = payload["NodeNames"]
            out = [{"Host": x, "Score": int(x[-1])} for x in names]
        else:
            out = {"Error": "unknown"}
        body = json.dumps(out).encode()
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


@pytest.fixture
def fake_extender():
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), _FakeExtender)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{httpd.server_address[1]}"
    httpd.shutdown()
    httpd.server_close()


def test_driver_consults_extender(fake_extender):
    cache = SchedulerCache()
    for i in range(6):
        cache.add_node(make_node(f"n{i}", cpu_milli=4000, mem=8 * 2**30))
    ext = HTTPExtender(ExtenderConfig(
        url_prefix=fake_extender, filter_verb="filter", prioritize_verb="prioritize",
        weight=100, node_cache_capable=True,
    ))
    binds = []
    sched = Scheduler(
        cache=cache, queue=PriorityQueue(),
        binder=Binder(lambda p, n: binds.append((p.name, n))),
        extenders=[ext], deterministic=True,
    )
    for i in range(3):
        sched.queue.add(make_pod(f"p{i}", cpu_milli=100, mem=0))
    res = sched.schedule_batch()
    sched.wait_for_binds()
    assert res.scheduled == 3
    # extender filter: only even nodes; extender prioritize x100 dominates
    # the default scores: highest even node (n4) wins for everyone
    assert set(res.assignments.values()) == {"n4"}


def test_driver_extender_filters_all_nodes_out(fake_extender):
    cache = SchedulerCache()
    cache.add_node(make_node("n1", cpu_milli=4000, mem=8 * 2**30))  # odd → filtered out
    ext = HTTPExtender(ExtenderConfig(
        url_prefix=fake_extender, filter_verb="filter", node_cache_capable=True,
    ))
    sched = Scheduler(cache=cache, queue=PriorityQueue(), extenders=[ext],
                      deterministic=True, enable_preemption=False)
    sched.queue.add(make_pod("p0", cpu_milli=100, mem=0))
    res = sched.schedule_batch()
    assert res.scheduled == 0 and res.unschedulable == 1


def test_driver_extender_wire_failure_is_error_not_fiterror():
    """A non-ignorable extender outage is a scheduling ERROR: the pod goes
    back to the queue via the error path and preemption must NOT fire
    (the reference never preempts on extender errors)."""
    cache = SchedulerCache()
    cache.add_node(make_node("n0", cpu_milli=4000, mem=8 * 2**30))
    victim = make_pod("running", cpu_milli=100, mem=0)
    victim.node_name = "n0"
    cache.add_pod(victim)
    dead = HTTPExtender(ExtenderConfig(
        url_prefix="http://127.0.0.1:1", filter_verb="filter",
        node_cache_capable=True, timeout_s=0.2,
    ))
    deleted = []
    sched = Scheduler(cache=cache, queue=PriorityQueue(), extenders=[dead],
                      deterministic=True, enable_preemption=True,
                      delete_fn=lambda p: deleted.append(p))
    p = make_pod("p0", cpu_milli=100, mem=0)
    p.priority = 1000
    sched.queue.add(p)
    res = sched.schedule_batch()
    assert res.errors == 1
    assert res.scheduled == 0 and res.unschedulable == 0
    assert res.preempted == 0 and deleted == []  # no eviction on a blip
    assert sched.queue.pending_count() == 1  # re-queued for retry


def test_driver_ignorable_extender_outage_is_skipped():
    cache = SchedulerCache()
    cache.add_node(make_node("n0", cpu_milli=4000, mem=8 * 2**30))
    dead = HTTPExtender(ExtenderConfig(
        url_prefix="http://127.0.0.1:1", filter_verb="filter",
        node_cache_capable=True, ignorable=True, timeout_s=0.2,
    ))
    sched = Scheduler(cache=cache, queue=PriorityQueue(), extenders=[dead],
                      deterministic=True)
    sched.queue.add(make_pod("p0", cpu_milli=100, mem=0))
    res = sched.schedule_batch()
    assert res.scheduled == 1  # ignorable extender outage doesn't block


def test_filter_device_path_matches_oracle():
    """With device_threshold lowered, /filter runs the fused [1, N] device
    mask over the mirror — results must match the oracle path."""
    cache = SchedulerCache()
    for i in range(8):
        cache.add_node(make_node(f"n{i}", cpu_milli=1000 if i % 2 else 4000, mem=8 * 2**30))
    srv = ExtenderServer(cache=cache, device_threshold=4).start()
    try:
        pod = make_pod("p0", cpu_milli=2000, mem=0)
        names = [f"n{i}" for i in range(8)] + ["ghost"]
        res = _post(srv.url + "/filter", {"Pod": pod_to_k8s(pod), "NodeNames": names})
        assert sorted(res["NodeNames"]) == ["n0", "n2", "n4", "n6"]
        assert set(res["FailedNodes"]) == {"n1", "n3", "n5", "n7", "ghost"}
        assert res["FailedNodes"]["ghost"] == "node unknown"
    finally:
        srv.stop()


def test_filter_device_path_memoizes_same_spec_pods():
    """Term-plane satellite: /filter used to compile a fresh single-pod
    PodBatch + TermBank per HTTP request. Repeated requests for
    SAME-SPEC pods (replicas of one controller — the common extender
    traffic) must hit the per-spec_key encode memo; a different spec
    must miss it; and the cached answer must equal the fresh one."""
    cache = SchedulerCache()
    for i in range(8):
        cache.add_node(make_node(
            f"n{i}", cpu_milli=1000 if i % 2 else 4000, mem=8 * 2**30
        ))
    srv = ExtenderServer(cache=cache, device_threshold=4).start()
    try:
        names = [f"n{i}" for i in range(8)]
        answers = []
        for rep in range(3):  # replicas: same spec, different names
            pod = make_pod(f"web-{rep}", cpu_milli=2000, mem=0,
                           labels={"app": "web"})
            res = _post(srv.url + "/filter",
                        {"Pod": pod_to_k8s(pod), "NodeNames": names})
            answers.append(sorted(res["NodeNames"]))
        assert answers[0] == answers[1] == answers[2] == ["n0", "n2", "n4", "n6"]
        assert srv.filter_encode_cache["misses"] == 1
        assert srv.filter_encode_cache["hits"] == 2
        other = make_pod("db-0", cpu_milli=500, mem=0, labels={"app": "db"})
        _post(srv.url + "/filter", {"Pod": pod_to_k8s(other), "NodeNames": names})
        assert srv.filter_encode_cache["misses"] == 2
    finally:
        srv.stop()


def test_end_to_end_server_as_extender_for_fake_scheduler(server):
    """The fake-kube-scheduler flow end-to-end against ExtenderServer:
    filter → prioritize → bind round trip picking the best feasible node."""
    # load n0..n4 heavily; n5 stays empty (LeastRequested will prefer it)
    for i in range(5):
        p = make_pod(f"load{i}", cpu_milli=2500, mem=2**30)
        p.node_name = f"n{i}"
        server.cache.add_pod(p)
    pod = make_pod("incoming", cpu_milli=1000, mem=2**28)
    names = [f"n{i}" for i in range(6)]
    fres = _post(server.url + "/filter", {"Pod": pod_to_k8s(pod), "NodeNames": names})
    feasible = fres["NodeNames"]
    assert "n5" in feasible and len(feasible) == 6  # all still fit 1000m
    pres = _post(server.url + "/prioritize", {"Pod": pod_to_k8s(pod), "NodeNames": feasible})
    best = max(pres, key=lambda d: d["Score"])["Host"]
    assert best == "n5"
    bres = _post(server.url + "/bind", {
        "PodName": pod.name, "PodNamespace": pod.namespace, "PodUID": pod.uid, "Node": best,
    })
    assert bres["Error"] == ""
    assert server.test_binds[-1].node == "n5"
