"""Threaded stress tests for the queue / cache / bind-pool interplay.

The reference leans on `go test -race` plus the informer cache mutation
detector (client-go `tools/cache/mutation_detector.go`) to keep the
scheduler's three mutable shared structures honest under concurrency:
the scheduling queue (scheduling_queue.go), the scheduler cache
(internal/cache/cache.go), and the async bind goroutines
(scheduler.go:631-673). Python has no race detector, so this file takes
the other road: hammer the same interleavings from many writer threads
while the batch loop runs, then assert global invariants — every bound
pod landed on a node that exists, the incremental cache state matches a
from-scratch recomputation (CacheComparer plays the
cache_comparer.go:71 role), and the tensor mirror stays rebuildable.
"""

import threading
import time

import pytest

pytest.importorskip("jax")

from kubernetes_tpu.models.generators import make_node, make_pod
from kubernetes_tpu.scheduler.driver import Binder, Scheduler
from kubernetes_tpu.scheduler.eventhandlers import EventHandlers
from kubernetes_tpu.state.cache import SchedulerCache, TensorMirror
from kubernetes_tpu.state.debugger import CacheComparer
from kubernetes_tpu.state.queue import PriorityQueue


def test_concurrent_event_writers_while_scheduling():
    """4 writer threads fire pod/node events straight at EventHandlers (the
    informer serializes per-resource; direct calls are strictly harsher)
    while the main thread drives schedule_batch. No exceptions, no
    deadlock, and the end state is consistent."""
    cache = SchedulerCache()
    for i in range(8):
        cache.add_node(make_node(f"n{i}", cpu_milli=32_000, mem=64 * 2**30))
    queue = PriorityQueue()
    bound = {}
    bound_lock = threading.Lock()

    def bind_fn(pod, node_name):
        # simulate bind RPC latency so binds genuinely overlap the solve
        time.sleep(0.001)
        with bound_lock:
            bound[pod.key()] = node_name

    sched = Scheduler(
        cache=cache, queue=queue, binder=Binder(bind_fn=bind_fn),
        batch_size=64, enable_preemption=False,
    )
    handlers = EventHandlers(cache, queue)
    errors = []
    live_nodes = {f"n{i}" for i in range(8)}
    node_lock = threading.Lock()

    def pod_writer(base):
        try:
            for i in range(80):
                handlers.on_pod_add(make_pod(f"w{base}-{i}", cpu_milli=50, mem=0))
        except Exception as e:  # pragma: no cover
            errors.append(e)

    def node_churner():
        try:
            for i in range(30):
                name = f"extra-{i}"
                n = make_node(name, cpu_milli=32_000, mem=64 * 2**30)
                handlers.on_node_add(n)
                with node_lock:
                    live_nodes.add(name)
                time.sleep(0.002)
                if i % 3 == 0:
                    # update path: relabel (dirty row, MoveAllToActive)
                    n2 = make_node(name, cpu_milli=32_000, mem=64 * 2**30,
                                   labels={"churned": "yes"})
                    handlers.on_node_update(n, n2)
        except Exception as e:  # pragma: no cover
            errors.append(e)

    writers = [threading.Thread(target=pod_writer, args=(k,)) for k in range(3)]
    writers.append(threading.Thread(target=node_churner))
    for t in writers:
        t.start()
    deadline = time.time() + 120
    total_pods = 3 * 80
    while time.time() < deadline:
        sched.queue.flush()
        sched.schedule_batch()
        if all(not t.is_alive() for t in writers):
            with bound_lock:
                done = len(bound)
            if done >= total_pods:
                break
        time.sleep(0.001)
    for t in writers:
        t.join()
    # drain stragglers deterministically
    for _ in range(30):
        sched.queue.move_all_to_active()
        sched.queue.flush()
        sched.schedule_batch()
    sched.wait_for_binds()

    assert not errors, errors
    with bound_lock:
        assert len(bound) == total_pods, f"bound {len(bound)}/{total_pods}"
        for key, node in bound.items():
            with node_lock:
                assert node in live_nodes, f"{key} bound to unknown node {node}"
    # incremental cache state == from-scratch recomputation
    comparer = CacheComparer(cache)
    nodes_now = [cache.snapshot.node_infos[n].node for n in cache.snapshot.node_infos]
    missing, stale = comparer.compare_nodes(nodes_now)
    assert not missing and not stale
    # the mirror can still rebuild cleanly from the post-stress cache
    mirror = TensorMirror(cache)
    assert mirror.nodes.valid.sum() == len(cache.snapshot.node_infos)


def test_assume_expire_requeue_under_concurrent_binds():
    """Binds succeed but the informer confirmation never arrives: once the
    post-bind TTL lapses, every assumed pod is rolled out of the cache with
    node accounting intact (cleanupAssumedPods, cache.go:658 — the TTL
    clock starts at FinishBinding, cache.go:300, so in-flight binds are
    never expired)."""
    cache = SchedulerCache(ttl=0.05)
    cache.add_node(make_node("n0", cpu_milli=4000, mem=8 * 2**30))
    queue = PriorityQueue()

    def bind_ok_no_confirm(pod, node_name):
        time.sleep(0.02)  # overlap the binds

    sched = Scheduler(
        cache=cache, queue=queue, binder=Binder(bind_fn=bind_ok_no_confirm),
        batch_size=8, enable_preemption=False,
    )
    for i in range(4):
        queue.add(make_pod(f"p{i}", cpu_milli=100, mem=0))
    r = sched.schedule_batch()
    assert r.scheduled == 4
    # while binds are still in flight the pods must NOT be expirable
    expired_early = cache.cleanup_expired()
    assert expired_early == []
    sched.wait_for_binds()  # finish_binding has now stamped each deadline
    assert cache.assumed_count() == 4
    time.sleep(0.1)  # outlive the 50ms TTL with no informer add_pod echo
    expired = cache.cleanup_expired()
    assert len(expired) == 4
    ni = cache.snapshot.get("n0")
    assert len(ni.pods) == 0
    assert ni.requested().get("cpu", 0) == 0


def test_sigbank_stays_consistent_under_churn():
    """Property: after arbitrary pod add/remove/node-remove churn, the
    incremental SigBank equals a from-scratch re-encode — counts per
    (node, signature) match, no negative counts, freed node rows hold
    zero counts, and refcounts equal the count-matrix column sums."""
    import random

    import numpy as np

    from kubernetes_tpu.state.tensors import encode_snapshot

    rng = random.Random(42)
    cache = SchedulerCache()
    for i in range(12):
        cache.add_node(make_node(f"n{i}"))
    mirror = TensorMirror(cache)
    live = []
    label_sets = [{"app": "a"}, {"app": "b", "tier": "web"}, {}, {"app": "a", "env": "p"}]
    for step in range(300):
        op = rng.random()
        if op < 0.55 or not live:
            p = make_pod(f"c{step}", labels=dict(rng.choice(label_sets)),
                         node_name=f"n{rng.randrange(12)}")
            if rng.random() < 0.1:
                p.deletion_timestamp = 123.0
            cache.add_pod(p)
            live.append(p)
        elif op < 0.9:
            p = live.pop(rng.randrange(len(live)))
            cache.remove_pod(p)
        else:
            victim = f"n{rng.randrange(12)}"
            if cache.snapshot.get(victim) is not None and len(cache.snapshot.node_infos) > 2:
                cache.remove_node(victim)
                live = [p for p in live if p.node_name != victim]
        if step % 25 == 0:
            mirror.sync()
    mirror.sync()

    sig = mirror.eps
    # 1. no negative counts anywhere
    assert (sig.counts >= 0).all()
    # 2. refcounts == column sums, valid rows exactly the referenced ones
    col = sig.counts.astype(np.int64).sum(axis=0)
    assert (col == sig._refs).all()
    assert (sig.valid == (sig._refs > 0)).all()
    # 3. freed node rows hold zero counts
    for row in mirror._free_rows:
        assert sig.counts[row].sum() == 0, f"stale counts in free row {row}"
    # 4. equivalence with a from-scratch encode: per-node signature
    #    histograms (keyed by label bytes + ns + deleting) must match
    # same vocab → identical interned ids, so raw byte histograms compare
    _, fresh, fresh_row_of = encode_snapshot(
        cache.snapshot, vocab=mirror.vocab, with_images=False
    )

    def histogram(bank, row):
        out = {}
        for s in range(bank.capacity):
            c = int(bank.counts[row, s])
            if c:
                out[(bank.label_vals[s].tobytes(), int(bank.ns_id[s]), bool(bank.deleting[s]))] = c
        return out

    for name, row in mirror.row_of.items():
        fr = fresh_row_of[name]
        assert histogram(sig, row) == histogram(fresh, fr), f"node {name} diverged"


def test_patternbank_stays_consistent_under_churn():
    """Property: after arbitrary churn of affinity-carrying pods (delta
    adds/removes, node removals, periodic syncs), the incremental
    PatternBank equals a from-scratch compile — per-(node, pattern-key)
    counts match, refcounts equal column sums, freed rows are clean."""
    import random

    import numpy as np

    from kubernetes_tpu.api.types import (
        Affinity,
        LabelSelector,
        PodAffinityTerm,
        PodAntiAffinity,
        PodAffinity,
        WeightedPodAffinityTerm,
    )
    from kubernetes_tpu.state.terms import compile_existing_patterns

    rng = random.Random(7)
    cache = SchedulerCache()
    for i in range(10):
        cache.add_node(make_node(f"n{i}", labels={"kubernetes.io/hostname": f"n{i}"}))
    mirror = TensorMirror(cache)

    def mk_affinity(kind: int):
        term = PodAffinityTerm(
            label_selector=LabelSelector(match_labels={"app": f"svc-{kind}"}),
            topology_key="kubernetes.io/hostname",
        )
        if kind % 2:
            return Affinity(pod_anti_affinity=PodAntiAffinity(required=[term]))
        return Affinity(pod_affinity=PodAffinity(
            preferred=[WeightedPodAffinityTerm(weight=5 + kind, pod_affinity_term=term)]
        ))

    live = []
    for step in range(300):
        op = rng.random()
        if op < 0.55 or not live:
            p = make_pod(f"a{step}", labels={"app": f"svc-{step % 4}"},
                         node_name=f"n{rng.randrange(10)}")
            if rng.random() < 0.7:
                p.affinity = mk_affinity(rng.randrange(6))
            cache.add_pod(p)
            live.append(p)
        elif op < 0.9:
            p = live.pop(rng.randrange(len(live)))
            cache.remove_pod(p)
        else:
            victim = f"n{rng.randrange(10)}"
            if cache.snapshot.get(victim) is not None and len(cache.snapshot.node_infos) > 2:
                cache.remove_node(victim)
                live = [p for p in live if p.node_name != victim]
        if step % 20 == 0:
            mirror.sync()
    mirror.sync()

    pats = mirror.pats
    assert (pats.counts >= 0).all()
    col = pats.counts.astype(np.int64).sum(axis=0)
    assert (col == pats._refs).all()
    assert (pats.valid == (pats._refs > 0)).all()
    for row in mirror._free_rows:
        assert pats.counts[row].sum() == 0, f"stale pattern counts in free row {row}"
    # per-(node, pattern-key) histograms equal a from-scratch compile
    fresh = compile_existing_patterns(
        mirror.vocab, cache.snapshot, mirror.row_of, mirror.nodes.capacity
    )
    for name, row in mirror.row_of.items():
        mine = {
            pats._key_of_row[s]: int(pats.counts[row, s])
            for s in range(pats.capacity)
            if pats.counts[row, s]
        }
        theirs = {
            fresh._key_of_row[s]: int(fresh.counts[row, s])
            for s in range(fresh.capacity)
            if fresh.counts[row, s]
        }
        assert mine == theirs, (name, mine, theirs)
