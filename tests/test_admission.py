"""Admission chain: PriorityClass resolution + defaultTolerationSeconds on
apiserver writes, end-to-end over HTTP into the scheduler's priority view.
Reference: plugin/pkg/admission/priority/admission.go:137,
plugin/pkg/admission/defaulttolerationseconds/admission.go:76."""

import pytest

pytest.importorskip("jax")

from kubernetes_tpu.api.types import (
    PriorityClass,
    SYSTEM_CLUSTER_CRITICAL,
    SYSTEM_CRITICAL_PRIORITY,
)
from kubernetes_tpu.apiserver import (
    AdmissionError,
    APIServerHTTP,
    FakeAPIServer,
    default_admission_chain,
    install_system_priority_classes,
)
from kubernetes_tpu.client import RemoteAPIServer
from kubernetes_tpu.models.generators import make_pod


@pytest.fixture()
def api():
    store = FakeAPIServer(admission=default_admission_chain())
    install_system_priority_classes(store)
    return store


def test_priority_class_resolution(api):
    api.create("priorityclasses", PriorityClass(name="high", value=1000))
    p = make_pod("a", cpu_milli=100, mem=2**20)
    p.priority_class_name = "high"
    created = api.create("pods", p)
    assert created.priority == 1000
    assert created.get_priority() == 1000


def test_priority_unknown_class_rejected(api):
    p = make_pod("b", cpu_milli=100, mem=2**20)
    p.priority_class_name = "nope"
    with pytest.raises(AdmissionError):
        api.create("pods", p)


def test_priority_global_default_applies(api):
    api.create(
        "priorityclasses",
        PriorityClass(name="default-tier", value=7, global_default=True),
    )
    created = api.create("pods", make_pod("c", cpu_milli=100, mem=2**20))
    assert created.priority == 7


def test_priority_system_classes_builtin(api):
    p = make_pod("d", cpu_milli=100, mem=2**20)
    p.priority_class_name = SYSTEM_CLUSTER_CRITICAL
    created = api.create("pods", p)
    assert created.priority == SYSTEM_CRITICAL_PRIORITY


def test_system_prefix_protected(api):
    with pytest.raises(AdmissionError):
        api.create("priorityclasses", PriorityClass(name="system-mine", value=5))


def test_default_toleration_seconds(api):
    created = api.create("pods", make_pod("e", cpu_milli=100, mem=2**20))
    tols = {t.key: t for t in created.tolerations}
    for key in ("node.kubernetes.io/not-ready", "node.kubernetes.io/unreachable"):
        assert key in tols
        assert tols[key].effect == "NoExecute"
        assert tols[key].toleration_seconds == 300


def test_priority_resolution_over_http_to_scheduler_view(api):
    """A pod POSTed over the wire with priorityClassName comes back with the
    resolved priority — what the scheduler's informer then sees."""
    srv = APIServerHTTP(api).start()
    try:
        remote = RemoteAPIServer(srv.url)
        remote.create("priorityclasses", PriorityClass(name="web-tier", value=500))
        got = remote.get("priorityclasses", "web-tier")
        assert got.value == 500
        p = make_pod("w", cpu_milli=100, mem=2**20)
        p.priority_class_name = "web-tier"
        created = remote.create("pods", p)
        assert created.priority == 500
        # rejection surfaces as AdmissionError over the wire too
        bad = make_pod("x", cpu_milli=100, mem=2**20)
        bad.priority_class_name = "missing"
        with pytest.raises(AdmissionError):
            remote.create("pods", bad)
    finally:
        srv.stop()


def test_default_toleration_ignores_noschedule_only(api):
    """A NoSchedule-only toleration for not-ready must NOT suppress the
    default NoExecute toleration (admission.go:87-99 checks the effect)."""
    from kubernetes_tpu.api.types import Toleration

    p = make_pod("f", cpu_milli=100, mem=2**20)
    p.tolerations = [
        Toleration(key="node.kubernetes.io/not-ready", operator="Exists",
                   effect="NoSchedule")
    ]
    created = api.create("pods", p)
    ne = [t for t in created.tolerations
          if t.key == "node.kubernetes.io/not-ready" and t.effect == "NoExecute"]
    assert len(ne) == 1 and ne[0].toleration_seconds == 300
