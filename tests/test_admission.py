"""Admission chain: PriorityClass resolution + defaultTolerationSeconds on
apiserver writes, end-to-end over HTTP into the scheduler's priority view.
Reference: plugin/pkg/admission/priority/admission.go:137,
plugin/pkg/admission/defaulttolerationseconds/admission.go:76."""

import pytest

pytest.importorskip("jax")

from kubernetes_tpu.api.types import (
    PriorityClass,
    SYSTEM_CLUSTER_CRITICAL,
    SYSTEM_CRITICAL_PRIORITY,
)
from kubernetes_tpu.apiserver import (
    AdmissionError,
    APIServerHTTP,
    FakeAPIServer,
    default_admission_chain,
    install_system_priority_classes,
)
from kubernetes_tpu.client import RemoteAPIServer
from kubernetes_tpu.models.generators import make_pod


@pytest.fixture()
def api():
    store = FakeAPIServer(admission=default_admission_chain())
    install_system_priority_classes(store)
    return store


def test_priority_class_resolution(api):
    api.create("priorityclasses", PriorityClass(name="high", value=1000))
    p = make_pod("a", cpu_milli=100, mem=2**20)
    p.priority_class_name = "high"
    created = api.create("pods", p)
    assert created.priority == 1000
    assert created.get_priority() == 1000


def test_priority_unknown_class_rejected(api):
    p = make_pod("b", cpu_milli=100, mem=2**20)
    p.priority_class_name = "nope"
    with pytest.raises(AdmissionError):
        api.create("pods", p)


def test_priority_global_default_applies(api):
    api.create(
        "priorityclasses",
        PriorityClass(name="default-tier", value=7, global_default=True),
    )
    created = api.create("pods", make_pod("c", cpu_milli=100, mem=2**20))
    assert created.priority == 7


def test_priority_system_classes_builtin(api):
    p = make_pod("d", cpu_milli=100, mem=2**20)
    p.priority_class_name = SYSTEM_CLUSTER_CRITICAL
    created = api.create("pods", p)
    assert created.priority == SYSTEM_CRITICAL_PRIORITY


def test_system_prefix_protected(api):
    with pytest.raises(AdmissionError):
        api.create("priorityclasses", PriorityClass(name="system-mine", value=5))


def test_default_toleration_seconds(api):
    created = api.create("pods", make_pod("e", cpu_milli=100, mem=2**20))
    tols = {t.key: t for t in created.tolerations}
    for key in ("node.kubernetes.io/not-ready", "node.kubernetes.io/unreachable"):
        assert key in tols
        assert tols[key].effect == "NoExecute"
        assert tols[key].toleration_seconds == 300


def test_priority_resolution_over_http_to_scheduler_view(api):
    """A pod POSTed over the wire with priorityClassName comes back with the
    resolved priority — what the scheduler's informer then sees."""
    srv = APIServerHTTP(api).start()
    try:
        remote = RemoteAPIServer(srv.url)
        remote.create("priorityclasses", PriorityClass(name="web-tier", value=500))
        got = remote.get("priorityclasses", "web-tier")
        assert got.value == 500
        p = make_pod("w", cpu_milli=100, mem=2**20)
        p.priority_class_name = "web-tier"
        created = remote.create("pods", p)
        assert created.priority == 500
        # rejection surfaces as AdmissionError over the wire too
        bad = make_pod("x", cpu_milli=100, mem=2**20)
        bad.priority_class_name = "missing"
        with pytest.raises(AdmissionError):
            remote.create("pods", bad)
    finally:
        srv.stop()


def test_default_toleration_ignores_noschedule_only(api):
    """A NoSchedule-only toleration for not-ready must NOT suppress the
    default NoExecute toleration (admission.go:87-99 checks the effect)."""
    from kubernetes_tpu.api.types import Toleration

    p = make_pod("f", cpu_milli=100, mem=2**20)
    p.tolerations = [
        Toleration(key="node.kubernetes.io/not-ready", operator="Exists",
                   effect="NoSchedule")
    ]
    created = api.create("pods", p)
    ne = [t for t in created.tolerations
          if t.key == "node.kubernetes.io/not-ready" and t.effect == "NoExecute"]
    assert len(ne) == 1 and ne[0].toleration_seconds == 300


# ---------------------------------------------------------------------------
# LimitRanger (plugin/pkg/admission/limitranger/admission.go:77)
# ---------------------------------------------------------------------------

def _lr(namespace="default", **item_kwargs):
    from kubernetes_tpu.api.types import LimitRange, LimitRangeItem

    return LimitRange(name="limits", namespace=namespace,
                      limits=[LimitRangeItem(type="Container", **item_kwargs)])


def _bare_pod(name, namespace="default"):
    from kubernetes_tpu.api.types import Container, Pod

    return Pod(name=name, namespace=namespace, containers=[Container(name="c")])


def test_limitranger_defaults_requests(api):
    from kubernetes_tpu.api.types import Quantity, RESOURCE_CPU, RESOURCE_MEMORY

    api.create("limitranges", _lr(default_request={
        RESOURCE_CPU: Quantity.parse("200m"), RESOURCE_MEMORY: Quantity.parse("128Mi"),
    }))
    created = api.create("pods", _bare_pod("nolimits"))
    req = created.resource_request()
    # THIS is what the scheduler's informer sees: the defaults, not zero
    assert req[RESOURCE_CPU] == 200 and req[RESOURCE_MEMORY] == 128 * 2**20


def test_limitranger_default_limit_backs_request(api):
    from kubernetes_tpu.api.types import Quantity, RESOURCE_CPU

    api.create("limitranges", _lr(default={RESOURCE_CPU: Quantity.parse("500m")}))
    created = api.create("pods", _bare_pod("limonly"))
    c = created.containers[0]
    assert c.limits[RESOURCE_CPU].milli_value() == 500
    assert created.resource_request()[RESOURCE_CPU] == 500


def test_limitranger_min_max_enforced(api):
    from kubernetes_tpu.api.types import Container, Pod, Quantity, RESOURCE_CPU

    api.create("limitranges", _lr(
        min={RESOURCE_CPU: Quantity.parse("100m")},
        max={RESOURCE_CPU: Quantity.parse("1")},
    ))
    lo = Pod(name="toolow", containers=[
        Container(name="c", requests={RESOURCE_CPU: Quantity.parse("50m")})])
    with pytest.raises(AdmissionError):
        api.create("pods", lo)
    hi = Pod(name="toohigh", containers=[
        Container(name="c", requests={RESOURCE_CPU: Quantity.parse("2")})])
    with pytest.raises(AdmissionError):
        api.create("pods", hi)
    ok = Pod(name="inband", containers=[
        Container(name="c", requests={RESOURCE_CPU: Quantity.parse("500m")})])
    api.create("pods", ok)


def test_limitranger_namespace_scoped(api):
    from kubernetes_tpu.api.types import Quantity, RESOURCE_CPU

    api.create("limitranges", _lr(namespace="prod",
                                  default_request={RESOURCE_CPU: Quantity.parse("200m")}))
    created = api.create("pods", _bare_pod("elsewhere", namespace="default"))
    assert created.resource_request().get(RESOURCE_CPU, 0) == 0


# ---------------------------------------------------------------------------
# ResourceQuota admission (plugin/pkg/admission/resourcequota/admission.go)
# ---------------------------------------------------------------------------

def test_quota_rejects_over_pod_count(api):
    from kubernetes_tpu.api.types import ResourceQuota

    api.create("resourcequotas", ResourceQuota(name="q", hard={"pods": 2}))
    api.create("pods", make_pod("q1", cpu_milli=100, mem=2**20))
    api.create("pods", make_pod("q2", cpu_milli=100, mem=2**20))
    with pytest.raises(AdmissionError):
        api.create("pods", make_pod("q3", cpu_milli=100, mem=2**20))
    # usage was charged synchronously at admission
    assert api.get("resourcequotas", "default/q").used["pods"] == 2


def test_quota_rejects_over_cpu_sum(api):
    from kubernetes_tpu.api.types import ResourceQuota

    api.create("resourcequotas", ResourceQuota(
        name="cpu", hard={"requests.cpu": 1000}))
    api.create("pods", make_pod("c1", cpu_milli=600, mem=2**20))
    with pytest.raises(AdmissionError):
        api.create("pods", make_pod("c2", cpu_milli=600, mem=2**20))
    api.create("pods", make_pod("c3", cpu_milli=400, mem=2**20))
    assert api.get("resourcequotas", "default/cpu").used["requests.cpu"] == 1000


def test_quota_count_kind(api):
    from kubernetes_tpu.api.types import ResourceQuota, Service

    api.create("resourcequotas", ResourceQuota(
        name="svc", hard={"count/services": 1}))
    api.create("services", Service(name="s1", selector={"a": "b"}))
    with pytest.raises(AdmissionError):
        api.create("services", Service(name="s2", selector={"a": "b"}))


def test_quota_charged_after_limitranger_defaults(api):
    """Quota runs LAST: a pod whose requests come entirely from LimitRange
    defaults is charged at the defaulted value, not zero."""
    from kubernetes_tpu.api.types import Quantity, RESOURCE_CPU, ResourceQuota

    api.create("limitranges", _lr(default_request={RESOURCE_CPU: Quantity.parse("600m")}))
    api.create("resourcequotas", ResourceQuota(
        name="both", hard={"requests.cpu": 1000}))
    api.create("pods", _bare_pod("d1"))
    with pytest.raises(AdmissionError):
        api.create("pods", _bare_pod("d2"))  # 600 + 600 > 1000


def test_quota_over_http_is_422(api):
    from kubernetes_tpu.api.types import ResourceQuota

    api.create("resourcequotas", ResourceQuota(name="w", hard={"pods": 1}))
    srv = APIServerHTTP(api).start()
    try:
        remote = RemoteAPIServer(srv.url)
        remote.create("pods", make_pod("h1", cpu_milli=100, mem=2**20))
        with pytest.raises(AdmissionError) as exc:
            remote.create("pods", make_pod("h2", cpu_milli=100, mem=2**20))
        assert "exceeded quota" in str(exc.value)
    finally:
        srv.stop()


def test_limitranger_min_enforced_against_explicit_limit(api):
    """Advisor finding #5: a container with an explicit LIMIT below
    item.min must be rejected, exactly as max already checks both."""
    from kubernetes_tpu.api.types import Container, Pod, Quantity, RESOURCE_CPU

    api.create("limitranges", _lr(min={RESOURCE_CPU: Quantity.parse("100m")}))
    lo = Pod(name="lowlimit", containers=[
        Container(name="c",
                  requests={RESOURCE_CPU: Quantity.parse("150m")},
                  limits={RESOURCE_CPU: Quantity.parse("50m")})])
    with pytest.raises(AdmissionError) as exc:
        api.create("pods", lo)
    assert "limit" in str(exc.value)


def test_quota_not_charged_on_duplicate_create(api):
    """Advisor finding #2 (the CronJob Replace/dedupe leak): admission
    charges quota BEFORE the store's duplicate-name check; a
    ConflictError create must roll the charge back, not strand it until
    the controller resync."""
    from kubernetes_tpu.api.types import Job, ResourceQuota
    from kubernetes_tpu.apiserver import ConflictError

    api.create("resourcequotas", ResourceQuota(name="jq", hard={"count/jobs": 5}))
    api.create("jobs", Job(name="replace-me"))
    assert api.get("resourcequotas", "default/jq").used["count/jobs"] == 1
    # the CronJob Replace path re-creates the same name -> ConflictError
    for _ in range(3):
        with pytest.raises(ConflictError):
            api.create("jobs", Job(name="replace-me"))
    assert api.get("resourcequotas", "default/jq").used["count/jobs"] == 1
    # pods leak the same way (requests.* deltas, not just counts)
    api.create("resourcequotas", ResourceQuota(
        name="pq", hard={"pods": 10, "requests.cpu": 10_000}))
    api.create("pods", make_pod("dup", cpu_milli=500, mem=2**20))
    used0 = dict(api.get("resourcequotas", "default/pq").used)
    with pytest.raises(ConflictError):
        api.create("pods", make_pod("dup", cpu_milli=500, mem=2**20))
    assert api.get("resourcequotas", "default/pq").used == used0


def test_quota_multi_quota_rejection_rolls_back_earlier_charges(api):
    """Two matching quotas: when the SECOND rejects, the first's charge
    must be rolled back (compute-all, charge-all-or-nothing)."""
    from kubernetes_tpu.api.types import ResourceQuota

    api.create("resourcequotas", ResourceQuota(name="loose", hard={"pods": 100}))
    api.create("resourcequotas", ResourceQuota(name="tight", hard={"requests.cpu": 100}))
    with pytest.raises(AdmissionError):
        api.create("pods", make_pod("big", cpu_milli=500, mem=2**20))
    assert api.get("resourcequotas", "default/loose").used.get("pods", 0) == 0
    assert api.get("resourcequotas", "default/tight").used.get("requests.cpu", 0) == 0
    # a pod that clears both charges both
    api.create("pods", make_pod("small", cpu_milli=50, mem=2**20))
    assert api.get("resourcequotas", "default/loose").used["pods"] == 1
    assert api.get("resourcequotas", "default/tight").used["requests.cpu"] == 50


def test_quota_rolled_back_on_wal_failure(api):
    """A create that fails AFTER admission for any reason (not just a
    duplicate name — e.g. a WAL write error) must uncharge quota and
    leave no object behind."""
    from kubernetes_tpu.api.types import ResourceQuota
    from kubernetes_tpu.apiserver import FakeAPIServer, default_admission_chain

    class _BrokenWAL:
        """Fails pod writes only — the quota uncharge (an update to the
        resourcequotas kind) must still be able to land."""

        def replay(self):
            return {}, 0

        def append(self, op, kind, *a, **k):
            if kind == "pods":
                raise OSError("disk full")

        def maybe_compact(self, *a, **k):
            pass

    store = FakeAPIServer(admission=default_admission_chain(), wal=_BrokenWAL())
    store.create("resourcequotas", ResourceQuota(name="w", hard={"pods": 5}))
    with pytest.raises(OSError):
        store.create("pods", make_pod("doomed", cpu_milli=100, mem=2**20))
    assert store.get("resourcequotas", "default/w").used.get("pods", 0) == 0
    with pytest.raises(Exception):
        store.get("pods", "default/doomed")
