"""NodeTree iteration, cache debugger, leader election."""

import threading

import pytest

pytest.importorskip("jax")

from kubernetes_tpu.apiserver import FakeAPIServer
from kubernetes_tpu.models.generators import make_node, make_pod
from kubernetes_tpu.oracle.nodeinfo import LABEL_ZONE_FAILURE_DOMAIN
from kubernetes_tpu.state.cache import SchedulerCache
from kubernetes_tpu.state.debugger import CacheComparer, CacheDumper
from kubernetes_tpu.state.node_tree import NodeTree
from kubernetes_tpu.state.queue import PriorityQueue
from kubernetes_tpu.utils.leaderelection import LeaderElector, LeaseLock


def _zn(name, zone):
    n = make_node(name, cpu_milli=1000, mem=2**30)
    n.labels[LABEL_ZONE_FAILURE_DOMAIN] = zone
    return n


def test_node_tree_zone_interleaving():
    t = NodeTree()
    for name, zone in [("a1", "z1"), ("a2", "z1"), ("a3", "z1"),
                       ("b1", "z2"), ("c1", "z3")]:
        t.add_node(_zn(name, zone))
    assert t.num_nodes == 5
    order = t.order()
    # one node per zone per round: z1,z2,z3 then z1's remainder
    assert order == ["a1", "b1", "c1", "a2", "a3"]
    # next() round-robins across zones
    seen = [t.next() for _ in range(5)]
    assert seen[0] == "a1" and seen[1] == "b1" and seen[2] == "c1"
    t.remove_node(_zn("b1", "z2"))
    assert t.num_nodes == 4
    assert "b1" not in t.order()


def test_node_tree_zone_change_on_update():
    t = NodeTree()
    t.add_node(_zn("n", "z1"))
    t.update_node(_zn("n", "z1"), _zn("n", "z2"))
    assert t.order() == ["n"]
    assert t.num_nodes == 1


def test_cache_dumper_and_comparer():
    cache = SchedulerCache()
    cache.add_node(make_node("n0", cpu_milli=1000, mem=2**30))
    p = make_pod("p0", cpu_milli=100, mem=0)
    p.node_name = "n0"
    cache.add_pod(p)
    q = PriorityQueue()
    q.add(make_pod("pending", cpu_milli=1, mem=0))
    out = CacheDumper(cache, q).dump()
    assert "node n0" in out and "default/p0" in out and "active=1" in out

    cmp_ = CacheComparer(cache)
    ghost = make_pod("ghost", cpu_milli=1, mem=0)
    ghost.node_name = "n0"
    missed, redundant = cmp_.compare_pods([p, ghost])
    assert missed == ["default/ghost"] and redundant == []
    missed, redundant = cmp_.compare_pods([])
    assert redundant == ["default/p0"]
    missed_n, redundant_n = cmp_.compare_nodes([make_node("n0", cpu_milli=1, mem=1),
                                                make_node("n9", cpu_milli=1, mem=1)])
    assert missed_n == ["n9"] and redundant_n == []


def test_leader_election_single_winner_and_failover():
    api = FakeAPIServer()
    clock = [0.0]
    now = lambda: clock[0]
    events = []

    def mk(identity):
        return LeaderElector(
            LeaseLock(api), identity,
            lease_duration_s=15, renew_deadline_s=10, retry_period_s=2,
            on_started_leading=lambda: events.append(f"{identity}:start"),
            on_stopped_leading=lambda: events.append(f"{identity}:stop"),
            now=now,
        )

    a, b = mk("sched-a"), mk("sched-b")
    assert a.try_acquire_or_renew() is True
    assert a.is_leader()
    # b cannot take an unexpired lease
    assert b.try_acquire_or_renew() is False
    # renewal keeps it
    clock[0] += 5
    assert a.try_acquire_or_renew() is True
    # b observes the renewed record (client-go expiry counts from when the
    # OBSERVER last saw the record change, leaderelection.go observedTime)
    assert b.try_acquire_or_renew() is False
    # a dies; the lease expires from b's viewpoint; b takes over
    clock[0] += 20
    assert b.try_acquire_or_renew() is True
    assert b.is_leader()
    # a still BELIEVES it leads until its next renewal observes b's record
    # (client-go IsLeader reads the cached observation) — then it knows
    assert a.try_acquire_or_renew() is False
    assert not a.is_leader()
    rec = LeaseLock(api).get()
    assert rec.holder_identity == "sched-b"
    assert rec.leader_transitions == 1


def test_leader_election_cas_race():
    """Two candidates racing an expired lease: exactly one wins (the CAS
    conflict on resourceVersion settles it)."""
    api = FakeAPIServer()
    clock = [100.0]
    now = lambda: clock[0]
    a = LeaderElector(LeaseLock(api), "a", 15, 10, 2, now=now)
    b = LeaderElector(LeaseLock(api), "b", 15, 10, 2, now=now)
    # seed an expired lease from a dead holder
    assert a.try_acquire_or_renew()
    clock[0] += 100
    # both observe, then race the update
    results = {}
    barrier = threading.Barrier(2)

    def race(elector, key):
        barrier.wait()
        results[key] = elector.try_acquire_or_renew()

    ta = threading.Thread(target=race, args=(a, "a"))
    tb = threading.Thread(target=race, args=(b, "b"))
    ta.start(); tb.start(); ta.join(); tb.join()
    holder = LeaseLock(api).get().holder_identity
    assert holder in ("a", "b")
    # the loser's CAS must have failed unless it retried after the winner —
    # at most one True for a DIFFERENT holder
    winners = [k for k, v in results.items() if v]
    assert holder in winners
