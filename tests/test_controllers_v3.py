"""Wave-2 controllers (controller count 9 → 17): ReplicationController,
PodGC, TTLAfterFinished, CronJob, Disruption (PDB status), ServiceAccount,
ResourceQuota, HorizontalPodAutoscaler. Reference anchors:
pkg/controller/{replication,podgc,ttlafterfinished,cronjob,disruption,
serviceaccount,resourcequota,podautoscaler}. Where placement matters the
pods flow through the real scheduler loop (same harness as
test_controllers_v2)."""

import time

import pytest

pytest.importorskip("jax")

from kubernetes_tpu.api.types import (
    Container,
    CronJob,
    Deployment,
    HorizontalPodAutoscaler,
    Job,
    LabelSelector,
    Namespace,
    Pod,
    PodDisruptionBudget,
    PodMetrics,
    Quantity,
    RESOURCE_CPU,
    RESOURCE_MEMORY,
    ReplicationController,
    ResourceQuota,
)
from kubernetes_tpu.apiserver import FakeAPIServer
from kubernetes_tpu.client import APIBinder, start_scheduler_informers
from kubernetes_tpu.controllers import ControllerManager
from kubernetes_tpu.models.generators import make_node
from kubernetes_tpu.scheduler.driver import Binder, Scheduler
from kubernetes_tpu.scheduler.eventhandlers import EventHandlers
from kubernetes_tpu.utils.cron import CronSchedule


def _template(app: str, cpu="100m") -> Pod:
    return Pod(
        name="template", labels={"app": app},
        containers=[Container(name="c", requests={
            RESOURCE_CPU: Quantity.parse(cpu),
            RESOURCE_MEMORY: Quantity.parse("64Mi"),
        })],
    )


def _pods(api, app=None):
    pods, _ = api.list("pods")
    if app is None:
        return pods
    return [p for p in pods if p.labels.get("app") == app]


def _wait(pred, timeout=15.0, msg="condition"):
    end = time.monotonic() + timeout
    while time.monotonic() < end:
        if pred():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {msg}")


@pytest.fixture()
def stack():
    api = FakeAPIServer()
    for i in range(3):
        api.create("nodes", make_node(
            f"n{i}", cpu_milli=4000, mem=8 * 2**30,
            labels={"kubernetes.io/hostname": f"n{i}"},
        ))
    sched = Scheduler(batch_size=16, deterministic=True, enable_preemption=False)
    sched.binder = Binder(APIBinder(api).bind)
    handlers = EventHandlers(sched.cache, sched.queue, "default-scheduler")
    informers = start_scheduler_informers(api, handlers)
    for inf in informers.values():
        inf.wait_for_sync()
    cm = ControllerManager(api, resync_period_s=0.2).start()

    def drain(expect, app=None, deadline=20.0):
        end = time.monotonic() + deadline
        while time.monotonic() < end:
            sched.schedule_batch()
            sched.wait_for_binds()
            bound = [p for p in _pods(api, app) if p.node_name]
            if len(bound) >= expect and cm.wait_idle(timeout=0.5):
                return bound
            time.sleep(0.05)
        raise AssertionError(
            f"drain: wanted {expect} bound, have "
            f"{[(p.key(), p.node_name, p.phase) for p in _pods(api, app)]}"
        )

    yield api, sched, cm, drain
    cm.stop()
    for inf in informers.values():
        inf.stop()


# ---------------------------------------------------------------------------
# cron schedule evaluation (vendored robfig/cron equivalent)
# ---------------------------------------------------------------------------

def test_cron_schedule_basics():
    s = CronSchedule("*/5 * * * *")
    base = time.mktime((2026, 8, 1, 12, 2, 0, 0, 0, -1))
    nxt = s.next_after(base)
    assert time.localtime(nxt).tm_min == 5
    # exactly on a boundary → strictly after
    on = time.mktime((2026, 8, 1, 12, 5, 0, 0, 0, -1))
    assert time.localtime(s.next_after(on)).tm_min == 10

    daily = CronSchedule("30 3 * * *")
    t = time.localtime(daily.next_after(base))
    assert (t.tm_hour, t.tm_min) == (3, 30) and t.tm_mday == 2

    unmet = s.unmet_since(base, base + 11 * 60)
    assert [time.localtime(u).tm_min for u in unmet] == [5, 10]

    with pytest.raises(Exception):
        CronSchedule("not a schedule")
    # bounded give-up: a month-stale lastScheduleTime must not walk
    # 40k minutes — too-many-missed returns [] (cronjob controller then
    # self-heals by advancing lastScheduleTime)
    t0 = time.monotonic()
    assert s.unmet_since(base - 30 * 86400, base) == []
    assert time.monotonic() - t0 < 1.0
    # day-of-week field: Sunday=0; 2026-08-02 is a Sunday
    sun = CronSchedule("0 12 * * 0")
    sat = time.mktime((2026, 8, 1, 13, 0, 0, 0, 0, -1))
    t = time.localtime(sun.next_after(sat))
    assert (t.tm_mday, t.tm_hour) == (2, 12)


# ---------------------------------------------------------------------------
# controllers
# ---------------------------------------------------------------------------

def test_replicationcontroller_scales_and_replaces(stack):
    api, sched, cm, drain = stack
    api.create("replicationcontrollers", ReplicationController(
        name="rc", replicas=3,
        selector=LabelSelector(match_labels={"app": "rc"}),
        template=_template("rc"),
    ))
    bound = drain(3, app="rc")
    assert len(bound) == 3
    assert all(any(r.get("kind") == "ReplicationController"
                   for r in p.owner_references) for p in bound)
    # kill one replica: the RC adapter refills it
    api.delete("pods", bound[0].key())
    drain(3, app="rc")

    # scale down through the API
    rc = api.get("replicationcontrollers", "default/rc")
    rc.replicas = 1
    api.update("replicationcontrollers", rc)
    _wait(lambda: len([p for p in _pods(api, "rc")
                       if p.phase not in ("Succeeded", "Failed")]) == 1,
          msg="RC scale-down to 1")


def test_podgc_orphaned_and_unscheduled_terminating(stack):
    api, sched, cm, drain = stack
    # pod bound to a node that was deleted → orphan sweep removes it
    orphan = Pod(name="orphan", labels={"app": "gcpod"}, node_name="gone-node",
                 containers=_template("gcpod").containers)
    api.create("pods", orphan)
    # unscheduled pod already marked terminating → force-deleted
    doomed = Pod(name="doomed", labels={"app": "gcpod"},
                 containers=_template("gcpod").containers)
    doomed.deletion_timestamp = time.time()
    api.create("pods", doomed)
    _wait(lambda: len(_pods(api, "gcpod")) == 0, msg="podgc sweeps")


def test_job_status_ttl_and_cascade(stack):
    api, sched, cm, drain = stack
    api.create("jobs", Job(
        name="once", parallelism=1, completions=1,
        template=_template("once"), ttl_seconds_after_finished=1,
    ))
    bound = drain(1, app="once")
    # workload reports success
    p = api.get("pods", bound[0].key())
    p.phase = "Succeeded"
    api.update("pods", p)
    # job controller stamps status.completionTime; TTL controller deletes
    # the job 1s later; the GC cascade then removes its pods
    _wait(lambda: "default/once" not in
          {j.key() for j in api.list("jobs")[0]}, msg="TTL deletes finished job")
    _wait(lambda: len(_pods(api, "once")) == 0, msg="GC cascades job pods")


def test_finished_job_stays_finished_after_pod_gc(stack):
    """A completed Job whose Succeeded pods are later deleted must neither
    re-create pods nor hot-loop status writes (completionTime is
    write-once terminal, job_controller.go Complete condition)."""
    api, sched, cm, drain = stack
    api.create("jobs", Job(name="keep", parallelism=1, completions=1,
                           template=_template("keep")))
    bound = drain(1, app="keep")
    p = api.get("pods", bound[0].key())
    p.phase = "Succeeded"
    api.update("pods", p)
    _wait(lambda: api.get("jobs", "default/keep").completion_time is not None,
          msg="job completion stamped")
    # simulate PodGC's terminated sweep removing the succeeded pod
    api.delete("pods", bound[0].key())
    time.sleep(0.5)
    job = api.get("jobs", "default/keep")
    assert job.completion_time is not None and job.succeeded == 0
    assert len(_pods(api, "keep")) == 0  # no replacement pods
    rv = job.resource_version
    time.sleep(0.5)
    assert api.get("jobs", "default/keep").resource_version == rv  # settled


def test_cronjob_spawns_scheduled_jobs(stack):
    api, sched, cm, drain = stack
    cj = CronJob(
        name="tick", schedule="* * * * *",
        job_template=Job(parallelism=1, completions=1, template=_template("tick")),
    )
    # two minute-boundaries already unmet → the controller starts the most
    # recent one immediately (getRecentUnmetScheduleTimes semantics)
    cj.last_schedule_time = time.time() - 120
    api.create("cronjobs", cj)
    _wait(lambda: len(api.list("jobs")[0]) >= 1, msg="cronjob spawned a job")
    jobs, _ = api.list("jobs")
    assert all(any(r.get("kind") == "CronJob" for r in j.owner_references)
               for j in jobs)
    stored = api.get("cronjobs", "default/tick")
    assert stored.last_schedule_time is not None and stored.last_schedule_time > cj.last_schedule_time
    drain(1, app="tick")  # its pod flows through the real scheduler


def test_cronjob_forbid_policy_skips_while_active(stack):
    api, sched, cm, drain = stack
    cj = CronJob(
        name="fb", schedule="* * * * *", concurrency_policy="Forbid",
        job_template=Job(parallelism=1, completions=1, template=_template("fb")),
    )
    cj.last_schedule_time = time.time() - 120
    api.create("cronjobs", cj)
    _wait(lambda: len(api.list("jobs")[0]) == 1, msg="first job")
    # the job is active (no completion); further unmet times must NOT start
    # a second one while Forbid holds
    time.sleep(0.6)  # several resync ticks
    assert len(api.list("jobs")[0]) == 1


def test_disruption_controller_computes_pdb_status(stack):
    api, sched, cm, drain = stack
    api.create("poddisruptionbudgets", PodDisruptionBudget(
        name="budget", selector=LabelSelector(match_labels={"app": "pdb"}),
        min_available=2,
    ))
    for i in range(3):
        p = Pod(name=f"pdb-{i}", labels={"app": "pdb"},
                containers=_template("pdb").containers)
        api.create("pods", p)
    drain(3, app="pdb")
    for p in _pods(api, "pdb"):
        live = api.get("pods", p.key())
        live.phase = "Running"
        api.update("pods", live)
    def status_ok():
        pdb = api.get("poddisruptionbudgets", "default/budget")
        return (pdb.current_healthy == 3 and pdb.desired_healthy == 2
                and pdb.disruptions_allowed == 1 and pdb.expected_pods == 3)
    _wait(status_ok, msg="PDB status")

    # percentage maxUnavailable: 34% of 3 → 1.02 ceil → 2 → desired=1, allowed=2
    pdb = api.get("poddisruptionbudgets", "default/budget")
    pdb.min_available = None
    pdb.max_unavailable = "34%"
    api.update("poddisruptionbudgets", pdb)
    def pct_ok():
        got = api.get("poddisruptionbudgets", "default/budget")
        return got.desired_healthy == 1 and got.disruptions_allowed == 2
    _wait(pct_ok, msg="percent maxUnavailable")


def test_serviceaccount_default_created_and_recreated(stack):
    api, sched, cm, drain = stack
    api.create("namespaces", Namespace(name="prod"))
    _wait(lambda: any(sa.key() == "prod/default"
                      for sa in api.list("serviceaccounts")[0]),
          msg="default SA created")
    api.delete("serviceaccounts", "prod/default")
    _wait(lambda: any(sa.key() == "prod/default"
                      for sa in api.list("serviceaccounts")[0]),
          msg="default SA recreated")


def test_resourcequota_status_tracks_usage(stack):
    api, sched, cm, drain = stack
    api.create("resourcequotas", ResourceQuota(
        name="quota", namespace="default",
        hard={"pods": 5, "requests.cpu": 1000, "count/services": 2},
    ))
    for i in range(2):
        api.create("pods", Pod(name=f"q-{i}", labels={"app": "q"},
                               containers=_template("q", cpu="300m").containers))
    def used_ok():
        rq = api.get("resourcequotas", "default/quota")
        return rq.used.get("pods") == 2 and rq.used.get("requests.cpu") == 600
    _wait(used_ok, msg="quota usage")
    api.delete("pods", "default/q-0")
    _wait(lambda: api.get("resourcequotas", "default/quota").used.get("pods") == 1,
          msg="quota replenished on delete")


def test_hpa_scales_deployment_from_pod_metrics(stack):
    api, sched, cm, drain = stack
    api.create("deployments", Deployment(
        name="web", replicas=1,
        selector=LabelSelector(match_labels={"app": "web"}),
        template=_template("web", cpu="100m"),
    ))
    bound = drain(1, app="web")
    api.create("horizontalpodautoscalers", HorizontalPodAutoscaler(
        name="web", target_kind="Deployment", target_name="web",
        min_replicas=1, max_replicas=4, target_cpu_utilization_pct=100,
    ))
    # usage = 200m against a 100m request → 200% of target → desired 2
    for p in _pods(api, "web"):
        api.create("podmetrics", PodMetrics(
            name=p.name, namespace=p.namespace, cpu_milli=200, timestamp=time.time(),
        ))
    _wait(lambda: api.get("deployments", "default/web").replicas == 2,
          msg="HPA scaled deployment to 2")
    drain(2, app="web")
    # the new replica has no metrics yet: missing-metrics conservatism
    # (assumed 0 on the way up) must HOLD at 2, not run to max_replicas
    time.sleep(0.8)  # several resync ticks
    assert api.get("deployments", "default/web").replicas == 2
    hpa = api.get("horizontalpodautoscalers", "default/web")
    assert hpa.desired_replicas == 2 and hpa.current_cpu_utilization_pct == 200


# ---------------------------------------------------------------------------
# round-5 advisor fixes: creation floor, Replace race, HPA windows, quota resync
# ---------------------------------------------------------------------------

def _clear_minute_boundary(margin=3.0):
    """Sleep past the next minute boundary if it is closer than `margin`,
    so minute-schedule tests can't race a real boundary mid-assert."""
    now = time.time()
    nxt = 60.0 * (int(now // 60) + 1)
    if nxt - now < margin:
        time.sleep(nxt - now + 0.1)


def test_cronjob_fresh_object_waits_for_post_creation_boundary(stack):
    # cronjob_controller.go getRecentUnmetScheduleTimes: earliestTime is the
    # CronJob's creationTimestamp when lastScheduleTime is unset — a freshly
    # created '* * * * *' job must NOT fire for a boundary that predates it
    api, sched, cm, drain = stack
    _clear_minute_boundary()
    api.create("cronjobs", CronJob(
        name="fresh", schedule="* * * * *",
        job_template=Job(parallelism=1, completions=1, template=_template("fresh")),
    ))
    time.sleep(1.0)  # several resync ticks
    assert len(api.list("jobs")[0]) == 0, \
        "fresh cronjob fired for a pre-creation minute boundary"


def test_cronjob_replace_does_not_churn_own_scheduled_job(stack):
    # Replace must not delete the active job that already represents the
    # current scheduled time (informer-lag replay of the same unmet time
    # would otherwise free the name and defeat the ConflictError dedupe)
    api, sched, cm, drain = stack
    _clear_minute_boundary(margin=8.0)  # test body runs ~2-3s; stay clear
    cj = CronJob(
        name="rep", schedule="* * * * *", concurrency_policy="Replace",
        job_template=Job(parallelism=1, completions=1, template=_template("rep")),
    )
    cj.last_schedule_time = time.time() - 120
    api.create("cronjobs", cj)
    _wait(lambda: len(api.list("jobs")[0]) == 1, msg="first job")
    job = api.list("jobs")[0][0]
    # replay: rewind lastScheduleTime as if the status write were unobserved
    stored = api.get("cronjobs", "default/rep")
    stored.last_schedule_time = time.time() - 120
    api.update("cronjobs", stored)
    time.sleep(0.8)  # several resync ticks recompute the same scheduled time
    jobs, _ = api.list("jobs")
    assert len(jobs) == 1 and jobs[0].uid == job.uid, \
        "Replace churned the job for its own scheduled time"


def test_hpa_forbidden_windows_gate_rescale(stack):
    # horizontal.go shouldScale: no rescale within the upscale (3m) /
    # downscale (5m) forbidden window after lastScaleTime
    api, sched, cm, drain = stack
    api.create("deployments", Deployment(
        name="win", replicas=1,
        selector=LabelSelector(match_labels={"app": "win"}),
        template=_template("win", cpu="100m"),
    ))
    drain(1, app="win")
    hpa = HorizontalPodAutoscaler(
        name="win", target_kind="Deployment", target_name="win",
        min_replicas=1, max_replicas=4, target_cpu_utilization_pct=100,
    )
    hpa.last_scale_time = time.time()  # a scale "just happened"
    api.create("horizontalpodautoscalers", hpa)
    for p in _pods(api, "win"):
        api.create("podmetrics", PodMetrics(
            name=p.name, namespace=p.namespace, cpu_milli=200, timestamp=time.time(),
        ))
    time.sleep(0.8)  # several resync ticks at 200% of target
    assert api.get("deployments", "default/win").replicas == 1, \
        "scaled inside the upscale forbidden window"
    held = api.get("horizontalpodautoscalers", "default/win")
    # status is still published while the scale is held (setStatus runs
    # regardless of shouldScale; desiredReplicas reports current)
    assert held.current_cpu_utilization_pct == 200 and held.desired_replicas == 1
    # age the last scale past both windows → the held rescale proceeds
    stored = api.get("horizontalpodautoscalers", "default/win")
    stored.last_scale_time = time.time() - 400
    api.update("horizontalpodautoscalers", stored)
    _wait(lambda: api.get("deployments", "default/win").replicas == 2,
          msg="rescale after window elapsed")


def test_resourcequota_count_usage_refreshes_on_resync(stack):
    # deleting a counted non-pod object emits no pod event; the periodic
    # resync must still replenish count/{kind} usage
    api, sched, cm, drain = stack
    from kubernetes_tpu.api.types import Service
    api.create("resourcequotas", ResourceQuota(
        name="cq", namespace="default", hard={"count/services": 5},
    ))
    api.create("services", Service(name="s1", selector={"app": "a"}))
    api.create("services", Service(name="s2", selector={"app": "b"}))
    _wait(lambda: api.get("resourcequotas", "default/cq").used.get("count/services") == 2,
          msg="count usage up")
    api.delete("services", "default/s2")
    _wait(lambda: api.get("resourcequotas", "default/cq").used.get("count/services") == 1,
          msg="count usage replenished by resync")
