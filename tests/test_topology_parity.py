"""Bit-for-bit parity: topology kernels (spread / inter-pod affinity /
selector spread) vs the scalar oracle."""

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from kubernetes_tpu.api.types import LabelSelector
from kubernetes_tpu.models.generators import ClusterGen
from kubernetes_tpu.ops import filters as F
from kubernetes_tpu.ops import topology as T
from kubernetes_tpu.oracle import Snapshot
from kubernetes_tpu.oracle import predicates as opred
from kubernetes_tpu.oracle import priorities as opri
from kubernetes_tpu.state.tensors import PodBatch, _bucket, encode_snapshot
from kubernetes_tpu.state.terms import compile_batch_terms, compile_existing_patterns


def _setup(seed, n_nodes=20, n_existing=80, n_pending=12, feature_rate=0.6, selectors=None):
    g = ClusterGen(seed)
    nodes, existing = g.cluster(n_nodes, n_existing, feature_rate)
    snap = Snapshot(nodes, existing)
    pods = [g.pod(80_000 + i, feature_rate) for i in range(n_pending)]
    bank, epsb, row_of = encode_snapshot(snap)
    vocab = bank.vocab
    batch = PodBatch(vocab, _bucket(len(pods)))
    for i, p in enumerate(pods):
        batch.set_pod(i, p)
    tb, aux = compile_batch_terms(vocab, pods, spread_selectors=selectors)
    etb = compile_existing_patterns(vocab, snap, row_of, bank.capacity)
    na = {k: jnp.asarray(v) for k, v in bank.arrays().items()}
    pa = {k: jnp.asarray(v) for k, v in batch.arrays().items()}
    ea = {k: jnp.asarray(v) for k, v in epsb.arrays().items()}
    ta = {k: jnp.asarray(v) for k, v in tb.arrays().items()}
    xa = {k: jnp.asarray(v) for k, v in etb.arrays().items()}
    auxa = {k: jnp.asarray(v) for k, v in aux.items()}
    sel_mask = F.pod_match_node_selector(na, pa)
    return snap, pods, na, pa, ea, ta, xa, auxa, sel_mask


@pytest.mark.parametrize("seed", [20, 21, 22])
def test_spread_filter_parity(seed):
    snap, pods, na, pa, ea, ta, xa, aux, sel_mask = _setup(seed)
    got = np.asarray(T.spread_filter(na, ea, ta, sel_mask))
    node_list = list(snap.node_infos.values())
    for b, p in enumerate(pods):
        meta = opred.compute_even_pods_spread_metadata(p, snap)
        for n, ni in enumerate(node_list):
            expect = opred.even_pods_spread_predicate(p, ni, meta)
            assert bool(got[b, n]) == expect, f"seed={seed} pod={p.name} node={ni.node.name}"


@pytest.mark.parametrize("seed", [23, 24, 25])
def test_interpod_filter_parity(seed):
    snap, pods, na, pa, ea, ta, xa, aux, sel_mask = _setup(seed)
    got = np.asarray(T.interpod_filter(na, ea, ta, aux, xa, pa))
    node_list = list(snap.node_infos.values())
    for b, p in enumerate(pods):
        meta = opred.compute_pod_affinity_metadata(p, snap)
        for n, ni in enumerate(node_list):
            expect = opred.inter_pod_affinity_matches(p, ni, meta)
            assert bool(got[b, n]) == expect, f"seed={seed} pod={p.name} node={ni.node.name}"


@pytest.mark.parametrize("seed", [26, 27])
def test_spread_score_parity(seed):
    snap, pods, na, pa, ea, ta, xa, aux, sel_mask = _setup(seed)
    got = np.asarray(T.spread_score(na, ea, ta, aux, sel_mask))
    node_names = list(snap.node_infos.keys())
    for b, p in enumerate(pods):
        expect = opri.even_pods_spread_priority(p, snap)
        for n, name in enumerate(node_names):
            assert int(got[b, n]) == expect[name], (
                f"seed={seed} pod={p.name} node={name} oracle={expect[name]} got={int(got[b, n])}"
            )


@pytest.mark.parametrize("seed", [28, 29])
def test_interpod_score_parity(seed):
    snap, pods, na, pa, ea, ta, xa, aux, sel_mask = _setup(seed)
    got = np.asarray(T.interpod_score(na, ea, ta, xa, pa))
    node_names = list(snap.node_infos.keys())
    for b, p in enumerate(pods):
        expect = opri.inter_pod_affinity_priority(p, snap)
        for n, name in enumerate(node_names):
            assert int(got[b, n]) == expect[name], (
                f"seed={seed} pod={p.name} node={name} oracle={expect[name]} got={int(got[b, n])}"
            )


def test_selector_spread_parity():
    g = ClusterGen(33)
    nodes, existing = g.cluster(16, 60, 0.5)
    snap = Snapshot(nodes, existing)
    pods = [g.pod(90_000 + i, 0.5) for i in range(8)]
    sels = {
        id(p): [LabelSelector(match_labels={"app": p.labels.get("app", "web")})]
        for p in pods[:6]  # last two pods: no controller selectors
    }
    bank, epsb, row_of = encode_snapshot(snap)
    vocab = bank.vocab
    batch = PodBatch(vocab, _bucket(len(pods)))
    for i, p in enumerate(pods):
        batch.set_pod(i, p)
    tb, aux = compile_batch_terms(vocab, pods, spread_selectors=sels)
    na = {k: jnp.asarray(v) for k, v in bank.arrays().items()}
    ea = {k: jnp.asarray(v) for k, v in epsb.arrays().items()}
    ta = {k: jnp.asarray(v) for k, v in tb.arrays().items()}
    auxa = {k: jnp.asarray(v) for k, v in aux.items()}
    got = np.asarray(T.selector_spread_score(na, ea, ta, auxa))
    node_names = list(snap.node_infos.keys())
    for b, p in enumerate(pods):
        expect = opri.selector_spread_priority(p, snap, sels.get(id(p)))
        for n, name in enumerate(node_names):
            assert int(got[b, n]) == expect[name], (
                f"pod={p.name} node={name} oracle={expect[name]} got={int(got[b, n])}"
            )
