"""HTTP transport for the fake apiserver: REST list/watch/create/bind on
k8s wire JSON, consumed by the UNCHANGED Informer through RemoteAPIServer
— including a genuinely out-of-process client (subprocess). Reference
anchors: reflector.go:184 ListAndWatch, cacher.go:234 chunked watch."""

import json
import subprocess
import sys
import time
import urllib.request

import pytest

pytest.importorskip("jax")

from kubernetes_tpu.apiserver import APIServerHTTP, FakeAPIServer
from kubernetes_tpu.client import Informer, RemoteAPIServer
from kubernetes_tpu.models.generators import make_node, make_pod


@pytest.fixture()
def served():
    store = FakeAPIServer()
    srv = APIServerHTTP(store).start()
    yield store, srv
    srv.stop()


def test_http_list_create_get_delete(served):
    store, srv = served
    remote = RemoteAPIServer(srv.url)
    remote.create("pods", make_pod("a", cpu_milli=100, mem=2**20))
    remote.create("nodes", make_node("n0"))
    pods, rv = remote.list("pods")
    assert [p.name for p in pods] == ["a"] and rv >= 1
    got = remote.get("pods", "default/a")
    assert got.containers[0].requests["cpu"].milli_value() == 100
    node = remote.get("nodes", "n0")  # cluster-scoped path
    assert node.name == "n0"
    remote.delete("pods", "default/a")
    assert remote.list("pods")[0] == []


def test_http_watch_streams_and_replays(served):
    store, srv = served
    remote = RemoteAPIServer(srv.url)
    store.create("pods", make_pod("old"))
    _, rv0 = remote.list("pods")
    w = remote.watch("pods", 0)  # replay from 0: sees "old"
    ev = w.next(timeout=2)
    assert ev is not None and ev.obj.name == "old" and ev.type == "ADDED"
    # live event after subscription
    store.create("pods", make_pod("live"))
    ev = w.next(timeout=2)
    assert ev is not None and ev.obj.name == "live"
    w.close()


def test_http_watch_410_gone(served):
    store, srv = served
    # overflow the history window so rv=1 compacts
    for i in range(store._history_window + 10):
        store.create("pods", make_pod(f"p{i}"))
        store.delete("pods", f"default/p{i}")
    from kubernetes_tpu.apiserver import GoneError

    remote = RemoteAPIServer(srv.url)
    with pytest.raises(GoneError):
        remote.watch("pods", 1)


def test_http_bind_subresource_and_conflict(served):
    store, srv = served
    remote = RemoteAPIServer(srv.url)
    remote.create("pods", make_pod("b"))
    remote.bind("default", "b", "n1")
    assert store.get("pods", "default/b").node_name == "n1"
    from kubernetes_tpu.apiserver import ConflictError

    with pytest.raises(ConflictError):
        remote.bind("default", "b", "n2")


def test_informer_over_http(served):
    """The UNCHANGED Informer consumes the HTTP transport: list+watch,
    handler fan-out, live updates — cross-process protocol, in-process
    client object."""
    store, srv = served
    store.create("pods", make_pod("pre"))
    remote = RemoteAPIServer(srv.url)
    seen = []
    inf = Informer(remote, "pods")
    inf.add_event_handler(on_add=lambda p: seen.append(("add", p.name)),
                          on_delete=lambda p: seen.append(("del", p.name)))
    inf.start()
    assert inf.wait_for_sync()
    assert inf.get("default/pre") is not None
    store.create("pods", make_pod("during"))
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and inf.get("default/during") is None:
        time.sleep(0.05)
    assert inf.get("default/during") is not None
    store.delete("pods", "default/pre")
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and inf.get("default/pre") is not None:
        time.sleep(0.05)
    assert inf.get("default/pre") is None
    assert ("add", "pre") in seen and ("del", "pre") in seen
    inf.stop()


def test_out_of_process_client(served):
    """A SEPARATE PYTHON PROCESS lists, watches, creates, and binds over
    plain HTTP — the integration bar: no shared memory, only the wire."""
    store, srv = served
    store.create("nodes", make_node("n0"))
    script = f"""
import json, sys, urllib.request
base = {srv.url!r}
# create a pod over the wire
pod = {{"metadata": {{"name": "xp", "namespace": "default", "uid": "u-xp"}},
        "spec": {{"containers": [{{"name": "c", "resources": {{"requests": {{"cpu": "100m"}}}}}}]}}}}
req = urllib.request.Request(base + "/api/v1/pods", method="POST",
                             data=json.dumps(pod).encode(),
                             headers={{"Content-Type": "application/json"}})
urllib.request.urlopen(req).read()
# list
d = json.load(urllib.request.urlopen(base + "/api/v1/pods"))
assert d["kind"] == "PodList" and len(d["items"]) == 1, d
# bind subresource
req = urllib.request.Request(base + "/api/v1/pods/default/xp/binding", method="POST",
                             data=json.dumps({{"target": {{"name": "n0"}}}}).encode())
urllib.request.urlopen(req).read()
# watch from 0 with a short timeout: replay must contain ADDED + MODIFIED(bind)
resp = urllib.request.urlopen(base + "/api/v1/pods?watch=1&resourceVersion=0&timeoutSeconds=2")
types = []
for line in resp:
    line = line.strip()
    if line:
        types.append(json.loads(line)["type"])
    if len(types) >= 2:
        break
assert "ADDED" in types and "MODIFIED" in types, types
print("OOP-CLIENT-OK")
"""
    out = subprocess.run(
        [sys.executable, "-c", script], capture_output=True, text=True, timeout=30,
        env={"PATH": "/usr/bin:/bin:/usr/local/bin"},
    )
    assert "OOP-CLIENT-OK" in out.stdout, (out.stdout, out.stderr)
    # the out-of-process bind is visible in the in-process store
    assert store.get("pods", "default/xp").node_name == "n0"


def test_create_conflict_maps_to_409(served):
    store, srv = served
    remote = RemoteAPIServer(srv.url)
    remote.create("pods", make_pod("dup"))
    from kubernetes_tpu.apiserver import ConflictError

    with pytest.raises(ConflictError):
        remote.create("pods", make_pod("dup"))


def test_leader_election_over_http(served):
    """An out-of-process scheduler replica can contend for the leader lease
    over the HTTP transport (leases codec, check_rv CAS semantics)."""
    store, srv = served
    from kubernetes_tpu.utils.leaderelection import LeaderElector, LeaseLock

    remote = RemoteAPIServer(srv.url)
    a = LeaderElector(LeaseLock(remote), identity="replica-a",
                      lease_duration_s=1.0, renew_deadline_s=0.5,
                      retry_period_s=0.05)
    b = LeaderElector(LeaseLock(remote), identity="replica-b",
                      lease_duration_s=1.0, renew_deadline_s=0.5,
                      retry_period_s=0.05)
    assert a.try_acquire_or_renew()
    assert not b.try_acquire_or_renew()  # a holds the lease
    assert a.try_acquire_or_renew()  # renew works
    # a stops renewing; b re-observes the (now final) record and takes
    # over once a full lease_duration passes without change (the
    # reference's observedTime discipline — expiry is measured from the
    # last OBSERVED change, not the record's own timestamps)
    deadline = time.monotonic() + 5.0
    won = False
    while time.monotonic() < deadline and not won:
        won = b.try_acquire_or_renew()
        time.sleep(0.1)
    assert won, "b never took over after a stopped renewing"


def test_kubectl_cli_over_http(served):
    """The debug CLI (kubectl subset) drives the control plane as a
    separate process over the wire: get/describe/cordon/drain."""
    store, srv = served
    store.create("nodes", make_node("n0"))
    store.create("nodes", make_node("n1"))
    p = make_pod("w1", cpu_milli=100, mem=2**20)
    p.node_name = "n0"
    store.create("pods", p)

    def kubectl(*args):
        out = subprocess.run(
            [sys.executable, "-m", "kubernetes_tpu.kubectl",
             "--server", srv.url, *args],
            capture_output=True, text=True, timeout=30,
            cwd="/root/repo",
        )
        assert out.returncode == 0, (args, out.stdout, out.stderr)
        return out.stdout

    assert "w1" in kubectl("get", "pods")
    assert "n0" in kubectl("get", "nodes")
    desc = kubectl("describe", "node", "n0")
    assert "default/w1" in desc and "Unschedulable: False" in desc
    desc = kubectl("describe", "pod", "default/w1")
    assert "Node:         n0" in desc
    kubectl("cordon", "n1")
    assert store.get("nodes", "n1").unschedulable is True
    kubectl("uncordon", "n1")
    assert store.get("nodes", "n1").unschedulable is False
    out = kubectl("drain", "n0")
    assert "evicting pod default/w1" in out
    assert store.get("nodes", "n0").unschedulable is True
    pods, _ = store.list("pods")
    assert not pods


def test_informer_over_http_survives_stream_drop(served):
    """The reflector discipline over the wire: when the server drops every
    watch stream (restart simulation), the remote informer relists and
    keeps replicating — no events lost across the gap."""
    store, srv = served
    store.create("pods", make_pod("a"))
    remote = RemoteAPIServer(srv.url)
    inf = Informer(remote, "pods")
    inf.start()
    assert inf.wait_for_sync()
    # wait until the reflector's watch ATTACHED server-side (sync happens
    # after list, before the watch connection registers)
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and not store._watchers.get("pods"):
        time.sleep(0.02)
    assert store._watchers.get("pods"), "watch never attached"
    relists0 = inf.relists()  # scheduler_informer_relists_total{kind}
    store.close_watchers("pods")  # server restart: all streams die
    store.create("pods", make_pod("b"))  # lands while no stream is up
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and inf.get("default/b") is None:
        time.sleep(0.05)
    assert inf.get("default/b") is not None, "relist never caught up"
    assert inf.relists() > relists0
    assert inf.last_relist_reason in ("stream-closed", "gone")
    inf.stop()


def test_events_over_http_and_kubectl(served):
    """Scheduler events flow recorder → apiserver "events" kind → wire →
    kubectl get events (series-aggregated: one object per pod+reason)."""
    from kubernetes_tpu.utils.events import Recorder, api_sink

    store, srv = served
    rec = Recorder(sink=api_sink(store))
    fn = rec.pod_event_fn()
    p = make_pod("w1")
    fn(p, "FailedScheduling", "0/3 nodes available")
    fn(p, "FailedScheduling", "0/3 nodes available")  # series bump
    fn(p, "Scheduled", "bound to n1")
    evs, _ = RemoteAPIServer(srv.url).list("events")
    by_reason = {e.reason: e for e in evs}
    assert by_reason["FailedScheduling"].count == 2
    assert by_reason["FailedScheduling"].type == "Warning"
    assert by_reason["Scheduled"].message == "bound to n1"
    out = subprocess.run(
        [sys.executable, "-m", "kubernetes_tpu.kubectl", "--server", srv.url,
         "get", "events"],
        capture_output=True, text=True, timeout=30, cwd="/root/repo",
    )
    assert out.returncode == 0, (out.stdout, out.stderr)
    assert "FailedScheduling" in out.stdout and "default/w1" in out.stdout


def _raw_put(srv, path, doc, token=None):
    body = json.dumps(doc).encode()
    req = urllib.request.Request(
        srv.url + path, data=body, method="PUT",
        headers={"Content-Type": "application/json",
                 **({"Authorization": f"Bearer {token}"} if token else {})},
    )
    try:
        with urllib.request.urlopen(req) as resp:
            return resp.status, json.loads(resp.read() or b"{}")
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


def test_put_path_body_mismatch_is_400(served):
    """The URL path is the write key: a body naming a different
    namespace or name must be rejected with 400 (the reference's
    BeforeUpdate name/namespace validation), never written."""
    from kubernetes_tpu.api.types import pod_to_k8s

    store, srv = served
    a = make_pod("a")
    other = make_pod("other")
    other.namespace = "prod"
    store.create("pods", a)
    store.create("pods", other)
    # body namespace != path namespace
    evil = pod_to_k8s(other)
    evil["spec"]["nodeName"] = "stolen"
    code, doc = _raw_put(srv, "/api/v1/pods/default/a", evil)
    assert code == 400, doc
    assert store.get("pods", "prod/other").node_name != "stolen"
    # body name != path name
    b = pod_to_k8s(a)
    b["metadata"]["name"] = "someone-else"
    code, _ = _raw_put(srv, "/api/v1/pods/default/a", b)
    assert code == 400
    # empty body namespace inherits the path (defaulting, not rejection)
    c = pod_to_k8s(a)
    c["metadata"].pop("namespace", None)
    c["metadata"].pop("resourceVersion", None)
    c["spec"]["nodeName"] = "n9"
    code, _ = _raw_put(srv, "/api/v1/pods/default/a", c)
    assert code == 200
    assert store.get("pods", "default/a").node_name == "n9"


def test_put_malformed_body_is_400_not_dropped(served):
    _, srv = served
    req = urllib.request.Request(
        srv.url + "/api/v1/pods/default/a", data=b"{ not json",
        method="PUT", headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req) as resp:
            code = resp.status
    except urllib.error.HTTPError as e:
        code = e.code
    assert code == 400


def test_set_based_label_selectors_round_trip(served):
    """VERDICT r5 missing #3: the wire parser speaks the FULL labels.Parse
    grammar — `in (a,b)` / `notin` / existence — and both list and watch
    filter with it server-side (the in-process matcher already supported
    the ops; only the parser was missing)."""
    store, srv = served
    remote = RemoteAPIServer(srv.url)
    for name, labels in (
        ("a", {"env": "prod", "tier": "web"}),
        ("b", {"env": "dev"}),
        ("c", {"tier": "db"}),
    ):
        p = make_pod(name)
        p.labels = labels
        remote.create("pods", p)

    def names(sel):
        pods, _ = remote.list("pods", label_selector=sel)
        return sorted(p.name for p in pods)

    assert names("env in (prod,dev)") == ["a", "b"]
    assert names("env in ( prod )") == ["a"]  # whitespace-lenient
    assert names("env notin (prod)") == ["b", "c"]  # absent key matches
    assert names("env") == ["a", "b"]  # exists
    assert names("!env") == ["c"]  # does-not-exist
    assert names("env=prod") == ["a"]
    assert names("env==prod") == ["a"]
    assert names("env!=prod") == ["b", "c"]  # absent key matches
    assert names("env in (prod,dev),tier") == ["a"]  # ANDed requirements
    # equality dicts (the in-process informer path) keep working
    pods, _ = remote.list("pods", label_selector={"env": "prod"})
    assert [p.name for p in pods] == ["a"]
    # malformed selector → 400 over the wire, never an unfiltered list
    with pytest.raises(RuntimeError):
        remote.list("pods", label_selector="env>prod")


def test_set_based_selector_watch_filters_server_side(served):
    store, srv = served
    remote = RemoteAPIServer(srv.url)
    w = remote.watch("pods", 0, label_selector="tier in (web,db)")
    for name, labels in (
        ("a", {"env": "prod", "tier": "web"}),
        ("b", {"env": "dev"}),
        ("c", {"tier": "db"}),
    ):
        p = make_pod(name)
        p.labels = labels
        store.create("pods", p)
    got = []
    for _ in range(2):
        ev = w.next(timeout=3)
        assert ev is not None
        got.append(ev.obj.name)
    assert sorted(got) == ["a", "c"]  # "b" never crossed the wire
    w.close()


def test_wire_selector_parser_edge_cases():
    from kubernetes_tpu.apiserver.store import parse_wire_label_selector

    assert parse_wire_label_selector(None) is None
    assert parse_wire_label_selector("") is None
    assert parse_wire_label_selector("  ") is None
    sel = parse_wire_label_selector("a in (x,y),b notin (z),c,!d,e=1,f!=2")
    ops = {(r.key, r.operator) for r in sel.match_expressions}
    assert ("a", "In") in ops and ("b", "NotIn") in ops
    assert ("c", "Exists") in ops and ("d", "DoesNotExist") in ops
    assert ("f", "NotIn") in ops
    assert sel.match_labels == {"e": "1"}
    # whitespace after in/notin is optional (real labels.Parse accepts it)
    sel = parse_wire_label_selector("env in(prod)")
    assert sel.match_expressions[0].values == ["prod"]
    # unsupported syntax (labels.Parse Gt/Lt, typo'd set ops) FAILS CLOSED
    # — ValueError → HTTP 400, never a silent no-filter over-match
    with pytest.raises(ValueError):
        parse_wire_label_selector("version>2,env=prod")
    with pytest.raises(ValueError):
        parse_wire_label_selector("version>2")
