"""Hollow-node runtime (kubemark): binds confirmed from the NODE side and
node death detected from heartbeat staleness — the fully autonomous loop
create → schedule → kubelet-ack → kubelet crash → staleness → taint →
evict → ReplicaSet refill → re-place → ack on survivors. Reference
anchors: pkg/kubemark/hollow_kubelet.go:64, nodelifecycle
monitorNodeHealth grace-period semantics."""

import time

import pytest

from kubernetes_tpu.api.types import Container, LabelSelector, Pod, Quantity, RESOURCE_CPU, RESOURCE_MEMORY, ReplicaSet
from kubernetes_tpu.apiserver import FakeAPIServer
from kubernetes_tpu.client import APIBinder, start_scheduler_informers
from kubernetes_tpu.controllers import ControllerManager, TAINT_NOT_READY
from kubernetes_tpu.kubemark import HollowCluster

# make_node pulls generators (no jax); the Scheduler-driven test below
# does its own importorskip so the pure control-plane tests run everywhere
from kubernetes_tpu.models.generators import make_node


def _wait(cond, timeout=15.0, msg=""):
    end = time.monotonic() + timeout
    while time.monotonic() < end:
        if cond():
            return True
        time.sleep(0.05)
    raise AssertionError(f"timeout: {msg}")


def test_hollow_kubelet_acks_bound_pods():
    api = FakeAPIServer()
    hollow = HollowCluster(api, [make_node("n0", cpu_milli=4000, mem=8 * 2**30)],
                           heartbeat_s=0.2).start()
    try:
        p = Pod(name="w", containers=[Container(name="c", requests={
            RESOURCE_CPU: Quantity.parse("100m")})])
        api.create("pods", p)
        api.bind("default", "w", "n0")
        _wait(lambda: api.get("pods", "default/w").phase == "Running",
              msg="kubelet never acked the bind")
        # heartbeats flow on the node LEASE (NodeLease), not the Node —
        # the node watch stays quiet while the lease renew time advances
        rv0 = api.get("nodes", "n0").resource_version
        b0 = api.get("leases", "node-n0").renew_time
        _wait(lambda: api.get("leases", "node-n0").renew_time > b0,
              msg="no lease renewal")
        assert api.get("nodes", "n0").resource_version == rv0
    finally:
        hollow.stop()


def test_heartbeat_staleness_marks_node_unready():
    api = FakeAPIServer()
    hollow = HollowCluster(api, [make_node("n0", cpu_milli=4000, mem=8 * 2**30)],
                           heartbeat_s=0.2).start()
    cm = ControllerManager(api, node_monitor_grace_s=1.0).start()
    try:
        # healthy: no taints appear
        time.sleep(1.2)
        assert not any(t.key == TAINT_NOT_READY
                       for t in api.get("nodes", "n0").taints)
        hollow.kill("n0")  # crash: heartbeats stop
        _wait(lambda: any(t.key == TAINT_NOT_READY
                          for t in api.get("nodes", "n0").taints),
              msg="stale heartbeat never tainted the node")
        ready = [c for c in api.get("nodes", "n0").conditions
                 if c.get("type") == "Ready"]
        assert ready and ready[0]["status"] == "Unknown"
    finally:
        cm.stop()
        hollow.stop()


def test_full_autonomous_node_failure_loop():
    """Nobody sets a condition by hand: the kubelet crash alone drives
    taint → evict → refill → re-place → ack on the survivors."""
    pytest.importorskip("jax")
    from kubernetes_tpu.scheduler.driver import Binder, Scheduler
    from kubernetes_tpu.scheduler.eventhandlers import EventHandlers

    api = FakeAPIServer()
    nodes = [make_node(f"n{i}", cpu_milli=2000, mem=8 * 2**30) for i in range(3)]
    hollow = HollowCluster(api, nodes, heartbeat_s=0.2).start()
    cm = ControllerManager(api, node_monitor_grace_s=1.0).start()
    sched = Scheduler(batch_size=16, deterministic=True, enable_preemption=False)
    sched.binder = Binder(APIBinder(api).bind)
    handlers = EventHandlers(sched.cache, sched.queue, "default-scheduler")
    informers = start_scheduler_informers(api, handlers)
    for inf in informers.values():
        inf.wait_for_sync()

    stop_pump = False

    def pump():
        while not stop_pump:
            sched.queue.flush()
            sched.schedule_batch()
            sched.wait_for_binds()
            time.sleep(0.05)

    import threading

    pump_t = threading.Thread(target=pump, daemon=True)
    pump_t.start()
    try:
        tmpl = Pod(name="t", labels={"app": "svc"}, containers=[
            Container(name="c", requests={
                RESOURCE_CPU: Quantity.parse("100m"),
                RESOURCE_MEMORY: Quantity.parse("16Mi")})])
        api.create("replicasets", ReplicaSet(
            name="svc", replicas=6,
            selector=LabelSelector(match_labels={"app": "svc"}), template=tmpl))

        def running():
            pods, _ = api.list("pods")
            return [p for p in pods if p.phase == "Running" and p.node_name]

        _wait(lambda: len(running()) == 6, timeout=30,
              msg="initial replicas never all Running")
        victim_node = running()[0].node_name
        hollow.kill(victim_node)
        # the ONLY intervention above is killing the kubelet process
        def settled():
            live = running()
            return (len(live) == 6
                    and all(p.node_name != victim_node for p in live))
        _wait(settled, timeout=30, msg="cluster never re-converged off the dead node")
        assert cm.nodelifecycle.evictions >= 1
    finally:
        stop_pump = True
        pump_t.join(timeout=3)
        cm.stop()
        hollow.stop()
        for inf in informers.values():
            inf.stop()
