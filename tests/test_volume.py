"""Volume scheduling: predicates (table-driven, predicates_test.go style),
binder seam, and driver integration."""

import pytest

pytest.importorskip("jax")

from kubernetes_tpu.api.types import Volume, pod_from_k8s, pod_to_k8s
from kubernetes_tpu.models.generators import make_node, make_pod
from kubernetes_tpu.oracle.nodeinfo import (
    LABEL_ZONE_FAILURE_DOMAIN,
    LABEL_ZONE_REGION,
    NodeInfo,
)
from kubernetes_tpu.scheduler.driver import Binder, Scheduler
from kubernetes_tpu.state.cache import SchedulerCache
from kubernetes_tpu.state.queue import PriorityQueue
from kubernetes_tpu.volume import (
    CSINode,
    EBS_FILTER,
    PersistentVolume,
    PersistentVolumeClaim,
    StorageClass,
    VolumeBinder,
    make_volume_checker,
    max_csi_volume_count,
    max_pd_volume_count,
    no_disk_conflict,
    no_volume_zone_conflict,
)


def _ni(labels=None, pods=()):
    n = make_node("n0", cpu_milli=4000, mem=8 * 2**30)
    n.labels.update(labels or {})
    ni = NodeInfo(node=n)
    ni.set_pods(list(ni.pods) + list(pods))
    return ni


def _vol_pod(name, *vols):
    p = make_pod(name, cpu_milli=100, mem=0)
    p.volumes = list(vols)
    return p


# --- NoDiskConflict (predicates.go:227-293) --------------------------------

DISK_CASES = [
    # (new volume, existing volume, expect_fit)
    (Volume(gce_pd_name="pd1"), Volume(gce_pd_name="pd1"), False),
    (Volume(gce_pd_name="pd1", gce_pd_read_only=True),
     Volume(gce_pd_name="pd1", gce_pd_read_only=True), True),  # all RO → ok
    (Volume(gce_pd_name="pd1"), Volume(gce_pd_name="pd2"), True),
    (Volume(aws_volume_id="v1"), Volume(aws_volume_id="v1"), False),
    (Volume(aws_volume_id="v1", aws_read_only=True),
     Volume(aws_volume_id="v1", aws_read_only=True), False),  # EBS: RO irrelevant
    (Volume(iscsi_iqn="iqn1"), Volume(iscsi_iqn="iqn1"), False),
    (Volume(iscsi_iqn="iqn1", iscsi_read_only=True),
     Volume(iscsi_iqn="iqn1", iscsi_read_only=True), True),
    (Volume(rbd_pool="p", rbd_image="i", rbd_monitors=("m1",)),
     Volume(rbd_pool="p", rbd_image="i", rbd_monitors=("m1", "m2")), False),
    (Volume(rbd_pool="p", rbd_image="i", rbd_monitors=("m1",)),
     Volume(rbd_pool="other", rbd_image="i", rbd_monitors=("m1",)), True),
]


@pytest.mark.parametrize("new,existing,expect", DISK_CASES)
def test_no_disk_conflict(new, existing, expect):
    ni = _ni(pods=[_vol_pod("existing", existing)])
    assert no_disk_conflict(_vol_pod("new", new), ni) is expect


# --- NoVolumeZoneConflict (predicates.go:698-800) ---------------------------

def _zone_env():
    pvcs = {
        ("default", "claim-a"): PersistentVolumeClaim(
            name="claim-a", volume_name="pv-a"),
        ("default", "claim-unbound"): PersistentVolumeClaim(
            name="claim-unbound", storage_class_name="wait-class"),
    }
    pvs = {
        "pv-a": PersistentVolume(name="pv-a",
                                 labels={LABEL_ZONE_FAILURE_DOMAIN: "us-a__us-b"}),
    }
    scs = {"wait-class": StorageClass(name="wait-class",
                                      volume_binding_mode="WaitForFirstConsumer")}
    return (lambda ns, n: pvcs.get((ns, n))), (lambda n: pvs.get(n)), (lambda n: scs.get(n))


def test_volume_zone_match():
    pvc_l, pv_l, sc_l = _zone_env()
    pod = _vol_pod("p", Volume(pvc_claim_name="claim-a"))
    assert no_volume_zone_conflict(pod, _ni({LABEL_ZONE_FAILURE_DOMAIN: "us-a"}), pvc_l, pv_l, sc_l)
    assert no_volume_zone_conflict(pod, _ni({LABEL_ZONE_FAILURE_DOMAIN: "us-b"}), pvc_l, pv_l, sc_l)
    assert not no_volume_zone_conflict(pod, _ni({LABEL_ZONE_FAILURE_DOMAIN: "us-c"}), pvc_l, pv_l, sc_l)


def test_volume_zone_no_node_labels_passes():
    pvc_l, pv_l, sc_l = _zone_env()
    pod = _vol_pod("p", Volume(pvc_claim_name="claim-a"))
    assert no_volume_zone_conflict(pod, _ni({}), pvc_l, pv_l, sc_l)


def test_volume_zone_unbound_wait_class_skipped():
    pvc_l, pv_l, sc_l = _zone_env()
    pod = _vol_pod("p", Volume(pvc_claim_name="claim-unbound"))
    assert no_volume_zone_conflict(pod, _ni({LABEL_ZONE_FAILURE_DOMAIN: "us-z"}), pvc_l, pv_l, sc_l)


def test_volume_zone_missing_pvc_fails():
    pvc_l, pv_l, sc_l = _zone_env()
    pod = _vol_pod("p", Volume(pvc_claim_name="nope"))
    assert not no_volume_zone_conflict(pod, _ni({LABEL_ZONE_FAILURE_DOMAIN: "us-a"}), pvc_l, pv_l, sc_l)


def test_volume_zone_region_label():
    pvcs = {("default", "c"): PersistentVolumeClaim(name="c", volume_name="pv-r")}
    pvs = {"pv-r": PersistentVolume(name="pv-r", labels={LABEL_ZONE_REGION: "eu"})}
    pod = _vol_pod("p", Volume(pvc_claim_name="c"))
    assert no_volume_zone_conflict(
        pod, _ni({LABEL_ZONE_REGION: "eu"}), lambda ns, n: pvcs.get((ns, n)), lambda n: pvs.get(n))
    assert not no_volume_zone_conflict(
        pod, _ni({LABEL_ZONE_REGION: "us"}), lambda ns, n: pvcs.get((ns, n)), lambda n: pvs.get(n))


# --- Max volume counts ------------------------------------------------------

def test_max_ebs_volume_count(monkeypatch):
    monkeypatch.setenv("KUBE_MAX_PD_VOLS", "2")
    pvc_l, pv_l = (lambda ns, n: None), (lambda n: None)
    existing = [
        _vol_pod("e1", Volume(aws_volume_id="v1")),
        _vol_pod("e2", Volume(aws_volume_id="v2")),
    ]
    ni = _ni(pods=existing)
    # third distinct volume exceeds the limit of 2
    assert not max_pd_volume_count(EBS_FILTER, _vol_pod("p", Volume(aws_volume_id="v3")), ni, pvc_l, pv_l)
    # re-using an attached volume is free
    assert max_pd_volume_count(EBS_FILTER, _vol_pod("p", Volume(aws_volume_id="v1")), ni, pvc_l, pv_l)
    # no EBS volumes at all → pass
    assert max_pd_volume_count(EBS_FILTER, _vol_pod("p"), ni, pvc_l, pv_l)


def test_max_ebs_count_via_pvc(monkeypatch):
    monkeypatch.setenv("KUBE_MAX_PD_VOLS", "1")
    pvcs = {("default", "c1"): PersistentVolumeClaim(name="c1", volume_name="pv1")}
    pvs = {"pv1": PersistentVolume(name="pv1", aws_volume_id="vol-9")}
    pvc_l, pv_l = (lambda ns, n: pvcs.get((ns, n))), (lambda n: pvs.get(n))
    existing = [_vol_pod("e1", Volume(aws_volume_id="vol-8"))]
    ni = _ni(pods=existing)
    assert not max_pd_volume_count(EBS_FILTER, _vol_pod("p", Volume(pvc_claim_name="c1")), ni, pvc_l, pv_l)


def test_max_csi_volume_count():
    pvcs = {
        ("default", "c1"): PersistentVolumeClaim(name="c1", volume_name="pv1"),
        ("default", "c2"): PersistentVolumeClaim(name="c2", volume_name="pv2"),
    }
    pvs = {
        "pv1": PersistentVolume(name="pv1", csi_driver="ebs.csi", csi_volume_handle="h1"),
        "pv2": PersistentVolume(name="pv2", csi_driver="ebs.csi", csi_volume_handle="h2"),
    }
    pvc_l, pv_l = (lambda ns, n: pvcs.get((ns, n))), (lambda n: pvs.get(n))
    csinode = CSINode(name="n0", driver_limits={"ebs.csi": 1})
    csi_l = lambda name: csinode
    existing = [_vol_pod("e1", Volume(pvc_claim_name="c1"))]
    ni = _ni(pods=existing)
    assert not max_csi_volume_count(_vol_pod("p", Volume(pvc_claim_name="c2")), ni, pvc_l, pv_l, csi_l)
    # no CSINode limits → pass
    assert max_csi_volume_count(_vol_pod("p", Volume(pvc_claim_name="c2")), ni, pvc_l, pv_l, lambda n: None)


# --- VolumeBinder -----------------------------------------------------------

def test_binder_bound_claim_zone_conflict():
    pvcs = {("default", "c"): PersistentVolumeClaim(name="c", volume_name="pv")}
    pvs = {"pv": PersistentVolume(name="pv", labels={LABEL_ZONE_FAILURE_DOMAIN: "us-a"})}
    b = VolumeBinder(lambda ns, n: pvcs.get((ns, n)), lambda n: pvs.get(n))
    pod = _vol_pod("p", Volume(pvc_claim_name="c"))
    ok, _ = b.find_pod_volumes(pod, _ni({LABEL_ZONE_FAILURE_DOMAIN: "us-a"}))
    assert ok
    ok, reasons = b.find_pod_volumes(pod, _ni({LABEL_ZONE_FAILURE_DOMAIN: "us-b"}))
    assert not ok and "node(s) had volume node affinity conflict" in reasons


def test_binder_assume_prevents_double_claim_and_bind_externalizes():
    pvcs = {
        ("default", "c1"): PersistentVolumeClaim(name="c1", storage_class_name="std"),
        ("default", "c2"): PersistentVolumeClaim(name="c2", storage_class_name="std"),
    }
    the_pv = PersistentVolume(name="pv1", storage_class_name="std")
    bound = []
    b = VolumeBinder(
        lambda ns, n: pvcs.get((ns, n)), lambda n: None,
        all_pvs=lambda: [the_pv],
        bind_fn=lambda ns, claim, pv: bound.append((ns, claim, pv)),
    )
    p1 = _vol_pod("p1", Volume(pvc_claim_name="c1"))
    p2 = _vol_pod("p2", Volume(pvc_claim_name="c2"))
    ok, _ = b.find_pod_volumes(p1, _ni())
    assert ok
    assert b.assume_pod_volumes(p1, "n0")  # matched pv1 tentatively
    assert b.assumed_pv_count() == 1
    # p2 can no longer match the same PV, and there's no storage class → fail
    ok, reasons = b.find_pod_volumes(p2, _ni())
    assert not ok
    b.bind_pod_volumes(p1)
    assert bound == [("default", "c1", "pv1")]


# --- driver integration -----------------------------------------------------

def test_driver_routes_volume_pods_through_checker():
    """A pod with a zone-bound PV only lands on the matching zone's node."""
    cache = SchedulerCache()
    for i, zone in enumerate(["us-a", "us-b", "us-c"]):
        n = make_node(f"n{i}", cpu_milli=4000, mem=8 * 2**30)
        n.labels[LABEL_ZONE_FAILURE_DOMAIN] = zone
        cache.add_node(n)
    pvcs = {("default", "c"): PersistentVolumeClaim(name="c", volume_name="pv")}
    pvs = {"pv": PersistentVolume(name="pv", labels={LABEL_ZONE_FAILURE_DOMAIN: "us-b"})}
    checker = make_volume_checker(lambda ns, n: pvcs.get((ns, n)), lambda n: pvs.get(n))
    binds = []
    sched = Scheduler(
        cache=cache, queue=PriorityQueue(),
        binder=Binder(lambda p, n: binds.append((p.name, n))),
        volume_checker=checker, deterministic=True, enable_preemption=False,
    )
    pod = _vol_pod("vp", Volume(pvc_claim_name="c"))
    sched.queue.add(pod)
    plain = make_pod("plain", cpu_milli=100, mem=0)
    sched.queue.add(plain)
    res = sched.schedule_batch()
    sched.wait_for_binds()
    assert res.scheduled == 2
    assert res.assignments["default/vp"] == "n1"  # the us-b node


def test_binder_assume_respects_node_zone():
    """assume must not claim a PV unusable on the CHOSEN node (review r1):
    first class-matching PV is in us-a, pod lands in us-b → pv-b claimed."""
    pvcs = {("default", "c"): PersistentVolumeClaim(name="c", storage_class_name="fast")}
    pv_a = PersistentVolume(name="pv-a", storage_class_name="fast",
                            labels={LABEL_ZONE_FAILURE_DOMAIN: "us-a"})
    pv_b = PersistentVolume(name="pv-b", storage_class_name="fast",
                            labels={LABEL_ZONE_FAILURE_DOMAIN: "us-b"})
    b = VolumeBinder(lambda ns, n: pvcs.get((ns, n)), lambda n: None,
                     all_pvs=lambda: [pv_a, pv_b])
    pod = _vol_pod("p", Volume(pvc_claim_name="c"))
    node_b = _ni({LABEL_ZONE_FAILURE_DOMAIN: "us-b"})
    assert b.assume_pod_volumes(pod, "n0", node_b)
    assert "pv-b" in b._assumed_pvs and "pv-a" not in b._assumed_pvs


def test_binder_one_pv_cannot_satisfy_two_claims():
    pvcs = {
        ("default", "c1"): PersistentVolumeClaim(name="c1", storage_class_name="fast"),
        ("default", "c2"): PersistentVolumeClaim(name="c2", storage_class_name="fast"),
    }
    only_pv = PersistentVolume(name="pv1", storage_class_name="fast")
    b = VolumeBinder(lambda ns, n: pvcs.get((ns, n)), lambda n: None,
                     all_pvs=lambda: [only_pv])
    pod = _vol_pod("p", Volume(pvc_claim_name="c1"), Volume(pvc_claim_name="c2"))
    ok, reasons = b.find_pod_volumes(pod, _ni())
    assert not ok  # second claim has nothing to match (review r3)
    # assume likewise refuses and rolls back the partial match
    assert not b.assume_pod_volumes(pod, "n0", _ni())
    assert b.assumed_pv_count() == 0


def test_binder_no_provisioner_class_not_provisionable():
    pvcs = {("default", "c"): PersistentVolumeClaim(
        name="c", storage_class_name="local-storage")}
    scs = {"local-storage": StorageClass(
        name="local-storage", provisioner="kubernetes.io/no-provisioner",
        volume_binding_mode="WaitForFirstConsumer")}
    b = VolumeBinder(lambda ns, n: pvcs.get((ns, n)), lambda n: None,
                     sc_lister=lambda n: scs.get(n), all_pvs=lambda: [])
    pod = _vol_pod("p", Volume(pvc_claim_name="c"))
    ok, reasons = b.find_pod_volumes(pod, _ni())
    assert not ok  # no PVs + no real provisioner → Filter fails (review r4)


def test_preemption_respects_volume_zone():
    """Preemption must not evict victims on nodes where the preemptor's
    volume can never attach (review r5)."""
    cache = SchedulerCache()
    na = make_node("na", cpu_milli=1000, mem=2**30)
    na.labels[LABEL_ZONE_FAILURE_DOMAIN] = "us-a"
    nb = make_node("nb", cpu_milli=1000, mem=2**30)
    nb.labels[LABEL_ZONE_FAILURE_DOMAIN] = "us-b"
    cache.add_node(na)
    cache.add_node(nb)
    for node in ("na", "nb"):
        filler = make_pod(f"fill-{node}", cpu_milli=900, mem=0)
        filler.node_name = node
        filler.priority = 0
        cache.add_pod(filler)
    pvcs = {("default", "c"): PersistentVolumeClaim(name="c", volume_name="pv")}
    pvs = {"pv": PersistentVolume(name="pv", labels={LABEL_ZONE_FAILURE_DOMAIN: "us-a"})}
    checker = make_volume_checker(lambda ns, n: pvcs.get((ns, n)), lambda n: pvs.get(n))
    deleted = []
    sched = Scheduler(
        cache=cache, queue=PriorityQueue(), volume_checker=checker,
        deterministic=True, delete_fn=lambda p: deleted.append(p.node_name),
    )
    preemptor = _vol_pod("pre", Volume(pvc_claim_name="c"))
    preemptor.priority = 100
    preemptor.containers[0].requests = dict(
        make_pod("tmp", cpu_milli=500, mem=0).containers[0].requests)
    sched.queue.add(preemptor)
    res = sched.schedule_batch()
    # the only viable preemption target is the us-a node
    assert res.preempted == 1
    assert deleted == ["na"]


def test_driver_volume_binder_lifecycle():
    cache = SchedulerCache()
    cache.add_node(make_node("n0", cpu_milli=4000, mem=8 * 2**30))
    pvcs = {("default", "c"): PersistentVolumeClaim(name="c", storage_class_name="std")}
    the_pv = PersistentVolume(name="pv1", storage_class_name="std")
    bound = []
    vb = VolumeBinder(
        lambda ns, n: pvcs.get((ns, n)), lambda n: None,
        all_pvs=lambda: [the_pv],
        bind_fn=lambda ns, claim, pv: bound.append((ns, claim, pv)),
    )
    checker = make_volume_checker(
        lambda ns, n: pvcs.get((ns, n)), lambda n: None, binder=vb)
    sched = Scheduler(
        cache=cache, queue=PriorityQueue(),
        volume_checker=checker, volume_binder=vb,
        deterministic=True, enable_preemption=False,
    )
    sched.queue.add(_vol_pod("vp", Volume(pvc_claim_name="c")))
    res = sched.schedule_batch()
    sched.wait_for_binds()
    assert res.scheduled == 1
    assert bound == [("default", "c", "pv1")]
    assert vb.assumed_pv_count() == 1  # pv stays claimed until informer confirms


def test_volume_json_round_trip():
    pod = pod_from_k8s({
        "metadata": {"name": "p"},
        "spec": {
            "containers": [{"name": "c"}],
            "volumes": [
                {"name": "data", "persistentVolumeClaim": {"claimName": "c1"}},
                {"name": "pd", "gcePersistentDisk": {"pdName": "disk-1", "readOnly": True}},
                {"name": "scratch", "emptyDir": {}},
            ],
        },
    })
    assert pod.volumes[0].pvc_claim_name == "c1"
    assert pod.volumes[1].gce_pd_name == "disk-1" and pod.volumes[1].gce_pd_read_only
    assert pod.volumes[2].name == "scratch" and not pod.volumes[2].pvc_claim_name
    back = pod_to_k8s(pod)
    vols = back["spec"]["volumes"]
    assert vols[0]["persistentVolumeClaim"]["claimName"] == "c1"
    assert vols[1]["gcePersistentDisk"] == {"pdName": "disk-1", "readOnly": True}
