"""End-to-end scheduler driver tests: queue -> device solve -> assume/bind."""

import time

import pytest

pytest.importorskip("jax")

from kubernetes_tpu.api.types import Affinity, LabelSelector, PodAffinityTerm, PodAntiAffinity
from kubernetes_tpu.models.generators import ClusterGen, make_node, make_pod
from kubernetes_tpu.oracle import Snapshot, find_nodes_that_fit
from kubernetes_tpu.scheduler.driver import Binder, Scheduler
from kubernetes_tpu.scheduler.eventhandlers import EventHandlers
from kubernetes_tpu.state.cache import SchedulerCache
from kubernetes_tpu.state.queue import PriorityQueue


def _mk_scheduler(nodes, existing=(), **kw):
    cache = SchedulerCache()
    for n in nodes:
        cache.add_node(n)
    for p in existing:
        cache.add_pod(p)
    binds = []
    binder = Binder(lambda pod, node: binds.append((pod.key(), node)))
    sched = Scheduler(cache=cache, queue=PriorityQueue(), binder=binder,
                      deterministic=True, **kw)
    return sched, binds


def test_schedules_simple_pods():
    nodes = [make_node(f"n{i}", cpu_milli=2000, mem=4 * 2**30) for i in range(4)]
    sched, binds = _mk_scheduler(nodes)
    for i in range(8):
        sched.queue.add(make_pod(f"p{i}", cpu_milli=500, mem=2**28))
    res = sched.schedule_batch()
    assert res.scheduled == 8
    sched.wait_for_binds()
    assert len(binds) == 8
    # capacity respected: 2000m / 500m = 4 pods max per node
    per_node = {}
    for _, n in binds:
        per_node[n] = per_node.get(n, 0) + 1
    assert all(v <= 4 for v in per_node.values())


def test_respects_capacity_and_requeues():
    nodes = [make_node("n0", cpu_milli=1000, mem=2**30)]
    sched, binds = _mk_scheduler(nodes)
    for i in range(4):
        sched.queue.add(make_pod(f"p{i}", cpu_milli=400, mem=0))
    res = sched.schedule_batch()
    assert res.scheduled == 2
    assert res.unschedulable == 2
    assert sched.queue.pending_count() == 2


def test_priority_order_wins_scarce_capacity():
    nodes = [make_node("n0", cpu_milli=1000, mem=2**30)]
    sched, binds = _mk_scheduler(nodes)
    low = make_pod("low", cpu_milli=800, mem=0)
    low.priority = 0
    high = make_pod("high", cpu_milli=800, mem=0)
    high.priority = 100
    sched.queue.add(low)
    sched.queue.add(high)
    res = sched.schedule_batch()
    assert res.assignments.get("default/high") == "n0"
    assert "default/low" not in res.assignments


def test_assumed_pods_visible_to_next_batch():
    nodes = [make_node("n0", cpu_milli=1000, mem=2**30)]
    sched, binds = _mk_scheduler(nodes)
    sched.queue.add(make_pod("a", cpu_milli=600, mem=0))
    r1 = sched.schedule_batch()
    assert r1.scheduled == 1
    sched.queue.add(make_pod("b", cpu_milli=600, mem=0))
    r2 = sched.schedule_batch()
    assert r2.scheduled == 0 and r2.unschedulable == 1


def test_anti_affinity_within_batch_oracle_recheck():
    # two pods with mutual anti-affinity must land on different hosts even
    # inside one batch (the oracle re-check path)
    nodes = [make_node(f"n{i}", labels={"kubernetes.io/hostname": f"n{i}"}) for i in range(2)]
    sched, binds = _mk_scheduler(nodes)
    term = PodAffinityTerm(
        label_selector=LabelSelector(match_labels={"app": "x"}),
        topology_key="kubernetes.io/hostname",
    )
    for i in range(3):
        p = make_pod(f"p{i}", labels={"app": "x"})
        p.affinity = Affinity(pod_anti_affinity=PodAntiAffinity(required=[term]))
        sched.queue.add(p)
    res = sched.schedule_batch()
    sched.wait_for_binds()
    assert res.scheduled == 2, res
    assert res.unschedulable == 1
    assert len(set(res.assignments.values())) == 2  # distinct nodes


def test_preemption_nominates_and_evicts():
    nodes = [make_node("n0", cpu_milli=1000, mem=2**30)]
    victim = make_pod("victim", cpu_milli=900, mem=0, node_name="n0")
    victim.priority = 0
    sched, binds = _mk_scheduler(nodes, existing=[victim])
    urgent = make_pod("urgent", cpu_milli=900, mem=0)
    urgent.priority = 1000
    sched.queue.add(urgent)
    res = sched.schedule_batch()
    assert res.preempted == 1
    assert urgent.nominated_node_name == "n0"
    # victim evicted from cache; after backoff the urgent pod schedules
    time.sleep(1.1)
    res2 = sched.schedule_batch()
    assert res2.assignments.get("default/urgent") == "n0"


def test_event_handlers_feed_queue_and_cache():
    cache = SchedulerCache()
    queue = PriorityQueue()
    h = EventHandlers(cache, queue)
    h.on_node_add(make_node("n0"))
    pending = make_pod("p0")
    h.on_pod_add(pending)
    assert queue.pending_count() == 1
    bound = make_pod("p1", node_name="n0")
    h.on_pod_add(bound)
    assert cache.pod_count() == 1
    h.on_pod_delete(bound)
    assert cache.pod_count() == 0


def test_bind_failure_forgets_and_requeues():
    nodes = [make_node("n0")]
    cache = SchedulerCache()
    cache.add_node(nodes[0])

    def failing_bind(pod, node):
        raise RuntimeError("apiserver down")

    sched = Scheduler(cache=cache, queue=PriorityQueue(), binder=Binder(failing_bind),
                      deterministic=True)
    sched.queue.add(make_pod("p0"))
    res = sched.schedule_batch()
    assert res.scheduled == 1  # optimistically assumed
    sched.wait_for_binds()
    # bind failed -> forgotten from cache, back in queue
    assert cache.pod_count() == 0
    assert sched.queue.pending_count() == 1


def _assert_sequential_equivalent(seed, n_nodes=16, n_existing=40, n_pending=12,
                                  feature_rate=0.4):
    """Sequential-equivalence property: replay the batch scheduler's commit
    order (priority desc, enqueue seq asc — driver.schedule_batch) through
    the pure oracle and assert that every assignment was oracle-feasible at
    its commit time, and every unschedulable pod had NO feasible node at its
    evaluation time. This is exactly what the reference's one-pod-at-a-time
    loop (scheduleOne, scheduler.go:579) would have decided."""
    import dataclasses

    g = ClusterGen(seed)
    nodes, existing = g.cluster(n_nodes, n_existing, feature_rate=feature_rate)
    sched, binds = _mk_scheduler(nodes, existing=existing, enable_preemption=False)
    pods = [g.pod(1000 + i, feature_rate=feature_rate) for i in range(n_pending)]
    for p in pods:
        sched.queue.add(p)
    res = sched.schedule_batch()
    assert res.scheduled + res.unschedulable == n_pending

    # replay in the driver's deterministic commit order
    snap = Snapshot(list(nodes), list(existing))
    ordered = sorted(range(len(pods)), key=lambda i: (-pods[i].get_priority(), i))
    for i in ordered:
        p = pods[i]
        feasible = find_nodes_that_fit(p, snap)
        node = res.assignments.get(p.key())
        if node is not None:
            assert node in feasible, (
                f"seed={seed}: {p.key()} committed to {node} which the oracle "
                f"rejects at commit time (feasible={feasible})"
            )
            ni = snap.get(node)
            ni.add_pod(dataclasses.replace(p, node_name=node))
        else:
            assert not feasible, (
                f"seed={seed}: {p.key()} declared unschedulable but oracle "
                f"finds feasible nodes {feasible} at evaluation time"
            )


@pytest.mark.parametrize("seed", list(range(20)))
def test_sequential_equivalence_random_clusters(seed):
    _assert_sequential_equivalent(seed)


@pytest.mark.parametrize("seed", [100, 101, 102, 103, 104])
def test_sequential_equivalence_affinity_heavy(seed):
    # high feature rate → most pods carry affinity/anti-affinity/spread
    _assert_sequential_equivalent(seed, feature_rate=0.9)


def test_speculative_pipeline_matches_non_speculative():
    """Speculation on vs off must produce identical assignments when the
    workload follows device choices (plain resource pods), and the
    speculative path must actually engage (spec_hits > 0)."""

    def build(speculate):
        cache = SchedulerCache()
        for i in range(24):
            cache.add_node(make_node(f"n{i}", cpu_milli=4000, mem=8 * 2**30))
        queue = PriorityQueue()
        binds = {}
        sched = Scheduler(
            cache=cache, queue=queue,
            binder=Binder(lambda p, n: binds.__setitem__(p.key(), n)),
            batch_size=32, deterministic=True, enable_preemption=False,
            speculate=speculate,
        )
        for i in range(160):
            queue.add(make_pod(f"p{i}", cpu_milli=300, mem=2**20))
        r = sched.run_until_empty()
        sched.wait_for_binds()
        return r, binds, sched

    r_on, binds_on, s_on = build(True)
    r_off, binds_off, _ = build(False)
    assert r_on.scheduled == r_off.scheduled == 160
    assert binds_on == binds_off
    assert s_on.stats.get("spec_hits", 0) >= 3, s_on.stats


def test_speculation_invalidated_by_anti_affinity_commits():
    """A batch that commits required anti-affinity pods must not hand its
    (stale-pattern) speculated solve to the next batch — and the final
    placements must still respect anti-affinity across batches."""
    from kubernetes_tpu.api.types import (
        Affinity,
        LabelSelector,
        PodAffinityTerm,
        PodAntiAffinity,
    )

    HOST = "kubernetes.io/hostname"
    cache = SchedulerCache()
    for i in range(8):
        cache.add_node(make_node(f"n{i}", labels={HOST: f"n{i}"}))
    queue = PriorityQueue()
    binds = {}
    sched = Scheduler(
        cache=cache, queue=queue,
        binder=Binder(lambda p, n: binds.__setitem__(p.key(), n)),
        batch_size=4, deterministic=True, enable_preemption=False,
    )
    term = PodAffinityTerm(
        label_selector=LabelSelector(match_labels={"app": "solo"}),
        topology_key=HOST,
    )
    for i in range(6):
        p = make_pod(f"solo-{i}", labels={"app": "solo"})
        p.affinity = Affinity(pod_anti_affinity=PodAntiAffinity(required=[term]))
        queue.add(p)
    r = sched.run_until_empty()
    sched.wait_for_binds()
    assert r.scheduled == 6
    assert len(set(binds.values())) == 6, binds  # one host each, across batches


def test_speculation_invalidated_by_external_event():
    """An informer event landing between batches (a foreign pod appears on
    a node) must invalidate the speculated solve — the next batch re-solves
    against the true state and does not overcommit the shrunken node."""
    cache = SchedulerCache()
    cache.add_node(make_node("n0", cpu_milli=1000, mem=8 * 2**30))
    cache.add_node(make_node("n1", cpu_milli=1000, mem=8 * 2**30))
    queue = PriorityQueue()
    binds = {}
    sched = Scheduler(
        cache=cache, queue=queue,
        binder=Binder(lambda p, n: binds.__setitem__(p.key(), n)),
        batch_size=2, deterministic=True, enable_preemption=False,
    )
    for i in range(6):
        queue.add(make_pod(f"p{i}", cpu_milli=400, mem=2**20))
    r1 = sched.schedule_batch()  # batch 1 commits, batch 2 speculated
    assert r1.scheduled == 2
    assert sched._spec_pending is not None and sched._spec_pending["disp"] is not None
    sched.wait_for_binds()
    batch1_pods = set(binds)
    # a foreign pod (another scheduler's bind) eats 400m of n0
    foreign = make_pod("foreign", cpu_milli=400, mem=2**20, node_name="n0")
    cache.add_pod(foreign)
    r2 = sched.schedule_batch()
    assert sched.stats.get("spec_misses", 0) >= 1, sched.stats
    r3 = sched.run_until_empty()
    sched.wait_for_binds()
    # batch 1 legally filled n0 to 800m before the event; the foreign pod
    # then overcommitted it externally (1200/1000 — not our doing, exactly
    # what a competing scheduler can cause in the reference too). What OUR
    # scheduler must guarantee: nothing committed AFTER the event lands on
    # the overcommitted node, and n1 never exceeds its capacity.
    after = {k: n for k, n in binds.items() if k not in batch1_pods}
    assert after and all(n == "n1" for n in after.values()), (after, binds)
    n1_used = sum(400 for n in binds.values() if n == "n1")
    assert n1_used <= 1000, binds
    assert r1.scheduled + r2.scheduled + r3.scheduled == 4, (r1, r2, r3)


def test_in_batch_affinity_anchor_rescues_minus_one():
    """Regression (round-2 VERDICT weak #1): a required-pod-affinity pod whose
    ANCHOR lands in the same batch. At batch start no pod matches the term
    anywhere, so the device mask is all-false (-1); the anchor's in-batch
    commit satisfies the term (predicates.go:1269 sequential semantics) and
    the -1 rescue path must oracle-place the dependent — formerly this path
    raised NameError and aborted the batch."""
    from kubernetes_tpu.api.types import PodAffinity

    HOST = "kubernetes.io/hostname"
    nodes = [make_node(f"n{i}", labels={HOST: f"n{i}"}) for i in range(4)]
    sched, binds = _mk_scheduler(nodes)
    anchor = make_pod("anchor", labels={"app": "anchor"})
    anchor.priority = 10  # commits before the dependent in pop order
    dep = make_pod("dep")
    dep.priority = 0
    dep.affinity = Affinity(pod_affinity=PodAffinity(required=[
        PodAffinityTerm(
            label_selector=LabelSelector(match_labels={"app": "anchor"}),
            topology_key=HOST,
        )
    ]))
    sched.queue.add(anchor)
    sched.queue.add(dep)
    res = sched.schedule_batch()
    sched.wait_for_binds()
    assert res.errors == 0, res
    assert res.scheduled == 2, res
    # hostname topology: the dependent must share the anchor's node
    assert res.assignments["default/dep"] == res.assignments["default/anchor"]


def test_commit_loop_exception_fails_pod_not_batch():
    """A per-pod exception inside the commit loop (here: a Filter plugin
    that raises) must fail THAT pod as an error and keep committing the
    rest of the batch — never abort schedule_batch mid-commit (round-2
    VERDICT weak #1, second half)."""
    from kubernetes_tpu.framework.interface import Framework, Plugin, Status

    class Exploding(Plugin):
        name = "Exploding"

        def filter(self, state, pod, node_info):
            if pod.name == "boom":
                raise RuntimeError("plugin bug")
            return Status.success()

    cache = SchedulerCache()
    for i in range(4):
        cache.add_node(make_node(f"n{i}", cpu_milli=4000, mem=8 * 2**30))
    binds = []
    sched = Scheduler(
        cache=cache, queue=PriorityQueue(),
        binder=Binder(lambda p, n: binds.append((p.key(), n))),
        framework=Framework([Exploding()]), deterministic=True,
    )
    for name, prio in [("a", 30), ("boom", 20), ("b", 10)]:
        p = make_pod(name, cpu_milli=100, mem=2**20)
        p.priority = prio
        sched.queue.add(p)
    res = sched.schedule_batch()
    sched.wait_for_binds()
    assert res.errors == 1, res
    assert res.scheduled == 2, res
    assert {k for k, _ in binds} == {"default/a", "default/b"}
    # the failed pod is requeued (error path), not lost
    assert sched.queue.pending_count() == 1


def test_close_requeues_speculative_pending():
    """Pods popped by a speculative dispatch but never consumed must return
    to the queue on close() — not silently drop (round-2 ADVICE low)."""
    cache = SchedulerCache()
    for i in range(4):
        cache.add_node(make_node(f"n{i}", cpu_milli=4000, mem=8 * 2**30))
    sched = Scheduler(
        cache=cache, queue=PriorityQueue(),
        binder=Binder(lambda p, n: None),
        batch_size=4, deterministic=True, enable_preemption=False,
    )
    for i in range(8):
        sched.queue.add(make_pod(f"p{i}", cpu_milli=100, mem=2**20))
    r1 = sched.schedule_batch()  # commits 4, speculatively pops the other 4
    assert r1.scheduled == 4
    assert sched._spec_pending is not None
    assert sched.queue.pending_count() == 0
    sched.close()
    assert sched._spec_pending is None
    assert sched.queue.pending_count() == 4


def test_spec_chain_poisoned_on_miss():
    """Depth-N speculation: a foreign event that forces one entry to
    re-solve fresh must poison the REST of the chain too — later entries
    were solved against the missed entry's never-materialized placements
    (round-3 review finding). Invariant checked: no node over-commit."""
    cache = SchedulerCache()
    for i in range(3):
        cache.add_node(make_node(f"n{i}", cpu_milli=1000, mem=8 * 2**30))
    queue = PriorityQueue()
    binds = {}
    sched = Scheduler(
        cache=cache, queue=queue,
        binder=Binder(lambda p, n: binds.__setitem__(p.key(), n)),
        batch_size=2, deterministic=True, enable_preemption=False,
        spec_depth=3,
    )
    for i in range(10):
        queue.add(make_pod(f"p{i}", cpu_milli=300, mem=2**20))
    r1 = sched.schedule_batch()  # fills the chain with up to 3 entries
    assert r1.scheduled == 2
    assert len(sched._spec_chain) == 3
    # a foreign pod lands on n0 (another scheduler's bind): one mutation
    foreign = make_pod("foreign", cpu_milli=900, mem=2**20, node_name="n0")
    cache.add_pod(foreign)
    total = r1.scheduled
    while True:
        r = sched.schedule_batch()
        if r.scheduled == 0 and r.unschedulable == 0 and r.errors == 0:
            break
        total += r.scheduled
    sched.wait_for_binds()
    assert sched.stats.get("spec_misses", 0) >= 1, sched.stats
    # capacity invariant on OUR commits: nothing after the event may land
    # on the overcommitted n0; n1/n2 stay within 1000m
    used = {}
    for k, n in binds.items():
        used[n] = used.get(n, 0) + 300
    assert used.get("n1", 0) <= 1000 and used.get("n2", 0) <= 1000, used
    post_event = {k: n for k, n in binds.items() if k not in ("default/p0", "default/p1")}
    assert all(n != "n0" or used.get("n0", 0) + 900 <= 1000 + 300 * 2
               for n in post_event.values()), (binds, used)


def test_inbatch_tracking_skips_light_rechecks():
    """With device-side in-batch anti tracking, a non-speculative batch of
    mutually-anti pods must commit with ZERO host LIGHT rechecks and still
    land one pod per hostname domain (round-2 VERDICT weak #3)."""
    HOST = "kubernetes.io/hostname"
    nodes = [make_node(f"n{i}", labels={HOST: f"n{i}"}) for i in range(4)]
    sched, binds = _mk_scheduler(nodes, speculate=False)
    term = PodAffinityTerm(
        label_selector=LabelSelector(match_labels={"app": "x"}),
        topology_key=HOST,
    )
    for i in range(5):
        p = make_pod(f"p{i}", labels={"app": "x"})
        p.affinity = Affinity(pod_anti_affinity=PodAntiAffinity(required=[term]))
        sched.queue.add(p)
    res = sched.schedule_batch()
    sched.wait_for_binds()
    assert res.scheduled == 4 and res.unschedulable == 1, res
    assert len(set(res.assignments.values())) == 4
    assert sched.stats.get("light_rechecks", 0) == 0, sched.stats
    assert sched.stats.get("oracle_places", 0) == 0, sched.stats


def test_warmup_compiles_without_consuming_queue():
    """warmup() peeks — it must compile/upload but pop, commit, and mutate
    nothing; the following schedule_batch sees the full queue."""
    nodes = [make_node(f"n{i}", cpu_milli=2000, mem=4 * 2**30) for i in range(4)]
    sched, binds = _mk_scheduler(nodes)
    for i in range(8):
        sched.queue.add(make_pod(f"p{i}", cpu_milli=200, mem=2**20))
    mut0 = sched.cache.mutation_count
    warmed = sched.warmup()
    assert warmed == 8
    assert sched.queue.pending_count() == 8
    assert sched.cache.mutation_count == mut0
    assert sched.cache.assumed_count() == 0
    res = sched.schedule_batch()
    assert res.scheduled == 8
    sched.wait_for_binds()
    assert len(binds) == 8


def test_bulk_commit_matches_scalar_shell():
    """The homogeneous-batch bulk commit path must place identically to the
    per-pod scalar shell given the same device solve (deterministic ties).
    An uninterested extender forces the scalar loop without changing any
    per-pod decision."""

    class _Uninterested:
        def is_interested(self, pod):
            return False

        def supports_filter(self):
            return False

        def supports_prioritize(self):
            return False

        def supports_bind(self):
            return False

        def supports_preemption(self):
            return False

        def is_ignorable(self):
            return True

    def build():
        nodes = [
            make_node(f"n{i}", cpu_milli=4000, mem=8 * 2**30,
                      labels={"zone": f"z{i % 3}"})
            for i in range(6)
        ]
        pods = [make_pod(f"p{i}", cpu_milli=300, mem=2**24) for i in range(24)]
        return nodes, pods

    nodes, pods = build()
    fast, fast_binds = _mk_scheduler(nodes, speculate=False)
    for p in pods:
        fast.queue.add(p)
    r1 = fast.schedule_batch()
    fast.wait_for_binds()

    nodes2, pods2 = build()
    slow, slow_binds = _mk_scheduler(nodes2, speculate=False,
                                     extenders=[_Uninterested()])
    for p in pods2:
        slow.queue.add(p)
    r2 = slow.schedule_batch()
    slow.wait_for_binds()

    assert r1.scheduled == r2.scheduled == 24
    assert r1.assignments == r2.assignments
    assert dict(fast_binds) == dict(slow_binds)
