"""Gang / all-or-nothing co-scheduling through the driver."""

import pytest

pytest.importorskip("jax")

from kubernetes_tpu.models.generators import make_node, make_pod
from kubernetes_tpu.scheduler.driver import (
    POD_GROUP_LABEL,
    Binder,
    Scheduler,
)
from kubernetes_tpu.state.cache import SchedulerCache
from kubernetes_tpu.state.queue import PriorityQueue


def _gang_pod(name, group, cpu=500):
    p = make_pod(name, cpu_milli=cpu, mem=0)
    p.labels[POD_GROUP_LABEL] = group
    return p


def _mk(n_nodes=4, cpu=2000):
    cache = SchedulerCache()
    for i in range(n_nodes):
        cache.add_node(make_node(f"n{i}", cpu_milli=cpu, mem=8 * 2**30))
    binds = []
    sched = Scheduler(
        cache=cache, queue=PriorityQueue(),
        binder=Binder(lambda p, n: binds.append((p.name, n))),
        deterministic=True, enable_preemption=False,
    )
    return sched, binds


def test_gang_fits_all_members_bind():
    sched, binds = _mk(n_nodes=4, cpu=2000)
    for i in range(8):  # 8 × 500m over 4 × 2000m nodes → fits
        sched.queue.add(_gang_pod(f"g{i}", "job-a"))
    res = sched.schedule_batch()
    sched.wait_for_binds()
    assert res.scheduled == 8
    assert len(binds) == 8


def test_gang_all_or_nothing_rejected():
    sched, binds = _mk(n_nodes=1, cpu=2000)
    # 5 × 500m = 2500m > 2000m: group cannot fully fit → nobody lands
    for i in range(5):
        sched.queue.add(_gang_pod(f"g{i}", "job-b"))
    res = sched.schedule_batch()
    sched.wait_for_binds()
    assert res.scheduled == 0
    assert res.unschedulable == 5
    assert binds == []
    # capacity untouched: a plain pod can take the whole node afterwards
    sched.queue.add(make_pod("plain", cpu_milli=2000, mem=0))
    res2 = sched.schedule_batch()
    assert res2.scheduled == 1


def test_dropped_gang_releases_capacity_to_others():
    sched, binds = _mk(n_nodes=1, cpu=2000)
    for i in range(5):  # infeasible gang
        sched.queue.add(_gang_pod(f"g{i}", "job-c"))
    sched.queue.add(make_pod("solo", cpu_milli=1500, mem=0))
    res = sched.schedule_batch()
    sched.wait_for_binds()
    # pass 2 of solve_gang re-solves without the dropped group, so the solo
    # pod gets the capacity in the SAME batch
    assert res.assignments.get("default/solo") == "n0"
    assert res.scheduled == 1 and res.unschedulable == 5


def test_two_gangs_independent():
    sched, binds = _mk(n_nodes=2, cpu=2000)
    for i in range(4):  # job-d: 4 × 500m = 2000m → fits
        sched.queue.add(_gang_pod(f"d{i}", "job-d"))
    for i in range(9):  # job-e: 9 × 500m = 4500m > 4000m total → dropped
        sched.queue.add(_gang_pod(f"e{i}", "job-e"))
    res = sched.schedule_batch()
    sched.wait_for_binds()
    assert res.scheduled == 4
    assert res.unschedulable == 9
    assert {k.split("/")[1][0] for k in res.assignments} == {"d"}


def test_gang_straddling_batch_boundary_pulls_whole_group():
    """A group bigger than batch_size must still be decided atomically:
    pop_batch pulls in every queued member (review finding r2)."""
    cache = SchedulerCache()
    for i in range(8):
        cache.add_node(make_node(f"n{i}", cpu_milli=2000, mem=8 * 2**30))
    sched = Scheduler(cache=cache, queue=PriorityQueue(), deterministic=True,
                      enable_preemption=False, batch_size=4)
    for i in range(12):  # 12 members, batch_size 4
        sched.queue.add(_gang_pod(f"g{i}", "big-job"))
    res = sched.schedule_batch()
    assert res.scheduled == 12  # one batch decided the whole group


def test_gang_min_available_defers_partial_group():
    """min-available: a slice smaller than the declared group size must not
    bind even if it fits."""
    from kubernetes_tpu.scheduler.driver import POD_GROUP_MIN_AVAILABLE

    sched, binds = _mk(n_nodes=4, cpu=2000)
    for i in range(3):  # only 3 of a declared 8 exist so far
        p = _gang_pod(f"g{i}", "job-partial")
        p.labels[POD_GROUP_MIN_AVAILABLE] = "8"
        sched.queue.add(p)
    res = sched.schedule_batch()
    sched.wait_for_binds()
    assert res.scheduled == 0 and res.unschedulable == 3
    assert binds == []


def test_gang_requeues_and_retries_after_capacity_frees():
    clock = [0.0]
    cache = SchedulerCache()
    cache.add_node(make_node("n0", cpu_milli=2000, mem=8 * 2**30))
    sched = Scheduler(
        cache=cache, queue=PriorityQueue(now=lambda: clock[0]),
        deterministic=True, enable_preemption=False,
    )
    blocker = make_pod("blocker", cpu_milli=1500, mem=0)
    blocker.node_name = "n0"
    sched.cache.add_pod(blocker)
    for i in range(3):  # 1500m needed, only 500m free
        sched.queue.add(_gang_pod(f"g{i}", "job-f"))
    res = sched.schedule_batch()
    assert res.scheduled == 0 and res.unschedulable == 3
    # capacity frees; the queue's unschedulable set flushes on a move event,
    # and the backoff window passes
    sched.cache.remove_pod(blocker)
    sched.queue.move_all_to_active()
    clock[0] += 15.0
    sched.queue.flush()
    total = sched.run_until_empty(max_cycles=20)
    sched.wait_for_binds()
    assert total.scheduled == 3


def test_gang_batches_participate_in_speculation():
    """Gang batches chain into the speculative pipeline (round-2 VERDICT
    weak #4): the second batch's solve rides the first gang batch's pass-2
    residual carry (spec_hits >= 1) and placements match the
    non-speculative run exactly."""
    from kubernetes_tpu.models.generators import make_node, make_pod
    from kubernetes_tpu.scheduler.driver import POD_GROUP_LABEL, Binder, Scheduler
    from kubernetes_tpu.state.cache import SchedulerCache
    from kubernetes_tpu.state.queue import PriorityQueue

    def run(speculate):
        cache = SchedulerCache()
        for i in range(8):
            cache.add_node(make_node(f"n{i}", cpu_milli=4000, mem=16 * 2**30))
        binds = {}
        sched = Scheduler(
            cache=cache, queue=PriorityQueue(),
            binder=Binder(lambda p, n: binds.__setitem__(p.key(), n)),
            batch_size=8, deterministic=True, enable_preemption=False,
            speculate=speculate, spec_depth=3,
        )
        for g in range(4):
            for m in range(8):
                p = make_pod(f"g{g}m{m}", cpu_milli=300, mem=2**20,
                             labels={POD_GROUP_LABEL: f"gang-{g}"})
                sched.queue.add(p)
        total = 0
        while True:
            r = sched.schedule_batch()
            if r.scheduled == 0 and r.unschedulable == 0 and r.errors == 0:
                break
            total += r.scheduled
        sched.wait_for_binds()
        sched.close()
        return binds, total, sched.stats.get("spec_hits", 0)

    b_on, n_on, hits = run(True)
    b_off, n_off, _ = run(False)
    assert n_on == n_off == 32
    assert b_on == b_off, (b_on, b_off)
    assert hits >= 1, "gang batches never consumed speculatively"
