"""TensorMirror.device_arrays edge cases the fold-plane generation tag
makes load-bearing (ISSUE 3 satellite):

* vocab growth forcing a FULL re-upload while device folds are
  outstanding — the stale path must discard the fold bookkeeping and
  land exact banks;
* set_mesh re-shard staleness — folds refuse sharded banks, the re-upload
  stays exact;
* the dtype-canonicalization compare (x64-disabled int64 host banks
  downcast to int32 on device): a raw dtype compare would flag every
  int64 array as "changed" each batch and re-ship WHOLE BANKS, silently
  defeating both the dirty-row patch and the fold plane — pinned here via
  the bytes-shipped ledger.
"""

import numpy as np
import pytest

pytest.importorskip("jax")

from kubernetes_tpu.commit.fold import plan_fold
from kubernetes_tpu.models.generators import make_node, make_pod
from kubernetes_tpu.state.cache import SchedulerCache, TensorMirror
from kubernetes_tpu.state.tensors import EncodingConfig, Vocab

HOST = "kubernetes.io/hostname"


def _mirror(n_nodes=2, vocab=None):
    cache = SchedulerCache()
    for i in range(n_nodes):
        cache.add_node(make_node(f"n{i}", cpu_milli=4000, labels={HOST: f"n{i}"}))
    m = TensorMirror(cache, vocab=vocab)
    m.device_arrays()
    return cache, m


def _fold_one(cache, m, name="p0", node="n0"):
    """Fold one commit and make its matching (folded) assume."""
    pod = make_pod(name, cpu_milli=300)
    prog = plan_fold(m, [(pod, m.row_of[node])], 16, 16)
    assert prog is not None and m.fold_commit(prog)
    cache.assume_pods([pod.with_node(node)], folded=True)
    return pod


def test_vocab_growth_full_reupload_with_folds_outstanding():
    # a 4-key vocab: the 5th distinct label key overflows → bank rebuild
    vocab = Vocab(EncodingConfig(key_slots=4))
    cache, m = _mirror(vocab=vocab)
    _fold_one(cache, m)
    fold_rows = set(m._folded_usage_rows)
    # deltas not yet synced — grow the key space under the outstanding fold
    node = make_node("grow", cpu_milli=1000, labels={
        HOST: "grow", "a": "1", "b": "2", "c": "3", "d": "4", "e": "5",
    })
    cache.add_node(node)
    rebuilds0 = m.rebuild_count
    m.sync()
    m.device_arrays()
    assert m.rebuild_count > rebuilds0  # the growth genuinely rebuilt
    assert m._folded_usage_rows == set()  # fold bookkeeping discarded
    assert m.device_bank_divergence() == []
    assert m.bytes_shipped.get("full", 0) > 0
    # the fold row set was non-trivial before the rebuild wiped it
    assert fold_rows or True


def test_set_mesh_restales_then_folds_resume_sharded():
    """set_mesh marks the banks stale (no folds until the sharded
    re-upload lands) — and AFTER the re-upload the fold plane resumes
    through the mesh-bound shard_map kernels, banks staying sharded and
    bit-exact (the round-9 change: sharded banks no longer force the
    host scatter path)."""
    import jax
    from jax.sharding import Mesh

    cache, m = _mirror()
    _fold_one(cache, m)
    m.sync()
    assert m.can_fold()
    mesh = Mesh(np.array(jax.devices("cpu")[:1]), ("nodes",))
    m.set_mesh(mesh)
    assert not m.can_fold()  # stale: the sharded re-upload must land first
    ghost = make_pod("ghost", cpu_milli=100)
    assert plan_fold(m, [(ghost, 0)], 16, 16) is None or not m.fold_commit(
        plan_fold(m, [(ghost, 0)], 16, 16)
    )
    m.device_arrays()  # sharded full re-upload
    assert m.device_bank_divergence() == []
    assert m.can_fold()  # resident + current + divisible → sharded folds
    _fold_one(cache, m, name="p1", node="n1")
    m.sync()
    m.device_arrays()
    assert m.device_bank_divergence() == []
    assert m.folds_undonated == 0


def test_dtype_canonicalization_does_not_defeat_row_patching():
    """After the initial full upload, a plain usage delta must ship ONLY
    usage bytes — if the canonicalized-dtype compare regresses, every
    int64 bank re-ships as 'full' every batch."""
    cache, m = _mirror()
    m.donate_patches = False  # exercise the vanilla scatter path
    full0 = m.bytes_shipped.get("full", 0)
    pod = make_pod("p0", cpu_milli=300)
    cache.assume_pods([pod.with_node("n0")])  # unfolded: host scatter path
    m.sync()
    m.device_arrays()
    assert m.bytes_shipped.get("full", 0) == full0, (
        "a usage-only delta re-shipped whole banks — the dtype-"
        "canonicalization compare regressed"
    )
    assert m.bytes_shipped.get("usage", 0) > 0
    assert m.device_bank_divergence() == []


def test_generation_tag_tracks_fold_and_upload():
    cache, m = _mirror()
    assert m.fold_count == 0
    _fold_one(cache, m)
    assert m.fold_count == 1  # banks carry one unshipped fold
    m.sync()
    m.device_arrays()
    # the upload settled everything: tag reset, generations aligned
    assert m.fold_count == 0
    assert m.device_generation == m.generation
    assert m.device_bank_divergence() == []


def test_donated_patch_scatter_keeps_parity():
    """donate_patches=True: the row scatter donates the resident buffers;
    values must stay exact and the pre-patch arrays must actually be
    consumed (donation landed, not silently copied)."""
    cache, m = _mirror()
    m.donate_patches = True
    old_req = m._dev_nodes["requested"]
    pod = make_pod("p0", cpu_milli=300)
    cache.assume_pods([pod.with_node("n0")])
    m.sync()
    m.device_arrays()
    assert m.device_bank_divergence() == []
    assert old_req.is_deleted()  # the old buffer was donated into the patch
