"""Round-4 controllers: garbage collector (ownerReference cascade),
DaemonSet (default-scheduler placement via matchFields pin), Endpoints,
StatefulSet (ordered ordinals), Namespace lifecycle — each through the
real scheduler loop where placement matters. Reference anchors:
garbagecollector.go:83, daemon_controller.go, endpoints_controller.go,
stateful_set.go, namespaced_resources_deleter.go."""

import time

import pytest

pytest.importorskip("jax")

from kubernetes_tpu.api.types import (
    Container,
    DaemonSet,
    LabelSelector,
    Namespace,
    Pod,
    Quantity,
    RESOURCE_CPU,
    RESOURCE_MEMORY,
    ReplicaSet,
    Service,
    StatefulSet,
)
from kubernetes_tpu.apiserver import FakeAPIServer
from kubernetes_tpu.client import APIBinder, start_scheduler_informers
from kubernetes_tpu.controllers import ControllerManager
from kubernetes_tpu.models.generators import make_node
from kubernetes_tpu.scheduler.driver import Binder, Scheduler
from kubernetes_tpu.scheduler.eventhandlers import EventHandlers


def _template(app: str, cpu="100m") -> Pod:
    return Pod(
        name="template", labels={"app": app},
        containers=[Container(name="c", requests={
            RESOURCE_CPU: Quantity.parse(cpu),
            RESOURCE_MEMORY: Quantity.parse("64Mi"),
        })],
    )


def _pods(api, app=None):
    pods, _ = api.list("pods")
    if app is None:
        return pods
    return [p for p in pods if p.labels.get("app") == app]


def _wait(pred, timeout=10.0, msg="condition"):
    end = time.monotonic() + timeout
    while time.monotonic() < end:
        if pred():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {msg}")


@pytest.fixture()
def stack():
    """apiserver + scheduler loop (driven manually) + controller manager."""
    api = FakeAPIServer()
    for i in range(3):
        api.create("nodes", make_node(
            f"n{i}", cpu_milli=4000, mem=8 * 2**30,
            labels={"kubernetes.io/hostname": f"n{i}",
                    "disk": "ssd" if i < 2 else "hdd"},
        ))
    sched = Scheduler(batch_size=16, deterministic=True, enable_preemption=False)
    sched.binder = Binder(APIBinder(api).bind)
    handlers = EventHandlers(sched.cache, sched.queue, "default-scheduler")
    informers = start_scheduler_informers(api, handlers)
    for inf in informers.values():
        inf.wait_for_sync()
    cm = ControllerManager(api).start()

    def drain(expect, app=None, deadline=20.0):
        end = time.monotonic() + deadline
        while time.monotonic() < end:
            sched.schedule_batch()
            sched.wait_for_binds()
            bound = [p for p in _pods(api, app) if p.node_name]
            if len(bound) >= expect and cm.wait_idle(timeout=0.5):
                return bound
            time.sleep(0.05)
        raise AssertionError(
            f"drain: wanted {expect} bound, have "
            f"{[(p.key(), p.node_name, p.phase) for p in _pods(api, app)]}"
        )

    yield api, sched, cm, drain
    cm.stop()
    for inf in informers.values():
        inf.stop()


def test_gc_cascades_deployment_to_pods(stack):
    api, sched, cm, drain = stack
    api.create("deployments", __import__(
        "kubernetes_tpu.api.types", fromlist=["Deployment"]
    ).Deployment(
        name="web", replicas=4,
        selector=LabelSelector(match_labels={"app": "web"}),
        template=_template("web"),
    ))
    assert cm.wait_idle()
    drain(4, "web")
    # delete the Deployment DIRECTLY: GC must cascade RS → pods
    api.delete("deployments", "default/web")
    _wait(lambda: cm.wait_idle(0.5) and not api.list("replicasets")[0]
          and not _pods(api, "web"),
          msg="gc cascade deployment→rs→pods")
    assert cm.garbagecollector.deleted >= 1


def test_daemonset_one_pod_per_matching_node_via_scheduler(stack):
    api, sched, cm, drain = stack
    tmpl = _template("agent")
    tmpl.node_selector = {"disk": "ssd"}
    api.create("daemonsets", DaemonSet(
        name="agent", selector=LabelSelector(match_labels={"app": "agent"}),
        template=tmpl,
    ))
    assert cm.wait_idle()
    bound = drain(2, "agent")
    # exactly the two ssd nodes, each exactly once, placed by the SCHEDULER
    # through the matchFields metadata.name pin
    assert sorted(p.node_name for p in bound) == ["n0", "n1"]
    # a NEW eligible node gets its daemon
    api.create("nodes", make_node(
        "n3", cpu_milli=4000, mem=8 * 2**30,
        labels={"kubernetes.io/hostname": "n3", "disk": "ssd"},
    ))
    _wait(lambda: cm.wait_idle(0.5) and len(_pods(api, "agent")) == 3,
          msg="daemon pod for new node")
    bound2 = drain(3, "agent")
    assert sorted(p.node_name for p in bound2) == ["n0", "n1", "n3"]


def test_endpoints_follow_service_selector(stack):
    api, sched, cm, drain = stack
    api.create("services", Service(name="svc", selector={"app": "web"}))
    api.create("replicasets", ReplicaSet(
        name="web", replicas=3,
        selector=LabelSelector(match_labels={"app": "web"}),
        template=_template("web"),
    ))
    assert cm.wait_idle()
    bound = drain(3, "web")
    _wait(lambda: len(api.get("endpoints", "default/svc").addresses) == 3,
          msg="endpoints populated")
    ep = api.get("endpoints", "default/svc")
    assert sorted(ep.addresses) == sorted(p.key() for p in bound)
    # scale down → membership shrinks
    rs = api.get("replicasets", "default/web")
    rs.replicas = 1
    api.update("replicasets", rs)
    _wait(lambda: cm.wait_idle(0.5)
          and len(api.get("endpoints", "default/svc").addresses) == 1,
          msg="endpoints shrink")
    # service deletion → endpoints deleted
    api.delete("services", "default/svc")
    def _gone():
        try:
            api.get("endpoints", "default/svc")
            return False
        except KeyError:
            return True
    _wait(lambda: cm.wait_idle(0.5) and _gone(), msg="endpoints removed")


def test_statefulset_ordered_identities(stack):
    api, sched, cm, drain = stack
    api.create("statefulsets", StatefulSet(
        name="db", replicas=3,
        selector=LabelSelector(match_labels={"app": "db"}),
        template=_template("db"),
    ))
    assert cm.wait_idle()
    # OrderedReady: db-1 is created only after db-0 Runs — drive the loop
    # with explicit Running acks (no kubelet in this stack)
    deadline = time.monotonic() + 20.0
    while time.monotonic() < deadline:
        sched.schedule_batch()
        sched.wait_for_binds()
        for p in _pods(api, "db"):
            if p.node_name and p.phase == "Pending":
                p.phase = "Running"
                api.update("pods", p)
        cm.wait_idle(0.3)
        names = sorted(p.name for p in _pods(api, "db") if p.phase == "Running")
        if names == ["db-0", "db-1", "db-2"]:
            break
        time.sleep(0.05)
    assert sorted(p.name for p in _pods(api, "db")) == ["db-0", "db-1", "db-2"]
    # scale down: HIGHEST ordinal goes first
    ss = api.get("statefulsets", "default/db")
    ss.replicas = 2
    api.update("statefulsets", ss)
    _wait(lambda: cm.wait_idle(0.5)
          and sorted(p.name for p in _pods(api, "db")) == ["db-0", "db-1"],
          msg="ordinal 2 deleted first")


def test_namespace_termination_drains_contents(stack):
    api, sched, cm, drain = stack
    api.create("namespaces", Namespace(name="team-a"))
    tmpl = _template("batch")
    tmpl.namespace = "team-a"
    api.create("replicasets", ReplicaSet(
        name="batch", namespace="team-a", replicas=3,
        selector=LabelSelector(match_labels={"app": "batch"}),
        template=tmpl,
    ))
    assert cm.wait_idle()
    drain(3, "batch")
    ns = api.get("namespaces", "team-a")
    ns.phase = "Terminating"
    api.update("namespaces", ns)
    def _empty():
        pods = [p for p in _pods(api) if p.namespace == "team-a"]
        rss = [r for r in api.list("replicasets")[0] if r.namespace == "team-a"]
        try:
            api.get("namespaces", "team-a")
            ns_gone = False
        except KeyError:
            ns_gone = True
        return not pods and not rss and ns_gone
    _wait(lambda: cm.wait_idle(0.5) and _empty(), msg="namespace drained")


def test_kubectl_apply_scale_to_running_on_hollow_nodes():
    """VERDICT #10's bar: a manifest round-trips kubectl apply →
    controllers → scheduler → RUNNING on hollow kubelets, then
    kubectl scale grows it — all over the HTTP transport as a separate
    process."""
    import json
    import subprocess
    import sys
    import tempfile

    from kubernetes_tpu.apiserver import APIServerHTTP
    from kubernetes_tpu.kubemark import HollowCluster

    api = FakeAPIServer()
    nodes = [make_node(f"n{i}", cpu_milli=4000, mem=8 * 2**30) for i in range(3)]
    srv = APIServerHTTP(api).start()
    sched = Scheduler(batch_size=16, deterministic=True, enable_preemption=False)
    sched.binder = Binder(APIBinder(api).bind)
    handlers = EventHandlers(sched.cache, sched.queue, "default-scheduler")
    informers = start_scheduler_informers(api, handlers)
    for inf in informers.values():
        inf.wait_for_sync()
    hollow = HollowCluster(api, nodes, heartbeat_s=0.5).start()
    cm = ControllerManager(api).start()

    def kubectl(*args, stdin=None):
        r = subprocess.run(
            [sys.executable, "-m", "kubernetes_tpu.kubectl",
             "--server", srv.url, *args],
            capture_output=True, text=True, input=stdin, timeout=60,
        )
        assert r.returncode == 0, (args, r.stdout, r.stderr)
        return r.stdout

    manifest = {
        "apiVersion": "apps/v1", "kind": "Deployment",
        "metadata": {"name": "web", "namespace": "default"},
        "spec": {
            "replicas": 2,
            "selector": {"matchLabels": {"app": "web"}},
            "template": {
                "metadata": {"labels": {"app": "web"}},
                "spec": {"containers": [{"name": "c", "resources": {
                    "requests": {"cpu": "100m", "memory": "64Mi"}}}]},
            },
        },
    }
    try:
        with tempfile.NamedTemporaryFile("w", suffix=".json", delete=False) as f:
            json.dump(manifest, f)
            path = f.name
        out = kubectl("apply", "-f", path)
        assert "deployment/web created" in out

        def running(n):
            return [p for p in _pods(api, "web")
                    if p.node_name and p.phase == "Running"]

        deadline = time.monotonic() + 25.0
        while time.monotonic() < deadline:
            sched.schedule_batch()
            sched.wait_for_binds()
            cm.wait_idle(0.3)
            if len(running(2)) >= 2:
                break
            time.sleep(0.05)
        assert len(running(2)) == 2, [(p.key(), p.phase) for p in _pods(api)]

        out = kubectl("scale", "deployment/web", "--replicas", "5")
        assert "scaled to 5" in out
        deadline = time.monotonic() + 25.0
        while time.monotonic() < deadline:
            sched.schedule_batch()
            sched.wait_for_binds()
            cm.wait_idle(0.3)
            if len(running(5)) >= 5:
                break
            time.sleep(0.05)
        assert len(running(5)) == 5

        # re-apply with replicas=1: configured, controllers shrink
        manifest["spec"]["replicas"] = 1
        out = kubectl("apply", "-f", "-", stdin=json.dumps(manifest))
        assert "deployment/web configured" in out
        deadline = time.monotonic() + 25.0
        while time.monotonic() < deadline:
            cm.wait_idle(0.3)
            live = [p for p in _pods(api, "web") if p.phase != "Failed"]
            if len(live) == 1:
                break
            time.sleep(0.05)
        assert len([p for p in _pods(api, "web") if p.phase != "Failed"]) == 1
    finally:
        cm.stop()
        hollow.stop()
        for inf in informers.values():
            inf.stop()
        srv.stop()
