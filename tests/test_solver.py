"""Batch solver semantics: identical to sequential greedy scheduling."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp

from kubernetes_tpu.ops.solver import pop_order, solve_gang, solve_greedy


def _sequential(mask, score, req, free, count, allowed, order):
    """Reference semantics: one pod at a time, deterministic argmax."""
    free = free.copy()
    count = count.copy()
    out = np.full(mask.shape[0], -1, np.int32)
    for i in order:
        feas = mask[i] & np.all(req[i][None, :] <= free, axis=-1) & (count + 1 <= allowed)
        if not feas.any():
            continue
        s = np.where(feas, score[i], np.iinfo(score.dtype).min)
        n = int(np.argmax(s))
        out[i] = n
        free[n] -= req[i]
        count[n] += 1
    return out


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_greedy_matches_sequential(seed):
    rng = np.random.RandomState(seed)
    B, N, R = 24, 12, 3
    mask = rng.rand(B, N) < 0.7
    score = rng.randint(0, 50, (B, N)).astype(np.int64)
    req = rng.randint(1, 5, (B, R)).astype(np.int64)
    free = rng.randint(5, 20, (N, R)).astype(np.int64)
    count = np.zeros(N, np.int64)
    allowed = np.full(N, 8, np.int64)
    prio = rng.randint(0, 3, B).astype(np.int32)
    seq = np.arange(B, dtype=np.int32)
    valid = np.ones(B, bool)

    order = np.asarray(pop_order(jnp.asarray(prio), jnp.asarray(seq), jnp.asarray(valid)))
    # order is priority-desc then FIFO
    ps = prio[order]
    assert all(ps[i] >= ps[i + 1] for i in range(B - 1))

    got = np.asarray(
        solve_greedy(
            jnp.asarray(mask), jnp.asarray(score), jnp.asarray(req), jnp.asarray(free),
            jnp.asarray(count), jnp.asarray(allowed), jnp.asarray(order),
            jax.random.PRNGKey(seed), deterministic=True,
        )
    )
    expect = _sequential(mask, score, req, free, count, allowed, order)
    assert (got == expect).all(), (got, expect)


def test_capacity_respected_within_batch():
    # two identical pods, one node with room for exactly one
    mask = np.ones((2, 1), bool)
    score = np.zeros((2, 1), np.int64)
    req = np.array([[3], [3]], np.int64)
    free = np.array([[5]], np.int64)
    got = np.asarray(
        solve_greedy(
            jnp.asarray(mask), jnp.asarray(score), jnp.asarray(req), jnp.asarray(free),
            jnp.asarray(np.zeros(1, np.int64)), jnp.asarray(np.full(1, 10, np.int64)),
            jnp.arange(2), jax.random.PRNGKey(0), deterministic=True,
        )
    )
    assert sorted(got.tolist()) == [-1, 0]


def test_random_tie_break_within_argmax():
    mask = np.ones((1, 8), bool)
    score = np.array([[5, 9, 9, 1, 9, 0, 9, 2]], np.int64)
    picks = set()
    for s in range(20):
        got = np.asarray(
            solve_greedy(
                jnp.asarray(mask), jnp.asarray(score), jnp.ones((1, 1), jnp.int64),
                jnp.full((8, 1), 100, jnp.int64), jnp.zeros(8, jnp.int64),
                jnp.full(8, 10, jnp.int64), jnp.arange(1), jax.random.PRNGKey(s),
            )
        )
        picks.add(int(got[0]))
    assert picks <= {1, 2, 4, 6}
    assert len(picks) > 1  # actually randomizes


def test_gang_all_or_nothing():
    # group 0: two pods needing 3 each; node has 5 → gang must drop BOTH,
    # releasing room for the ungrouped pod
    mask = np.ones((3, 1), bool)
    score = np.zeros((3, 1), np.int64)
    req = np.array([[3], [3], [4]], np.int64)
    free = np.array([[5]], np.int64)
    group = np.array([0, 0, -1], np.int32)
    prio = np.array([10, 10, 0], np.int32)  # gang first in pop order
    order = np.asarray(pop_order(jnp.asarray(prio), jnp.arange(3), jnp.ones(3, bool)))
    got, ok = solve_gang(
        jnp.asarray(mask), jnp.asarray(score), jnp.asarray(req), jnp.asarray(free),
        jnp.zeros(1, jnp.int64), jnp.full(1, 10, jnp.int64), jnp.asarray(order),
        jnp.asarray(group), jax.random.PRNGKey(0), deterministic=True,
    )
    got = np.asarray(got)
    ok = np.asarray(ok)
    assert got[0] == -1 and got[1] == -1  # gang dropped
    assert got[2] == 0  # ungrouped pod fits after release
    assert not ok[0] and not ok[1] and ok[2]


def test_gang_fits_entirely():
    mask = np.ones((2, 2), bool)
    score = np.array([[1, 0], [1, 0]], np.int64)
    req = np.array([[3], [3]], np.int64)
    free = np.array([[3], [3]], np.int64)
    group = np.array([0, 0], np.int32)
    got, ok = solve_gang(
        jnp.asarray(mask), jnp.asarray(score), jnp.asarray(req), jnp.asarray(free),
        jnp.zeros(2, jnp.int64), jnp.full(2, 10, jnp.int64), jnp.arange(2),
        jnp.asarray(group), jax.random.PRNGKey(0), deterministic=True,
    )
    assert sorted(np.asarray(got).tolist()) == [0, 1]
    assert np.asarray(ok).all()


def test_term_kind_gating_is_bit_identical():
    """solve_pipeline with the host-computed term-kind statics must produce
    the SAME assignment and scores as the assume-everything program — a
    skipped kernel's term-absent identity is exact, not approximate."""
    import numpy as np

    from kubernetes_tpu.models.generators import ClusterGen
    from kubernetes_tpu.oracle import Snapshot
    from kubernetes_tpu.ops.pipeline import encode_solve_args, solve_pipeline
    from kubernetes_tpu.scheduler.driver import _present_term_kinds
    from kubernetes_tpu.state.tensors import PodBatch, _bucket, encode_snapshot
    from kubernetes_tpu.state.terms import compile_batch_terms, compile_existing_patterns

    for seed, feature_rate in ((5, 0.0), (6, 0.5)):
        g = ClusterGen(seed)
        nodes, existing = g.cluster(12, 40, feature_rate=feature_rate)
        snap = Snapshot(nodes, existing)
        pods = [g.pod(30_000 + i, feature_rate=feature_rate) for i in range(10)]
        args = encode_solve_args(snap, pods)
        # recompute host banks to derive kinds the way the driver does
        bank, _, row_of = encode_snapshot(snap)
        batch = PodBatch(bank.vocab, _bucket(len(pods)))
        for i, p in enumerate(pods):
            batch.set_pod(i, p)
        tb, aux = compile_batch_terms(bank.vocab, pods, b_capacity=batch.capacity)
        etb = compile_existing_patterns(bank.vocab, snap, row_of, bank.capacity)
        kinds = _present_term_kinds(tb, etb, aux)
        a_all, s_all = solve_pipeline(*args, deterministic=True)
        a_gated, s_gated = solve_pipeline(*args, deterministic=True, term_kinds=kinds)
        assert np.array_equal(np.asarray(a_all), np.asarray(a_gated)), (seed, kinds)
        assert np.array_equal(np.asarray(s_all), np.asarray(s_gated)), (seed, kinds)


def _sequential_noise(mask, score, req, free, count, allowed, order, noise, req_any):
    """Sequential reference WITH the selectHost noise tie-break: pod at scan
    position p uses noise row p (the tie_noise stream)."""
    free = free.copy()
    count = count.copy()
    out = np.full(mask.shape[0], -1, np.int32)
    for p, i in enumerate(order):
        res_ok = (not req_any[i]) or np.all(req[i][None, :] <= free, axis=-1)
        feas = mask[i] & res_ok & (count + 1 <= allowed)
        if not feas.any():
            continue
        s = np.where(feas, score[i], np.iinfo(score.dtype).min)
        best = s.max()
        ties = feas & (s == best)
        n = int(np.argmax(np.where(ties, noise[p], -1.0)))
        out[i] = n
        free[n] -= req[i]
        count[n] += 1
    return out


@pytest.mark.parametrize("seed", [0, 3, 7])
@pytest.mark.parametrize("deterministic", [True, False])
def test_chunked_contention_matches_sequential(seed, deterministic):
    """High contention across chunk boundaries: B=256 pods (4 chunks of 64)
    fighting over 8 tight nodes — the chunked prefix-acceptance repair loop
    must still be bit-identical to one-pod-at-a-time scheduling."""
    from kubernetes_tpu.ops.solver import tie_noise

    rng = np.random.RandomState(seed)
    B, N, R = 256, 8, 2
    mask = rng.rand(B, N) < 0.9
    # few distinct scores → massive ties → noise path heavily exercised
    score = rng.randint(0, 3, (B, N)).astype(np.int64)
    req = rng.randint(1, 4, (B, R)).astype(np.int64)
    req_any = np.ones(B, bool)
    free = rng.randint(10, 30, (N, R)).astype(np.int64)  # ~5% of demand fits
    count = np.zeros(N, np.int64)
    allowed = np.full(N, 12, np.int64)
    order = np.arange(B, dtype=np.int32)
    key = jax.random.PRNGKey(seed)

    got = np.asarray(solve_greedy(
        jnp.asarray(mask), jnp.asarray(score), jnp.asarray(req), jnp.asarray(free),
        jnp.asarray(count), jnp.asarray(allowed), jnp.asarray(order), key,
        deterministic=deterministic, req_any=jnp.asarray(req_any),
    ))
    if deterministic:
        noise = np.zeros((B, N))  # ties break by argmax first-index
        expect = _sequential(mask, score, req, free, count, allowed, order)
    else:
        noise = np.asarray(tie_noise(key, B, N))
        expect = _sequential_noise(mask, score, req, free, count, allowed, order,
                                   noise, req_any)
    assert (got == expect).all(), np.nonzero(got != expect)


def test_chunked_sig_dedup_matches_expanded():
    """sig-mapped spec rows must behave exactly like materialized per-pod
    rows, including duplicates contending for the same node."""
    rng = np.random.RandomState(11)
    U, B, N, R = 5, 128, 6, 2
    mask_u = rng.rand(U, N) < 0.8
    score_u = rng.randint(0, 4, (U, N)).astype(np.int64)
    req_u = rng.randint(1, 3, (U, R)).astype(np.int64)
    req_any_u = np.ones(U, bool)
    sig = rng.randint(0, U, B).astype(np.int32)
    valid = np.ones(B, bool)
    valid[100:] = False  # tail padding
    free = rng.randint(8, 20, (N, R)).astype(np.int64)
    count = np.zeros(N, np.int64)
    allowed = np.full(N, 40, np.int64)
    order = np.arange(B, dtype=np.int32)
    key = jax.random.PRNGKey(4)

    got = np.asarray(solve_greedy(
        jnp.asarray(mask_u), jnp.asarray(score_u), jnp.asarray(req_u),
        jnp.asarray(free), jnp.asarray(count), jnp.asarray(allowed),
        jnp.asarray(order), key, deterministic=False,
        req_any=jnp.asarray(req_any_u), sig=jnp.asarray(sig),
        pod_valid=jnp.asarray(valid),
    ))
    # expand spec rows to per-pod rows; invalid pods get an all-false mask
    mask_b = mask_u[sig] & valid[:, None]
    expect = np.asarray(solve_greedy(
        jnp.asarray(mask_b), jnp.asarray(score_u[sig]), jnp.asarray(req_u[sig]),
        jnp.asarray(free), jnp.asarray(count), jnp.asarray(allowed),
        jnp.asarray(order), key, deterministic=False,
        req_any=jnp.asarray(req_any_u[sig]),
    ))
    assert (got == expect).all()
    assert (got[100:] == -1).all()


def test_chunk_guard_non_divisible_batch():
    """B not divisible by the chunk size falls back to one whole-batch
    chunk instead of mis-reshaping."""
    rng = np.random.RandomState(2)
    B, N, R = 96, 5, 2
    mask = rng.rand(B, N) < 0.8
    score = rng.randint(0, 10, (B, N)).astype(np.int64)
    req = np.ones((B, R), np.int64)
    free = np.full((N, R), 25, np.int64)
    count = np.zeros(N, np.int64)
    allowed = np.full(N, 30, np.int64)
    order = np.arange(B, dtype=np.int32)
    got = np.asarray(solve_greedy(
        jnp.asarray(mask), jnp.asarray(score), jnp.asarray(req), jnp.asarray(free),
        jnp.asarray(count), jnp.asarray(allowed), jnp.asarray(order),
        jax.random.PRNGKey(0), deterministic=True,
    ))
    expect = _sequential(mask, score, req, free, count, allowed, order)
    assert (got == expect).all()


def test_inbatch_anti_tracking_matches_sequential():
    """solve_greedy with `inb`: required anti-affinity conflicts between
    BATCH pods must resolve exactly like the sequential walk — the earlier
    pod (in order) wins the topology domain, later conflicting pods move
    or go -1 — with no host involvement."""
    # 4 nodes in 2 zones (bucket 0/1); every pod mutually anti on zone
    N, B = 4, 4
    mask = np.ones((B, N), bool)
    score = np.zeros((B, N), np.int64)
    score[:, 0] = 5  # all prefer node 0 (zone 0)
    req = np.ones((B, 1), np.int64)
    free = np.full((N, 1), 100, np.int64)
    count = np.zeros(N, np.int64)
    allowed = np.full(N, 10, np.int64)
    order = np.arange(B, dtype=np.int32)
    TT, V = 4, 2
    zone_of_node = np.array([0, 0, 1, 1], np.int32)
    inb = {
        # one anti term per pod, all selecting everyone (mutual anti)
        "anti": jnp.asarray(np.array([True] * B)),
        "owner": jnp.asarray(np.arange(B, dtype=np.int32)),
        "m_bb": jnp.asarray(np.ones((TT, B), bool)),
        "bucket_n": jnp.asarray(np.broadcast_to(zone_of_node, (TT, N)).copy()),
        "haskey_n": jnp.asarray(np.ones((TT, N), bool)),
        "port_conflict": jnp.asarray(np.zeros((B, B), bool)),
        "ca0": jnp.zeros((TT, V), jnp.float32),
        "cb0": jnp.zeros((TT, V), jnp.float32),
        "cs0": jnp.zeros((B, N), jnp.float32),
    }
    got = np.asarray(solve_greedy(
        jnp.asarray(mask), jnp.asarray(score), jnp.asarray(req),
        jnp.asarray(free), jnp.asarray(count), jnp.asarray(allowed),
        jnp.asarray(order), jax.random.PRNGKey(0), deterministic=True,
        req_any=jnp.ones(B, bool), inb=inb,
    ))
    # sequential: pod0 -> node0 (zone0); pod1 blocked in zone0 -> first
    # zone-1 node (2); pods 2,3: both zones occupied -> -1
    assert got.tolist() == [0, 2, -1, -1], got


def test_inbatch_port_tracking_matches_sequential():
    """Host-port conflicts between batch pods: the spec x spec conflict
    matrix + per-(spec, node) commit table must force later replicas of a
    ported spec onto distinct nodes (hostname semantics)."""
    N, B = 3, 4
    mask = np.ones((B, N), bool)
    score = np.zeros((B, N), np.int64)
    score[:, 0] = 3
    score[:, 1] = 2
    req = np.ones((B, 1), np.int64)
    free = np.full((N, 1), 100, np.int64)
    order = np.arange(B, dtype=np.int32)
    TT, V = 1, 1
    pconf = np.ones((B, B), bool)  # every pod carries the same host port
    inb = {
        "anti": jnp.asarray(np.zeros(TT, bool)),
        "owner": jnp.asarray(np.zeros(TT, np.int32)),
        "m_bb": jnp.asarray(np.zeros((TT, B), bool)),
        "bucket_n": jnp.asarray(np.zeros((TT, N), np.int32)),
        "haskey_n": jnp.asarray(np.zeros((TT, N), bool)),
        "port_conflict": jnp.asarray(pconf),
        "ca0": jnp.zeros((TT, V), jnp.float32),
        "cb0": jnp.zeros((TT, V), jnp.float32),
        "cs0": jnp.zeros((B, N), jnp.float32),
    }
    got = np.asarray(solve_greedy(
        jnp.asarray(mask), jnp.asarray(score), jnp.asarray(req),
        jnp.asarray(free), jnp.asarray(np.zeros(N, np.int64)),
        jnp.asarray(np.full(N, 10, np.int64)),
        jnp.asarray(order), jax.random.PRNGKey(0), deterministic=True,
        req_any=jnp.ones(B, bool), inb=inb,
    ))
    # one ported pod per node, in score order; the 4th has nowhere to go
    assert got.tolist() == [0, 1, 2, -1], got
