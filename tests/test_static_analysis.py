"""ktpu-lint + lock-order harness coverage (tier-1, CPU-only, no bench).

Three layers:
  * fixture corpus — each KTPU rule has a must-flag fixture reproducing
    the historical bug it is the static twin of, and a must-not-flag
    twin exercising the sanctioned pattern/annotation;
  * the tree gate — the full kubernetes_tpu/ scan must not grow beyond
    the checked-in baseline (the same gate preflight runs), and the
    PERF.md/README bench table must match BENCH_DETAILS.json
    (gen_perf_table --check);
  * the runtime lock-order harness — deliberate ABBA deadlock fixture
    detected, clean ordering passes, reentrancy and condition-wait
    bookkeeping correct. (The audited full smoke drains live in
    test_perf_smoke with KTPU_LOCK_AUDIT=1.)
"""

import os
import subprocess
import sys
import threading

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_FIXTURES = os.path.join(_REPO, "tests", "fixtures", "lint")

from kubernetes_tpu.analysis import (  # noqa: E402
    AnalysisConfig,
    Baseline,
    load_module,
    run_checkers,
    scan_paths,
)
from kubernetes_tpu.analysis.checkers import ALL_CHECKERS, repo_config  # noqa: E402
from kubernetes_tpu.analysis.core import Violation, parse_annotations  # noqa: E402


def fixture_config() -> AnalysisConfig:
    """Fixtures are treated as both jit-restricted AND resident-surface
    modules so every rule applies to them."""
    return AnalysisConfig(
        jit_allowed_prefixes=(),
        surface_prefixes=("tests/fixtures/lint/",),
        sync_allowlist=(
            "Mirror.device_bank_divergence",
            "Recorder.resolve_pending",
        ),
    )


def scan_fixture(name: str):
    mod = load_module(os.path.join(_FIXTURES, name), _REPO)
    return run_checkers(mod, fixture_config(), ALL_CHECKERS)


def rules_by_scope(violations):
    return {(v.rule, v.scope) for v in violations}


# ---------------------------------------------------------------------------
# fixture corpus: must-flag / must-not-flag per rule
# ---------------------------------------------------------------------------

def test_ktpu001_flags_unplanned_jit():
    """PR 4's invisible patch-program compile: a jit factory with no plan
    admission in scope must flag."""
    got = scan_fixture("ktpu001_unplanned_jit.py")
    hits = [v for v in got if v.rule == "KTPU001"]
    assert hits and hits[0].scope.startswith("scatter_fn")


def test_ktpu001_passes_planned_and_annotated_jit():
    got = scan_fixture("ktpu001_planned_jit.py")
    assert not [v for v in got if v.rule == "KTPU001"], [v.render() for v in got]


def test_ktpu002_flags_use_after_donate():
    got = scan_fixture("ktpu002_use_after_donate.py")
    hits = [v for v in got if v.rule == "KTPU002" and "use-after-donate" in v.detail]
    assert hits and hits[0].scope == "bad_apply"
    # the rebind idiom must NOT flag
    assert not [v for v in got if v.scope == "good_apply"]


def test_ktpu002_flags_host_sync_on_resident():
    """PR 4's np.asarray-on-sharded bug: direct host view of a resident
    array flags; the allowlisted sync point and the annotated line do
    not."""
    got = scan_fixture("ktpu002_sync_on_resident.py")
    scopes = rules_by_scope(got)
    assert ("KTPU002", "Mirror.bad_probe") in scopes
    assert ("KTPU002", "Mirror.device_bank_divergence") not in scopes
    assert ("KTPU002", "Mirror.annotated_probe") not in scopes


def test_ktpu002_flags_forcing_span_resolver():
    """The flight recorder's two-phase device-timing idiom: blocking on a
    parked handle in a NON-allowlisted resolver flags; the sanctioned
    `resolve_pending` twin (sync_allowlist) does not."""
    got = scan_fixture("ktpu002_span_resolver.py")
    scopes = rules_by_scope(got)
    assert ("KTPU002", "Recorder.eager_resolve") in scopes
    assert ("KTPU002", "Recorder.resolve_pending") not in scopes


def test_ktpu002_obs_resolver_allowlisted_in_tree():
    """The REAL recorder module is a resident-surface module and its
    resolver is in the repo allowlist — the tree scan must be clean on
    obs/ (a forcing call added anywhere else in obs/ would flag)."""
    cfg = repo_config()
    assert any("kubernetes_tpu/obs/" in p for p in cfg.surface_prefixes)
    assert "FlightRecorder.resolve_pending" in cfg.sync_allowlist
    path = os.path.join(_REPO, "kubernetes_tpu", "obs", "recorder.py")
    mod = load_module(path, _REPO)
    got = run_checkers(mod, cfg, ALL_CHECKERS)
    assert not [v.render() for v in got if v.rule in ("KTPU002", "KTPU004")]


def test_ktpu004_fault_injection_site_idiom():
    """The fault plane's injection-site contract: a site that forces a
    device value to decide whether to fire inside a hot-path dispatch
    flags; the attribute-read + counted-raise idiom does not."""
    got = scan_fixture("ktpu004_fault_site.py")
    scopes = rules_by_scope(got)
    assert ("KTPU004", "Dispatcher.bad_dispatch") in scopes
    assert ("KTPU004", "Dispatcher.good_dispatch") not in scopes


def test_ktpu003_flags_unlocked_guarded_access():
    """PR 5's unlocked vocab-slot interning: guarded attr accessed outside
    the lock flags; with-block, _locked suffix and holds() pass."""
    got = scan_fixture("ktpu003_guarded.py")
    scopes = rules_by_scope(got)
    assert ("KTPU003", "SlotTable.bad_slot_of") in scopes
    assert ("KTPU003", "SlotTable.good_slot_of") not in scopes
    assert ("KTPU003", "SlotTable._drain_locked") not in scopes
    assert ("KTPU003", "SlotTable._helper") not in scopes


def test_ktpu003_confined_requires_matching_mark():
    """confined() declares lock-FREE single-thread state (the mirror's
    fold bookkeeping): accesses from methods without the matching
    confined mark flag; marked methods and __init__ pass."""
    got = scan_fixture("ktpu003_guarded.py")
    hits = {(v.scope, v.detail) for v in got if v.rule == "KTPU003"}
    assert ("FoldBook.bad_note", "unconfined:FoldBook.folded_rows") in hits
    assert not [v for v in got if v.scope in ("FoldBook.good_note", "FoldBook.__init__")]


def test_ktpu003_term_slab_refcount_pair():
    """The term-bank plane's fixture pair: an unlocked refcount
    release on the entry map flags (lost-update race between informer
    holders and the dispatch prologue); the locked twin and the holds()-
    marked resolve helper pass."""
    got = scan_fixture("ktpu003_term_slab.py")
    scopes = rules_by_scope(got)
    assert ("KTPU003", "TermSlab.bad_release") in scopes
    assert ("KTPU003", "TermSlab.good_release") not in scopes
    assert ("KTPU003", "TermSlab.entry_for") not in scopes


def test_ktpu003_columnar_cache_pair():
    """The columnar cache's fixture pair: an unlocked scatter-add into
    the guarded hot columns flags (lost-update race between the commit
    worker's bulk writes, the informer's scalar path, and the fold
    planner's spec-row reads); the with-block twin, the *_locked-suffix
    bulk method, and the holds()-marked delta-row gather pass."""
    got = scan_fixture("ktpu003_columns.py")
    scopes = rules_by_scope(got)
    assert ("KTPU003", "Columns.bad_assume") in scopes
    assert ("KTPU003", "Columns.good_assume") not in scopes
    assert ("KTPU003", "Columns.assume_bulk_locked") not in scopes
    assert ("KTPU003", "Columns.delta_rows") not in scopes


def test_columns_module_clean_in_tree():
    """The REAL columnar cache module: every guarded column access in
    state/columns.py must satisfy KTPU003 (locked, *_locked, or holds)
    — the tree scan must be clean on it."""
    path = os.path.join(_REPO, "kubernetes_tpu", "state", "columns.py")
    mod = load_module(path, _REPO)
    got = run_checkers(mod, repo_config(), ALL_CHECKERS)
    assert not [v.render() for v in got], [v.render() for v in got]


def test_terms_plane_is_resident_surface_in_tree():
    """The REAL term plane is a KTPU002 resident-surface module (its
    device dicts must never be forced outside the designated sync
    points) and the tree scan must be clean on it."""
    cfg = repo_config()
    assert any("kubernetes_tpu/terms_plane/" in p for p in cfg.surface_prefixes)
    for name in ("stage.py", "bank.py", "gather.py"):
        path = os.path.join(_REPO, "kubernetes_tpu", "terms_plane", name)
        mod = load_module(path, _REPO)
        got = run_checkers(mod, cfg, ALL_CHECKERS)
        assert not [v.render() for v in got], name


def test_ktpu004_flags_hot_path_sync():
    got = scan_fixture("ktpu004_hot_sync.py")
    scopes = rules_by_scope(got)
    assert ("KTPU004", "bad_dispatch") in scopes
    assert ("KTPU004", "good_dispatch") not in scopes  # shape probe is free
    assert ("KTPU004", "cold_fetch") not in scopes  # not hot-marked


def test_ktpu004_monitor_census_fixture_pair():
    """The health monitor's fixture pair (obs/introspect): a census that
    FORCES a device value from the hot-path-marked monitor refresh must
    flag KTPU004, its unlocked write to the monitor's guarded mailbox
    must flag KTPU003, and the sanctioned metadata-only census (shape
    probes, host counters, locked mailbox write) must stay clean."""
    got = scan_fixture("ktpu004_monitor_census.py")
    bad = [v for v in got if "bad_census" in v.scope]
    assert any(v.rule == "KTPU004" for v in bad), [v.render() for v in got]
    assert any(
        v.rule == "KTPU003" and "last_census" in v.detail for v in bad
    ), [v.render() for v in got]
    assert not [v for v in got if "good_census" in v.scope], [
        v.render() for v in got if "good_census" in v.scope
    ]


def test_ktpu005_flags_shadowed_bucket_import():
    """The seed `_bucket` UnboundLocalError (broke warmup for every
    enable_preemption=False drain), plus the generalized shadow."""
    got = scan_fixture("ktpu005_shadowed_bucket.py")
    details = {(v.scope, v.detail) for v in got if v.rule == "KTPU005"}
    assert ("bad_warm", "use-before-local-import:_bucket") in details
    assert ("shadow_only", "shadowed-import:_bucket") in details
    assert not [v for v in got if v.scope == "good_local_import"]


# ---------------------------------------------------------------------------
# annotations + baseline mechanics
# ---------------------------------------------------------------------------

def test_annotation_grammar():
    ann = parse_annotations([
        "x = 1  # ktpu: guarded-by(self._lock)",
        "# ktpu: holds(self._lock) callers are locked",
        "y = 2  # ktpu: allow(KTPU003) reviewed 2026-08; hot-path",
        "plain = 3  # ordinary comment",
    ])
    assert ann[1][0].kind == "guarded-by" and ann[1][0].args == ("self._lock",)
    assert ann[2][0].kind == "holds" and "locked" in ann[2][0].reason
    kinds = {a.kind for a in ann[3]}
    assert kinds == {"allow", "hot-path"}
    assert 4 not in ann


def _vio(rule="KTPU001", path="a.py", scope="f", detail="jax.jit"):
    return Violation(rule=rule, path=path, line=1, scope=scope,
                     detail=detail, message="m")


def test_baseline_grow_fail_and_ratchet(tmp_path):
    base_path = str(tmp_path / "baseline.txt")
    v1, v2 = _vio(scope="f"), _vio(scope="g")
    Baseline({}).save(base_path, [v1])
    base = Baseline.load(base_path)
    # justification text survives the round-trip
    assert list(base.entries.values()) == ["JUSTIFY ME"]
    assert base.missing([v1]) == []           # unchanged set: pass
    assert base.missing([v1, v2]) == [v2]     # the set GREW: fail closed
    assert base.stale([]) == [v1.fingerprint()]  # fixed: ratchet down


def test_baseline_fingerprint_is_line_free():
    a = Violation("KTPU001", "a.py", 10, "f", "jax.jit", "m")
    b = Violation("KTPU001", "a.py", 99, "f", "jax.jit", "m")
    assert a.fingerprint() == b.fingerprint()


# ---------------------------------------------------------------------------
# the tree gate (tier-1 twin of `scripts/ktpu_lint.py --check`)
# ---------------------------------------------------------------------------

def test_tree_scan_does_not_grow_beyond_baseline():
    violations = scan_paths(
        [os.path.join(_REPO, "kubernetes_tpu")], _REPO, repo_config(), ALL_CHECKERS
    )
    base = Baseline.load(
        os.path.join(_REPO, "kubernetes_tpu", "analysis", "baseline.txt")
    )
    new = base.missing(violations)
    assert not new, "NEW lint violations beyond the baseline:\n" + "\n".join(
        v.render() for v in new
    )


def test_cli_check_exits_zero():
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "scripts", "ktpu_lint.py"), "--check"],
        capture_output=True, text=True, cwd=_REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_update_baseline_refuses_filtered_scan(tmp_path):
    """--update-baseline over a --rule/path-filtered scan would rewrite
    the baseline to the filtered SUBSET, silently dropping every other
    entry and its justification — it must refuse instead."""
    scratch = str(tmp_path / "baseline.txt")
    lint = os.path.join(_REPO, "scripts", "ktpu_lint.py")
    for extra in (["--rule", "KTPU003"], ["kubernetes_tpu/state"]):
        proc = subprocess.run(
            [sys.executable, lint, "--update-baseline", "--baseline", scratch]
            + extra,
            capture_output=True, text=True, cwd=_REPO,
        )
        assert proc.returncode == 2, proc.stdout + proc.stderr
        assert not os.path.exists(scratch)


def test_perf_table_docs_not_drifted():
    """PERF.md/README must render from BENCH_DETAILS.json (VERDICT r5's
    doc-drift complaint) — the --check travels with pytest, not a
    separate workflow."""
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "scripts", "gen_perf_table.py"),
         "--check"],
        capture_output=True, text=True, cwd=_REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


# ---------------------------------------------------------------------------
# runtime lock-order harness
# ---------------------------------------------------------------------------

@pytest.fixture
def audit_registry(monkeypatch):
    monkeypatch.setenv("KTPU_LOCK_AUDIT", "1")
    from kubernetes_tpu.analysis.lockorder import REGISTRY

    REGISTRY.reset()
    yield REGISTRY
    REGISTRY.reset()


def test_lockorder_detects_deliberate_abba(audit_registry):
    """The classic ABBA deadlock, serialized so the test itself cannot
    hang: thread 1 nests A→B, thread 2 nests B→A; the edge graph must
    contain the cycle."""
    from kubernetes_tpu.analysis.lockorder import LockOrderViolation, audited_lock

    a, b = audited_lock("lockA"), audited_lock("lockB")

    def t1():
        with a:
            with b:
                pass

    def t2():
        with b:
            with a:
                pass

    for fn in (t1, t2):
        th = threading.Thread(target=fn)
        th.start()
        th.join()
    with pytest.raises(LockOrderViolation) as exc:
        audit_registry.assert_acyclic()
    assert "lockA" in str(exc.value) and "lockB" in str(exc.value)
    assert audit_registry.find_cycles()


def test_lockorder_clean_ordering_passes(audit_registry):
    from kubernetes_tpu.analysis.lockorder import audited_condition, audited_rlock

    q = audited_condition("queueX")
    s = audited_rlock("stageX")

    def informer():
        with q:  # queue → stage, the package's documented order
            with s:
                pass

    th = threading.Thread(target=informer, name="informer")
    th.start()
    th.join()
    with q:
        with s:
            pass
    audit_registry.assert_acyclic()
    rep = audit_registry.report()
    assert "queueX -> stageX" in rep["edges"]
    assert "informer" in rep["edges"]["queueX -> stageX"]["thread"]


def test_lockorder_condition_reentrant_like_threading(audit_registry):
    """threading.Condition()'s default underlying lock is an RLock; the
    audited twin must keep identical reentrancy semantics or enabling
    the audit changes what deadlocks."""
    from kubernetes_tpu.analysis.lockorder import audited_condition

    c = audited_condition("reentC")
    with c:
        with c:  # deadlocks (test hangs) if the inner lock is not an RLock
            pass
    audit_registry.assert_acyclic()


def test_lockorder_rlock_reentrancy_no_self_edge(audit_registry):
    from kubernetes_tpu.analysis.lockorder import audited_rlock

    r = audited_rlock("reent")
    with r:
        with r:  # same INSTANCE: reentrant, no edge
            pass
    audit_registry.assert_acyclic()
    assert not audit_registry.report()["edges"]


def test_lockorder_condition_wait_releases_held(audit_registry):
    """A waiter holds nothing: edges acquired by the notifier while the
    waiter sleeps must not point backwards through the waiting lock."""
    from kubernetes_tpu.analysis.lockorder import audited_condition, audited_lock

    c = audited_condition("condQ")
    other = audited_lock("other")
    woke = threading.Event()

    def waiter():
        with c:
            c.wait(timeout=5)
            woke.set()

    th = threading.Thread(target=waiter)
    th.start()
    # give the waiter time to enter wait(), then take the other lock and
    # notify from under it — with the waiter's lock properly released,
    # no other→condQ edge from THIS thread's nesting can form a cycle
    import time

    time.sleep(0.1)
    with other:
        with c:
            c.notify()
    th.join(timeout=5)
    assert woke.is_set()
    audit_registry.assert_acyclic()


def test_lockorder_disabled_returns_plain_primitives(monkeypatch):
    monkeypatch.delenv("KTPU_LOCK_AUDIT", raising=False)
    from kubernetes_tpu.analysis.lockorder import audited_lock

    lk = audited_lock("plain")
    assert type(lk) is type(threading.Lock())
