"""ktpu-lint + lock-order harness coverage (tier-1, CPU-only, no bench).

Four layers:
  * fixture corpus — each KTPU rule has a must-flag fixture reproducing
    the historical bug it is the static twin of, and a must-not-flag
    twin exercising the sanctioned pattern/annotation (KTPU006–008 ride
    the repo-wide call graph: their fixtures build a one-file graph);
  * the call graph + role inference — resolution units (cross-module
    imports, self-method dispatch, typed attributes, Thread(target=...)
    indirection) and lock-role inference;
  * the tree gate — the full kubernetes_tpu/ scan (module rules AND the
    interprocedural KTPU006–008) must not grow beyond the checked-in
    baseline (the same gate preflight runs), and the PERF.md/README
    bench table must match BENCH_DETAILS.json (gen_perf_table --check);
  * the runtime lock-order + thread-role harness — deliberate ABBA
    deadlock fixture detected, clean ordering passes, reentrancy and
    condition-wait bookkeeping correct, and the role audit's
    assert_roles_subset contract (observed ⊆ static, non-empty). (The
    audited full smoke drains live in test_perf_smoke with
    KTPU_LOCK_AUDIT=1.)
"""

import json
import os
import subprocess
import sys
import textwrap
import threading

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_FIXTURES = os.path.join(_REPO, "tests", "fixtures", "lint")

from kubernetes_tpu.analysis import (  # noqa: E402
    AnalysisConfig,
    Baseline,
    load_module,
    run_checkers,
    scan_paths,
)
from kubernetes_tpu.analysis.checkers import ALL_CHECKERS, repo_config  # noqa: E402
from kubernetes_tpu.analysis.core import Violation, parse_annotations  # noqa: E402
from kubernetes_tpu.analysis import callgraph as cg  # noqa: E402
from kubernetes_tpu.analysis import roles as roles_mod  # noqa: E402


def fixture_config() -> AnalysisConfig:
    """Fixtures are treated as both jit-restricted AND resident-surface
    modules so every rule applies to them."""
    return AnalysisConfig(
        jit_allowed_prefixes=(),
        surface_prefixes=("tests/fixtures/lint/",),
        sync_allowlist=(
            "Mirror.device_bank_divergence",
            "Recorder.resolve_pending",
        ),
    )


def scan_fixture(name: str):
    mod = load_module(os.path.join(_FIXTURES, name), _REPO)
    return run_checkers(mod, fixture_config(), ALL_CHECKERS)


def repo_fixture_config() -> AnalysisConfig:
    """Config for the interprocedural fixtures (KTPU006–008)."""
    return AnalysisConfig(
        surface_prefixes=("tests/fixtures/lint/",),
        sync_allowlist=("fetch_results",),
    )


def scan_repo_fixture(name: str):
    """Run ONLY the repo-wide rules over a one-file graph."""
    graph = cg.load_graph([os.path.join(_FIXTURES, name)], _REPO)
    return roles_mod.run_repo_checkers(graph, repo_fixture_config())


def graph_from_sources(tmp_path, files):
    """Write {relpath: source} under tmp_path and build a RepoGraph."""
    paths = []
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
        paths.append(str(p))
    return cg.load_graph(paths, str(tmp_path))


def rules_by_scope(violations):
    return {(v.rule, v.scope) for v in violations}


# ---------------------------------------------------------------------------
# fixture corpus: must-flag / must-not-flag per rule
# ---------------------------------------------------------------------------

def test_ktpu001_flags_unplanned_jit():
    """PR 4's invisible patch-program compile: a jit factory with no plan
    admission in scope must flag."""
    got = scan_fixture("ktpu001_unplanned_jit.py")
    hits = [v for v in got if v.rule == "KTPU001"]
    assert hits and hits[0].scope.startswith("scatter_fn")


def test_ktpu001_passes_planned_and_annotated_jit():
    got = scan_fixture("ktpu001_planned_jit.py")
    assert not [v for v in got if v.rule == "KTPU001"], [v.render() for v in got]


def test_ktpu002_flags_use_after_donate():
    got = scan_fixture("ktpu002_use_after_donate.py")
    hits = [v for v in got if v.rule == "KTPU002" and "use-after-donate" in v.detail]
    assert hits and hits[0].scope == "bad_apply"
    # the rebind idiom must NOT flag
    assert not [v for v in got if v.scope == "good_apply"]


def test_ktpu002_flags_host_sync_on_resident():
    """PR 4's np.asarray-on-sharded bug: direct host view of a resident
    array flags; the allowlisted sync point and the annotated line do
    not."""
    got = scan_fixture("ktpu002_sync_on_resident.py")
    scopes = rules_by_scope(got)
    assert ("KTPU002", "Mirror.bad_probe") in scopes
    assert ("KTPU002", "Mirror.device_bank_divergence") not in scopes
    assert ("KTPU002", "Mirror.annotated_probe") not in scopes


def test_ktpu002_flags_forcing_span_resolver():
    """The flight recorder's two-phase device-timing idiom: blocking on a
    parked handle in a NON-allowlisted resolver flags; the sanctioned
    `resolve_pending` twin (sync_allowlist) does not."""
    got = scan_fixture("ktpu002_span_resolver.py")
    scopes = rules_by_scope(got)
    assert ("KTPU002", "Recorder.eager_resolve") in scopes
    assert ("KTPU002", "Recorder.resolve_pending") not in scopes


def test_ktpu002_obs_resolver_allowlisted_in_tree():
    """The REAL recorder module is a resident-surface module and its
    resolver is in the repo allowlist — the tree scan must be clean on
    obs/ (a forcing call added anywhere else in obs/ would flag)."""
    cfg = repo_config()
    assert any("kubernetes_tpu/obs/" in p for p in cfg.surface_prefixes)
    assert "FlightRecorder.resolve_pending" in cfg.sync_allowlist
    path = os.path.join(_REPO, "kubernetes_tpu", "obs", "recorder.py")
    mod = load_module(path, _REPO)
    got = run_checkers(mod, cfg, ALL_CHECKERS)
    assert not [v.render() for v in got if v.rule in ("KTPU002", "KTPU004")]


def test_ktpu004_fault_injection_site_idiom():
    """The fault plane's injection-site contract: a site that forces a
    device value to decide whether to fire inside a hot-path dispatch
    flags; the attribute-read + counted-raise idiom does not."""
    got = scan_fixture("ktpu004_fault_site.py")
    scopes = rules_by_scope(got)
    assert ("KTPU004", "Dispatcher.bad_dispatch") in scopes
    assert ("KTPU004", "Dispatcher.good_dispatch") not in scopes


def test_ktpu003_flags_unlocked_guarded_access():
    """PR 5's unlocked vocab-slot interning: guarded attr accessed outside
    the lock flags; with-block, _locked suffix and holds() pass."""
    got = scan_fixture("ktpu003_guarded.py")
    scopes = rules_by_scope(got)
    assert ("KTPU003", "SlotTable.bad_slot_of") in scopes
    assert ("KTPU003", "SlotTable.good_slot_of") not in scopes
    assert ("KTPU003", "SlotTable._drain_locked") not in scopes
    assert ("KTPU003", "SlotTable._helper") not in scopes


def test_ktpu003_confined_requires_matching_mark():
    """confined() declares lock-FREE single-thread state (the mirror's
    fold bookkeeping): accesses from methods without the matching
    confined mark flag; marked methods and __init__ pass."""
    got = scan_fixture("ktpu003_guarded.py")
    hits = {(v.scope, v.detail) for v in got if v.rule == "KTPU003"}
    assert ("FoldBook.bad_note", "unconfined:FoldBook.folded_rows") in hits
    assert not [v for v in got if v.scope in ("FoldBook.good_note", "FoldBook.__init__")]


def test_ktpu003_term_slab_refcount_pair():
    """The term-bank plane's fixture pair: an unlocked refcount
    release on the entry map flags (lost-update race between informer
    holders and the dispatch prologue); the locked twin and the holds()-
    marked resolve helper pass."""
    got = scan_fixture("ktpu003_term_slab.py")
    scopes = rules_by_scope(got)
    assert ("KTPU003", "TermSlab.bad_release") in scopes
    assert ("KTPU003", "TermSlab.good_release") not in scopes
    assert ("KTPU003", "TermSlab.entry_for") not in scopes


def test_ktpu003_columnar_cache_pair():
    """The columnar cache's fixture pair: an unlocked scatter-add into
    the guarded hot columns flags (lost-update race between the commit
    worker's bulk writes, the informer's scalar path, and the fold
    planner's spec-row reads); the with-block twin, the *_locked-suffix
    bulk method, and the holds()-marked delta-row gather pass."""
    got = scan_fixture("ktpu003_columns.py")
    scopes = rules_by_scope(got)
    assert ("KTPU003", "Columns.bad_assume") in scopes
    assert ("KTPU003", "Columns.good_assume") not in scopes
    assert ("KTPU003", "Columns.assume_bulk_locked") not in scopes
    assert ("KTPU003", "Columns.delta_rows") not in scopes


def test_columns_module_clean_in_tree():
    """The REAL columnar cache module: every guarded column access in
    state/columns.py must satisfy KTPU003 (locked, *_locked, or holds)
    — the tree scan must be clean on it."""
    path = os.path.join(_REPO, "kubernetes_tpu", "state", "columns.py")
    mod = load_module(path, _REPO)
    got = run_checkers(mod, repo_config(), ALL_CHECKERS)
    assert not [v.render() for v in got], [v.render() for v in got]


def test_terms_plane_is_resident_surface_in_tree():
    """The REAL term plane is a KTPU002 resident-surface module (its
    device dicts must never be forced outside the designated sync
    points) and the tree scan must be clean on it."""
    cfg = repo_config()
    assert any("kubernetes_tpu/terms_plane/" in p for p in cfg.surface_prefixes)
    for name in ("stage.py", "bank.py", "gather.py"):
        path = os.path.join(_REPO, "kubernetes_tpu", "terms_plane", name)
        mod = load_module(path, _REPO)
        got = run_checkers(mod, cfg, ALL_CHECKERS)
        assert not [v.render() for v in got], name


def test_ktpu004_flags_hot_path_sync():
    got = scan_fixture("ktpu004_hot_sync.py")
    scopes = rules_by_scope(got)
    assert ("KTPU004", "bad_dispatch") in scopes
    assert ("KTPU004", "good_dispatch") not in scopes  # shape probe is free
    assert ("KTPU004", "cold_fetch") not in scopes  # not hot-marked


def test_ktpu004_monitor_census_fixture_pair():
    """The health monitor's fixture pair (obs/introspect): a census that
    FORCES a device value from the hot-path-marked monitor refresh must
    flag KTPU004, its unlocked write to the monitor's guarded mailbox
    must flag KTPU003, and the sanctioned metadata-only census (shape
    probes, host counters, locked mailbox write) must stay clean."""
    got = scan_fixture("ktpu004_monitor_census.py")
    bad = [v for v in got if "bad_census" in v.scope]
    assert any(v.rule == "KTPU004" for v in bad), [v.render() for v in got]
    assert any(
        v.rule == "KTPU003" and "last_census" in v.detail for v in bad
    ), [v.render() for v in got]
    assert not [v for v in got if "good_census" in v.scope], [
        v.render() for v in got if "good_census" in v.scope
    ]


def test_ktpu005_flags_shadowed_bucket_import():
    """The seed `_bucket` UnboundLocalError (broke warmup for every
    enable_preemption=False drain), plus the generalized shadow."""
    got = scan_fixture("ktpu005_shadowed_bucket.py")
    details = {(v.scope, v.detail) for v in got if v.rule == "KTPU005"}
    assert ("bad_warm", "use-before-local-import:_bucket") in details
    assert ("shadow_only", "shadowed-import:_bucket") in details
    assert not [v for v in got if v.scope == "good_local_import"]


# ---------------------------------------------------------------------------
# interprocedural fixture corpus (KTPU006–008 over the call graph)
# ---------------------------------------------------------------------------

def test_ktpu006_flags_unannotated_shared_attr():
    """The unannotated uploader→driver attribute KTPU003 cannot see:
    written on one role, read on another, no guarded-by/confined."""
    got = scan_repo_fixture("ktpu006_shared_attr.py")
    details = {v.detail for v in got if v.rule == "KTPU006"}
    assert "shared:Bank.report_generation" in details
    # declared, ctor-only, and allow(KTPU006)-justified attrs stay clean
    assert not {d for d in details if "declared_rows" in d}
    assert not {d for d in details if "ctor_only" in d}
    assert not {d for d in details if "handoff" in d}


def test_ktpu007_flags_transitive_hot_sync():
    """hot-path → helper → np.asarray(dev) one call deep (the KTPU004
    hole); the allowlisted sync point is a traversal barrier and
    host-only chains are free."""
    got = scan_repo_fixture("ktpu007_hot_chain.py")
    hits = {(v.scope, v.detail) for v in got if v.rule == "KTPU007"}
    assert ("hot_dispatch", "hot-reach:hot_dispatch->_summarize") in hits
    scopes = {v.scope for v in got if v.rule == "KTPU007"}
    assert "hot_via_syncpoint" not in scopes
    assert "hot_host_only" not in scopes
    assert "cold_dispatch" not in scopes


def test_ktpu008_flags_confined_reach_and_unrooted_spawn():
    """A confined(driver) method reached by the monitor role flags (the
    claim was purely syntactic before); a Thread spawn with no
    thread-entry root flags; the driver-only confined method and the
    mailbox read stay clean."""
    got = scan_repo_fixture("ktpu008_confined_reach.py")
    details = {v.detail for v in got if v.rule == "KTPU008"}
    assert "confined-reach:Mirror.census" in details
    assert any(d.startswith("unrooted-spawn:") for d in details)
    assert "confined-reach:Mirror.fold_rows" not in details
    scopes = {v.scope for v in got if "confined-reach" in v.detail}
    assert "Monitor.read_mailbox" not in scopes


# ---------------------------------------------------------------------------
# call graph resolution + role propagation units
# ---------------------------------------------------------------------------

def test_callgraph_resolves_cross_module_imports(tmp_path):
    graph = graph_from_sources(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/a.py": """
            def helper():
                return 1
        """,
        "pkg/b.py": """
            from .a import helper

            def caller():
                return helper()
        """,
    })
    caller = graph.functions["pkg/b.py::caller"]
    dsts = {e.dst for e in graph.callees(caller.uid)}
    assert "pkg/a.py::helper" in dsts


def test_callgraph_resolves_self_method_dispatch_and_subclass(tmp_path):
    """self.m() dispatches through the class family: the base's caller
    links to the base method AND the subclass override (the receiver may
    be either — StageBank/TermBankDevice)."""
    graph = graph_from_sources(tmp_path, {
        "m.py": """
            class Base:
                def run(self):
                    return self.step()

                def step(self):
                    return 0

            class Sub(Base):
                def step(self):
                    return 1
        """,
    })
    run = graph.functions["m.py::Base.run"]
    dsts = {e.dst for e in graph.callees(run.uid)}
    assert "m.py::Base.step" in dsts and "m.py::Sub.step" in dsts


def test_callgraph_resolves_typed_attribute_receiver(tmp_path):
    """`self.queue.pop()`-style chains resolve through the inferred
    attribute type (ctor `self.q = Queue()`), NOT by name over every
    class defining the method."""
    graph = graph_from_sources(tmp_path, {
        "m.py": """
            class Queue:
                def pop_next(self):
                    return None

            class Decoy:
                def pop_next(self):
                    return "wrong"

            class Driver:
                def __init__(self):
                    self.q = Queue()

                def cycle(self):
                    return self.q.pop_next()
        """,
    })
    cyc = graph.functions["m.py::Driver.cycle"]
    dsts = {e.dst for e in graph.callees(cyc.uid)}
    assert "m.py::Queue.pop_next" in dsts
    assert "m.py::Decoy.pop_next" not in dsts


def test_role_propagation_through_thread_target(tmp_path):
    """thread-entry on a Thread(target=self._loop) spawn line seeds the
    RESOLVED target; roles then propagate through its call chain."""
    graph = graph_from_sources(tmp_path, {
        "m.py": """
            import threading

            class Worker:
                def start(self):
                    # ktpu: thread-entry(pump)
                    threading.Thread(target=self._loop).start()

                def _loop(self):
                    self._step()

                def _step(self):
                    pass
        """,
    })
    analysis = roles_mod.RoleAnalysis(graph, AnalysisConfig())
    assert analysis.roles_of("m.py::Worker._loop") == {"pump"}
    assert analysis.roles_of("m.py::Worker._step") == {"pump"}
    assert analysis.roles_of("m.py::Worker.start") == set()


def test_static_lock_roles_inference(tmp_path):
    """audited_lock("q") constructed by a class credits the lock role
    with every role reaching the class's methods — and the alias idiom
    (`self._lock = stage._lock` through an annotated param) unions the
    source's roles."""
    graph = graph_from_sources(tmp_path, {
        "m.py": """
            from kubernetes_tpu.analysis.lockorder import audited_lock

            class Stage:
                def __init__(self):
                    self._lock = audited_lock("q")

                # ktpu: thread-entry(feeder)
                def feed(self):
                    with self._lock:
                        pass

            class Bank:
                def __init__(self, stage: Stage):
                    self._lock = stage._lock

                # ktpu: thread-entry(shipper)
                def ship(self):
                    with self._lock:
                        pass
        """,
    })
    analysis = roles_mod.RoleAnalysis(graph, AnalysisConfig())
    locks = roles_mod.static_lock_roles(analysis)
    assert {"feeder", "shipper"} <= locks["q"]
    # omni roles always present, role-universal
    assert locks["metric"] == {"*"}


# ---------------------------------------------------------------------------
# runtime role audit (assert_roles_subset)
# ---------------------------------------------------------------------------

def test_roles_subset_pass_and_fail(audit_registry):
    from kubernetes_tpu.analysis.lockorder import (
        RoleAuditViolation,
        audited_lock,
        register_thread_role,
    )

    lk = audited_lock("roleQ")
    mt = audited_lock("metric")

    def as_role(role, lock):
        def body():
            register_thread_role(role)
            with lock:
                pass
        th = threading.Thread(target=body)
        th.start()
        th.join()

    as_role("driver", lk)
    as_role("informer", lk)
    as_role("health", mt)
    obs = audit_registry.observed_roles()
    assert obs["roleQ"] == {"driver", "informer"}
    # subset holds (omni "*" covers the metric lock)
    audit_registry.assert_roles_subset(
        {"roleQ": {"driver", "informer", "bind"}, "metric": {"*"}}
    )
    # an observed role the static inference missed fails loudly
    with pytest.raises(RoleAuditViolation) as exc:
        audit_registry.assert_roles_subset(
            {"roleQ": {"driver"}, "metric": {"*"}}
        )
    assert "informer" in str(exc.value)


def test_roles_subset_requires_nonempty_graph(audit_registry):
    """Silently unwiring register_thread_role must fail exactly like the
    lock-audit's non-empty-edge assertion."""
    from kubernetes_tpu.analysis.lockorder import (
        RoleAuditViolation,
        audited_lock,
    )

    lk = audited_lock("quietQ")
    with lk:  # acquisitions happen, but no thread ever registered a role
        pass
    with pytest.raises(RoleAuditViolation):
        audit_registry.assert_roles_subset({"quietQ": {"driver"}})


def test_runtime_static_roles_covers_core_lock_roles():
    """The installed tree's inferred lock-role map — what perf_smoke's
    assert_roles_subset compares against — must credit the core plane
    locks with the roles that really touch them (a regression here would
    make the runtime probe fail on the next smoke drain)."""
    static = roles_mod.runtime_static_roles()
    assert "driver" in static.get("queue", set())
    assert "bind" in static.get("queue", set())       # requeue_backoff
    assert "driver" in static.get("cache", set())
    assert "bind" in static.get("cache", set())       # finish_binding
    assert {"driver", "ingest-upload"} <= static.get("stage", set())
    assert "warmup" in static.get("compile-plan", set())
    assert "health" in static.get("health", set())
    assert "*" in static.get("metric", set())         # omni by declaration


# ---------------------------------------------------------------------------
# CLI: --json + per-rule timings + the lint-time budget
# ---------------------------------------------------------------------------

def test_cli_json_report_shape():
    """--json emits one object with rule/file/line/message/fingerprint
    per violation plus per-rule wall timings (all 8 rules + the shared
    callgraph build) — the machine-readable face preflight's budget
    gate and dashboards consume. Scans one small subtree: every rule's
    timer still runs, and the full-tree gate has its own tests."""
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "scripts", "ktpu_lint.py"),
         "--check", "--json", "kubernetes_tpu/obs"],
        capture_output=True, text=True, cwd=_REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)
    assert doc["ok"] is True and doc["violations"] == []
    for rule in ("KTPU001", "KTPU002", "KTPU003", "KTPU004", "KTPU005",
                 "KTPU006", "KTPU007", "KTPU008", "callgraph"):
        assert rule in doc["timings_s"], rule
    assert doc["total_s"] > 0


def test_cli_time_budget_exceeded_exits_3():
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "scripts", "ktpu_lint.py"),
         "--check", "--json", "--time-budget", "0.000001",
         "kubernetes_tpu/obs"],
        capture_output=True, text=True, cwd=_REPO,
    )
    assert proc.returncode == 3, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)
    assert doc["budget_exceeded"] is True and doc["ok"] is False


def test_new_rule_fingerprints_ride_the_ratchet(tmp_path):
    """KTPU006–008 violations integrate with the baseline exactly like
    the module rules: line-free fingerprints, grow-fails, fixed
    violations ratchet down as stale."""
    got = scan_repo_fixture("ktpu006_shared_attr.py")
    v = next(v for v in got if v.rule == "KTPU006")
    moved = Violation(v.rule, v.path, v.line + 40, v.scope, v.detail, v.message)
    assert v.fingerprint() == moved.fingerprint()
    base_path = str(tmp_path / "baseline.txt")
    Baseline({}).save(base_path, [v])
    base = Baseline.load(base_path)
    assert base.missing([v]) == []
    extra = Violation("KTPU008", "x.py", 1, "S.m", "confined-reach:S.m", "m")
    assert base.missing([v, extra]) == [extra]
    assert base.stale([]) == [v.fingerprint()]


# ---------------------------------------------------------------------------
# annotations + baseline mechanics
# ---------------------------------------------------------------------------

def test_annotation_grammar():
    ann = parse_annotations([
        "x = 1  # ktpu: guarded-by(self._lock)",
        "# ktpu: holds(self._lock) callers are locked",
        "y = 2  # ktpu: allow(KTPU003) reviewed 2026-08; hot-path",
        "plain = 3  # ordinary comment",
        "# ktpu: thread-entry(ingest-upload, terms-upload) uploader loop",
    ])
    assert ann[1][0].kind == "guarded-by" and ann[1][0].args == ("self._lock",)
    assert ann[2][0].kind == "holds" and "locked" in ann[2][0].reason
    kinds = {a.kind for a in ann[3]}
    assert kinds == {"allow", "hot-path"}
    assert 4 not in ann
    te = ann[5][0]
    assert te.kind == "thread-entry"
    assert te.args == ("ingest-upload", "terms-upload")


def _vio(rule="KTPU001", path="a.py", scope="f", detail="jax.jit"):
    return Violation(rule=rule, path=path, line=1, scope=scope,
                     detail=detail, message="m")


def test_baseline_grow_fail_and_ratchet(tmp_path):
    base_path = str(tmp_path / "baseline.txt")
    v1, v2 = _vio(scope="f"), _vio(scope="g")
    Baseline({}).save(base_path, [v1])
    base = Baseline.load(base_path)
    # justification text survives the round-trip
    assert list(base.entries.values()) == ["JUSTIFY ME"]
    assert base.missing([v1]) == []           # unchanged set: pass
    assert base.missing([v1, v2]) == [v2]     # the set GREW: fail closed
    assert base.stale([]) == [v1.fingerprint()]  # fixed: ratchet down


def test_baseline_fingerprint_is_line_free():
    a = Violation("KTPU001", "a.py", 10, "f", "jax.jit", "m")
    b = Violation("KTPU001", "a.py", 99, "f", "jax.jit", "m")
    assert a.fingerprint() == b.fingerprint()


# ---------------------------------------------------------------------------
# the tree gate (tier-1 twin of `scripts/ktpu_lint.py --check`)
# ---------------------------------------------------------------------------

def test_tree_scan_does_not_grow_beyond_baseline():
    """Module rules AND the interprocedural KTPU006–008: the full tree
    must stay at 0 violations with the baseline still empty."""
    violations = scan_paths(
        [os.path.join(_REPO, "kubernetes_tpu")], _REPO, repo_config(), ALL_CHECKERS
    ) + roles_mod.scan_repo_rules(
        [os.path.join(_REPO, "kubernetes_tpu")], _REPO, repo_config()
    )
    base = Baseline.load(
        os.path.join(_REPO, "kubernetes_tpu", "analysis", "baseline.txt")
    )
    new = base.missing(violations)
    assert not new, "NEW lint violations beyond the baseline:\n" + "\n".join(
        v.render() for v in new
    )


def test_cli_check_exits_zero():
    """CLI plumbing (arg parsing → scan → baseline → exit code) on a
    small subtree; the FULL-tree gate runs in-process above
    (test_tree_scan_does_not_grow_beyond_baseline) and as the first
    preflight stage — duplicating the whole-tree scan in a subprocess
    here cost ~10s of tier-1 wall for no extra coverage."""
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "scripts", "ktpu_lint.py"),
         "--check", "kubernetes_tpu/analysis"],
        capture_output=True, text=True, cwd=_REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_update_baseline_refuses_filtered_scan(tmp_path):
    """--update-baseline over a --rule/path-filtered scan would rewrite
    the baseline to the filtered SUBSET, silently dropping every other
    entry and its justification — it must refuse instead."""
    scratch = str(tmp_path / "baseline.txt")
    lint = os.path.join(_REPO, "scripts", "ktpu_lint.py")
    for extra in (["--rule", "KTPU003"], ["kubernetes_tpu/state"]):
        proc = subprocess.run(
            [sys.executable, lint, "--update-baseline", "--baseline", scratch]
            + extra,
            capture_output=True, text=True, cwd=_REPO,
        )
        assert proc.returncode == 2, proc.stdout + proc.stderr
        assert not os.path.exists(scratch)


def test_perf_table_docs_not_drifted():
    """PERF.md/README must render from BENCH_DETAILS.json (VERDICT r5's
    doc-drift complaint) — the --check travels with pytest, not a
    separate workflow."""
    proc = subprocess.run(
        [sys.executable, os.path.join(_REPO, "scripts", "gen_perf_table.py"),
         "--check"],
        capture_output=True, text=True, cwd=_REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


# ---------------------------------------------------------------------------
# runtime lock-order harness
# ---------------------------------------------------------------------------

@pytest.fixture
def audit_registry(monkeypatch):
    monkeypatch.setenv("KTPU_LOCK_AUDIT", "1")
    from kubernetes_tpu.analysis.lockorder import REGISTRY

    REGISTRY.reset()
    yield REGISTRY
    REGISTRY.reset()


def test_lockorder_detects_deliberate_abba(audit_registry):
    """The classic ABBA deadlock, serialized so the test itself cannot
    hang: thread 1 nests A→B, thread 2 nests B→A; the edge graph must
    contain the cycle."""
    from kubernetes_tpu.analysis.lockorder import LockOrderViolation, audited_lock

    a, b = audited_lock("lockA"), audited_lock("lockB")

    def t1():
        with a:
            with b:
                pass

    def t2():
        with b:
            with a:
                pass

    for fn in (t1, t2):
        th = threading.Thread(target=fn)
        th.start()
        th.join()
    with pytest.raises(LockOrderViolation) as exc:
        audit_registry.assert_acyclic()
    assert "lockA" in str(exc.value) and "lockB" in str(exc.value)
    assert audit_registry.find_cycles()


def test_lockorder_clean_ordering_passes(audit_registry):
    from kubernetes_tpu.analysis.lockorder import audited_condition, audited_rlock

    q = audited_condition("queueX")
    s = audited_rlock("stageX")

    def informer():
        with q:  # queue → stage, the package's documented order
            with s:
                pass

    th = threading.Thread(target=informer, name="informer")
    th.start()
    th.join()
    with q:
        with s:
            pass
    audit_registry.assert_acyclic()
    rep = audit_registry.report()
    assert "queueX -> stageX" in rep["edges"]
    assert "informer" in rep["edges"]["queueX -> stageX"]["thread"]


def test_lockorder_condition_reentrant_like_threading(audit_registry):
    """threading.Condition()'s default underlying lock is an RLock; the
    audited twin must keep identical reentrancy semantics or enabling
    the audit changes what deadlocks."""
    from kubernetes_tpu.analysis.lockorder import audited_condition

    c = audited_condition("reentC")
    with c:
        with c:  # deadlocks (test hangs) if the inner lock is not an RLock
            pass
    audit_registry.assert_acyclic()


def test_lockorder_rlock_reentrancy_no_self_edge(audit_registry):
    from kubernetes_tpu.analysis.lockorder import audited_rlock

    r = audited_rlock("reent")
    with r:
        with r:  # same INSTANCE: reentrant, no edge
            pass
    audit_registry.assert_acyclic()
    assert not audit_registry.report()["edges"]


def test_lockorder_condition_wait_releases_held(audit_registry):
    """A waiter holds nothing: edges acquired by the notifier while the
    waiter sleeps must not point backwards through the waiting lock."""
    from kubernetes_tpu.analysis.lockorder import audited_condition, audited_lock

    c = audited_condition("condQ")
    other = audited_lock("other")
    woke = threading.Event()

    def waiter():
        with c:
            c.wait(timeout=5)
            woke.set()

    th = threading.Thread(target=waiter)
    th.start()
    # give the waiter time to enter wait(), then take the other lock and
    # notify from under it — with the waiter's lock properly released,
    # no other→condQ edge from THIS thread's nesting can form a cycle
    import time

    time.sleep(0.1)
    with other:
        with c:
            c.notify()
    th.join(timeout=5)
    assert woke.is_set()
    audit_registry.assert_acyclic()


def test_lockorder_disabled_returns_plain_primitives(monkeypatch):
    monkeypatch.delenv("KTPU_LOCK_AUDIT", raising=False)
    from kubernetes_tpu.analysis.lockorder import audited_lock

    lk = audited_lock("plain")
    assert type(lk) is type(threading.Lock())
