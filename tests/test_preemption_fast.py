"""Parity: the arithmetic fast victim selector vs the shadow-snapshot
oracle (select_victims_on_node) — bit-identical victims, violations, and
preempt() outcomes under the static-metadata routing preconditions."""

import random

from kubernetes_tpu.api.types import LabelSelector, PodDisruptionBudget
from kubernetes_tpu.models.generators import make_node, make_pod
from kubernetes_tpu.oracle import Snapshot
from kubernetes_tpu.scheduler.preemption import (
    _select_victims_fast,
    pick_one_node_for_preemption,
    preempt,
    select_victims_on_node,
)


def _cluster(rng, n_nodes=12, ports=False):
    nodes = [
        make_node(f"n{i}", cpu_milli=8000, mem=16 * 2**30)
        for i in range(n_nodes)
    ]
    existing = []
    k = 0
    for i in range(n_nodes):
        for _ in range(rng.randint(0, 6)):
            p = make_pod(
                f"low-{k}",
                cpu_milli=rng.choice([500, 1000, 2000, 3000]),
                mem=rng.choice([2**28, 2**30]),
                labels={"app": f"a{rng.randint(0, 3)}"},
            )
            p.priority = rng.choice([0, 0, 10, 50])
            p.creation_timestamp = rng.random() * 1000
            p.node_name = f"n{i}"
            if ports and rng.random() < 0.3:
                p.containers[0].ports = []
            existing.append(p)
            k += 1
    return nodes, existing


def _pdbs(rng):
    out = []
    for i in range(rng.randint(0, 2)):
        out.append(
            PodDisruptionBudget(
                name=f"pdb{i}",
                selector=LabelSelector(match_labels={"app": f"a{i}"}),
                disruptions_allowed=rng.choice([0, 1]),
            )
        )
    return out


def test_fast_matches_oracle_randomized():
    rng = random.Random(7)
    checked = 0
    for trial in range(40):
        nodes, existing = _cluster(rng)
        snap = Snapshot(nodes, existing)
        pdbs = _pdbs(rng)
        pre = make_pod(
            "hi",
            cpu_milli=rng.choice([4000, 6000, 7500]),
            mem=2 * 2**30,
        )
        pre.priority = 1000
        for name in snap.node_infos:
            slow = select_victims_on_node(pre, name, snap, pdbs=pdbs)
            fast = _select_victims_fast(pre, snap.get(name), pdbs, None)
            assert (slow is None) == (fast is None), (trial, name)
            if slow is None:
                continue
            checked += 1
            assert [p.key() for p in slow.pods] == [p.key() for p in fast.pods], (
                trial,
                name,
            )
            assert slow.num_pdb_violations == fast.num_pdb_violations
    assert checked > 20  # the generator actually produced preemptable nodes


def test_preempt_end_to_end_same_choice():
    """preempt() routed through the fast path must pick the same node and
    victims as a run forced down the oracle path (enabled set non-None
    disables the fast routing without changing semantics)."""
    from kubernetes_tpu.config.provider import default_predicates

    DEFAULT_PREDICATE_SET = default_predicates()
    rng = random.Random(11)
    for trial in range(10):
        nodes, existing = _cluster(rng)
        snap = Snapshot(nodes, existing)
        pdbs = _pdbs(rng)
        pre = make_pod("hi", cpu_milli=6000, mem=2 * 2**30)
        pre.priority = 1000
        fast_node, fast_victims, _ = preempt(pre, snap, pdbs=pdbs)
        slow_node, slow_victims, _ = preempt(
            pre, snap, pdbs=pdbs, enabled=DEFAULT_PREDICATE_SET
        )
        assert fast_node == slow_node, trial
        assert [p.key() for p in fast_victims] == [p.key() for p in slow_victims]


def test_device_batch_matches_sequential_host():
    """ops/preempt.preempt_batch (via batch_preempt_device) must reproduce
    the sequential host loop exactly: same chosen node and same victim set
    for every preemptor, with earlier victims' deletions visible to later
    preemptors."""
    import pytest

    pytest.importorskip("jax")
    from kubernetes_tpu.scheduler.preemption import batch_preempt_device

    rng = random.Random(23)
    for trial in range(6):
        # FULL cluster: every node packed so no preemptor ever fits free
        # (free <= 2000m everywhere; preemptors need >= 4000m)
        nodes = [make_node(f"n{i}", cpu_milli=8000, mem=16 * 2**30) for i in range(10)]
        existing = []
        k = 0
        for i in range(10):
            total = 0
            while total < 6000:
                cpu = rng.choice([1000, 1500, 2000])
                p = make_pod(f"low-{k}", cpu_milli=cpu, mem=2**28,
                             labels={"app": f"a{rng.randint(0, 3)}"})
                p.priority = rng.choice([0, 0, 10, 50])
                p.creation_timestamp = rng.random() * 1000
                p.node_name = f"n{i}"
                existing.append(p)
                total += cpu
                k += 1
        pdbs = _pdbs(rng)
        pres = []
        for i in range(12):
            p = make_pod(f"hi-{i}", cpu_milli=rng.choice([4000, 6000, 7000]),
                         mem=2 * 2**30)
            p.priority = rng.choice([100, 500, 1000])
            p.creation_timestamp = 2000 + i
            pres.append(p)

        # host sequential replay under the DRIVER contract: preemption runs
        # only for pods that fit nowhere live counting NOMINEE reservations
        # (podFitsOnNode pass-1); victim search counts them too
        # (selectVictimsOnNode :1160). Earlier preemptors' nominations
        # charge their nodes for later steps.
        from kubernetes_tpu.api.types import (
            RESOURCE_CPU,
            RESOURCE_EPHEMERAL_STORAGE,
            RESOURCE_MEMORY,
        )
        from kubernetes_tpu.oracle.nodeinfo import accumulated_request

        noms = []  # (node, preemptor)

        def charge_for(name, pod):
            tot, c = {}, 0
            for n2, p2 in noms:
                if n2 == name and p2.key() != pod.key():
                    for rn, v in accumulated_request(p2).items():
                        if rn != "pods":
                            tot[rn] = tot.get(rn, 0) + v
                    c += 1
            return (tot, c) if c else None

        def fits_on(pod, ni, charge):
            req = pod.resource_request()
            alloc = ni.node.allocatable_int()
            used = dict(ni.requested())
            count = len(ni.pods)
            if charge:
                for rn, v in charge[0].items():
                    used[rn] = used.get(rn, 0) + v
                count += charge[1]
            if count + 1 > ni.allowed_pod_number():
                return False
            if all(v == 0 for k, v in req.items() if k != "pods"):
                return True
            for rn in (RESOURCE_CPU, RESOURCE_MEMORY, RESOURCE_EPHEMERAL_STORAGE):
                if alloc.get(rn, 0) < req.get(rn, 0) + used.get(rn, 0):
                    return False
            for rn, r in req.items():
                if rn in (RESOURCE_CPU, RESOURCE_MEMORY, RESOURCE_EPHEMERAL_STORAGE, "pods"):
                    continue
                if r != 0 and alloc.get(rn, 0) < r + used.get(rn, 0):
                    return False
            return True

        snap_h = Snapshot(nodes, list(existing))
        host_plan = []
        saw_free = saw_evict = False
        for p in pres:
            if any(
                fits_on(p, ni, charge_for(nm, p))
                for nm, ni in snap_h.node_infos.items()
            ):
                host_plan.append((None, [], True))
                saw_free = True
                continue
            cands = {}
            for nm, ni in snap_h.node_infos.items():
                v = _select_victims_fast(
                    p, ni, pdbs, None, nominee_charge=charge_for(nm, p)
                )
                if v is not None:
                    cands[nm] = v
            node = pick_one_node_for_preemption(cands)
            victims = cands[node].pods if node is not None else []
            host_plan.append((node, [v.key() for v in victims], False))
            if node is not None:
                saw_evict = True
                noms.append((node, p))
                for v in victims:
                    snap_h.get(v.node_name).remove_pod(v)

        # device batch (fresh snapshot; kernel carries the deletions)
        snap_d = Snapshot(nodes, list(existing))
        plans = batch_preempt_device(pres, snap_d, pdbs=pdbs)
        assert plans is not None
        dev_plan = [(n, [v.key() for v in vs], free) for n, vs, free in plans]
        assert dev_plan == host_plan, (trial, dev_plan, host_plan)
        assert saw_evict  # the generator actually exercised eviction steps


def test_fast_path_with_ported_preemptor():
    """A hostPort-carrying preemptor routes through the fast path (ports do
    not disqualify it) — the port-conflict branch must run, not NameError
    (round-4 review finding)."""
    from kubernetes_tpu.api.types import ContainerPort

    nodes = [make_node(f"n{i}", cpu_milli=8000, mem=16 * 2**30) for i in range(4)]
    existing = []
    for i in range(4):
        p = make_pod(f"low-{i}", cpu_milli=6000, mem=2**30)
        p.priority = 0
        p.node_name = f"n{i}"
        # one low pod holds the port the preemptor wants
        if i == 0:
            p.containers[0].ports = [ContainerPort(host_port=8080, container_port=80)]
        existing.append(p)
    snap = Snapshot(nodes, existing)
    pre = make_pod("hi", cpu_milli=4000, mem=2**30)
    pre.priority = 1000
    pre.containers[0].ports = [ContainerPort(host_port=8080, container_port=80)]
    node, victims, _ = preempt(pre, snap)
    # any candidate works: evicting the 6000m victim frees both cpu AND
    # (on n0) the port — the call just must not crash and must be exact
    assert node is not None and len(victims) == 1
    v = _select_victims_fast(pre, snap.get(node), (), None)
    assert [p.key() for p in v.pods] == [p.key() for p in victims]
