"""Scheduling Framework: plugin extension points + CycleState.

Re-creates the v1alpha1 framework API surface
(pkg/scheduler/framework/v1alpha1/interface.go:190-354): QueueSort,
PreFilter (with AddPod/RemovePod extensions), Filter, PostFilter, Score
(with NormalizeScore), Reserve, Permit, PreBind, Bind, PostBind, Unreserve.

Python adaptation: plugins are duck-typed objects registering for the
extension points they implement; statuses are (code, message) tuples via the
Status class. The batch driver invokes the same hook order as scheduleOne
(scheduler.go:579-743) around the vectorized solve — plugins see one pod at
a time, exactly like upstream, so out-of-tree plugin logic ports directly.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..analysis.lockorder import audited_rlock
from ..api.types import Pod

MAX_NODE_SCORE = 10
MIN_NODE_SCORE = 0

# Status codes (interface.go:77-110)
SUCCESS = 0
ERROR = 1
UNSCHEDULABLE = 2
WAIT = 3
SKIP = 4


class Status:
    def __init__(self, code: int = SUCCESS, message: str = ""):
        self.code = code
        self.message = message

    def is_success(self) -> bool:
        return self.code == SUCCESS

    def is_unschedulable(self) -> bool:
        return self.code == UNSCHEDULABLE

    @staticmethod
    def success() -> "Status":
        return Status(SUCCESS)

    @staticmethod
    def unschedulable(msg: str = "") -> "Status":
        return Status(UNSCHEDULABLE, msg)

    @staticmethod
    def error(msg: str = "") -> "Status":
        return Status(ERROR, msg)

    def __repr__(self) -> str:
        return f"Status(code={self.code}, message={self.message!r})"


class CycleState:
    """framework.CycleState (cycle_state.go): per-scheduling-cycle KV store
    shared across a pod's plugin invocations."""

    def __init__(self):
        self._lock = audited_rlock("cycle-state")
        self._data: Dict[str, Any] = {}

    def read(self, key: str) -> Any:
        with self._lock:
            if key not in self._data:
                raise KeyError(key)
            return self._data[key]

    def write(self, key: str, value: Any) -> None:
        with self._lock:
            self._data[key] = value

    def delete(self, key: str) -> None:
        with self._lock:
            self._data.pop(key, None)

    def clone(self) -> "CycleState":
        cs = CycleState()
        cs._data = dict(self._data)
        return cs


class Plugin:
    """Base plugin: subclasses implement any subset of the hook methods.
    Presence of the method (overridden from this base) registers the plugin
    at that extension point."""

    name = "unnamed"

    # QueueSort
    def less(self, pod_info_a, pod_info_b) -> bool:
        raise NotImplementedError

    # PreFilter + extensions
    def pre_filter(self, state: CycleState, pod: Pod) -> Status:
        raise NotImplementedError

    def add_pod(self, state: CycleState, pod: Pod, pod_to_add: Pod, node_info) -> Status:
        raise NotImplementedError

    def remove_pod(self, state: CycleState, pod: Pod, pod_to_remove: Pod, node_info) -> Status:
        raise NotImplementedError

    # Filter
    def filter(self, state: CycleState, pod: Pod, node_info) -> Status:
        raise NotImplementedError

    # PostFilter (after filtering, before scoring)
    def post_filter(self, state: CycleState, pod: Pod, nodes, filtered_nodes_statuses) -> Status:
        raise NotImplementedError

    # Score + normalize
    def score(self, state: CycleState, pod: Pod, node_name: str) -> Tuple[int, Status]:
        raise NotImplementedError

    def normalize_score(self, state: CycleState, pod: Pod, scores: Dict[str, int]) -> Status:
        raise NotImplementedError

    score_weight = 1

    # Reserve / Unreserve
    def reserve(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        raise NotImplementedError

    def unreserve(self, state: CycleState, pod: Pod, node_name: str) -> None:
        raise NotImplementedError

    # Permit
    def permit(self, state: CycleState, pod: Pod, node_name: str) -> Tuple[Status, float]:
        """Returns (status, timeout_seconds); WAIT status parks the pod."""
        raise NotImplementedError

    # PreBind / Bind / PostBind
    def pre_bind(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        raise NotImplementedError

    def bind(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        raise NotImplementedError

    def post_bind(self, state: CycleState, pod: Pod, node_name: str) -> None:
        raise NotImplementedError


def _implements(plugin: Plugin, method: str) -> bool:
    return getattr(type(plugin), method, None) is not getattr(Plugin, method, None)


@dataclass
class WaitingPod:
    """waiting_pods_map.go: a pod parked by a Permit plugin."""

    pod: Pod
    deadline: float
    allowed: Optional[bool] = None  # None = still waiting
    event: threading.Event = field(default_factory=threading.Event)

    def allow(self) -> None:
        self.allowed = True
        self.event.set()

    def reject(self) -> None:
        self.allowed = False
        self.event.set()


class Framework:
    """framework.go: runs the registered plugins at each extension point."""

    def __init__(self, plugins: Optional[List[Plugin]] = None):
        self.plugins = list(plugins or [])
        self.waiting_pods: Dict[str, WaitingPod] = {}

    def _at(self, point: str) -> List[Plugin]:
        return [p for p in self.plugins if _implements(p, point)]

    def has_plugins(self, point: str) -> bool:
        """Any plugin registered at this extension point? The driver uses
        this to decide whether a pod can stay on the pure-device fast path
        (no host plugins) or must route through the host commit path where
        plugin hooks run (framework.go RunFilterPlugins/RunScorePlugins)."""
        return bool(self._at(point))

    def queue_sort_less(self):
        qs = self._at("less")
        return qs[0].less if qs else None

    def run_pre_filter(self, state: CycleState, pod: Pod) -> Status:
        for p in self._at("pre_filter"):
            s = p.pre_filter(state, pod)
            if not s.is_success():
                return s
        return Status.success()

    def run_filter(self, state: CycleState, pod: Pod, node_info) -> Status:
        for p in self._at("filter"):
            s = p.filter(state, pod, node_info)
            if not s.is_success():
                return s
        return Status.success()

    def run_post_filter(self, state: CycleState, pod: Pod, nodes, statuses) -> Status:
        for p in self._at("post_filter"):
            s = p.post_filter(state, pod, nodes, statuses)
            if not s.is_success():
                return s
        return Status.success()

    def run_scores(self, state: CycleState, pod: Pod, node_names: List[str]) -> Dict[str, int]:
        """RunScorePlugins: per-plugin map + normalize + weighted sum."""
        total = {n: 0 for n in node_names}
        for p in self._at("score"):
            scores = {}
            for n in node_names:
                sc, st = p.score(state, pod, n)
                if not st.is_success():
                    sc = 0
                scores[n] = sc
            if _implements(p, "normalize_score"):
                p.normalize_score(state, pod, scores)
            w = getattr(p, "score_weight", 1)
            for n in node_names:
                total[n] += w * scores[n]
        return total

    def run_reserve(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        for p in self._at("reserve"):
            s = p.reserve(state, pod, node_name)
            if not s.is_success():
                return s
        return Status.success()

    def run_unreserve(self, state: CycleState, pod: Pod, node_name: str) -> None:
        for p in self._at("unreserve"):
            p.unreserve(state, pod, node_name)

    def run_permit(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        """RunPermitPlugins: WAIT parks the pod until allow/reject/timeout."""
        max_timeout = 0.0
        statuses = []
        for p in self._at("permit"):
            s, timeout = p.permit(state, pod, node_name)
            if s.code == ERROR:
                return s
            if s.is_unschedulable():
                return s
            if s.code == WAIT:
                max_timeout = max(max_timeout, timeout)
                statuses.append(s)
        if not statuses:
            return Status.success()
        wp = WaitingPod(pod=pod, deadline=time.monotonic() + max_timeout)
        self.waiting_pods[pod.key()] = wp
        try:
            wp.event.wait(max_timeout)
        finally:
            self.waiting_pods.pop(pod.key(), None)
        if wp.allowed:
            return Status.success()
        if wp.allowed is None:
            return Status.unschedulable("permit timeout")
        return Status.unschedulable("rejected by permit")

    def run_pre_bind(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        for p in self._at("pre_bind"):
            s = p.pre_bind(state, pod, node_name)
            if not s.is_success():
                return s
        return Status.success()

    def run_bind(self, state: CycleState, pod: Pod, node_name: str) -> Status:
        """First bind plugin that doesn't SKIP handles the bind."""
        for p in self._at("bind"):
            s = p.bind(state, pod, node_name)
            if s.code == SKIP:
                continue
            return s
        return Status(SKIP)

    def run_post_bind(self, state: CycleState, pod: Pod, node_name: str) -> None:
        for p in self._at("post_bind"):
            p.post_bind(state, pod, node_name)

    def get_waiting_pod(self, key: str) -> Optional[WaitingPod]:
        return self.waiting_pods.get(key)


class Registry:
    """registry.go: plugin name → factory."""

    def __init__(self):
        self._factories: Dict[str, Callable[..., Plugin]] = {}

    def register(self, name: str, factory: Callable[..., Plugin]) -> None:
        if name in self._factories:
            raise ValueError(f"plugin {name} already registered")
        self._factories[name] = factory

    def unregister(self, name: str) -> None:
        self._factories.pop(name, None)

    def make(self, name: str, *args, **kwargs) -> Plugin:
        return self._factories[name](*args, **kwargs)

    def names(self) -> List[str]:
        return sorted(self._factories)
