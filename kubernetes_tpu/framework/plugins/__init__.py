"""Built-in framework plugins + default registry
(pkg/scheduler/framework/plugins/)."""

from .builtin import (
    Handle,
    NodeName,
    PrioritySort,
    TaintToleration,
    VolumeBinding,
    predicate_plugin,
    priority_plugin,
)
from .registry import new_default_registry

__all__ = [
    "Handle",
    "NodeName",
    "PrioritySort",
    "TaintToleration",
    "VolumeBinding",
    "predicate_plugin",
    "priority_plugin",
    "new_default_registry",
]
