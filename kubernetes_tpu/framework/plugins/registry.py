"""Default plugin registry (framework/plugins/default_registry.go).

Maps the plugin names this version of the reference knows about to
factories. NewDefaultRegistry registers: prioritysort (queue),
nodename, tainttoleration, volumebinding (+ migration-shimmed legacy
predicates, which on this framework run as fused device kernels and are
exposed as shims only for custom configs)."""

from __future__ import annotations

from typing import Optional

from ..interface import Registry
from . import builtin


def new_default_registry(handle: Optional[builtin.Handle] = None, volume_binder=None) -> Registry:
    r = Registry()
    r.register("PrioritySort", lambda: builtin.PrioritySort())
    r.register("NodeName", lambda: builtin.NodeName())
    r.register("TaintToleration", lambda: builtin.TaintToleration(handle))
    r.register("VolumeBinding", lambda: builtin.VolumeBinding(volume_binder))
    return r
