"""Built-in framework plugins.

The reference migrated three plugins to the framework in this version —
NodeName, TaintToleration, VolumeBinding
(pkg/scheduler/framework/plugins/{nodename,tainttoleration,volumebinding},
default_registry.go) — plus `migration/` shims that wrap any legacy
predicate/priority as a plugin. Same set here. Note the DEFAULT config
does not register them as framework plugins (the legacy predicate set
covers the same checks — on this framework, as fused device kernels); they
exist for Policy/ComponentConfig configurations and as porting targets for
out-of-tree plugins.

Plugins that need cluster state beyond the NodeInfo handed to Filter take a
`handle` — the FrameworkHandle equivalent exposing a snapshot accessor
(framework/v1alpha1/interface.go FrameworkHandle).
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Tuple

from ...api.types import Pod
from ...oracle import predicates as opred
from ...oracle import priorities as opri
from ..interface import CycleState, Plugin, Status


class Handle:
    """FrameworkHandle: what built-in plugins need from the scheduler."""

    def __init__(self, snapshot_fn: Callable[[], object]):
        self.snapshot_fn = snapshot_fn

    def snapshot(self):
        return self.snapshot_fn()


class PrioritySort(Plugin):
    """QueueSort: priority desc, then enqueue order — the default activeQ
    comparator (scheduling_queue.go activeQComp)."""

    name = "PrioritySort"

    def less(self, a, b) -> bool:
        pa, pb = a.pod.get_priority(), b.pod.get_priority()
        if pa != pb:
            return pa > pb
        return a.seq < b.seq


class NodeName(Plugin):
    """plugins/nodename: Filter = PodFitsHost (predicates.go:991)."""

    name = "NodeName"

    def filter(self, state: CycleState, pod: Pod, node_info) -> Status:
        if opred.pod_fits_host(pod, node_info):
            return Status.success()
        return Status.unschedulable("node didn't match the requested hostname")


class TaintToleration(Plugin):
    """plugins/tainttoleration: Filter = PodToleratesNodeTaints
    (predicates.go:1604); Score = preferred-taint count, normalized
    (taint_toleration.go:55)."""

    name = "TaintToleration"
    score_weight = 1

    def __init__(self, handle: Optional[Handle] = None):
        self.handle = handle

    def filter(self, state: CycleState, pod: Pod, node_info) -> Status:
        if opred.pod_tolerates_node_taints(pod, node_info):
            return Status.success()
        return Status.unschedulable("node has taints the pod doesn't tolerate")

    def score(self, state: CycleState, pod: Pod, node_name: str) -> Tuple[int, Status]:
        snap = self.handle.snapshot() if self.handle else None
        if snap is None:
            return 0, Status.success()
        key = f"tt-scores/{pod.key()}"
        try:
            scores = state.read(key)
        except KeyError:
            scores = opri.taint_toleration_priority(pod, snap)
            state.write(key, scores)
        return scores.get(node_name, 0), Status.success()


class VolumeBinding(Plugin):
    """plugins/volumebinding: Filter = CheckVolumeBinding via the volume
    binder seam (volumebinder/volume_binder.go; plugin shim
    framework/plugins/volumebinding/volume_binding.go)."""

    name = "VolumeBinding"

    def __init__(self, binder=None):
        # kubernetes_tpu.volume.VolumeBinder (or anything with
        # find_pod_volumes(pod, node_info) -> (bool, reasons))
        self.binder = binder

    def filter(self, state: CycleState, pod: Pod, node_info) -> Status:
        if self.binder is None:
            return Status.success()
        ok, reasons = self.binder.find_pod_volumes(pod, node_info)
        if ok:
            return Status.success()
        return Status.unschedulable("; ".join(reasons) or "volume binding failed")


def predicate_plugin(plugin_name: str, fn: Callable[[Pod, object], bool], msg: str = "") -> Plugin:
    """migration shim: legacy FitPredicate → Filter plugin
    (framework/plugins/migration/utils.go)."""

    class _Shim(Plugin):
        name = plugin_name

        def filter(self, state: CycleState, pod: Pod, node_info) -> Status:
            if fn(pod, node_info):
                return Status.success()
            return Status.unschedulable(msg or f"{plugin_name} failed")

    return _Shim()


def priority_plugin(
    plugin_name: str,
    fn: Callable[[Pod, object], Dict[str, int]],
    handle: Handle,
    weight: int = 1,
) -> Plugin:
    """migration shim: legacy PriorityFunction → Score plugin. `fn` maps
    (pod, snapshot) → {node: score}; cached in CycleState per cycle."""

    class _Shim(Plugin):
        name = plugin_name
        score_weight = weight

        def score(self, state: CycleState, pod: Pod, node_name: str) -> Tuple[int, Status]:
            key = f"{plugin_name}/{pod.key()}"
            try:
                scores = state.read(key)
            except KeyError:
                scores = fn(pod, handle.snapshot())
                state.write(key, scores)
            return scores.get(node_name, 0), Status.success()

    return _Shim()


class ServiceAffinityPlugin(Plugin):
    """Policy serviceAffinity predicate as a plugin: PreFilter runs the
    once-per-pod anchor-candidate scan (serviceAffinityMetadataProducer,
    predicates.go:1060), Filter applies the per-node backfill + match
    (checkServiceAffinity, predicates.go:1123). State travels in
    CycleState so Filter never rescans the cluster."""

    def __init__(self, plugin_name: str, labels, snapshot_fn, services_fn):
        self.name = plugin_name
        self._labels = tuple(labels)
        self._snapshot_fn = snapshot_fn
        self._services_fn = services_fn

    def _key(self, pod: Pod) -> str:
        return f"{self.name}/meta/{pod.key()}"

    def pre_filter(self, state: CycleState, pod: Pod) -> Status:
        from ...oracle.predicates import service_affinity_precompute

        state.write(
            self._key(pod),
            service_affinity_precompute(
                pod, self._snapshot_fn(), self._labels, self._services_fn()
            ),
        )
        return Status.success()

    def filter(self, state: CycleState, pod: Pod, node_info) -> Status:
        from ...oracle.predicates import (
            service_affinity_fits,
            service_affinity_precompute,
        )

        try:
            base, cands = state.read(self._key(pod))
        except KeyError:
            # resilient like the reference when metadata is missing
            base, cands = service_affinity_precompute(
                pod, self._snapshot_fn(), self._labels, self._services_fn()
            )
        if service_affinity_fits(
            pod, node_info, self._snapshot_fn(), self._labels, base, cands
        ):
            return Status.success()
        return Status.unschedulable("node(s) didn't match service affinity")
