"""Scheduling Framework (v1alpha1 equivalent): plugin extension points,
CycleState, Registry, built-in plugins."""

from .interface import (
    ERROR,
    SKIP,
    SUCCESS,
    UNSCHEDULABLE,
    WAIT,
    CycleState,
    Framework,
    Plugin,
    Registry,
    Status,
    WaitingPod,
)

__all__ = [
    "ERROR",
    "SKIP",
    "SUCCESS",
    "UNSCHEDULABLE",
    "WAIT",
    "CycleState",
    "Framework",
    "Plugin",
    "Registry",
    "Status",
    "WaitingPod",
]
