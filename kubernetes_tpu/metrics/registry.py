"""Minimal Prometheus-compatible metrics: the component-base/metrics +
legacyregistry subset the scheduler uses (SURVEY §2.2 component-base row;
pkg/scheduler/metrics/metrics.go imports component-base/metrics).

Counter / Gauge / Histogram with label support and text exposition
(text/plain; version=0.0.4) so a real Prometheus can scrape /metrics.
Thread-safe; lock granularity is per-metric.
"""

from __future__ import annotations

import random as _random
from bisect import bisect_left
from typing import Dict, List, Optional, Sequence, Tuple

from ..analysis.lockorder import audited_lock

_DEFAULT_BUCKETS = (
    0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
)


# Prometheus text-format escaping (exposition format spec): inside a
# label value, backslash, double-quote, and newline MUST be escaped —
# emitting them raw produces a scrape the parser rejects wholesale (one
# bad label value poisons every series in the response)
_LABEL_ESC = str.maketrans({"\\": "\\\\", '"': '\\"', "\n": "\\n"})
# HELP text escapes only backslash and newline (quotes are legal there)
_HELP_ESC = str.maketrans({"\\": "\\\\", "\n": "\\n"})


def _fmt_labels(names: Sequence[str], values: Tuple[str, ...]) -> str:
    if not names:
        return ""
    inner = ",".join(
        f'{n}="{str(v).translate(_LABEL_ESC)}"' for n, v in zip(names, values)
    )
    return "{" + inner + "}"


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help_: str, label_names: Sequence[str] = ()):
        self.name = name
        self.help = help_
        self.label_names = tuple(label_names)
        self._lock = audited_lock("metric")

    def _header(self) -> List[str]:
        return [
            f"# HELP {self.name} {self.help.translate(_HELP_ESC)}",
            f"# TYPE {self.name} {self.kind}",
        ]

    def expose(self) -> List[str]:
        raise NotImplementedError


class Counter(_Metric):
    kind = "counter"

    def __init__(self, name, help_, label_names=()):
        super().__init__(name, help_, label_names)
        self._values: Dict[Tuple[str, ...], float] = {}

    def inc(self, *labels: str, by: float = 1.0) -> None:
        with self._lock:
            self._values[labels] = self._values.get(labels, 0.0) + by

    def value(self, *labels: str) -> float:
        with self._lock:
            return self._values.get(labels, 0.0)

    def expose(self) -> List[str]:
        with self._lock:
            items = sorted(self._values.items())
        out = self._header()
        if not items and not self.label_names:
            items = [((), 0.0)]
        for labels, v in items:
            out.append(f"{self.name}{_fmt_labels(self.label_names, labels)} {v}")
        return out


class Gauge(_Metric):
    kind = "gauge"

    def __init__(self, name, help_, label_names=()):
        super().__init__(name, help_, label_names)
        self._values: Dict[Tuple[str, ...], float] = {}

    def set(self, value: float, *labels: str) -> None:
        with self._lock:
            self._values[labels] = float(value)

    def add(self, delta: float, *labels: str) -> None:
        with self._lock:
            self._values[labels] = self._values.get(labels, 0.0) + delta

    def value(self, *labels: str) -> float:
        with self._lock:
            return self._values.get(labels, 0.0)

    def expose(self) -> List[str]:
        with self._lock:
            items = sorted(self._values.items())
        out = self._header()
        if not items and not self.label_names:
            items = [((), 0.0)]
        for labels, v in items:
            out.append(f"{self.name}{_fmt_labels(self.label_names, labels)} {v}")
        return out


class Histogram(_Metric):
    """Bucket counts are stored PER-BUCKET (non-cumulative, one slot past
    the last boundary for +Inf) and cumulated only on read: observe() is a
    bisect + one increment instead of a walk over every boundary — the
    scheduler observes 3-4 histograms per pod, so at 4096-pod batches the
    O(buckets) walk was measurable in the commit loop."""

    kind = "histogram"

    def __init__(self, name, help_, label_names=(), buckets: Sequence[float] = _DEFAULT_BUCKETS):
        super().__init__(name, help_, label_names)
        self.buckets = tuple(sorted(buckets))
        self._counts: Dict[Tuple[str, ...], List[int]] = {}
        self._sums: Dict[Tuple[str, ...], float] = {}
        self._totals: Dict[Tuple[str, ...], int] = {}
        # optional raw-sample reservoir for EXACT percentiles: bucket upper
        # bounds are honest for serving /metrics, but a benchmark quoting
        # "p99 pod-schedule latency" must not round to a coarse tail bucket
        self._samples: Optional[List[float]] = None
        self._sample_cap = 0
        self._sample_seen = 0

    def enable_sampling(self, cap: int = 1 << 18) -> None:
        """Keep raw observed values (uniform reservoir past `cap`) so
        exact_percentile() can answer to full resolution."""
        with self._lock:
            self._samples = []
            self._sample_cap = cap
            self._sample_seen = 0

    def reset_samples(self) -> None:
        with self._lock:
            if self._samples is not None:
                self._samples = []
                self._sample_seen = 0

    def _sample_locked(self, value: float) -> None:
        s = self._samples
        self._sample_seen += 1
        if len(s) < self._sample_cap:
            s.append(value)
            return
        j = _random.randrange(self._sample_seen)
        if j < self._sample_cap:
            s[j] = value

    def exact_percentile(self, q: float) -> Optional[float]:
        """Exact (reservoir-sampled past cap) percentile of the raw values
        seen since enable_sampling/reset_samples; None without samples."""
        with self._lock:
            if not self._samples:
                return None
            s = sorted(self._samples)
        i = min(int(q * len(s)), len(s) - 1)
        return s[i]

    def observe(self, value: float, *labels: str) -> None:
        idx = bisect_left(self.buckets, value)
        with self._lock:
            counts = self._counts.get(labels)
            if counts is None:
                counts = self._counts[labels] = [0] * (len(self.buckets) + 1)
            counts[idx] += 1
            self._sums[labels] = self._sums.get(labels, 0.0) + value
            self._totals[labels] = self._totals.get(labels, 0) + 1
            if self._samples is not None:
                self._sample_locked(value)

    def observe_many(self, values: Sequence[float], *labels: str) -> None:
        """Batched observe: one lock acquisition for a whole batch of
        samples (the lean bind path records per-pod latencies in bulk)."""
        if not len(values):
            return
        buckets = self.buckets
        idxs = [bisect_left(buckets, v) for v in values]
        with self._lock:
            counts = self._counts.get(labels)
            if counts is None:
                counts = self._counts[labels] = [0] * (len(buckets) + 1)
            for i in idxs:
                counts[i] += 1
            self._sums[labels] = self._sums.get(labels, 0.0) + float(sum(values))
            self._totals[labels] = self._totals.get(labels, 0) + len(values)
            if self._samples is not None:
                for v in values:
                    self._sample_locked(v)

    def count(self, *labels: str) -> int:
        with self._lock:
            return self._totals.get(labels, 0)

    def labels(self) -> List[Tuple[str, ...]]:
        """Every label combination observed so far (sorted) — lets the
        perf-budget gate discover which stages have data without reaching
        into the private maps."""
        with self._lock:
            return sorted(self._totals)

    def bucket_counts(self, *labels: str) -> Tuple[List[int], int, float]:
        """(per-bucket counts incl. the +Inf slot, total, sum) for one
        label combination — the raw material for DELTA percentiles: the
        perf-budget gate snapshots before a measured drain and diffs
        after, so warmup compiles and other tests' observations in the
        shared process-global histogram never pollute the gated p99."""
        with self._lock:
            c = self._counts.get(labels)
            return (
                list(c) if c else [0] * (len(self.buckets) + 1),
                self._totals.get(labels, 0),
                self._sums.get(labels, 0.0),
            )

    def sum(self, *labels: str) -> float:
        with self._lock:
            return self._sums.get(labels, 0.0)

    def percentile(self, q: float, *labels: str) -> float:
        """Approximate quantile from bucket boundaries (upper bound of the
        bucket holding the q-th observation)."""
        with self._lock:
            counts = self._counts.get(labels)
            total = self._totals.get(labels, 0)
        if not counts or total == 0:
            return 0.0
        target = q * total
        acc = 0
        for i, b in enumerate(self.buckets):
            acc += counts[i]
            if acc >= target:
                return b
        return float("inf")

    def expose(self) -> List[str]:
        with self._lock:
            keys = sorted(self._counts)
            snap = {k: (list(self._counts[k]), self._sums[k], self._totals[k]) for k in keys}
        out = self._header()
        if not snap and not self.label_names:
            snap = {(): ([0] * (len(self.buckets) + 1), 0.0, 0)}
        for labels, (counts, sum_, total) in snap.items():
            acc = 0
            for i, b in enumerate(self.buckets):
                acc += counts[i]
                lbl = _fmt_labels(self.label_names + ("le",), labels + (repr(b),))
                out.append(f"{self.name}_bucket{lbl} {acc}")
            lbl_inf = _fmt_labels(self.label_names + ("le",), labels + ("+Inf",))
            out.append(f"{self.name}_bucket{lbl_inf} {total}")
            out.append(f"{self.name}_sum{_fmt_labels(self.label_names, labels)} {sum_}")
            out.append(f"{self.name}_count{_fmt_labels(self.label_names, labels)} {total}")
        return out


class Registry:
    """legacyregistry equivalent: register + text exposition."""

    def __init__(self):
        self._lock = audited_lock("metrics-registry")
        self._metrics: Dict[str, _Metric] = {}

    def register(self, metric: _Metric) -> _Metric:
        with self._lock:
            if metric.name in self._metrics:
                raise ValueError(f"metric {metric.name} already registered")
            self._metrics[metric.name] = metric
        return metric

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def expose_text(self) -> str:
        with self._lock:
            metrics = [self._metrics[k] for k in sorted(self._metrics)]
        lines: List[str] = []
        for m in metrics:
            lines.extend(m.expose())
        return "\n".join(lines) + "\n"
