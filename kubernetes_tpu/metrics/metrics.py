"""The scheduler's metric series (pkg/scheduler/metrics/metrics.go:91-233).

Same names and semantics where the concept maps 1:1; batch-specific series
(batch size, device-phase splits) are additions the reference cannot have.
All registered on a module-level registry (legacyregistry pattern,
metrics.go:23-24) that serving exposes at /metrics.
"""

from __future__ import annotations

import time
from typing import Optional

from .registry import Counter, Gauge, Histogram, Registry

registry = Registry()

_DURATION_BUCKETS = (0.001, 0.002, 0.005, 0.01, 0.02, 0.05, 0.1, 0.2, 0.5, 1.0, 2.0, 5.0, 10.0)

# result labels for schedule_attempts_total (metrics.go:41-47)
SCHEDULED = "scheduled"
UNSCHEDULABLE = "unschedulable"
ERROR = "error"

e2e_scheduling_duration = registry.register(Histogram(
    "scheduler_e2e_scheduling_duration_seconds",
    "E2e scheduling latency per pod (scheduling algorithm + binding)",
    buckets=_DURATION_BUCKETS,
))
scheduling_algorithm_duration = registry.register(Histogram(
    "scheduler_scheduling_algorithm_duration_seconds",
    "Scheduling algorithm latency (device solve + commit decisions)",
    buckets=_DURATION_BUCKETS,
))
binding_duration = registry.register(Histogram(
    "scheduler_binding_duration_seconds",
    "Binding latency",
    buckets=_DURATION_BUCKETS,
))
predicate_evaluation_duration = registry.register(Histogram(
    "scheduler_scheduling_algorithm_predicate_evaluation_seconds",
    "Predicate (Filter mask) evaluation latency per batch",
    buckets=_DURATION_BUCKETS,
))
priority_evaluation_duration = registry.register(Histogram(
    "scheduler_scheduling_algorithm_priority_evaluation_seconds",
    "Priority (Score matrix) evaluation latency per batch",
    buckets=_DURATION_BUCKETS,
))
preemption_evaluation_duration = registry.register(Histogram(
    "scheduler_scheduling_algorithm_preemption_evaluation_seconds",
    "Preemption evaluation latency",
    buckets=_DURATION_BUCKETS,
))
schedule_attempts = registry.register(Counter(
    "scheduler_schedule_attempts_total",
    "Scheduling attempts by result (scheduled|unschedulable|error)",
    label_names=("result",),
))
preemption_victims = registry.register(Histogram(
    "scheduler_preemption_victims",
    "Number of victims selected per preemption",
    buckets=(1, 2, 4, 8, 16, 32, 64),
))
preemption_attempts = registry.register(Counter(
    "scheduler_preemption_attempts_total",
    "Total preemption attempts",
))
pending_pods = registry.register(Gauge(
    "scheduler_pending_pods",
    "Pending pods by queue (active|backoff|unschedulable)",
    label_names=("queue",),
))
pod_scheduling_duration = registry.register(Histogram(
    "scheduler_pod_scheduling_duration_seconds",
    "Time from first attempt to successful scheduling per pod",
    # queue-add → bound can span a whole queue drain (100k pods enqueued at
    # once wait tens of seconds for their batch): extend the tail so p99 is
    # a number, not +Inf (metrics.go PodSchedulingDuration uses exponential
    # buckets to 512s for the same reason)
    buckets=_DURATION_BUCKETS + (20.0, 40.0, 80.0, 160.0, 320.0, 640.0, 1280.0, 2560.0),
))
pod_scheduling_attempts = registry.register(Histogram(
    "scheduler_pod_scheduling_attempts",
    "Attempts needed to schedule a pod",
    buckets=(1, 2, 4, 8, 16),
))
# per-pod latency ATTRIBUTION series (kubernetes_tpu/obs): where each
# pod's time went — queue wait (enqueue → pop, the incoming-pods wait),
# then the attempt itself (pop → bound/failed, by result). Together with
# pod_scheduling_duration (enqueue → bound) these decompose the e2e
# number the bench quotes; observed via observe_many on the bulk paths.
queue_incoming_wait = registry.register(Histogram(
    "scheduler_queue_incoming_wait_seconds",
    "Time a pod spent queued between (re-)admission and being popped "
    "into a batch (one observation per pop, so deferred/requeued pods "
    "observe once per round trip)",
    buckets=_DURATION_BUCKETS + (20.0, 40.0, 80.0, 160.0, 320.0, 640.0,
                                 1280.0, 2560.0),
))
scheduling_attempt_duration = registry.register(Histogram(
    "scheduler_scheduling_attempt_duration_seconds",
    "Per-pod attempt latency (pop -> bound or terminal failure) by "
    "result (scheduled|unschedulable) — the scheduling_attempt_duration"
    "_seconds shape of the reference's metrics.go",
    label_names=("result",),
    buckets=_DURATION_BUCKETS,
))
scheduling_stage_duration = registry.register(Histogram(
    "scheduler_scheduling_stage_duration_seconds",
    "Per-batch wall of each pipeline stage (sync|encode|dispatch|fetch|"
    "commit|apply|bind|fold|gather) — the framework_extension_point_"
    "duration_seconds analogue for the batch pipeline's real stages",
    label_names=("stage",),
    buckets=_DURATION_BUCKETS,
))
# batch-native additions (no reference counterpart)
batch_size = registry.register(Histogram(
    "scheduler_batch_size_pods",
    "Pods per device-solve batch",
    buckets=(1, 8, 32, 128, 512, 2048, 8192),
))
device_solve_duration = registry.register(Histogram(
    "scheduler_device_solve_duration_seconds",
    "Fused mask+score+assign device program latency per batch",
    buckets=_DURATION_BUCKETS,
))
tensor_sync_duration = registry.register(Histogram(
    "scheduler_tensor_sync_duration_seconds",
    "Dirty-row tensor mirror patch latency per batch",
    buckets=_DURATION_BUCKETS,
))
# compile-plan series (kubernetes_tpu/compile): the drain must never meet
# the XLA compiler — these are the evidence
xla_compile_duration = registry.register(Histogram(
    "scheduler_xla_compile_duration_seconds",
    "Trace+compile wall per solve-spec (warmup or inline fallback)",
    # compiles run seconds-to-minutes on a remote-attached chip
    buckets=_DURATION_BUCKETS + (20.0, 60.0, 120.0, 300.0),
))
compile_plan_lookups = registry.register(Counter(
    "scheduler_compile_plan_lookups_total",
    "Solve-spec plan lookups by result (hit|miss)",
    label_names=("result",),
))
compile_ladder_specs = registry.register(Gauge(
    "scheduler_compile_ladder_specs",
    "Declared solve-specs in the compile plan's shape ladder",
))
compile_spec_misses_after_warmup = registry.register(Gauge(
    "scheduler_compile_spec_misses_after_warmup",
    "Solve-spec misses (inline XLA compiles) AFTER warmup declared the "
    "ladder — zero on a healthy drain",
))
# commit-plane series (kubernetes_tpu/commit): which path a batch's commit
# took, what the device arbiter decided, and what the bulk apply cost
commit_plane_batches = registry.register(Counter(
    "scheduler_commit_plane_batches_total",
    "Batches by commit path (arbiter = device-arbitrated columnar apply, "
    "bulk = plugin-free fast path, scalar = legacy per-pod host loop)",
    label_names=("path",),
))
commit_arbiter_verdicts = registry.register(Counter(
    "scheduler_commit_arbiter_verdicts_total",
    "Device commit-arbiter verdicts (place|defer|nofit)",
    label_names=("verdict",),
))
commit_apply_duration = registry.register(Histogram(
    "scheduler_commit_apply_duration_seconds",
    "Columnar bulk-apply wall per batch (clone + bulk assume + nomination "
    "clears + bind submission, on the commit-pipeline worker)",
    buckets=_DURATION_BUCKETS,
))
# resident-state plane (kubernetes_tpu/ops/fold): every byte the tensor
# mirror ships host→device, by transport kind — full bank uploads, dirty
# node-row scatters, usage-column scatters, and fold control data. On a
# covered steady-state drain only `fold` (tiny control arrays) should
# grow; `usage` staying ~0 IS the tentpole's win, as a measured number.
mirror_bytes_shipped = registry.register(Counter(
    "scheduler_mirror_bytes_shipped_total",
    "Host-to-device bank bytes shipped by the tensor mirror, by kind "
    "(full = whole-bank upload, rows = dirty node-row scatter, usage = "
    "usage-column scatter, fold = device-fold control data, warm = "
    "warmup's no-op scatter pre-compiles, pods/terms = per-dispatch "
    "pod/term payloads, stage/term_bank = staged-slab uploads)",
    label_names=("kind",),
))
fold_batches = registry.register(Counter(
    "scheduler_fold_batches_total",
    "Commit batches whose state deltas were folded into the resident "
    "device banks (no host scatter shipped for their rows)",
))
# pod-ingest plane (kubernetes_tpu/ingest): which pod-array transport a
# dispatch used — index = gathered from the device-resident staged bank
# (ships an int32 index vector, the covered steady state), legacy = the
# host-built PodBatch upload (stale staged rows, slab overflow/rebuild),
# off = the plane is disabled. Per DISPATCH, like sharded_fallbacks.
ingest_batches = registry.register(Counter(
    "scheduler_ingest_batches_total",
    "Solve dispatches by pod-array transport path (index = device-"
    "resident staged bank gather, legacy = host-built upload with the "
    "plane on, off = ingest plane disabled)",
    label_names=("path",),
))
# term-bank plane (kubernetes_tpu/terms_plane): which term-table
# transport a dispatch used — the TermBank twin of ingest_batches.
# `terms` joins the mirror_bytes_shipped kind set: the full padded term
# table on the legacy path vs KB-scale index/owner vectors covered.
term_batches = registry.register(Counter(
    "scheduler_term_batches_total",
    "Solve dispatches by term-table transport path (index = device-"
    "resident term bank gather, legacy = host-compiled TermBank upload "
    "with the plane on, off = term plane disabled)",
    label_names=("path",),
))
term_restage = registry.register(Counter(
    "scheduler_term_restage_total",
    "Stale interned term entries re-staged at dispatch time (pod "
    "updated/deleted between enqueue and pop, spreading-selector drift, "
    "or a term-slab rebuild)",
))
# multi-chip series (kubernetes_tpu/parallel): a mesh-configured driver
# that cannot shard a batch (node bucket stops dividing the shard count
# mid-churn) quietly drops to the replicated solve — which is a different,
# usually unwarmed XLA program AND idles the whole mesh. Zero on a
# healthy multi-chip drain.
sharded_fallbacks = registry.register(Counter(
    "scheduler_sharded_fallbacks_total",
    "Solve DISPATCHES a mesh-configured driver routed through the "
    "replicated (single-device) pipeline instead of the sharded one, by "
    "reason (per dispatch, not per batch: speculative chaining and "
    "warmup's peeked dispatches each count — zero is the only healthy "
    "value either way)",
    label_names=("reason",),
))
# steady-state health plane (kubernetes_tpu/obs/introspect): always-on
# gauges refreshed by the background health monitor (and by the driver's
# per-batch gauge block for the queue split) — the production counterpart
# of the flight recorder's traced windows. Every value is a counter or a
# metadata read; nothing here ever forces a device value (KTPU004).
queue_oldest_pending_age = registry.register(Gauge(
    "scheduler_queue_oldest_pending_age_seconds",
    "Age of the OLDEST currently-pending pod (active+backoff+"
    "unschedulable), on the queue's own clock — the starvation gauge "
    "next to scheduler_pending_pods",
))
plane_slab_occupancy = registry.register(Gauge(
    "ktpu_plane_slab_occupancy",
    "Rows/entries in use per device-residency plane slab (ingest = "
    "staged pod rows, terms = interned term rows, columns = cache "
    "column rows, mirror_nodes/mirror_sigs/mirror_patterns = bank rows)",
    label_names=("plane",),
))
plane_slab_capacity = registry.register(Gauge(
    "ktpu_plane_slab_capacity",
    "Allocated slab capacity per plane (same label set as "
    "ktpu_plane_slab_occupancy)",
    label_names=("plane",),
))
plane_free_rows = registry.register(Gauge(
    "ktpu_plane_free_rows",
    "Free-list depth per plane slab",
    label_names=("plane",),
))
plane_stale_rows = registry.register(Gauge(
    "ktpu_plane_stale_rows",
    "Rows whose derived copy lags the source of truth, per plane "
    "(ingest/terms = staged rows not yet shipped to the device twin, "
    "columns = lazy NodeInfo views behind the columns, mirror_nodes = "
    "host rows pending a device patch)",
    label_names=("plane",),
))
plane_refs_total = registry.register(Gauge(
    "ktpu_plane_refs_total",
    "Outstanding queue-entry references into a refcounted plane slab",
    label_names=("plane",),
))
cache_journal_depth = registry.register(Gauge(
    "ktpu_cache_journal_depth",
    "Total journaled (sign, pod) ops pending behind the columnar "
    "cache's lazy NodeInfo views (bounded by JOURNAL_BOUND per row)",
))
compile_ladder_rungs = registry.register(Gauge(
    "ktpu_compile_ladder_rungs",
    "Declared compile-plan specs per KIND_* family (the per-kind ladder "
    "census)",
    label_names=("kind",),
))
commit_inflight = registry.register(Gauge(
    "ktpu_commit_inflight",
    "1 while a columnar apply is in flight on the commit-pipeline "
    "worker (the <=1-batch backpressure invariant, as a gauge)",
))
recorder_pending_device = registry.register(Gauge(
    "ktpu_recorder_pending_device_spans",
    "Flight-recorder two-phase device spans currently parked (bounded "
    "by MAX_PENDING_DEVICE)",
))
health_monitor_up = registry.register(Gauge(
    "ktpu_health_monitor_up",
    "1 while the background steady-state health monitor thread is "
    "running",
))
health_refresh = registry.register(Counter(
    "ktpu_health_refresh_total",
    "Health-monitor gauge refresh cycles completed",
))
shadow_audit = registry.register(Counter(
    "ktpu_shadow_audit_total",
    "Sampled shadow audits (device_bank_divergence + columns-vs-banks "
    "cross-check) executed at the driver's safe sync point, by result "
    "(clean|divergent|skipped — skipped means no resident device banks "
    "existed to compare, never counted as clean)",
    label_names=("result",),
))
# fault plane (kubernetes_tpu/faults): the runtime degradation ladder —
# per-plane circuit breakers over the existing legacy host paths, plus
# the failure-path counters the reference scheduler keeps implicitly
# (bind errors requeue through backoff, broken watches relist).
plane_breaker_state = registry.register(Gauge(
    "ktpu_plane_breaker_state",
    "Circuit-breaker state per device-residency plane boundary "
    "(0 = closed/covered, 1 = half-open probe, 2 = open/legacy path)",
    label_names=("plane",),
))
plane_trips = registry.register(Counter(
    "ktpu_plane_trips_total",
    "Circuit-breaker trips per plane, by the reason that tripped it "
    "(exception class, uploader-dead, shadow-divergence, probe:<reason>)",
    label_names=("plane", "reason"),
))
bind_failures = registry.register(Counter(
    "scheduler_bind_failures_total",
    "Bind-pipeline failures by reason (rpc = the bind call itself, "
    "volumes/permit/prebind = earlier pipeline stages, pipeline = an "
    "unclassified bind-path error); each failed pod re-queues through "
    "the backoff tier with per-pod exponential backoff (1s→10s, the "
    "DefaultPodBackoff shape), never straight back to activeQ",
    label_names=("reason",),
))
informer_relists = registry.register(Counter(
    "scheduler_informer_relists_total",
    "Reflector relists per informer kind (ListAndWatch restarts: initial "
    "sync, 410 Gone, stream close, handler error, list error) — the "
    "replication-health counter next to the queue gauges",
    label_names=("kind",),
))
bind_conflicts = registry.register(Counter(
    "scheduler_bind_conflicts_total",
    "409 Conflicts from the pods/binding subresource by outcome: benign "
    "= the pod is already bound to the SAME node the binder asked for "
    "(an at-least-once replay — crash between the bind POST and its "
    "bookkeeping, or a retried RPC whose first attempt landed — counted "
    "and treated as success, never routed to the bind-failure backoff "
    "tier), mismatch = bound to a DIFFERENT node (a double-schedule; "
    "escalates as a real bind failure)",
    label_names=("outcome",),
))
restarts = registry.register(Counter(
    "scheduler_restarts_total",
    "Cold starts reconciled by the crash-restart plane "
    "(kubernetes_tpu/restart): each count is one full rebuild of the "
    "scheduler's device-resident state from an API-server relist",
))
restart_reconcile_duration = registry.register(Histogram(
    "scheduler_restart_reconcile_duration_seconds",
    "Cold-start reconciliation wall by phase (kubernetes_tpu/restart): "
    "relist (the API-server list round-trips), nodes (cache/columns "
    "node rebuild), assume (bulk re-assume of bound pods through the "
    "columnar path), queue (pending re-admission through the ingest/"
    "term slabs), nominations (nominated-pod overlay reconstruction), "
    "banks (TensorMirror/staged-bank device rebuild), warmup (compile-"
    "plan re-warm from the persistent ladder), informers (reflector "
    "start + initial sync)",
    buckets=(0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0),
    label_names=("phase",),
))
uploader_stalled = registry.register(Gauge(
    "ktpu_uploader_stalled",
    "1 while a plane's background uploader thread is dead/stalled with "
    "the slab still live (the health monitor's liveness flag; the drain "
    "stays correct via synchronous dispatch-time flushes, but the "
    "off-thread win is gone until the fault plane restarts it)",
    label_names=("plane",),
))


class _Timer:
    def __init__(self, hist: Histogram):
        self._hist = hist
        self._t0: Optional[float] = None

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._hist.observe(time.perf_counter() - self._t0)
        return False


def timed(hist: Histogram) -> _Timer:
    return _Timer(hist)
