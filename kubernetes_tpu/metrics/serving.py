"""Serving mux: /metrics, /healthz, /readyz (cmd/kube-scheduler/app/
server.go:287-333 newMetricsHandler / newHealthzHandler).

Prometheus scrapes /metrics (text exposition from the module registry);
healthz answers 200 once the scheduler reports healthy. Runs on a daemon
thread like the extender server.

/readyz is gated SEPARATELY from /healthz (the reference gates readiness
on informer sync + WaitForCacheSync): a scheduler whose warmup has not
completed is alive but must answer 503 to readiness probes, so a
scrape-driven harness cannot race a cold scheduler into a drain whose
first batches pay the XLA compiles warmup exists to pre-pay.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional, Tuple

from .metrics import registry as default_registry


class MetricsServer:
    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        registry=None,
        healthy_fn: Optional[Callable[[], bool]] = None,
        ready_fn: Optional[Callable[[], bool]] = None,
        debug_fn: Optional[Callable[[], dict]] = None,
    ):
        self.registry = registry or default_registry
        self.healthy_fn = healthy_fn or (lambda: True)
        # readiness defaults to health for servers with no warmup notion
        # (the extender); a scheduler passes lambda: sched.ready
        self.ready_fn = ready_fn or self.healthy_fn
        # /debug/ktpu (statusz-style): a callable returning the versioned
        # plane-census JSON document (obs/introspect.census). Gated on
        # ready_fn exactly like /readyz — a cold scheduler's census would
        # describe a pre-warmup world the gauges never will.
        self.debug_fn = debug_fn
        self._httpd = ThreadingHTTPServer((host, port), self._make_handler())
        self._thread: Optional[threading.Thread] = None

    @property
    def address(self) -> Tuple[str, int]:
        return self._httpd.server_address[:2]

    @property
    def url(self) -> str:
        h, p = self.address
        return f"http://{h}:{p}"

    def start(self) -> "MetricsServer":
        # ktpu: thread-entry(metrics-serve) stdlib mux: handlers run on
        # socketserver threads the call graph cannot follow
        self._thread = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)

    def _make_handler(self):
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *a):
                pass

            def _send(self, body: bytes, code: int = 200, ctype: str = "text/plain") -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path = self.path.split("?")[0].rstrip("/")
                if path == "/metrics":
                    self._send(
                        server.registry.expose_text().encode(),
                        ctype="text/plain; version=0.0.4",
                    )
                elif path == "/readyz":
                    # 503 until warmup completes: readiness is a gate, not
                    # an echo of liveness (newHealthzHandler vs the
                    # WaitForCacheSync-gated readiness of the reference)
                    if server.ready_fn():
                        self._send(b"ok")
                    else:
                        self._send(b"not ready", code=503)
                elif path in ("/healthz", "/livez"):
                    if server.healthy_fn():
                        self._send(b"ok")
                    else:
                        self._send(b"unhealthy", code=500)
                elif path == "/debug/ktpu":
                    # the plane-census introspection route (versioned JSON
                    # schema, obs/introspect): 503 before warmup —
                    # consistent with /readyz by construction (same gate)
                    if server.debug_fn is None:
                        self._send(b"not found", code=404)
                    elif not server.ready_fn():
                        self._send(
                            b'{"error": "not ready"}', code=503,
                            ctype="application/json",
                        )
                    else:
                        try:
                            body = json.dumps(
                                server.debug_fn(), default=str
                            ).encode()
                        except Exception as e:  # census must never 500 the mux silently
                            self._send(
                                json.dumps({"error": str(e)}).encode(),
                                code=500, ctype="application/json",
                            )
                        else:
                            self._send(body, ctype="application/json")
                else:
                    self._send(b"not found", code=404)

        return Handler
