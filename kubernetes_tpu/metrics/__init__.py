"""Scheduler metrics (pkg/scheduler/metrics) on a component-base-style
registry with Prometheus text exposition + /metrics+/healthz serving."""

from . import metrics
from .metrics import registry, timed
from .registry import Counter, Gauge, Histogram, Registry
from .serving import MetricsServer

__all__ = [
    "metrics",
    "registry",
    "timed",
    "Counter",
    "Gauge",
    "Histogram",
    "Registry",
    "MetricsServer",
]
