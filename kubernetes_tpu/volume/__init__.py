"""Volume-aware scheduling: storage API objects, the volume predicate set
(defaults.go:40-56), and the volume binder seam
(pkg/scheduler/volumebinder)."""

from .binder import VolumeBinder
from .predicates import (
    AZURE_DISK_FILTER,
    DEFAULT_MAX_AZURE_DISK_VOLUMES,
    DEFAULT_MAX_EBS_VOLUMES,
    DEFAULT_MAX_GCE_PD_VOLUMES,
    EBS_FILTER,
    GCE_PD_FILTER,
    make_volume_checker,
    max_csi_volume_count,
    max_pd_volume_count,
    no_disk_conflict,
    no_volume_zone_conflict,
    scheduling_relevant_volumes,
)
from .types import (
    CSINode,
    PersistentVolume,
    PersistentVolumeClaim,
    StorageClass,
    csinode_from_k8s,
    label_zones_to_set,
    pv_from_k8s,
    pvc_from_k8s,
    storage_class_from_k8s,
)

__all__ = [
    "VolumeBinder",
    "AZURE_DISK_FILTER",
    "DEFAULT_MAX_AZURE_DISK_VOLUMES",
    "DEFAULT_MAX_EBS_VOLUMES",
    "DEFAULT_MAX_GCE_PD_VOLUMES",
    "EBS_FILTER",
    "GCE_PD_FILTER",
    "make_volume_checker",
    "max_csi_volume_count",
    "max_pd_volume_count",
    "no_disk_conflict",
    "no_volume_zone_conflict",
    "scheduling_relevant_volumes",
    "CSINode",
    "PersistentVolume",
    "PersistentVolumeClaim",
    "StorageClass",
    "csinode_from_k8s",
    "label_zones_to_set",
    "pv_from_k8s",
    "pvc_from_k8s",
    "storage_class_from_k8s",
]
