"""Volume binder: delayed PV binding participating in scheduling.

The seam of pkg/scheduler/volumebinder/volume_binder.go over the logic of
pkg/controller/volume/scheduling (FindPodVolumes / AssumePodVolumes /
BindPodVolumes), reduced to the scheduling-visible contract:

  Filter:   find_pod_volumes(pod, node_info) — all bound claims' PVs must
            be usable on the node (zone labels), and every unbound claim
            must either match an available PV (by class) or be dynamically
            provisionable (class exists; WaitForFirstConsumer or Immediate).
  Reserve:  assume_pod_volumes(pod, node) — record tentative PVC→PV
            matches so concurrent pods don't double-claim a PV.
  PreBind:  bind_pod_volumes(pod) — hand the assumed bindings to the
            API-write hook (the PV controller's business upstream).

The binder is deliberately authoritative-state-free: assumptions are an
in-memory overlay (like the scheduler cache's assumed pods) that the
informer-confirmed PVC updates clear.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

from ..api.types import Pod
from ..oracle.nodeinfo import (
    LABEL_ZONE_FAILURE_DOMAIN,
    LABEL_ZONE_REGION,
    NodeInfo,
)
from ..analysis.lockorder import audited_lock
from .predicates import PVCLister, PVLister, SCLister
from .types import PersistentVolume, label_zones_to_set


class VolumeBinder:
    def __init__(
        self,
        pvc_lister: PVCLister,
        pv_lister: PVLister,
        sc_lister: Optional[SCLister] = None,
        all_pvs: Optional[Callable[[], List[PersistentVolume]]] = None,
        bind_fn: Optional[Callable[[str, str, str], None]] = None,
    ):
        self.pvc_lister = pvc_lister
        self.pv_lister = pv_lister
        self.sc_lister = sc_lister or (lambda name: None)
        self.all_pvs = all_pvs or (lambda: [])
        self.bind_fn = bind_fn  # (namespace, claim, pv_name) -> None
        self._lock = audited_lock("volume-binder")
        # pod key -> [(namespace, claim, pv_name)] tentative matches
        self._assumed: Dict[str, List[Tuple[str, str, str]]] = {}  # ktpu: guarded-by(self._lock)
        self._assumed_pvs: Dict[str, str] = {}  # ktpu: guarded-by(self._lock) pv name -> claiming pod key

    # -- Filter --------------------------------------------------------------

    def _pv_usable_on_node(self, pv: PersistentVolume, node_info: NodeInfo) -> bool:
        node = node_info.node
        for k, v in pv.labels.items():
            if k not in (LABEL_ZONE_FAILURE_DOMAIN, LABEL_ZONE_REGION):
                continue
            zones = label_zones_to_set(v)
            if zones and node.labels.get(k, "") not in zones:
                return False
        return True

    def _provisionable(self, storage_class_name: str) -> bool:
        """A claim is dynamically provisionable only if its class has a real
        provisioner (kubernetes.io/no-provisioner marks local-volume classes
        that can never provision — FindPodVolumes must fail those)."""
        sc = self.sc_lister(storage_class_name)
        return sc is not None and sc.provisioner not in ("", "kubernetes.io/no-provisioner")

    def find_pod_volumes(self, pod: Pod, node_info: NodeInfo) -> Tuple[bool, List[str]]:
        """FindPodVolumes: (all bound satisfied, all unbound matchable).
        PV matches are tentative WITHIN the call too: two unbound claims of
        the same pod can't both be satisfied by one PV."""
        reasons: List[str] = []
        with self._lock:
            used: set = set()  # PVs matched to earlier claims of THIS pod
            for v in pod.volumes:
                if not v.pvc_claim_name:
                    continue
                pvc = self.pvc_lister(pod.namespace, v.pvc_claim_name)
                if pvc is None:
                    reasons.append(f"pvc {v.pvc_claim_name} not found")
                    continue
                if pvc.volume_name:
                    pv = self.pv_lister(pvc.volume_name)
                    if pv is None:
                        reasons.append(f"pv {pvc.volume_name} not found")
                    elif not self._pv_usable_on_node(pv, node_info):
                        reasons.append("node(s) had volume node affinity conflict")
                    continue
                # unbound: find an available matching PV on this node's zone
                matched = False
                for pv in self.all_pvs():
                    if pv.storage_class_name != pvc.storage_class_name:
                        continue
                    if pv.name in self._assumed_pvs or pv.name in used:
                        continue
                    if self._pv_usable_on_node(pv, node_info):
                        used.add(pv.name)
                        matched = True
                        break
                if matched:
                    continue
                if self._provisionable(pvc.storage_class_name):
                    continue
                reasons.append("node(s) didn't find available persistent volumes to bind")
        return (not reasons), reasons

    # -- Reserve -------------------------------------------------------------

    def assume_pod_volumes(
        self, pod: Pod, node_name: str, node_info: Optional[NodeInfo] = None
    ) -> bool:
        """AssumePodVolumes: tentatively match unbound claims to PVs that
        are usable on the CHOSEN node (matching Filter's zone logic — the
        first class-matching PV might live in another zone). Returns ok;
        False (after rolling back partial matches) when some unbound,
        non-provisionable claim matched nothing — the caller must fail the
        pod rather than bind it with a claim that can never bind."""
        matches: List[Tuple[str, str, str]] = []
        with self._lock:
            for v in pod.volumes:
                if not v.pvc_claim_name:
                    continue
                pvc = self.pvc_lister(pod.namespace, v.pvc_claim_name)
                if pvc is None or pvc.volume_name:
                    continue
                matched = False
                for pv in self.all_pvs():
                    if (
                        pv.storage_class_name == pvc.storage_class_name
                        and pv.name not in self._assumed_pvs
                        and (node_info is None or self._pv_usable_on_node(pv, node_info))
                    ):
                        self._assumed_pvs[pv.name] = pod.key()
                        matches.append((pod.namespace, v.pvc_claim_name, pv.name))
                        matched = True
                        break
                if not matched and not self._provisionable(pvc.storage_class_name):
                    for _, _, pv_name in matches:  # roll back partial assumes
                        self._assumed_pvs.pop(pv_name, None)
                    return False
            if matches:
                self._assumed[pod.key()] = matches
        return True

    def forget_pod_volumes(self, pod: Pod) -> None:
        with self._lock:
            for _, _, pv_name in self._assumed.pop(pod.key(), []):
                self._assumed_pvs.pop(pv_name, None)

    # -- PreBind -------------------------------------------------------------

    def bind_pod_volumes(self, pod: Pod) -> None:
        """BindPodVolumes: externalize the assumed matches (API writes)."""
        with self._lock:
            matches = list(self._assumed.get(pod.key(), []))
        for ns, claim, pv_name in matches:
            if self.bind_fn is not None:
                self.bind_fn(ns, claim, pv_name)
        with self._lock:
            self._assumed.pop(pod.key(), None)

    def assumed_pv_count(self) -> int:
        with self._lock:
            return len(self._assumed_pvs)
