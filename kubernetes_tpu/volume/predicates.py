"""Volume predicates: the 6 volume rows of the default provider
(algorithmprovider/defaults/defaults.go:40-56) plus the CSI count check.

All are host-side scalar predicates (as in the reference — they walk PVC →
PV → cloud-source chains that have no dense tensor encoding); the driver
routes pods carrying scheduling-relevant volumes through the host commit
path, which is the same per-pod cost profile the reference pays for every
pod.

Listers: callables mirroring the cached-informer interfaces
(predicates.go:150-225 CachedPersistentVolume[Claim]Info etc.):
    pvc_lister(namespace, name) -> PersistentVolumeClaim | None
    pv_lister(name) -> PersistentVolume | None
    sc_lister(name) -> StorageClass | None
    csinode_lister(node_name) -> CSINode | None
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Callable, List, Optional, Set, Tuple

from ..api.types import Pod, Volume
from ..oracle.nodeinfo import (
    LABEL_ZONE_FAILURE_DOMAIN,
    LABEL_ZONE_REGION,
    NodeInfo,
)
from .types import (
    VOLUME_BINDING_WAIT,
    CSINode,
    PersistentVolume,
    PersistentVolumeClaim,
    StorageClass,
    label_zones_to_set,
)

# predicates.go:112-121 / volumeutil.DefaultMaxEBSVolumes
DEFAULT_MAX_EBS_VOLUMES = 39
DEFAULT_MAX_GCE_PD_VOLUMES = 16
DEFAULT_MAX_AZURE_DISK_VOLUMES = 16
KUBE_MAX_PD_VOLS = "KUBE_MAX_PD_VOLS"

ERR_DISK_CONFLICT = "NoDiskConflict"
ERR_VOLUME_ZONE_CONFLICT = "NoVolumeZoneConflict"
ERR_MAX_VOLUME_COUNT = "MaxVolumeCount"
ERR_VOLUME_BINDING = "VolumeBindingFailed"

PVCLister = Callable[[str, str], Optional[PersistentVolumeClaim]]
PVLister = Callable[[str], Optional[PersistentVolume]]
SCLister = Callable[[str], Optional[StorageClass]]
CSINodeLister = Callable[[str], Optional[CSINode]]


def scheduling_relevant_volumes(pod: Pod) -> List[Volume]:
    """Volumes that can change a scheduling decision (PVC refs or the
    inline conflict/count sources)."""
    return [
        v
        for v in pod.volumes
        if v.pvc_claim_name
        or v.gce_pd_name
        or v.aws_volume_id
        or v.azure_disk_name
        or v.rbd_image
        or v.iscsi_iqn
    ]


# ---------------------------------------------------------------------------
# NoDiskConflict (predicates.go:227-293)
# ---------------------------------------------------------------------------

def _is_volume_conflict(volume: Volume, existing_pod: Pod) -> bool:
    if not (volume.gce_pd_name or volume.aws_volume_id or volume.rbd_image or volume.iscsi_iqn):
        return False
    for ev in existing_pod.volumes:
        if volume.gce_pd_name and ev.gce_pd_name:
            if volume.gce_pd_name == ev.gce_pd_name and not (
                volume.gce_pd_read_only and ev.gce_pd_read_only
            ):
                return True
        if volume.aws_volume_id and ev.aws_volume_id:
            if volume.aws_volume_id == ev.aws_volume_id:
                return True
        if volume.iscsi_iqn and ev.iscsi_iqn:
            if volume.iscsi_iqn == ev.iscsi_iqn and not (
                volume.iscsi_read_only and ev.iscsi_read_only
            ):
                return True
        if volume.rbd_image and ev.rbd_image:
            if (
                set(volume.rbd_monitors) & set(ev.rbd_monitors)
                and volume.rbd_pool == ev.rbd_pool
                and volume.rbd_image == ev.rbd_image
                and not (volume.rbd_read_only and ev.rbd_read_only)
            ):
                return True
    return False


def no_disk_conflict(pod: Pod, node_info: NodeInfo) -> bool:
    for v in pod.volumes:
        for ev in node_info.pods:
            if _is_volume_conflict(v, ev):
                return False
    return True


# ---------------------------------------------------------------------------
# NoVolumeZoneConflict (predicates.go:698-800)
# ---------------------------------------------------------------------------

def no_volume_zone_conflict(
    pod: Pod,
    node_info: NodeInfo,
    pvc_lister: PVCLister,
    pv_lister: PVLister,
    sc_lister: Optional[SCLister] = None,
) -> bool:
    """VolumeZoneChecker.predicate: every bound PV's zone/region label set
    must contain the node's value for the same key. Unbound claims of a
    WaitForFirstConsumer class are skipped; other resolution failures fail
    the node (the reference returns an error, which fails the pod there)."""
    if not pod.volumes:
        return True
    node = node_info.node
    node_constraints = {
        k: v
        for k, v in node.labels.items()
        if k in (LABEL_ZONE_FAILURE_DOMAIN, LABEL_ZONE_REGION)
    }
    if not node_constraints:
        return True
    for v in pod.volumes:
        if not v.pvc_claim_name:
            continue
        pvc = pvc_lister(pod.namespace, v.pvc_claim_name)
        if pvc is None:
            return False
        if not pvc.volume_name:
            sc = sc_lister(pvc.storage_class_name) if sc_lister else None
            if sc is not None and sc.volume_binding_mode == VOLUME_BINDING_WAIT:
                continue  # unbound + delayed binding → skip
            return False
        pv = pv_lister(pvc.volume_name)
        if pv is None:
            return False
        for k, val in pv.labels.items():
            if k not in (LABEL_ZONE_FAILURE_DOMAIN, LABEL_ZONE_REGION):
                continue
            node_v = node_constraints.get(k, "")
            zone_set = label_zones_to_set(val)
            if not zone_set:
                continue
            if node_v not in zone_set:
                return False
    return True


# ---------------------------------------------------------------------------
# Max{EBS,GCEPD,AzureDisk}VolumeCount (predicates.go:300-470)
# ---------------------------------------------------------------------------

@dataclass
class VolumeFilter:
    """predicates.go VolumeFilter: map a Volume / PV to its unique id."""

    name: str
    inline_id: Callable[[Volume], str]
    pv_id: Callable[[PersistentVolume], str]
    default_max: int


EBS_FILTER = VolumeFilter(
    name="MaxEBSVolumeCount",
    inline_id=lambda v: v.aws_volume_id,
    pv_id=lambda pv: pv.aws_volume_id,
    default_max=DEFAULT_MAX_EBS_VOLUMES,
)
GCE_PD_FILTER = VolumeFilter(
    name="MaxGCEPDVolumeCount",
    inline_id=lambda v: v.gce_pd_name,
    pv_id=lambda pv: pv.gce_pd_name,
    default_max=DEFAULT_MAX_GCE_PD_VOLUMES,
)
AZURE_DISK_FILTER = VolumeFilter(
    name="MaxAzureDiskVolumeCount",
    inline_id=lambda v: v.azure_disk_name,
    pv_id=lambda pv: pv.azure_disk_name,
    default_max=DEFAULT_MAX_AZURE_DISK_VOLUMES,
)


def max_volume_func(filter_: VolumeFilter) -> int:
    """getMaxVolLimitFromEnv (predicates.go:370-402): KUBE_MAX_PD_VOLS
    overrides the per-cloud default."""
    raw = os.environ.get(KUBE_MAX_PD_VOLS, "")
    if raw:
        try:
            n = int(raw)
            if n > 0:
                return n
        except ValueError:
            pass
    return filter_.default_max


def _filter_volume_ids(
    filter_: VolumeFilter,
    pod: Pod,
    pvc_lister: PVCLister,
    pv_lister: PVLister,
) -> Set[str]:
    """Unique volume ids of `pod` matching the filter; unbound/unresolvable
    PVCs count as their own conservative placeholder id
    (predicates.go:480-540 filterVolumes)."""
    ids: Set[str] = set()
    for v in pod.volumes:
        vid = filter_.inline_id(v)
        if vid:
            ids.add(vid)
            continue
        if not v.pvc_claim_name:
            continue
        pvc = pvc_lister(pod.namespace, v.pvc_claim_name)
        if pvc is None or not pvc.volume_name:
            # unknown/unbound claim: conservatively unique per claim
            ids.add(f"{pod.namespace}/{v.pvc_claim_name}")
            continue
        pv = pv_lister(pvc.volume_name)
        if pv is None:
            ids.add(pvc.volume_name)
            continue
        pvid = filter_.pv_id(pv)
        if pvid:
            ids.add(pvid)
    return ids


def max_pd_volume_count(
    filter_: VolumeFilter,
    pod: Pod,
    node_info: NodeInfo,
    pvc_lister: PVCLister,
    pv_lister: PVLister,
) -> bool:
    new_ids = _filter_volume_ids(filter_, pod, pvc_lister, pv_lister)
    if not new_ids:
        return True
    existing: Set[str] = set()
    for ep in node_info.pods:
        existing |= _filter_volume_ids(filter_, ep, pvc_lister, pv_lister)
    num_new = len(new_ids - existing)
    return len(existing) + num_new <= max_volume_func(filter_)


# ---------------------------------------------------------------------------
# MaxCSIVolumeCount (csi_volume_predicate.go)
# ---------------------------------------------------------------------------

def max_csi_volume_count(
    pod: Pod,
    node_info: NodeInfo,
    pvc_lister: PVCLister,
    pv_lister: PVLister,
    csinode_lister: Optional[CSINodeLister],
) -> bool:
    """Per-driver attachable limits from CSINode. No CSINode / no limits →
    predicate passes (csi_volume_predicate.go:63-75)."""
    if csinode_lister is None:
        return True
    csinode = csinode_lister(node_info.node.name)
    if csinode is None or not csinode.driver_limits:
        return True

    def csi_ids(p: Pod):
        out = {}
        for v in p.volumes:
            if not v.pvc_claim_name:
                continue
            pvc = pvc_lister(p.namespace, v.pvc_claim_name)
            if pvc is None or not pvc.volume_name:
                continue
            pv = pv_lister(pvc.volume_name)
            if pv is None or not pv.csi_driver:
                continue
            out[f"{pv.csi_driver}/{pv.csi_volume_handle or pv.name}"] = pv.csi_driver
        return out

    new = csi_ids(pod)
    if not new:
        return True
    existing = {}
    for ep in node_info.pods:
        existing.update(csi_ids(ep))
    for driver, limit in csinode.driver_limits.items():
        have = {k for k, d in existing.items() if d == driver}
        want = {k for k, d in new.items() if d == driver}
        if len(have | want) > limit:
            return False
    return True


# ---------------------------------------------------------------------------
# Combined checker (what the driver installs)
# ---------------------------------------------------------------------------

def make_volume_checker(
    pvc_lister: PVCLister,
    pv_lister: PVLister,
    sc_lister: Optional[SCLister] = None,
    csinode_lister: Optional[CSINodeLister] = None,
    binder=None,
    enabled: Optional[frozenset] = None,
) -> Callable[[Pod, NodeInfo], Tuple[bool, List[str]]]:
    """The volume predicates in default-provider order, filtered by the
    Policy/provider `enabled` set (None = all); `binder` adds the
    CheckVolumeBinding row (volumebinder seam)."""

    def on(name: str) -> bool:
        return enabled is None or name in enabled

    def check(pod: Pod, node_info: NodeInfo) -> Tuple[bool, List[str]]:
        reasons: List[str] = []
        if on("NoDiskConflict") and not no_disk_conflict(pod, node_info):
            reasons.append(ERR_DISK_CONFLICT)
        if on("NoVolumeZoneConflict") and not no_volume_zone_conflict(
            pod, node_info, pvc_lister, pv_lister, sc_lister
        ):
            reasons.append(ERR_VOLUME_ZONE_CONFLICT)
        for f in (EBS_FILTER, GCE_PD_FILTER, AZURE_DISK_FILTER):
            if on(f.name) and not max_pd_volume_count(f, pod, node_info, pvc_lister, pv_lister):
                reasons.append(f.name)
        if on("MaxCSIVolumeCountPred") and not max_csi_volume_count(
            pod, node_info, pvc_lister, pv_lister, csinode_lister
        ):
            reasons.append("MaxCSIVolumeCount")
        if binder is not None and on("CheckVolumeBinding"):
            ok, r = binder.find_pod_volumes(pod, node_info)
            if not ok:
                reasons.extend(r or [ERR_VOLUME_BINDING])
        return (not reasons), reasons

    return check
