"""Storage API objects the scheduler reads.

The scheduling-visible subsets of PersistentVolume, PersistentVolumeClaim,
StorageClass (storage.k8s.io/v1) and CSINode (storage.k8s.io/v1beta1) —
exactly the fields the reference's volume predicates and binder consult
(predicates.go:698-800 VolumeZoneChecker, :300-470 MaxPDVolumeCountChecker,
csi_volume_predicate.go, volumebinder/volume_binder.go).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict

VOLUME_BINDING_IMMEDIATE = "Immediate"
VOLUME_BINDING_WAIT = "WaitForFirstConsumer"

# Multi-zone PV label separator (volumehelpers.LabelZonesToSet: "us-a__us-b").
ZONE_LABEL_SEPARATOR = "__"


def label_zones_to_set(value: str) -> set:
    """volumehelpers.LabelZonesToSet: '__'-separated zone list → set."""
    return {z for z in value.split(ZONE_LABEL_SEPARATOR) if z} if value else set()


@dataclass
class PersistentVolume:
    name: str = ""
    labels: Dict[str, str] = field(default_factory=dict)
    # sources (exactly one set), for volume-count filters + CSI limits
    gce_pd_name: str = ""
    aws_volume_id: str = ""
    azure_disk_name: str = ""
    csi_driver: str = ""
    csi_volume_handle: str = ""
    storage_class_name: str = ""
    # simplified NodeAffinity: required zone/region sets already folded into
    # labels (the reference's PV.NodeAffinity is out of scope in this
    # version's default predicates; zone labels are the contract)


@dataclass
class PersistentVolumeClaim:
    name: str = ""
    namespace: str = "default"
    volume_name: str = ""  # bound PV name ("" = unbound)
    storage_class_name: str = ""
    phase: str = "Pending"


@dataclass
class StorageClass:
    name: str = ""
    provisioner: str = ""
    volume_binding_mode: str = VOLUME_BINDING_IMMEDIATE


@dataclass
class CSINode:
    """storage.k8s.io CSINode: per-driver attachable volume limits."""

    name: str = ""
    driver_limits: Dict[str, int] = field(default_factory=dict)


def pv_from_k8s(obj: dict) -> PersistentVolume:
    meta = obj.get("metadata") or {}
    spec = obj.get("spec") or {}
    pv = PersistentVolume(
        name=meta.get("name", ""),
        labels=dict(meta.get("labels") or {}),
        storage_class_name=spec.get("storageClassName", ""),
    )
    if spec.get("gcePersistentDisk"):
        pv.gce_pd_name = spec["gcePersistentDisk"].get("pdName", "")
    if spec.get("awsElasticBlockStore"):
        pv.aws_volume_id = spec["awsElasticBlockStore"].get("volumeID", "")
    if spec.get("azureDisk"):
        pv.azure_disk_name = spec["azureDisk"].get("diskName", "")
    if spec.get("csi"):
        pv.csi_driver = spec["csi"].get("driver", "")
        pv.csi_volume_handle = spec["csi"].get("volumeHandle", "")
    return pv


def pvc_from_k8s(obj: dict) -> PersistentVolumeClaim:
    meta = obj.get("metadata") or {}
    spec = obj.get("spec") or {}
    status = obj.get("status") or {}
    return PersistentVolumeClaim(
        name=meta.get("name", ""),
        namespace=meta.get("namespace", "default"),
        volume_name=spec.get("volumeName", ""),
        storage_class_name=spec.get("storageClassName", "") or "",
        phase=status.get("phase", "Pending"),
    )


def storage_class_from_k8s(obj: dict) -> StorageClass:
    meta = obj.get("metadata") or {}
    return StorageClass(
        name=meta.get("name", ""),
        provisioner=obj.get("provisioner", ""),
        volume_binding_mode=obj.get("volumeBindingMode") or VOLUME_BINDING_IMMEDIATE,
    )


def csinode_from_k8s(obj: dict) -> CSINode:
    meta = obj.get("metadata") or {}
    limits: Dict[str, int] = {}
    for drv in (obj.get("spec") or {}).get("drivers") or []:
        alloc = drv.get("allocatable") or {}
        if alloc.get("count") is not None:
            limits[drv.get("name", "")] = int(alloc["count"])
    return CSINode(name=meta.get("name", ""), driver_limits=limits)
