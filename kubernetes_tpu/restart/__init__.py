"""Crash-restart plane: cold-start reconciliation + kill-point chaos.

The process-level complement of the fault plane (kubernetes_tpu/faults):
PR 13 made the scheduler survive plane faults INSIDE a live process;
this package makes the process itself killable anywhere — ``kill -9``
mid-drain, mid-bind, mid-preemption — and restartable with zero lost
and zero double-bound pods, because the API server is the only durable
state and everything device-resident is reconstructible from a relist.

* ``reconcile`` — ``cold_start``: the phase-timed rebuild (relist →
  nodes → bulk columnar re-assume → queue/slab re-admission →
  nomination overlay → informers → bank resync → persistent-ladder
  re-warm).
* ``supervisor`` — the deterministic crash harness: ``crash:<site>``
  kill-points (faults/inject) raise ``SimulatedCrash``, the Supervisor
  buries the dead instance, rebuilds, reconciles, resumes.
* ``invariants`` — ``check_invariants``: the per-cell acceptance gate
  (zero lost, zero double-bound, no over-commit, clean shadow audit).
"""

from .invariants import check_invariants, check_overcommit
from .reconcile import PHASES, ReconcileReport, cold_start
from .supervisor import (
    Incarnation,
    Supervisor,
    SupervisorReport,
    make_scheduler_factory,
    run_cell,
)

__all__ = [
    "PHASES",
    "Incarnation",
    "ReconcileReport",
    "Supervisor",
    "SupervisorReport",
    "check_invariants",
    "check_overcommit",
    "cold_start",
    "make_scheduler_factory",
    "run_cell",
]
