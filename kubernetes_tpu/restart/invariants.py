"""The restart matrix's invariant checker: what MUST hold after any
kill × restart × resumed-drain cell.

* **Zero lost pods** — every pod ever created is either bound in the
  store, legitimately evicted (a preemption victim — the caller names
  the evictable set), or still present and pending (a completed drain
  has none of those). A pod in NONE of these states died with a process
  and was never reconstructed: the exact bug class this plane exists to
  kill.
* **Zero double-bound pods** — structural at the store (the binding
  subresource 409s any re-bind), so the checker asserts the conflict
  ledger: ``mismatch`` outcomes must be zero (a mismatch means some
  incarnation tried to bind a pod somewhere else — a double-schedule
  the fence caught; benign outcomes are expected replays and fine).
* **No node over-commit** — per node, the bound pods' accumulated
  requests fit the allocatable for every resource, pod count included.
  Over-commit is how a missed re-assume manifests: the restarted
  scheduler solves against capacity the dead process already spent.
* **Clean shadow audit** — the surviving instance's device banks and
  columns are bit-true to host truth (the PR 10 probe at the PR 13
  sync point): a restart must not leave the device planes quietly
  skewed.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set


def check_invariants(
    api,
    created_keys: Sequence[str],
    evictable_keys: Sequence[str] = (),
    sched=None,
    mismatch_conflicts: Optional[int] = None,
) -> List[str]:
    """Return the list of violated invariants (empty = cell green).
    `mismatch_conflicts` is the DELTA of
    ``scheduler_bind_conflicts_total{outcome=mismatch}`` over the cell
    (the caller baselines the process-global counter). `sched` (the
    surviving instance, driver thread) arms the shadow-audit check."""
    problems: List[str] = []
    pods, _ = api.list("pods")
    by_key = {p.key(): p for p in pods}
    evictable: Set[str] = set(evictable_keys)

    # zero lost
    lost = [
        k for k in created_keys
        if k not in by_key and k not in evictable
    ]
    if lost:
        problems.append(f"lost pods (absent from the store): {sorted(lost)[:8]}")
    unbound = [k for k, p in by_key.items() if not p.node_name]
    if unbound:
        problems.append(
            f"{len(unbound)} pod(s) present but never bound: "
            f"{sorted(unbound)[:8]}"
        )

    # zero double-bound
    if mismatch_conflicts:
        problems.append(
            f"{mismatch_conflicts} mismatch bind conflict(s): some "
            "incarnation attempted to bind an already-bound pod to a "
            "DIFFERENT node"
        )

    # no node over-commit
    problems.extend(check_overcommit(api))

    # clean shadow audit on the survivor
    if sched is not None:
        try:
            sched._commit_pipe.drain()
            sched.mirror.sync()
            div = sched._probe_divergence(["ingest", "terms"])
        except Exception as e:
            div = [f"audit-error:{e!r}"]
        if div:
            problems.append(f"shadow audit divergent after restart: {div}")
    return problems


def check_overcommit(api) -> List[str]:
    """Per-node occupancy vs allocatable over the STORE's bound pods —
    independent of any scheduler instance's cache, so a cache that
    forgot a binding cannot hide the over-commit it caused."""
    from ..api.types import RESOURCE_CPU
    from ..oracle.nodeinfo import accumulated_request

    problems: List[str] = []
    nodes, _ = api.list("nodes")
    pods, _ = api.list("pods")
    used: Dict[str, Dict[str, int]] = {}
    count: Dict[str, int] = {}
    for p in pods:
        if not p.node_name:
            continue
        acc = used.setdefault(p.node_name, {})
        count[p.node_name] = count.get(p.node_name, 0) + 1
        # accumulated_request is milli for cpu, raw for everything else
        for rn, v in accumulated_request(p).items():
            if rn != "pods":
                acc[rn] = acc.get(rn, 0) + v
    for n in nodes:
        acc = used.get(n.name, {})
        for rn, v in acc.items():
            alloc_q = n.allocatable.get(rn)
            if alloc_q is None:
                continue
            cap = (
                alloc_q.milli_value() if rn == RESOURCE_CPU else alloc_q.value()
            )
            if v > cap:
                problems.append(
                    f"node {n.name} over-committed on {rn}: {v} > {cap}"
                )
        pods_alloc = n.allocatable.get("pods")
        if pods_alloc is not None and count.get(n.name, 0) > pods_alloc.value():
            problems.append(
                f"node {n.name} over pod capacity: "
                f"{count.get(n.name, 0)} > {pods_alloc.value()}"
            )
    return problems
