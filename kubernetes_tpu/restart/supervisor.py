"""Crash-restart supervisor: kill the scheduler anywhere, resume the drain.

The deterministic chaos harness for process-level death. A ``Supervisor``
owns ONE persistent ``FakeAPIServer`` (the durable state — "etcd is the
checkpoint") and drives scheduler INSTANCES against it. The active
``FaultPlan``'s ``crash:<site>[@n]`` kill-points simulate ``kill -9`` at
a named pipeline stage: the firing thread raises ``SimulatedCrash`` (a
BaseException no fault handler absorbs) and latches ``plan.crashed``, so

* the supervisor's drive loop detects the death even when the kill-point
  fired on a worker thread (commit worker, bind pool, uploader), and
* the dead instance's surviving threads are FENCED: every outward write
  (bind POST, victim delete, nomination patch) passes ``crash_gate()``
  first and dies instead of mutating the API server post-mortem —
  ``kill -9`` stops all threads at once; the gate is the in-process
  equivalent, with the one honest relaxation that a write already past
  the gate when the crash fires behaves as if it landed just before
  death (indistinguishable from the API server's point of view).

On death the supervisor ABANDONS the instance (``Scheduler.abort()`` —
no flush, no persist, no graceful anything; a dead process cleans
nothing), builds a fresh instance with a fresh cache/queue/mirror, and
``cold_start``-reconciles it from the API server (restart/reconcile.py).
The compile plan hands each incarnation the SAME persistent cache
directory, so a restart re-warms trace-only (``misses_after_warmup ==
0`` across the kill).

``check_invariants`` is the per-cell acceptance gate: zero lost pods,
zero double-bound pods (structural: the binding subresource 409s any
re-bind, plus a zero mismatch-conflict count), no node over-commit
against allocatable, and a clean shadow audit on the surviving
instance's device banks.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..faults.inject import FaultPlan, SimulatedCrash
from .reconcile import ReconcileReport, cold_start


@dataclass
class Incarnation:
    """One scheduler instance's lifetime under the supervisor."""

    index: int
    sched: object
    informers: Dict = field(default_factory=dict)
    report: Optional[ReconcileReport] = None
    outcome: str = "running"  # running | crashed:<site> | done | timeout


@dataclass
class SupervisorReport:
    """One chaos cell's result: the incarnation trail + terminal state."""

    incarnations: List[Incarnation] = field(default_factory=list)
    crashes: int = 0
    completed: bool = False
    problems: List[str] = field(default_factory=list)

    @property
    def final(self) -> Incarnation:
        return self.incarnations[-1]


class Supervisor:
    """Build → drive → (crash → bury → rebuild → reconcile)* → verify.

    `scheduler_factory(fault_plan)` must return a FRESH Scheduler wired
    to the supervisor's API server: its binder/delete_fn/nominate_fn
    must route through ``guard()`` so the crash fence holds (the
    module-level ``build_instance`` helper wires the standard shape).
    """

    def __init__(self, api, plan: Optional[FaultPlan],
                 scheduler_factory, scheduler_name: str = "default-scheduler"):
        self.api = api
        self.plan = plan
        self.scheduler_factory = scheduler_factory
        self.scheduler_name = scheduler_name
        self.report = SupervisorReport()
        # harness hook: called as on_tick(supervisor, incarnation) once
        # per drive iteration — chaos cells inject mid-drain arrivals /
        # node churn here (the open-loop traffic the matrix needs)
        self.on_tick = None
        # harness hook: called as on_restart(supervisor) after a dead
        # incarnation is buried and BEFORE its successor cold-starts —
        # the window where "traffic that arrived while the process was
        # down" lands in the store, so the restart's relist (and its
        # warmup census over the relisted queue) sees it
        self.on_restart = None

    # -- the crash fence ------------------------------------------------------

    def guard(self, fn):
        """Wrap an outward-facing write so a dead instance's surviving
        threads cannot keep mutating the API server."""
        plan = self.plan
        if plan is None:
            return fn

        def gated(*a, **k):
            plan.crash_gate()
            return fn(*a, **k)

        return gated

    # -- lifecycle ------------------------------------------------------------

    def _spawn(self) -> Incarnation:
        plan = self.plan
        if plan is not None and self.report.incarnations:
            # the restarted incarnation sees the same schedule (already-
            # fired kill-points stay fired) with the crash latch cleared
            self.plan = plan = plan.rearm()
        sched = self.scheduler_factory(plan)
        inc = Incarnation(index=len(self.report.incarnations), sched=sched)
        self.report.incarnations.append(inc)
        # cold_start may itself hit a kill-point (a crash scheduled into
        # a warmup-time flush) — the caller supervises it like any death
        inc.report = cold_start(
            sched, self.api, scheduler_name=self.scheduler_name,
            fault_plan=plan,
        )
        inc.informers = getattr(sched, "restart_informers", {}) or {}
        return inc

    def _bury(self, inc: Incarnation) -> None:
        """Post-mortem cleanup of the HARNESS's threads (informers,
        pools) — never graceful scheduler shutdown: the crash fence has
        every in-flight task fast-failing, so the joins are bounded.
        The dead instance's state is garbage by definition; only the
        API server carries truth forward."""
        # a crash INSIDE cold_start means inc.informers was never
        # populated — the reconcile path publishes the started watchers
        # on the scheduler the moment they exist, so read both
        informers = dict(
            getattr(inc.sched, "restart_informers", {}) or {}
        )
        informers.update(inc.informers)
        for inf in informers.values():
            try:
                inf.stop()
            except Exception:
                pass
        try:
            inc.sched.abort()
        except BaseException:
            pass  # a second SimulatedCrash out of a drain is expected

    def _drive(self, inc: Incarnation, deadline: float,
               settle_s: float = 0.05) -> str:
        """Run one incarnation's drain until the cluster is fully bound
        (API-server truth), a kill-point fires, or the deadline passes."""
        plan = self.plan
        api = self.api
        sched = inc.sched
        queue = sched.queue
        while time.monotonic() < deadline:
            if plan is not None and plan.crashed is not None:
                return f"crashed:{plan.crashed}"
            if self.on_tick is not None:
                self.on_tick(self, inc)
            live, _ = api.list("pods")
            if all(p.node_name for p in live) and queue.pending_count() == 0:
                try:
                    sched.wait_for_binds()
                except SimulatedCrash as e:
                    return f"crashed:{e}"
                live, _ = api.list("pods")
                if all(p.node_name for p in live):
                    return "done"
            try:
                r = sched.schedule_batch()
            except SimulatedCrash as e:
                return f"crashed:{e}"
            if plan is not None and plan.crashed is not None:
                # a worker-thread kill-point fired during this batch
                return f"crashed:{plan.crashed}"
            if not (r.scheduled or r.unschedulable or r.errors or r.deferred):
                try:
                    sched.service_faults()
                except SimulatedCrash as e:
                    return f"crashed:{e}"
                queue.flush()
                time.sleep(settle_s)  # binds/backoffs/informer lag settle
        return "timeout"

    # ktpu: thread-entry(driver) the supervisor's thread IS each
    # incarnation's driver: it cold-starts, drives schedule_batch, and
    # buries — there is no separate supervisor thread to confine
    def run(self, budget_s: float = 120.0, max_restarts: int = 8) -> SupervisorReport:
        """The supervision loop: drive until the drain completes, the
        budget expires, or the restart bound trips (a runaway crash
        schedule must fail loudly, not spin). A kill-point firing inside
        reconciliation/warmup is supervised like any other death."""
        deadline = time.monotonic() + budget_s
        while True:
            try:
                inc = self._spawn()
                outcome = self._drive(inc, deadline)
            except SimulatedCrash as e:
                inc = self.report.incarnations[-1]
                outcome = f"crashed:{e}"
            inc.outcome = outcome
            if outcome == "done":
                self.report.completed = True
                return self.report
            if outcome == "timeout":
                self.report.problems.append(
                    f"incarnation {inc.index} timed out mid-drain"
                )
                return self.report
            # crashed: bury, rebuild, reconcile, resume
            self.report.crashes += 1
            self._bury(inc)
            if self.report.crashes > max_restarts:
                self.report.problems.append(
                    f"restart bound exceeded ({max_restarts})"
                )
                return self.report
            if self.on_restart is not None:
                self.on_restart(self)


# ---------------------------------------------------------------------------
# the standard instance shape (what perf_smoke/tests wire)
# ---------------------------------------------------------------------------

def make_scheduler_factory(
    supervisor_ref: Dict,
    api,
    compile_cache_dir: Optional[str] = None,
    scheduler_kwargs: Optional[Dict] = None,
):
    """Factory building the standard API-server-wired instance: an
    idempotent APIBinder, victim deletes and nomination patches against
    the store, every outward write behind the crash fence, and a
    compile plan persisting to `compile_cache_dir` so every incarnation
    re-warms from the previous one's ladder. `supervisor_ref` is a
    one-slot dict the caller fills with the Supervisor after
    construction (factory and supervisor reference each other)."""
    from ..apiserver.store import NotFoundError
    from ..client.informer import APIBinder
    from ..compile import CompilePlan
    from ..compile.cache import PersistentCompileCache
    from ..scheduler.driver import Binder, Scheduler
    from ..state.cache import SchedulerCache
    from ..state.queue import PriorityQueue

    def factory(fault_plan):
        sup = supervisor_ref["sup"]
        api_binder = APIBinder(api)

        def delete_victim(p):
            # kube semantics: deleting an already-gone victim is a no-op
            try:
                api.delete("pods", p.key())
            except NotFoundError:
                pass

        def nominate(pod, node):
            api.update_pod_status(
                pod.namespace, pod.name, nominated_node_name=node
            )

        plan = None
        if compile_cache_dir is not None:
            plan = CompilePlan(cache=PersistentCompileCache(compile_cache_dir))
        kwargs = dict(
            cache=SchedulerCache(),
            queue=PriorityQueue(),
            binder=Binder(sup.guard(api_binder.bind)),
            delete_fn=sup.guard(delete_victim),
            nominate_fn=sup.guard(nominate),
            fault_plan=fault_plan,
        )
        if plan is not None:
            kwargs["compile_plan"] = plan
        kwargs.update(scheduler_kwargs or {})
        return Scheduler(**kwargs)

    return factory


def run_cell(
    api,
    crash_spec: str,
    compile_cache_dir: Optional[str] = None,
    scheduler_kwargs: Optional[Dict] = None,
    budget_s: float = 120.0,
    extra_faults: str = "",
    on_tick=None,
    on_restart=None,
) -> SupervisorReport:
    """One chaos-matrix cell: supervise a drain of `api`'s current pods
    under `crash_spec` (e.g. ``"crash:mid-bind-chunk@2"``; semicolon-
    join several for multi-restart cells; `extra_faults` appends
    ordinary PR 13 fault sites). Returns the SupervisorReport — the
    caller asserts invariants via ``check_invariants``."""
    spec = ";".join(s for s in (crash_spec, extra_faults) if s)
    plan = FaultPlan.parse(spec) if spec else None
    ref: Dict = {}
    factory = make_scheduler_factory(
        ref, api, compile_cache_dir=compile_cache_dir,
        scheduler_kwargs=scheduler_kwargs,
    )
    sup = Supervisor(api, plan, factory)
    sup.on_tick = on_tick
    sup.on_restart = on_restart
    ref["sup"] = sup
    return sup.run(budget_s=budget_s)
