"""Cold-start reconciliation: rebuild EVERYTHING from the API server.

The reference kube-scheduler is deliberately restartable: on startup it
relists through its informers, rebuilds the scheduler cache from the
assigned pods it finds (`spec.nodeName`), reconstructs the nominated-pod
map from `status.nominatedNodeName`, and resumes scheduling — etcd (the
API server) is the ONLY durable state (PAPER.md §6, the `scheduleOne` /
cache-rebuild contract). This module gives our scheduler the same
property with six device-resident planes in the way: a ``cold_start``
rebuilds, in order,

  1. **relist** — one LIST per kind against the persistent API server
     (the single source of truth; nothing from the dead process is
     consulted, because nothing from the dead process exists).
  2. **nodes** — the cluster topology into the cache (and its
     CacheColumns rows, when the columnar plane is armed).
  3. **assume** — every BOUND pod bulk re-added as CONFIRMED state
     through the columnar path (``SchedulerCache.add_pods``: one
     vectorized scatter of interned per-spec delta rows — O(batch), not
     an O(pods) object walk). This MUST precede any scheduling: a pod
     solved before its node's occupancy is restored would double-book
     capacity (the re-assume-before-schedule ordering invariant,
     INVARIANTS.md).
  4. **queue** — every pending pod owned by this scheduler re-admitted
     through ``PriorityQueue.add``, which re-stages the ingest/term
     slabs exactly as a live admission would (enqueue-time encoding is
     the admission path — a restart is just a very large admission
     burst) and rebuilds the nominated-pod overlay from each pod's
     persisted ``status.nominatedNodeName`` — an in-flight preemption
     RESUMES (the preemptor re-solves into its reserved capacity)
     instead of re-evicting fresh victims.
  5. **nominations** — verification that the overlay matches the wire
     truth exactly (counted; a mismatch is a reconciliation bug, not a
     warning).
  6. **informers** — the reflector loops start and complete their
     initial sync. Their relist re-delivers objects the direct phases
     already applied; every handler target (queue.add, cache.add_pod)
     is idempotent under re-delivery by contract, and no scheduling has
     begun yet, so the duplicate window is race-free.
  7. **banks** — the TensorMirror is marked device-stale (host truth
     wins; the PR 13 resync primitive) and synced host-side.
  8. **warmup** — ``Scheduler.warmup()``: the persisted compile ladder
     re-warms trace-only against the XLA persistent cache
     (``misses_after_warmup == 0`` holds across a restart), the full
     device banks upload, and the staged-bank uploaders arm.

Binds that were IN FLIGHT at death need no replay log: the API server
already resolved them. A bind whose POST landed shows up in the relist
as a bound pod (phase 3 re-assumes it); one whose POST never happened
shows up pending (phase 4 re-queues it; the resumed drain re-solves and
re-binds). The only ambiguous case — the POST landed but the dead
process never saw the response — resolves at re-bind time: the binding
subresource 409s, and the idempotent binder counts a same-node Conflict
as success (``scheduler_bind_conflicts_total{outcome=benign}``).

Every phase is timed into
``scheduler_restart_reconcile_duration_seconds{phase}`` and the report
lands on ``sched.restart_report`` (surfaced by the census /
``ktpu_top``).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..metrics import metrics as M

#: report phases, in execution order (the census/ktpu_top render order)
PHASES = (
    "relist", "nodes", "assume", "queue", "nominations", "informers",
    "banks", "warmup",
)


@dataclass
class ReconcileReport:
    """One cold start's phase-timed flight record (JSON-serializable via
    as_dict — the census carries it)."""

    started_unix: float = 0.0
    phases_s: Dict[str, float] = field(default_factory=dict)
    nodes: int = 0
    bound: int = 0
    pending: int = 0
    nominations: int = 0
    nomination_mismatches: int = 0
    warmed_pods: int = 0
    total_s: float = 0.0

    def as_dict(self) -> Dict:
        return {
            "started_unix": self.started_unix,
            "phases_s": {k: round(v, 6) for k, v in self.phases_s.items()},
            "nodes": self.nodes,
            "bound": self.bound,
            "pending": self.pending,
            "nominations": self.nominations,
            "nomination_mismatches": self.nomination_mismatches,
            "warmed_pods": self.warmed_pods,
            "total_s": round(self.total_s, 6),
        }


class _Phase:
    """Context manager: one timed reconciliation phase (metric + report)."""

    def __init__(self, report: ReconcileReport, name: str):
        self.report = report
        self.name = name

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dt = time.perf_counter() - self._t0
        self.report.phases_s[self.name] = dt
        M.restart_reconcile_duration.observe(dt, self.name)
        return False


def cold_start(
    sched,
    api,
    scheduler_name: str = "default-scheduler",
    handlers=None,
    start_informers: bool = True,
    fault_plan=None,
    warmup: bool = True,
    informer_sync_timeout: float = 30.0,
) -> ReconcileReport:
    """Reconcile a FRESH ``Scheduler`` against `api` (module docstring
    for the phase contract). The scheduler must not have scheduled
    anything yet — reconciliation is a cold-start path, not a live
    repair (the fault plane owns live repairs). Returns the phase-timed
    report (also stored on ``sched.restart_report``); when
    `start_informers`, the started informers land on
    ``sched.restart_informers`` (the caller owns stopping them)."""
    from ..client.informer import start_scheduler_informers
    from ..scheduler.eventhandlers import EventHandlers

    report = ReconcileReport(started_unix=time.time())
    t_total = time.perf_counter()

    with _Phase(report, "relist"):
        node_items, _node_rv = api.list("nodes")
        pod_items, _pod_rv = api.list("pods")

    with _Phase(report, "nodes"):
        for node in node_items:
            sched.cache.add_node(node)
        report.nodes = len(node_items)

    with _Phase(report, "assume"):
        bound = [p for p in pod_items if p.node_name]
        sched.cache.add_pods(bound)
        report.bound = len(bound)

    with _Phase(report, "queue"):
        pending = [
            p for p in pod_items
            if not p.node_name and p.scheduler_name == scheduler_name
        ]
        for p in pending:
            sched.queue.add(p)
        report.pending = len(pending)

    with _Phase(report, "nominations"):
        # the overlay was rebuilt by queue.add (each pod's persisted
        # status.nominatedNodeName feeds _update_nominated); verify it
        # matches the wire truth EXACTLY — a miss here means a resumed
        # preemption would re-evict, the bug this phase exists to catch
        want = {
            p.key(): p.nominated_node_name
            for p in pending if p.nominated_node_name
        }
        have: Dict[str, str] = {}
        for node in set(want.values()):
            for p in sched.queue.nominated_pods_for_node(node):
                have[p.key()] = node
        report.nominations = len(want)
        report.nomination_mismatches = sum(
            1 for k, n in want.items() if have.get(k) != n
        )

    if start_informers:
        with _Phase(report, "informers"):
            h = handlers or EventHandlers(
                sched.cache, sched.queue, scheduler_name=scheduler_name
            )
            informers = start_scheduler_informers(
                api, h, fault_plan=fault_plan
            )
            # publish IMMEDIATELY: a crash in the banks/warmup phases
            # below must not strand the just-started watcher threads in
            # a local the supervisor's _bury can never reach
            sched.restart_informers = informers
            for inf in informers.values():
                if not inf.wait_for_sync(informer_sync_timeout):
                    raise TimeoutError(
                        f"informer {inf.kind} failed initial sync within "
                        f"{informer_sync_timeout}s"
                    )

    with _Phase(report, "banks"):
        # host truth wins: whatever a previous incarnation left on the
        # device is unreachable (new process) — mark stale so the first
        # device_arrays() performs the full re-upload, then build the
        # host-side mirror structures from the reconciled cache
        sched.mirror.mark_device_stale()
        sched.mirror.sync()

    if warmup:
        with _Phase(report, "warmup"):
            report.warmed_pods = sched.warmup()

    report.total_s = time.perf_counter() - t_total
    M.restarts.inc()
    sched.restart_report = report.as_dict()
    return report
