"""CompilePlan: the registry of declared XLA program signatures.

Every solve entry point (ops/pipeline solve/gang/filter, ops/preempt)
routes its signature through `admit()` before dispatch. The plan
canonicalizes it onto the ladder, answers "was this pre-declared?", and
keeps the telemetry the north-star bench asserts on: per-spec compile
time, hit/miss counters, ladder coverage, and the
misses-after-warmup gauge that must read ZERO on a healthy drain. A miss
never blocks anything — the jit fallback compiles inline — but it is
logged loudly (utils/trace logger) because each one is a multi-second
stall the warmup should have paid.
"""

from __future__ import annotations

import logging
import time
from typing import Dict, List, Optional, Tuple

from ..analysis.lockorder import audited_lock
from .cache import PersistentCompileCache
from .ladder import ShapeLadder, SolveSpec

logger = logging.getLogger("kubernetes_tpu.compile")

SOURCE_WARMUP = "warmup"
SOURCE_PERSISTED = "persisted"
SOURCE_INLINE = "inline"


class CompilePlan:
    """Thread-safe (the warmup worker declares from its own thread while
    the driver admits from the scheduling loop)."""

    def __init__(
        self,
        ladder: Optional[ShapeLadder] = None,
        cache: Optional[PersistentCompileCache] = None,
    ):
        self.ladder = ladder or ShapeLadder()
        self.cache = cache
        self._lock = audited_lock("compile-plan")
        # spec key -> {"spec", "compile_s", "source", "count"}
        self._records: Dict[Tuple, Dict] = {}  # ktpu: guarded-by(self._lock)
        # ktpu: allow(KTPU006) monotone warm flag: single False->True
        # transition under the lock (mark_warmed); racy readers see a
        # stale False at worst (a miss counted as warmup-sourced), never
        # a correctness fault — deliberately lock-free on the hot path
        self.warmed = False
        # ktpu: guarded-by(self._lock)
        self.stats: Dict[str, float] = {
            "hits": 0,
            "misses": 0,
            "misses_after_warmup": 0,
            "compiles": 0,
            "compile_s": 0.0,
        }

    @classmethod
    def default(cls) -> "CompilePlan":
        """Plan with persistence iff KTPU_COMPILE_CACHE_DIR names a dir."""
        return cls(cache=PersistentCompileCache.from_env())

    # -- the hot-path gate ----------------------------------------------------

    def canonicalize(self, spec: SolveSpec) -> SolveSpec:
        return self.ladder.canonicalize(spec)

    def admit(self, spec: SolveSpec) -> bool:
        """Account one dispatch of `spec` (already at canonical buckets —
        the driver's monotone buckets are ladder rungs by construction).
        Returns True on a hit (program already declared). A miss declares
        the spec (the inline jit compile that follows makes it real) and,
        after warmup, bumps the miss gauge and logs — the signal that the
        ladder under-covers the workload."""
        c = self.ladder.canonicalize(spec)
        with self._lock:
            rec = self._records.get(c.key())
            if rec is not None:
                rec["count"] += 1
                self.stats["hits"] += 1
                self._metric_hit(len(self._records))
                return True
            self.stats["misses"] += 1
            after = self.warmed
            if after:
                self.stats["misses_after_warmup"] += 1
            self._declare_locked(c, 0.0, SOURCE_INLINE)
            n_specs = len(self._records)
            mis = int(self.stats["misses_after_warmup"])
        self._metric_miss(after, n_specs, mis)
        if after:
            logger.warning(
                "compile-plan MISS after warmup: %s — compiling inline "
                "(declare this spec in the warmup ladder)", c.short(),
            )
        return False

    # -- declaration / compile accounting -------------------------------------

    def _declare_locked(self, c: SolveSpec, secs: float, source: str) -> None:
        self.ladder.declare(c)
        self._records[c.key()] = {
            "spec": c, "compile_s": float(secs), "source": source, "count": 0,
        }

    def declare(self, spec: SolveSpec, source: str = SOURCE_WARMUP) -> SolveSpec:
        """Pre-declare a spec (warmup/persisted ladder) without counting a
        dispatch."""
        c = self.ladder.canonicalize(spec)
        with self._lock:
            if c.key() not in self._records:
                self._declare_locked(c, 0.0, source)
        return c

    def note_compiled(self, spec: SolveSpec, seconds: float, source: str) -> None:
        """Record an actual trace+compile of `spec` (warmup measures its
        warm calls; the driver attributes a missed dispatch's wall)."""
        c = self.ladder.canonicalize(spec)
        with self._lock:
            rec = self._records.get(c.key())
            if rec is None:
                self._declare_locked(c, seconds, source)
                rec = self._records[c.key()]
            else:
                rec["compile_s"] = max(rec["compile_s"], float(seconds))
                if rec["source"] == SOURCE_INLINE and source != SOURCE_INLINE:
                    rec["source"] = source
            self.stats["compiles"] += 1
            self.stats["compile_s"] += float(seconds)
        self._metric_compile(seconds)
        if source == SOURCE_INLINE and self.warmed:
            # a mid-drain trace+compile is a slow-cycle event: surface it
            # through the utiltrace contract, not just the miss counter
            from ..utils.trace import log_slow

            log_slow("xla_inline_compile", seconds, spec=c.short())

    def undeclare(self, spec: SolveSpec) -> None:
        """Forget a declared spec. The warmup service calls this when a
        PERSISTED spec's warm fails or is skipped: leaving it declared
        would make the drain's real inline compile count as a plan HIT —
        silently defeating the misses-after-warmup honesty gauge."""
        c = self.ladder.canonicalize(spec)
        with self._lock:
            self._records.pop(c.key(), None)
            self.ladder.undeclare(c)

    def is_declared(self, spec: SolveSpec) -> bool:
        with self._lock:
            return self.ladder.canonicalize(spec).key() in self._records

    def mark_warmed(self) -> None:
        """Warmup finished: from here every miss is a drain stall."""
        with self._lock:
            self.warmed = True

    # -- persistence -----------------------------------------------------------

    def load_persisted(self) -> List[SolveSpec]:
        """Declare the on-disk ladder (restart path) and return its specs
        for the warmup service to compile (the XLA persistent cache makes
        each one cheap)."""
        if self.cache is None:
            return []
        out = []
        for spec, secs in self.cache.load_ladder():
            c = self.declare(spec, source=SOURCE_PERSISTED)
            with self._lock:
                rec = self._records[c.key()]
                rec["compile_s"] = max(rec["compile_s"], secs)
            out.append(c)
        return out

    def persist(self) -> bool:
        if self.cache is None:
            return False
        with self._lock:
            records = [(r["spec"], r["compile_s"]) for r in self._records.values()]
        return self.cache.save_ladder(records)

    # -- telemetry -------------------------------------------------------------

    def snapshot(self) -> Dict:
        """One dict for bench detail / driver stats / debugging."""
        with self._lock:
            total = self.stats["hits"] + self.stats["misses"]
            return {
                "declared_specs": len(self._records),
                "hits": int(self.stats["hits"]),
                "misses": int(self.stats["misses"]),
                "misses_after_warmup": int(self.stats["misses_after_warmup"]),
                "compiles": int(self.stats["compiles"]),
                "compile_s": round(self.stats["compile_s"], 3),
                "coverage": round(self.stats["hits"] / total, 4) if total else None,
                "warmed": self.warmed,
                "specs": sorted(
                    (
                        {
                            "spec": r["spec"].short(),
                            "source": r["source"],
                            "compile_s": round(r["compile_s"], 3),
                            "dispatches": r["count"],
                        }
                        for r in self._records.values()
                    ),
                    key=lambda e: -e["compile_s"],
                ),
            }

    # ktpu: holds(self._lock) shared by kind_census and health_census
    def _kind_census_locked(self) -> Dict[str, Dict]:
        out: Dict[str, Dict] = {}
        for rec in self._records.values():
            k = str(rec["spec"].kind)
            e = out.setdefault(
                k, {"rungs": 0, "dispatches": 0, "inline": 0, "compile_s": 0.0}
            )
            e["rungs"] += 1
            e["dispatches"] += int(rec["count"])
            if rec["source"] == SOURCE_INLINE:
                e["inline"] += 1
            e["compile_s"] += float(rec["compile_s"])
        for e in out.values():
            e["compile_s"] = round(e["compile_s"], 3)
        return out

    def kind_census(self) -> Dict[str, Dict]:
        """Per-KIND_* ladder census (obs/introspect): declared rungs,
        dispatch hits, inline-compiled rungs, and accumulated compile
        wall per family — the 'is the ladder covering the workload'
        answer at a glance, without the full per-spec list."""
        with self._lock:
            return self._kind_census_locked()

    def health_census(self) -> Dict:
        """The health monitor's compile block: the scalar stats + the
        per-kind census in ONE short lock hold. Deliberately NOT
        snapshot(): that builds and sorts the full per-spec list under
        the lock — fine for bench detail, pure discarded work (and hot-
        path lock contention) at a monitor's refresh cadence."""
        with self._lock:
            total = self.stats["hits"] + self.stats["misses"]
            return {
                "declared_specs": len(self._records),
                "hits": int(self.stats["hits"]),
                "misses": int(self.stats["misses"]),
                "misses_after_warmup": int(self.stats["misses_after_warmup"]),
                "compiles": int(self.stats["compiles"]),
                "compile_s": round(self.stats["compile_s"], 3),
                "coverage": round(self.stats["hits"] / total, 4) if total else None,
                "warmed": self.warmed,
                "kinds": self._kind_census_locked(),
            }

    # -- metrics glue (lazy import: the plan must work without the registry) --

    def _metrics(self):
        try:
            from ..metrics import metrics as M

            return M
        except Exception:  # pragma: no cover
            return None

    def _metric_hit(self, n_specs: int) -> None:
        """Pure metric emitter: plan-state values arrive as arguments so
        the caller reads them under the lock (KTPU003 discipline)."""
        M = self._metrics()
        if M is not None:
            M.compile_plan_lookups.inc("hit")
            M.compile_ladder_specs.set(n_specs)

    def _metric_miss(self, after_warmup: bool, n_specs: int, misses_after: int) -> None:
        M = self._metrics()
        if M is not None:
            M.compile_plan_lookups.inc("miss")
            M.compile_ladder_specs.set(n_specs)
            if after_warmup:
                M.compile_spec_misses_after_warmup.set(misses_after)

    def _metric_compile(self, seconds: float) -> None:
        M = self._metrics()
        if M is not None:
            M.xla_compile_duration.observe(seconds)
