"""AOT warmup service: compile the declared ladder OFF the drain loop.

Executes each declared SolveSpec once against template arguments whose
shapes/dtypes/pytree structure are — by construction — identical to what
the driver dispatches (the templates come from the same encoder classes:
NodeBank/PodBatch/SigBank/PatternBank/compile_batch_terms), so the jit
call cache the drain hits is the very cache this service populates.

Two modes:
* **foreground** (`warm_specs`) at driver startup — `Scheduler.warmup()`
  drives it with the persisted ladder plus the live peeked batch;
* **background** (`warm_async`) for growth events — when a bucket grows
  or a bank rebuild looms, the next rung compiles on a daemon worker
  thread while the drain keeps executing the current rung. The worker
  never touches the TensorMirror's mutable dirty-row bookkeeping: live
  device dicts are snapshotted by the CALLING (driver) thread and handed
  over; otherwise the worker builds synthetic banks from scratch.

A warm that fails (encoder drift, backend quirk) is counted and logged,
never raised — the inline jit fallback still guarantees correctness.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from ..analysis.lockorder import audited_lock, register_thread_role
from .ladder import (
    KIND_ARBITER,
    KIND_FILTER,
    KIND_FOLD,
    KIND_PATCH,
    KIND_PREEMPT,
    KIND_SOLVE_GANG,
    KIND_STAGE,
    KIND_TERM,
    SolveSpec,
)
from .plan import CompilePlan, SOURCE_PERSISTED, SOURCE_WARMUP

logger = logging.getLogger("kubernetes_tpu.compile")


class _WarmContext:
    """Everything a warm needs from the TensorMirror, captured at the
    ROLE BOUNDARY on the driver thread (warm_specs / warm_async): the
    warm pipeline below this point never touches the driver-confined
    mirror, structurally — the old code read bank capacities/vocab/image
    widths (and gated device_arrays on a main-thread check) from the
    background worker, racing any concurrent rebuild (KTPU006/008).
    `place` and `live_banks` are bound mirror methods invoked lazily;
    `live_banks` is captured ONLY for foreground (driver-thread) use —
    `place` consults just the set_mesh-time placement recipe, frozen
    before any drain spawns workers."""

    __slots__ = ("live_shape", "vocab", "img_w", "place", "fold_fns",
                 "live_banks")

    def __init__(self, mirror, specs: Sequence[SolveSpec], foreground: bool):
        nodes = mirror.nodes
        self.live_shape = (
            nodes.capacity, nodes.key_capacity, nodes.alloc.shape[1],
            mirror.eps.capacity, mirror.pats.capacity,
        )
        self.vocab = mirror.vocab
        img = getattr(nodes, "image_scaled", None)
        self.img_w = img.shape[1] if img is not None else 64
        self.place = mirror._to_dev
        # live banks only for foreground warms: device_arrays' dirty-row
        # bookkeeping is driver-only, so a background ctx never gets it
        self.live_banks = mirror.device_arrays if foreground else None
        # sharded fold warms dispatch through the mirror's memoized
        # mesh-bound kernels — capture them HERE (driver thread) so the
        # worker never touches the _sharded_folds memo
        self.fold_fns = (
            mirror._fold_fns()
            if any(s.kind == KIND_FOLD and s.shards for s in specs)
            else None
        )


class WarmupService:
    """Owns no policy: the plan says WHAT to compile, this service does."""

    def __init__(self, scheduler, plan: Optional[CompilePlan] = None):
        self.sched = scheduler
        self.plan = plan if plan is not None else scheduler.compile_plan
        self._lock = audited_lock("warmup")
        self._done: set = set()
        self._pending: List[Tuple[SolveSpec, Optional[Tuple], _WarmContext]] = []
        self._worker: Optional[threading.Thread] = None
        # True from the moment a worker is started until it observes an
        # empty queue UNDER THE LOCK and exits. Checked instead of
        # Thread.is_alive(): a worker that decided to exit is still alive
        # for a moment, and an enqueue landing in that window would see
        # is_alive() and start nothing — specs stuck unwarmed (lost
        # wakeup).
        self._worker_active = False  # ktpu: guarded-by(self._lock)
        # ktpu: guarded-by(self._lock) foreground AND worker warms count
        self.stats: Dict[str, float] = {"warmed": 0, "failures": 0, "warm_s": 0.0}

    # -- public entry points --------------------------------------------------

    def warm_specs(
        self, specs: Sequence[SolveSpec], dev: Optional[Tuple] = None,
        source: str = SOURCE_WARMUP,
    ) -> int:
        """Foreground warm — the caller's thread is the driver, so the
        ctx it captures here may carry the live-bank resolver. Returns
        the number of specs actually executed."""
        ctx = _WarmContext(self.sched.mirror, specs, foreground=True)
        n = 0
        for spec in specs:
            if self._warm_one(spec, dev, source, ctx=ctx):
                n += 1
        return n

    def warm_async(self, specs: Sequence[SolveSpec], dev: Optional[Tuple] = None) -> None:
        """Queue specs for the background worker. `dev` is a (na, ea, xp)
        device-dict snapshot taken by the caller — background warms MUST
        NOT touch the TensorMirror (device_arrays' dirty-row bookkeeping
        is not thread-safe, and every bank attribute is driver-confined);
        the shapes/vocab the worker needs travel in a _WarmContext
        captured HERE, on the calling (driver) thread."""
        ctx = _WarmContext(self.sched.mirror, specs, foreground=False)
        with self._lock:
            queued = {s.key() for s, _, _ in self._pending}
            for s in specs:
                c = self.plan.canonicalize(s)
                if c.key() in self._done or c.key() in queued:
                    continue
                self._pending.append((c, dev, ctx))
                queued.add(c.key())
            if self._pending and not self._worker_active:
                self._worker_active = True
                if not getattr(self, "_atexit_armed", False):
                    # an XLA compile in flight on a daemon thread when the
                    # interpreter exits aborts the process (C++ terminate);
                    # drain queued work and let the in-flight one finish
                    # even when the embedding app never calls close()
                    import atexit

                    atexit.register(self._atexit_join)
                    self._atexit_armed = True
                self._worker = threading.Thread(
                    target=self._drain, name="compile-warmup", daemon=True
                )
                self._worker.start()

    def _atexit_join(self) -> None:
        self.stop()
        self.join()

    def stop(self) -> None:
        """Drop queued (not-yet-started) warms. The in-flight spec still
        finishes — interrupting an XLA compile mid-flight aborts the
        process at teardown; callers stop() then join()."""
        with self._lock:
            self._pending.clear()

    def join(self, timeout: Optional[float] = None) -> None:
        w = self._worker
        if w is not None and w.is_alive():
            w.join(timeout)

    # ktpu: thread-entry(warmup) the background compile worker
    def _drain(self) -> None:
        register_thread_role("warmup")
        while True:
            with self._lock:
                if not self._pending:
                    self._worker_active = False
                    return
                spec, dev, ctx = self._pending.pop(0)
            self._warm_one(spec, dev, SOURCE_WARMUP, ctx=ctx)

    # -- the actual warm -------------------------------------------------------

    def _warm_one(
        self, spec: SolveSpec, dev, source: str, ctx: _WarmContext,
    ) -> bool:
        c = self.plan.canonicalize(spec)
        with self._lock:
            if c.key() in self._done:
                return False
        try:
            secs = self.warm_spec(c, dev, ctx=ctx)
        except Exception:
            with self._lock:  # foreground + worker both count here
                self.stats["failures"] += 1
            logger.warning("warmup failed for %s", c.short(), exc_info=True)
            if source == SOURCE_PERSISTED:
                # the spec was declared at LOAD time on the promise of this
                # warm — withdraw the declaration, so a later dispatch of
                # it counts as the (real) miss it is. Other sources were
                # never pre-declared (undeclaring could forget a spec an
                # inline compile legitimately made hot).
                self.plan.undeclare(c)
            return False
        if secs is None:
            if source == SOURCE_PERSISTED:
                self.plan.undeclare(c)
            return False  # incompatible with the current deployment: skipped
        with self._lock:
            self._done.add(c.key())
            self.stats["warmed"] += 1
            self.stats["warm_s"] += secs
        self.plan.declare(c, source=source)
        self.plan.note_compiled(c, secs, source)
        return True

    def warm_spec(
        self, spec: SolveSpec, dev=None, *, ctx: _WarmContext,
    ) -> Optional[float]:
        """Execute one spec at its declared shapes; returns wall seconds,
        or None when the spec can't be realized here (a SolveConfig this
        process can't reconstruct, zero-size axes). `ctx` is the mirror
        snapshot captured at the role boundary (warm_specs/warm_async,
        both driver-thread) — nothing below this point touches the
        driver-confined TensorMirror."""
        if spec.kind == KIND_PREEMPT:
            return self._warm_preempt(spec)  # no SolveConfig static
        if spec.kind == KIND_PATCH:
            # dirty-row scatters warm at LIVE shapes only — the driver's
            # warmup drives TensorMirror.warm_patches, which re-declares
            # the current bank structures; a persisted patch spec from a
            # previous shape cannot be replayed synthetically, so skip it
            # (undeclared for persisted sources, by design)
            return None
        if spec.kind == KIND_FOLD:
            return self._warm_fold(spec, ctx)  # no SolveConfig static
        if spec.kind == KIND_STAGE:
            return self._warm_stage(spec, ctx)  # no SolveConfig static
        if spec.kind == KIND_TERM:
            return self._warm_term(spec, ctx)  # no SolveConfig static
        if spec.config_repr != repr(self.sched.solve_config):
            return None  # persisted ladder from a differently-policied run
        if not (spec.b and spec.u and spec.t and spec.n and spec.v):
            return None
        # the driver keeps _mesh_shards 0 when no mesh is configured —
        # one source for "this process's shard count" (the spec's own
        # shards field already encodes the routing decision)
        if spec.shards and spec.shards != self.sched._mesh_shards:
            return None  # partitioned for a different mesh: not realizable

        import jax
        import numpy as np

        from ..ops import filters as F
        from ..ops.pipeline import filter_mask, solve_pipeline, solve_pipeline_gang
        from ..state.terms import compile_batch_terms
        from ..state.tensors import PodBatch

        vocab = ctx.vocab
        na, ea, xp = self._banks_for(spec, dev, ctx)
        if na is None:
            return None
        use_sharded = spec.shards > 0
        if self.sched.mesh is not None:
            # the dispatch-time banks are device-resident with the
            # mirror's NamedSharding (node-major axes split over "nodes");
            # the jit cache keys on input shardings, so the warm must
            # place its banks through the SAME recipe or it compiles a
            # program the drain never dispatches. This includes shards=0
            # specs on a MESH driver (the indivisible-bucket fallback):
            # the replicated pipeline still receives sharded banks there.
            na, ea, xp = self._shard_banks(na, ea, xp, ctx)
        batch = PodBatch(vocab, spec.u)
        tb, aux = compile_batch_terms(vocab, [], capacity=spec.t, b_capacity=spec.u)
        pb = {
            "sig": np.zeros(spec.b, np.int32),
            "valid": np.zeros(spec.b, bool),
            "priority": np.zeros(spec.b, np.int32),
        }
        ids = self.sched._ids if self.sched._ids is not None else F.make_ids(vocab)
        key = jax.random.PRNGKey(0)
        args = (na, batch.arrays(), ea, tb.arrays(), xp, aux, ids, key)
        statics = dict(
            deterministic=spec.deterministic,
            config=self.sched.solve_config,
            term_kinds=spec.term_kinds,
            n_buckets=spec.v,
        )
        t0 = time.perf_counter()
        if spec.kind == KIND_FILTER:
            out = filter_mask(args[0], args[1], args[2], args[3], args[4],
                              args[5], args[6], **statics)
            jax.block_until_ready(out)
        elif spec.kind == KIND_ARBITER:
            from ..commit.arbiter import arbitrate

            import jax.numpy as jnp

            assign = np.full(spec.b, -1, np.int32)
            arb_statics = dict(term_kinds=spec.term_kinds, n_buckets=spec.v)
            carry = None
            if spec.with_carry:
                # the driver hands the arbiter the SAME residual tuple the
                # chained solve ran on — mirror its dtypes exactly (on a
                # mesh these are node-sharded outputs of sharded ops, so
                # the carry built from sharded banks shards identically)
                f0 = jnp.asarray(na["alloc"]) - jnp.asarray(na["requested"])
                carry = (
                    f0,
                    jnp.asarray(na["pod_count"]).astype(f0.dtype),
                    jnp.asarray(na["nonzero_req"]).astype(f0.dtype),
                )
            arb_fn = (
                self.sched._sharded.arbitrate if use_sharded else arbitrate
            )
            if use_sharded:
                # the dispatch-time assign is the sharded solve's output
                # (mesh-replicated committed array) — mirror that so the
                # warmed executable is the dispatched one
                from jax.sharding import NamedSharding, PartitionSpec as P

                assign = jax.device_put(
                    jnp.asarray(assign),
                    NamedSharding(self.sched.mesh, P()),
                )
            out = arb_fn(
                na, batch.arrays(), ea, tb.arrays(), ids, assign,
                pb=pb, carry=carry, **arb_statics,
            )
            jax.block_until_ready(out)
        elif spec.kind == KIND_SOLVE_GANG:
            fn = self.sched._sharded.gang if use_sharded else solve_pipeline_gang
            garr = np.full(spec.b, -1, np.int32)
            out = fn(*args, garr, pb=pb, carry=None, return_carry=True, **statics)
            if spec.with_carry:
                out = fn(*args, garr, pb=pb, carry=out[3], return_carry=True, **statics)
            jax.block_until_ready(out[0])
        else:
            fn = self.sched._sharded if use_sharded else solve_pipeline
            out = fn(
                *args, pb=pb, carry=None, return_carry=True,
                track_inbatch=spec.track_inbatch, **statics,
            )
            if spec.with_carry:
                out = fn(
                    *args, pb=pb, carry=out[2], return_carry=True,
                    track_inbatch=spec.track_inbatch, **statics,
                )
            jax.block_until_ready(out[0])
        return time.perf_counter() - t0

    # -- templates -------------------------------------------------------------

    def _shard_banks(self, na, ea, xp, ctx: _WarmContext):
        """Place template banks exactly the way TensorMirror uploads the
        live ones on a mesh (node-major axes NamedSharding'd over "nodes",
        everything else plain) — the same `_to_dev` recipe, so the warmed
        executable's input shardings equal the dispatched ones."""
        place = ctx.place
        na = {k: place(v, True) for k, v in na.items()}
        ea = {k: place(v, k == "counts") for k, v in ea.items()}
        xp = {k: place(v, k == "counts") for k, v in xp.items()}
        return na, ea, xp

    def _banks_for(self, spec: SolveSpec, dev, ctx: _WarmContext):
        """(na, ea, xp) argument dicts at the spec's bank shapes. The live
        snapshot (`dev`, or — foreground only — the ctx's live-bank
        resolver) is used when every bank axis matches; otherwise
        synthetic banks are built from the encoder classes — shape-exact
        for specs one growth rung AHEAD of the live banks (sig/pattern/
        node growth warming). The shape comparison uses the ctx capture,
        so a background call never reads the mirror's capacities racily
        (the old current_thread() gate did)."""
        if (spec.n, spec.k, spec.r, spec.s, spec.pt) == ctx.live_shape:
            if dev is not None:
                return dev
            if ctx.live_banks is not None:  # foreground: driver thread
                return ctx.live_banks()
            # background without a snapshot: fall through to synthetic
        return self._synthetic_banks(spec, ctx)

    def _synthetic_banks(self, spec: SolveSpec, ctx: _WarmContext):
        import numpy as np

        from ..state.tensors import EncodingConfig, NodeBank, SigBank, Vocab
        from ..state.terms import PatternBank

        base_vocab = ctx.vocab
        if (spec.k, spec.r) != (
            base_vocab.config.key_slots, base_vocab.config.resource_slots
        ):
            # a different key/resource width needs its own vocab config;
            # the ids the kernels consume are scalars, so a throwaway
            # vocab still yields the identical program signature
            vocab = Vocab(EncodingConfig(key_slots=spec.k, resource_slots=spec.r))
        else:
            vocab = base_vocab
        if vocab.config.key_slots != spec.k or vocab.config.resource_slots != spec.r:
            return None, None, None  # config grew concurrently: skip
        nb = NodeBank(vocab, spec.n)
        # the live node dict carries image_scaled (ImageTable.apply runs on
        # every rebuild); mirror its CURRENT width — image-vocab growth is
        # its own (rare) recompile, not this spec's axis
        nb.image_scaled = np.zeros((spec.n, ctx.img_w), np.int64)
        eb = SigBank(vocab, spec.s, spec.n)
        pb = PatternBank(vocab, spec.pt, spec.n)
        return nb.arrays(), eb.arrays(), pb.arrays()

    def _warm_fold(
        self, spec: SolveSpec, ctx: _WarmContext
    ) -> Optional[float]:
        """ops/fold at the spec's shapes. Always synthetic zero banks —
        the LIVE resident banks must never be donated into a warm (the
        drain still needs them). Dtypes mirror the mirror's canonicalized
        uploads (jnp.asarray of the host banks' numpy dtypes), so the jit
        cache entry is the one the driver's dispatch hits. Donating
        freshly built arrays keeps the warmed program the donated one.
        Sharded specs place the banks with the mirror's NamedSharding and
        dispatch through the mirror's CACHED mesh-bound kernels — the
        very callables the drain folds through."""
        if not (spec.b and spec.n and spec.r):
            return None
        import jax
        import jax.numpy as jnp
        import numpy as np

        sharded = spec.shards > 0
        if sharded:
            if (
                spec.shards != self.sched._mesh_shards
                or spec.n % spec.shards != 0
            ):
                return None  # foreign mesh / indivisible: not realizable
            if ctx.fold_fns is None:
                # the ctx capture didn't include the mesh-bound kernels
                # (no sharded fold spec was visible at the role boundary)
                # — skip rather than touch the driver-confined memo here
                return None
            fold_commit_banks, fold_usage = ctx.fold_fns

            from jax.sharding import NamedSharding, PartitionSpec as P

            from ..parallel.mesh import AXIS_NODES

            sh = NamedSharding(self.sched.mesh, P(AXIS_NODES))

            def bank(a):
                return jax.device_put(jnp.asarray(a), sh)
        else:
            from ..ops.fold import fold_commit_banks, fold_usage

            bank = jnp.asarray

        b, n, r = spec.b, spec.n, spec.r
        req_bank = bank(np.zeros((n, r), np.int64))
        pc_bank = bank(np.zeros(n, np.int32))
        rows = np.full(b, n, np.int32)  # all-padding sentinel lanes
        t0 = time.perf_counter()
        if spec.s:  # commit variant (signature + pattern count scatters)
            if not (spec.t and spec.pt):
                return None
            out = fold_commit_banks(
                req_bank,
                bank(np.zeros((n, 2), np.int64)),
                pc_bank,
                bank(np.zeros((n, spec.s), np.int16)),
                bank(np.zeros((n, spec.pt), np.int16)),
                rows,
                np.zeros((b, r), np.int64),
                np.zeros((b, 2), np.int64),
                np.zeros(b, np.int32),
                np.full(b, spec.s, np.int32),
                np.full(spec.t, n, np.int32),
                np.full(spec.t, spec.pt, np.int32),
                np.zeros(spec.t, np.int16),
            )
        else:  # nominee-overlay variant (usage columns only)
            out = fold_usage(
                req_bank, pc_bank, rows,
                np.zeros((b, r), np.int64), np.zeros(b, np.int32),
            )
        jax.block_until_ready(out[0])
        return time.perf_counter() - t0

    def _warm_stage(
        self, spec: SolveSpec, ctx: _WarmContext
    ) -> Optional[float]:
        """ingest/gather.gather_stage at the spec's shapes (u = index
        rung, s = slab capacity, k/r = encoding widths). Synthetic slab —
        a PodBatch at the spec's capacity, placed through the mirror's
        `_to_dev(node_major=False)` recipe exactly like StageBank uploads
        the live one — so the warmed executable's input placements equal
        the dispatched ones. Row-scatter ("patch|...") specs warm at LIVE
        shapes only, via StageBank.warm (the KIND_PATCH contract): a
        persisted one from a previous shape is skipped, undeclared for
        persisted sources by the caller."""
        if not spec.config_repr.startswith("gather"):
            return None
        if not (spec.u and spec.s and spec.k and spec.r):
            return None
        import jax
        import numpy as np

        from ..ingest.gather import gather_stage
        from ..state.tensors import EncodingConfig, PodBatch, Vocab

        vocab = ctx.vocab
        if (spec.k, spec.r) != (
            vocab.config.key_slots, vocab.config.resource_slots
        ):
            vocab = Vocab(EncodingConfig(key_slots=spec.k, resource_slots=spec.r))
            if (
                vocab.config.key_slots != spec.k
                or vocab.config.resource_slots != spec.r
            ):
                return None
        _to_dev = ctx.place
        place = lambda v: _to_dev(v, False)  # noqa: E731
        bank = {k: place(v) for k, v in PodBatch(vocab, spec.s).arrays().items()}
        empty = {k: place(v) for k, v in PodBatch(vocab, 1).arrays().items()}
        idx = np.zeros(spec.u, np.int32)
        keep = np.zeros(spec.u, bool)
        fb = np.zeros(spec.u, bool)
        t0 = time.perf_counter()
        out = gather_stage(bank, idx, keep, empty, fb)
        jax.block_until_ready(out["valid"])
        return time.perf_counter() - t0

    def _warm_term(
        self, spec: SolveSpec, ctx: _WarmContext
    ) -> Optional[float]:
        """terms_plane/gather.gather_terms at the spec's shapes (t = term
        index rung, s = slab row capacity). Synthetic slab — a TermBank
        at the spec's capacity, placed through the mirror's
        `_to_dev(node_major=False)` recipe exactly like TermBankDevice
        uploads the live one. Row-scatter ("patch|...") specs warm at
        LIVE shapes only, via TermBankDevice.warm (the KIND_PATCH
        contract): a persisted one from a previous shape is skipped,
        undeclared for persisted sources by the caller."""
        if not spec.config_repr.startswith("gather"):
            return None
        if not (spec.t and spec.s):
            return None
        import jax
        import numpy as np

        from ..state.terms import TermBank
        from ..terms_plane.gather import gather_terms

        vocab = ctx.vocab
        _to_dev = ctx.place
        place = lambda v: _to_dev(v, False)  # noqa: E731
        bank = {
            k: place(v)
            for k, v in TermBank(vocab, spec.s).arrays().items()
        }
        empty = {
            k: place(v) for k, v in TermBank(vocab, 1).arrays().items()
        }
        idx = np.zeros(spec.t, np.int32)
        owner = np.zeros(spec.t, np.int32)
        keep = np.zeros(spec.t, bool)
        t0 = time.perf_counter()
        out = gather_terms(bank, idx, owner, keep, empty)
        jax.block_until_ready(out["valid"])
        return time.perf_counter() - t0

    def _warm_preempt(self, spec: SolveSpec) -> Optional[float]:
        """ops/preempt.preempt_batch at (b=preemptors, n=nodes,
        v=victim slots, r=resource slots)."""
        if not (spec.b and spec.n and spec.v and spec.r):
            return None
        import jax
        import numpy as np

        from ..ops.preempt import preempt_batch

        b, n, v, r = spec.b, spec.n, spec.v, spec.r
        t0 = time.perf_counter()
        out = preempt_batch(
            np.zeros((b, n), bool),
            np.zeros((b, r), np.int64),
            np.zeros(b, bool),
            np.zeros(b, np.int32),
            np.zeros(b, bool),
            np.zeros((n, v, r), np.int64),
            np.zeros((n, v), np.int32),
            np.zeros((n, v), np.int64),
            np.zeros((n, v), bool),
            np.zeros((n, v), bool),
            np.zeros((n, r), np.int64),
            np.zeros(n, np.int32),
            np.zeros(n, bool),
            np.zeros((n, r), np.int64),
            np.zeros(n, np.int32),
        )
        jax.block_until_ready(out[0])
        return time.perf_counter() - t0
