"""Shape-ladder policy: canonical bucket quantizers + the SolveSpec key.

Every distinct (shape bucket, jit-static) combination the solve pipeline
executes is one XLA program. The ladder declares WHICH combinations are
legal: raw sizes round UP to a rung, so a 37-pod tail batch executes the
64-bucket program that already exists instead of tracing a fresh 37-shape
one. The quantizers here are the single source of truth — state/tensors'
`_bucket`/`_node_bucket` are aliases of these (the bucket policy moved
behind the ladder), so encoders, the driver, and the warmup service can
never disagree about what shapes exist.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Dict, List, Tuple


def pow2_bucket(n: int, minimum: int = 16) -> int:
    """Next power-of-two capacity ≥ n (bounded recompilation buckets)."""
    cap = minimum
    while cap < n:
        cap *= 2
    return cap


def node_axis_bucket(n: int, minimum: int = 16) -> int:
    """Node-axis capacity: power of two up to 2048, then the next multiple
    of 2048. Every [*, N] kernel pays for the padding — at 10k nodes a
    pow-2 bucket (16384) wastes 64% of all mask/score/topology work, while
    2048-multiples cap waste at <20% and still divide evenly for any
    power-of-two device-mesh shard count (parallel/sharded.py)."""
    if n <= 2048:
        return pow2_bucket(n, minimum)
    return -(-n // 2048) * 2048


def next_rung(n: int, minimum: int = 16) -> int:
    """The rung ABOVE the one holding n — what a growth event lands on.
    The warmup service compiles this ahead of time (headroom warming) so
    the growth, when it happens, finds a hot program."""
    return pow2_bucket(pow2_bucket(n, minimum) + 1, minimum)


#: every term kind mask_and_score can gate on (ops/pipeline.py)
ALL_TERM_KINDS = frozenset({
    "spread_hard", "spread_soft", "aff_req", "anti_req", "pref",
    "sel_spread", "et_anti", "et_score",
})

KIND_SOLVE = "solve"
KIND_SOLVE_GANG = "solve_gang"
KIND_FILTER = "filter"
KIND_PREEMPT = "preempt"
# commit-plane arbiter (kubernetes_tpu/commit/arbiter.py): rides the same
# b/u/t/n/v axes as the solve it validates, so its rungs are the solve's
KIND_ARBITER = "arbiter"
# resident-state fold (ops/fold.py): b = commit-row bucket (the solve's
# batch rung), t = pattern-triple bucket, n/r/s/pt = bank capacities. The
# nominee-overlay variant is the same kind with s=pt=t=0 (it touches only
# the usage columns — a genuinely different XLA program).
KIND_FOLD = "fold"
# tensor-mirror dirty-row scatter (state/cache.TensorMirror._scatter_rows):
# b = row rung (PATCH_RUNGS quantizer, NOT this ladder's pow-2 buckets),
# n = the bank's row capacity, config_repr = the update-dict structure.
# Routed through the plan so a post-warmup scatter compile is a counted
# miss — these were the invisible mid-drain stalls on preemption drains.
KIND_PATCH = "patch"
# pod-ingest plane (kubernetes_tpu/ingest): the device-resident staged
# pod bank's programs. Two variants distinguished by config_repr:
#   "gather|..." — the index-only dispatch prologue (u = index-vector
#     rung, s = slab row capacity, k/r = encoding widths);
#   "patch|..."  — the staging uploader's dirty-row scatter (b = row
#     rung from ingest.bank.STAGE_RUNGS, s = slab capacity, structure in
#     config_repr exactly like KIND_PATCH).
# Both call sites bucket their own axes, so specs pass canonicalize
# unchanged (same contract as KIND_PREEMPT/KIND_PATCH).
KIND_STAGE = "stage"
# term-bank plane (kubernetes_tpu/terms_plane): the device-resident term
# slab's programs — same two-variant shape as KIND_STAGE:
#   "gather"     — the index-only term dispatch prologue (t = term-index
#     vector rung, s = slab row capacity);
#   "patch|..."  — the term uploader's dirty-row scatter (b = row rung
#     from terms_plane.bank.TERM_RUNGS, s = slab capacity, structure in
#     config_repr).
# Call sites bucket their own axes; specs pass canonicalize unchanged.
KIND_TERM = "terms"


@dataclass(frozen=True)
class SolveSpec:
    """Canonical description of ONE XLA program signature of the solve
    stack: the shape buckets of every padded axis plus the jit statics.
    Hashable and orderable so it can key plan registries and serialize to
    the persistent ladder. Axes not used by a kind stay 0.

    Axis legend: b = pod batch, u = unique pod specs, t = batch terms,
    n = nodes, v = topology segment buckets (n_buckets static), k = label
    key slots, r = resource slots, s = existing-pod signatures, pt =
    existing-pod term patterns. For KIND_PREEMPT, b is the preemptor
    bucket and v the victim-slot bucket.

    `shards` is the node-mesh shard count the program is partitioned
    over (0 = single-device/replicated). It is part of the program
    identity: the sharded solve/arbiter/fold are DIFFERENT XLA
    executables from their replicated twins, so a mesh-configured driver
    that silently falls back to the replicated pipeline (indivisible
    node bucket) now reports a real spec miss instead of a phantom hit."""

    kind: str = KIND_SOLVE
    b: int = 0
    u: int = 0
    t: int = 0
    n: int = 0
    v: int = 0
    k: int = 0
    r: int = 0
    s: int = 0
    pt: int = 0
    shards: int = 0
    term_kinds: frozenset = frozenset()
    config_repr: str = "None"  # SolveConfig repr (jit static; opaque here)
    deterministic: bool = False
    with_carry: bool = False
    track_inbatch: bool = False

    def key(self) -> Tuple:
        return (
            self.kind, self.b, self.u, self.t, self.n, self.v, self.k,
            self.r, self.s, self.pt, self.shards,
            tuple(sorted(self.term_kinds)),
            self.config_repr, self.deterministic, self.with_carry,
            self.track_inbatch,
        )

    def hash_hex(self) -> str:
        import hashlib

        return hashlib.sha1(repr(self.key()).encode()).hexdigest()[:16]

    def short(self) -> str:
        """Compact human form for logs/telemetry."""
        kinds = ",".join(sorted(self.term_kinds)) or "-"
        flags = "".join(
            c for c, on in (
                ("c", self.with_carry), ("i", self.track_inbatch),
                ("d", self.deterministic),
            ) if on
        ) or "-"
        mesh = f"x{self.shards}" if self.shards else ""
        return (
            f"{self.kind}{mesh}[b{self.b}/u{self.u}/t{self.t}/n{self.n}"
            f"/v{self.v}/k{self.k}/r{self.r}/s{self.s}/p{self.pt}"
            f"|{kinds}|{flags}]"
        )

    def to_dict(self) -> Dict:
        return {
            "kind": self.kind, "b": self.b, "u": self.u, "t": self.t,
            "n": self.n, "v": self.v, "k": self.k, "r": self.r,
            "s": self.s, "pt": self.pt, "shards": self.shards,
            "term_kinds": sorted(self.term_kinds),
            "config_repr": self.config_repr,
            "deterministic": self.deterministic,
            "with_carry": self.with_carry,
            "track_inbatch": self.track_inbatch,
        }

    @classmethod
    def from_dict(cls, d: Dict) -> "SolveSpec":
        return cls(
            kind=d.get("kind", KIND_SOLVE),
            b=int(d.get("b", 0)), u=int(d.get("u", 0)), t=int(d.get("t", 0)),
            n=int(d.get("n", 0)), v=int(d.get("v", 0)), k=int(d.get("k", 0)),
            r=int(d.get("r", 0)), s=int(d.get("s", 0)), pt=int(d.get("pt", 0)),
            shards=int(d.get("shards", 0)),
            term_kinds=frozenset(d.get("term_kinds", ())),
            config_repr=d.get("config_repr", "None"),
            deterministic=bool(d.get("deterministic", False)),
            with_carry=bool(d.get("with_carry", False)),
            track_inbatch=bool(d.get("track_inbatch", False)),
        )


class ShapeLadder:
    """Rounds raw axis sizes up to declared rungs and tracks the declared
    spec set. The pod/term/segment axes quantize to powers of two, the
    node axis to the node-axis policy — identical to what the encoders
    produce, so a canonicalized spec always names shapes that real banks
    can have."""

    def __init__(self, minimum: int = 16):
        self.minimum = minimum
        self._declared: Dict[Tuple, SolveSpec] = {}

    # -- canonicalization ---------------------------------------------------

    def canonicalize(self, spec: SolveSpec) -> SolveSpec:
        """Round every padded axis up to its rung; u never exceeds b (a
        batch cannot hold more unique specs than pods).

        KIND_PREEMPT, KIND_PATCH, KIND_STAGE, and KIND_TERM specs pass
        through UNCHANGED: those call sites bucket their own axes
        (minimum 8 preemptor/victim rungs; the mirror's PATCH_RUNGS; the
        ingest plane's STAGE_RUNGS and monotone u-rung; the term plane's
        TERM_RUNGS and monotone t-rung) and the spec must name the EXACT
        executed shapes — re-rounding here with this ladder's minimum
        would collapse distinct kernel signatures onto one key and
        report a mid-drain compile as a plan hit."""
        if spec.kind in (KIND_PREEMPT, KIND_PATCH, KIND_STAGE, KIND_TERM):
            return spec
        m = self.minimum
        b = pow2_bucket(spec.b, m) if spec.b else 0
        u = min(pow2_bucket(spec.u, m), b) if spec.u and b else (
            pow2_bucket(spec.u, m) if spec.u else 0
        )
        return replace(
            spec,
            b=b,
            u=u,
            t=pow2_bucket(spec.t, m) if spec.t else 0,
            n=node_axis_bucket(spec.n, m) if spec.n else 0,
            v=pow2_bucket(spec.v, m) if spec.v else 0,
        )

    def growth_specs(self, spec: SolveSpec) -> List[SolveSpec]:
        """The specs one growth event away on the axes that actually grow
        mid-drain — the headroom-warming set: unique-spec count, term
        table, segment buckets (monotone driver buckets), and the
        signature/pattern banks (whose overflow quadruples capacity and
        forces a mirror rebuild — state/cache.TensorMirror._rebuild — so
        pre-compiling the post-rebuild solve turns a multi-second stall
        into just the re-encode). The node axis is excluded: cluster
        growth arrives via informer events, not mid-drain."""
        out = []
        if spec.u and spec.u < spec.b:
            out.append(replace(spec, u=min(next_rung(spec.u, self.minimum), spec.b)))
        if spec.t:
            out.append(replace(spec, t=next_rung(spec.t, self.minimum)))
        if spec.v:
            out.append(replace(spec, v=next_rung(spec.v, self.minimum)))
        if spec.s:
            out.append(replace(spec, s=spec.s * 4))
        if spec.pt:
            out.append(replace(spec, pt=spec.pt * 4))
        return [self.canonicalize(s) for s in out]

    # -- declaration --------------------------------------------------------

    def declare(self, spec: SolveSpec) -> SolveSpec:
        c = self.canonicalize(spec)
        self._declared.setdefault(c.key(), c)
        return c

    def undeclare(self, spec: SolveSpec) -> None:
        self._declared.pop(self.canonicalize(spec).key(), None)

    def covers(self, spec: SolveSpec) -> bool:
        return self.canonicalize(spec).key() in self._declared

    @property
    def declared(self) -> List[SolveSpec]:
        return list(self._declared.values())

    def __len__(self) -> int:
        return len(self._declared)
